{{- define "gubernator-tpu.name" -}}
{{- .Chart.Name | trunc 63 | trimSuffix "-" -}}
{{- end -}}

{{- define "gubernator-tpu.labels" -}}
app: {{ include "gubernator-tpu.name" . }}
chart: {{ .Chart.Name }}-{{ .Chart.Version }}
release: {{ .Release.Name }}
{{- end -}}
