#!/usr/bin/env bash
# Regenerate the Python protobuf stubs (reference scripts/proto.sh).
#
# Only `protoc --python_out` is needed: gRPC service wiring is
# hand-rolled with grpc generic handlers (gubernator_tpu/grpc_server.py,
# peer_client.py) so the grpc_python_plugin is not required.
set -euo pipefail
cd "$(dirname "$0")/../gubernator_tpu/proto"

protoc --python_out=. gubernator.proto peers.proto etcd_kv.proto etcd_rpc.proto

# peers_columns.proto has no protoc dependency: its pb2 is generated
# programmatically (the build image ships no protoc).  Keep it in sync:
python ../../scripts/gen_columns_proto.py

# protoc emits an absolute sibling import; rewrite it for package use.
sed -i 's/^import gubernator_pb2 as gubernator__pb2$/from gubernator_tpu.proto import gubernator_pb2 as gubernator__pb2/' peers_pb2.py
sed -i 's/^import etcd_kv_pb2 as etcd__kv__pb2$/from gubernator_tpu.proto import etcd_kv_pb2 as etcd__kv__pb2/' etcd_rpc_pb2.py

echo "generated: $(ls *_pb2.py)"
