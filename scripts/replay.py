#!/usr/bin/env python
"""Deterministic incident replay from a black-box bundle.

Boots a fresh single daemon (in-process V1Service + gateway, no
sockets) from an incident bundle (blackbox.py): restores the captured
state snapshot when the bundle carries one, freezes the service clock
to the captured wall stamps, re-drives every captured INBOUND frame
through the real gateway router in capture order, and reconstructs the
sender-side conservation ledger from the captured OUTBOUND frames —
a FaultPlan DUPLICATE that double-delivered a forward during the
incident re-appears as byte-identical back-to-back outbound frames
and re-fires the same `forward_conservation` violation through the
real Auditor.  The final report is normalized (frame counts, response
status tally, CRC32 over every response body, violations) so two
replays of one bundle are byte-identical — the determinism oracle
tests/test_blackbox.py asserts.

Determinism contract + slack (architecture.md "Incident black box"):
frames replay sequentially on one thread against a frozen clock, so
batching, bucket math and reset stamps reproduce; capture slack —
native express-lane singles answered in C++, gRPC/JSON peer bodies,
and frames evicted from the byte-budgeted rings — replays as absent
traffic, and identical back-to-back outbound frames are indistinguish-
able from a real duplicate by design.

Usage:
  python scripts/replay.py BUNDLE_DIR                   # replay + report
  python scripts/replay.py --pace original BUNDLE_DIR   # captured pacing
  python scripts/replay.py --twice BUNDLE_DIR           # determinism check
  python scripts/replay.py --to-test out_test.py BUNDLE_DIR
  python scripts/replay.py --smoke                      # self-contained CI

Exit codes: 0 replay ran (and, with --twice, was deterministic; when
the bundle recorded audit violations, they reproduced); 1 the bundle
failed verification or the replay diverged; 2 usage/IO error.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time
import zlib

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

#: Captured inbound frame kind -> the gateway path that serves it.
#: Inbound kinds 2/6 are responses this daemon RECEIVED as a client —
#: not driveable (their requests replay on the daemon that served
#: them; scripts/incident_collect.py pairs the two sides).
ENDPOINT_BY_KIND = {
    5: "/v1/GetRateLimits",
    1: "/v1/peer.GetPeerRateLimits",
    3: "/v1/peer.UpdatePeerGlobals",
    4: "/v1/peer.TransferOwnership",
    7: "/v1/peer.UpdateRegionColumns",
}

#: Outbound kinds whose sender-side conservation ledger replay
#: reconstructs: kind -> (wire counter, admitted counter).
LEDGER_BY_KIND = {
    1: ("forward_wire_hits", "forward_admitted_hits"),
    7: ("region_wire_hits", "region_admitted_hits"),
}


def _frame_hits(raw: bytes) -> int:
    from gubernator_tpu import wire

    try:
        kind = raw[5]
        cols = (
            wire.decode_region_frame(raw) if kind == 7
            else wire.decode_columns_frame(raw)
        )
        return int(sum(int(h) for h in cols.hits))
    except Exception:  # noqa: BLE001 — unreconstructable frame
        return 0


def replay_bundle(bundle_path: str, pace: str = "fast") -> dict:
    """Replay one bundle; returns the normalized report dict.  Raises
    blackbox.BundleError if the bundle fails verification (nothing is
    driven — the no-half-replay contract)."""
    from gubernator_tpu import audit, blackbox, gateway, tracing
    from gubernator_tpu.config import BehaviorConfig
    from gubernator_tpu.service import ServiceConfig, V1Service
    from gubernator_tpu.utils.clock import Clock

    bundle = blackbox.load_bundle(bundle_path)
    records = bundle.merged_records()

    # The replayed daemon's config: the captured scalar knobs on top of
    # defaults, with the observability feedback loops forced off — the
    # replay must not write bundles about itself, and the audit verdict
    # comes from ONE deterministic check_now() at the end, not a timer.
    behaviors = BehaviorConfig()
    for k, v in bundle.manifest.get("knobs", {}).items():
        if hasattr(behaviors, k) and isinstance(
            getattr(behaviors, k), (bool, int, float, str)
        ):
            setattr(behaviors, k, type(getattr(behaviors, k))(v))
    behaviors.audit = False
    behaviors.blackbox = False
    behaviors.snapshot_interval_s = 0.0

    clock = Clock()
    first_wall_ms = records[0][0] // 1_000_000 if records else int(
        bundle.manifest.get("wallNs", time.time_ns()) // 1_000_000
    )
    clock.freeze(first_wall_ms)

    tmp_state = None
    snapshot_path = ""
    if os.path.exists(os.path.join(bundle_path, "state.snap")):
        # Restore the captured device state: boot from the exact
        # counters the incident daemon held at bundle-write time.
        tmp_state = tempfile.mkdtemp(prefix="gubernator-replay-")
        snapshot_path = os.path.join(tmp_state, "state.snap")
        shutil.copyfile(
            os.path.join(bundle_path, "state.snap"), snapshot_path
        )

    audit.reset()
    svc = V1Service(ServiceConfig(
        cache_size=4096,
        behaviors=behaviors,
        advertise_address=(
            bundle.manifest.get("service", {}).get("advertiseAddress", "")
            or "replay:0"
        ),
        clock=clock,
        snapshot_path=snapshot_path,
    ))
    try:
        svc.set_peers([])  # everything owned locally: no re-forwarding
        tracing.bind_recorder(svc.recorder)
        svc.auditor.check_now()  # seed the extent table (zero traffic)

        driven: dict = {}
        skipped = 0
        statuses: dict = {}
        body_crc = 0
        reconstructed: dict = {}
        last_out: dict = {}
        prev_mono = records[0][1] if records else 0
        last_ms = first_wall_ms
        for wall_ns, mono_ns, direction, peer, kind, frame in records:
            if pace == "original" and mono_ns > prev_mono:
                time.sleep(min((mono_ns - prev_mono) / 1e9, 0.25))
            prev_mono = mono_ns
            # The frozen clock tracks the CAPTURED wall stamps: bucket
            # expiry and reset math replay exactly as they ran.
            rec_ms = wall_ns // 1_000_000
            if rec_ms > last_ms:
                clock.advance(rec_ms - last_ms)
                last_ms = rec_ms
            if direction == "out":
                counters = LEDGER_BY_KIND.get(kind)
                if counters is not None:
                    wire_c, admitted_c = counters
                    hits = _frame_hits(frame)
                    audit.note(wire_c, hits)
                    # Byte-identical back-to-back frames to one peer =
                    # the captured signature of a redelivery: wire-side
                    # only, which re-creates the original excess.
                    if last_out.get((kind, peer)) != frame:
                        audit.note(admitted_c, hits)
                    last_out[(kind, peer)] = frame
                    for c in counters:
                        reconstructed[c] = int(
                            audit.ledger_snapshot().get(c, 0)
                        )
                continue
            endpoint = ENDPOINT_BY_KIND.get(kind)
            if endpoint is None:
                skipped += 1
                continue
            status, _ctype, body = gateway.handle_request(
                svc, "POST", endpoint, frame
            )
            wire_name = blackbox._KIND_WIRE.get(kind, "?")  # noqa: SLF001
            driven[wire_name] = driven.get(wire_name, 0) + 1
            statuses[str(status)] = statuses.get(str(status), 0) + 1
            body_crc = zlib.crc32(body, body_crc)

        svc.auditor.check_now()
        violations = dict(svc.auditor.violations)
        bundle_audit = bundle.doc("audit.json") or {}
        bundle_violations = {
            k: v for k, v in (bundle_audit.get("violations") or {}).items()
            if v
        }
        return {
            "bundle": bundle.manifest.get("name", ""),
            "framesCaptured": {
                w: len(recs) for w, recs in bundle.frames.items()
            },
            "driven": driven,
            "skippedResponses": skipped,
            "responseStatuses": statuses,
            "responseCrc32": body_crc,
            "reconstructedLedger": reconstructed,
            "violations": violations,
            "bundleViolations": bundle_violations,
            # The acceptance verdict: every invariant the live incident
            # tripped re-trips under replay.
            "reproducesBundleViolations": set(bundle_violations)
            <= set(violations),
        }
    finally:
        svc.close()
        if tmp_state is not None:
            shutil.rmtree(tmp_state, ignore_errors=True)


def emit_test(bundle_path: str, out_path: str) -> None:
    """--to-test: write a pytest regression file that replays the
    bundle twice and pins the determinism + violation-reproduction
    verdicts — a production incident turned into a repo test."""
    bundle_path = os.path.abspath(bundle_path)
    src = f'''"""Auto-generated incident regression (scripts/replay.py --to-test).

Replays the captured bundle twice and asserts (1) the replay is
deterministic (byte-identical normalized reports) and (2) every audit
invariant the live incident tripped re-trips under replay.
"""

import json
import os

import pytest

BUNDLE = {bundle_path!r}


@pytest.mark.skipif(
    not os.path.isdir(BUNDLE), reason="incident bundle not present"
)
def test_incident_replays_deterministically():
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    from scripts.replay import replay_bundle

    first = replay_bundle(BUNDLE)
    second = replay_bundle(BUNDLE)
    assert json.dumps(first, sort_keys=True) == json.dumps(
        second, sort_keys=True
    )
    assert first["reproducesBundleViolations"], (
        first["violations"], first["bundleViolations"]
    )
'''
    with open(out_path, "w") as f:
        f.write(src)


def run_smoke() -> int:
    """--smoke (make replay-smoke): synthesize a bundle carrying a
    duplicated forward (the FaultPlan DUPLICATE signature), replay it
    twice, and require determinism + the reproduced violation."""
    import numpy as np

    from gubernator_tpu import blackbox, wire

    workdir = tempfile.mkdtemp(prefix="gubernator-replay-smoke-")
    try:
        bb = blackbox.BlackBox(service=None, path=workdir, budget_mb=4)
        ingress = wire.encode_ingress_frame((
            ["smoke"], ["k1"],
            np.zeros(1, np.int32), np.zeros(1, np.int32),
            np.ones(1, np.int64), np.full(1, 10, np.int64),
            np.full(1, 60_000, np.int64),
        ))
        forward = wire.encode_columns_frame((
            ["smoke"], ["k2"],
            np.zeros(1, np.int32), np.zeros(1, np.int32),
            np.full(1, 3, np.int64), np.full(1, 10, np.int64),
            np.full(1, 60_000, np.int64),
        ))
        bb.tap("in", "", ingress)
        bb.tap("out", "peer-b", forward)
        bb.tap("out", "peer-b", forward)  # the duplicate delivery
        bundle_dir = bb.write_bundle(
            [{"kind": "manual", "wallNs": time.time_ns(),
              "monoNs": time.monotonic_ns(), "fields": {}}]
        )
        # The synthetic incident has no audit.json; pin the expectation
        # the live auto-dump path records, so the replay verdict is
        # exercised end to end.
        first = replay_bundle(bundle_dir)
        second = replay_bundle(bundle_dir)
        if json.dumps(first, sort_keys=True) != json.dumps(
            second, sort_keys=True
        ):
            print("replay-smoke: NONDETERMINISTIC", file=sys.stderr)
            print(json.dumps(first, indent=2), file=sys.stderr)
            print(json.dumps(second, indent=2), file=sys.stderr)
            return 1
        ok = (
            first["violations"].get("forward_conservation", 0) >= 1
            and first["driven"].get("public") == 1
            and first["responseStatuses"].get("200") == 1
        )
        if not ok:
            print("replay-smoke: violation not reproduced", file=sys.stderr)
            print(json.dumps(first, indent=2), file=sys.stderr)
            return 1
        print(
            "replay-smoke: OK — deterministic, forward_conservation "
            f"excess reproduced (report crc={first['responseCrc32']})"
        )
        return 0
    finally:
        shutil.rmtree(workdir, ignore_errors=True)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("bundle", nargs="?", help="incident bundle directory")
    p.add_argument("--pace", choices=("fast", "original"), default="fast",
                   help="fast = back-to-back (default); original = sleep "
                        "the captured inter-frame gaps (capped 250ms)")
    p.add_argument("--twice", action="store_true",
                   help="replay twice and fail unless byte-identical")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="print the raw report JSON")
    p.add_argument("--to-test", metavar="FILE", default="",
                   help="also emit a pytest regression file")
    p.add_argument("--smoke", action="store_true",
                   help="self-contained synthesize+replay CI check")
    args = p.parse_args(argv)

    if args.smoke:
        return run_smoke()
    if not args.bundle:
        p.error("BUNDLE_DIR required (or --smoke)")
    if not os.path.isdir(args.bundle):
        print(f"replay: {args.bundle}: no such bundle directory",
              file=sys.stderr)
        return 2

    from gubernator_tpu.blackbox import BundleError

    try:
        report = replay_bundle(args.bundle, pace=args.pace)
        if args.twice:
            again = replay_bundle(args.bundle, pace=args.pace)
            if json.dumps(report, sort_keys=True) != json.dumps(
                again, sort_keys=True
            ):
                print("replay: NONDETERMINISTIC across two replays",
                      file=sys.stderr)
                return 1
    except BundleError as e:
        print(f"replay: {args.bundle}: REJECTED: {e}", file=sys.stderr)
        return 1

    if args.to_test:
        emit_test(args.bundle, args.to_test)
        print(f"replay: wrote regression test {args.to_test}")

    if args.as_json:
        print(json.dumps(report, indent=2, sort_keys=True))
    else:
        drv = " ".join(f"{w}:{n}" for w, n in sorted(report["driven"].items()))
        vio = (
            ", ".join(
                f"{k}x{v}" for k, v in sorted(report["violations"].items())
            ) or "none"
        )
        print(
            f"{args.bundle}: replayed [{drv or 'nothing'}] "
            f"statuses={report['responseStatuses']} "
            f"crc={report['responseCrc32']:#010x} violations={vio} "
            f"reproduces-bundle="
            f"{report['reproducesBundleViolations']}"
        )
    if report["bundleViolations"] and not report["reproducesBundleViolations"]:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
