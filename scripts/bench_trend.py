#!/usr/bin/env python
"""Bench-history trend view + regression gate.

`make bench` appends every run's JSON row (stamped with git sha +
backend) to `benchmarks/history/`; the repo-root BENCH_r01–r05 files
are the pre-history seed entries.  This script renders the per-metric
trajectory across all of them and — for the newest run — exits
non-zero on a >20% NOISE-ADJUSTED regression against the rolling
median of the preceding same-backend runs, so a slow drift that no
single-run gate row would trip still fails a release check.

Rules (deliberately boring):

* Runs compare only within one backend ("cpu" vs "tpu" vs the
  unstamped legacy seeds, which group as "unknown"): a CPU dev box
  legitimately runs the identical path 10-100x slower than the tunnel
  (the gate_thresholds only_backend precedent) and must not read as a
  regression of it.
* The baseline is the rolling MEDIAN of up to the 5 preceding runs —
  robust to one outlier run in either direction.
* Lower-is-better metrics (latency ms, device µs) invert the
  comparison; everything else is higher-is-better throughput.
* Noise adjustment: the per-metric `*_noise_us` fields recorded by
  bench.py widen the allowance where present; otherwise the 20%
  threshold IS the noise allowance (bench absolutes swing ~2.5x with
  host weather — the same-run ratio rows in `make bench-gate` stay the
  sharp gates; this one catches multi-run drift).

Usage:
    python scripts/bench_trend.py                 # trajectory + gate
    python scripts/bench_trend.py --metric service_ingress_checks_per_sec
    python scripts/bench_trend.py --no-gate       # print only
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

REPO = os.path.join(os.path.dirname(__file__), "..")

# The stable cross-run rows worth a trajectory (absolute values; the
# same-run ratio rows are gated per-run by bench --gate instead).
DEFAULT_METRICS = (
    "rate_limit_checks_per_sec",
    "service_ingress_checks_per_sec",
    "ingress_columns_checks_per_sec",
    "peer_forward_checks_per_sec",
    "device_checks_per_sec",
    "device_batch_us",
    "service_ingress_latency_ms_p50",
    "service_ingress_latency_ms_p99",
)

# Lower-is-better name shapes (the gate_thresholds fail_above rows).
LOWER_IS_BETTER_SUFFIXES = ("_us", "_ms", "_ms_p50", "_ms_p99")
REGRESSION_FRACTION = 0.20
ROLLING_WINDOW = 5


def lower_is_better(metric: str) -> bool:
    return metric.endswith(LOWER_IS_BETTER_SUFFIXES) or "_latency_" in metric


def load_runs() -> list:
    """All known runs, oldest first: the BENCH_r* seeds (legacy,
    backend 'unknown'), then benchmarks/history/ by timestamp."""
    runs = []
    for path in sorted(glob.glob(os.path.join(REPO, "BENCH_r[0-9]*.json"))):
        try:
            with open(path) as f:
                row = json.load(f)
        except (OSError, ValueError):
            continue
        # The r01-r05 seeds wrap the bench row as {"cmd", "rc",
        # "parsed": {...}}; history entries are the row itself.
        if isinstance(row.get("parsed"), dict):
            row = row["parsed"]
        _lift_headline(row)
        runs.append({
            "label": os.path.basename(path).replace(".json", ""),
            "backend": row.get("backend", "unknown"),
            "time": 0.0,
            "row": row,
        })
    hist = []
    for path in glob.glob(os.path.join(REPO, "benchmarks", "history", "*.json")):
        try:
            with open(path) as f:
                row = json.load(f)
        except (OSError, ValueError):
            continue
        _lift_headline(row)
        hist.append({
            "label": os.path.basename(path).replace(".json", ""),
            "backend": row.get("backend", "unknown"),
            "time": float(row.get("time", 0.0)),
            "row": row,
        })
    hist.sort(key=lambda r: r["time"])
    return runs + hist


def _lift_headline(row: dict) -> None:
    """The bench row names its headline metric indirectly
    ({"metric": "rate_limit_checks_per_sec", "value": X}); lift it to
    a first-class key so it trends like every other metric."""
    name, value = row.get("metric"), row.get("value")
    if isinstance(name, str) and isinstance(value, (int, float)):
        row.setdefault(name, value)


def median(vals: list) -> float:
    vals = sorted(vals)
    n = len(vals)
    mid = n // 2
    return vals[mid] if n % 2 else (vals[mid - 1] + vals[mid]) / 2.0


def noise_for(run: dict, metric: str) -> float:
    """Per-metric measurement noise where bench.py recorded it (the
    device rows' `<metric>_noise_us` convention); 0 otherwise."""
    return float(run["row"].get(f"{metric}_noise_us", 0.0) or 0.0)


def spark(vals: list) -> str:
    blocks = "▁▂▃▄▅▆▇█"
    lo, hi = min(vals), max(vals)
    if hi <= lo:
        return blocks[0] * len(vals)
    return "".join(
        blocks[min(int((v - lo) / (hi - lo) * (len(blocks) - 1)),
                   len(blocks) - 1)]
        for v in vals
    )


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--metric", action="append", default=None,
                    help="metric(s) to trend (default: the stable set)")
    ap.add_argument("--window", type=int, default=ROLLING_WINDOW,
                    help="rolling-median window (preceding runs)")
    ap.add_argument("--threshold", type=float, default=REGRESSION_FRACTION,
                    help="regression fraction vs the rolling median")
    ap.add_argument("--no-gate", action="store_true",
                    help="print trajectories only, always exit 0")
    args = ap.parse_args()
    metrics = tuple(args.metric) if args.metric else DEFAULT_METRICS

    runs = load_runs()
    if not runs:
        print("bench-trend: no history (run `make bench` to record one)")
        return 0
    newest = runs[-1]
    print(
        f"bench-trend: {len(runs)} runs "
        f"(newest: {newest['label']}, backend {newest['backend']})"
    )
    failures = []
    for metric in metrics:
        series = [
            (r["label"], r["backend"], float(r["row"][metric]), r)
            for r in runs
            if isinstance(r["row"].get(metric), (int, float))
        ]
        if not series:
            continue
        vals = [v for _, _, v, _ in series]
        direction = "v" if lower_is_better(metric) else "^"
        print(
            f"  {metric} [{direction}]  {spark(vals)}  "
            + " ".join(f"{v:.4g}" for _, _, v, _ in series[-8:])
        )
        # Gate only the NEWEST run, only against preceding runs of the
        # SAME backend (cross-backend absolutes are not comparable).
        if args.no_gate or series[-1][3] is not newest:
            continue
        prior = [
            v for _, be, v, r in series[:-1]
            if be == newest["backend"] and r is not newest
        ][-args.window:]
        if len(prior) < 2:
            continue  # one prior point is weather, not a trend
        base = median(prior)
        value = series[-1][2]
        noise = noise_for(newest, metric)
        if lower_is_better(metric):
            limit = base * (1.0 + args.threshold)
            regressed = value - noise > limit
        else:
            limit = base * (1.0 - args.threshold)
            regressed = value + noise < limit
        if regressed:
            failures.append(
                f"{metric}: {value:.4g} vs rolling median {base:.4g} "
                f"(limit {limit:.4g}, n={len(prior)}, "
                f"backend {newest['backend']})"
            )
    if failures:
        print("bench-trend: REGRESSION vs rolling median")
        for f in failures:
            print(f"  - {f}")
        return 1
    print("bench-trend: PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
