#!/usr/bin/env python
"""Multi-daemon incident stitcher: merge black-box bundles into one
fleet-level incident timeline.

A cluster incident writes one bundle PER DAEMON (each V1Service owns
its own black box).  This script takes the bundle directories from
every involved daemon — or a parent directory holding several
GUBER_BLACKBOX_DIR trees — verifies each (the replay/fsck loader, so
a corrupt bundle rejects instead of polluting the timeline), and
stitches:

* **Triggers** across daemons, merged by wall clock: which daemon
  dumped first, and what cascade followed.
* **Wire frames** across daemons, merged by wall clock with their
  direction + peer: daemon A's "out" to B pairs with B's "in" from A,
  so a double-delivery or a lost frame is visible as an unpaired edge.
* **Trace ids** across span snapshots (the trace_collect.py rule): a
  trace that appears in more than one bundle marks the request chains
  that crossed the incident.

Usage:
    python scripts/incident_collect.py BUNDLE_DIR [BUNDLE_DIR ...]
    python scripts/incident_collect.py --scan /var/lib/gubernator/bb/
    python scripts/incident_collect.py --json BUNDLE_DIR ...

Exit code: 0 when every named bundle verified and at least one was
stitched; 1 when any bundle was rejected or none were found.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def _scan(root: str) -> list:
    """Find incident-* bundle dirs anywhere under `root` (each daemon
    points GUBER_BLACKBOX_DIR at its own subdirectory)."""
    found = []
    for dirpath, dirnames, _files in os.walk(root):
        for d in list(dirnames):
            if d.startswith("incident-"):
                found.append(os.path.join(dirpath, d))
                dirnames.remove(d)  # bundles don't nest
    return sorted(found)


def collect(paths: list) -> dict:
    """Load + verify every bundle; return the stitched incident doc."""
    from gubernator_tpu.blackbox import BundleError, load_bundle

    bundles, rejected = [], []
    for p in paths:
        try:
            bundles.append(load_bundle(p))
        except BundleError as e:
            rejected.append({"path": p, "error": str(e)})
    triggers = []
    frames = []
    traces: dict = {}
    for b in bundles:
        daemon = (
            b.manifest.get("service", {}).get("advertiseAddress", "")
            or b.manifest.get("name", b.path)
        )
        for t in b.manifest.get("triggers", []):
            triggers.append({
                "daemon": daemon,
                "kind": t.get("kind", "?"),
                "wallNs": t.get("wallNs", 0),
                "fields": t.get("fields", {}),
            })
        for wire_name, recs in b.frames.items():
            for wall_ns, _mono_ns, direction, peer, kind, frame in recs:
                frames.append({
                    "daemon": daemon, "wire": wire_name,
                    "dir": direction, "peer": peer, "kind": kind,
                    "bytes": len(frame), "wallNs": wall_ns,
                })
        spans_doc = b.doc("spans.json") or []
        for span in spans_doc:
            tid = span.get("trace_id")
            if tid:
                traces.setdefault(tid, set()).add(daemon)
    triggers.sort(key=lambda t: t["wallNs"])
    frames.sort(key=lambda f: f["wallNs"])
    cross = {
        tid: sorted(daemons)
        for tid, daemons in traces.items() if len(daemons) > 1
    }
    return {
        "bundles": [b.manifest.get("name", b.path) for b in bundles],
        "rejected": rejected,
        "triggers": triggers,
        "frames": frames,
        "crossDaemonTraces": cross,
        "firstTrigger": triggers[0] if triggers else None,
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("bundles", nargs="*", metavar="BUNDLE_DIR",
                   help="incident bundle directories to stitch")
    p.add_argument("--scan", metavar="DIR", default="",
                   help="also stitch every incident-* dir under DIR")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="print the raw stitched doc")
    p.add_argument("--frames", action="store_true",
                   help="include the merged frame timeline in the table "
                        "output")
    args = p.parse_args(argv)

    paths = list(args.bundles)
    if args.scan:
        paths.extend(_scan(args.scan))
    paths = sorted(set(paths))
    if not paths:
        print("incident_collect: no bundles named or found", file=sys.stderr)
        return 1
    doc = collect(paths)
    if args.as_json:
        print(json.dumps(doc, indent=2))
    else:
        print(f"bundles: {len(doc['bundles'])} "
              f"(rejected: {len(doc['rejected'])})")
        for r in doc["rejected"]:
            print(f"  REJECTED {r['path']}: {r['error']}")
        print("trigger timeline:")
        t0 = doc["triggers"][0]["wallNs"] if doc["triggers"] else 0
        for t in doc["triggers"]:
            dt_ms = (t["wallNs"] - t0) / 1e6
            print(f"  +{dt_ms:9.1f}ms  {t['daemon']:<22} {t['kind']} "
                  f"{t['fields'] or ''}")
        if doc["crossDaemonTraces"]:
            print("cross-daemon traces:")
            for tid, daemons in sorted(doc["crossDaemonTraces"].items()):
                print(f"  {tid}: {' '.join(daemons)}")
        if args.frames:
            print("frame timeline:")
            for f in doc["frames"]:
                dt_ms = (f["wallNs"] - t0) / 1e6 if t0 else 0.0
                print(
                    f"  +{dt_ms:9.1f}ms  {f['daemon']:<22} {f['dir']:<3} "
                    f"{f['wire']}/k{f['kind']} peer={f['peer'] or '-'} "
                    f"{f['bytes']}B"
                )
    return 1 if (doc["rejected"] or not doc["bundles"]) else 0


if __name__ == "__main__":
    sys.exit(main())
