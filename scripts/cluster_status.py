#!/usr/bin/env python
"""Cluster status poller: render every daemon's GET /debug/status as
one table — the whole-cluster view of the saturation & SLO plane
(health, breaker state, bucket-table occupancy, ingress queue, SLO
burn) plus the federation plane (data center, remote-region rings with
breaker-open marks, carry depth, last-flush age) and the cost
observatory (hot tenant per daemon; `--tenants` renders the
fleet-aggregated per-tenant cost table from every daemon's
GET /debug/tenants — "which tenant is burning region X's SLO" in one
view).  The soak harness (make soak-smoke, tests/test_soak_smoke.py)
asserts against the same JSON doc this renders.

Usage:
    python scripts/cluster_status.py HOST:PORT [HOST:PORT ...]
    python scripts/cluster_status.py --watch 5 10.0.0.1:1050 10.0.0.2:1050
    python scripts/cluster_status.py --json HOST:PORT      # raw docs
    python scripts/cluster_status.py --tenants HOST:PORT [...]  # cost table

Exit status: 0 when every polled daemon answered and reports healthy
with all breakers closed; 1 otherwise — so a deploy script can gate on
it directly.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.error
import urllib.request

COLUMNS = ("daemon", "health", "peers", "brk-open", "ring", "handoff",
           "occupancy", "evict", "queue", "shed", "burn-5m", "burn-1h",
           "audit", "recompiles", "dc", "regions", "carry", "flush-age",
           "hot-key", "hot-tenant", "blackbox")

TENANT_COLUMNS = ("tenant", "hits", "lanes", "over-limit", "shed",
                  "ingress-MB", "lane-time-s", "queue-s", "daemons")


def fetch_status(addr: str, timeout_s: float = 5.0) -> dict:
    url = f"http://{addr}/debug/status"
    with urllib.request.urlopen(url, timeout=timeout_s) as resp:
        return json.loads(resp.read())


def summarize(addr: str, doc: dict) -> dict:
    occ = doc.get("occupancy", {})
    ingress = doc.get("ingress", {})
    slo = doc.get("slo", {})
    hot = doc.get("hotkeys") or []
    region = doc.get("region", {})
    ring = doc.get("ring", {})
    reshard = ring.get("reshard", {})
    # gen@hash (short), e.g. "3@13db0387"; handoff column shows the
    # live double-dispatch window or the abort count when nonzero.
    ring_cell = f"{ring.get('generation', 0)}@{ring.get('hash', '')[:8]}"
    if ring.get("handoffActive"):
        handoff_cell = f"active {ring.get('handoffRemainingS', 0)}s"
    elif reshard.get("transfersAborted"):
        handoff_cell = f"aborts:{reshard['transfersAborted']}"
    else:
        handoff_cell = "-"
    # Federation plane (PR 11): remote-region ring sizes with their
    # breaker-open counts, e.g. "eu:2 us:2!1" (! = open breakers), plus
    # the carry depth and last-flush age a stalled WAN link shows up in.
    remotes = region.get("regions", {})
    if region.get("dataCenter") and remotes:
        regions_cell = " ".join(
            f"{dc}:{st.get('peers', 0)}"
            + (f"!{st['breakerOpen']}" if st.get("breakerOpen") else "")
            for dc, st in sorted(remotes.items())
        )
    else:
        regions_cell = "-"
    flush_age = region.get("lastFlushAgeS")
    top_tenants = doc.get("tenants", {}).get("topk") or []
    # Incident black box (PR 15): bundles written this run / bundles on
    # disk, with the last-trigger age when one fired — "bb 2/2 31s ago"
    # answers "did the incident leave evidence" at a glance.
    bb = doc.get("blackbox", {})
    if bb.get("enabled"):
        bb_cell = f"{bb.get('bundles', 0)}/{bb.get('bundlesOnDisk', 0)}"
        age = bb.get("lastTriggerAgeS")
        if age is not None:
            bb_cell += f" {int(age)}s ago"
    else:
        bb_cell = "-"
    return {
        "daemon": addr,
        "health": doc.get("health", {}).get("status", "?"),
        "peers": doc.get("health", {}).get("peerCount", 0),
        "brk-open": doc.get("health", {}).get("breakerOpenCount", 0),
        "ring": ring_cell,
        "handoff": handoff_cell,
        "occupancy": f"{occ.get('used', 0)}/{occ.get('capacity', 0)}",
        "evict": occ.get("evictions", 0),
        "queue": ingress.get("queuedLanes", 0),
        "shed": ingress.get("shedLanes", 0),
        "burn-5m": slo.get("burn_rate_5m", "-") if slo.get("enabled") else "-",
        "burn-1h": slo.get("burn_rate_1h", "-") if slo.get("enabled") else "-",
        # Conservation-audit verdicts + XLA steady-state recompiles
        # (PR 9): either nonzero is a page-worthy cell.
        "audit": (
            doc.get("audit", {}).get("violationTotal", 0)
            if doc.get("audit", {}).get("enabled", False) else "-"
        ),
        "recompiles": (
            doc.get("xla", {}).get("steadyRecompiles", 0)
            if doc.get("xla", {}).get("enabled", False) else "-"
        ),
        "dc": region.get("dataCenter") or "-",
        "regions": regions_cell,
        "carry": (
            region.get("carryKeyTotal", 0)
            if region.get("dataCenter") else "-"
        ),
        "flush-age": (
            f"{flush_age}s" if flush_age is not None else "-"
        ),
        "hot-key": hot[0]["key"] if hot else "-",
        # Cost observatory (PR 12): the daemon's costliest tenant by
        # ledger rank, e.g. "tenant-hot:4821" (name:hits).
        "hot-tenant": (
            f"{top_tenants[0]['tenant']}:{top_tenants[0]['hits']}"
            if top_tenants else "-"
        ),
        "blackbox": bb_cell,
    }


def render(rows: list, columns: tuple = COLUMNS) -> str:
    widths = {
        c: max(len(c), *(len(str(r.get(c, ""))) for r in rows))
        for c in columns
    }
    lines = ["  ".join(c.ljust(widths[c]) for c in columns)]
    for r in rows:
        lines.append(
            "  ".join(str(r.get(c, "")).ljust(widths[c]) for c in columns)
        )
    return "\n".join(lines)


def poll_tenants(addrs: list, as_json: bool) -> int:
    """Fleet-aggregated per-tenant cost table: every daemon's
    GET /debug/tenants summed by tenant name (a forwarded lane folds
    at both its ingress daemon and its owner, so fleet rows read as
    door-crossings — consistent across daemons, like the audit's
    ingress counters).  Each daemon's `other` rollup and totals are
    carried as their own rows so the fleet view conserves too."""
    agg: dict = {}
    docs = {}
    other = dict.fromkeys(
        ("hits", "lanes", "overLimit", "shed", "ingressBytes",
         "laneTimeS", "queueS"), 0.0
    )
    totals = dict(other)
    rc = 0
    for addr in addrs:
        try:
            with urllib.request.urlopen(
                f"http://{addr}/debug/tenants", timeout=5.0
            ) as resp:
                doc = json.loads(resp.read())
        except (urllib.error.URLError, OSError, ValueError) as e:
            print(f"{addr}: UNREACHABLE ({e})", file=sys.stderr)
            rc = 1
            continue
        docs[addr] = doc
        for row in doc.get("topk", []):
            cell = agg.setdefault(
                row["tenant"], {**dict.fromkeys(other, 0.0), "daemons": 0}
            )
            for k in other:
                cell[k] += row.get(k, 0)
            cell["daemons"] += 1
        for k in other:
            other[k] += doc.get("other", {}).get(k, 0)
            totals[k] += doc.get("totals", {}).get(k, 0)
    if as_json:
        print(json.dumps(docs, indent=2))
        return rc
    if not agg and not docs:
        return rc

    def _row(name, cell, daemons):
        return {
            "tenant": name,
            "hits": int(cell["hits"]),
            "lanes": int(cell["lanes"]),
            "over-limit": int(cell["overLimit"]),
            "shed": int(cell["shed"]),
            "ingress-MB": round(cell["ingressBytes"] / 1e6, 3),
            "lane-time-s": round(cell["laneTimeS"], 3),
            "queue-s": round(cell["queueS"], 3),
            "daemons": daemons,
        }

    rows = [
        _row(name, cell, cell["daemons"])
        for name, cell in sorted(
            agg.items(), key=lambda kv: kv[1]["hits"], reverse=True
        )
    ]
    rows.append(_row("(other)", other, len(docs)))
    rows.append(_row("(fleet total)", totals, len(docs)))
    print(render(rows, TENANT_COLUMNS))
    return rc


def poll_once(addrs: list, as_json: bool) -> int:
    rows, docs, rc = [], {}, 0
    for addr in addrs:
        try:
            doc = fetch_status(addr)
        except (urllib.error.URLError, OSError, ValueError) as e:
            rows.append({"daemon": addr, "health": f"UNREACHABLE ({e})"})
            rc = 1
            continue
        docs[addr] = doc
        row = summarize(addr, doc)
        if row["health"] != "healthy" or row["brk-open"]:
            rc = 1
        # Conservation violations gate the exit code like health does:
        # a deploy script must not read a double-committing cluster as
        # green.
        if isinstance(row["audit"], int) and row["audit"]:
            rc = 1
        rows.append(row)
    if as_json:
        print(json.dumps(docs, indent=2))
    else:
        print(render(rows))
    return rc


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("addrs", nargs="+", metavar="HOST:PORT",
                    help="daemon HTTP gateway addresses")
    ap.add_argument("--watch", type=float, metavar="SECONDS", default=0,
                    help="re-poll every N seconds until interrupted")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="print raw /debug/status docs instead of the table")
    ap.add_argument("--tenants", action="store_true",
                    help="fleet-aggregated per-tenant cost table "
                         "(GET /debug/tenants across all daemons)")
    args = ap.parse_args()
    poll = poll_tenants if args.tenants else poll_once
    if not args.watch:
        return poll(args.addrs, args.as_json)
    rc = 0
    try:
        while True:
            print(f"-- {time.strftime('%H:%M:%S')} --")
            rc = max(rc, poll(args.addrs, args.as_json))
            time.sleep(args.watch)
    except KeyboardInterrupt:
        # Exit-code contract holds in watch mode too: nonzero if ANY
        # poll saw an unreachable/unhealthy daemon.
        return rc


if __name__ == "__main__":
    sys.exit(main())
