#!/usr/bin/env python
"""Multi-daemon soak harness (`make soak`): the ROADMAP item-5 proving
ground, gated by the conservation audit.

Stands up an in-process cluster of N daemons (default 4) on loopback
ports — real gateways, real peer wire, real device dispatch — and
drives it for minutes with:

* **Zipf traffic** — key popularity drawn from a seeded Zipf
  distribution (the viral-key shape), mixed token/leaky algorithms,
  a slice of GLOBAL-behavior lanes, through rotating entry daemons so
  every request shape crosses the peer hop.  Lanes spread over a small
  TENANT pool (the rate-limit name is the tenant unit), with one
  PLANTED HOT TENANT soaking the burst traffic — the cost
  observatory's per-tenant ledger (profiling.py, GET /debug/tenants)
  must rank it #1 on its owner daemon and must conserve
  (top-K + other == totals) on every poll, and at final quiesce the
  summed tenant ledgers must reconcile EXACTLY against the audit
  ledger's ingress counters (ingress_hits + peer_ingress_hits).
* **Burst replay** — periodic bursts replaying one hot key at
  many-lane batches (the retry-storm shape), under the hot tenant's
  name on one fixed key so the tenant has a single owner daemon.
* **FaultPlan partitions** — a seeded fault plan periodically
  partitions one daemon's data plane (ERROR rules) and heals it, so
  breakers trip, degraded evaluation engages, and the GLOBAL plane
  requeues — all paths the conservation ledger must reconcile through.
* **Membership churn** — periodically drops one daemon from everyone's
  peer list and re-adds it, driving ring deltas, the double-dispatch
  window, and reshard transfers.
* **Multi-region federation** (`--regions RxD`, e.g. `2x2`) — the
  daemons split into R regions of D (distinct GUBER_DATA_CENTER
  labels), a slice of lanes turns MULTI_REGION so the federation plane
  replicates cross-region, the inter-region wire runs under an
  always-on seeded WAN shape (FaultPlan `wan`: normal-ish latency +
  jitter + rate loss), fault events become WAN storms against one
  region's daemons (heavy loss — an effective partition — injected
  then healed), and churn rotates WITHIN a region so each region
  reshards independently.  The exit gate additionally requires the
  region ledger to have moved (the plane demonstrably ran).

Trace-sampled (GUBER_TRACE_SAMPLE default 0.02) so
scripts/trace_collect.py can stitch cross-daemon traces from the run.

PASS/FAIL gate, checked every poll and at exit (exit code 1 on any):

* any `gubernator_audit_violations_total` increment on any daemon
  (the audit IS the soak's oracle: no double-commits, no lost hits,
  carry within the documented slack, no negative remaining);
* a daemon that stops answering /debug/status outside a deliberate
  partition window;
* zero traffic progress.

`--smoke` runs the 60-second 2-daemon variant (the `make soak-smoke`
pytest twin asserts the same invariants in-suite).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
import urllib.request

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def _fetch(addr: str, path: str, timeout_s: float = 10.0) -> dict:
    with urllib.request.urlopen(
        f"http://{addr}{path}", timeout=timeout_s
    ) as r:
        return json.loads(r.read())


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--minutes", type=float, default=3.0)
    ap.add_argument("--daemons", type=int, default=4)
    ap.add_argument("--workers", type=int, default=8)
    ap.add_argument("--keys", type=int, default=2000)
    ap.add_argument("--zipf-a", type=float, default=1.2,
                    help="Zipf exponent (>1; larger = hotter head)")
    ap.add_argument("--seed", type=int, default=42)
    ap.add_argument("--trace-sample", type=float, default=0.02)
    ap.add_argument("--poll-every", type=float, default=3.0)
    ap.add_argument("--fault-every", type=float, default=20.0,
                    help="seconds between partition injections (0=off)")
    ap.add_argument("--fault-for", type=float, default=4.0,
                    help="partition duration seconds")
    ap.add_argument("--churn-every", type=float, default=45.0,
                    help="seconds between membership churn events (0=off)")
    ap.add_argument("--regions", default="",
                    help="RxD federation topology (e.g. 2x2 = two "
                         "2-daemon regions); overrides --daemons")
    ap.add_argument("--smoke", action="store_true",
                    help="60s, 2 daemons, no churn (CI-speed)")
    args = ap.parse_args()
    if args.smoke:
        args.minutes = 1.0
        args.daemons = 2
        args.churn_every = 0.0
    n_regions, per_region = 0, 0
    if args.regions:
        try:
            r, d = args.regions.lower().split("x")
            n_regions, per_region = int(r), int(d)
        except ValueError:
            ap.error(f"--regions must look like 2x2, got {args.regions!r}")
        if n_regions < 2 or per_region < 1:
            ap.error("--regions needs >= 2 regions of >= 1 daemon")
        args.daemons = n_regions * per_region

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.setdefault(
        "XLA_FLAGS", "--xla_force_host_platform_device_count=8"
    )

    import numpy as np

    from gubernator_tpu import faults
    from gubernator_tpu.client import V1Client
    from gubernator_tpu.cluster import Cluster, fast_test_behaviors
    from gubernator_tpu.types import (
        Algorithm,
        Behavior,
        GetRateLimitsRequest,
        RateLimitRequest,
    )

    rng = np.random.RandomState(args.seed)
    beh = fast_test_behaviors()
    beh.batch_timeout_s = 30.0
    beh.trace_sample = args.trace_sample
    beh.latency_target_ms = 30_000.0
    beh.audit = True
    beh.audit_interval_s = 2.0
    # Churn opens the double-dispatch window for real (the test default
    # turns it off because every fixture startup is a membership change).
    beh.reshard_handoff_s = 1.0 if args.churn_every else 0.0

    plan = faults.FaultPlan(seed=args.seed)
    faults.install(plan)

    deadline = time.time() + args.minutes * 60.0
    # Region labels per daemon: "" (single-region, the pre-federation
    # shape) unless --regions asked for an RxD split.
    dcs = (
        [f"region-{chr(97 + r)}"
         for r in range(n_regions) for _ in range(per_region)]
        if n_regions else [""] * args.daemons
    )
    print(
        f"soak: {args.daemons} daemons"
        + (f" in {n_regions} regions of {per_region}" if n_regions else "")
        + f", {args.minutes:.1f} min, "
        f"zipf a={args.zipf_a} over {args.keys} keys, seed {args.seed}, "
        f"trace sample {args.trace_sample}"
    )
    cl = Cluster().start_with(dcs, behaviors=beh)
    addrs = [d.gateway.address for d in cl.daemons]
    print(f"soak: gateways {addrs}")
    if n_regions:
        # Always-on WAN shape on the inter-region wire (the region op
        # only matches cross-region sends, so local rings stay LAN).
        plan.wan(op="UpdateRegionColumns",
                 latency_s=0.02, jitter_s=0.005, loss=0.02)
        print("soak: WAN shape on region wire "
              "(20ms ± 5ms, 2% loss, seeded)")

    stop = threading.Event()
    lock = threading.Lock()
    stats = {"requests": 0, "lanes": 0, "errors": []}
    # Zipf ranks -> key ids (bounded; np.random.zipf is unbounded)
    zipf_pool = (rng.zipf(args.zipf_a, size=200_000) - 1) % args.keys

    # Tenant pool (the cost-observatory soak satellite): the planted
    # hot tenant rides every burst ON ONE FIXED KEY — a single hash
    # key has a single owner daemon, which is where the "is the hot
    # tenant ranked #1 on its owner" assertion is checked; steady
    # lanes rotate over the cold tenants.
    HOT_TENANT = "tenant-hot"
    HOT_KEY = f"{HOT_TENANT}_hot"  # name_unique-key, the hash-key rule
    cold_tenants = [f"tenant-{c}" for c in "abcdef"]

    def worker(wid: int) -> None:
        wrng = np.random.RandomState(args.seed * 1000 + wid)
        client = V1Client(addrs[wid % len(addrs)], timeout_s=60.0)
        i = 0
        while not stop.is_set():
            # Burst cadence sized so the hot tenant DOMINATES: ~1/15
            # of requests x 200 lanes ≈ half of all lanes, vs ~1/6 of
            # the rest per cold tenant — rank #1 must be unambiguous
            # on every daemon even in a 60s smoke.
            burst = (i % 15) == 14
            lanes = 200 if burst else int(wrng.choice([1, 8, 50]))
            ids = (
                np.full(lanes, zipf_pool[wrng.randint(len(zipf_pool))])
                if burst  # burst replay: one hot key, many lanes
                else zipf_pool[wrng.randint(0, len(zipf_pool), size=lanes)]
            )
            reqs = [
                RateLimitRequest(
                    name=(
                        HOT_TENANT if burst
                        else cold_tenants[(int(k) + j) % len(cold_tenants)]
                    ),
                    unique_key="hot" if burst else f"k{int(k)}",
                    hits=1,
                    limit=1_000_000_000,
                    duration=300_000,
                    algorithm=(
                        Algorithm.TOKEN_BUCKET if (j + wid) % 2 == 0
                        else Algorithm.LEAKY_BUCKET
                    ),
                    behavior=(
                        # The hot tenant stays on the plain forwarded
                        # fast path: its folds land at ONE owner.
                        0 if burst
                        else int(Behavior.GLOBAL) if int(k) % 17 == 0
                        else int(Behavior.MULTI_REGION)
                        if n_regions and int(k) % 13 == 5
                        else 0
                    ),
                )
                for j, k in enumerate(ids)
            ]
            try:
                resp = client.get_rate_limits(
                    GetRateLimitsRequest(requests=reqs)
                )
                errs = [r.error for r in resp.responses if r.error]
                with lock:
                    stats["requests"] += 1
                    stats["lanes"] += lanes
                    stats["errors"].extend(errs[:2])
            except Exception as e:  # noqa: BLE001 — partitions make some fail
                with lock:
                    stats["errors"].append(f"{type(e).__name__}: {e}")
            i += 1

    threads = [
        threading.Thread(target=worker, args=(w,), daemon=True)
        for w in range(args.workers)
    ]
    for t in threads:
        t.start()

    failures: list = []
    heal_at = None
    heal_fault = None
    fault_events = 0
    churn_events = 0
    next_fault = time.time() + args.fault_every if args.fault_every else None
    next_churn = time.time() + args.churn_every if args.churn_every else None
    churned_idx = None
    baseline_err = 0
    try:
        while time.time() < deadline and not failures:
            time.sleep(args.poll_every)
            now = time.time()
            # -- fault scheduling --------------------------------------
            if heal_at is not None and now >= heal_at:
                heal_fault()
                heal_at, heal_fault = None, None
            if (next_fault is not None and now >= next_fault
                    and heal_at is None):
                if n_regions and fault_events % 2 == 0:
                    # WAN storm: near-total seeded loss on the region
                    # wire TOWARD one region — an inter-region
                    # partition the federation carry must ride out —
                    # injected, then healed back to the steady WAN
                    # shape (its peer="*" rule survives the per-peer
                    # heal).
                    region = int(rng.randint(n_regions))
                    victims = [
                        d.peer_info.grpc_address
                        for d in cl.daemons[
                            region * per_region:(region + 1) * per_region
                        ]
                    ]
                    for v in victims:
                        plan.wan(peer=v, op="UpdateRegionColumns",
                                 latency_s=0.08, jitter_s=0.03, loss=0.9)

                    def heal_fault(vs=tuple(victims),
                                   label=chr(97 + region)) -> None:
                        for v in vs:
                            plan.heal(v, "UpdateRegionColumns")
                        print(f"soak: healed WAN storm toward region-{label}")

                    print(
                        f"soak: WAN storm toward region-{chr(97 + region)} "
                        f"({victims}) for {args.fault_for}s"
                    )
                else:
                    victim = cl.daemons[
                        int(rng.randint(len(cl.daemons)))
                    ].peer_info.grpc_address
                    plan.partition(victim)

                    def heal_fault(v=victim) -> None:
                        plan.heal(v)
                        print(f"soak: healed partition of {v}")

                    print(f"soak: partitioned {victim} for {args.fault_for}s")
                heal_at = now + args.fault_for
                fault_events += 1
                next_fault = now + args.fault_every
            if next_churn is not None and now >= next_churn:
                next_churn = now + args.churn_every
                if churned_idx is None:
                    if n_regions and per_region >= 2:
                        # Per-region churn: rotate regions, drop the
                        # region's LAST member so its local ring
                        # reshards while the other regions' ownership
                        # stays put (the region-picker stability
                        # property).
                        region = churn_events % n_regions
                        churned_idx = region * per_region + per_region - 1
                        churn_events += 1
                    else:
                        churned_idx = int(rng.randint(1, len(cl.daemons)))
                    peers = [
                        p for j, p in enumerate(cl.peers) if j != churned_idx
                    ]
                    print(
                        f"soak: churn OUT {cl.peers[churned_idx].grpc_address}"
                    )
                else:
                    peers = list(cl.peers)
                    print(
                        f"soak: churn IN {cl.peers[churned_idx].grpc_address}"
                    )
                    churned_idx = None
                for d in cl.daemons:
                    d.set_peers(peers)
            # -- invariant polling -------------------------------------
            for i, addr in enumerate(addrs):
                try:
                    aud = _fetch(addr, "/debug/audit")
                except OSError as e:
                    if heal_at is None:
                        failures.append(f"{addr}: unreachable: {e}")
                    continue
                if aud["violationTotal"]:
                    failures.append(
                        f"{addr}: AUDIT VIOLATIONS {aud['violations']} "
                        f"ledger={aud['ledger']}"
                    )
                # Cost observatory: the tenant ledger must CONSERVE on
                # every poll — top-K rows + the `other` rollup must sum
                # exactly to the totals for every stat (eviction moves
                # stats between buckets, never loses them).
                try:
                    ten = _fetch(addr, "/debug/tenants")
                except OSError:
                    continue  # reachability already judged above
                for stat in ("hits", "lanes", "overLimit", "shed",
                             "ingressBytes"):
                    parts = (
                        sum(r[stat] for r in ten["topk"])
                        + ten["other"][stat]
                    )
                    if parts != ten["totals"][stat]:
                        failures.append(
                            f"{addr}: tenant ledger LEAK on {stat}: "
                            f"topk+other={parts} != "
                            f"totals={ten['totals'][stat]}"
                        )
            with lock:
                nerr = len(stats["errors"])
                reqs = stats["requests"]
            print(
                f"soak: t-{max(deadline - now, 0):.0f}s requests={reqs} "
                f"errors={nerr - baseline_err}"
            )
            baseline_err = nerr
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=60)
        # Final reconciliation with traffic quiesced: run one audit
        # check on every daemon (in-flight lag has drained, so the
        # inequalities are at their tightest).
        for d in cl.daemons:
            try:
                d.service.auditor.check_now()
                snap = d.service.auditor.snapshot()
                if snap["violationTotal"]:
                    failures.append(
                        f"{d.gateway.address}: final audit violations "
                        f"{snap['violations']}"
                    )
            except Exception as e:  # noqa: BLE001
                failures.append(f"final audit check failed: {e}")
        sample = {}
        try:
            sample = _fetch(addrs[0], "/debug/audit")
        except OSError:
            pass
        # -- cost-observatory final reconciliation (quiesced) ----------
        # (1) The planted hot tenant must be ranked #1 on its owner
        # daemon: HOT_KEY has exactly one owner in the current ring,
        # and every burst lane folded there (locally or through the
        # peer door).
        try:
            owner_addr = (
                cl.daemons[0].service.get_peer(HOT_KEY).info.grpc_address
            )
            owner = next(
                d for d in cl.daemons
                if d.peer_info.grpc_address == owner_addr
            )
            ten = _fetch(owner.gateway.address, "/debug/tenants")
            if not ten["topk"] or ten["topk"][0]["tenant"] != HOT_TENANT:
                failures.append(
                    f"hot tenant not #1 on owner {owner.gateway.address}: "
                    f"top={[r['tenant'] for r in ten['topk'][:3]]}"
                )
            else:
                print(
                    f"soak: hot tenant '{HOT_TENANT}' ranked #1 on owner "
                    f"{owner.gateway.address} "
                    f"(hits={ten['topk'][0]['hits']})"
                )
        except Exception as e:  # noqa: BLE001
            failures.append(f"hot-tenant owner check failed: {e}")
        # (2) The summed per-daemon tenant ledgers must reconcile
        # EXACTLY with the audit ledger's ingress counters: every
        # audit ingress note has a tenant fold beside it, so at
        # quiesce  sum(tenant totals.hits) == ingress_hits +
        # peer_ingress_hits  (the in-process cluster shares one audit
        # ledger; forwarded lanes count once per door on both sides).
        try:
            from gubernator_tpu import audit as audit_ledger

            tenant_hits = sum(
                d.service.tenants.totals()["hits"] for d in cl.daemons
            )
            led = audit_ledger.ledger_snapshot()
            audit_ingress = (
                led.get("ingress_hits", 0) + led.get("peer_ingress_hits", 0)
            )
            if tenant_hits != audit_ingress:
                failures.append(
                    f"tenant ledger does not reconcile with audit: "
                    f"sum(tenant hits)={tenant_hits} != ingress_hits+"
                    f"peer_ingress_hits={audit_ingress}"
                )
            else:
                print(
                    f"soak: tenant ledgers reconcile with audit ingress "
                    f"({tenant_hits} hits)"
                )
        except Exception as e:  # noqa: BLE001
            failures.append(f"tenant/audit reconciliation failed: {e}")
        faults.uninstall()
        cl.stop()

    with lock:
        reqs, lanes = stats["requests"], stats["lanes"]
    print(
        f"soak: done — {reqs} requests / {lanes} lanes; "
        f"ledger sample: { {k: v for k, v in sample.get('ledger', {}).items() if v} }"
    )
    if reqs == 0:
        failures.append("soak made zero progress")
    if n_regions:
        # The topology must have EXERCISED the federation plane: a 2x2
        # run whose region ledger never moved proves nothing about it.
        # (The ledger is process-shared, so read it directly — it
        # outlives the stopped cluster.)
        from gubernator_tpu import audit as audit_ledger

        if not audit_ledger.ledger_snapshot().get("region_sent_hits"):
            failures.append(
                "region plane made zero progress (region_sent_hits == 0)"
            )
    if failures:
        print("soak: FAIL")
        for f in failures[:10]:
            print(f"  - {f}")
        return 1
    print("soak: PASS (zero conservation violations)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
