#!/usr/bin/env python
"""Generate gubernator_tpu/proto/peers_columns_pb2.py from
peers_columns.proto WITHOUT protoc.

The build image has no protoc binary, so this script constructs the
FileDescriptorProto programmatically (google.protobuf.descriptor_pb2 —
the exact structure protoc serializes) and emits a pb2 module in the
same AddSerializedFile style protoc generates.  The .proto source file
next to the output is the human-readable schema of record; when a
protoc becomes available, `protoc --python_out` over it must produce a
wire-identical descriptor (same fields, same numbers, proto3 packed
defaults).

Run: python scripts/gen_columns_proto.py
"""

from __future__ import annotations

import os

from google.protobuf import descriptor_pb2 as dp

F = dp.FieldDescriptorProto

OUT = os.path.join(
    os.path.dirname(__file__), "..", "gubernator_tpu", "proto",
    "peers_columns_pb2.py",
)


def _field(msg, name, number, ftype, label=F.LABEL_REPEATED, type_name=""):
    f = msg.field.add()
    f.name = name
    f.number = number
    f.label = label
    f.type = ftype
    if type_name:
        f.type_name = type_name
    return f


def build() -> bytes:
    fd = dp.FileDescriptorProto()
    fd.name = "peers_columns.proto"
    fd.package = "pb.gubernator"
    fd.syntax = "proto3"
    fd.dependency.append("gubernator.proto")

    req = fd.message_type.add()
    req.name = "PeerColumnsReq"
    _field(req, "names", 1, F.TYPE_STRING)
    _field(req, "unique_keys", 2, F.TYPE_STRING)
    _field(req, "algorithm", 3, F.TYPE_INT32)
    _field(req, "behavior", 4, F.TYPE_INT32)
    _field(req, "hits", 5, F.TYPE_INT64)
    _field(req, "limit", 6, F.TYPE_INT64)
    _field(req, "duration", 7, F.TYPE_INT64)
    # Sparse trace-context column (tracing.py): 32-byte packed entries
    # `<II` lane_lo, lane_hi + 16B trace id + 8B span id (big-endian).
    # proto3 unknown-field semantics make this safely invisible to
    # pre-trace receivers; absent entries leave the wire byte-identical
    # (the GUBER_TRACE_SAMPLE=0 parity contract).
    _field(req, "trace", 8, F.TYPE_BYTES)

    ov = fd.message_type.add()
    ov.name = "PeerLaneOverride"
    _field(ov, "lane", 1, F.TYPE_INT32, label=F.LABEL_OPTIONAL)
    _field(ov, "resp", 2, F.TYPE_MESSAGE, label=F.LABEL_OPTIONAL,
           type_name=".pb.gubernator.RateLimitResp")

    resp = fd.message_type.add()
    resp.name = "PeerColumnsResp"
    _field(resp, "status", 1, F.TYPE_INT32)
    _field(resp, "limit", 2, F.TYPE_INT64)
    _field(resp, "remaining", 3, F.TYPE_INT64)
    _field(resp, "reset_time", 4, F.TYPE_INT64)
    _field(resp, "overrides", 5, F.TYPE_MESSAGE,
           type_name=".pb.gubernator.PeerLaneOverride")

    # Public columnar ingress response (V1/GetRateLimitsColumns): the
    # PeerColumnsResp layout plus the owner annotation — forwarded
    # lanes carry owner_of (index into owner_addrs, -1 = local) so the
    # client rebuilds metadata.owner without per-lane overrides.  The
    # REQUEST reuses PeerColumnsReq verbatim (same seven columns + the
    # sparse trace column; one codec, one golden).
    ir = fd.message_type.add()
    ir.name = "IngressColumnsResp"
    _field(ir, "status", 1, F.TYPE_INT32)
    _field(ir, "limit", 2, F.TYPE_INT64)
    _field(ir, "remaining", 3, F.TYPE_INT64)
    _field(ir, "reset_time", 4, F.TYPE_INT64)
    _field(ir, "overrides", 5, F.TYPE_MESSAGE,
           type_name=".pb.gubernator.PeerLaneOverride")
    _field(ir, "owner_of", 6, F.TYPE_INT32)
    _field(ir, "owner_addrs", 7, F.TYPE_STRING)

    # Column form of UpdatePeerGlobalsReq (the GLOBAL broadcast): lane i
    # of every column is one key's authoritative status.  Served as
    # PeersV1/UpdatePeerGlobalsColumns; the response reuses
    # UpdatePeerGlobalsResp (peers.proto) — the broadcast needs no body.
    gc = fd.message_type.add()
    gc.name = "GlobalsColumnsReq"
    _field(gc, "keys", 1, F.TYPE_STRING)
    _field(gc, "algorithm", 2, F.TYPE_INT32)
    _field(gc, "status", 3, F.TYPE_INT32)
    _field(gc, "limit", 4, F.TYPE_INT64)
    _field(gc, "remaining", 5, F.TYPE_INT64)
    _field(gc, "reset_time", 6, F.TYPE_INT64)

    # Ownership transfer (elastic membership, reshard.py): the moved
    # keys' full device bucket rows, stamped with the destination
    # ring's fingerprint (the epoch fence).  Served as
    # PeersV1/TransferOwnership.
    tr = fd.message_type.add()
    tr.name = "TransferColumnsReq"
    _field(tr, "ring_hash", 1, F.TYPE_UINT64, label=F.LABEL_OPTIONAL)
    _field(tr, "keys", 2, F.TYPE_STRING)
    _field(tr, "algorithm", 3, F.TYPE_INT32)
    _field(tr, "status", 4, F.TYPE_INT32)
    _field(tr, "limit", 5, F.TYPE_INT64)
    _field(tr, "remaining", 6, F.TYPE_INT64)
    _field(tr, "duration", 7, F.TYPE_INT64)
    _field(tr, "stamp", 8, F.TYPE_INT64)
    _field(tr, "expire_at", 9, F.TYPE_INT64)

    tresp = fd.message_type.add()
    tresp.name = "TransferResp"
    _field(tresp, "committed", 1, F.TYPE_INT64, label=F.LABEL_OPTIONAL)
    _field(tresp, "rejected", 2, F.TYPE_INT64, label=F.LABEL_OPTIONAL)

    # Multi-region federation (federation.py): one cross-region hit
    # batch — per-key summed MULTI_REGION hits plus the origin region's
    # GUBER_DATA_CENTER, the behavior column with MULTI_REGION already
    # stripped (the receiver applies, never re-queues).  Served as
    # PeersV1/UpdateRegionColumns.
    rc = fd.message_type.add()
    rc.name = "RegionColumnsReq"
    _field(rc, "origin", 1, F.TYPE_STRING, label=F.LABEL_OPTIONAL)
    _field(rc, "names", 2, F.TYPE_STRING)
    _field(rc, "unique_keys", 3, F.TYPE_STRING)
    _field(rc, "algorithm", 4, F.TYPE_INT32)
    _field(rc, "behavior", 5, F.TYPE_INT32)
    _field(rc, "hits", 6, F.TYPE_INT64)
    _field(rc, "limit", 7, F.TYPE_INT64)
    _field(rc, "duration", 8, F.TYPE_INT64)

    rresp = fd.message_type.add()
    rresp.name = "RegionColumnsResp"
    _field(rresp, "applied", 1, F.TYPE_INT64, label=F.LABEL_OPTIONAL)

    return fd.SerializeToString()


TEMPLATE = '''# -*- coding: utf-8 -*-
# Generated by scripts/gen_columns_proto.py (no protoc in this image;
# the descriptor below is the FileDescriptorProto for
# peers_columns.proto).  DO NOT EDIT BY HAND.
"""Generated protocol buffer code."""
from google.protobuf.internal import builder as _builder
from google.protobuf import descriptor as _descriptor
from google.protobuf import descriptor_pool as _descriptor_pool
from google.protobuf import symbol_database as _symbol_database

_sym_db = _symbol_database.Default()


from gubernator_tpu.proto import gubernator_pb2 as gubernator__pb2


DESCRIPTOR = _descriptor_pool.Default().AddSerializedFile({serialized!r})

_builder.BuildMessageAndEnumDescriptors(DESCRIPTOR, globals())
_builder.BuildTopDescriptorsAndMessages(DESCRIPTOR, 'peers_columns_pb2', globals())
'''


def main() -> None:
    serialized = build()
    with open(OUT, "w") as f:
        f.write(TEMPLATE.format(serialized=serialized))
    print(f"wrote {os.path.normpath(OUT)} ({len(serialized)} descriptor bytes)")


if __name__ == "__main__":
    main()
