#!/usr/bin/env python
"""Offline incident-bundle verify/dump (the black box's fsck).

Reads a gubernator-tpu incident bundle directory (blackbox.py format),
verifies manifest format/version, every file's size + CRC32 against
the manifest table, and every frame log's header + per-record CRC —
exactly the checks scripts/replay.py runs before it will re-drive a
single frame — and prints a summary.  Exit codes are gate-ready:

  0  bundle is complete and checksum-valid
  1  bundle is corrupt / truncated / bit-flipped / wrong version
  2  usage / IO error (missing directory)

Usage:
  python scripts/blackbox_fsck.py /var/lib/gubernator/blackbox/incident-...
  python scripts/blackbox_fsck.py --json BUNDLE_DIR
  python scripts/blackbox_fsck.py --frames BUNDLE_DIR   # per-frame rows
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("path", help="incident bundle directory to verify")
    p.add_argument("--json", action="store_true",
                   help="emit the verdict as JSON")
    p.add_argument("--frames", action="store_true",
                   help="include per-frame rows in the dump")
    args = p.parse_args(argv)

    from gubernator_tpu.blackbox import BundleError, load_bundle

    if not os.path.exists(args.path):
        print(f"blackbox_fsck: {args.path}: no such directory",
              file=sys.stderr)
        return 2
    if not os.path.isdir(args.path):
        print(f"blackbox_fsck: {args.path}: not a bundle directory",
              file=sys.stderr)
        return 2
    try:
        bundle = load_bundle(args.path)
    except OSError as e:
        print(f"blackbox_fsck: {args.path}: {e}", file=sys.stderr)
        return 2
    except BundleError as e:
        if args.json:
            print(json.dumps({"ok": False, "path": args.path,
                              "error": str(e)}))
        else:
            print(f"blackbox_fsck: {args.path}: REJECTED: {e}",
                  file=sys.stderr)
        return 1

    m = bundle.manifest
    doc = {
        "ok": True,
        "path": args.path,
        "name": m.get("name", ""),
        "version": m.get("version"),
        "wallNs": m.get("wallNs"),
        "service": m.get("service", {}),
        "triggers": [t.get("kind") for t in m.get("triggers", [])],
        "suppressedTriggers": m.get("suppressedTriggers", 0),
        "files": len(m.get("files", {})),
        "frames": {w: len(recs) for w, recs in bundle.frames.items()},
        "frameBytes": {
            w: sum(len(r[5]) for r in recs)
            for w, recs in bundle.frames.items()
        },
    }
    if args.frames:
        doc["frameRows"] = [
            {"wire": w, "wallNs": r[0], "monoNs": r[1], "dir": r[2],
             "peer": r[3], "kind": r[4], "bytes": len(r[5])}
            for w, recs in sorted(bundle.frames.items()) for r in recs
        ]
    if args.json:
        print(json.dumps(doc, indent=2))
    else:
        frames = " ".join(
            f"{w}:{n}" for w, n in sorted(doc["frames"].items()) if n
        ) or "none"
        print(
            f"{args.path}: OK v{doc['version']} — "
            f"triggers={','.join(doc['triggers']) or 'none'} "
            f"frames=[{frames}] files={doc['files']}"
        )
        if args.frames:
            for row in doc["frameRows"]:
                print(
                    f"  {row['wire']:<9} {row['dir']:<3} kind={row['kind']} "
                    f"peer={row['peer'] or '-'} bytes={row['bytes']} "
                    f"wall_ns={row['wallNs']}"
                )
    return 0


if __name__ == "__main__":
    sys.exit(main())
