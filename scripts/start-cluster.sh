#!/bin/sh
# Start a local multi-node gubernator-tpu cluster for development
# (the scripts/start-cluster.sh equivalent: the reference launches N
# server binaries with per-instance env; here the in-process cluster
# binary spawns N real daemons sharing the device and prints their
# addresses, Ctrl-C to stop).
set -eu
NODES="${NODES:-6}"
exec python -m gubernator_tpu.cmd.cluster_main --nodes "$NODES" "$@"
