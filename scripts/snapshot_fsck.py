#!/usr/bin/env python
"""Offline snapshot verify/dump (the durability plane's fsck).

Reads a gubernator-tpu snapshot file (snapshot.py format), verifies
magic/version/length/checksum — exactly the checks the boot restore
runs — and prints a summary or a JSON dump.  Exit codes are gate-ready:

  0  file is a complete, checksum-valid snapshot
  1  file is corrupt / truncated / wrong version / wrong ring
  2  usage / IO error (missing file)

Usage:
  python scripts/snapshot_fsck.py /var/lib/gubernator/gub.snap
  python scripts/snapshot_fsck.py --json --keys gub.snap
  python scripts/snapshot_fsck.py --expect-ring 0xDEADBEEF... gub.snap
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("path", help="snapshot file to verify")
    p.add_argument("--json", action="store_true",
                   help="emit the verdict (and --keys dump) as JSON")
    p.add_argument("--keys", action="store_true",
                   help="include per-lane key/remaining rows in the dump")
    p.add_argument("--expect-ring", default=None, metavar="HASH",
                   help="strict fencing: fail unless the file's membership "
                        "fingerprint matches (hex or decimal; unfenced "
                        "files always pass)")
    args = p.parse_args(argv)

    from gubernator_tpu.snapshot import SnapshotError, read_snapshot

    expected = int(args.expect_ring, 0) if args.expect_ring else None
    try:
        cols, meta = read_snapshot(args.path, expected_ring=expected)
    except FileNotFoundError:
        print(f"snapshot_fsck: {args.path}: no such file", file=sys.stderr)
        return 2
    except OSError as e:
        print(f"snapshot_fsck: {args.path}: {e}", file=sys.stderr)
        return 2
    except SnapshotError as e:
        if args.json:
            print(json.dumps({"ok": False, "path": args.path,
                              "error": str(e)}))
        else:
            print(f"snapshot_fsck: {args.path}: REJECTED: {e}",
                  file=sys.stderr)
        return 1

    doc = {
        "ok": True,
        "path": args.path,
        "version": meta["version"],
        "lanes": meta["lanes"],
        "bytes": meta["bytes"],
        "savedAtMs": meta["saved_at_ms"],
        "ringHash": format(meta["ring_hash"], "016x"),
    }
    if args.keys:
        doc["rows"] = [
            {
                "key": cols.keys[i],
                "algorithm": int(cols.algorithm[i]),
                "limit": int(cols.limit[i]),
                "remaining": int(cols.remaining[i]),
                "expireAtMs": int(cols.expire_at[i]),
            }
            for i in range(len(cols))
        ]
    if args.json:
        print(json.dumps(doc, indent=2))
    else:
        print(
            f"{args.path}: OK v{doc['version']} — {doc['lanes']} lanes, "
            f"{doc['bytes']} bytes, saved_at_ms={doc['savedAtMs']}, "
            f"ring={doc['ringHash']}"
        )
        if args.keys:
            for row in doc["rows"]:
                print(
                    f"  {row['key']}: remaining={row['remaining']}/"
                    f"{row['limit']} algo={row['algorithm']} "
                    f"expire={row['expireAtMs']}"
                )
    return 0


if __name__ == "__main__":
    sys.exit(main())
