"""Long soak of the async native edge on the REAL TPU: 2 daemons,
mixed request shapes (single-key batched/NO_BATCHING, 50/200-lane,
GLOBAL, MULTI_REGION), raw half-close clients, one daemon RESTART
mid-soak.  Steady-state phases must be error-free; only the churn
window tolerates transient failures (fast connect-refused retries
while the restarted daemon is down).

Recorded run (round 5, 10 min on the tunnel chip): 34,724 requests /
756,447 lanes, ZERO steady-state errors, restart survived, no stuck
threads.  Run from the repo root:

    PYTHONPATH=/root/.axon_site:. python -u scripts/long_soak.py

The peer deadline is tunnel-provisioned (60 s) -- the same
GUBER_BATCH_TIMEOUT tuning a real deployment applies for its device
latency; the default deadline would measure expiry, not the software
(the cfg5 lesson, benchmarks/RESULTS.md)."""
import json
import socket
import threading
import time

from gubernator_tpu.client import V1Client
from gubernator_tpu.cluster import Cluster, fast_test_behaviors
from gubernator_tpu.gateway import NativeGatewayServer
from gubernator_tpu.types import (
    Algorithm, Behavior, GetRateLimitsRequest, RateLimitRequest,
)

SOAK_S = 600
CHURN_AT_S = 240
CHURN_WINDOW_S = 90  # restart + re-peer + client reconnect grace (tunnel warmup)

# Tunnel-provisioned peer deadline (the cfg5 lesson, RESULTS.md): each
# forwarded leg waits on device rounds costing 100-400 ms+queueing
# through the tunnel; the default deadline measures expiry, not the
# software.  A real deployment sets GUBER_BATCH_TIMEOUT for its device.
beh = fast_test_behaviors()
beh.batch_timeout_s = 60.0
cl = Cluster().start_with(["", ""], native_http=True, behaviors=beh)
assert all(isinstance(d.gateway, NativeGatewayServer) for d in cl.daemons)
print(f"cluster up: {[d.gateway.address for d in cl.daemons]}", flush=True)

stop = threading.Event()
lock = threading.Lock()
stats = {"requests": 0, "lanes": 0, "steady_errors": [], "churn_errors": 0}
churn = {"active": False}
SHAPES = [
    (1, 0), (1, int(Behavior.NO_BATCHING)), (50, 0),
    (200, 0), (4, int(Behavior.GLOBAL)), (8, int(Behavior.MULTI_REGION)),
]


def worker(wid):
    i = 0
    client = None
    while not stop.is_set():
        if client is None:
            client = V1Client(cl.daemons[wid % 2].gateway.address, timeout_s=120.0)
        lanes, beh = SHAPES[(wid + i) % len(SHAPES)]
        reqs = [
            RateLimitRequest(
                name="lsoak", unique_key=f"w{wid % 3}k{(i + j) % 40}", hits=1,
                limit=100_000_000, duration=120_000,
                algorithm=Algorithm.TOKEN_BUCKET if j % 2 == 0 else Algorithm.LEAKY_BUCKET,
                behavior=beh,
            )
            for j in range(lanes)
        ]
        try:
            resp = client.get_rate_limits(GetRateLimitsRequest(requests=reqs))
            errs = [r.error for r in resp.responses if r.error]
            with lock:
                stats["requests"] += 1
                stats["lanes"] += lanes
                if errs:
                    if churn["active"]:
                        stats["churn_errors"] += len(errs)
                    else:
                        stats["steady_errors"].extend(errs[:2])
        except Exception as e:  # noqa: BLE001
            client = None  # reconnect (the daemon may have restarted)
            with lock:
                stats["requests"] += 1
                if churn["active"]:
                    stats["churn_errors"] += lanes
                else:
                    stats["steady_errors"].append(f"{type(e).__name__}: {e}")
        i += 1


def half_close_client():
    """Periodically exercise the EOF framing path against daemon 0."""
    while not stop.is_set():
        time.sleep(7)
        try:
            host, _, port = cl.daemons[0].gateway.address.partition(":")
            body = json.dumps({"requests": [{
                "name": "lsoak", "uniqueKey": "hc", "hits": "1",
                "limit": "1000000", "duration": "60000",
                "algorithm": "TOKEN_BUCKET"}]}).encode()
            with socket.create_connection((host, int(port)), timeout=120) as s:
                s.sendall(b"POST /v1/GetRateLimits HTTP/1.1\r\nHost: x\r\n"
                          b"Content-Length: %d\r\n\r\n" % len(body) + body)
                s.shutdown(socket.SHUT_WR)
                data = s.recv(65536)
                assert data.startswith(b"HTTP/1.1 200"), data[:80]
        except AssertionError:
            with lock:
                if not churn["active"]:
                    stats["steady_errors"].append("half-close got non-200")
        except Exception:  # noqa: BLE001 — churn-window connect refusals
            pass


threads = [threading.Thread(target=worker, args=(w,)) for w in range(12)]
threads.append(threading.Thread(target=half_close_client))
for t in threads:
    t.start()

t0 = time.time()
restarted = False
while time.time() - t0 < SOAK_S:
    time.sleep(5)
    el = time.time() - t0
    if not restarted and el >= CHURN_AT_S:
        print(f"[{el:.0f}s] RESTARTING daemon 1 mid-traffic", flush=True)
        churn["active"] = True
        cl.restart(1)
        restarted = True
        churn_end = time.time() + CHURN_WINDOW_S
    if restarted and churn["active"] and time.time() > churn_end:
        churn["active"] = False
        print(f"[{el:.0f}s] churn window closed; back to steady-state strictness", flush=True)
    with lock:
        print(f"[{el:.0f}s] reqs={stats['requests']} lanes={stats['lanes']} "
              f"steady_errs={len(stats['steady_errors'])} churn_errs={stats['churn_errors']}",
              flush=True)
    if stats["steady_errors"]:
        print("EARLY ERRORS:", stats["steady_errors"][:6], flush=True)
        break

stop.set()
for t in threads:
    t.join(timeout=180)
alive = [t.name for t in threads if t.is_alive()]
cl.stop()

print(f"final: {stats['requests']} requests / {stats['lanes']} lanes; "
      f"steady errors: {len(stats['steady_errors'])}; "
      f"churn-window errors: {stats['churn_errors']}; stuck threads: {alive}")
assert not alive, f"threads deadlocked: {alive}"
assert stats["requests"] > 200, "soak made no progress"
assert not stats["steady_errors"], stats["steady_errors"][:5]
print("LONG SOAK PASS", flush=True)
