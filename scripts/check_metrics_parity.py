#!/usr/bin/env python
"""Lint exported Prometheus metric names against the golden list.

Two classes of names, two rules:

* REFERENCE_PARITY — names ported verbatim from the reference
  Gubernator so its dashboards/alerts work unchanged (metrics.py
  module docstring).  FROZEN: renaming or dropping one silently breaks
  every deployed dashboard, so a diff here fails the build until the
  golden list is updated in the same reviewed change.

* EXTENSIONS — names this project added (fault tolerance, columnar
  hop, dispatch pipeline, tracing).  New names are allowed only by
  editing this list — i.e. every new exported series passes review
  here instead of appearing silently.

Exit 0 on exact match, 1 with a readable diff otherwise.  Wired into
`make tier1` and covered by tests/test_metrics_parity.py so the
ROADMAP verify command exercises it too.
"""

from __future__ import annotations

import os
import sys

# Runnable as `python scripts/check_metrics_parity.py` from the repo
# root without an installed package.
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# Names as prometheus_client reports them at collect() time (counters
# WITHOUT the _total suffix).
REFERENCE_PARITY = frozenset(
    {
        "gubernator_cache_size",            # cache.go:88-92
        "gubernator_cache_access_count",    # cache.go:205-218
        "gubernator_grpc_request_counts",   # grpc_stats.go:45-51
        "gubernator_grpc_request_duration", # grpc_stats.go:52-59
        "gubernator_async_durations",       # global.go:40-48
        "gubernator_broadcast_durations",   # global.go:49-56
    }
)

EXTENSIONS = frozenset(
    {
        # PR 1: peer fault tolerance
        "gubernator_circuit_breaker_state",
        "gubernator_circuit_breaker_transitions",
        "gubernator_peer_retry_count",
        "gubernator_degraded_local_evals",
        # PR 2: columnar peer hop
        "gubernator_peer_columns_batches",
        # PR 3: bounded ingress + dispatch pipeline
        "gubernator_ingress_shed",
        "gubernator_dispatch_inflight",
        "gubernator_dispatch_inflight_hwm",
        "gubernator_dispatch_stage_seconds",
        # PR 4: observability
        "gubernator_build_info",
        "gubernator_request_duration_seconds",
        # PR 5: columnar GLOBAL replication plane
        "gubernator_global_broadcast_batches",
        "gubernator_global_fanout_concurrency",
        "gubernator_global_requeued_hits",
        "gubernator_global_dropped_hits",
        # PR 6: saturation & SLO observability plane (saturation.py)
        "gubernator_latency_attribution_seconds",
        "gubernator_occupancy_slots",
        "gubernator_occupancy_capacity",
        "gubernator_occupancy_evictions",
        "gubernator_ingress_queue_lanes",
        "gubernator_batch_window_wait_seconds",
        "gubernator_lane_utilization",
        "gubernator_dispatcher_busy_ratio",
        "gubernator_slo_latency_target_ms",
        "gubernator_slo_burn_rate",
        "gubernator_slo_requests",
        "gubernator_hotkey_lanes",
        "gubernator_hotkey_topk",
        # PR 8: public columnar ingress (the front door)
        "gubernator_ingress_columns_batches",
        # PR 13: native service loop (host_runtime.cpp gt_ingress_*)
        "gubernator_native_ingress_batches",
        "gubernator_ingress_acceptor_requests",
        "gubernator_ingress_acceptor_conns",
        "gubernator_ingress_acceptor_frames",
        "gubernator_ingress_acceptor_lanes",
        # PR 7: elastic membership / live resharding (reshard.py)
        "gubernator_reshard_transfers",
        "gubernator_reshard_lanes",
        "gubernator_reshard_handoff_seconds",
        "gubernator_ring_generation",
        # PR 9: XLA/device telemetry (telemetry.py)
        "gubernator_xla_compiles",
        "gubernator_xla_compile_seconds",
        "gubernator_xla_steady_recompiles",
        "gubernator_xla_program_runs",
        "gubernator_device_memory_bytes",
        "gubernator_device_live_buffers",
        # PR 9: conservation audit (audit.py)
        "gubernator_audit_violations",
        "gubernator_audit_checks",
        "gubernator_audit_ledger",
        # PR 11: multi-region federation plane (federation.py)
        "gubernator_region_batches",
        "gubernator_region_carry_keys",
        "gubernator_region_requeued_hits",
        "gubernator_region_dropped_hits",
        # PR 10: durability plane (snapshot.py)
        "gubernator_snapshot_writes",
        "gubernator_snapshot_restores",
        "gubernator_snapshot_lanes",
        "gubernator_snapshot_age_seconds",
        # PR 12: cost observatory (profiling.py) — per-tenant cost
        # attribution (top-K + other rollup, cardinality-bounded) and
        # the continuous host profiler's vitals.
        "gubernator_tenant_cost",
        "gubernator_tenant_other",
        "gubernator_tenant_total",
        "gubernator_profile_samples",
        "gubernator_profile_hz",
        # PR 14: millisecond express lane (architecture.md "Express
        # lane") + the jax readback-flake quarantine counter.
        "gubernator_express_lanes",
        "gubernator_express_hit_ratio",
        "gubernator_readback_retries",
        # PR 15: incident black box (blackbox.py) — always-on wire
        # capture rings + triggered bundle writes.
        "gubernator_blackbox_frames",
        "gubernator_blackbox_ring_bytes",
        "gubernator_blackbox_bundles",
        "gubernator_blackbox_last_trigger_age_seconds",
    }
)

GOLDEN = REFERENCE_PARITY | EXTENSIONS


def main() -> int:
    from gubernator_tpu.metrics import Metrics

    exported = {fam.name for fam in Metrics().registry.collect()}
    missing = sorted(GOLDEN - exported)
    unexpected = sorted(exported - GOLDEN)
    if not missing and not unexpected:
        print(f"metrics parity OK ({len(exported)} families)")
        return 0
    if missing:
        frozen = sorted(set(missing) & REFERENCE_PARITY)
        print("MISSING metric families (golden names not exported):")
        for name in missing:
            tag = "REFERENCE-PARITY, FROZEN" if name in frozen else "extension"
            print(f"  - {name}  [{tag}]")
    if unexpected:
        print("UNEXPECTED metric families (new names need review here):")
        for name in unexpected:
            print(f"  + {name}")
        print(
            "add intentionally-new names to EXTENSIONS in "
            "scripts/check_metrics_parity.py"
        )
    return 1


if __name__ == "__main__":
    sys.exit(main())
