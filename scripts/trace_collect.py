#!/usr/bin/env python
"""Cross-daemon trace stitcher: reassemble ONE trace from many daemons.

PR 4 made the peer wire carry a single trace id across daemons (the
sparse trace-context column / GTRC trailer) — but nothing CONSUMED it:
each daemon's flight recorder shows only its own spans.  This script is
the consumer: it polls every daemon's `GET /debug/traces`
(incrementally, via the `since`/`limit` parameters), groups spans that
share a trace id — matching a span's OWN id or its span-links, the
batch link rule — and stitches them into one tree per trace with the
cross-daemon hops annotated.

Stitching rules (tracing.py's span taxonomy):

* Same-daemon edges come from `parent_id` (a span's parent lives in
  the same process).
* Cross-daemon and batch fan-in edges come from LINKS: a span that
  links (trace, span_id) attaches under that span — a coalesced
  window/dispatch span attaches under every lane it carried; a
  receiving daemon's batch spans attach under the sender's span whose
  context rode the wire.
* `start_ns` is MONOTONIC and per-process: ordering and hop latency
  across daemons use the wall-clock end stamp (`wall_ns`) each span
  records, start = wall_ns - dur_ns (NTP-grade skew applies; fine at
  hop scale).

Usage:
    python scripts/trace_collect.py ADDR [ADDR...] [--trace-id HEX]
        [--watch SECONDS] [--json] [--limit N]

ADDR is a daemon gateway host:port.  Without --trace-id, every trace
seen across the fleet is stitched; with it, only that trace.  --watch
polls incrementally.  Exit code: 0 when at least one trace stitched
(or --allow-empty), 1 otherwise — so a soak can gate on "sampling and
stitching actually work".
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.request
from typing import Dict, List, Optional


def fetch_spans(addr: str, trace_id: str = "", since_ns: int = 0,
                limit: int = 0, timeout_s: float = 10.0) -> List[dict]:
    """One daemon's recorded spans, tagged with the daemon address."""
    params = []
    if trace_id:
        params.append(f"trace_id={trace_id}")
    if since_ns:
        params.append(f"since={since_ns}")
    if limit:
        params.append(f"limit={limit}")
    qs = ("?" + "&".join(params)) if params else ""
    url = f"http://{addr}/debug/traces{qs}"
    with urllib.request.urlopen(url, timeout=timeout_s) as r:
        doc = json.loads(r.read())
    spans = doc.get("spans", [])
    for s in spans:
        s["daemon"] = addr
    return spans


class Collector:
    """Incremental fleet poller: per-daemon `since` cursors advance on
    each poll, so a watch loop re-reads only new spans.

    The cursor trails the newest received stamp by CURSOR_LAG_NS:
    wall_ns is stamped inside record_span BEFORE the ring insert, so a
    preempted writer can land a span with an OLDER stamp than one a
    poll already returned — a cursor at the exact max would then skip
    it forever.  Re-fetched spans inside the lag window are dropped by
    the `_seen` dedup, so the lag costs bandwidth, not correctness."""

    CURSOR_LAG_NS = 200_000_000  # 200ms >> any GIL preemption gap

    def __init__(self, addrs: List[str], trace_id: str = "",
                 limit: int = 0):
        self.addrs = list(addrs)
        self.trace_id = trace_id
        self.limit = limit
        self.cursors: Dict[str, int] = {a: 0 for a in self.addrs}
        self.spans: List[dict] = []
        self._seen = set()

    def poll(self) -> int:
        """One pass over the fleet; returns how many NEW spans landed.
        A dead daemon is skipped (the soak kills daemons on purpose)."""
        new = 0
        for addr in self.addrs:
            try:
                spans = fetch_spans(
                    addr, self.trace_id, since_ns=self.cursors.get(addr, 0),
                    limit=self.limit,
                )
            except OSError:
                continue
            page_new = 0
            for s in spans:
                key = (s["daemon"], s["trace_id"], s["span_id"],
                       s.get("wall_ns", 0))
                if key in self._seen:
                    continue
                self._seen.add(key)
                self.spans.append(s)
                page_new += 1
            new += page_new
            if not spans:
                continue
            cur = self.cursors.get(addr, 0)
            page_max = max(s.get("wall_ns", 0) for s in spans)
            if page_new:
                self.cursors[addr] = max(cur, page_max - self.CURSOR_LAG_NS)
            elif self.limit and len(spans) >= self.limit:
                # A FULL page with nothing new: everything up to
                # page_max is already consumed, and a lagged cursor
                # could sit at-or-before the page start forever (all
                # stamps inside one lag window) — step past the page,
                # trading the (already-consumed) lag protection for
                # livelock-freedom.
                self.cursors[addr] = max(cur, page_max)
        return new


def stitch(spans: List[dict]) -> Dict[str, dict]:
    """Group spans into per-trace trees.

    Returns {trace_id: {"roots": [node...], "daemons": [...],
    "hops": [...]}} where a node is {"span": dict, "children":
    [node...], "via": "parent"|"link"}.  A span belongs to every trace
    it names (own id) or links; within one trace, it parents under its
    parent_id span when that span is present, else under the span a
    link targets, else it is a root."""
    by_trace: Dict[str, List[dict]] = {}
    for s in spans:
        ids = {s["trace_id"]}
        ids.update(l["trace_id"] for l in s.get("links", ()))
        for tid in ids:
            by_trace.setdefault(tid, []).append(s)
    out: Dict[str, dict] = {}
    for tid, group in by_trace.items():
        # Wall start for ordering (start_ns is per-process monotonic).
        for s in group:
            s["_wall_start"] = s.get("wall_ns", 0) - s.get("dur_ns", 0)
        group.sort(key=lambda s: s["_wall_start"])
        nodes = {}
        for s in group:
            # One span can appear in several traces; node identity is
            # per (trace, daemon, span) so trees never share children.
            nodes[(s["daemon"], s["span_id"])] = {
                "span": s, "children": [], "via": None,
            }
        own = {
            s["span_id"]: (s["daemon"], s["span_id"])
            for s in group if s["trace_id"] == tid
        }
        roots = []
        for s in group:
            node = nodes[(s["daemon"], s["span_id"])]
            parent_key = None
            via = None
            pid = s.get("parent_id", "")
            # parent_id is a same-process edge: resolve it against this
            # daemon's spans (the parent may carry a different trace id
            # — a batch span parented under its window span — which is
            # exactly how a lane's tree reaches the coalesced spans).
            same = (s["daemon"], pid)
            if pid and same in nodes and pid != s["span_id"]:
                parent_key, via = same, "parent"
            elif pid and pid in own and own[pid] != (s["daemon"], s["span_id"]):
                parent_key, via = own[pid], "parent"
            else:
                for l in s.get("links", ()):
                    if l["trace_id"] == tid and l["span_id"] in own:
                        cand = own[l["span_id"]]
                        if cand != (s["daemon"], s["span_id"]):
                            parent_key, via = cand, "link"
                            break
            if parent_key is not None:
                node["via"] = via
                nodes[parent_key]["children"].append(node)
            else:
                roots.append(node)
        daemons = sorted({s["daemon"] for s in group})
        out[tid] = {
            "roots": roots,
            "daemons": daemons,
            "spanCount": len(group),
            "hops": _hops(group),
        }
    return out


def _hops(group: List[dict]) -> List[dict]:
    """Cross-daemon hop latencies: for each client-side `peer.rpc`
    span, the delta from its wall start to each remote daemon's
    earliest same-trace span that started INSIDE the RPC's window.
    Per-daemon, not winner-takes-all: a fan-out batch can drive several
    owners concurrently, and pairing every RPC with the globally
    earliest remote span would attribute one daemon's timing to an RPC
    aimed at another.  The RPC's declared target rides along as `peer`
    (a gRPC data-plane address — the polled daemons are gateway
    addresses, so it annotates rather than joins)."""
    hops = []
    for s in group:
        if s["name"] != "peer.rpc":
            continue
        t0 = s["_wall_start"]
        t1 = s.get("wall_ns", t0)
        by_daemon: Dict[str, dict] = {}
        for r in group:
            if r["daemon"] == s["daemon"]:
                continue
            if not t0 <= r["_wall_start"] <= t1:
                continue  # remote work outside this RPC's lifetime
            cur = by_daemon.get(r["daemon"])
            if cur is None or r["_wall_start"] < cur["_wall_start"]:
                by_daemon[r["daemon"]] = r
        for daemon, first in sorted(by_daemon.items()):
            hops.append({
                "from": s["daemon"],
                "to": daemon,
                "peer": s.get("attrs", {}).get("peer", ""),
                "latency_ms": round((first["_wall_start"] - t0) / 1e6, 3),
                "firstRemoteSpan": first["name"],
            })
    return hops


def render_tree(tid: str, tree: dict, out=sys.stdout) -> None:
    out.write(
        f"trace {tid}  spans={tree['spanCount']}  "
        f"daemons={','.join(tree['daemons'])}\n"
    )
    for hop in tree["hops"]:
        out.write(
            f"  hop {hop['from']} -> {hop['to']} "
            f"({hop['firstRemoteSpan']}) +{hop['latency_ms']}ms\n"
        )

    def walk(node, depth):
        s = node["span"]
        marker = {"link": "~", "parent": "+"}.get(node["via"], "*")
        out.write(
            f"  {'  ' * depth}{marker} {s['name']} "
            f"[{s['daemon']}] {s.get('dur_ns', 0) / 1e6:.3f}ms"
            f"{' thread=' + s['thread'] if s.get('thread') else ''}\n"
        )
        for c in sorted(node["children"],
                        key=lambda n: n["span"]["_wall_start"]):
            walk(c, depth + 1)

    for r in tree["roots"]:
        walk(r, 0)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("addrs", nargs="+", help="daemon gateway host:port")
    ap.add_argument("--trace-id", default="", help="stitch one trace only")
    ap.add_argument("--watch", type=float, default=0.0,
                    help="poll every N seconds (0 = once)")
    ap.add_argument("--limit", type=int, default=0,
                    help="per-poll span cap per daemon")
    ap.add_argument("--json", action="store_true", dest="as_json")
    ap.add_argument("--allow-empty", action="store_true",
                    help="exit 0 even when no trace stitched")
    args = ap.parse_args()

    coll = Collector(args.addrs, trace_id=args.trace_id, limit=args.limit)
    try:
        while True:
            coll.poll()
            if args.watch <= 0:
                break
            time.sleep(args.watch)
    except KeyboardInterrupt:
        pass
    trees = stitch(coll.spans)
    if args.as_json:
        def strip(node):
            s = {k: v for k, v in node["span"].items()
                 if not k.startswith("_")}
            return {"span": s, "via": node["via"],
                    "children": [strip(c) for c in node["children"]]}

        print(json.dumps({
            tid: {
                "daemons": t["daemons"],
                "spanCount": t["spanCount"],
                "hops": t["hops"],
                "roots": [strip(r) for r in t["roots"]],
            }
            for tid, t in trees.items()
        }, indent=2))
    else:
        if not trees:
            print("no spans collected (is GUBER_TRACE_SAMPLE > 0?)")
        for tid, tree in sorted(trees.items()):
            render_tree(tid, tree)
    return 0 if trees or args.allow_empty else 1


if __name__ == "__main__":
    sys.exit(main())
