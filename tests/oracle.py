"""Reference-semantics oracle for differential testing.

A deliberately unoptimized, line-faithful Python model of the reference's
sequential algorithms (`algorithms.go:24-180` tokenBucket,
`algorithms.go:183-336` leakyBucket, with the cache expiry rules of
`cache.go:138-163`).  The production kernel (gubernator_tpu.ops.buckets)
is validated against this model on randomized request sequences; the
oracle itself is validated by the pinned tables ported from
functional_test.go.

The one intentional divergence mirrored here: the production code uses
`now + duration` for the leaky-bucket expiry refresh where the reference
has the `now * duration` bug (algorithms.go:287), so the oracle does too.
"""

from __future__ import annotations

import datetime as _dt
from dataclasses import dataclass, field
from typing import Dict, Optional

from gubernator_tpu.types import Algorithm, Behavior, RateLimitRequest, RateLimitResponse, Status, has_behavior
from gubernator_tpu.utils import gregorian


@dataclass
class TokenItem:
    limit: int
    duration: int
    remaining: int
    created_at: int
    status: int = Status.UNDER_LIMIT


@dataclass
class LeakyItem:
    limit: int
    duration: int
    remaining: float
    updated_at: int


@dataclass
class Item:
    algorithm: int
    key: str
    value: object
    expire_at: int


class OracleCache:
    def __init__(self):
        self.items: Dict[str, Item] = {}

    def get(self, key: str, now: int) -> Optional[Item]:
        item = self.items.get(key)
        if item is None:
            return None
        if item.expire_at < now:  # strict expiry == miss (cache.go:151)
            del self.items[key]
            return None
        return item

    def add(self, item: Item):
        self.items[item.key] = item

    def remove(self, key: str):
        self.items.pop(key, None)


def _now_dt(now: int) -> _dt.datetime:
    return _dt.datetime.fromtimestamp(now / 1000.0, tz=_dt.timezone.utc)


def token_bucket(c: OracleCache, r: RateLimitRequest, now: int) -> RateLimitResponse:
    key = r.hash_key()
    item = c.get(key, now)

    if item is not None:
        if has_behavior(r.behavior, Behavior.RESET_REMAINING):
            c.remove(key)
            return RateLimitResponse(
                status=Status.UNDER_LIMIT, limit=r.limit, remaining=r.limit, reset_time=0
            )
        if not isinstance(item.value, TokenItem):
            c.remove(key)
            return token_bucket(c, r, now)
        t = item.value

        if t.limit != r.limit:
            t.remaining += r.limit - t.limit
            if t.remaining < 0:
                t.remaining = 0
            t.limit = r.limit

        rl = RateLimitResponse(
            status=t.status, limit=r.limit, remaining=t.remaining, reset_time=item.expire_at
        )

        if t.duration != r.duration:
            expire = t.created_at + r.duration
            if has_behavior(r.behavior, Behavior.DURATION_IS_GREGORIAN):
                expire = gregorian.gregorian_expiration(_now_dt(now), r.duration)
            if expire < now:
                c.remove(key)
                return token_bucket(c, r, now)
            item.expire_at = expire
            rl.reset_time = expire

        if r.hits == 0:
            return rl
        if rl.remaining == 0:
            rl.status = Status.OVER_LIMIT
            t.status = rl.status
            return rl
        if t.remaining == r.hits:
            t.remaining = 0
            rl.remaining = 0
            return rl
        if r.hits > t.remaining:
            rl.status = Status.OVER_LIMIT
            return rl
        t.remaining -= r.hits
        rl.remaining = t.remaining
        return rl

    expire = now + r.duration
    if has_behavior(r.behavior, Behavior.DURATION_IS_GREGORIAN):
        expire = gregorian.gregorian_expiration(_now_dt(now), r.duration)

    t = TokenItem(limit=r.limit, duration=r.duration, remaining=r.limit - r.hits, created_at=now)
    rl = RateLimitResponse(
        status=Status.UNDER_LIMIT, limit=r.limit, remaining=t.remaining, reset_time=expire
    )
    if r.hits > r.limit:
        rl.status = Status.OVER_LIMIT
        rl.remaining = r.limit
        t.remaining = r.limit
    c.add(Item(algorithm=r.algorithm, key=key, value=t, expire_at=expire))
    return rl


def leaky_bucket(c: OracleCache, r: RateLimitRequest, now: int) -> RateLimitResponse:
    key = r.hash_key()
    item = c.get(key, now)

    if item is not None:
        if not isinstance(item.value, LeakyItem):
            c.remove(key)
            return leaky_bucket(c, r, now)
        b = item.value

        if has_behavior(r.behavior, Behavior.RESET_REMAINING):
            b.remaining = float(r.limit)
        b.limit = r.limit
        b.duration = r.duration

        duration = r.duration
        rate = float(duration) / float(r.limit)
        if has_behavior(r.behavior, Behavior.DURATION_IS_GREGORIAN):
            d = gregorian.gregorian_duration(_now_dt(now), r.duration)
            expire = gregorian.gregorian_expiration(_now_dt(now), r.duration)
            rate = float(d) / float(r.limit)
            duration = expire - now

        elapsed = now - b.updated_at
        leak = float(elapsed) / rate
        if int(leak) > 0:
            b.remaining += leak
            b.updated_at = now
        if int(b.remaining) > b.limit:
            b.remaining = float(b.limit)

        rl = RateLimitResponse(
            limit=b.limit,
            remaining=int(b.remaining),
            status=Status.UNDER_LIMIT,
            reset_time=now + int(rate),
        )
        if int(b.remaining) == 0:
            rl.status = Status.OVER_LIMIT
            return rl
        if int(b.remaining) == r.hits:
            b.remaining -= float(r.hits)
            rl.remaining = 0
            return rl
        if r.hits > int(b.remaining):
            rl.status = Status.OVER_LIMIT
            return rl
        if r.hits == 0:
            return rl
        b.remaining -= float(r.hits)
        rl.remaining = int(b.remaining)
        item.expire_at = now + duration  # deliberate divergence (see module doc)
        return rl

    duration = r.duration
    if has_behavior(r.behavior, Behavior.DURATION_IS_GREGORIAN):
        expire = gregorian.gregorian_expiration(_now_dt(now), r.duration)
        duration = expire - now

    b = LeakyItem(
        remaining=float(r.limit - r.hits), limit=r.limit, duration=duration, updated_at=now
    )
    rl = RateLimitResponse(
        status=Status.UNDER_LIMIT,
        limit=r.limit,
        remaining=r.limit - r.hits,
        reset_time=now + duration // max(r.limit, 1),
    )
    if r.hits > r.limit:
        rl.status = Status.OVER_LIMIT
        rl.remaining = 0
        b.remaining = 0.0
    c.add(Item(algorithm=r.algorithm, key=key, value=b, expire_at=now + duration))
    return rl


def apply(c: OracleCache, r: RateLimitRequest, now: int) -> RateLimitResponse:
    if r.algorithm == Algorithm.LEAKY_BUCKET:
        return leaky_bucket(c, r, now)
    return token_bucket(c, r, now)
