"""FNV-1/FNV-1a hashing against published test vectors."""

from gubernator_tpu.utils import hashing


def test_fnv1a_vectors():
    # Standard FNV-64 reference vectors.
    assert hashing.fnv1a_64(b"") == 0xCBF29CE484222325
    assert hashing.fnv1a_64(b"a") == 0xAF63DC4C8601EC8C
    assert hashing.fnv1a_64(b"foobar") == 0x85944171F73967E8


def test_fnv1_vectors():
    assert hashing.fnv1_64(b"") == 0xCBF29CE484222325
    assert hashing.fnv1_64(b"a") == 0xAF63BD4C8601B7BE
    assert hashing.fnv1_64(b"foobar") == 0x340D8765A4DDA9C2


def test_hash_batch_matches_scalar():
    keys = [f"key_{i}" for i in range(100)]
    batch = hashing.hash_batch_64(keys)
    assert batch == [hashing.hash_string_64(k) for k in keys]
