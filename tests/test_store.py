"""Store/Loader SPI tests: exact call-count sequences from
store_test.go:125-287 (TestStore) and :75-123 (TestLoader), applied at
the ShardStore level."""

import pytest

from gubernator_tpu.models.shard import ShardStore
from gubernator_tpu.store import (
    CacheItem,
    LeakyBucketItem,
    MockLoader,
    MockStore,
    TokenBucketItem,
)
from gubernator_tpu.types import Algorithm, RateLimitRequest, Status, SECOND

T0 = 1_573_430_430_000


def mk(algo, hits=1):
    return RateLimitRequest(
        name="test_over_limit", unique_key="account:1234", hits=hits,
        limit=10, duration=SECOND, algorithm=algo,
    )


def get_remaining(item):
    return int(item.value.remaining)


@pytest.mark.parametrize(
    "algo,switch_algo,preload,first_rem,first_status,second_rem,second_status",
    [
        (Algorithm.TOKEN_BUCKET, Algorithm.LEAKY_BUCKET, False, 9, Status.UNDER_LIMIT, 8, Status.UNDER_LIMIT),
        (Algorithm.TOKEN_BUCKET, Algorithm.LEAKY_BUCKET, True, 0, Status.UNDER_LIMIT, 0, Status.OVER_LIMIT),
        (Algorithm.LEAKY_BUCKET, Algorithm.TOKEN_BUCKET, False, 9, Status.UNDER_LIMIT, 8, Status.UNDER_LIMIT),
        (Algorithm.LEAKY_BUCKET, Algorithm.TOKEN_BUCKET, True, 0, Status.UNDER_LIMIT, 0, Status.OVER_LIMIT),
    ],
    ids=["token-empty", "token-preloaded", "leaky-empty", "leaky-preloaded"],
)
def test_store_call_sequences(algo, switch_algo, preload, first_rem, first_status, second_rem, second_status):
    store = MockStore()
    shard = ShardStore(capacity=64, store=store)
    req = mk(algo)

    if preload:
        if algo == Algorithm.TOKEN_BUCKET:
            value = TokenBucketItem(limit=10, duration=SECOND, created_at=T0, remaining=1)
        else:
            value = LeakyBucketItem(limit=10, duration=SECOND, updated_at=T0, remaining=1.0)
        store.cache_items[req.hash_key()] = CacheItem(
            algorithm=algo, key=req.hash_key(), value=value, expire_at=T0 + SECOND
        )

    assert store.called["OnChange()"] == 0 and store.called["Get()"] == 0

    r = shard.apply([req], T0)[0]
    assert r.error == ""
    assert r.remaining == first_rem
    assert r.limit == 10
    assert r.status == first_status
    assert store.called["OnChange()"] == 1
    assert store.called["Get()"] == 1
    assert get_remaining(store.cache_items[req.hash_key()]) == first_rem

    r = shard.apply([req], T0)[0]
    assert r.remaining == second_rem
    assert r.status == second_status
    assert store.called["OnChange()"] == 2
    assert store.called["Get()"] == 1  # cache hit: no store read
    assert get_remaining(store.cache_items[req.hash_key()]) == second_rem

    # Algorithm switch: Remove + re-Get + OnChange (algorithms.go:54-62).
    r = shard.apply([mk(switch_algo)], T0)[0]
    assert store.called["Remove()"] == 1
    assert store.called["OnChange()"] == 3
    assert store.called["Get()"] == 2
    assert store.cache_items[req.hash_key()].algorithm == switch_algo


def test_reset_remaining_removes_from_store():
    """algorithms.go:36-47: token RESET_REMAINING removes cache + store."""
    from gubernator_tpu.types import Behavior

    store = MockStore()
    shard = ShardStore(capacity=64, store=store)
    shard.apply([mk(Algorithm.TOKEN_BUCKET)], T0)
    assert store.called["OnChange()"] == 1
    req = mk(Algorithm.TOKEN_BUCKET)
    req.behavior = Behavior.RESET_REMAINING
    r = shard.apply([req], T0)[0]
    assert r.remaining == 10
    assert store.called["Remove()"] == 1
    assert req.hash_key() not in store.cache_items
    assert store.called["OnChange()"] == 1  # reset lane fires no OnChange


def test_loader_roundtrip():
    """TestLoader (store_test.go:75-123): load at start, save at stop."""
    loader = MockLoader()
    shard = ShardStore(capacity=64)
    for item in loader.load():
        shard.load_item(item)
    assert loader.called["Load()"] == 1 and loader.called["Save()"] == 0

    req = RateLimitRequest(
        name="test_over_limit", unique_key="account:1234", hits=1,
        limit=2, duration=SECOND, algorithm=Algorithm.TOKEN_BUCKET,
    )
    r = shard.apply([req], T0)[0]
    assert r.error == ""

    loader.save(shard.snapshot_items())
    assert loader.called["Save()"] == 1
    assert len(loader.cache_items) == 1
    item = loader.cache_items[0]
    assert isinstance(item.value, TokenBucketItem)
    assert item.value.limit == 2
    assert item.value.remaining == 1
    assert item.value.status == Status.UNDER_LIMIT


def test_loader_preload_then_hit():
    """Preloaded items serve subsequent traffic."""
    loader = MockLoader()
    loader.cache_items.append(
        CacheItem(
            algorithm=Algorithm.TOKEN_BUCKET,
            key="ns_k",
            value=TokenBucketItem(limit=10, duration=60_000, remaining=4, created_at=T0),
            expire_at=T0 + 60_000,
        )
    )
    shard = ShardStore(capacity=64)
    for item in loader.load():
        shard.load_item(item)
    req = RateLimitRequest(name="ns", unique_key="k", hits=1, limit=10, duration=60_000)
    r = shard.apply([req], T0 + 5)[0]
    assert r.remaining == 3
