"""Config parsing tests (config.go:220-388 env precedence + validation)."""

import pytest

from gubernator_tpu.config import (
    MAX_BATCH_SIZE,
    from_env_file,
    parse_duration,
    setup_daemon_config,
)


def test_defaults():
    conf = setup_daemon_config(env={})
    assert conf.listen_address == "127.0.0.1:1050"
    assert conf.cache_size == 50_000
    assert conf.behaviors.batch_limit == 1000
    assert conf.behaviors.batch_wait_s == pytest.approx(0.0005)
    assert conf.peer_discovery_type == "static"


def test_env_overrides():
    env = {
        "GUBER_HTTP_ADDRESS": "0.0.0.0:9090",
        "GUBER_CACHE_SIZE": "1234",
        "GUBER_BACK_CACHE_SIZE": "99999",
        "GUBER_DATA_CENTER": "dc-west",
        "GUBER_BATCH_LIMIT": "500",
        "GUBER_BATCH_WAIT": "2ms",
        "GUBER_GLOBAL_SYNC_WAIT": "50ms",
        "GUBER_STATIC_PEERS": "10.0.0.1:81,10.0.0.2:81",
        "GUBER_DEBUG": "true",
        "GUBER_NATIVE_HTTP": "1",
        "GUBER_NATIVE_WORKERS": "12",
    }
    conf = setup_daemon_config(env=env)
    assert conf.native_http is True
    assert conf.native_workers == 12
    assert conf.listen_address == "0.0.0.0:9090"
    assert conf.cache_size == 1234
    assert conf.back_cache_size == 99999
    assert conf.data_center == "dc-west"
    assert conf.behaviors.batch_limit == 500
    assert conf.behaviors.batch_wait_s == pytest.approx(0.002)
    assert conf.behaviors.global_sync_wait_s == pytest.approx(0.05)
    assert [p.grpc_address for p in conf.peers] == ["10.0.0.1:81", "10.0.0.2:81"]
    assert conf.debug


def test_env_file_precedence(tmp_path):
    """Env file loads first; process env (GUBER_*) wins (config.go:238+)."""
    f = tmp_path / "guber.conf"
    f.write_text("# comment\nGUBER_CACHE_SIZE=777\nGUBER_DATA_CENTER=dc-file\n")
    conf = setup_daemon_config(
        config_file=str(f), env={"GUBER_DATA_CENTER": "dc-env"}
    )
    assert conf.cache_size == 777
    assert conf.data_center == "dc-env"


def test_env_file_malformed(tmp_path):
    f = tmp_path / "bad.conf"
    f.write_text("NOT A KV LINE\n")
    with pytest.raises(ValueError, match="malformed"):
        from_env_file(str(f))


def test_batch_limit_validation():
    with pytest.raises(ValueError, match=f"cannot exceed '{MAX_BATCH_SIZE}'"):
        setup_daemon_config(env={"GUBER_BATCH_LIMIT": "5000"})


def test_discovery_type_validation():
    with pytest.raises(ValueError, match="GUBER_PEER_DISCOVERY_TYPE is invalid"):
        setup_daemon_config(env={"GUBER_PEER_DISCOVERY_TYPE": "zookeeper"})


def test_snapshot_knobs():
    conf = setup_daemon_config(env={
        "GUBER_SNAPSHOT": "/var/lib/gub.snap",
        "GUBER_SNAPSHOT_INTERVAL": "30s",
    })
    assert conf.snapshot_path == "/var/lib/gub.snap"
    assert conf.behaviors.snapshot_interval_s == pytest.approx(30.0)
    # Boolean-flavored opt-outs read as DISABLED, never as a filename.
    for v in ("0", "false", "off", "no", ""):
        assert setup_daemon_config(
            env={"GUBER_SNAPSHOT": v}
        ).snapshot_path == ""
    # Defaults: disabled path, 1m cadence; 0 = shutdown-only is legal,
    # negative is loud.
    conf = setup_daemon_config(env={})
    assert conf.snapshot_path == ""
    assert conf.behaviors.snapshot_interval_s == pytest.approx(60.0)
    assert setup_daemon_config(
        env={"GUBER_SNAPSHOT_INTERVAL": "0"}
    ).behaviors.snapshot_interval_s == 0.0
    with pytest.raises(ValueError, match="GUBER_SNAPSHOT_INTERVAL"):
        setup_daemon_config(env={"GUBER_SNAPSHOT_INTERVAL": "-5s"})


def test_parse_duration_go_strings():
    """Full Go time.ParseDuration unit set, incl. compound values."""
    cases = {
        "500ms": 0.5,
        "500us": 0.0005,
        "300ns": 3e-7,
        "1m": 60.0,
        "1m30s": 90.0,
        "1.5h": 5400.0,
        "2h45m": 9900.0,
        "250": 0.25,  # bare number = milliseconds
    }
    for s, want in cases.items():
        assert parse_duration(s) == pytest.approx(want), s


def test_parse_duration_invalid_names_var():
    with pytest.raises(ValueError, match="GUBER_GLOBAL_TIMEOUT"):
        setup_daemon_config(env={"GUBER_GLOBAL_TIMEOUT": "fast"})
    conf = setup_daemon_config(env={"GUBER_GLOBAL_TIMEOUT": "1m"})
    assert conf.behaviors.global_timeout_s == pytest.approx(60.0)


def test_resolve_host_ip():
    from gubernator_tpu.utils.net import resolve_host_ip

    assert resolve_host_ip("10.1.2.3:80") == "10.1.2.3:80"
    host, _, port = resolve_host_ip("0.0.0.0:9090").rpartition(":")
    assert port == "9090"
    assert host not in ("", "0.0.0.0")


def test_example_conf_documents_valid_knobs(tmp_path):
    """Every commented GUBER_* line in example.conf, uncommented, must
    parse (the reference documents its full env surface in example.conf;
    drift between docs and parser is a bug)."""
    import re

    from gubernator_tpu.config import setup_daemon_config

    lines = []
    with open("example.conf") as f:
        for line in f:
            m = re.match(r"#\s*(GUBER_[A-Z0-9_]+=.*)$", line.strip())
            if m:
                lines.append(m.group(1))
    assert len(lines) > 30, "example.conf should document the full GUBER_* surface"
    p = tmp_path / "ex.conf"
    p.write_text("\n".join(lines) + "\n")
    conf = setup_daemon_config(config_file=str(p), env={})
    assert conf.listen_address == "127.0.0.1:1050"
    assert conf.member_list_known_nodes == ["node1:7946", "node2:7946"]
    assert conf.etcd_endpoints == ["localhost:2379"]
    assert conf.k8s_selector == "app=gubernator"
    assert conf.behaviors.batch_wait_s == 0.0005
    assert conf.tls is not None and conf.tls.client_auth == "require-and-verify"
