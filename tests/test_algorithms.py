"""Algorithm semantics tests: frozen-clock tables ported from the
reference's functional suite, plus randomized differential testing of the
vectorized kernel against the sequential oracle.

Table sources: functional_test.go TestTokenBucket (:108-167),
TestOverTheLimit (:60-106), TestTokenBucketGregorian (:169-242),
TestLeakyBucket (:244-348), TestLeakyBucketGregorian (:350-413),
TestChangeLimit (:548-641), TestResetRemaining (:643-713),
TestLeakyBucketDivBug (:784-824).
"""

import random

import pytest

from gubernator_tpu.models.shard import ShardStore
from gubernator_tpu.types import (
    Algorithm,
    Behavior,
    RateLimitRequest,
    Status,
    MILLISECOND,
    SECOND,
    MINUTE,
)
from gubernator_tpu.utils.clock import Clock
from gubernator_tpu.utils import gregorian

from . import oracle

T0 = 1_573_430_430_000  # 2019-11-11T00:00:30Z


def mk(name="t", key="account:1234", hits=1, limit=10, duration=SECOND, algo=Algorithm.TOKEN_BUCKET, behavior=0):
    return RateLimitRequest(
        name=name, unique_key=key, hits=hits, limit=limit,
        duration=duration, algorithm=algo, behavior=behavior,
    )


def one(store, req, now):
    return store.apply([req], now)[0]


def test_over_the_limit():
    store = ShardStore(capacity=64)
    now = T0
    expect = [(1, Status.UNDER_LIMIT), (0, Status.UNDER_LIMIT), (0, Status.OVER_LIMIT)]
    for remaining, status in expect:
        r = one(store, mk(name="test_over_limit", limit=2, duration=9 * SECOND), now)
        assert r.status == status
        assert r.remaining == remaining
        assert r.limit == 2
        assert r.reset_time != 0


def test_token_bucket():
    store = ShardStore(capacity=64)
    clock = Clock()
    clock.freeze(T0)
    table = [
        (1, Status.UNDER_LIMIT, 0),
        (0, Status.UNDER_LIMIT, 100),
        (1, Status.UNDER_LIMIT, 0),  # expired after 100ms > 5ms duration
    ]
    for remaining, status, sleep_ms in table:
        r = one(store, mk(name="test_token_bucket", limit=2, duration=5 * MILLISECOND), clock.now_ms())
        assert r.status == status
        assert r.remaining == remaining
        assert r.reset_time != 0
        clock.advance(sleep_ms)


def test_token_bucket_gregorian():
    store = ShardStore(capacity=64)
    clock = Clock()
    clock.freeze(T0)
    table = [
        (1, 59, Status.UNDER_LIMIT, 0),
        (1, 58, Status.UNDER_LIMIT, 0),
        (58, 0, Status.UNDER_LIMIT, 0),
        (1, 0, Status.OVER_LIMIT, 61 * SECOND),
        (0, 60, Status.UNDER_LIMIT, 0),
    ]
    for hits, remaining, status, sleep_ms in table:
        req = mk(
            name="test_token_bucket_greg", key="account:12345", hits=hits, limit=60,
            duration=gregorian.GREGORIAN_MINUTES, behavior=Behavior.DURATION_IS_GREGORIAN,
        )
        r = one(store, req, clock.now_ms())
        assert r.status == status, r
        assert r.remaining == remaining
        assert r.limit == 60
        assert r.reset_time != 0
        clock.advance(sleep_ms)


def test_leaky_bucket():
    store = ShardStore(capacity=64)
    clock = Clock()
    clock.freeze(T0)
    table = [
        # hits, remaining, status, sleep_ms
        (1, 9, Status.UNDER_LIMIT, SECOND),
        (1, 8, Status.UNDER_LIMIT, SECOND),
        (1, 7, Status.UNDER_LIMIT, 1500),
        (0, 8, Status.UNDER_LIMIT, 3 * SECOND),
        (0, 9, Status.UNDER_LIMIT, 0),
        (9, 0, Status.UNDER_LIMIT, 0),
        (1, 0, Status.OVER_LIMIT, 3 * SECOND),
        (0, 1, Status.UNDER_LIMIT, 60 * SECOND),
        (0, 10, Status.UNDER_LIMIT, SECOND),
    ]
    for hits, remaining, status, sleep_ms in table:
        req = mk(
            name="test_leaky_bucket", hits=hits, limit=10, duration=30 * SECOND,
            algo=Algorithm.LEAKY_BUCKET,
        )
        now = clock.now_ms()
        r = one(store, req, now)
        assert r.status == status, (r, hits)
        assert r.remaining == remaining
        assert r.limit == 10
        # rate = 30s/10 = 3s per token (functional_test.go:334)
        assert r.reset_time // 1000 == now // 1000 + 3
        clock.advance(sleep_ms)


def test_leaky_bucket_gregorian():
    store = ShardStore(capacity=64)
    clock = Clock()
    clock.freeze(T0)
    table = [
        (1, 59, Status.UNDER_LIMIT, 500),
        (1, 58, Status.UNDER_LIMIT, SECOND),
        (1, 58, Status.UNDER_LIMIT, 0),  # leaked one back at 1.5s elapsed
    ]
    for hits, remaining, status, sleep_ms in table:
        req = mk(
            name="test_leaky_bucket_greg", key="account:12345", hits=hits, limit=60,
            duration=gregorian.GREGORIAN_MINUTES, algo=Algorithm.LEAKY_BUCKET,
            behavior=Behavior.DURATION_IS_GREGORIAN,
        )
        now = clock.now_ms()
        r = one(store, req, now)
        assert r.status == status
        assert r.remaining == remaining
        assert r.limit == 60
        assert r.reset_time > T0 // 1000
        clock.advance(sleep_ms)


def test_change_limit():
    store = ShardStore(capacity=64)
    now = T0
    table = [
        # algorithm, limit, expected_remaining
        (Algorithm.TOKEN_BUCKET, 100, 99),
        (Algorithm.TOKEN_BUCKET, 100, 98),
        (Algorithm.TOKEN_BUCKET, 10, 7),  # 98 + (10-100) = 8, hit -> 7
        (Algorithm.TOKEN_BUCKET, 10, 6),
        (Algorithm.TOKEN_BUCKET, 200, 195),  # 6 + 190 = 196, hit -> 195
        (Algorithm.LEAKY_BUCKET, 100, 99),  # algo switch resets
        (Algorithm.LEAKY_BUCKET, 10, 9),  # clamp 99 -> 10, hit -> 9
        (Algorithm.LEAKY_BUCKET, 10, 8),
    ]
    for algo, limit, remaining in table:
        r = one(store, mk(name="test_change_limit", limit=limit, duration=9000, algo=algo), now)
        assert r.status == Status.UNDER_LIMIT
        assert r.remaining == remaining, (algo, limit, remaining, r)
        assert r.limit == limit
        assert r.reset_time != 0


def test_reset_remaining():
    store = ShardStore(capacity=64)
    now = T0
    table = [
        (Behavior.BATCHING, 99),
        (Behavior.BATCHING, 98),
        (Behavior.RESET_REMAINING, 100),
        (Behavior.BATCHING, 99),
    ]
    for behavior, remaining in table:
        r = one(store, mk(name="test_reset_remaining", limit=100, duration=9000, behavior=behavior), now)
        assert r.status == Status.UNDER_LIMIT
        assert r.remaining == remaining


def test_leaky_bucket_div_bug():
    store = ShardStore(capacity=64)
    now = T0
    r = one(store, mk(name="div", limit=2000, duration=1000, algo=Algorithm.LEAKY_BUCKET), now)
    assert r.status == Status.UNDER_LIMIT
    assert r.remaining == 1999
    assert r.limit == 2000
    r = one(store, mk(name="div", hits=100, limit=2000, duration=1000, algo=Algorithm.LEAKY_BUCKET), now)
    assert r.remaining == 1899
    assert r.limit == 2000


def test_hits_greater_than_limit_on_create():
    """algorithms.go:161-166 / :318-323"""
    store = ShardStore(capacity=64)
    now = T0
    r = one(store, mk(name="big", hits=1000, limit=100, duration=9000), now)
    assert r.status == Status.OVER_LIMIT
    assert r.remaining == 100  # token keeps remaining = limit
    r = one(store, mk(name="bigl", hits=1000, limit=100, duration=9000, algo=Algorithm.LEAKY_BUCKET), now)
    assert r.status == Status.OVER_LIMIT
    assert r.remaining == 0  # leaky drains to 0


def test_over_limit_does_not_mutate():
    """algorithms.go:126-130: a rejected over-sized request leaves state."""
    store = ShardStore(capacity=64)
    now = T0
    one(store, mk(name="nm", hits=1, limit=100, duration=9000), now)  # rem 99
    r = one(store, mk(name="nm", hits=1000, limit=100, duration=9000), now)
    assert r.status == Status.OVER_LIMIT
    assert r.remaining == 99
    r = one(store, mk(name="nm", hits=99, limit=100, duration=9000), now)
    assert r.status == Status.UNDER_LIMIT
    assert r.remaining == 0


def test_expiry_boundary_exact_ms():
    """At now == ExpireAt the bucket is still live (cache.go:151 is a
    strict `<`); one ms later it recreates."""
    store = ShardStore(capacity=64)
    clock = Clock()
    clock.freeze(T0)
    req = mk(name="edge", hits=2, limit=2, duration=1000)
    r = one(store, req, clock.now_ms())
    assert r.remaining == 0
    clock.advance(1000)  # now == ExpireAt exactly
    r = one(store, mk(name="edge", hits=1, limit=2, duration=1000), clock.now_ms())
    assert r.status == Status.OVER_LIMIT  # still the drained bucket
    clock.advance(1)
    r = one(store, mk(name="edge", hits=1, limit=2, duration=1000), clock.now_ms())
    assert r.status == Status.UNDER_LIMIT and r.remaining == 1


def test_leaky_nonrepresentable_rate():
    """Non-binary-representable rates (duration=1000, limit=30): the
    kernel computes leak = elapsed*limit/duration exactly, where the
    reference double-rounds through float64 and can under-count by one
    token at exact multiples.  Pin exactness and the <=1-token bound
    vs the float oracle."""
    store = ShardStore(capacity=64)
    ocache = oracle.OracleCache()
    clock = Clock()
    clock.freeze(T0)
    req = mk(name="nr", hits=30, limit=30, duration=1000, algo=Algorithm.LEAKY_BUCKET)
    now = clock.now_ms()
    got, want = one(store, req, now), oracle.apply(ocache, req, now)
    assert got.remaining == want.remaining == 0
    clock.advance(500)  # exact leak = 500*30/1000 = 15; float64: 14.999...
    q = mk(name="nr", hits=0, limit=30, duration=1000, algo=Algorithm.LEAKY_BUCKET)
    now = clock.now_ms()
    got, want = one(store, q, now), oracle.apply(ocache, q, now)
    assert got.remaining == 15  # exact integer math
    assert want.remaining == 14  # reference float64 under-counts from 0.0
    assert abs(got.remaining - want.remaining) <= 1


def test_leaky_huge_limit_no_overflow():
    """elapsed*limit exceeding int64 must not wrap (128-bit muldiv)."""
    store = ShardStore(capacity=64)
    clock = Clock()
    clock.freeze(T0)
    month = 30 * 24 * 3600 * 1000  # 2.59e9 ms
    big = 2**42
    req = mk(name="huge", hits=big, limit=big, duration=month, algo=Algorithm.LEAKY_BUCKET)
    r = one(store, req, clock.now_ms())
    assert r.remaining == 0
    clock.advance(month // 2)  # half the period -> half the bucket leaks back
    r = one(store, mk(name="huge", hits=0, limit=big, duration=month, algo=Algorithm.LEAKY_BUCKET), clock.now_ms())
    assert r.status == Status.UNDER_LIMIT
    assert abs(r.remaining - big // 2) <= 1


def test_duplicate_keys_in_one_batch():
    """Duplicate keys in a single batch behave like sequential requests."""
    store = ShardStore(capacity=64)
    now = T0
    reqs = [mk(name="dup", hits=3, limit=10, duration=9000) for _ in range(4)]
    resps = store.apply(reqs, now)
    assert [r.remaining for r in resps] == [7, 4, 1, 1]
    assert [r.status for r in resps] == [
        Status.UNDER_LIMIT, Status.UNDER_LIMIT, Status.UNDER_LIMIT, Status.OVER_LIMIT,
    ]


def test_eviction_collision_with_reset_does_not_drop_new_key():
    """Regression: a RESET_REMAINING lane whose slot gets evicted and
    remapped mid-batch must not delete the new key's mapping when its
    removed-flag commits (key-guarded commit)."""
    store = ShardStore(capacity=2)
    now = T0
    one(store, mk(name="x", key="A", hits=1, limit=10, duration=9000), now)
    one(store, mk(name="x", key="B", hits=1, limit=10, duration=9000), now)
    resps = store.apply(
        [
            mk(name="x", key="A", hits=1, limit=10, duration=9000,
               behavior=Behavior.RESET_REMAINING),
            mk(name="x", key="B", hits=1, limit=10, duration=9000),
            mk(name="x", key="C", hits=1, limit=10, duration=9000),  # evicts A's slot
        ],
        now,
    )
    assert [r.remaining for r in resps] == [10, 8, 9]
    # C must still be mapped: another hit continues its bucket.
    r = one(store, mk(name="x", key="C", hits=1, limit=10, duration=9000), now)
    assert r.remaining == 8


def test_padding_lanes_do_not_corrupt_last_slot():
    """Regression: jax .at[-1] wraps, so padding lanes (slot=-1) used to
    scatter garbage into the table's last slot."""
    store = ShardStore(capacity=2)
    now = T0
    one(store, mk(name="p", key="K0", hits=1, limit=10, duration=9000), now)
    one(store, mk(name="p", key="K1", hits=1, limit=10, duration=9000), now)  # slot 1 (last)
    # Another padded batch touching only K0 must leave K1's bucket intact.
    one(store, mk(name="p", key="K0", hits=1, limit=10, duration=9000), now)
    r = one(store, mk(name="p", key="K1", hits=1, limit=10, duration=9000), now)
    assert r.remaining == 8


def test_lru_eviction():
    store = ShardStore(capacity=4)
    now = T0
    for i in range(6):
        one(store, mk(name="ev", key=f"k{i}", hits=1, limit=10, duration=9000), now)
    assert store.size() == 4
    assert store.table.evictions == 2
    # k0 was evicted; hitting it again recreates a fresh bucket
    r = one(store, mk(name="ev", key="k0", hits=1, limit=10, duration=9000), now)
    assert r.remaining == 9


@pytest.mark.parametrize("algo", [Algorithm.TOKEN_BUCKET, Algorithm.LEAKY_BUCKET])
def test_differential_vs_oracle(algo):
    """Randomized sequences must match the sequential reference oracle."""
    rng = random.Random(1234 + algo)
    store = ShardStore(capacity=256)
    ocache = oracle.OracleCache()
    clock = Clock()
    clock.freeze(T0)
    keys = [f"k{i}" for i in range(8)]
    for step in range(300):
        key = rng.choice(keys)
        behavior = 0
        if rng.random() < 0.05:
            behavior |= Behavior.RESET_REMAINING
        req = mk(
            name="diff",
            key=key,
            hits=rng.choice([0, 1, 1, 2, 5, 10, 50]),
            limit=rng.choice([5, 10, 100]),
            duration=rng.choice([1000, 5000, 60_000]),
            algo=algo,
            behavior=behavior,
        )
        now = clock.now_ms()
        got = one(store, req, now)
        want = oracle.apply(ocache, req, now)
        assert got.status == want.status, (step, req, got, want)
        assert got.limit == want.limit, (step, req, got, want)
        assert got.remaining == want.remaining, (step, req, got, want)
        assert got.reset_time == want.reset_time, (step, req, got, want)
        clock.advance(rng.choice([0, 0, 1, 7, 100, 1500, 6000]))


def test_differential_mixed_algo_switches():
    """Algorithm switches mid-stream reset buckets (algorithms.go:54-62)."""
    rng = random.Random(99)
    store = ShardStore(capacity=256)
    ocache = oracle.OracleCache()
    clock = Clock()
    clock.freeze(T0)
    for step in range(200):
        req = mk(
            name="sw",
            key=f"k{rng.randrange(4)}",
            hits=rng.choice([0, 1, 2]),
            limit=10,
            duration=5000,
            algo=rng.choice([Algorithm.TOKEN_BUCKET, Algorithm.LEAKY_BUCKET]),
        )
        now = clock.now_ms()
        got = one(store, req, now)
        want = oracle.apply(ocache, req, now)
        assert (got.status, got.remaining, got.reset_time) == (
            want.status, want.remaining, want.reset_time,
        ), (step, req)
        clock.advance(rng.choice([0, 3, 50, 700]))
