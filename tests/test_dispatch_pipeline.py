"""Overlapped dispatch pipeline: interleaving oracle + machinery tests.

The pipeline's contract (models/shard.py ColumnarPipeline): however
many ingress threads race `apply_columns_async`, the observable results
are BYTE-IDENTICAL to applying the same batches serially in ticket
(plan) order on a fresh store.  Staleness from commits landing after
younger plans is absorbed by the pending-write guard + device-side
expiry revalidation, and launch fusion is semantically invisible — so
any divergence here is a real ordering bug, not noise.

The oracle deliberately avoids capacity pressure: under eviction the
documented pipelined-staleness semantics allow eviction decisions to
act on slightly-old expire times, which is a legitimate (and tested
elsewhere) divergence, not an ordering violation.
"""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest

from gubernator_tpu import native
from gubernator_tpu.faults import DELAY, FaultPlan, FaultRule
from gubernator_tpu.models.shard import ShardStore
from gubernator_tpu.parallel.mesh import MeshBucketStore

pytestmark = pytest.mark.skipif(
    not native.available(), reason="columnar pipeline needs the native runtime"
)

NOW = 1_573_430_400_000


def _make_batches(seed: int, n_batches: int, lanes: int, n_keys: int,
                  wide: bool):
    """Deterministic batches with heavy cross-batch key overlap; each
    batch owns a fixed now_ms (NOW + index) so a serial replay is
    exactly reproducible regardless of which thread dispatched it."""
    rng = np.random.RandomState(seed)
    batches = []
    for b in range(n_batches):
        ids = rng.randint(0, n_keys, size=lanes)
        batches.append(dict(
            keys=[f"orc:{i}" for i in ids],
            algorithm=(ids % 2).astype(np.int32),
            behavior=np.zeros(lanes, np.int32),
            hits=rng.randint(1, 4, size=lanes).astype(np.int64),
            # wide: limits beyond int32 push the batch off the narrow
            # output wire (models/shard.narrow_ok).
            limit=np.full(lanes, (1 << 40) if wide else 50, np.int64),
            duration=np.full(lanes, 3_600_000, np.int64),
            now=NOW + b,
        ))
    return batches


def _dispatch(store, b, force_wire):
    return store.apply_columns_async(
        b["keys"], b["algorithm"], b["behavior"], b["hits"], b["limit"],
        b["duration"], b["now"], force_wire=force_wire,
    )


def _race(store, batches, n_threads, force_wire, delay_fn=None):
    """Race the batches over n_threads dispatcher threads; returns
    [(ticket, batch_idx, result_dict)] sorted by ticket."""
    out = []
    out_mu = threading.Lock()
    errs = []

    def worker(tid):
        try:
            for bi in range(tid, len(batches), n_threads):
                if delay_fn is not None:
                    delay_fn(tid, bi)
                h = _dispatch(store, batches[bi], force_wire)
                r = h.result()
                with out_mu:
                    out.append((h.ticket, bi, r))
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    threads = [
        threading.Thread(target=worker, args=(t,)) for t in range(n_threads)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs, errs
    out.sort()
    assert [t for t, _, _ in out] == sorted(t for t, _, _ in out)
    return out


def _assert_matches_serial(make_store, batches, raced, force_wire):
    """Replay the raced batches serially in ticket order on a fresh
    store; every lane's status/remaining/reset must match bitwise."""
    serial = make_store()
    for ticket, bi, raced_result in raced:
        b = batches[bi]
        expect = serial.apply_columns(
            b["keys"], b["algorithm"], b["behavior"], b["hits"], b["limit"],
            b["duration"], b["now"], force_wire=force_wire,
        )
        for f in ("status", "remaining", "reset_time"):
            assert np.array_equal(
                np.asarray(raced_result[f]), np.asarray(expect[f])
            ), (
                f"field {f} diverged for batch {bi} (ticket {ticket}, "
                f"wire={force_wire})"
            )


@pytest.mark.parametrize("seed", [7, 1234])
@pytest.mark.parametrize("force_wire", [None, "wide"])
def test_shard_interleaved_matches_serial(seed, force_wire):
    store = ShardStore(capacity=4096)
    batches = _make_batches(seed, n_batches=12, lanes=96, n_keys=64,
                            wide=force_wire == "wide")
    raced = _race(store, batches, n_threads=3, force_wire=force_wire)
    _assert_matches_serial(
        lambda: ShardStore(capacity=4096), batches, raced, force_wire
    )


@pytest.mark.parametrize("seed", [11, 4242])
@pytest.mark.parametrize("force_wire", [None, "wide"])
def test_mesh_interleaved_matches_serial(seed, force_wire):
    store = MeshBucketStore(capacity_per_shard=1024)
    batches = _make_batches(seed, n_batches=10, lanes=128, n_keys=80,
                            wide=force_wire == "wide")
    raced = _race(store, batches, n_threads=3, force_wire=force_wire)
    _assert_matches_serial(
        lambda: MeshBucketStore(capacity_per_shard=1024), batches, raced,
        force_wire,
    )


@pytest.mark.chaos
@pytest.mark.parametrize("seed", [3, 99])
def test_interleaved_matches_serial_under_fault_delays(seed):
    """Chaos variant: per-(thread, op) seeded FaultPlan DELAY rules
    jitter the dispatchers' schedules — the interleavings shift with
    the seed, the oracle verdict must not."""
    plan = FaultPlan(seed=seed)
    plan.add(FaultRule(peer="*", op="dispatch", kind=DELAY,
                       delay_s=0.004, rate=0.6))
    store = MeshBucketStore(capacity_per_shard=1024)
    batches = _make_batches(seed, n_batches=9, lanes=64, n_keys=48,
                            wide=False)

    def delay_fn(tid, bi):
        act = plan.intercept(f"t{tid}", "dispatch")
        if act is not None and act.kind == DELAY:
            time.sleep(act.delay_s)

    raced = _race(store, batches, n_threads=3, force_wire=None,
                  delay_fn=delay_fn)
    _assert_matches_serial(
        lambda: MeshBucketStore(capacity_per_shard=1024), batches, raced,
        None,
    )


def test_launch_fusion_under_backlog(monkeypatch):
    """Stall ticket 0 in its STAGE step; tickets 1..3 stage behind it
    and wait at the launch gate, so ticket 0's launch fuses all four
    into one program — and the results still match the serial replay."""
    store = ShardStore(capacity=4096)
    batches = _make_batches(21, n_batches=4, lanes=64, n_keys=32,
                            wide=False)
    orig = store._stage_columns
    stalled = threading.Event()

    def slow_stage(prep):
        if not stalled.is_set():
            stalled.set()
            time.sleep(0.4)  # let tickets 1..3 reach the gate
        return orig(prep)

    monkeypatch.setattr(store, "_stage_columns", slow_stage)
    store.take_pipeline_stats()
    raced = _race(store, batches, n_threads=4, force_wire=None)
    stats, _depth, _hwm = store.take_pipeline_stats()
    # 4 dispatches, fewer launches than dispatches = fusion happened.
    assert stats["prepare"][0] == 4
    assert stats["launch"][0] < 4, stats
    _assert_matches_serial(
        lambda: ShardStore(capacity=4096), batches, raced, None
    )


def test_fused_kernel_matches_solo_sequence():
    """The fused launch program is bit-equivalent to the same wires
    applied by consecutive solo dispatches (state threading included)."""
    from gubernator_tpu.models.shard import make_columns
    from gubernator_tpu.ops import buckets

    lanes, cap = 64, 256
    slot = np.arange(lanes, dtype=np.int32)

    def wire(hits, exists):
        cols = make_columns(
            np.zeros(lanes, np.int32), np.zeros(lanes, np.int32),
            np.full(lanes, hits, np.int64), np.full(lanes, 100, np.int64),
            np.full(lanes, 60_000, np.int64), lanes,
        )
        cfg, table = buckets.build_config_dict(cols, NOW)
        return buckets.pack_dict_wire(
            slot[None, :], np.full((1, lanes), exists, bool),
            np.ones((1, lanes), bool), cfg[None, :].astype(np.uint8),
            np.zeros((1, lanes), np.int32), np.zeros((1, lanes), np.int32),
            table,
        )[0]

    wires = [wire(1, False), wire(2, True), wire(3, True), wire(5, True)]
    nows = [NOW, NOW + 10, NOW + 20, NOW + 30]

    solo_state = buckets.init_state(cap)
    solo_out = []
    for w, t in zip(wires, nows):
        solo_state, packed = buckets.apply_rounds_packed_jit(
            solo_state, np.array(w), 1, t
        )
        solo_out.append(np.asarray(packed))

    fused_state = buckets.init_state(cap)
    fn = buckets.fused_packed_jit(4, wide=False, donate_wires=False)
    fused_state, stacked = fn(
        fused_state, *[np.array(w) for w in wires],
        np.ones(4, np.int32), np.asarray(nows, np.int64),
    )
    stacked = np.asarray(stacked)
    for i in range(4):
        assert np.array_equal(stacked[i], solo_out[i]), f"sub-batch {i}"
    for a, b in zip(
        __import__("jax").tree.leaves(solo_state),
        __import__("jax").tree.leaves(fused_state),
    ):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_ingress_queue_sheds_with_429_error():
    from gubernator_tpu.config import BehaviorConfig
    from gubernator_tpu.metrics import Metrics
    from gubernator_tpu.service import ColumnarBatcher, IngressShedError, LocalBatcher
    from gubernator_tpu.types import RateLimitRequest
    from gubernator_tpu.utils.clock import DEFAULT_CLOCK

    # express=False: this test pins the WINDOWED queue's shed semantics
    # (express bypass lanes never queue, so they only shed when
    # concurrent in-flight lanes exceed the cap).
    beh = BehaviorConfig(batch_wait_s=5.0, ingress_queue_lanes=100,
                         express=False)
    metrics = Metrics()
    cb = ColumnarBatcher(object(), beh, DEFAULT_CLOCK, metrics=metrics)
    try:
        n = 60
        args = (
            [f"k{i}" for i in range(n)], np.zeros(n, np.int32),
            np.zeros(n, np.int32), np.ones(n, np.int64),
            np.full(n, 5, np.int64), np.full(n, 60_000, np.int64),
            None, None,
        )
        fut1 = cb.submit(*args)
        fut2 = cb.submit(*args)  # 60 + 60 > 100: shed
        with pytest.raises(IngressShedError) as ei:
            fut2.result(timeout=1)
        assert ei.value.http_status == 429
        assert "OVER_LIMIT" not in str(ei.value)
        assert metrics.ingress_shed._value.get() == n  # noqa: SLF001
        assert not fut1.done()  # admitted lanes still queued, not shed
    finally:
        cb.stop()

    lb = LocalBatcher(object(), BehaviorConfig(
        batch_wait_s=5.0, ingress_queue_lanes=2, express=False),
        DEFAULT_CLOCK, metrics=metrics)
    try:
        r = RateLimitRequest(name="a", unique_key="b", hits=1, limit=5,
                             duration=60_000)
        lb.submit(r)
        lb.submit(r)
        with pytest.raises(IngressShedError):
            lb.submit(r).result(timeout=1)
    finally:
        lb.stop()


def test_ingress_queue_env_knob():
    from gubernator_tpu.config import setup_daemon_config

    conf = setup_daemon_config(env={"GUBER_INGRESS_QUEUE_LANES": "123"})
    assert conf.behaviors.ingress_queue_lanes == 123
    assert setup_daemon_config(env={}).behaviors.ingress_queue_lanes == 262_144


def test_dispatch_metrics_cleared_per_scrape():
    from gubernator_tpu.metrics import Metrics

    store = ShardStore(capacity=1024)
    b = _make_batches(5, 1, 32, 16, wide=False)[0]
    _dispatch(store, b, None).result()
    m = Metrics()
    m.observe_dispatch(store)
    text = m.render().decode()
    assert "gubernator_dispatch_inflight 0.0" in text
    assert 'gubernator_dispatch_stage_seconds{stage="prepare",stat="count"} 1.0' in text
    assert 'stage="launch"' in text and 'stage="commit"' in text
    # Second scrape with no traffic since: stage series cleared (PR 1
    # breaker-gauge convention), gauges report an idle pipeline.
    m.observe_dispatch(store)
    text2 = m.render().decode()
    assert 'stage="prepare"' not in text2
    assert "gubernator_dispatch_inflight 0.0" in text2


def test_gate_verdict_noise_adjusted():
    import bench

    # Round-5's failing shape: tiny point estimate, big timer noise —
    # the noise-adjusted bound is still far under the limit: PASS.
    assert bench.gate_verdict(4.7, {"fail_above_us": 250.0}, 77.2)[0] == "PASS"
    # A real regression clears the limit even after subtracting noise.
    assert bench.gate_verdict(400.0, {"fail_above_us": 250.0}, 20.0)[0] == "FAIL"
    # Noise straddling the limit is inconclusive, never a flip.
    assert bench.gate_verdict(240.0, {"fail_above_us": 250.0}, 30.0)[0] == "SKIP"
    assert bench.gate_verdict(0.9, {"fail_below": 0.65})[0] == "PASS"
    assert bench.gate_verdict(0.5, {"fail_below": 0.65})[0] == "FAIL"
