"""Test harness configuration.

Forces JAX onto an 8-device virtual CPU mesh so multi-shard sharding
paths run without real multi-chip hardware (the reference's analogue is
the in-process loopback cluster, cluster/cluster.go:82-131).

Note: the environment's sitecustomize may pre-register a TPU platform;
`jax.config.update('jax_platforms', 'cpu')` after import reliably forces
CPU even then (env vars alone are overridden at interpreter start).
"""

import os

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
if "--xla_force_host_platform_device_count" not in os.environ["XLA_FLAGS"]:
    os.environ["XLA_FLAGS"] += " --xla_force_host_platform_device_count=8"

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import pytest  # noqa: E402


@pytest.fixture
def frozen_clock():
    from gubernator_tpu.utils.clock import Clock

    c = Clock()
    c.freeze(1_573_430_400_000)  # 2019-11-11T00:00:00Z
    return c
