"""Mesh-sharded store tests on the 8-device virtual CPU mesh.

The reference's analogue is the in-process loopback cluster
(cluster/cluster.go:82-131): N real peers, full peer list known
statically.  Here N shards are N devices in one mesh program.
"""

import random

import jax
import numpy as np
import pytest

from gubernator_tpu import native
from gubernator_tpu.models.shard import ShardStore
from gubernator_tpu.parallel.mesh import MeshBucketStore, make_mesh, shard_of_key
from gubernator_tpu.types import Algorithm, RateLimitRequest, Status
from gubernator_tpu.utils.clock import Clock

T0 = 1_573_430_430_000


def mk(key, hits=1, limit=10, duration=5000, algo=Algorithm.TOKEN_BUCKET):
    return RateLimitRequest(
        name="mesh", unique_key=key, hits=hits, limit=limit, duration=duration, algorithm=algo
    )


def test_requires_8_devices():
    assert len(jax.devices()) == 8


def test_state_is_sharded():
    store = MeshBucketStore(capacity_per_shard=64)
    assert store.n_shards == 8
    shard_dim = store.state.hot.shape[0]
    assert shard_dim == 8
    # each row table must actually be laid out across all 8 devices
    assert len(store.state.hot.sharding.device_set) == 8


def test_shard_assignment_is_stable_and_covers():
    n = 8
    seen = set()
    for i in range(2000):
        s = shard_of_key(f"name_k{i}", n)
        assert 0 <= s < n
        seen.add(s)
    assert seen == set(range(n))  # all shards get traffic


def test_mesh_matches_single_shard_semantics():
    """The sharded store must give byte-identical responses to a single
    ShardStore fed the same sequential workload."""
    rng = random.Random(7)
    mesh_store = MeshBucketStore(capacity_per_shard=256)
    ref = ShardStore(capacity=4096)
    clock = Clock()
    clock.freeze(T0)
    for _ in range(30):
        batch = []
        for _ in range(rng.randrange(1, 40)):
            batch.append(
                mk(
                    key=f"k{rng.randrange(64)}",
                    hits=rng.choice([0, 1, 2, 5]),
                    limit=rng.choice([5, 100]),
                    duration=rng.choice([1000, 60_000]),
                    algo=rng.choice([Algorithm.TOKEN_BUCKET, Algorithm.LEAKY_BUCKET]),
                )
            )
        now = clock.now_ms()
        got = mesh_store.apply(batch, now)
        want = ref.apply(batch, now)
        for g, w, req in zip(got, want, batch):
            assert (g.status, g.limit, g.remaining, g.reset_time) == (
                w.status, w.limit, w.remaining, w.reset_time,
            ), req
        clock.advance(rng.choice([0, 10, 900, 5000]))


def test_mesh_duplicate_keys_serialize():
    store = MeshBucketStore(capacity_per_shard=64)
    reqs = [mk("dup", hits=3, limit=10) for _ in range(4)]
    resps = store.apply(reqs, T0)
    assert [r.remaining for r in resps] == [7, 4, 1, 1]
    assert resps[3].status == Status.OVER_LIMIT


def test_mesh_scales_keyspace():
    """1k distinct keys land across shards and all get correct answers."""
    store = MeshBucketStore(capacity_per_shard=512)
    reqs = [mk(f"k{i}", hits=1, limit=7) for i in range(1000)]
    resps = store.apply(reqs, T0)
    assert all(r.remaining == 6 for r in resps)
    assert store.size() == 1000
    per_shard = [len(t) for t in store.tables]
    assert min(per_shard) > 0


@pytest.mark.parametrize(
    "fused_native",
    [
        pytest.param(
            True,
            marks=pytest.mark.skipif(
                not native.available(),
                reason="native runtime unavailable: True case would be Python-vs-Python",
            ),
        ),
        False,
    ],
)
def test_fused_duplicates_match_sequential(fused_native):
    """Hot-key duplicate batches through the fused mesh dispatch
    (grouped round 0 + slow rounds in one program) must match applying
    the same requests one at a time — with the fused store on BOTH slot
    table backends, pinning C++/Python table parity through the mesh
    path (the serial store always runs the Python tables)."""
    import numpy as np

    from gubernator_tpu.parallel.mesh import MeshBucketStore
    from gubernator_tpu.types import Algorithm, Behavior, RateLimitRequest

    rng = np.random.RandomState(9)
    fused = MeshBucketStore(capacity_per_shard=128, g_capacity=32,
                            use_native=fused_native)
    serial = MeshBucketStore(capacity_per_shard=128, g_capacity=32,
                             use_native=False)
    now = 1_700_000_000_000
    for step in range(25):
        reqs = []
        # uniform hot group
        for _ in range(rng.randint(1, 12)):
            reqs.append(RateLimitRequest(
                name="mf", unique_key="hot", hits=1, limit=9, duration=4_000,
                algorithm=Algorithm.TOKEN_BUCKET,
            ))
        # non-uniform duplicates (slow path)
        for _ in range(rng.randint(0, 6)):
            reqs.append(RateLimitRequest(
                name="mf", unique_key="mix", hits=int(rng.choice([1, 2])),
                limit=7, duration=4_000, algorithm=Algorithm.LEAKY_BUCKET,
            ))
        # occasional RESET_REMAINING (excluded from grouping)
        if rng.random() < 0.3:
            reqs.append(RateLimitRequest(
                name="mf", unique_key="hot", hits=1, limit=9, duration=4_000,
                behavior=Behavior.RESET_REMAINING,
            ))
        rng.shuffle(reqs)
        now += rng.randint(0, 900)
        got = fused.apply(reqs, now)
        want = [serial.apply([r], now)[0] for r in reqs]
        for i, (g, w) in enumerate(zip(got, want)):
            assert (g.status, g.remaining, g.reset_time) == (
                w.status, w.remaining, w.reset_time,
            ), (step, i, reqs[i], g, w)


def test_measure_sync_cost_refuses_live_global_traffic():
    """measure_sync_cost_s drains device-side GLOBAL accumulations
    without the host commit/broadcast legs, so it must refuse to run on
    a store already serving GLOBAL keys (mesh.py documents the contract;
    this pins it as an assertion, not a comment)."""
    from gubernator_tpu.types import Behavior

    store = MeshBucketStore(capacity_per_shard=64, g_capacity=16)
    now = 1_700_000_000_000
    store.apply(
        [
            RateLimitRequest(
                name="mesh", unique_key="live_global", hits=1, limit=10,
                duration=5000, behavior=Behavior.GLOBAL,
            )
        ],
        now,
    )
    with pytest.raises(RuntimeError, match="live GLOBAL"):
        store.measure_sync_cost_s(now + 1, iters=1)

    # A fresh store (no GLOBAL traffic) measures fine.
    clean = MeshBucketStore(capacity_per_shard=64, g_capacity=16)
    assert clean.measure_sync_cost_s(now, iters=1) > 0
