"""Daemon-level discovery wiring tests: two real daemons find each other
through the member-list gossip backend (the reference covers this
indirectly via docker-compose; here it runs in-process) and a forwarded
rate limit crosses between them.
"""

import time

import pytest

from gubernator_tpu.client import V1Client
from gubernator_tpu.config import DaemonConfig, setup_daemon_config
from gubernator_tpu.daemon import spawn_daemon
from gubernator_tpu.types import (
    Algorithm,
    GetRateLimitsRequest,
    RateLimitRequest,
    Status,
)


def wait_until(fn, timeout_s=10.0, every_s=0.05, msg="condition"):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if fn():
            return
        time.sleep(every_s)
    raise AssertionError(f"timed out waiting for {msg}")


def test_member_list_daemons_converge_and_forward():
    d1 = d2 = None
    try:
        d1 = spawn_daemon(
            DaemonConfig(
                listen_address="127.0.0.1:0",
                peer_discovery_type="member-list",
                member_list_address="127.0.0.1:0",
            )
        )
        seed = d1._pool.address
        d2 = spawn_daemon(
            DaemonConfig(
                listen_address="127.0.0.1:0",
                peer_discovery_type="member-list",
                member_list_address="127.0.0.1:0",
                member_list_known_nodes=[seed],
            )
        )
        for d in (d1, d2):
            wait_until(
                lambda d=d: len(d.service.get_peer_list()) == 2,
                msg="both daemons see 2 peers",
            )
        # Keys route identically on both daemons; a key owned by the
        # other daemon is forwarded over the peer data plane.
        c1 = V1Client(d1.gateway.address)
        c2 = V1Client(d2.gateway.address)
        for i, c in ((1, c1), (2, c2)):
            req = GetRateLimitsRequest(
                requests=[
                    RateLimitRequest(
                        name="disc_test",
                        unique_key=f"k{i}",
                        hits=1,
                        limit=5,
                        duration=60_000,
                        algorithm=Algorithm.TOKEN_BUCKET,
                    )
                ]
            )
            r = c.get_rate_limits(req).responses[0]
            assert r.error == ""
            assert r.status == Status.UNDER_LIMIT
            assert r.remaining == 4
        # Same key hit from BOTH daemons must decrement one shared
        # bucket (ownership, not per-daemon state).
        shared = GetRateLimitsRequest(
            requests=[
                RateLimitRequest(
                    name="disc_test", unique_key="shared", hits=1,
                    limit=10, duration=60_000,
                )
            ]
        )
        rem1 = c1.get_rate_limits(shared).responses[0].remaining
        rem2 = c2.get_rate_limits(shared).responses[0].remaining
        assert {rem1, rem2} == {9, 8}
    finally:
        for d in (d2, d1):
            if d is not None:
                d.close()


def test_member_list_config_requires_known_nodes():
    with pytest.raises(ValueError, match="MEMBERLIST_KNOWN_NODES"):
        setup_daemon_config(env={"GUBER_PEER_DISCOVERY_TYPE": "member-list"})


def test_member_list_env_parsing():
    conf = setup_daemon_config(
        env={
            "GUBER_PEER_DISCOVERY_TYPE": "member-list",
            "GUBER_MEMBERLIST_ADDRESS": "127.0.0.1:7946",
            "GUBER_MEMBERLIST_KNOWN_NODES": "a:7946, b:7946",
            "GUBER_MEMBERLIST_NODE_NAME": "node-a",
        }
    )
    assert conf.member_list_address == "127.0.0.1:7946"
    assert conf.member_list_known_nodes == ["a:7946", "b:7946"]
    assert conf.member_list_node_name == "node-a"


def test_file_pool_watches_membership(tmp_path):
    """The watched-JSON-file backend (peers.FilePool): editing the file
    IS the membership event."""
    import json
    import os

    from gubernator_tpu.peers import FilePool

    path = tmp_path / "peers.json"
    path.write_text(json.dumps([{"grpcAddress": "10.0.0.1:81"}]))
    updates = []
    pool = FilePool(str(path), on_update=updates.append, poll_s=0.05)
    try:
        assert [p.grpc_address for p in updates[-1]] == ["10.0.0.1:81"]
        path.write_text(json.dumps(
            [{"grpcAddress": "10.0.0.1:81"}, {"grpcAddress": "10.0.0.2:81"}]
        ))
        # Explicitly bump mtime by a full second: on a coarse-granularity
        # filesystem the rewrite alone can land in the same mtime tick
        # and the poll would (correctly) skip it.
        m = os.path.getmtime(path)
        os.utime(path, (m + 1, m + 1))
        wait_until(
            lambda: updates
            and [p.grpc_address for p in updates[-1]]
            == ["10.0.0.1:81", "10.0.0.2:81"],
            msg="file edit delivers new peer list",
        )
    finally:
        pool.close()


def test_file_pool_tolerates_torn_and_malformed_content(tmp_path):
    """A half-written or schema-invalid peers file must be retried on a
    later tick (never marked seen, never killing the watcher), and a
    torn file at construction must not fail pool startup."""
    import json
    import os

    from gubernator_tpu.peers import FilePool

    path = tmp_path / "peers.json"
    path.write_text('[{"grpcAddress": "10.0.0.1:81"')  # torn at construction
    updates = []
    pool = FilePool(str(path), on_update=updates.append, poll_s=0.05)
    try:
        assert updates == []  # survived, nothing delivered yet
        # JSON-valid but wrong shape: still not marked seen.
        path.write_text(json.dumps(["10.0.0.2:81"]))
        m = os.path.getmtime(path)
        os.utime(path, (m + 1, m + 1))
        time.sleep(0.2)
        assert updates == []
        # Now a good file with the SAME content length: must deliver.
        path.write_text(json.dumps([{"grpcAddress": "10.0.0.3:81"}]))
        os.utime(path, (m + 2, m + 2))
        wait_until(
            lambda: updates
            and [p.grpc_address for p in updates[-1]] == ["10.0.0.3:81"],
            msg="recovered after torn/malformed content",
        )
    finally:
        pool.close()
