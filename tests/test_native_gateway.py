"""Native C++ epoll HTTP edge (gt_http_* + gateway.NativeGatewayServer).

Same surface as the stdlib gateway — the handler behind both is ONE
function (gateway.handle_request) — so these tests focus on what the
native edge newly owns: framing, keep-alive, pipelining order,
Connection: close, malformed input, and daemon integration.
"""

import json
import socket
import threading
import time

import pytest

from gubernator_tpu import native
from gubernator_tpu.client import V1Client
from gubernator_tpu.config import DaemonConfig
from gubernator_tpu.daemon import Daemon
from gubernator_tpu.gateway import NativeGatewayServer
from gubernator_tpu.service import ServiceConfig, V1Service
from gubernator_tpu.types import (
    Algorithm,
    GetRateLimitsRequest,
    PeerInfo,
    RateLimitRequest,
)

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native runtime unavailable"
)

T0 = 1_573_430_430_000


@pytest.fixture
def edge_service(frozen_clock):
    svc = V1Service(ServiceConfig(cache_size=512, clock=frozen_clock,
                                  advertise_address="127.0.0.1:9981"))
    svc.set_peers([PeerInfo(grpc_address="127.0.0.1:9981", is_owner=True)])
    gw = NativeGatewayServer(svc, "127.0.0.1:0")
    gw.start()
    yield gw, svc
    gw.close()
    svc.close()


@pytest.fixture
def frozen_clock():
    from gubernator_tpu.utils.clock import Clock

    c = Clock()
    c.freeze(T0)
    return c


def _post(addr, path, payload, extra_headers=""):
    host, _, port = addr.partition(":")
    body = json.dumps(payload).encode()
    with socket.create_connection((host, int(port)), timeout=30) as s:
        s.sendall(
            f"POST {path} HTTP/1.1\r\nHost: x\r\nContent-Type: application/json\r\n"
            f"Content-Length: {len(body)}\r\n{extra_headers}\r\n".encode() + body
        )
        return _read_response(s)


def _read_response(s):
    data = b""
    while b"\r\n\r\n" not in data:
        chunk = s.recv(65536)
        if not chunk:
            raise ConnectionError(f"EOF mid-headers: {data!r}")
        data += chunk
    head, _, rest = data.partition(b"\r\n\r\n")
    status = int(head.split(b" ", 2)[1])
    clen = 0
    for line in head.split(b"\r\n")[1:]:
        if line.lower().startswith(b"content-length:"):
            clen = int(line.split(b":", 1)[1])
    while len(rest) < clen:
        chunk = s.recv(65536)
        if not chunk:
            raise ConnectionError("EOF mid-body")
        rest += chunk
    return status, rest[:clen], rest[clen:]


def _rl(key, hits=1, limit=10):
    return {
        "name": "ng", "uniqueKey": key, "hits": str(hits),
        "limit": str(limit), "duration": "60000", "algorithm": "TOKEN_BUCKET",
    }


def test_get_rate_limits_roundtrip(edge_service):
    gw, _ = edge_service
    status, body, _ = _post(gw.address, "/v1/GetRateLimits",
                            {"requests": [_rl("a", hits=3)]})
    assert status == 200
    resp = json.loads(body)["responses"][0]
    assert resp["status"] == "UNDER_LIMIT" and resp["remaining"] == "7"


def test_health_metrics_and_404(edge_service):
    gw, _ = edge_service
    host, _, port = gw.address.partition(":")
    with socket.create_connection((host, int(port)), timeout=30) as s:
        s.sendall(b"GET /v1/HealthCheck HTTP/1.1\r\nHost: x\r\n\r\n")
        status, body, _ = _read_response(s)
        assert status == 200 and json.loads(body)["status"] == "healthy"
        # keep-alive: same connection serves the next two requests
        s.sendall(b"GET /metrics HTTP/1.1\r\nHost: x\r\n\r\n")
        status, body, _ = _read_response(s)
        assert status == 200 and b"gubernator_grpc_request_counts" in body
        s.sendall(b"GET /nope HTTP/1.1\r\nHost: x\r\n\r\n")
        status, body, _ = _read_response(s)
        assert status == 404 and json.loads(body)["code"] == 5


def test_invalid_json_is_400(edge_service):
    gw, _ = edge_service
    host, _, port = gw.address.partition(":")
    with socket.create_connection((host, int(port)), timeout=30) as s:
        s.sendall(
            b"POST /v1/GetRateLimits HTTP/1.1\r\nHost: x\r\n"
            b"Content-Length: 9\r\n\r\nnot json!"
        )
        status, body, _ = _read_response(s)
    assert status == 400
    assert json.loads(body)["code"] == 3


def test_pipelined_requests_answer_in_order(edge_service):
    """Two requests written back-to-back before reading: responses must
    come back in request order even though worker threads may finish
    out of order (the per-connection token-ordered done-queue)."""
    gw, _ = edge_service
    host, _, port = gw.address.partition(":")
    b1 = json.dumps({"requests": [_rl("p1", hits=1, limit=100)]}).encode()
    b2 = json.dumps({"requests": [_rl("p2", hits=2, limit=200)]}).encode()
    with socket.create_connection((host, int(port)), timeout=30) as s:
        s.sendall(
            b"POST /v1/GetRateLimits HTTP/1.1\r\nHost: x\r\n"
            + f"Content-Length: {len(b1)}\r\n\r\n".encode() + b1
            + b"POST /v1/GetRateLimits HTTP/1.1\r\nHost: x\r\n"
            + f"Content-Length: {len(b2)}\r\n\r\n".encode() + b2
        )
        status1, body1, rest = _read_response(s)
        # Any tail bytes of response 2 already read stay in `rest`.
        data = rest
        s.settimeout(5)
        while b"\r\n\r\n" not in data:
            data += s.recv(65536)
        head, _, tail = data.partition(b"\r\n\r\n")
        status2 = int(head.split(b" ", 2)[1])
        clen = next(int(l.split(b":", 1)[1]) for l in head.split(b"\r\n")
                    if l.lower().startswith(b"content-length:"))
        while len(tail) < clen:
            tail += s.recv(65536)
        body2 = tail[:clen]
    assert status1 == status2 == 200
    assert json.loads(body1)["responses"][0]["limit"] == "100"
    assert json.loads(body2)["responses"][0]["limit"] == "200"


def test_pipelined_mixed_sizes_stay_ordered_under_async(edge_service):
    """Eight pipelined requests alternating 200-lane (slow) and 1-lane
    (fast): with async completion the fast ones finish internally
    FIRST, so the per-connection token-ordered done-queue is what keeps
    the wire order correct.  Each response is tagged by its batch size."""
    gw, _ = edge_service
    host, _, port = gw.address.partition(":")
    sizes = [200, 1, 200, 1, 200, 1, 200, 1]
    raw = b""
    for i, sz in enumerate(sizes):
        body = json.dumps(
            {"requests": [_rl(f"ord{i}", limit=10000 + i)] * sz}
        ).encode()
        raw += (b"POST /v1/GetRateLimits HTTP/1.1\r\nHost: x\r\n"
                + f"Content-Length: {len(body)}\r\n\r\n".encode() + body)
    with socket.create_connection((host, int(port)), timeout=60) as s:
        s.sendall(raw)
        leftover = b""
        for i, sz in enumerate(sizes):
            data = leftover
            while b"\r\n\r\n" not in data:
                chunk = s.recv(65536)
                assert chunk, f"EOF before response {i}"
                data += chunk
            head, _, rest = data.partition(b"\r\n\r\n")
            assert head.split(b" ", 2)[1] == b"200", head[:60]
            clen = next(int(l.split(b":", 1)[1]) for l in head.split(b"\r\n")
                        if l.lower().startswith(b"content-length:"))
            while len(rest) < clen:
                chunk = s.recv(65536)
                assert chunk, f"EOF mid-body {i}"
                rest += chunk
            payload = json.loads(rest[:clen])
            leftover = rest[clen:]
            resps = payload["responses"]
            # Response i must be THIS request's: right size, right tag.
            assert len(resps) == sz, f"response {i}: {len(resps)} != {sz}"
            assert int(resps[0]["limit"]) == 10000 + i, (i, resps[0])


def test_connection_close_honored(edge_service):
    gw, _ = edge_service
    status, body, _ = _post(gw.address, "/v1/GetRateLimits",
                            {"requests": [_rl("c")]},
                            extra_headers="Connection: close\r\n")
    assert status == 200


def test_malformed_request_line_closes(edge_service):
    gw, _ = edge_service
    host, _, port = gw.address.partition(":")
    with socket.create_connection((host, int(port)), timeout=30) as s:
        s.sendall(b"BOGUS\r\n\r\n")
        assert s.recv(1024) == b""  # server closes without a response


def test_trickled_request_frames_correctly(edge_service):
    """A request delivered one byte at a time (worst-case TCP
    segmentation) must frame identically to a single write."""
    gw, _ = edge_service
    host, _, port = gw.address.partition(":")
    body = json.dumps({"requests": [_rl("trickle", hits=2)]}).encode()
    raw = (b"POST /v1/GetRateLimits HTTP/1.1\r\nHost: x\r\n"
           + f"Content-Length: {len(body)}\r\n\r\n".encode() + body)
    with socket.create_connection((host, int(port)), timeout=30) as s:
        # byte-at-a-time through the headers, then the body in 3 chunks
        split = raw.index(b"\r\n\r\n") + 4
        for i in range(split):
            s.sendall(raw[i:i + 1])
        third = max(1, (len(raw) - split) // 3)
        for off in range(split, len(raw), third):
            s.sendall(raw[off:off + third])
            time.sleep(0.005)
        status, rbody, _ = _read_response(s)
    assert status == 200
    assert json.loads(rbody)["responses"][0]["remaining"] == "8"


def test_oversize_header_closes_connection(edge_service):
    """A header block past the 64 KiB cap must kill the connection, not
    buffer unboundedly."""
    gw, _ = edge_service
    host, _, port = gw.address.partition(":")
    with socket.create_connection((host, int(port)), timeout=30) as s:
        s.sendall(b"POST /v1/GetRateLimits HTTP/1.1\r\n")
        try:
            # no terminating \r\n\r\n: stream junk headers past the cap
            for _ in range(80):
                s.sendall(b"X-Pad: " + b"a" * 1024 + b"\r\n")
            got = s.recv(1024)
        except (BrokenPipeError, ConnectionResetError):
            got = b""
        assert got == b""  # server closed without a response


def test_oversize_content_length_closes_connection(edge_service):
    """Content-Length past the body cap is rejected at the header, not
    after buffering 32 MiB."""
    gw, _ = edge_service
    host, _, port = gw.address.partition(":")
    with socket.create_connection((host, int(port)), timeout=30) as s:
        s.sendall(b"POST /v1/GetRateLimits HTTP/1.1\r\nHost: x\r\n"
                  b"Content-Length: 99999999999\r\n\r\n")
        assert s.recv(1024) == b""


def test_disconnect_mid_body_is_survivable(edge_service):
    """A client vanishing mid-body must not wedge the edge or leak the
    half-request into the service; the next client is served."""
    gw, _ = edge_service
    host, _, port = gw.address.partition(":")
    s = socket.create_connection((host, int(port)), timeout=30)
    s.sendall(b"POST /v1/GetRateLimits HTTP/1.1\r\nHost: x\r\n"
              b"Content-Length: 5000\r\n\r\n" + b"{" * 100)
    s.close()  # abort with 4900 bytes owed
    status, body, _ = _post(gw.address, "/v1/GetRateLimits",
                            {"requests": [_rl("after-abort")]})
    assert status == 200
    assert json.loads(body)["responses"][0]["status"] == "UNDER_LIMIT"


def test_disconnect_with_response_in_flight(edge_service):
    """Client closes after sending a full request but before reading
    the response: the completion must discard safely (token unmapped),
    and the edge keeps serving."""
    gw, _ = edge_service
    host, _, port = gw.address.partition(":")
    body = json.dumps({"requests": [_rl("ghost", hits=1)] * 50}).encode()
    for _ in range(5):
        s = socket.create_connection((host, int(port)), timeout=30)
        s.sendall(b"POST /v1/GetRateLimits HTTP/1.1\r\nHost: x\r\n"
                  + f"Content-Length: {len(body)}\r\n\r\n".encode() + body)
        s.close()  # don't read the response
    time.sleep(0.5)
    status, rbody, _ = _post(gw.address, "/v1/GetRateLimits",
                             {"requests": [_rl("ghost", hits=0)]})
    assert status == 200
    # The 250 ghost hits actually applied (limit 10 -> fully drained):
    # an unread response discards the BYTES, never the state change.
    assert int(json.loads(rbody)["responses"][0]["remaining"]) == 0


def test_invalid_worker_count_is_startup_error(frozen_clock):
    svc = V1Service(ServiceConfig(cache_size=64, clock=frozen_clock,
                                  advertise_address="127.0.0.1:9982"))
    try:
        with pytest.raises(ValueError, match="native_workers"):
            NativeGatewayServer(svc, "127.0.0.1:0", n_workers=0)
        with pytest.raises(ValueError, match="native_workers"):
            NativeGatewayServer(svc, "127.0.0.1:0", n_workers=-1)
    finally:
        svc.close()


def test_half_close_client_still_gets_response(edge_service):
    """shutdown(SHUT_WR) after the request (FIN arrives with the data):
    the server must frame + serve the request and deliver the response
    on the still-open write side — not kill the connection on EOF."""
    gw, _ = edge_service
    host, _, port = gw.address.partition(":")
    body = json.dumps({"requests": [_rl("halfclose", hits=4)]}).encode()
    with socket.create_connection((host, int(port)), timeout=30) as s:
        s.sendall(b"POST /v1/GetRateLimits HTTP/1.1\r\nHost: x\r\n"
                  + f"Content-Length: {len(body)}\r\n\r\n".encode() + body)
        s.shutdown(socket.SHUT_WR)
        status, rbody, _ = _read_response(s)
    assert status == 200
    assert json.loads(rbody)["responses"][0]["remaining"] == "6"


def test_header_names_case_insensitive(edge_service):
    gw, _ = edge_service
    host, _, port = gw.address.partition(":")
    body = json.dumps({"requests": [_rl("case")]}).encode()
    with socket.create_connection((host, int(port)), timeout=30) as s:
        s.sendall(b"POST /v1/GetRateLimits HTTP/1.1\r\nhost: x\r\n"
                  b"CONTENT-LENGTH: " + str(len(body)).encode()
                  + b"\r\ncOnNeCtIoN: Close\r\n\r\n" + body)
        status, rbody, _ = _read_response(s)
        assert status == 200
        assert s.recv(1024) == b""  # Connection: close honored


def test_concurrent_clients(edge_service):
    gw, _ = edge_service
    errs = []

    def worker(tid):
        try:
            for i in range(5):
                status, body, _ = _post(
                    gw.address, "/v1/GetRateLimits",
                    {"requests": [_rl(f"w{tid}", limit=1000)] * 8},
                )
                assert status == 200, body
                assert len(json.loads(body)["responses"]) == 8
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    ts = [threading.Thread(target=worker, args=(t,)) for t in range(12)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert not errs, errs


def test_daemon_uses_native_edge_and_serves_clients(frozen_clock):
    """native_http=True serves the gateway from the C++ edge; the
    standard V1Client and the HTTP peer data plane work against it."""
    d = Daemon(
        DaemonConfig(
            listen_address="127.0.0.1:0",
            grpc_listen_address="127.0.0.1:0",
            cache_size=512,
            peer_discovery_type="static",
            native_http=True,
        ),
        clock=frozen_clock,
    ).start()
    try:
        assert isinstance(d.gateway, NativeGatewayServer), type(d.gateway)
        c = V1Client(d.gateway.address, timeout_s=10.0)
        r = c.get_rate_limits(GetRateLimitsRequest(requests=[
            RateLimitRequest(name="d", unique_key="k", hits=4, limit=10,
                             duration=60_000,
                             algorithm=Algorithm.TOKEN_BUCKET)
        ]))
        assert r.responses[0].remaining == 6
        hc = c.health_check()
        assert hc.status == "healthy"
        # peer HTTP data plane against the native edge
        status, body, _ = _post(
            d.gateway.address, "/v1/peer.GetPeerRateLimits",
            {"requests": [_rl("peer-k", hits=1, limit=9)]},
        )
        assert status == 200
        assert json.loads(body)["rateLimits"][0]["limit"] == "9"
    finally:
        d.close()


def test_daemon_default_is_stdlib(frozen_clock):
    from gubernator_tpu.gateway import GatewayServer

    d = Daemon(
        DaemonConfig(
            listen_address="127.0.0.1:0",
            grpc_listen_address="127.0.0.1:0",
            cache_size=512,
            peer_discovery_type="static",
        ),
        clock=frozen_clock,
    ).start()
    try:
        assert isinstance(d.gateway, GatewayServer), type(d.gateway)
        c = V1Client(d.gateway.address, timeout_s=10.0)
        assert c.health_check().status == "healthy"
    finally:
        d.close()


def test_unknown_method_gets_501(edge_service):
    """HEAD/OPTIONS/PUT get a parseable 501 response, not a reset —
    load balancers doing HEAD probes must see HTTP, never a RST."""
    gw, _ = edge_service
    host, _, port = gw.address.partition(":")
    with socket.create_connection((host, int(port)), timeout=30) as s:
        s.sendall(b"HEAD /v1/HealthCheck HTTP/1.1\r\nHost: x\r\n\r\n")
        status, body, _ = _read_response(s)
        assert status == 501
        assert json.loads(body)["code"] == 12
        assert s.recv(1024) == b""  # then the server closes


def test_async_inflight_exceeds_worker_pool(edge_service):
    """The async completion path's defining property: far more
    concurrent in-flight requests than worker threads (N_WORKERS=4),
    all served, with exact hit accounting — the old blocking-worker
    edge would cap coalescing (and convoy) at the pool size."""
    gw, svc = edge_service
    n_clients, per_client = 24, 4
    errs: list = []

    def worker(tid):
        try:
            host, _, port = gw.address.partition(":")
            with socket.create_connection((host, int(port)), timeout=30) as s:
                for i in range(per_client):
                    body = json.dumps(
                        {"requests": [_rl("shared", limit=100000)] * 4}
                    ).encode()
                    s.sendall(
                        b"POST /v1/GetRateLimits HTTP/1.1\r\nHost: x\r\n"
                        b"Content-Length: %d\r\n\r\n" % len(body) + body
                    )
                    status, rbody, _ = _read_response(s)
                    assert status == 200, rbody
                    assert len(json.loads(rbody)["responses"]) == 4
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    ts = [threading.Thread(target=worker, args=(t,)) for t in range(n_clients)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert not errs, errs
    # Exact accounting: every request drained 4 hits off one key.
    status, rbody, _ = _post(gw.address, "/v1/GetRateLimits",
                             {"requests": [_rl("shared", hits=0, limit=100000)]})
    assert status == 200
    rem = int(json.loads(rbody)["responses"][0]["remaining"])
    assert rem == 100000 - n_clients * per_client * 4


def test_peer_endpoint_async_roundtrip(edge_service):
    """The PeersV1 receive path rides the async completion too."""
    gw, _ = edge_service
    status, body, _ = _post(
        gw.address, "/v1/peer.GetPeerRateLimits",
        {"requests": [_rl("peer-async", hits=2, limit=50)] * 3},
    )
    assert status == 200
    resps = json.loads(body)["rateLimits"]
    assert len(resps) == 3
    assert int(resps[-1]["remaining"]) == 50 - 6


def test_native_http_with_tls_is_startup_error(tmp_path, frozen_clock):
    from gubernator_tpu.tls import TLSConfig

    with pytest.raises(RuntimeError, match="incompatible with TLS"):
        Daemon(
            DaemonConfig(
                listen_address="127.0.0.1:0",
                grpc_listen_address="127.0.0.1:0",
                cache_size=64,
                peer_discovery_type="static",
                native_http=True,
                tls=TLSConfig(auto_tls=True),
            ),
            clock=frozen_clock,
        ).start()


def test_hostname_listen_address_resolves(frozen_clock):
    """'localhost:0' must bind (the edge resolves hostnames before the
    AF_INET-only native bind)."""
    d = Daemon(
        DaemonConfig(
            listen_address="localhost:0",
            grpc_listen_address="127.0.0.1:0",
            cache_size=64,
            peer_discovery_type="static",
            native_http=True,
        ),
        clock=frozen_clock,
    ).start()
    try:
        assert isinstance(d.gateway, NativeGatewayServer)
        c = V1Client(d.gateway.address, timeout_s=10.0)
        assert c.health_check().status == "healthy"
    finally:
        d.close()


def test_single_key_forwarded_between_native_daemons():
    """Single-key requests through BOTH native-edge daemons of a
    2-node cluster drain ONE shared bucket: the async n==1 path must
    DECLINE its standalone fast path on a multi-peer ring and route
    through the sync router (owner-local or forwarded), whichever
    daemon receives the request."""
    from gubernator_tpu.cluster import Cluster

    cl = Cluster().start_with(["", ""], native_http=True)
    try:
        addrs = [d.gateway.address for d in cl.daemons]
        hits_per, rounds = 2, 6
        for i in range(rounds):
            status, body, _ = _post(
                addrs[i % 2], "/v1/GetRateLimits",
                {"requests": [_rl("fwd-shared", hits=hits_per, limit=1000)]},
            )
            assert status == 200, body
            resp = json.loads(body)["responses"][0]
            assert resp.get("error", "") == "", resp
        status, body, _ = _post(
            addrs[0], "/v1/GetRateLimits",
            {"requests": [_rl("fwd-shared", hits=0, limit=1000)]},
        )
        remaining = int(json.loads(body)["responses"][0]["remaining"])
        assert remaining == 1000 - hits_per * rounds
    finally:
        cl.stop()


@pytest.mark.slow
def test_native_edge_soak_with_shutdown_under_load():
    """The two-phase teardown under real load: mixed-behavior traffic
    through TWO native-edge daemons, one closed MID-TRAFFIC.  The
    surviving daemon keeps serving, the closing daemon's workers (some
    mid-device-round) join without deadlock or crash, and the failure
    rate stays at transient-churn levels."""
    from gubernator_tpu.cluster import Cluster
    from gubernator_tpu.types import Behavior

    cl = Cluster().start_with(["", ""], native_http=True)
    assert all(isinstance(d.gateway, NativeGatewayServer) for d in cl.daemons)
    stop = threading.Event()
    failures = []
    totals = {"requests": 0}
    lock = threading.Lock()
    behaviors = [0, Behavior.NO_BATCHING, Behavior.GLOBAL]

    def worker(wid):
        client = V1Client(cl.daemons[0].gateway.address, timeout_s=30.0)
        i = 0
        while not stop.is_set():
            reqs = [
                RateLimitRequest(
                    name="nsoak", unique_key=f"k{(i + j) % 5}", hits=1,
                    limit=1_000_000, duration=60_000,
                    algorithm=Algorithm.TOKEN_BUCKET,
                    behavior=behaviors[i % len(behaviors)],
                )
                for j in range(4)
            ]
            try:
                resp = client.get_rate_limits(GetRateLimitsRequest(requests=reqs))
                errs = [r.error for r in resp.responses if r.error]
                if errs:
                    with lock:
                        failures.extend(errs)
            except Exception as e:  # noqa: BLE001
                with lock:
                    # Weight a whole-batch failure like len(reqs) lane
                    # failures so the rate denominator stays consistent.
                    failures.extend([f"{type(e).__name__}: {e}"] * len(reqs))
            with lock:
                totals["requests"] += len(reqs)
            i += 1

    threads = [threading.Thread(target=worker, args=(w,)) for w in range(6)]
    try:
        for t in threads:
            t.start()
        time.sleep(1.0)
        # Close daemon 1 mid-traffic (its edge may be answering forwards)
        # and shrink the ring to the survivor.
        cl.daemons[1].close()
        cl.daemons[0].set_peers([cl.daemons[0].peer_info])
        time.sleep(1.5)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=60)
            assert not t.is_alive(), "worker deadlocked"
        cl.stop()  # Daemon.close() is idempotent for the closed one

    with lock:
        assert totals["requests"] > 50, "soak made no progress"
        rate = len(failures) / max(totals["requests"], 1)
        assert rate < 0.2, (
            f"{len(failures)}/{totals['requests']} failed; first: {failures[:3]}"
        )
