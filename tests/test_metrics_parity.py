"""scripts/check_metrics_parity.py — the metric-name lint `make tier1`
runs — must pass against the live registry, and must actually FAIL on
a drifted registry (a lint that cannot fail guards nothing)."""

import os
import subprocess
import sys

SCRIPT = os.path.join(
    os.path.dirname(__file__), "..", "scripts", "check_metrics_parity.py"
)


def test_parity_script_passes():
    out = subprocess.run(
        [sys.executable, SCRIPT],
        capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert out.returncode == 0, out.stdout + out.stderr
    assert "metrics parity OK" in out.stdout


def test_parity_module_detects_unexpected_name():
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "scripts"))
    try:
        import check_metrics_parity as parity
    finally:
        sys.path.pop(0)
    # A registry with an unreviewed extra family must fail the lint.
    from prometheus_client import Counter

    from gubernator_tpu.metrics import Metrics

    m = Metrics()
    Counter("gubernator_surprise_total", "drift", registry=m.registry)
    exported = {fam.name for fam in m.registry.collect()}
    assert exported - parity.GOLDEN == {"gubernator_surprise"}
