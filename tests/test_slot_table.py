"""SlotTable unit tests (cache.go semantics: expiry, LRU, accounting)."""

from gubernator_tpu.models.slot_table import SlotTable


def test_assign_and_hit():
    t = SlotTable(4)
    s, exists = t.lookup_or_assign("a", 100)
    assert not exists
    t.commit([s], [200], [False])
    s2, exists = t.lookup_or_assign("a", 150)
    assert s2 == s and exists
    assert t.hits == 1 and t.misses == 1


def test_expired_recycles_same_slot():
    t = SlotTable(4)
    s, _ = t.lookup_or_assign("a", 100)
    t.commit([s], [200], [False])
    # Strict expiry boundary: at exactly ExpireAt the item is still live
    # (cache.go:151 `ExpireAt < now`).
    s2, exists = t.lookup_or_assign("a", 200)
    assert s2 == s and exists
    s2, exists = t.lookup_or_assign("a", 201)  # past expiry
    assert s2 == s and not exists


def test_lru_eviction_order():
    t = SlotTable(2)
    sa, _ = t.lookup_or_assign("a", 0)
    sb, _ = t.lookup_or_assign("b", 0)
    t.commit([sa, sb], [10**15, 10**15], [False, False])
    t.lookup_or_assign("a", 1)  # touch a; b becomes LRU
    sc, _ = t.lookup_or_assign("c", 2)
    assert sc == sb  # b evicted
    assert t.get_slot("b") is None
    assert t.get_slot("a") == sa
    assert t.evictions == 1


def test_removed_slot_freed():
    t = SlotTable(2)
    s, _ = t.lookup_or_assign("a", 0)
    t.commit([s], [0], [True])
    assert len(t) == 0
    s2, exists = t.lookup_or_assign("b", 0)
    assert not exists
    assert s2 == s  # freed slot reused




class TestColumnarNarrowAndPipelined:
    """The int32 wire (buckets.apply_rounds32) and the pipelined
    apply_columns_async must be semantically identical to the wide
    synchronous path."""

    def _cols(self, n, rng, now, greg=False):
        import numpy as np

        key_ids = rng.randint(0, max(n // 2, 1), size=n)
        keys = [f"nw:{k}" for k in key_ids]
        return keys, dict(
            algorithm=(key_ids % 2).astype(np.int32),
            behavior=np.zeros(n, np.int32),
            hits=np.ones(n, np.int64),
            limit=np.full(n, 7, np.int64),
            duration=np.full(n, 60_000, np.int64),
        )

    def test_narrow_matches_wide(self):
        import numpy as np

        from gubernator_tpu.models.shard import ShardStore

        rng = np.random.RandomState(7)
        now = 1_700_000_000_000
        n = 257
        keys, cols = self._cols(n, rng, now)
        narrow = ShardStore(capacity=1024)
        wide = ShardStore(capacity=1024)
        # Force the wide path by pushing one value over int32.
        wide_cols = dict(cols)
        for step in range(3):
            r1 = narrow.apply_columns(keys, now_ms=now + step, **cols)
            big = dict(wide_cols)
            big["limit"] = cols["limit"].copy()
            r2 = wide.apply_columns(
                keys, now_ms=now + step,
                algorithm=cols["algorithm"], behavior=cols["behavior"],
                hits=cols["hits"].astype(np.int64),
                limit=np.where(np.arange(n) == n - 1, 2**32, cols["limit"]),
                duration=cols["duration"],
            )
            # all lanes except the int64-limit one must agree
            for f in ("status", "remaining", "reset_time"):
                assert (np.asarray(r1[f])[:-1] == np.asarray(r2[f])[:-1]).all(), (
                    step, f)

    def test_narrow_predicate(self):
        import numpy as np

        from gubernator_tpu.models.shard import _Columns, narrow_ok

        now = 1_700_000_000_000
        c = _Columns(4)
        c.hits[:] = 1
        c.limit[:] = 10
        c.duration[:] = 1000
        c.greg_expire[:] = 0
        c.greg_duration[:] = 0
        assert narrow_ok(c, now)
        c.limit[2] = 2**31
        assert not narrow_ok(c, now)
        c.limit[2] = 10
        # Gregorian monthly: delta exceeds int32 only for huge spans
        c.greg_duration[1] = 3_000_000_000
        c.greg_expire[1] = now + 1000
        assert not narrow_ok(c, now)

    def test_dict_wire_parity_and_fallback(self):
        """The config-dictionary wire (few distinct configs) must match
        the per-lane narrow wire exactly; >256 distinct configs fall
        back; the lane->config mapping is exact."""
        import numpy as np

        from gubernator_tpu.models.shard import ShardStore, make_columns
        from gubernator_tpu.ops import buckets

        rng = np.random.RandomState(11)
        now = 1_700_000_000_000
        n = 400
        key_ids = rng.randint(0, 200, size=n)
        keys = [f"dw:{k}" for k in key_ids]
        few = dict(
            algorithm=(key_ids % 2).astype(np.int32),
            behavior=np.zeros(n, np.int32),
            hits=(1 + key_ids % 3).astype(np.int64),
            limit=np.full(n, 50, np.int64),
            duration=(60_000 + (key_ids % 4) * 1000).astype(np.int64),
        )
        # few-configs batch dict-encodes: 2 algos x 3 hits x 4 durations
        cols = make_columns(few["algorithm"], few["behavior"], few["hits"],
                            few["limit"], few["duration"], n)
        enc = buckets.build_config_dict(cols, now)
        assert enc is not None
        cfg_idx, table = enc
        for j in range(0, n, 37):  # spot-check exact lane->config mapping
            k = cfg_idx[j]
            assert table[0][k] == few["algorithm"][j]
            assert table[2][k] == few["hits"][j]
            assert table[4][k] == few["duration"][j]

        # >256 distinct configs: fallback to per-lane wire
        many = dict(few)
        many["limit"] = (10 + np.arange(n)).astype(np.int64)
        cols_many = make_columns(many["algorithm"], many["behavior"],
                                 many["hits"], many["limit"],
                                 many["duration"], n)
        assert buckets.build_config_dict(cols_many, now) is None

        # End-to-end: the dict wire must match the WIDE path lane for
        # lane on identical values (wide forced by one int64 lane,
        # which is excluded from the comparison).
        a = ShardStore(capacity=1024)
        b = ShardStore(capacity=1024)
        wide_keys = keys + ["dw:wide"]
        for step in range(3):
            r1 = a.apply_columns(keys, now_ms=now + step, **few)
            r2 = b.apply_columns(
                wide_keys, now_ms=now + step,
                algorithm=np.append(few["algorithm"], 0).astype(np.int32),
                behavior=np.append(few["behavior"], 0).astype(np.int32),
                hits=np.append(few["hits"], 1),
                limit=np.append(few["limit"], 2**32),  # forces wide
                duration=np.append(few["duration"], 60_000),
            )
            for f in ("status", "remaining", "reset_time"):
                assert (np.asarray(r1[f]) == np.asarray(r2[f])[:-1]).all(), (step, f)

    def test_pipelined_matches_sync_with_duplicates(self):
        import numpy as np

        from gubernator_tpu.models.shard import ShardStore

        rng = np.random.RandomState(3)
        now = 1_700_000_000_000
        n = 128
        keys, cols = self._cols(n, rng, now)
        sync = ShardStore(capacity=512)
        pipe = ShardStore(capacity=512)
        sync_res = [sync.apply_columns(keys, now_ms=now + i, **cols) for i in range(4)]
        handles = [pipe.apply_columns_async(keys, now_ms=now + i, **cols) for i in range(4)]
        pipe_res = [h.result() for h in handles]
        # resolving out of order must also be safe (FIFO enforced inside)
        assert handles[2].done
        for a, b in zip(sync_res, pipe_res):
            for f in ("status", "remaining", "reset_time"):
                assert (np.asarray(a[f]) == np.asarray(b[f])).all()


class TestGroupedDuplicates:
    """The analytic duplicate-group path (gt_batch_plan_grouped +
    occurrence math in ops/buckets.py) must match applying the same
    requests ONE AT A TIME in request order — the reference's
    mutex-serialized semantics (gubernator.go:336-337)."""

    def _differential(self, make_req, steps=60, seed=0):
        import numpy as np

        from gubernator_tpu.models.shard import ShardStore
        from gubernator_tpu.types import RateLimitRequest

        rng = np.random.RandomState(seed)
        grouped = ShardStore(capacity=256)
        serial = ShardStore(capacity=256)
        now = 1_700_000_000_000
        for step in range(steps):
            reqs = make_req(rng, step)
            now += rng.randint(0, 400)
            got = grouped.apply(reqs, now)
            want = [serial.apply([r], now)[0] for r in reqs]
            for i, (g, w) in enumerate(zip(got, want)):
                assert (g.status, g.remaining, g.reset_time) == (
                    w.status, w.remaining, w.reset_time,
                ), (step, i, reqs[i], g, w)

    def test_hot_key_token(self):
        from gubernator_tpu.types import Algorithm, RateLimitRequest

        def make(rng, step):
            # one hot key hammered 1-30x per batch + a few cold keys
            n_hot = rng.randint(1, 30)
            hits = int(rng.choice([0, 1, 1, 2, 5]))
            return [
                RateLimitRequest(
                    name="grp", unique_key="hot", hits=hits, limit=17,
                    duration=5_000, algorithm=Algorithm.TOKEN_BUCKET,
                )
                for _ in range(n_hot)
            ] + [
                RateLimitRequest(
                    name="grp", unique_key=f"cold{rng.randint(5)}", hits=1,
                    limit=3, duration=2_000, algorithm=Algorithm.TOKEN_BUCKET,
                )
                for _ in range(rng.randint(0, 4))
            ]

        self._differential(make, seed=11)

    def test_hot_key_leaky(self):
        from gubernator_tpu.types import Algorithm, RateLimitRequest

        def make(rng, step):
            n = rng.randint(1, 25)
            hits = int(rng.choice([0, 1, 2, 7]))
            return [
                RateLimitRequest(
                    name="grp", unique_key="lk", hits=hits, limit=21,
                    duration=3_000, algorithm=Algorithm.LEAKY_BUCKET,
                )
                for _ in range(n)
            ]

        self._differential(make, seed=22)

    def test_non_uniform_falls_back(self):
        """Varying hits/limit per duplicate forces the round path; the
        mix of grouped and round lanes in one batch must still match."""
        from gubernator_tpu.types import Algorithm, RateLimitRequest

        def make(rng, step):
            out = []
            for _ in range(rng.randint(2, 12)):
                out.append(
                    RateLimitRequest(
                        name="grp", unique_key="mix",
                        hits=int(rng.choice([1, 2])),   # non-uniform
                        limit=int(rng.choice([9, 9, 11])),
                        duration=4_000,
                        algorithm=Algorithm.TOKEN_BUCKET,
                    )
                )
            for _ in range(rng.randint(1, 10)):
                out.append(
                    RateLimitRequest(  # uniform group alongside
                        name="grp", unique_key="uni", hits=1, limit=6,
                        duration=4_000, algorithm=Algorithm.LEAKY_BUCKET,
                    )
                )
            rng.shuffle(out)
            return out

        self._differential(make, seed=33)

    def test_reset_remaining_group_is_sequential(self):
        from gubernator_tpu.types import Algorithm, Behavior, RateLimitRequest

        def make(rng, step):
            return [
                RateLimitRequest(
                    name="grp", unique_key="rr", hits=1, limit=4,
                    duration=3_000, algorithm=Algorithm.TOKEN_BUCKET,
                    behavior=(Behavior.RESET_REMAINING if rng.random() < 0.3 else 0),
                )
                for _ in range(rng.randint(1, 10))
            ]

        self._differential(make, seed=44)

    def test_grouped_over_limit_create(self):
        """Thundering herd on a cold key with hits > limit (the leaky
        over-create stores 0, token keeps limit)."""
        from gubernator_tpu.types import Algorithm, RateLimitRequest

        def make(rng, step):
            algo = Algorithm.TOKEN_BUCKET if step % 2 else Algorithm.LEAKY_BUCKET
            return [
                RateLimitRequest(
                    name="grp", unique_key=f"burst{step}", hits=9, limit=5,
                    duration=1_000, algorithm=algo,
                )
                for _ in range(rng.randint(2, 8))
            ]

        self._differential(make, steps=20, seed=55)


def test_narrow_batch_preserves_wide_expiry():
    """A leaky bucket created with a >int32-ms duration (wide path)
    keeps its exact far-future expiry bookkeeping when a later NARROW
    batch passes it through unchanged (hits=0 status query with a small
    config): the -2 sentinel reconstructs the absolute value instead of
    clipping the delta to ~24.8 days."""
    import numpy as np

    from gubernator_tpu.models.shard import ShardStore
    from gubernator_tpu.types import Algorithm

    now = 1_700_000_000_000
    thirty_days = 30 * 24 * 3600 * 1000  # > 2**31 ms
    store = ShardStore(capacity=64)
    store.apply_columns(
        ["long_k"],
        algorithm=np.array([Algorithm.LEAKY_BUCKET], np.int32),
        behavior=np.zeros(1, np.int32),
        hits=np.ones(1, np.int64),
        limit=np.array([10], np.int64),
        duration=np.array([thirty_days], np.int64),
        now_ms=now,
    )
    slot = store.table.get_slot("long_k")
    assert int(store.table.get_expire_bulk([slot])[0]) == now + thirty_days

    # Narrow batch (every column fits int32): a status query on the
    # long-lived key.  hits=0 on a leaky bucket mutates nothing — the
    # kernel passes the stored expiry straight through.
    later = now + 1000
    r = store.apply_columns(
        ["long_k", "other_k"],
        algorithm=np.array([Algorithm.LEAKY_BUCKET] * 2, np.int32),
        behavior=np.zeros(2, np.int32),
        hits=np.array([0, 1], np.int64),
        limit=np.array([10, 5], np.int64),
        duration=np.array([60_000, 1000], np.int64),
        now_ms=later,
    )
    assert int(np.asarray(r["remaining"])[0]) == 9
    # The regression: a clipped delta would have rewritten this to
    # later + ~2**31 ms (~24.8 days), silently shortening the bucket's
    # life by ~5 days.
    assert int(store.table.get_expire_bulk([slot])[0]) == now + thirty_days
