"""SlotTable unit tests (cache.go semantics: expiry, LRU, accounting)."""

from gubernator_tpu.models.slot_table import SlotTable


def test_assign_and_hit():
    t = SlotTable(4)
    s, exists = t.lookup_or_assign("a", 100)
    assert not exists
    t.commit([s], [200], [False])
    s2, exists = t.lookup_or_assign("a", 150)
    assert s2 == s and exists
    assert t.hits == 1 and t.misses == 1


def test_expired_recycles_same_slot():
    t = SlotTable(4)
    s, _ = t.lookup_or_assign("a", 100)
    t.commit([s], [200], [False])
    # Strict expiry boundary: at exactly ExpireAt the item is still live
    # (cache.go:151 `ExpireAt < now`).
    s2, exists = t.lookup_or_assign("a", 200)
    assert s2 == s and exists
    s2, exists = t.lookup_or_assign("a", 201)  # past expiry
    assert s2 == s and not exists


def test_lru_eviction_order():
    t = SlotTable(2)
    sa, _ = t.lookup_or_assign("a", 0)
    sb, _ = t.lookup_or_assign("b", 0)
    t.commit([sa, sb], [10**15, 10**15], [False, False])
    t.lookup_or_assign("a", 1)  # touch a; b becomes LRU
    sc, _ = t.lookup_or_assign("c", 2)
    assert sc == sb  # b evicted
    assert t.get_slot("b") is None
    assert t.get_slot("a") == sa
    assert t.evictions == 1


def test_removed_slot_freed():
    t = SlotTable(2)
    s, _ = t.lookup_or_assign("a", 0)
    t.commit([s], [0], [True])
    assert len(t) == 0
    s2, exists = t.lookup_or_assign("b", 0)
    assert not exists
    assert s2 == s  # freed slot reused


