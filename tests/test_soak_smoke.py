"""CPU-backend soak smoke (`make soak-smoke`): a short
scripts/long_soak.py-derived run that drives mixed-shape traffic at a
2-daemon cluster while POLLING GET /debug/status — the observability
backbone the ROADMAP item-5 soak harness will assert against — and
checks steady-state invariants on every poll:

  * health stays "healthy", zero breakers open,
  * zero ingress shed,
  * occupancy monotone-consistent (used <= capacity, eviction counters
    never go backwards),
  * queue depth bounded by the configured cap,
  * the SLO engine live (enabled, burn rates present) and the latency
    attribution phases populated,
  * the CONSERVATION AUDIT silent: zero invariant violations on every
    poll and on a final quiesced reconciliation pass (audit.py — a
    clean soak is the audit's no-false-positive contract).

Marked `slow` (excluded from tier-1); `make soak-smoke` runs it alone.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request

import pytest

from gubernator_tpu.client import V1Client
from gubernator_tpu.cluster import Cluster, fast_test_behaviors
from gubernator_tpu.types import (
    Algorithm,
    Behavior,
    GetRateLimitsRequest,
    RateLimitRequest,
)

SOAK_S = 20
POLL_EVERY_S = 2.0

SHAPES = [
    (1, 0), (1, int(Behavior.NO_BATCHING)), (50, 0),
    (200, 0), (4, int(Behavior.GLOBAL)),
]


def _fetch(addr: str, path: str) -> dict:
    with urllib.request.urlopen(f"http://{addr}{path}", timeout=10) as r:
        return json.loads(r.read())


@pytest.mark.slow
def test_soak_smoke_status_invariants():
    beh = fast_test_behaviors()
    beh.batch_timeout_s = 30.0
    # SLO engine live for the soak: a generous CPU-box target — the
    # invariant checked is "the plane reports", the bench gate owns
    # latency regression verdicts.
    beh.latency_target_ms = 30_000.0
    cl = Cluster().start_with(["", ""], behaviors=beh)
    stop = threading.Event()
    lock = threading.Lock()
    stats = {"requests": 0, "errors": []}

    def worker(wid: int) -> None:
        client = V1Client(cl.daemons[wid % 2].gateway.address, timeout_s=60.0)
        i = 0
        while not stop.is_set():
            lanes, b = SHAPES[(wid + i) % len(SHAPES)]
            reqs = [
                RateLimitRequest(
                    name="smoke", unique_key=f"w{wid % 3}k{(i + j) % 40}",
                    hits=1, limit=100_000_000, duration=120_000,
                    algorithm=(
                        Algorithm.TOKEN_BUCKET if j % 2 == 0
                        else Algorithm.LEAKY_BUCKET
                    ),
                    behavior=b,
                )
                for j in range(lanes)
            ]
            try:
                resp = client.get_rate_limits(
                    GetRateLimitsRequest(requests=reqs)
                )
                errs = [r.error for r in resp.responses if r.error]
                with lock:
                    stats["requests"] += 1
                    stats["errors"].extend(errs[:2])
            except Exception as e:  # noqa: BLE001
                with lock:
                    stats["errors"].append(f"{type(e).__name__}: {e}")
            i += 1

    threads = [threading.Thread(target=worker, args=(w,)) for w in range(6)]
    for t in threads:
        t.start()

    polls = 0
    last_evictions = {}
    violations = []
    try:
        t0 = time.time()
        while time.time() - t0 < SOAK_S:
            time.sleep(POLL_EVERY_S)
            for d in cl.daemons:
                addr = d.gateway.address
                doc = _fetch(addr, "/debug/status")
                polls += 1
                h = doc["health"]
                if h["status"] != "healthy":
                    violations.append(f"{addr}: unhealthy: {h['message']}")
                if h["breakerOpenCount"]:
                    violations.append(
                        f"{addr}: {h['breakerOpenCount']} breakers open"
                    )
                ing = doc["ingress"]
                if ing["shedLanes"]:
                    violations.append(f"{addr}: shed {ing['shedLanes']} lanes")
                if ing["capLanes"] and ing["queuedLanes"] > ing["capLanes"]:
                    violations.append(
                        f"{addr}: queue {ing['queuedLanes']} > cap"
                    )
                occ = doc["occupancy"]
                if occ["used"] > occ["capacity"]:
                    violations.append(
                        f"{addr}: occupancy {occ['used']} > {occ['capacity']}"
                    )
                if occ["evictions"] < last_evictions.get(addr, 0):
                    violations.append(f"{addr}: eviction counter went back")
                last_evictions[addr] = occ["evictions"]
                assert doc["slo"]["enabled"] is True
                assert "burn_rate_5m" in doc["slo"]
                aud = doc["audit"]
                assert aud["enabled"] is True
                if aud["violationTotal"]:
                    violations.append(
                        f"{addr}: audit violations {aud['violations']}"
                    )
            if violations:
                break
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=60)
        alive = [t.name for t in threads if t.is_alive()]
        # Attribution phases populated by the soak traffic (the
        # /debug/latency half of the backbone).
        lat = _fetch(cl.daemons[0].gateway.address, "/debug/latency")
        cl.stop()

    assert not alive, f"threads deadlocked: {alive}"
    assert not violations, violations[:5]
    # Final quiesced reconciliation: with traffic drained the ledger
    # inequalities are at their tightest — still zero violations.  The
    # thread-liveness assert runs FIRST: check_now() below also bumps
    # `checks`, which would mask a checker thread that never started.
    for d in cl.daemons:
        assert d.service.auditor.checks > 0, "auditor thread never ran"
        assert d.service.auditor.check_now() == []
        assert d.service.auditor.violations == {}
    assert polls >= 4, "soak made too few status polls"
    assert stats["requests"] > 50, "soak made no progress"
    assert not stats["errors"], stats["errors"][:5]
    assert "dispatch.launch" in lat["phases"], lat["phases"].keys()
    assert "ingress.total" in lat["phases"]
