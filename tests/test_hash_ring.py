"""Consistent-hash ring tests, incl. the pinned distribution table from
replicated_hash_test.go:40-86 (same hosts, same 10k synthetic IPs, same
expected per-host counts for fnv1 and fnv1a)."""

import pytest

from gubernator_tpu.parallel.hash_ring import (
    DEFAULT_REPLICAS,
    ReplicatedConsistentHash,
    fnv1_hash,
    fnv1a_hash,
)

HOSTS = ["a.svc.local", "b.svc.local", "c.svc.local"]


def test_size_and_membership():
    ring = ReplicatedConsistentHash()
    for h in HOSTS:
        ring.add(h, peer={"addr": h})
    assert ring.size() == 3
    assert ring.get_by_peer_id("a.svc.local") == {"addr": "a.svc.local"}
    assert sorted(ring.peer_ids()) == sorted(HOSTS)


def test_empty_ring_raises():
    ring = ReplicatedConsistentHash()
    with pytest.raises(RuntimeError, match="pool is empty"):
        ring.get("x")


@pytest.mark.parametrize(
    "hash_fn,expected",
    [
        (None, {"a.svc.local": 2948, "b.svc.local": 3592, "c.svc.local": 3460}),
        (fnv1a_hash(), {"a.svc.local": 3110, "b.svc.local": 3856, "c.svc.local": 3034}),
        (fnv1_hash(), {"a.svc.local": 2948, "b.svc.local": 3592, "c.svc.local": 3460}),
    ],
    ids=["default", "fnv1a", "fnv1"],
)
def test_pinned_distribution(hash_fn, expected):
    """Exact parity with the reference's pinned table — proves vnode
    construction, hashing, and ring search all match bit-for-bit."""
    ring = ReplicatedConsistentHash(hash_fn, DEFAULT_REPLICAS)
    for h in HOSTS:
        ring.add(h)
    keys = [f"192.168.{i >> 8}.{i & 255}" for i in range(10000)]
    dist = {h: 0 for h in HOSTS}
    for owner in ring.get_batch(keys):
        dist[owner] += 1
    assert dist == expected


def test_get_matches_get_batch():
    ring = ReplicatedConsistentHash()
    for h in HOSTS:
        ring.add(h)
    keys = [f"key_{i}" for i in range(500)]
    assert ring.get_batch(keys) == [ring.get(k) for k in keys]


# ---------------------------------------------------------------------
# Ring-delta ownership math (elastic membership, reshard.py): the
# vectorized get_batch_codes diff between two rings must EXACTLY
# partition any key set into stay/move — the resharding plane's drain
# scan and the double-dispatch window both hang off this property.
# ---------------------------------------------------------------------
def _owners(ring, keys):
    """Per-key owner ids via the vectorized code path."""
    codes, ids = ring.get_batch_codes(keys)
    return [ids[c] for c in codes]


def _build(hosts, replicas):
    ring = ReplicatedConsistentHash(replicas=replicas)
    for h in hosts:
        ring.add(h)
    return ring


DELTA_KEYS = [f"user_{i}" for i in range(2000)]


@pytest.mark.parametrize("replicas", [16, 128, DEFAULT_REPLICAS])
@pytest.mark.parametrize(
    "old_hosts,new_hosts",
    [
        (HOSTS[:2], HOSTS),                 # join
        (HOSTS, HOSTS[:2]),                 # leave
        (HOSTS[:2], [HOSTS[0], "d.svc.local"]),  # replace
        (HOSTS, ["d.svc.local", "e.svc.local"]),  # multi-replace
    ],
    ids=["join", "leave", "replace", "multi-replace"],
)
def test_ownership_diff_partitions_keys(replicas, old_hosts, new_hosts):
    old = _build(old_hosts, replicas)
    new = _build(new_hosts, replicas)
    before = _owners(old, DELTA_KEYS)
    after = _owners(new, DELTA_KEYS)
    stay = {k for k, o, n in zip(DELTA_KEYS, before, after) if o == n}
    move = {k for k, o, n in zip(DELTA_KEYS, before, after) if o != n}
    # Exact partition: disjoint, exhaustive.
    assert stay | move == set(DELTA_KEYS)
    assert not (stay & move)
    # The codes diff agrees with the scalar reference lookup per key.
    for k, o, n in zip(DELTA_KEYS, before, after):
        assert o == old.get(k)
        assert n == new.get(k)
    surviving = set(old_hosts) & set(new_hosts)
    joined = set(new_hosts) - set(old_hosts)
    for k in move:
        # A moved key's new owner is a ring member; keys never move
        # BETWEEN two surviving peers on a pure join (consistent
        # hashing only reassigns ranges claimed by new vnodes).
        assert new.get(k) in new_hosts
        if not joined:
            continue
        if old.get(k) in surviving and not (set(old_hosts) - set(new_hosts)):
            assert new.get(k) in joined


@pytest.mark.parametrize("replicas", [16, DEFAULT_REPLICAS])
def test_pure_join_moves_only_to_new_peer(replicas):
    old = _build(HOSTS[:2], replicas)
    new = _build(HOSTS, replicas)
    before = _owners(old, DELTA_KEYS)
    after = _owners(new, DELTA_KEYS)
    moved_to = {n for o, n in zip(before, after) if o != n}
    assert moved_to == {HOSTS[2]}  # every moved key lands on the joiner
    # And a pure LEAVE moves exactly the departed peer's keys.
    back = _owners(old, DELTA_KEYS)
    lost = [k for k, o in zip(DELTA_KEYS, after) if o == HOSTS[2]]
    relocated = {
        k: n for k, o, n in zip(DELTA_KEYS, after, back) if o != n
    }
    assert set(relocated) == set(lost)
    assert all(n in HOSTS[:2] for n in relocated.values())


def test_fingerprint_tracks_membership_not_order():
    r1 = _build(HOSTS, DEFAULT_REPLICAS)
    r2 = _build(list(reversed(HOSTS)), DEFAULT_REPLICAS)
    assert r1.fingerprint() == r2.fingerprint()
    r3 = _build(HOSTS[:2], DEFAULT_REPLICAS)
    assert r3.fingerprint() != r1.fingerprint()
    # replicas participate: same members, different vnode count, a
    # DIFFERENT ownership map — must be a different epoch.
    assert _build(HOSTS, 16).fingerprint() != r1.fingerprint()
