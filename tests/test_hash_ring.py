"""Consistent-hash ring tests, incl. the pinned distribution table from
replicated_hash_test.go:40-86 (same hosts, same 10k synthetic IPs, same
expected per-host counts for fnv1 and fnv1a)."""

import pytest

from gubernator_tpu.parallel.hash_ring import (
    DEFAULT_REPLICAS,
    ReplicatedConsistentHash,
    fnv1_hash,
    fnv1a_hash,
)

HOSTS = ["a.svc.local", "b.svc.local", "c.svc.local"]


def test_size_and_membership():
    ring = ReplicatedConsistentHash()
    for h in HOSTS:
        ring.add(h, peer={"addr": h})
    assert ring.size() == 3
    assert ring.get_by_peer_id("a.svc.local") == {"addr": "a.svc.local"}
    assert sorted(ring.peer_ids()) == sorted(HOSTS)


def test_empty_ring_raises():
    ring = ReplicatedConsistentHash()
    with pytest.raises(RuntimeError, match="pool is empty"):
        ring.get("x")


@pytest.mark.parametrize(
    "hash_fn,expected",
    [
        (None, {"a.svc.local": 2948, "b.svc.local": 3592, "c.svc.local": 3460}),
        (fnv1a_hash(), {"a.svc.local": 3110, "b.svc.local": 3856, "c.svc.local": 3034}),
        (fnv1_hash(), {"a.svc.local": 2948, "b.svc.local": 3592, "c.svc.local": 3460}),
    ],
    ids=["default", "fnv1a", "fnv1"],
)
def test_pinned_distribution(hash_fn, expected):
    """Exact parity with the reference's pinned table — proves vnode
    construction, hashing, and ring search all match bit-for-bit."""
    ring = ReplicatedConsistentHash(hash_fn, DEFAULT_REPLICAS)
    for h in HOSTS:
        ring.add(h)
    keys = [f"192.168.{i >> 8}.{i & 255}" for i in range(10000)]
    dist = {h: 0 for h in HOSTS}
    for owner in ring.get_batch(keys):
        dist[owner] += 1
    assert dist == expected


def test_get_matches_get_batch():
    ring = ReplicatedConsistentHash()
    for h in HOSTS:
        ring.add(h)
    keys = [f"key_{i}" for i in range(500)]
    assert ring.get_batch(keys) == [ring.get(k) for k in keys]
