"""Federation plane tests (federation.py): the per-region accumulator,
encode-once fan-out, carry/requeue partition semantics, the
region_conservation audit chain, and mixed-version interop.

Two tiers:

* unit tests against a FakeService — deterministic, no device, no
  sockets: batching semantics (multi_region_batch_limit honored, per-key
  aggregation), the PR 5 hit-carry discipline per destination region
  (provably-unapplied requeues, timeout-shaped drops counted, bounded
  carry, departed regions), and the encode-once sharing rule;
* cluster tests against real daemons — the columnar wire end-to-end,
  a seeded FaultPlan DUPLICATE on the region wire proven caught by
  `region_conservation`, the chaos-safe carry/requeue exactly-once
  regression, and both interop directions.
"""

from __future__ import annotations

import time
from types import SimpleNamespace

import numpy as np
import pytest

from gubernator_tpu import audit, faults, federation
from gubernator_tpu.cluster import fast_test_behaviors
from gubernator_tpu.config import BehaviorConfig, DaemonConfig
from gubernator_tpu.daemon import Daemon
from gubernator_tpu.federation import FederationManager, RegionBatch
from gubernator_tpu.metrics import Metrics
from gubernator_tpu.parallel.region import RegionPicker
from gubernator_tpu.peer_client import PeerError
from gubernator_tpu.types import (
    Behavior,
    GetRateLimitsRequest,
    PeerInfo,
    RateLimitRequest,
)
from gubernator_tpu.utils.clock import Clock


# ----------------------------------------------------------------------
# Unit tier: FakeService drives the manager deterministically
# ----------------------------------------------------------------------
class FakePeer:
    """Region-owner stand-in recording update_region_columns sends; a
    script of exceptions makes it misbehave first."""

    def __init__(self, addr: str, dc: str, script=()):
        self.info = PeerInfo(
            grpc_address=addr, http_address=f"h-{addr}", data_center=dc
        )
        self.batches = []
        self.script = list(script)

    def update_region_columns(self, batch, timeout_s=None, trace_ctx=None):
        if self.script:
            raise self.script.pop(0)
        self.batches.append(batch)


class FakeService:
    def __init__(self, peers, data_center="dc-a", batch_limit=1000,
                 sync_wait_s=3600.0):
        beh = BehaviorConfig(
            multi_region_sync_wait_s=sync_wait_s,
            multi_region_batch_limit=batch_limit,
            multi_region_timeout_s=5.0,
        )
        self.conf = SimpleNamespace(behaviors=beh, data_center=data_center)
        self.metrics = Metrics()
        self._rp = RegionPicker()
        for p in peers:
            self._rp.add(p)

    def get_region_picker(self):
        return self._rp

    def _peer_send_ex(self, op, fn):
        try:
            fn()
            return True, None
        except Exception as e:  # noqa: BLE001 — shape-classified by caller
            return False, e


def mr_req(key, hits=1, limit=1000):
    return RateLimitRequest(
        name="mr", unique_key=key, hits=hits, limit=limit, duration=60_000,
        behavior=int(Behavior.MULTI_REGION),
    )


@pytest.fixture
def ledger():
    before = audit.ledger_snapshot()

    def delta(counter):
        return audit.ledger_snapshot()[counter] - before[counter]

    return delta


def make_mgr(peers, **kw):
    svc = FakeService(peers, **kw)
    mgr = FederationManager(svc)
    return svc, mgr


def test_per_key_aggregation_and_flush(ledger):
    peer = FakePeer("b:81", "dc-b")
    svc, mgr = make_mgr([peer])
    try:
        for _ in range(3):
            mgr.queue_hits(mr_req("a", hits=2))
        mgr.queue_hits(mr_req("b", hits=1))
        assert mgr.run_once() is True
        (batch,) = peer.batches
        assert sorted(
            zip(batch.cols.unique_keys, batch.cols.hits.tolist())
        ) == [("a", 6), ("b", 1)]
        # MULTI_REGION stripped on the wire (the no-amplification rule)
        assert not (
            batch.cols.behavior & int(Behavior.MULTI_REGION)
        ).any()
        assert batch.cols.origin == "dc-a"
        assert ledger("region_agg_hits") == 7
        assert ledger("region_sent_hits") == 7
        # idle flush is a no-op
        assert mgr.run_once() is False
    finally:
        mgr.stop()


def test_batch_limit_kicks_early_flush():
    """multi_region_batch_limit was parsed-but-unenforced before the
    federation plane: reaching it must flush WITHOUT waiting out the
    3600s window (the reference's queue-full flush)."""
    peer = FakePeer("b:81", "dc-b")
    svc, mgr = make_mgr([peer], batch_limit=3)
    try:
        for i in range(3):
            mgr.queue_hits(mr_req(f"k{i}"))
        deadline = time.time() + 5.0
        while time.time() < deadline and not peer.batches:
            time.sleep(0.01)
        assert peer.batches, "batch-limit flush never kicked"
        assert len(peer.batches[0]) == 3
    finally:
        mgr.stop()


def test_encode_once_across_regions():
    """When every region's ring maps the whole flush to one owner, all
    regions share the SAME RegionBatch object — the frame/proto bytes
    encode once per flush, not once per region."""
    pb_ = FakePeer("b:81", "dc-b")
    pc_ = FakePeer("c:81", "dc-c")
    svc, mgr = make_mgr([pb_, pc_])
    try:
        mgr.queue_hits(mr_req("a", hits=2))
        assert mgr.run_once()
        assert pb_.batches and pc_.batches
        assert pb_.batches[0] is pc_.batches[0]
    finally:
        mgr.stop()


def test_provably_unapplied_requeues_then_delivers_once(ledger):
    """The PR 5 hit-carry discipline per destination region: a breaker
    fast-fail / connection-level not-ready provably never applied, so
    the hits carry into the next flush (summed per key) and deliver
    exactly once after heal."""
    peer = FakePeer(
        "b:81", "dc-b",
        script=[PeerError("injected", not_ready=True)],
    )
    svc, mgr = make_mgr([peer])
    try:
        mgr.queue_hits(mr_req("a", hits=3))
        assert mgr.run_once()
        assert peer.batches == []
        assert mgr.snapshot()["carryKeyTotal"] == 1
        assert ledger("region_sent_hits") == 0
        # next window adds 2 more hits for the same key
        mgr.queue_hits(mr_req("a", hits=2))
        assert mgr.run_once()
        (batch,) = peer.batches
        assert batch.cols.unique_keys == ["a"]
        assert batch.cols.hits.tolist() == [5]  # carried 3 + new 2
        assert mgr.snapshot()["carryKeyTotal"] == 0
        assert ledger("region_sent_hits") == 5
        assert ledger("region_agg_hits") == 5
        assert ledger("region_dropped_hits") == 0
    finally:
        mgr.stop()


def test_timeout_shaped_failure_drops_counted(ledger):
    """A timeout may have applied remotely: re-sending would
    double-count, so the hits drop COUNTED instead of requeueing."""
    peer = FakePeer(
        "b:81", "dc-b",
        script=[PeerError("deadline", not_ready=False)],
    )
    svc, mgr = make_mgr([peer])
    try:
        mgr.queue_hits(mr_req("a", hits=4))
        assert mgr.run_once()
        assert mgr.snapshot()["carryKeyTotal"] == 0
        assert mgr.snapshot()["droppedHits"] == 4
        assert ledger("region_dropped_hits") == 4
        # delivery inequality stays one-sided: sent + dropped <= agg
        assert ledger("region_sent_hits") == 0
        assert ledger("region_agg_hits") == 4
    finally:
        mgr.stop()


def test_carry_is_bounded_and_overflow_drops_counted(ledger, monkeypatch):
    monkeypatch.setattr(federation, "REGION_CARRY_MAX", 2)
    peer = FakePeer(
        "b:81", "dc-b",
        script=[PeerError("injected", not_ready=True)],
    )
    svc, mgr = make_mgr([peer])
    try:
        for i in range(4):
            mgr.queue_hits(mr_req(f"k{i}", hits=1))
        assert mgr.run_once()
        snap = mgr.snapshot()
        assert snap["carryKeyTotal"] == 2  # capped
        assert snap["droppedHits"] == 2   # overflow counted, not lost
        assert ledger("region_dropped_hits") == 2
        # the audited gauge reflects the live carry for region_slack
        assert audit.gauges_snapshot()[audit.REGION_CARRY_GAUGE] == 2
    finally:
        mgr.stop()


def test_departed_region_carry_drops_counted(ledger):
    peer = FakePeer(
        "b:81", "dc-b",
        script=[PeerError("injected", not_ready=True)],
    )
    svc, mgr = make_mgr([peer])
    try:
        mgr.queue_hits(mr_req("a", hits=3))
        assert mgr.run_once()
        assert mgr.snapshot()["carryKeyTotal"] == 1
        # dc-b leaves the membership entirely
        svc._rp.remove(peer)
        mgr.run_once()
        assert mgr.snapshot()["carryKeyTotal"] == 0
        assert ledger("region_dropped_hits") == 3
    finally:
        mgr.stop()


def test_unset_data_center_single_region_is_a_noop(ledger):
    """A GUBER_DATA_CENTER-unset daemon with no named-region peers must
    behave exactly like the pre-PR build: MULTI_REGION hits apply
    locally, the queue drains without sends, and NO region ledger
    counters move."""
    svc, mgr = make_mgr([], data_center="")
    try:
        mgr.queue_hits(mr_req("a", hits=3))
        assert mgr.run_once() is False
        for c in ("region_agg_hits", "region_sent_hits",
                  "region_dropped_hits", "region_admitted_hits",
                  "region_wire_hits"):
            assert ledger(c) == 0, c
        assert mgr.snapshot()["flushes"] == 0
    finally:
        mgr.stop()


def test_unroutable_keys_requeue(ledger):
    """A region ring that churns mid-flush (pick answers None) is a
    provably-unapplied outcome: the keys carry instead of dropping."""
    peer = FakePeer("b:81", "dc-b")
    svc, mgr = make_mgr([peer])
    try:
        mgr.queue_hits(mr_req("a", hits=2))

        real_pick = svc._rp.pick
        svc._rp.pick = lambda dc, k: None
        assert mgr.run_once() is False  # nothing routable
        assert mgr.snapshot()["carryKeyTotal"] == 1
        svc._rp.pick = real_pick
        assert mgr.run_once()
        (batch,) = peer.batches
        assert batch.cols.hits.tolist() == [2]
        assert ledger("region_sent_hits") == 2
    finally:
        mgr.stop()


# ----------------------------------------------------------------------
# Cluster tier: real daemons, real wire
# ----------------------------------------------------------------------
T0 = 1_700_000_000_000


def _regional_daemon(dc, clock, region_columns=True, sync_wait_s=3600.0):
    behaviors = fast_test_behaviors()
    behaviors.multi_region_sync_wait_s = sync_wait_s
    behaviors.global_sync_wait_s = 3600.0
    behaviors.region_columns = region_columns
    return Daemon(
        DaemonConfig(
            listen_address="127.0.0.1:0",
            grpc_listen_address="127.0.0.1:0",
            cache_size=4096,
            global_cache_size=256,
            data_center=dc,
            behaviors=behaviors,
            peer_discovery_type="static",
        ),
        clock=clock,
    ).start()


@pytest.fixture
def two_region_pair(request):
    """One daemon per region, manual flush control (3600s window)."""
    marker = request.node.get_closest_marker("region_pair")
    kwargs = dict(marker.kwargs) if marker else {}
    clock = Clock()
    clock.freeze(T0)
    a = _regional_daemon("dc-a", clock, **kwargs.get("a", {}))
    b = _regional_daemon("dc-b", clock, **kwargs.get("b", {}))
    peers = [a.peer_info, b.peer_info]
    a.set_peers(peers)
    b.set_peers(peers)
    yield a, b
    a.close()
    b.close()


def _remaining_on(daemon, name, key, limit=1000):
    resp = daemon.service.get_peer_rate_limits(
        GetRateLimitsRequest(requests=[
            RateLimitRequest(name=name, unique_key=key, hits=0, limit=limit,
                             duration=60_000)
        ])
    )
    assert resp.responses[0].error == ""
    return resp.responses[0].remaining


def _region_client(daemon, dc, hash_key):
    client = daemon.service.get_region_picker().pick(dc, hash_key)
    assert client is not None
    return client


def test_columnar_wire_end_to_end(two_region_pair):
    a, b = two_region_pair
    a.service.get_rate_limits(GetRateLimitsRequest(requests=[
        RateLimitRequest(name="mr", unique_key="e2e", hits=5, limit=1000,
                         duration=60_000,
                         behavior=int(Behavior.MULTI_REGION))
    ]))
    before = audit.ledger_snapshot()
    assert a.service.multi_region_mgr.run_once()
    after = audit.ledger_snapshot()
    # negotiated columnar, not the classic fallback
    client = _region_client(a, "dc-b", "mr_e2e")
    assert client._region_columnar is True
    assert _remaining_on(b, "mr", "e2e") == 995
    # sender chain: admitted == wire == sent == 5; receiver chain:
    # recv == applied == 5 (the shared in-process ledger sees both)
    for c in ("region_admitted_hits", "region_wire_hits",
              "region_sent_hits", "region_recv_hits",
              "region_applied_hits"):
        assert after[c] - before[c] == 5, c
    # audits on both sides stay silent
    for d in two_region_pair:
        d.service.auditor.check_now()
        assert d.service.auditor.snapshot()["violationTotal"] == 0
    # debug surface carries the region section
    status = a.service.debug_status()["region"]
    assert status["dataCenter"] == "dc-a"
    assert status["regions"] == {"dc-b": {"peers": 1, "breakerOpen": 0}}
    assert status["sentHits"] == 5


@pytest.mark.chaos
def test_seeded_duplicate_on_region_wire_is_caught(two_region_pair):
    """Acceptance line: a FaultPlan DUPLICATE on the region wire — the
    byzantine re-delivery of an applied batch — must double
    region_wire_hits against a single region_admitted_hits note and
    trip region_conservation on the audit."""
    a, b = two_region_pair
    # burn the auditor's silent seeding pass so the next check can fire
    a.service.auditor.check_now()
    plan = faults.FaultPlan(seed=17)
    plan.duplicate(op="UpdateRegionColumns")
    faults.install(plan)
    try:
        a.service.get_rate_limits(GetRateLimitsRequest(requests=[
            RateLimitRequest(name="mr", unique_key="dup", hits=4, limit=1000,
                             duration=60_000,
                             behavior=int(Behavior.MULTI_REGION))
        ]))
        before = audit.ledger_snapshot()
        assert a.service.multi_region_mgr.run_once()
        after = audit.ledger_snapshot()
        assert after["region_admitted_hits"] - before["region_admitted_hits"] == 4
        assert after["region_wire_hits"] - before["region_wire_hits"] == 8
        a.service.auditor.check_now()
        snap = a.service.auditor.snapshot()
        assert snap["violations"].get("region_conservation", 0) >= 1
    finally:
        faults.uninstall()


@pytest.mark.chaos
def test_chaos_carry_requeues_and_delivers_exactly_once(two_region_pair):
    """The carry/requeue regression the bench gate rides on: a
    partition toward the remote region carries the flush; heal delivers
    the carried hits EXACTLY once (remote remaining moves by the summed
    hits, audits silent)."""
    a, b = two_region_pair
    plan = faults.FaultPlan(seed=23)
    rule = plan.partition(b.peer_info.grpc_address,
                          op="UpdateRegionColumns")
    faults.install(plan)
    try:
        a.service.get_rate_limits(GetRateLimitsRequest(requests=[
            RateLimitRequest(name="mr", unique_key="carry", hits=3,
                             limit=1000, duration=60_000,
                             behavior=int(Behavior.MULTI_REGION))
        ]))
        a.service.multi_region_mgr.run_once()
        assert a.service.multi_region_mgr.snapshot()["carryKeyTotal"] == 1
        assert _remaining_on(b, "mr", "carry") == 1000  # nothing landed
        # second window queues 2 more hits while partitioned
        a.service.get_rate_limits(GetRateLimitsRequest(requests=[
            RateLimitRequest(name="mr", unique_key="carry", hits=2,
                             limit=1000, duration=60_000,
                             behavior=int(Behavior.MULTI_REGION))
        ]))
        plan.heal(rule.peer)
        assert a.service.multi_region_mgr.run_once()
        assert _remaining_on(b, "mr", "carry") == 995  # 3+2, exactly once
        assert a.service.multi_region_mgr.snapshot()["carryKeyTotal"] == 0
        for d in two_region_pair:
            d.service.auditor.check_now()
            assert d.service.auditor.snapshot()["violationTotal"] == 0
    finally:
        faults.uninstall()


@pytest.mark.region_pair(b={"region_columns": False})
def test_interop_columnar_sender_classic_receiver(two_region_pair):
    """Downgrade direction: the receiver predates the plane (or runs
    GUBER_REGION_COLUMNS=0) — UNIMPLEMENTED/404 on the probe, sticky
    classic per-item fallback inside the same guarded call,
    breaker/health-neutral, hits still land exactly once."""
    a, b = two_region_pair
    a.service.get_rate_limits(GetRateLimitsRequest(requests=[
        RateLimitRequest(name="mr", unique_key="iop", hits=4, limit=1000,
                         duration=60_000,
                         behavior=int(Behavior.MULTI_REGION))
    ]))
    assert a.service.multi_region_mgr.run_once()
    client = _region_client(a, "dc-b", "mr_iop")
    assert client._region_columnar is False  # remembered per client
    assert _remaining_on(b, "mr", "iop") == 996
    assert not client.breaker.is_open
    assert a.service.health_check().status == "healthy"
    # sticky: the next flush goes straight to classic, still lands
    a.service.get_rate_limits(GetRateLimitsRequest(requests=[
        RateLimitRequest(name="mr", unique_key="iop", hits=1, limit=1000,
                         duration=60_000,
                         behavior=int(Behavior.MULTI_REGION))
    ]))
    assert a.service.multi_region_mgr.run_once()
    assert _remaining_on(b, "mr", "iop") == 995
    for d in two_region_pair:
        d.service.auditor.check_now()
        assert d.service.auditor.snapshot()["violationTotal"] == 0


@pytest.mark.region_pair(a={"region_columns": False})
def test_interop_classic_sender_columnar_receiver(two_region_pair):
    """Upgrade direction: a classic sender (pre-federation wire) talks
    to a columnar receiver through the ordinary GetPeerRateLimits door
    — behavior-identical application, no region receive counters."""
    a, b = two_region_pair
    before = audit.ledger_snapshot()
    a.service.get_rate_limits(GetRateLimitsRequest(requests=[
        RateLimitRequest(name="mr", unique_key="up", hits=2, limit=1000,
                         duration=60_000,
                         behavior=int(Behavior.MULTI_REGION))
    ]))
    assert a.service.multi_region_mgr.run_once()
    client = _region_client(a, "dc-b", "mr_up")
    assert client._region_columnar is False  # knob-off: never probes
    assert _remaining_on(b, "mr", "up") == 998
    after = audit.ledger_snapshot()
    # classic wire enters the receiver through the peer door, not the
    # region columnar surface
    assert after["region_recv_hits"] == before["region_recv_hits"]
    assert after["region_sent_hits"] - before["region_sent_hits"] == 2
    for d in two_region_pair:
        d.service.auditor.check_now()
        assert d.service.auditor.snapshot()["violationTotal"] == 0
