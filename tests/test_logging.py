"""Logging helper tests (reference logging/logging.go)."""

import io
import json
import logging

import pytest

from gubernator_tpu.utils.logging import (
    LogLevelJSON,
    LogWriter,
    category_logger,
    setup_logging,
)


def test_log_level_json_round_trip():
    for name, level in (("debug", logging.DEBUG), ("info", logging.INFO),
                        ("warning", logging.WARNING), ("error", logging.ERROR)):
        l = LogLevelJSON(level)
        assert json.loads(l.to_json()) == name
        assert LogLevelJSON.from_json(l.to_json()) == l


def test_log_level_json_numeric_and_invalid():
    assert LogLevelJSON.from_json("10").level == logging.DEBUG
    with pytest.raises(ValueError):
        LogLevelJSON.from_json('"noisy"')


def test_log_writer_forwards_lines():
    logger = logging.getLogger("gubernator.test_writer")
    logger.setLevel(logging.DEBUG)
    records = []
    handler = logging.Handler()
    handler.emit = lambda r: records.append(r.getMessage())
    logger.addHandler(handler)
    try:
        w = LogWriter(logger)
        w.write("[DEBUG] partial")
        assert records == []  # incomplete line buffered
        w.write(" line\nsecond line\ntrailing")
        assert records == ["[DEBUG] partial line", "second line"]
        w.flush()
        assert records[-1] == "trailing"
    finally:
        logger.removeHandler(handler)


def test_setup_logging_category_format():
    buf = io.StringIO()
    logger = setup_logging(debug=True, stream=buf)
    try:
        category_logger("unit").debug("hello world")
        out = buf.getvalue()
        assert "category=gubernator" in out
        assert "logger=gubernator.unit" in out
        assert "msg=hello world" in out
        assert logger.level == logging.DEBUG
    finally:
        logger.handlers.clear()
