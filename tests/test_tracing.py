"""End-to-end request tracing + flight recorder (tracing.py).

Covers the PR's acceptance legs:

* one trace id across the columnar peer hop — ingress, batch-window,
  all five pipeline-stage spans and the peer RPC span surface in
  /debug/traces, queried over both daemons' gateways;
* GUBER_TRACE_SAMPLE=0 wire parity — frame bytes and proto-columns
  bytes are identical to the pre-trace encodings in both directions,
  and peers ignore/renegotiate the trace column cleanly;
* the flight recorder's ring ordering, event auto-dump triggers, and
  the no-op fast path;
* satellites: trace ids on structured log records, the build-info
  gauge + /healthz version, and the concurrent-scrape guarantee for
  take_pipeline_stats-backed gauges.
"""

import http.client
import io
import json
import logging
import struct
import threading

import numpy as np
import pytest

from gubernator_tpu import tracing, wire
from gubernator_tpu import __version__
from gubernator_tpu.cluster import fast_test_behaviors
from gubernator_tpu.config import DaemonConfig
from gubernator_tpu.daemon import Daemon
from gubernator_tpu.metrics import Metrics
from gubernator_tpu.peer_client import PeerClient, PeerError
from gubernator_tpu.proto import peers_columns_pb2 as pc_pb
from gubernator_tpu.types import PeerInfo, SECOND
from gubernator_tpu.utils.clock import Clock
from gubernator_tpu.utils.logging import category_logger, setup_logging

T0 = 1_573_430_430_000


@pytest.fixture
def sampled():
    """Tracing at sample rate 1.0 with clean rings; always restored."""
    tracing.reset()
    prev = tracing.sample_rate()
    tracing.set_sample_rate(1.0)
    yield
    tracing.set_sample_rate(prev)
    tracing.reset()


# ----------------------------------------------------------------------
# W3C traceparent + span primitives
# ----------------------------------------------------------------------
def test_traceparent_round_trip():
    ctx = tracing.SpanContext(0xABCDEF, 0x1234)
    tp = tracing.format_traceparent(ctx)
    assert tp == f"00-{0xABCDEF:032x}-{0x1234:016x}-01"
    assert tracing.parse_traceparent(tp) == (0xABCDEF, 0x1234, True)
    # sampled flag clear
    assert tracing.parse_traceparent(tp[:-2] + "00")[2] is False


@pytest.mark.parametrize(
    "bad",
    ["", "garbage", "00-zz-1-01", "00-" + "0" * 32 + "-" + "0" * 16 + "-01",
     "ff-" + "a" * 32 + "-" + "b" * 16 + "-01", "00-abc-def-01"],
)
def test_traceparent_malformed(bad):
    assert tracing.parse_traceparent(bad) is None


def test_disabled_is_noop_singleton():
    tracing.reset()
    prev = tracing.sample_rate()
    tracing.set_sample_rate(0.0)
    try:
        a = tracing.ingress_span("http", "/x")
        b = tracing.ingress_span("grpc", "/y")
        assert a is b and not a  # shared no-op, falsy
        with a:
            assert tracing.current() is None
        assert tracing.spans_snapshot() == []
        assert tracing.new_batch([tracing.SpanContext(1, 2)]) is None
    finally:
        tracing.set_sample_rate(prev)


def test_sampled_span_links_and_filter(sampled):
    with tracing.ingress_span("http", "/v1/GetRateLimits") as sp:
        lane_ctx = tracing.current()
        assert lane_ctx is sp.ctx
    bt = tracing.new_batch([lane_ctx])
    tracing.stage_span("prepare", 0.001, bt, lanes=4)
    spans = tracing.spans_snapshot(lane_ctx.trace_hex)
    names = {s["name"] for s in spans}
    # dispatch.prepare matches via its LINK, not its own trace id
    assert names == {"ingress.http", "dispatch.prepare"}
    prep = next(s for s in spans if s["name"] == "dispatch.prepare")
    assert prep["trace_id"] == bt.ctx.trace_hex != lane_ctx.trace_hex
    assert prep["links"][0]["trace_id"] == lane_ctx.trace_hex
    assert prep["attrs"]["lanes"] == 4


def test_local_rate_decides_not_the_upstream_flag(sampled):
    """The traceparent contributes ids; its sampled flag neither
    forces nor suppresses — untrusted callers must not control the
    sampling rate in either direction."""
    # flag 00 at local rate 1.0: still traced, trace id adopted
    tp = f"00-{'a' * 32}-{'b' * 16}-00"
    sp = tracing.ingress_span("http", "/x", tp)
    assert sp and sp.ctx.trace_hex == "a" * 32
    # flag 01 at local rate 0: stays dark — no forced sampling
    tracing.set_sample_rate(0.0)
    assert not tracing.ingress_span("http", "/x", tp[:-2] + "01")


def test_ring_wraps_in_order(sampled):
    ring = tracing._Ring(8)
    for i in range(20):
        ring.record({"i": i})
    got = [r["i"] for r in ring.snapshot()]
    assert got == list(range(12, 20))


def test_event_auto_dump_and_snapshot(sampled):
    tracing.record_event("shed", lanes=5, queued=10, cap=8)
    evs = tracing.events_snapshot()
    assert evs and evs[-1]["kind"] == "shed" and evs[-1]["lanes"] == 5


# ----------------------------------------------------------------------
# Wire parity: GUBER_TRACE_SAMPLE=0 is byte-identical, trace column
# decodes, classic peers ignore it
# ----------------------------------------------------------------------
def _cols(n=1):
    return (
        [f"n{i}" for i in range(n)],
        [f"k{i}" for i in range(n)],
        np.zeros(n, np.int32),
        np.zeros(n, np.int32),
        np.ones(n, np.int64),
        np.full(n, 10, np.int64),
        np.full(n, 9 * SECOND, np.int64),
    )


def test_frame_trace_trailer_golden():
    cols = _cols(1)
    plain = wire.encode_columns_frame(cols)
    traced = wire.encode_columns_frame(cols, trace=[(0, 1, 0xAB, 0xCD)])
    # sample-0 parity: no trace -> exact pre-trace bytes
    assert wire.encode_columns_frame(cols, trace=None) == plain
    assert wire.encode_columns_frame(cols, trace=[]) == plain
    # the trailer is strictly appended, pinned byte-for-byte
    expected_trailer = (
        b"GTRC"
        + (1).to_bytes(4, "little")
        + (0).to_bytes(4, "little") + (1).to_bytes(4, "little")
        + (0xAB).to_bytes(16, "big")
        + (0xCD).to_bytes(8, "big")
    )
    assert traced == plain + expected_trailer
    got = wire.decode_columns_frame(traced)
    assert got.trace_ctx == [(0, 1, 0xAB, 0xCD)]
    assert wire.decode_columns_frame(plain).trace_ctx is None


def test_frame_garbage_trailer_still_rejected():
    frame = wire.encode_columns_frame(_cols(1))
    with pytest.raises(ValueError):
        wire.decode_columns_frame(frame + b"XXXXYYYY")
    with pytest.raises(ValueError):  # truncated trace trailer
        wire.decode_columns_frame(
            frame + b"GTRC" + (4).to_bytes(4, "little") + b"\0" * 8
        )


def test_proto_columns_trace_parity_and_ignore():
    cols = _cols(2)
    plain = wire.peer_columns_req_to_pb(cols).SerializeToString()
    assert wire.peer_columns_req_to_pb(cols, trace=[]).SerializeToString() == plain
    traced = wire.peer_columns_req_to_pb(
        cols, trace=[(0, 2, 0xAB, 0xCD)]
    ).SerializeToString()
    assert traced != plain and traced.startswith(plain)
    ic = wire.ingress_from_peer_columns_pb(pc_pb.PeerColumnsReq.FromString(traced))
    assert ic.trace_ctx == [(0, 2, 0xAB, 0xCD)]
    # proto3 unknown-field tolerance — the mechanism that lets a
    # pre-trace peer skip field 8 also skips this crafted field 15:
    unknown = plain + b"\x7a\x04abcd"
    m = pc_pb.PeerColumnsReq.FromString(unknown)
    assert list(m.names) == ["n0", "n1"]


def test_http_frame_trace_negotiation_downgrade(sampled):
    """A columns peer that predates the trailer answers 400 'length
    mismatch'; the sender must resend the SAME frame without the
    trailer (no classic downgrade, no double-send of applied work)."""
    client = PeerClient(
        PeerInfo(grpc_address="127.0.0.1:1", http_address="127.0.0.1:1"),
        fast_test_behaviors(), transport="http",
    )
    calls = []

    def fake_roundtrip(path, data, timeout_s, content_type):
        calls.append(bytes(data))
        if wire.decode_columns_frame(data).trace_ctx is not None:
            raise PeerError(
                "peer returned HTTP 400: invalid columns frame: "
                "columns frame length mismatch",
                http_status=400,
            )
        n = len(wire.decode_columns_frame(data).names)
        from gubernator_tpu.service import ColumnarResult

        return wire.encode_result_frame(ColumnarResult.empty(n))

    client._http_roundtrip = fake_roundtrip
    rc = client._post_columns_inner(
        _cols(2), 1.0, trace=[(0, 2, 0xAB, 0xCD)]
    )
    assert rc.n == 2
    assert len(calls) == 2  # probe with trailer, resend without
    assert client._trace_frames is False
    assert client._columnar is not False  # still columnar, NOT classic
    # subsequent sends skip the trailer immediately
    rc = client._post_columns_inner(_cols(1), 1.0, trace=[(0, 1, 1, 2)])
    assert rc.n == 1 and len(calls) == 3
    client.shutdown(timeout_s=0.1)


# ----------------------------------------------------------------------
# Satellites: logging join, build info, scrape race
# ----------------------------------------------------------------------
def test_log_records_carry_trace_ids(sampled):
    buf = io.StringIO()
    logger = setup_logging(debug=True, stream=buf)
    try:
        with tracing.ingress_span("http", "/x") as sp:
            category_logger("unit").info("traced line")
        category_logger("unit").info("dark line")
        lines = buf.getvalue().splitlines()
        assert f"trace_id={sp.ctx.trace_hex}" in lines[0]
        assert f"span_id={sp.ctx.span_hex}" in lines[0]
        assert "trace_id=-" in lines[1] and "span_id=-" in lines[1]
    finally:
        logger.handlers.clear()


def test_build_info_gauge_labels():
    class _Store:
        def describe_topology(self):
            return "cpu", "8"

    m = Metrics()
    m.set_build_info(_Store())
    text = m.render().decode()
    assert (
        f'gubernator_build_info{{backend="cpu",mesh="8",version="{__version__}"}} 1.0'
        in text
    )


def test_concurrent_scrape_never_drops_stage_samples():
    """Two racing scrapers vs take_pipeline_stats: every observed stage
    sample must be rendered by EXACTLY one scrape (under the scrape
    lock the drain+clear+set+render sequence is atomic; without it one
    scraper's clear() could erase the other's just-drained sample
    before it rendered)."""

    class _Store:
        def __init__(self):
            self._lock = threading.Lock()
            self._count = 0

        def observe(self, k):
            with self._lock:
                self._count += k

        def take_pipeline_stats(self):
            with self._lock:
                count, self._count = self._count, 0
            return ({"prepare": (count, 0.0, 0.0)} if count else {}), 0, 0

    store = _Store()
    m = Metrics()

    def parse_count(text: str) -> float:
        for line in text.splitlines():
            if line.startswith(
                'gubernator_dispatch_stage_seconds{stage="prepare",stat="count"}'
            ):
                return float(line.rsplit(" ", 1)[1])
        return 0.0

    total_observed = 0
    harvested = []
    barrier = threading.Barrier(2)

    def scraper():
        barrier.wait()
        with m.scrape_lock:
            m.observe_dispatch(store)
            harvested.append(parse_count(m.render().decode()))

    for round_no in range(50):
        store.observe(7)
        total_observed += 7
        harvested.clear()
        ts = [threading.Thread(target=scraper) for _ in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        # One scraper drained the 7, the other saw an empty delta —
        # never both zero (a dropped sample), never both 7 (a double).
        assert sorted(harvested) == [0.0, 7.0], (round_no, harvested)


def test_debug_routing_and_profile_gate():
    from gubernator_tpu import gateway

    tracing.reset()
    prev = tracing.sample_rate()
    tracing.set_sample_rate(0.0)
    try:
        # typo'd debug paths must 404, not serve plausible data
        status, _, _ = gateway.handle_request(None, "GET", "/debug/tracesfoo", b"")
        assert status == 404
        status, _, _ = gateway.handle_request(None, "GET", "/debug/traces", b"")
        assert status == 200
        # profiling is gated on tracing being enabled
        status, _, body = gateway.handle_request(None, "POST", "/debug/profile", b"{}")
        assert status == 403, body
        tracing.set_sample_rate(1.0)
        # malformed bodies are the caller's fault: 400, not 500
        status, _, _ = gateway.handle_request(
            None, "POST", "/debug/profile", b"[1, 2]"
        )
        assert status == 400
        status, _, _ = gateway.handle_request(
            None, "POST", "/debug/profile", b'{"durationMs": "zzz"}'
        )
        assert status == 400
    finally:
        tracing.set_sample_rate(prev)


def test_profile_concurrent_run_guard():
    """POST /debug/profile: the response names the run (runId) and its
    artifact path (logDir); a SECOND request while one runs answers 409
    carrying the in-flight run's id + path, so racing operators
    converge on the same artifact instead of just being refused."""
    import json as _json

    from gubernator_tpu import gateway

    prev = tracing.sample_rate()
    tracing.set_sample_rate(1.0)
    try:
        status, _, body = gateway.handle_request(
            None, "POST", "/debug/profile", b'{"durationMs": 1500}'
        )
        assert status == 202, body
        doc = _json.loads(body)
        assert doc["runId"] and doc["logDir"]
        status2, _, body2 = gateway.handle_request(
            None, "POST", "/debug/profile", b'{"durationMs": 10}'
        )
        assert status2 == 409, body2
        doc2 = _json.loads(body2)
        assert doc2["runId"] == doc["runId"]
        assert doc2["logDir"] == doc["logDir"]
        # Let the in-flight run drain so later tests see an idle slot.
        t = gateway._profile_state["thread"]
        if t is not None:
            t.join(timeout=60)
    finally:
        tracing.set_sample_rate(prev)


def test_trace_sample_env_validation():
    from gubernator_tpu.config import setup_daemon_config

    conf = setup_daemon_config(env={"GUBER_TRACE_SAMPLE": "0.25"})
    assert conf.behaviors.trace_sample == 0.25
    for bad in ("5", "-1", "abc"):
        with pytest.raises(ValueError):
            setup_daemon_config(env={"GUBER_TRACE_SAMPLE": bad})


def test_shed_records_flight_event(sampled):
    from gubernator_tpu.service import IngressShedError, _IngressGate

    gate = _IngressGate(cap=4, metrics=None)
    gate.admit(3)
    with pytest.raises(IngressShedError):
        gate.admit(2)
    assert any(e["kind"] == "shed" for e in tracing.events_snapshot())


# ----------------------------------------------------------------------
# Integration: one trace across two daemons over the columnar peer hop
# ----------------------------------------------------------------------
@pytest.fixture(scope="module")
def traced_pair():
    tracing.reset()
    prev = tracing.sample_rate()
    tracing.set_sample_rate(1.0)
    clock = Clock()
    clock.freeze(T0)
    daemons = []
    for _ in range(2):
        behaviors = fast_test_behaviors()
        behaviors.global_sync_wait_s = 3600.0
        behaviors.multi_region_sync_wait_s = 3600.0
        behaviors.trace_sample = 1.0
        d = Daemon(
            DaemonConfig(
                listen_address="127.0.0.1:0",
                grpc_listen_address="127.0.0.1:0",
                cache_size=4096,
                global_cache_size=256,
                behaviors=behaviors,
                peer_discovery_type="static",
            ),
            clock=clock,
        ).start()
        daemons.append(d)
    peers = [d.peer_info for d in daemons]
    for d in daemons:
        d.set_peers(peers)
    yield daemons, clock
    tracing.set_sample_rate(prev)
    tracing.reset()
    for d in daemons:
        d.close()


def _http_get(address: str, path: str) -> dict:
    host, _, port = address.partition(":")
    conn = http.client.HTTPConnection(host, int(port), timeout=10)
    try:
        conn.request("GET", path)
        r = conn.getresponse()
        return json.loads(r.read())
    finally:
        conn.close()


def test_one_trace_spans_both_daemons(traced_pair):
    daemons, _clock = traced_pair
    entry = daemons[0]
    # Keys this daemon does NOT own: the whole batch must cross the
    # columnar peer hop to daemons[1].
    keys, i = [], 0
    while len(keys) < 4:
        k = f"trace{i}"
        if not entry.service.get_peer(f"tt_{k}").info.is_owner:
            keys.append(k)
        i += 1
    trace_id = "ab" * 16
    traceparent = f"00-{trace_id}-{'12' * 8}-01"
    body = json.dumps(
        {
            "requests": [
                {"name": "tt", "uniqueKey": k, "hits": "1", "limit": "100",
                 "duration": str(9 * SECOND)}
                for k in keys
            ]
        }
    )
    host, _, port = entry.gateway.address.partition(":")
    conn = http.client.HTTPConnection(host, int(port), timeout=30)
    try:
        conn.request(
            "POST", "/v1/GetRateLimits", body=body,
            headers={"Content-Type": "application/json",
                     "traceparent": traceparent},
        )
        r = conn.getresponse()
        payload = json.loads(r.read())
        # the ingress emits the continued trace back to the caller
        assert trace_id in (r.getheader("traceparent") or "")
    finally:
        conn.close()
    assert len(payload["responses"]) == 4
    assert all(resp.get("status", "UNDER_LIMIT") == "UNDER_LIMIT"
               for resp in payload["responses"])

    # ONE trace id, visible via /debug/traces on BOTH daemons: the
    # entry's ingress + peer RPC spans, the owner's batch window and
    # all five pipeline-stage spans (linked, not nested).
    for d in daemons:
        spans = _http_get(
            d.gateway.address, f"/debug/traces?trace_id={trace_id}"
        )["spans"]
        names = {s["name"] for s in spans}
        assert {
            "ingress.http", "peer.rpc", "batch.window",
            "dispatch.prepare", "dispatch.stage", "dispatch.launch",
            "dispatch.fetch", "dispatch.commit",
        } <= names, names
    # span-link rule: the stage spans LINK the ingress trace
    prep = next(s for s in spans if s["name"] == "dispatch.prepare")
    assert prep["trace_id"] != trace_id
    assert any(l["trace_id"] == trace_id for l in prep["links"])
    # /debug/events answers (empty or not — the endpoint must exist)
    assert "events" in _http_get(daemons[0].gateway.address, "/debug/events")


def test_healthz_version_and_build_info(traced_pair):
    daemons, _ = traced_pair
    hc = _http_get(daemons[0].gateway.address, "/healthz")
    assert hc["version"] == __version__
    host, _, port = daemons[0].gateway.address.partition(":")
    conn = http.client.HTTPConnection(host, int(port), timeout=10)
    try:
        conn.request("GET", "/metrics")
        text = conn.getresponse().read().decode()
    finally:
        conn.close()
    assert "gubernator_build_info{" in text
    assert f'version="{__version__}"' in text
    assert "gubernator_request_duration_seconds_bucket" in text


def test_trace_sample_zero_keeps_wire_dark(traced_pair):
    """With sampling forced off, the same forwarded request must emit
    no spans and carry no trace bytes (the wire-parity contract)."""
    daemons, _ = traced_pair
    entry = daemons[0]
    tracing.set_sample_rate(0.0)
    try:
        tracing.reset()
        k, i = None, 0
        while k is None:
            cand = f"dark{i}"
            if not entry.service.get_peer(f"tt_{cand}").info.is_owner:
                k = cand
            i += 1
        body = json.dumps(
            {"requests": [
                {"name": "tt", "uniqueKey": k, "hits": "1", "limit": "100",
                 "duration": str(9 * SECOND)},
                {"name": "tt", "uniqueKey": k + "b", "hits": "1",
                 "limit": "100", "duration": str(9 * SECOND)},
            ]}
        )
        host, _, port = entry.gateway.address.partition(":")
        conn = http.client.HTTPConnection(host, int(port), timeout=30)
        try:
            conn.request("POST", "/v1/GetRateLimits", body=body,
                         headers={"Content-Type": "application/json"})
            r = conn.getresponse()
            r.read()
            assert r.getheader("traceparent") is None
        finally:
            conn.close()
        assert tracing.spans_snapshot() == []
    finally:
        tracing.set_sample_rate(1.0)
