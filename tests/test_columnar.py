"""Columnar ingress path: the zero-dataclass hot path must be
semantically identical to the dataclass router (gubernator.go:116-227
behavior), lane for lane, for every routing class — plain local lanes,
validation errors, GLOBAL lanes, and remotely-owned forwards."""

import json
import threading
import urllib.request

import numpy as np
import pytest

from gubernator_tpu.parallel.mesh import MeshBucketStore
from gubernator_tpu.service import IngressColumns, ServiceConfig, V1Service
from gubernator_tpu.types import (
    Algorithm,
    Behavior,
    GetRateLimitsRequest,
    RateLimitRequest,
    Status,
)
from gubernator_tpu.utils.clock import Clock

NOW = 1_573_430_400_000


def make_cols(n, name="col", prefix="k", hits=1, limit=10, duration=60_000,
              behavior=0, algorithm=0):
    return IngressColumns(
        names=[name] * n,
        unique_keys=[f"{prefix}{i}" for i in range(n)],
        algorithm=np.full(n, algorithm, np.int32),
        behavior=np.full(n, behavior, np.int32),
        hits=np.full(n, hits, np.int64),
        limit=np.full(n, limit, np.int64),
        duration=np.full(n, duration, np.int64),
    )


@pytest.fixture
def service():
    clock = Clock()
    clock.freeze(NOW)
    svc = V1Service(ServiceConfig(cache_size=4096, clock=clock,
                                  advertise_address="127.0.0.1:9999"))
    from gubernator_tpu.types import PeerInfo

    svc.set_peers([PeerInfo(grpc_address="127.0.0.1:9999", is_owner=True)])
    yield svc
    svc.close()


def test_columnar_matches_dataclass_path(service):
    cols = make_cols(64, hits=3, limit=10)
    reqs = [cols.request_at(i) for i in range(64)]

    r1 = service.get_rate_limits_columns(cols)
    r2 = service.get_rate_limits(GetRateLimitsRequest(requests=reqs))

    # Same frozen now: second call sees state the first left behind.
    for i in range(64):
        a = r1.response_at(i)
        b = r2.responses[i]
        assert a.status == Status.UNDER_LIMIT
        assert b.status == Status.UNDER_LIMIT
        assert a.remaining == 7 and b.remaining == 4
        assert a.reset_time == b.reset_time == NOW + 60_000


def test_columnar_validation_errors(service):
    cols = make_cols(4)
    cols.unique_keys[1] = ""
    cols.names[2] = ""
    r = service.get_rate_limits_columns(cols)
    assert r.response_at(0).status == Status.UNDER_LIMIT
    assert r.response_at(1).error == "field 'unique_key' cannot be empty"
    assert r.response_at(2).error == "field 'namespace' cannot be empty"
    assert r.response_at(3).remaining == 9


def test_columnar_batch_cap(service):
    from gubernator_tpu.service import ApiError

    with pytest.raises(ApiError):
        service.get_rate_limits_columns(make_cols(1001))


def test_columnar_global_lanes_mixed(service):
    """GLOBAL lanes take the replica/dataclass path while plain lanes
    stay columnar — both classes must answer in one call."""
    n = 8
    cols = make_cols(n, prefix="mix")
    beh = cols.behavior.copy()
    beh[::2] = int(Behavior.GLOBAL)
    cols.behavior = beh
    r = service.get_rate_limits_columns(cols)
    for i in range(n):
        resp = r.response_at(i)
        assert resp.error == ""
        assert resp.status == Status.UNDER_LIMIT
        assert resp.remaining == 9


def test_columnar_multi_region_queues_aggregated_hits(service):
    """MULTI_REGION lanes stay columnar when locally owned; the region
    queue receives per-key aggregated hits (multiregion.go:37-47)."""
    n = 6
    cols = IngressColumns(
        names=["mr"] * n,
        unique_keys=["a", "a", "a", "b", "b", "c"],
        algorithm=np.zeros(n, np.int32),
        behavior=np.full(n, int(Behavior.MULTI_REGION), np.int32),
        hits=np.ones(n, np.int64),
        limit=np.full(n, 10, np.int64),
        duration=np.full(n, 60_000, np.int64),
    )
    r = service.get_rate_limits_columns(cols)
    assert [r.response_at(i).remaining for i in range(n)] == [9, 8, 7, 9, 8, 9]
    with service.multi_region_mgr._lock:
        queued = dict(service.multi_region_mgr._hits)
    assert queued["mr_a"].hits == 3
    assert queued["mr_b"].hits == 2
    assert queued["mr_c"].hits == 1


def test_columnar_reset_remaining_and_leaky(service):
    n = 6
    cols = make_cols(n, prefix="rr", hits=4, limit=4,
                     algorithm=int(Algorithm.LEAKY_BUCKET))
    r1 = service.get_rate_limits_columns(cols)
    assert all(r1.response_at(i).remaining == 0 for i in range(n))
    r2 = service.get_rate_limits_columns(cols)
    assert all(r2.response_at(i).status == Status.OVER_LIMIT for i in range(n))


def test_columnar_gregorian_error_lane(service):
    cols = make_cols(3, prefix="greg")
    beh = cols.behavior.copy()
    beh[1] = int(Behavior.DURATION_IS_GREGORIAN)
    cols.behavior = beh
    dur = cols.duration.copy()
    dur[1] = 99  # not a valid Gregorian interval
    cols.duration = dur
    r = service.get_rate_limits_columns(cols)
    assert r.response_at(0).error == ""
    assert "gregorian" in r.response_at(1).error.lower() or r.response_at(1).error
    assert r.response_at(2).error == ""


def test_columnar_duplicate_keys(service):
    """Duplicate keys in one columnar batch serialize like the mutex
    would (gubernator.go:336-337): k occurrences each subtract."""
    n = 10
    cols = IngressColumns(
        names=["dup"] * n,
        unique_keys=["same"] * n,
        algorithm=np.zeros(n, np.int32),
        behavior=np.zeros(n, np.int32),
        hits=np.ones(n, np.int64),
        limit=np.full(n, 6, np.int64),
        duration=np.full(n, 60_000, np.int64),
    )
    r = service.get_rate_limits_columns(cols)
    statuses = [r.response_at(i).status for i in range(n)]
    assert statuses.count(Status.UNDER_LIMIT) == 6
    assert statuses.count(Status.OVER_LIMIT) == 4


def test_columnar_concurrent_pipelining(service):
    """Concurrent columnar callers must pipeline without corrupting
    state: total accepted across threads == limit exactly."""
    n_threads, per_batch = 8, 4
    limit = n_threads * per_batch // 2
    results = []
    lock = threading.Lock()

    def worker(t):
        cols = IngressColumns(
            names=["conc"] * per_batch,
            unique_keys=["shared"] * per_batch,
            algorithm=np.zeros(per_batch, np.int32),
            behavior=np.zeros(per_batch, np.int32),
            hits=np.ones(per_batch, np.int64),
            limit=np.full(per_batch, limit, np.int64),
            duration=np.full(per_batch, 60_000, np.int64),
        )
        r = service.get_rate_limits_columns(cols)
        with lock:
            results.extend(r.response_at(i).status for i in range(per_batch))

    threads = [threading.Thread(target=worker, args=(t,)) for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert results.count(Status.UNDER_LIMIT) == limit
    assert results.count(Status.OVER_LIMIT) == limit


def test_gateway_columnar_roundtrip():
    """Multi-item JSON requests flow through parse_columns /
    render_columns and must match the reference JSON shape."""
    from gubernator_tpu.daemon import Daemon, DaemonConfig

    d = Daemon(DaemonConfig(listen_address="127.0.0.1:0",
                            grpc_listen_address="127.0.0.1:0"))
    d.start()
    try:
        body = {
            "requests": [
                {"name": "gw", "uniqueKey": f"k{i}", "hits": "1",
                 "limit": "5", "duration": "60000"}
                for i in range(3)
            ]
            + [{"name": "gw", "uniqueKey": ""}]
        }
        req = urllib.request.Request(
            f"http://{d.gateway.address}/v1/GetRateLimits",
            data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=10) as resp:
            payload = json.loads(resp.read())
        rs = payload["responses"]
        assert len(rs) == 4
        # Exact reference JSON shape (grpc-gateway camelCase, stringified
        # int64s) — pin every field.
        assert set(rs[0]) == {"status", "limit", "remaining", "resetTime"}
        assert rs[0]["status"] == "UNDER_LIMIT"
        assert rs[0]["limit"] == "5"
        assert rs[0]["remaining"] == "4"
        assert int(rs[0]["resetTime"]) > 0
        assert rs[3]["error"] == "field 'unique_key' cannot be empty"
    finally:
        d.close()


def test_columnar_fallback_without_native():
    """A store without columnar support routes the whole batch through
    the dataclass path transparently."""
    clock = Clock()
    clock.freeze(NOW)
    store = MeshBucketStore(capacity_per_shard=256, use_native=False)
    svc = V1Service(ServiceConfig(store=store, clock=clock,
                                  advertise_address="127.0.0.1:9998"))
    from gubernator_tpu.types import PeerInfo

    svc.set_peers([PeerInfo(grpc_address="127.0.0.1:9998", is_owner=True)])
    try:
        assert not store.supports_columns
        r = svc.get_rate_limits_columns(make_cols(5, prefix="nofast"))
        for i in range(5):
            assert r.response_at(i).remaining == 9
    finally:
        svc.close()


def test_wide_gregorian_stays_on_dict_wire_and_matches_wide():
    """Yearly Gregorian expiries exceed the narrow wire's i32 deltas;
    the dict wire must still carry them (int64 table rows + wide-output
    kernel) and produce results identical to the forced per-lane wide
    wire (interval.go:82-146 is first-class in the reference)."""
    import numpy as np

    from gubernator_tpu.models.shard import GregResolver, ShardStore
    from gubernator_tpu.types import Behavior
    from gubernator_tpu.utils import gregorian

    NOW = 1_700_000_000_000
    n = 96
    greg = GregResolver(NOW)
    ge_y, gd_y = greg.resolve(gregorian.GREGORIAN_YEARS)
    ge_d, gd_d = greg.resolve(gregorian.GREGORIAN_DAYS)
    yearly = (np.arange(n) % 2).astype(bool)
    kw = dict(
        algorithm=(np.arange(n) % 2).astype(np.int32),
        behavior=np.full(n, int(Behavior.DURATION_IS_GREGORIAN), np.int32),
        hits=np.ones(n, np.int64),
        limit=np.full(n, 1000, np.int64),
        duration=np.where(
            yearly, gregorian.GREGORIAN_YEARS, gregorian.GREGORIAN_DAYS
        ).astype(np.int64),
        greg_expire=np.where(yearly, ge_y, ge_d).astype(np.int64),
        greg_duration=np.where(yearly, gd_y, gd_d).astype(np.int64),
    )
    keys = [f"wg:{k % 24}" for k in range(n)]  # duplicates too

    # Guard against a vacuous pass: this batch must be dict-encodable
    # (otherwise both stores would silently take the same wide per-lane
    # wire and the comparison proves nothing).
    from gubernator_tpu.models.shard import make_columns
    from gubernator_tpu.ops import buckets

    cols = make_columns(
        kw["algorithm"], kw["behavior"], kw["hits"], kw["limit"],
        kw["duration"], n, kw["greg_expire"], kw["greg_duration"],
    )
    assert buckets.build_config_dict(cols, NOW) is not None

    a = ShardStore(capacity=256)
    b = ShardStore(capacity=256)
    for step in range(3):
        ra = a.apply_columns(keys, now_ms=NOW + step, **kw)
        rb = b.apply_columns(keys, now_ms=NOW + step, force_wire="wide", **kw)
        for f in ("status", "remaining", "reset_time", "limit"):
            np.testing.assert_array_equal(ra[f], rb[f], err_msg=f"{f} step {step}")
    # yearly lanes really do exceed the narrow delta (the point of the test)
    assert int((kw["greg_expire"] - NOW).max()) > (1 << 31) - 1


def test_compact_commit_matches_rounds_kernel():
    """apply_compact32 (single-round compacted scatter) must be
    byte-identical to apply_rounds32 for the same grouped plan —
    responses AND resulting state (round 4: the per-lane scatter prices
    every submitted row, so the production dispatch compacts)."""
    import jax.numpy as jnp

    from gubernator_tpu.ops import buckets

    rng = np.random.RandomState(9)
    C, B = 512, 256
    ids = rng.randint(0, 96, size=B)  # heavy duplicates
    # a grouped single-round plan shape: occ within groups, last writes
    order = np.argsort(ids, kind="stable")
    occ = np.zeros(B, np.int32)
    write = np.zeros(B, bool)
    slot_of = {k: i for i, k in enumerate(np.unique(ids))}
    slots = np.array([slot_of[k] for k in ids], np.int32)
    seen = {}
    for i in range(B):
        seen[ids[i]] = seen.get(ids[i], -1) + 1
        occ[i] = seen[ids[i]]
    last = {}
    for i in range(B):
        last[ids[i]] = i
    for i in last.values():
        write[i] = True

    def mk(exists):
        return buckets.make_batch32(
            slots, np.full(B, exists, bool), (ids % 2).astype(np.int32),
            np.zeros(B, np.int32), np.ones(B, np.int32),
            np.full(B, 1000, np.int32), np.full(B, 60_000, np.int32),
            occ=occ, write=write,
        )

    now = 1_700_000_000_000
    rid = jnp.zeros(B, jnp.int32)
    one = jnp.asarray(1, jnp.int32)

    sa = buckets.init_state(C)
    sa, pa = buckets.apply_rounds32(sa, mk(False), rid, one, now)

    wl = np.nonzero(write)[0].astype(np.int32)
    wlane = np.full(128, -1, np.int32)
    wlane[: len(wl)] = wl
    sb = buckets.init_state(C)
    sb, pb = buckets.apply_compact32(sb, mk(False), jnp.asarray(wlane), now)

    assert np.array_equal(np.asarray(pa), np.asarray(pb))
    assert np.array_equal(np.asarray(sa.hot), np.asarray(sb.hot))
    assert np.array_equal(np.asarray(sa.cold), np.asarray(sb.cold))

    # steady-state second batch too (exists=True, no cold rewrite)
    sa2, pa2 = buckets.apply_rounds32(sa, mk(True), rid, one, now + 500)
    sb2, pb2 = buckets.apply_compact32(sb, mk(True), jnp.asarray(wlane), now + 500)
    assert np.array_equal(np.asarray(pa2), np.asarray(pb2))
    assert np.array_equal(np.asarray(sa2.hot), np.asarray(sb2.hot))
    assert np.array_equal(np.asarray(sa2.cold), np.asarray(sb2.cold))
