"""Async columnar entry points (get_rate_limits_columns_async and the
PeersV1 twin): the callback-driven completion path the native epoll
edge uses must produce lane-for-lane the same responses as the
blocking entry — both share _submit_columns, so these tests pin the
completion machinery (_ColumnsJoin, _HandleDrainer): exactly-once
delivery, error conversion, shutdown behavior, and the no-blocked-
worker property (in-flight requests > worker threads)."""

import json
import threading
import time
import urllib.request

import numpy as np
import pytest

from gubernator_tpu.service import (
    ApiError,
    IngressColumns,
    ServiceConfig,
    V1Service,
)
from gubernator_tpu.types import Behavior, PeerInfo, Status
from gubernator_tpu.utils.clock import Clock

NOW = 1_573_430_400_000


def make_cols(n, name="acol", prefix="k", hits=1, limit=10, duration=60_000,
              behavior=0, algorithm=0):
    return IngressColumns(
        names=[name] * n,
        unique_keys=[f"{prefix}{i}" for i in range(n)],
        algorithm=np.full(n, algorithm, np.int32),
        behavior=np.full(n, behavior, np.int32),
        hits=np.full(n, hits, np.int64),
        limit=np.full(n, limit, np.int64),
        duration=np.full(n, duration, np.int64),
    )


@pytest.fixture
def service():
    clock = Clock()
    clock.freeze(NOW)
    svc = V1Service(ServiceConfig(cache_size=4096, clock=clock,
                                  advertise_address="127.0.0.1:9999"))
    svc.set_peers([PeerInfo(grpc_address="127.0.0.1:9999", is_owner=True)])
    yield svc
    svc.close()


def run_async(fn, cols, timeout=30.0):
    """Drive one async call to completion; asserts exactly-once."""
    done = threading.Event()
    calls = []

    def cb(result, exc):
        calls.append((result, exc))
        done.set()

    fn(cols, cb)
    assert done.wait(timeout), "async callback never fired"
    time.sleep(0.02)  # a double-call would land here
    assert len(calls) == 1, f"callback fired {len(calls)} times"
    return calls[0]


def assert_same_responses(res_a, res_b):
    assert res_a.n == res_b.n
    for i in range(res_a.n):
        a, b = res_a.response_at(i), res_b.response_at(i)
        assert (a.status, a.limit, a.remaining, a.error) == (
            b.status, b.limit, b.remaining, b.error
        ), f"lane {i} diverged"


def test_async_matches_sync(service):
    n = 64
    sync_res = service.get_rate_limits_columns(make_cols(n, hits=3))
    async_res, exc = run_async(
        service.get_rate_limits_columns_async, make_cols(n, hits=3)
    )
    assert exc is None
    # Same frozen clock: the async batch drains 3 more hits per key.
    assert async_res.n == n
    for i in range(n):
        assert async_res.response_at(i).remaining == (
            sync_res.response_at(i).remaining - 3
        )


def test_async_validation_error_lanes(service):
    cols = make_cols(8)
    cols.unique_keys[3] = ""
    cols.names[5] = ""
    res, exc = run_async(service.get_rate_limits_columns_async, cols)
    assert exc is None
    assert "unique_key" in res.response_at(3).error
    assert "namespace" in res.response_at(5).error
    assert res.response_at(0).error == ""
    assert res.response_at(0).status == int(Status.UNDER_LIMIT)


def test_async_over_batch_cap_is_api_error(service):
    cols = make_cols(2)

    class FakeLen:
        def __len__(self):
            return 1001

        def __getattr__(self, k):
            return getattr(cols, k)

    res, exc = run_async(service.get_rate_limits_columns_async, FakeLen())
    assert res is None
    assert isinstance(exc, ApiError)


def test_async_empty_batch(service):
    res, exc = run_async(service.get_rate_limits_columns_async, make_cols(0))
    assert exc is None
    assert res.n == 0


def test_async_single_lane_rides_dataclass_path(service):
    # n == 1 falls back to the (pool-run) dataclass router.
    res, exc = run_async(
        service.get_rate_limits_columns_async,
        make_cols(1, behavior=int(Behavior.NO_BATCHING)),
    )
    assert exc is None
    assert res.response_at(0).status == int(Status.UNDER_LIMIT)
    assert res.response_at(0).limit == 10


def test_async_global_lanes(service):
    # GLOBAL lanes ride the slow (dataclass) resolver inside the async
    # plan — owner-local here, so they answer authoritatively.
    n = 16
    beh = np.zeros(n, np.int32)
    beh[::2] = int(Behavior.GLOBAL)
    cols = make_cols(n)
    cols.behavior = beh
    res, exc = run_async(service.get_rate_limits_columns_async, cols)
    assert exc is None
    for i in range(n):
        assert res.response_at(i).status == int(Status.UNDER_LIMIT)
        assert res.response_at(i).remaining == 9


def test_async_mixed_no_batching(service):
    n = 12
    beh = np.zeros(n, np.int32)
    beh[:4] = int(Behavior.NO_BATCHING)
    cols = make_cols(n)
    cols.behavior = beh
    res, exc = run_async(service.get_rate_limits_columns_async, cols)
    assert exc is None
    for i in range(n):
        assert res.response_at(i).remaining == 9


def test_async_peer_columns_matches_sync(service):
    sync_res = service.get_peer_rate_limits_columns(make_cols(32, hits=2))
    async_res, exc = run_async(
        service.get_peer_rate_limits_columns_async, make_cols(32, hits=2)
    )
    assert exc is None
    for i in range(32):
        assert async_res.response_at(i).remaining == (
            sync_res.response_at(i).remaining - 2
        )


def test_async_many_inflight_few_workers(service):
    """The point of the async path: many concurrent requests in flight
    with NO per-request blocked thread.  120 requests submitted from 2
    threads all complete, and their hits all land."""
    n_reqs, lanes = 120, 8
    done = threading.Event()
    results = []
    lock = threading.Lock()

    def cb(result, exc):
        with lock:
            results.append((result, exc))
            if len(results) == n_reqs:
                done.set()

    def submit(base):
        for r in range(n_reqs // 2):
            cols = make_cols(lanes, prefix="storm", limit=100_000)
            service.get_rate_limits_columns_async(cols, cb)

    t1 = threading.Thread(target=submit, args=(0,))
    t2 = threading.Thread(target=submit, args=(1,))
    t1.start(); t2.start(); t1.join(); t2.join()
    assert done.wait(60), f"only {len(results)}/{n_reqs} completed"
    assert all(exc is None for _, exc in results)
    # Every request drained `lanes` hits off the same keys: the final
    # remaining must reflect all of them (no lost or double applies).
    final, exc = run_async(
        service.get_rate_limits_columns_async,
        make_cols(lanes, prefix="storm", limit=100_000),
    )
    assert exc is None
    assert final.response_at(0).remaining == 100_000 - (n_reqs + 1)


def test_async_single_lane_saturation_makes_progress(service):
    """More concurrent single-lane async requests than the slow pool
    has threads: they must all complete (queueing, not deadlock).  The
    round-5 review found the original fallback shared _forward_pool
    with _route's inner leaf forwards — 64 outer tasks could fill the
    pool and block forever on inner tasks queued behind them; the
    dedicated _slow_pool keeps outer and inner work on disjoint pools.
    GLOBAL|NO_BATCHING is the one single-key shape that still DECLINES
    the zero-thread fast path (sync parity: it takes store.apply with
    no window), so this pins the slow-pool route specifically."""
    n_reqs = 140  # > _slow_pool max_workers would deadlock the old way
    beh = int(Behavior.GLOBAL) | int(Behavior.NO_BATCHING)
    done = threading.Event()
    results = []
    lock = threading.Lock()

    def cb(result, exc):
        with lock:
            results.append(exc)
            if len(results) == n_reqs:
                done.set()

    for i in range(n_reqs):
        service.get_rate_limits_columns_async(
            make_cols(1, prefix=f"sat{i}", limit=1000, behavior=beh), cb
        )
    assert done.wait(60), f"only {len(results)}/{n_reqs} completed"
    assert all(e is None for e in results)


def test_async_single_lane_fast_path_no_thread_parked(service):
    """Plain single-key async requests on a standalone daemon take the
    zero-extra-thread fast path (_try_single_async): many more
    concurrent requests than ANY pool has threads all complete with
    exact accounting on a shared key."""
    n_reqs = 300
    done = threading.Event()
    results = []
    lock = threading.Lock()

    def cb(result, exc):
        with lock:
            results.append((result, exc))
            if len(results) == n_reqs:
                done.set()

    for i in range(n_reqs):
        service.get_rate_limits_columns_async(
            make_cols(1, prefix="fastone", limit=100_000), cb
        )
    assert done.wait(60), f"only {len(results)}/{n_reqs} completed"
    assert all(exc is None for _, exc in results)
    assert all(r.response_at(0).error == "" for r, _ in results)
    final, exc = run_async(
        service.get_rate_limits_columns_async,
        make_cols(1, prefix="fastone", hits=0, limit=100_000),
    )
    assert exc is None
    assert final.response_at(0).remaining == 100_000 - n_reqs


def test_async_single_lane_global_completes(service):
    """GLOBAL single-key async (owner-local): rides the LocalBatcher
    branch of the fast path — the batcher flush thread completes it."""
    res, exc = run_async(
        service.get_rate_limits_columns_async,
        make_cols(1, prefix="gfast", behavior=int(Behavior.GLOBAL)),
    )
    assert exc is None
    assert res.response_at(0).status == int(Status.UNDER_LIMIT)
    assert res.response_at(0).remaining == 9


def test_async_single_lane_empty_key_validates(service):
    """Empty unique_key declines the fast path; the sync router's exact
    validation wording must come back through the slow pool."""
    cols = make_cols(1, prefix="v")
    cols.unique_keys[0] = ""
    res, exc = run_async(service.get_rate_limits_columns_async, cols)
    assert exc is None
    assert "unique_key" in res.response_at(0).error


def test_async_after_close_reports_error(service):
    service.close()
    res, exc = run_async(service.get_rate_limits_columns_async, make_cols(4))
    # Either shape is acceptable — a hard error or per-lane errors —
    # but it must complete and must not claim success with zeroed lanes.
    if exc is None:
        assert res.response_at(0).error != ""


def test_handle_drainer_contract():
    """_HandleDrainer alone: value delivery, exception conversion,
    stop() draining already-registered work, and fail-fast on late
    registration."""
    from gubernator_tpu.peer_client import PeerError
    from gubernator_tpu.service import _HandleDrainer

    class Handle:
        def __init__(self, value=None, exc=None, delay=0.0):
            self._v, self._e, self._delay = value, exc, delay

        def result(self):
            if self._delay:
                time.sleep(self._delay)
            if self._e is not None:
                raise self._e
            return self._v

    d = _HandleDrainer()
    d.start()
    got = []
    ev = threading.Event()
    d.register(Handle(value={"x": 1}), lambda v, e: (got.append((v, e)), ev.set()))
    assert ev.wait(10) and got == [({"x": 1}, None)]

    got.clear(); ev.clear()
    boom = RuntimeError("boom")
    d.register(Handle(exc=boom), lambda v, e: (got.append((v, e)), ev.set()))
    assert ev.wait(10) and got == [(None, boom)]

    # Work registered BEFORE stop is resolved by the draining workers.
    got.clear()
    slow_done = threading.Event()
    d.register(Handle(value=7, delay=0.2),
               lambda v, e: (got.append((v, e)), slow_done.set()))
    d.stop()
    assert slow_done.wait(10) and got == [(7, None)]

    # Late registration fails fast with the closed error, still exactly
    # once, on the caller thread.
    late = []
    d.register(Handle(value=9), lambda v, e: late.append((v, e)))
    assert len(late) == 1
    v, e = late[0]
    assert v is None and isinstance(e, PeerError)


def test_async_callback_exception_does_not_wedge(service):
    """A raising callback must not kill the drainer pool: subsequent
    requests still complete."""
    fired = threading.Event()

    def bad_cb(result, exc):
        fired.set()
        raise RuntimeError("consumer bug")

    service.get_rate_limits_columns_async(make_cols(4, prefix="bad"), bad_cb)
    assert fired.wait(30)
    res, exc = run_async(
        service.get_rate_limits_columns_async, make_cols(4, prefix="good")
    )
    assert exc is None
    assert res.response_at(0).status == int(Status.UNDER_LIMIT)
