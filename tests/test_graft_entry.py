"""The driver's entry points must stay green.

`dryrun_multichip` must self-provision a virtual CPU mesh when the host
has fewer devices than requested (round-1 verdict: the bench host has one
chip, and the official multi-chip artifact was red because the old code
asserted on device count instead of provisioning).
"""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_dryrun_multichip_in_process():
    # The suite itself runs on a forced 8-device CPU mesh (conftest), so
    # the in-process fast path applies.
    sys.path.insert(0, REPO)
    try:
        import __graft_entry__ as g

        g.dryrun_multichip(4)
    finally:
        sys.path.remove(REPO)


def test_dryrun_multichip_self_provisions_subprocess():
    # A bare child process defaults to 1 device; dryrun_multichip(4) must
    # succeed anyway by re-exec'ing itself with a forced device count.
    # Deliberately do NOT export JAX_PLATFORMS=cpu: the real harness child
    # boots with whatever platform sitecustomize registers and relies on
    # the config.update('jax_platforms', 'cpu') inside the re-exec'd
    # grandchild, so this test must reproduce that condition.
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    child = "import __graft_entry__ as g; g.dryrun_multichip(4)"
    proc = subprocess.run(
        [sys.executable, "-c", child],
        env=env,
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=300,
    )
    assert proc.returncode == 0, proc.stderr
    assert "columnar dict-wire + GLOBAL sync collectives OK" in proc.stdout
