"""Conservation audit (gubernator_tpu/audit.py).

Unit tests of the ledger/invariant math (baseline arming, one-sided
inequalities, violation growth semantics, the GLOBAL-carry slack
bound), no-false-positive runs under eviction pressure / GLOBAL carry
accumulation / a mid-window reshard handoff, and the seeded
double-commit: a FaultPlan DUPLICATE rule on the forward wire must
trip forward_conservation (violation counter + flight-recorder
auto-dump event) while a clean run stays silent.
"""

from __future__ import annotations

import time

import numpy as np
import pytest

from gubernator_tpu import audit, faults, tracing
from gubernator_tpu.cluster import Cluster, fast_test_behaviors
from gubernator_tpu.types import (
    Algorithm,
    Behavior,
    GetRateLimitsRequest,
    RateLimitRequest,
)


@pytest.fixture(autouse=True)
def _clean_rings():
    tracing.reset()
    yield
    tracing.reset()
    faults.uninstall()


# ---------------------------------------------------------------------
# Ledger / invariant math
# ---------------------------------------------------------------------
def test_ledger_notes_and_baseline_arming():
    a = audit.Auditor(enabled=False)
    audit.note("dispatched_hits", 10)
    audit.note("applied_hits", 7)
    d = a.deltas()
    assert d["dispatched_hits"] == 10 and d["applied_hits"] == 7
    a.arm()  # baseline re-captured: deltas zero again
    assert a.deltas()["dispatched_hits"] == 0
    assert a.check_now() == []


def test_applied_exceeding_dispatched_violates():
    a = audit.Auditor(enabled=False)
    a.check_now()  # seed pass (see Auditor.arm)
    audit.note("dispatched_hits", 5)
    audit.note("applied_hits", 9)  # 4 hits granted from nowhere
    found = a.check_now()
    names = [v["invariant"] for v in found]
    assert "device_conservation" in names
    v = next(v for v in found if v["invariant"] == "device_conservation")
    assert v["excess"] == 4
    # Persisting-unchanged violation is not re-counted...
    assert a.check_now() == []
    assert a.violations["device_conservation"] == 1
    # ...but GROWTH is.
    audit.note("applied_hits", 2)
    assert [v["invariant"] for v in a.check_now()] == ["device_conservation"]
    assert a.violations["device_conservation"] == 2


def test_lag_direction_never_violates():
    """Every invariant tolerates the later layer lagging (in-flight
    work): earlier-side excess is NOT a violation."""
    a = audit.Auditor(enabled=False)
    a.check_now()  # seed pass (see Auditor.arm)
    audit.note("dispatched_hits", 100)   # dispatched, not yet applied
    audit.note("forward_admitted_hits", 50)  # admitted, not yet sent
    audit.note("global_agg_hits", 30)    # aggregated, not yet forwarded
    audit.note("reshard_drained_lanes", 9)   # drained, not yet acked
    audit.note("reshard_received_lanes", 4)  # received, commit pending
    assert a.check_now() == []
    assert a.violations == {}


def test_wire_hits_exceeding_admitted_violates():
    a = audit.Auditor(enabled=False)
    a.check_now()  # seed pass (see Auditor.arm)
    audit.note("forward_admitted_hits", 8)
    audit.note("forward_wire_hits", 16)  # the duplicate-delivery shape
    assert [v["invariant"] for v in a.check_now()] == [
        "forward_conservation"
    ]


def test_negative_remaining_violates():
    a = audit.Auditor(enabled=False)
    a.check_now()  # seed pass (see Auditor.arm)
    audit.note("negative_remaining", 1)
    assert [v["invariant"] for v in a.check_now()] == ["negative_remaining"]


def test_global_carry_slack_bound():
    from gubernator_tpu.service import GlobalManager

    a = audit.Auditor(enabled=False)
    a.check_now()  # seed pass (see Auditor.arm)
    audit.set_gauge(audit.GLOBAL_CARRY_GAUGE, GlobalManager.HIT_CARRY_MAX)
    assert a.check_now() == []  # at the cap = within the documented slack
    audit.set_gauge(audit.GLOBAL_CARRY_GAUGE, GlobalManager.HIT_CARRY_MAX + 3)
    found = a.check_now()
    assert [v["invariant"] for v in found] == ["global_slack"]
    assert found[0]["excess"] == 3
    audit.set_gauge(audit.GLOBAL_CARRY_GAUGE, 0)


def test_metrics_counters_and_dump_event():
    from gubernator_tpu.metrics import Metrics

    m = Metrics()
    a = audit.Auditor(metrics=m, enabled=False)
    a.check_now()  # seed pass (see Auditor.arm)
    audit.note("forward_wire_hits", 2)
    a.check_now()
    rendered = m.render().decode()
    assert (
        'gubernator_audit_violations_total{invariant="forward_conservation"}'
        in rendered
    )
    kinds = [e["kind"] for e in tracing.events_snapshot()]
    assert "audit-violation" in kinds  # the flight-recorder dump path


def test_snapshot_shape():
    a = audit.Auditor(enabled=False, interval_s=1.0)
    snap = a.snapshot()
    assert snap["intervalS"] == 1.0
    assert set(audit.INVARIANTS) <= set(snap["invariants"])
    assert "ledger" in snap and "violations" in snap


# ---------------------------------------------------------------------
# No-false-positive runs (the audit must be SILENT on clean traffic)
# ---------------------------------------------------------------------
def _drive(daemon, n_keys: int, hits: int = 1, behavior: int = 0,
           batches: int = 4, tag: str = "a"):
    svc = daemon.service
    for b in range(batches):
        reqs = [
            RateLimitRequest(
                name="audit", unique_key=f"{tag}{b}:{i}", hits=hits,
                limit=50, duration=60_000,
                algorithm=(
                    Algorithm.TOKEN_BUCKET if i % 2 == 0
                    else Algorithm.LEAKY_BUCKET
                ),
                behavior=behavior,
            )
            for i in range(n_keys)
        ]
        svc.get_rate_limits(GetRateLimitsRequest(requests=reqs))


def _assert_clean(*daemons):
    for d in daemons:
        # Two passes: the first may be the auditor's silent seed pass
        # (Auditor.arm) when its background thread hasn't ticked yet —
        # the second is guaranteed to be a counting reconciliation.
        d.service.auditor.check_now()
        found = d.service.auditor.check_now()
        assert found == [], found
        assert d.service.auditor.violations == {}


@pytest.mark.slow
def test_clean_under_eviction_pressure():
    """A tiny table under many distinct keys churns evictions; evicted
    state must not unbalance the hit ledgers."""
    cl = Cluster().start_with([""], behaviors=fast_test_behaviors(),
                              cache_size=256)
    try:
        _drive(cl.daemons[0], n_keys=200, batches=6, tag="ev")
        occ = cl.daemons[0].service.store.occupancy_stats()
        assert sum(r["evictions"] for r in occ) > 0, "no eviction pressure"
        _assert_clean(cl.daemons[0])
    finally:
        cl.stop()


@pytest.mark.chaos
def test_clean_global_carry_accumulation():
    """GLOBAL hits for a partitioned owner requeue into the carry tick
    after tick — accumulation within the documented slack must stay
    silent (sent+dropped <= aggregated, carry <= cap)."""
    cl = Cluster().start(2)
    plan = faults.FaultPlan(seed=3)
    try:
        # Find keys whose GLOBAL owner is daemon 1, driven via daemon 0.
        svc0 = cl.daemons[0].service
        victim = cl.daemons[1].service.advertise_address
        plan.partition(victim)
        faults.install(plan)
        _drive(cl.daemons[0], n_keys=40, behavior=int(Behavior.GLOBAL),
               batches=3, tag="gc")
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            d = svc0.auditor.deltas()
            if d["global_agg_hits"] > 0:
                break
            time.sleep(0.1)
        _assert_clean(*cl.daemons)
        plan.heal()
        time.sleep(0.5)
        _assert_clean(*cl.daemons)
    finally:
        faults.uninstall()
        cl.stop()


@pytest.mark.slow
def test_clean_mid_window_reshard_handoff():
    """Membership churn with the double-dispatch window open: drains,
    transfers, peeks and merges must all reconcile (acked <= drained,
    committed+rejected <= received) with zero violations."""
    beh = fast_test_behaviors()
    beh.reshard_handoff_s = 1.0
    cl = Cluster().start_with(["", ""], behaviors=beh)
    try:
        _drive(cl.daemons[0], n_keys=60, batches=2, tag="rh")
        # Drop daemon 1 from every ring: its resident keys move to d0.
        solo = [cl.peers[0]]
        for d in cl.daemons:
            d.set_peers(solo)
        assert cl.daemons[1].service.reshard.wait_idle(20.0)
        # Traffic during the double-dispatch window (peeks are hits=0).
        _drive(cl.daemons[0], n_keys=60, batches=2, tag="rh")
        deltas = cl.daemons[1].service.auditor.deltas()
        assert deltas["reshard_drained_lanes"] >= deltas["reshard_acked_lanes"]
        _assert_clean(*cl.daemons)
    finally:
        cl.stop()


# ---------------------------------------------------------------------
# The seeded double-commit
# ---------------------------------------------------------------------
@pytest.mark.chaos
def test_duplicate_delivery_caught_by_audit():
    """FaultPlan DUPLICATE on the forward wire: the transport delivers
    each matching RPC twice (the network/proxy re-delivering an applied
    RPC — a true double-commit: the owner applies the hits twice).  The
    sender's ledger counts the wire hits twice against hits admitted
    once, and the audit must catch it: forward_conservation violation,
    metric increment, audit-violation flight-recorder event.  The same
    traffic without the rule stays silent (asserted by every other test
    in this file)."""
    cl = Cluster().start(2)
    plan = faults.FaultPlan(seed=11)
    plan.duplicate(op="GetPeerRateLimits")
    try:
        svc0 = cl.daemons[0].service
        auditor = svc0.auditor
        auditor.arm()  # isolate this test's traffic
        auditor.check_now()  # seed pass (see Auditor.arm)
        faults.install(plan)
        # Keys owned by daemon 1, entered at daemon 0: every lane
        # crosses the forward wire (and gets delivered twice).
        me = svc0.advertise_address
        # Hash-derived probe keys: FNV-1 clusters structured key
        # families onto one owner (the documented hash_ring property),
        # and an unlucky port draw can leave a whole indexed range
        # locally owned — md5-hex keys disperse, so ~half are remote.
        import hashlib

        cand = [hashlib.md5(str(i).encode()).hexdigest() for i in range(64)]
        reqs = [
            RateLimitRequest(
                name="dup", unique_key=uk, hits=3, limit=1000,
                duration=60_000,
            )
            for uk in cand
            if svc0.get_peer(
                RateLimitRequest(name="dup", unique_key=uk).hash_key()
            ).info.grpc_address != me
        ]
        assert reqs, "no remotely-owned keys in the probe range"
        svc0.get_rate_limits(GetRateLimitsRequest(requests=reqs))
        d = auditor.deltas()
        assert d["forward_wire_hits"] > d["forward_admitted_hits"], d
        found = auditor.check_now()
        assert "forward_conservation" in [v["invariant"] for v in found]
        assert auditor.violations["forward_conservation"] >= 1
        kinds = [e["kind"] for e in tracing.events_snapshot()]
        assert "audit-violation" in kinds
        # The violation also surfaces on the status/audit surfaces.
        snap = auditor.snapshot()
        assert snap["violationTotal"] >= 1
    finally:
        faults.uninstall()
        cl.stop()


@pytest.mark.chaos
def test_error_retry_is_not_a_false_positive():
    """A connection-shaped failure + re-pick/retry is the LEGITIMATE
    twin of the duplicate: the failed attempt provably never applied,
    so it must not count wire hits — same traffic shape, zero
    violations."""
    cl = Cluster().start(2)
    plan = faults.FaultPlan(seed=5)
    try:
        svc0 = cl.daemons[0].service
        svc0.auditor.arm()
        victim = cl.daemons[1].service.advertise_address
        # Fail the FIRST forward attempt connection-shaped; the retry
        # (or degraded-local fallback) proceeds.
        plan.error_nth(victim, 1, op="GetPeerRateLimits", count=1)
        faults.install(plan)
        _drive(cl.daemons[0], n_keys=32, hits=2, batches=2, tag="rt")
        _assert_clean(*cl.daemons)
    finally:
        faults.uninstall()
        cl.stop()
