"""Elastic membership unit tests (reshard.py): ring fingerprints, the
transfer wire, columnar drain/commit with O(1)-dispatch pins and
monotone merge semantics, set_peers ring-delta bookkeeping, the epoch
fence, and the bounded membership pool.

The cross-daemon legs (live handoff, double-dispatch reads, chaos,
exactly-once oracle) live in tests/test_reshard_chaos.py.
"""

import threading
import time

import numpy as np
import pytest

from gubernator_tpu import wire
from gubernator_tpu.parallel.hash_ring import ReplicatedConsistentHash
from gubernator_tpu.parallel.mesh import MeshBucketStore
from gubernator_tpu.models.shard import ShardStore
from gubernator_tpu.reshard import (
    TransferColumns,
    ring_fingerprint,
)
from gubernator_tpu.service import ApiError, ServiceConfig, V1Service
from gubernator_tpu.types import (
    Algorithm,
    Behavior,
    PeerInfo,
    RateLimitRequest,
    RateLimitResponse,
    SECOND,
)
from gubernator_tpu.utils.clock import Clock

T0 = 1_573_430_430_000


@pytest.fixture
def clock():
    c = Clock()
    c.freeze(T0)
    return c


def _req(key, hits=1, limit=100, name="rs", duration=3600 * SECOND,
         algorithm=Algorithm.TOKEN_BUCKET, behavior=0):
    return RateLimitRequest(
        name=name, unique_key=key, hits=hits, limit=limit,
        duration=duration, algorithm=algorithm, behavior=behavior,
    )


def _cols(keys, remaining, limit=100, algo=0, status=0,
          duration=3600 * SECOND, stamp=T0, expire=T0 + 3600_000,
          ring_hash=0):
    n = len(keys)
    as_arr = lambda v, dt: (  # noqa: E731
        np.asarray(v, dt) if hasattr(v, "__len__")
        else np.full(n, v, dt)
    )
    return TransferColumns(
        keys=list(keys),
        algorithm=as_arr(algo, np.int32),
        status=as_arr(status, np.int32),
        limit=as_arr(limit, np.int64),
        remaining=as_arr(remaining, np.int64),
        duration=as_arr(duration, np.int64),
        stamp=as_arr(stamp, np.int64),
        expire_at=as_arr(expire, np.int64),
        ring_hash=ring_hash,
    )


# ---------------------------------------------------------------------
# Ring fingerprint (the transfer epoch fence)
# ---------------------------------------------------------------------
def test_ring_fingerprint_order_independent():
    a = ring_fingerprint(["h1:1", "h2:2", "h3:3"])
    b = ring_fingerprint(["h3:3", "h1:1", "h2:2"])
    assert a == b != 0


def test_ring_fingerprint_sensitivity():
    base = ring_fingerprint(["h1:1", "h2:2"])
    assert ring_fingerprint(["h1:1", "h2:2", "h3:3"]) != base  # join
    assert ring_fingerprint(["h1:1"]) != base  # leave
    assert ring_fingerprint(["h1:1", "h9:9"]) != base  # replace
    # A vnode-count change moves ownership without changing membership,
    # so it must change the epoch too.
    assert ring_fingerprint(["h1:1", "h2:2"], replicas=16) != base


def test_ring_fingerprint_matches_picker_method():
    ring = ReplicatedConsistentHash()
    for h in ("b:2", "a:1", "c:3"):
        ring.add(h)
    assert ring.fingerprint() == ring_fingerprint(
        sorted(["a:1", "b:2", "c:3"]), ring.replicas
    )


# ---------------------------------------------------------------------
# Transfer wire: GUBC frame kind 4 + proto columns
# ---------------------------------------------------------------------
def test_transfer_frame_roundtrip():
    cols = _cols(["rs_a", "rs_bc"], remaining=[93, 94],
                 ring_hash=0xDEAD_BEEF_CAFE_F00D)
    raw = wire.encode_transfer_frame(cols)
    assert wire.is_transfer_frame(raw)
    assert not wire.is_globals_frame(raw)  # kinds must not alias
    assert not wire.is_transfer_frame(
        wire.encode_globals_frame(
            __import__(
                "gubernator_tpu.parallel.global_mgr", fromlist=["x"]
            ).GlobalsColumns(
                keys=["k"], algorithm=np.zeros(1, np.int32),
                status=np.zeros(1, np.int32), limit=np.ones(1, np.int64),
                remaining=np.ones(1, np.int64),
                reset_time=np.ones(1, np.int64),
            )
        )
    )
    back = wire.decode_transfer_frame(raw)
    assert back.keys == ["rs_a", "rs_bc"]
    assert back.ring_hash == 0xDEAD_BEEF_CAFE_F00D
    assert list(back.remaining) == [93, 94]
    assert list(back.stamp) == [T0, T0]


def test_transfer_frame_rejects_corruption():
    raw = wire.encode_transfer_frame(_cols(["rs_a"], remaining=[1]))
    with pytest.raises(ValueError, match="length mismatch"):
        wire.decode_transfer_frame(raw + b"x")
    with pytest.raises(ValueError):
        wire.decode_transfer_frame(b"{not a frame}")


def test_transfer_pb_roundtrip():
    cols = _cols(["rs_a"], remaining=[42], ring_hash=7)
    m = wire.transfer_cols_to_pb(cols)
    back = wire.transfer_cols_from_pb(
        type(m).FromString(m.SerializeToString())
    )
    assert back.keys == ["rs_a"]
    assert back.ring_hash == 7
    assert list(back.remaining) == [42]
    assert list(back.expire_at) == [T0 + 3600_000]


# ---------------------------------------------------------------------
# Columnar drain + commit (MeshBucketStore): O(1) programs, monotone
# merge, idempotence
# ---------------------------------------------------------------------
@pytest.fixture(scope="module")
def mesh_store():
    return MeshBucketStore(capacity_per_shard=128, g_capacity=32)


def test_drain_is_one_gather_and_removes(mesh_store, clock):
    st = mesh_store
    now = clock.now_ms()
    reqs = [_req(f"dk{i}", hits=5) for i in range(12)]
    st.apply(reqs, now)
    keys = [r.hash_key() for r in reqs]
    before = st.device_dispatches
    drains_before = st.transfer_drain_dispatches
    cols = st.drain_keys(keys[:8], now)
    # ONE device program for the whole drain batch, by counting.
    assert st.device_dispatches - before == 1
    assert st.transfer_drain_dispatches - drains_before == 1
    assert sorted(cols.keys) == sorted(keys[:8])
    assert (np.asarray(cols.remaining) == 95).all()
    resident = set(st.resident_keys())
    assert not (set(keys[:8]) & resident)
    assert set(keys[8:]) <= resident
    # Draining a non-resident key is a no-op, no device program.
    before = st.device_dispatches
    assert len(st.drain_keys(["rs_gone"], now)) == 0
    assert st.device_dispatches == before


def test_drain_gather_only_then_forget(mesh_store, clock):
    """The handoff protocol: gather WITHOUT removal (the old owner's
    copy stays readable — the double-dispatch peek target — while the
    transfer is in flight), then forget_keys on ACK (host-only, no
    device program)."""
    st = mesh_store
    now = clock.now_ms()
    reqs = [_req(f"ff{i}", hits=2) for i in range(4)]
    st.apply(reqs, now)
    keys = [r.hash_key() for r in reqs]
    cols = st.drain_keys(keys, now, remove=False)
    assert sorted(cols.keys) == sorted(keys)
    assert set(keys) <= set(st.resident_keys())  # still resident
    before = st.device_dispatches
    st.forget_keys(keys)
    assert st.device_dispatches == before  # no device program
    assert not (set(keys) & set(st.resident_keys()))


def test_drain_skips_global_keys(mesh_store, clock):
    st = mesh_store
    now = clock.now_ms()
    g = _req("gkey", hits=1, behavior=int(Behavior.GLOBAL))
    st.apply([g], now)
    assert len(st.drain_keys([g.hash_key()], now)) == 0
    # The GLOBAL key stays: its migration is the replication plane's
    # job (every peer already holds replica state).


def test_commit_is_o1_merge_monotone_idempotent(clock):
    st = MeshBucketStore(capacity_per_shard=128, g_capacity=32)
    now = clock.now_ms()
    # The receiver admitted traffic during the window: k0 has 10 hits
    # locally (remaining 90).
    st.apply([_req("w0", hits=10)], now)
    k0 = _req("w0").hash_key()
    incoming = _cols([k0, "rs_new"], remaining=[85, 97])
    before = st.device_dispatches
    assert st.commit_transfer(incoming, now) == 2
    assert st.device_dispatches - before == 2  # gather + scatter, O(1)
    assert st.transfer_commit_dispatches == 2
    out = st.apply([_req("w0", hits=0)], now)
    # Monotone merge: min(90, 85) — never more permissive than either.
    assert out[0].remaining == 85
    # Idempotent: re-delivering the same batch (a retried transfer)
    # must not double-count.
    st.commit_transfer(incoming, now)
    out = st.apply([_req("w0", hits=0)], now)
    assert out[0].remaining == 85
    # The fresh key landed wholesale (rs_new is its own hash key).
    out = st.apply(
        [RateLimitRequest(name="rs", unique_key="new", hits=0, limit=100,
                          duration=3600 * SECOND)], now
    )
    assert out[0].remaining == 97


def test_commit_drops_expired_and_dedupes(clock):
    st = MeshBucketStore(capacity_per_shard=64, g_capacity=32)
    now = clock.now_ms()
    cols = _cols(
        ["rs_dup", "rs_dead", "rs_dup"],
        remaining=[50, 1, 40],
        expire=[now + 1000, now - 1, now + 1000],
    )
    assert st.commit_transfer(cols, now) == 1  # dup keeps LAST, dead dropped
    out = st.apply([_req("dup", name="rs", hits=0, limit=100)], now)
    assert out[0].remaining == 40


def test_commit_algorithm_switch_takes_incoming(clock):
    """Transferred rows travel in the device's raw representation
    (leaky remaining is fixed-point scaled), so the switch test drains
    a REAL leaky row rather than hand-building one.  A resident row of
    a different algorithm is overwritten wholesale — no cross-algorithm
    merge."""
    src = MeshBucketStore(capacity_per_shard=64, g_capacity=32)
    dst = MeshBucketStore(capacity_per_shard=64, g_capacity=32)
    now = clock.now_ms()
    dst.apply([_req("alg", hits=3)], now)  # token bucket resident at dst
    src.apply(
        [_req("alg", hits=2, algorithm=Algorithm.LEAKY_BUCKET)], now
    )
    cols = src.drain_keys([_req("alg").hash_key()], now)
    assert list(cols.algorithm) == [int(Algorithm.LEAKY_BUCKET)]
    assert dst.commit_transfer(cols, now) == 1
    out = dst.apply(
        [_req("alg", hits=0, algorithm=Algorithm.LEAKY_BUCKET)], now
    )
    assert out[0].remaining == 98


def test_shard_store_drain_commit_roundtrip(clock):
    """The single-shard twin (ShardStore) speaks the same drain/commit
    contract — Store-SPI deployments reshard too."""
    src, dst = ShardStore(capacity=64), ShardStore(capacity=64)
    now = clock.now_ms()
    src.apply([_req(f"ss{i}", hits=4) for i in range(6)], now)
    keys = [_req(f"ss{i}").hash_key() for i in range(6)]
    before = src.device_dispatches
    cols = src.drain_keys(keys, now)
    assert src.device_dispatches - before == 1
    assert len(cols) == 6 and not src.resident_keys()
    before = dst.device_dispatches
    assert dst.commit_transfer(cols, now) == 6
    assert dst.device_dispatches - before == 2
    out = dst.apply([_req(f"ss{i}", hits=0) for i in range(6)], now)
    assert [r.remaining for r in out] == [96] * 6


# ---------------------------------------------------------------------
# set_peers ring-delta bookkeeping + the epoch fence + bounded pool
# ---------------------------------------------------------------------
def _mk_service(clock, **beh_over):
    from gubernator_tpu.config import BehaviorConfig

    beh = BehaviorConfig(
        global_sync_wait_s=3600.0, multi_region_sync_wait_s=3600.0,
        **beh_over,
    )
    svc = V1Service(
        ServiceConfig(cache_size=512, clock=clock, behaviors=beh)
    )
    return svc


SELF = "127.0.0.1:19001"
OTHER = "127.0.0.1:19002"
THIRD = "127.0.0.1:19003"


def _info(addr, me=False):
    return PeerInfo(grpc_address=addr, http_address=addr, is_owner=me)


def test_set_peers_generation_and_noop(clock):
    svc = _mk_service(clock)
    try:
        svc.set_peers([_info(SELF, me=True)])
        assert svc.ring_generation == 1
        h1 = svc.ring_hash
        assert h1 != 0
        # Same membership re-pushed (discovery heartbeat): no bump, no
        # handoff window.
        svc.set_peers([_info(SELF, me=True)])
        assert svc.ring_generation == 1 and svc.ring_hash == h1
        assert svc._prev_picker is None
        # Membership change: bump + window opens.
        svc.set_peers([_info(SELF, me=True), _info(OTHER)])
        assert svc.ring_generation == 2 and svc.ring_hash != h1
        assert svc._prev_picker is not None
        assert svc.debug_status()["ring"]["handoffActive"] is True
    finally:
        svc.close()


def test_handoff_window_expires(clock):
    svc = _mk_service(clock, reshard_handoff_s=0.05)
    try:
        svc.set_peers([_info(SELF, me=True)])
        svc.set_peers([_info(SELF, me=True), _info(OTHER)])
        assert svc._handoff_prev_picker() is not None
        time.sleep(0.08)
        assert svc._handoff_prev_picker() is None  # window lapsed
        assert svc.debug_status()["ring"]["handoffActive"] is False
    finally:
        svc.close()


def test_transfer_ownership_fence_and_rejection(clock):
    svc = _mk_service(clock)
    try:
        svc.set_peers([_info(SELF, me=True), _info(OTHER)])
        # Wrong-epoch batch: fenced with FailedPrecondition/409.
        stale = _cols(["rs_x"], remaining=[5], ring_hash=12345)
        with pytest.raises(ApiError) as ei:
            svc.transfer_ownership(stale)
        assert ei.value.code == "FailedPrecondition"
        assert ei.value.http_status == 409
        assert svc.reshard.transfers_fenced_in == 1
        # Right-epoch batch: lanes owned by OTHER are dropped, lanes
        # owned here commit.
        ring = svc.local_picker
        mine, theirs = [], []
        for i in range(64):
            k = f"rs_f{i}"
            (mine if ring.get(k) == SELF else theirs).append(k)
        assert mine and theirs
        cols = _cols(mine + theirs, remaining=[9] * (len(mine) + len(theirs)),
                     ring_hash=svc.ring_hash)
        committed, rejected = svc.transfer_ownership(cols)
        assert committed == len(mine)
        assert rejected == len(theirs)
        assert svc.reshard.lanes_received == len(mine)
        assert svc.reshard.lanes_rejected == len(theirs)
    finally:
        svc.close()


def test_unfenced_transfer_accepted(clock):
    # ring_hash=0 (tests / tooling) commits anywhere.
    svc = _mk_service(clock)
    try:
        svc.set_peers([_info(SELF, me=True)])
        committed, rejected = svc.transfer_ownership(
            _cols(["rs_any"], remaining=[3], ring_hash=0)
        )
        assert (committed, rejected) == (1, 0)
    finally:
        svc.close()


def test_reshard_knob_off_is_metadata_only(clock):
    svc = _mk_service(clock, reshard=False)
    try:
        assert svc.serves_reshard is False
        svc.set_peers([_info(SELF, me=True)])
        svc.set_peers([_info(SELF, me=True), _info(OTHER)])
        # Generation still tracks (observability), but no handoff was
        # scheduled: the ring change is metadata-only, legacy semantics.
        assert svc.ring_generation == 2
        svc.reshard.wait_idle(5)
        assert svc.reshard.transfers_started == 0
    finally:
        svc.close()


def test_set_peers_bounded_shutdown_tracked(clock):
    svc = _mk_service(clock)
    try:
        svc.set_peers([_info(SELF, me=True), _info(OTHER), _info(THIRD)])
        dropped = [
            p for p in svc.get_peer_list()
            if p.info.grpc_address == THIRD
        ]
        assert len(dropped) == 1
        svc.set_peers([_info(SELF, me=True), _info(OTHER)])
        # The dropped client's shutdown ran on the TRACKED bounded pool
        # (no unbounded per-peer daemon threads), so wait_idle observes
        # its completion.
        assert svc.reshard.wait_idle(10)
        assert dropped[0]._shutdown.is_set()
        reshard_threads = [
            t.name for t in threading.enumerate()
            if t.name.startswith("reshard")
        ]
        assert len(reshard_threads) <= svc.reshard.POOL_WORKERS
    finally:
        svc.close()


def test_gateway_transfer_path(clock):
    """The HTTP surface: a GUBC transfer frame POSTed to
    /v1/peer.TransferOwnership commits; a fenced frame answers 409; a
    knob-off daemon serves NO handler on the path (404 — exactly what a
    pre-reshard build answers, which is the sender's version probe)."""
    import json

    from gubernator_tpu.gateway import handle_request

    svc = _mk_service(clock)
    try:
        svc.set_peers([_info(SELF, me=True)])
        raw = wire.encode_transfer_frame(
            _cols(["rs_http"], remaining=[11], ring_hash=svc.ring_hash)
        )
        status, _, body = handle_request(
            svc, "POST", "/v1/peer.TransferOwnership", raw
        )
        assert status == 200
        assert json.loads(body) == {"committed": 1, "rejected": 0}
        # Dead-epoch frame: fenced.
        stale = wire.encode_transfer_frame(
            _cols(["rs_http"], remaining=[11], ring_hash=12345)
        )
        status, _, body = handle_request(
            svc, "POST", "/v1/peer.TransferOwnership", stale
        )
        assert status == 409
        # Not a frame: 400.
        status, _, _ = handle_request(
            svc, "POST", "/v1/peer.TransferOwnership", b"{}"
        )
        assert status == 400
    finally:
        svc.close()
    off = _mk_service(clock, reshard=False)
    try:
        off.set_peers([_info(SELF, me=True)])
        status, _, _ = handle_request(
            off, "POST", "/v1/peer.TransferOwnership", raw
        )
        assert status == 404  # no handler: pre-reshard wire behavior
    finally:
        off.close()


def test_merge_handoff_monotone():
    primary = RateLimitResponse(status=0, limit=100, remaining=90,
                                reset_time=2000)
    peek = RateLimitResponse(status=1, limit=100, remaining=40,
                             reset_time=1500)
    out = V1Service._merge_handoff(primary, peek)
    assert (out.status, out.remaining, out.reset_time) == (1, 40, 2000)
    assert out.metadata["handoff"] == "true"
    # Peek failure / error answers leave the primary untouched.
    p2 = RateLimitResponse(status=0, limit=100, remaining=90)
    assert V1Service._merge_handoff(p2, None) is p2
    assert V1Service._merge_handoff(
        p2, RateLimitResponse(error="boom")
    ).remaining == 90
