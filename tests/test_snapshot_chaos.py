"""Durability chaos: kill-tested recovery (the ISSUE 10 acceptance
scenarios).

A REAL daemon subprocess is SIGKILLed — mid-traffic and, separately,
mid-snapshot-write — then restarted on the same address with the same
snapshot file, and the recovered state is asserted MONOTONE-BOUNDED:

  * spend recovered from the snapshot is at least everything admitted
    before the last completed snapshot (no un-spend beyond the
    documented staleness slack) and at most everything ever admitted
    (no minted hits),
  * expired buckets do not resurrect,
  * a kill -9 at ANY instant of the temp+fsync+rename sequence leaves
    the previous snapshot intact and loadable,
  * GUBER_SNAPSHOT=0 reproduces the pre-durability full reset, and a
    graceful SIGTERM restart restores the spend EXACTLY
    (zero-downtime deploy),
  * the restarted daemon's conservation audit stays silent.

`make chaos` runs these (chaos marker); the daemon-subprocess ones are
additionally slow-marked so tier-1 stays fast.
"""

import json
import os
import random
import re
import signal
import socket
import subprocess
import sys
import time
import urllib.request

import pytest

from gubernator_tpu import snapshot as snap
from gubernator_tpu.client import V1Client
from gubernator_tpu.types import (
    GetRateLimitsRequest,
    RateLimitRequest,
    Status,
)

pytestmark = pytest.mark.chaos

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LIMIT = 1000
DURATION_MS = 600_000


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn(addr: str, snapshot_path: str, interval_ms: int = 100,
           snapshot_on: bool = True) -> subprocess.Popen:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env["JAX_COMPILATION_CACHE_DIR"] = os.path.join(REPO, ".jax_cache")
    env["GUBER_HTTP_ADDRESS"] = addr
    env["GUBER_SNAPSHOT"] = snapshot_path if snapshot_on else "0"
    env["GUBER_SNAPSHOT_INTERVAL"] = str(interval_ms)
    # Keep startup lean: small cache, one warm shape.
    env["GUBER_CACHE_SIZE"] = "4096"
    env["GUBER_WARMUP_SHAPES"] = "1,250"
    proc = subprocess.Popen(
        [sys.executable, "-m", "gubernator_tpu.cmd.server"],
        stdout=subprocess.PIPE, text=True, env=env, cwd=REPO,
    )
    deadline = time.monotonic() + 240
    for line in proc.stdout:
        if re.search(r"listening on http://", line):
            return proc
        if time.monotonic() > deadline:
            break
    proc.kill()
    raise RuntimeError("daemon never printed its listening line")


def _stop(proc: subprocess.Popen, sig=signal.SIGTERM) -> None:
    if proc.poll() is None:
        proc.send_signal(sig)
        try:
            proc.wait(timeout=30)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=10)


def _req(key, hits, limit=LIMIT, duration=DURATION_MS):
    return RateLimitRequest(
        name="chaos", unique_key=key, hits=hits, limit=limit,
        duration=duration,
    )


def _hit(client, key, hits, **kw) -> int:
    r = client.get_rate_limits(
        GetRateLimitsRequest(requests=[_req(key, hits, **kw)])
    ).responses[0]
    assert r.error == "" and r.status == Status.UNDER_LIMIT
    return r.remaining


def _debug(addr: str, doc: str) -> dict:
    with urllib.request.urlopen(f"http://{addr}/debug/{doc}", timeout=10) as f:
        return json.loads(f.read())


@pytest.mark.slow
def test_kill9_mid_traffic_recovers_monotone_bounded(tmp_path):
    """SIGKILL under live traffic: the restarted daemon serves from the
    last completed snapshot, bounded by the staleness slack — and the
    audit ledger stays clean."""
    addr = f"127.0.0.1:{_free_port()}"
    path = str(tmp_path / "chaos.snap")
    proc = _spawn(addr, path, interval_ms=100)
    try:
        client = V1Client(addr, timeout_s=60.0)
        # Phase A: admitted spend that MUST survive (a snapshot interval
        # completes after it).
        for _ in range(5):
            r_a = _hit(client, "k_mono", hits=10)
        assert r_a == LIMIT - 50
        # A short-lived bucket that must NOT resurrect after the crash.
        _hit(client, "k_expire", hits=5, duration=1_500)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if os.path.exists(path):
                cols, _ = snap.read_snapshot(path)
                spent = {
                    k: LIMIT - int(cols.remaining[i])
                    for i, k in enumerate(cols.keys)
                }
                if any("k_mono" in k for k in cols.keys) and max(
                    (v for k, v in spent.items() if "k_mono" in k), default=0
                ) >= 50:
                    break
            time.sleep(0.05)
        # Phase B: the staleness slack — admitted after the snapshot we
        # just observed, may or may not make a later snapshot.
        r_b = _hit(client, "k_mono", hits=30)
        assert r_b == LIMIT - 80
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=10)

        proc = _spawn(addr, path, interval_ms=100)
        client = V1Client(addr, timeout_s=60.0)
        status = _debug(addr, "status")
        assert status["snapshot"]["restore"] == "ok"
        assert status["snapshot"]["restoredLanes"] >= 1
        r_post = _hit(client, "k_mono", hits=0)
        spent = LIMIT - r_post
        # Monotone-bounded: everything snapshotted (>= phase A) came
        # back; nothing was minted (<= everything admitted).
        assert 50 <= spent <= 80, (
            f"recovered spend {spent} outside the [50, 80] slack band"
        )
        # Expired bucket did not resurrect: a fresh read starts full.
        assert _hit(client, "k_expire", hits=0, duration=1_500) == LIMIT
        # Conservation audit silent on the recovered daemon.
        assert _debug(addr, "audit")["violationTotal"] == 0
    finally:
        _stop(proc)


@pytest.mark.slow
def test_sigterm_restart_is_zero_downtime_and_knob_off_resets(tmp_path):
    """Graceful restart restores the spend EXACTLY; the same sequence
    under GUBER_SNAPSHOT=0 reproduces the pre-durability full reset
    (the legacy failure class, proven still present behind the knob)."""
    addr = f"127.0.0.1:{_free_port()}"
    path = str(tmp_path / "deploy.snap")
    # -- knob ON: deploy-style SIGTERM restart, exact restore ----------
    proc = _spawn(addr, path, interval_ms=0)  # shutdown-only snapshots
    try:
        client = V1Client(addr, timeout_s=60.0)
        assert _hit(client, "k_deploy", hits=77) == LIMIT - 77
        _stop(proc, signal.SIGTERM)
        cols, _ = snap.read_snapshot(path)  # the close() snapshot
        assert len(cols) >= 1
        proc = _spawn(addr, path, interval_ms=0)
        client = V1Client(addr, timeout_s=60.0)
        assert _hit(client, "k_deploy", hits=0) == LIMIT - 77
        # -- knob OFF: same restart, state gone (full reset) -----------
        _stop(proc, signal.SIGTERM)
        proc = _spawn(addr, path, snapshot_on=False)
        client = V1Client(addr, timeout_s=60.0)
        assert _debug(addr, "status")["snapshot"]["enabled"] is False
        assert _hit(client, "k_deploy", hits=0) == LIMIT
    finally:
        _stop(proc)


WRITER_LOOP = r"""
import sys, numpy as np
from gubernator_tpu.reshard import TransferColumns
from gubernator_tpu.snapshot import write_snapshot

path = sys.argv[1]
gen = 0
print("WRITING", flush=True)
while True:
    n = 64 + (gen % 3) * 37  # vary size so renames change length
    cols = TransferColumns(
        keys=[f"g{gen}_k{i}" for i in range(n)],
        algorithm=np.zeros(n, np.int32), status=np.zeros(n, np.int32),
        limit=np.full(n, 100, np.int64),
        remaining=np.full(n, gen % 100, np.int64),
        duration=np.full(n, 60000, np.int64),
        stamp=np.full(n, 1, np.int64),
        expire_at=np.full(n, 10**15, np.int64),
    )
    write_snapshot(path, cols, saved_at_ms=gen)
    gen += 1
"""


def test_kill9_mid_write_leaves_previous_snapshot_loadable(tmp_path):
    """SIGKILL a process hammering write_snapshot at random instants:
    the snapshot path must read back a COMPLETE generation every time
    (the rename is the commit point; a torn temp is never the file)."""
    path = str(tmp_path / "torn.snap")
    rng = random.Random(0xC0FFEE)
    for round_ in range(4):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        proc = subprocess.Popen(
            [sys.executable, "-c", WRITER_LOOP, path],
            stdout=subprocess.PIPE, text=True, env=env, cwd=REPO,
        )
        try:
            assert proc.stdout.readline().strip() == "WRITING"
            time.sleep(rng.uniform(0.02, 0.35))
            proc.send_signal(signal.SIGKILL)
            proc.wait(timeout=10)
        finally:
            _stop(proc)
        # Whatever instant the kill landed on, the file is a complete,
        # checksum-valid snapshot of exactly one generation.
        cols, meta = snap.read_snapshot(path)
        gens = {k.split("_")[0] for k in cols.keys}
        assert len(gens) == 1, f"torn across generations: {gens}"
        assert len(cols) in (64, 101, 138)
        assert int(cols.remaining[0]) == meta["saved_at_ms"] % 100
