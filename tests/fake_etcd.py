"""In-process fake etcd v3 server for discovery tests.

Speaks the same wire subset as gubernator_tpu/proto/etcd_rpc.proto
(KV Range/Put/DeleteRange, Lease Grant/Revoke/KeepAlive, Watch) with
revisioned history, lease-scoped keys that vanish on TTL expiry, and
watch replay from start_revision — the etcd behaviors EtcdPool relies
on.  Plays the role the reference delegates to a real etcd container in
its docker-compose-etcd.yaml setup.
"""

from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import grpc

from gubernator_tpu.proto import etcd_kv_pb2 as kvpb
from gubernator_tpu.proto import etcd_rpc_pb2 as rpc


@dataclass
class _KV:
    value: bytes
    lease: int
    create_revision: int
    mod_revision: int
    version: int


class FakeEtcd:
    def __init__(self, lease_scale: float = 1.0, tls_creds=None,
                 auth_users: "Dict[str, str] | None" = None):
        """lease_scale shrinks granted TTLs (a 30s lease with
        lease_scale=0.01 expires in 0.3s) so expiry paths are testable.
        `tls_creds` (grpc.ServerCredentials) serves TLS; `auth_users`
        (name -> password) enforces etcd v3 token auth on every RPC."""
        self.lease_scale = lease_scale
        self.auth_users = dict(auth_users or {})
        self._tokens: set = set()
        self._lock = threading.RLock()
        self._kv: Dict[bytes, _KV] = {}
        self._revision = 0
        self._leases: Dict[int, float] = {}  # id -> expiry monotonic
        self._lease_ttl: Dict[int, float] = {}
        self._next_lease = 1000
        self._watchers: List[Tuple[bytes, bytes, "queue.Queue"]] = []
        self._history: List[Tuple[int, kvpb.Event]] = []  # (revision, event)
        # Real-etcd compaction semantics: history below this revision is
        # gone; a Watch created with start_revision < compact_revision
        # is answered created-then-canceled with compact_revision set
        # (mvcc ErrCompacted surface).
        self._compact_revision = 0
        self._stop = threading.Event()
        self._reaper = threading.Thread(target=self._reap_leases, daemon=True)
        self._reaper.start()

        self._server = grpc.server(ThreadPoolExecutor(max_workers=16))
        self._server.add_generic_rpc_handlers((self._handlers(),))
        if tls_creds is not None:
            self.port = self._server.add_secure_port("127.0.0.1:0", tls_creds)
        else:
            self.port = self._server.add_insecure_port("127.0.0.1:0")
        self.address = f"127.0.0.1:{self.port}"
        self._server.start()

    # ------------------------------------------------------------------
    def _handlers(self):
        def guard(fn):
            # etcd v3 auth: every RPC must carry a live token in the
            # `token` metadata once auth is enabled.
            def inner(req, ctx):
                if self.auth_users:
                    md = dict(ctx.invocation_metadata())
                    if md.get("token") not in self._tokens:
                        ctx.abort(
                            grpc.StatusCode.INVALID_ARGUMENT,
                            "etcdserver: invalid auth token",
                        )
                return fn(req, ctx)

            return inner

        def uu(fn, req_cls):
            return grpc.unary_unary_rpc_method_handler(
                guard(fn),
                request_deserializer=req_cls.FromString,
                response_serializer=lambda m: m.SerializeToString(),
            )

        def ss(fn, req_cls):
            return grpc.stream_stream_rpc_method_handler(
                guard(fn),
                request_deserializer=req_cls.FromString,
                response_serializer=lambda m: m.SerializeToString(),
            )

        def do_auth(req: rpc.AuthenticateRequest, ctx) -> rpc.AuthenticateResponse:
            if self.auth_users.get(req.name) != req.password:
                ctx.abort(
                    grpc.StatusCode.INVALID_ARGUMENT,
                    "etcdserver: authentication failed, invalid user ID or password",
                )
            tok = f"tok-{req.name}-{len(self._tokens)}"
            self._tokens.add(tok)
            return rpc.AuthenticateResponse(header=self._header(), token=tok)

        method_map = {
            "/etcdserverpb.KV/Range": uu(self._do_range, rpc.RangeRequest),
            "/etcdserverpb.KV/Put": uu(self._do_put, rpc.PutRequest),
            "/etcdserverpb.KV/DeleteRange": uu(self._do_delete, rpc.DeleteRangeRequest),
            "/etcdserverpb.KV/Compact": uu(self._do_compact, rpc.CompactionRequest),
            "/etcdserverpb.Lease/LeaseGrant": uu(self._do_grant, rpc.LeaseGrantRequest),
            "/etcdserverpb.Lease/LeaseRevoke": uu(self._do_revoke, rpc.LeaseRevokeRequest),
            "/etcdserverpb.Lease/LeaseKeepAlive": ss(self._do_keepalive, rpc.LeaseKeepAliveRequest),
            "/etcdserverpb.Watch/Watch": ss(self._do_watch, rpc.WatchRequest),
            "/etcdserverpb.Auth/Authenticate": grpc.unary_unary_rpc_method_handler(
                do_auth,
                request_deserializer=rpc.AuthenticateRequest.FromString,
                response_serializer=lambda m: m.SerializeToString(),
            ),
        }

        class Handler(grpc.GenericRpcHandler):
            def service(self, details):
                return method_map.get(details.method)

        return Handler()

    # ------------------------------------------------------------------
    def _header(self) -> rpc.ResponseHeader:
        return rpc.ResponseHeader(revision=self._revision)

    def _in_range(self, key: bytes, start: bytes, end: bytes) -> bool:
        if not end:
            return key == start
        return start <= key < end

    def _emit(self, ev: kvpb.Event) -> None:
        """Record history + fan out to live watchers (caller holds lock)."""
        self._history.append((self._revision, ev))
        for start, end, q in list(self._watchers):
            if self._in_range(ev.kv.key, start, end):
                q.put((self._revision, ev))

    def _put_locked(self, key: bytes, value: bytes, lease: int) -> None:
        self._revision += 1
        old = self._kv.get(key)
        self._kv[key] = _KV(
            value=value,
            lease=lease,
            create_revision=old.create_revision if old else self._revision,
            mod_revision=self._revision,
            version=(old.version + 1) if old else 1,
        )
        self._emit(
            kvpb.Event(
                type=kvpb.Event.PUT,
                kv=kvpb.KeyValue(
                    key=key, value=value, lease=lease,
                    mod_revision=self._revision,
                    create_revision=self._kv[key].create_revision,
                    version=self._kv[key].version,
                ),
            )
        )

    def _delete_locked(self, key: bytes) -> bool:
        if key not in self._kv:
            return False
        self._revision += 1
        del self._kv[key]
        self._emit(
            kvpb.Event(
                type=kvpb.Event.DELETE,
                kv=kvpb.KeyValue(key=key, mod_revision=self._revision),
            )
        )
        return True

    # ------------------------------------------------------------------
    def _do_range(self, req: rpc.RangeRequest, ctx) -> rpc.RangeResponse:
        with self._lock:
            kvs = [
                kvpb.KeyValue(
                    key=k, value=v.value, lease=v.lease,
                    create_revision=v.create_revision,
                    mod_revision=v.mod_revision, version=v.version,
                )
                for k, v in sorted(self._kv.items())
                if self._in_range(k, req.key, req.range_end)
            ]
            return rpc.RangeResponse(header=self._header(), kvs=kvs, count=len(kvs))

    def _do_put(self, req: rpc.PutRequest, ctx) -> rpc.PutResponse:
        with self._lock:
            if req.lease and req.lease not in self._leases:
                ctx.abort(grpc.StatusCode.NOT_FOUND, "etcdserver: requested lease not found")
            self._put_locked(req.key, req.value, req.lease)
            return rpc.PutResponse(header=self._header())

    def _do_delete(self, req: rpc.DeleteRangeRequest, ctx) -> rpc.DeleteRangeResponse:
        with self._lock:
            keys = [
                k for k in list(self._kv)
                if self._in_range(k, req.key, req.range_end)
            ]
            deleted = sum(1 for k in keys if self._delete_locked(k))
            return rpc.DeleteRangeResponse(header=self._header(), deleted=deleted)

    def _do_compact(self, req: rpc.CompactionRequest, ctx) -> rpc.CompactionResponse:
        with self._lock:
            if req.revision <= self._compact_revision:
                ctx.abort(
                    grpc.StatusCode.OUT_OF_RANGE,
                    "etcdserver: mvcc: required revision has been compacted",
                )
            if req.revision > self._revision:
                ctx.abort(
                    grpc.StatusCode.OUT_OF_RANGE,
                    "etcdserver: mvcc: required revision is a future revision",
                )
            self._compact_revision = req.revision
            self._history = [
                (rev, ev) for rev, ev in self._history if rev >= req.revision
            ]
            return rpc.CompactionResponse(header=self._header())

    def _do_grant(self, req: rpc.LeaseGrantRequest, ctx) -> rpc.LeaseGrantResponse:
        with self._lock:
            self._next_lease += 1
            lid = req.ID or self._next_lease
            ttl = req.TTL * self.lease_scale
            self._leases[lid] = time.monotonic() + ttl
            self._lease_ttl[lid] = ttl
            return rpc.LeaseGrantResponse(header=self._header(), ID=lid, TTL=req.TTL)

    def _do_revoke(self, req: rpc.LeaseRevokeRequest, ctx) -> rpc.LeaseRevokeResponse:
        self.revoke_lease(req.ID)
        with self._lock:
            return rpc.LeaseRevokeResponse(header=self._header())

    def _do_keepalive(self, request_iterator, ctx):
        for req in request_iterator:
            with self._lock:
                if req.ID not in self._leases:
                    # Real etcd keeps the stream open and answers an
                    # unknown/expired lease with TTL=0.
                    yield rpc.LeaseKeepAliveResponse(
                        header=self._header(), ID=req.ID, TTL=0
                    )
                    continue
                self._leases[req.ID] = time.monotonic() + self._lease_ttl[req.ID]
                yield rpc.LeaseKeepAliveResponse(
                    header=self._header(), ID=req.ID, TTL=int(self._lease_ttl[req.ID])
                )

    def _do_watch(self, request_iterator, ctx):
        create = next(request_iterator).create_request
        q: "queue.Queue" = queue.Queue()
        start, end = create.key, create.range_end
        with self._lock:
            if (
                create.start_revision
                and create.start_revision < self._compact_revision
            ):
                # Watch from a compacted revision: etcd creates the
                # watcher, then immediately cancels it with
                # compact_revision set (the client must re-list and
                # re-watch from a current revision).
                compact_rev = self._compact_revision
                stale = True
            else:
                stale = False
            backlog = [
                (rev, ev)
                for rev, ev in self._history
                if create.start_revision
                and rev >= create.start_revision
                and self._in_range(ev.kv.key, start, end)
            ]
            self._watchers.append((start, end, q))
        try:
            yield rpc.WatchResponse(header=rpc.ResponseHeader(), created=True, watch_id=1)
            if stale:
                yield rpc.WatchResponse(
                    header=self._header(), watch_id=1, canceled=True,
                    compact_revision=compact_rev,
                    cancel_reason="etcdserver: mvcc: required revision has been compacted",
                )
                return
            for rev, ev in backlog:
                yield rpc.WatchResponse(
                    header=rpc.ResponseHeader(revision=rev), watch_id=1, events=[ev]
                )
            while ctx.is_active():
                try:
                    rev, ev = q.get(timeout=0.05)
                except queue.Empty:
                    continue
                if rev == "CANCEL":  # cancel_watchers() sentinel
                    yield rpc.WatchResponse(
                        header=self._header(), watch_id=1, canceled=True,
                        compact_revision=ev,
                    )
                    return
                yield rpc.WatchResponse(
                    header=rpc.ResponseHeader(revision=rev), watch_id=1, events=[ev]
                )
        finally:
            with self._lock:
                self._watchers.remove((start, end, q))

    # ------------------------------------------------------------------
    def cancel_watchers(self) -> None:
        """Cancel every live watch stream (the server-side stream kill a
        real etcd performs on leader change / compaction pressure);
        clients must re-list and re-watch."""
        with self._lock:
            for _, _, q in list(self._watchers):
                q.put(("CANCEL", self._compact_revision))

    # ------------------------------------------------------------------
    def revoke_lease(self, lease_id: int) -> None:
        """Drop a lease and delete all keys attached to it."""
        with self._lock:
            self._leases.pop(lease_id, None)
            self._lease_ttl.pop(lease_id, None)
            for k, v in list(self._kv.items()):
                if v.lease == lease_id:
                    self._delete_locked(k)

    def _reap_leases(self) -> None:
        while not self._stop.wait(0.05):
            now = time.monotonic()
            with self._lock:
                expired = [lid for lid, exp in self._leases.items() if exp < now]
            for lid in expired:
                self.revoke_lease(lid)

    def keys(self) -> List[str]:
        with self._lock:
            return sorted(k.decode() for k in self._kv)

    def stop(self) -> None:
        self._stop.set()
        self._server.stop(grace=0.2)
