"""Native host runtime (C++ slot table / planner / fnv) parity tests.

The C++ twin must agree operation-for-operation with the Python
SlotTable (models/slot_table.py) — both mirror cache.go semantics — and
the batch planner must reproduce RoundPlanner's round splits.
"""

import numpy as np
import pytest

from gubernator_tpu import native
from gubernator_tpu.models.shard import ShardStore
from gubernator_tpu.models.slot_table import SlotTable
from gubernator_tpu.types import Algorithm, Behavior, RateLimitRequest, SECOND
from gubernator_tpu.utils import hashing

pytestmark = pytest.mark.skipif(
    not native.available(), reason=f"native runtime unavailable: {native.build_error()}"
)


def test_fnv_matches_python():
    keys = ["", "a", "foobar", "test_health_hc_0", "账户:1234"]
    for variant in (False, True):
        got = native.fnv1_batch(keys, variant_1a=variant)
        py = [
            (hashing.fnv1a_64 if variant else hashing.fnv1_64)(k.encode("utf-8"))
            for k in keys
        ]
        assert list(got) == py


def test_table_parity_random_ops():
    """Drive both tables with the same randomized op sequence and
    compare every observable output."""
    rng = np.random.RandomState(7)
    py = SlotTable(32)
    nat = native.NativeSlotTable(32)
    keys = [f"k{i}" for i in range(100)]
    now = 1000
    for step in range(3000):
        op = rng.randint(0, 10)
        key = keys[rng.randint(0, len(keys))]
        if op < 6:
            a = py.lookup_or_assign(key, now)
            b = nat.lookup_or_assign(key, now)
            assert a == b, (step, key, a, b)
        elif op < 8:
            slot = py.get_slot(key)
            assert slot == nat.get_slot(key), (step, key)
            if slot is not None:
                exp = now + int(rng.randint(0, 500))
                py.commit([slot], [exp], [False])
                nat.commit([slot], [exp], [False])
        elif op == 8:
            py.remove(key)
            nat.remove(key)
        else:
            now += int(rng.randint(0, 200))
    assert len(py) == len(nat)
    assert sorted(py.keys()) == sorted(nat.keys())
    assert (py.hits, py.misses, py.evictions) == (nat.hits, nat.misses, nat.evictions)


def test_commit_staleness_guard():
    """A lane whose slot was remapped (eviction mid-batch) must not
    touch the slot's new owner when committed with keys."""
    t = native.NativeSlotTable(2)
    s_a, _ = t.lookup_or_assign("A", 100)
    t.lookup_or_assign("B", 100)
    s_c, _ = t.lookup_or_assign("C", 100)  # evicts LRU (= A)
    assert s_c == s_a
    t.commit([s_a], [999], [False], keys=["A"])  # stale: dropped
    assert t.lookup_or_assign("C", 500) == (s_c, False)  # expire untouched
    t.commit([s_c], [999], [True], keys=["A"])  # stale removal: dropped
    assert t.get_slot("C") == s_c
    t.commit([s_c], [999], [False], keys=["C"])  # valid
    assert t.lookup_or_assign("C", 500) == (s_c, True)


def test_planner_rounds_duplicates():
    t = native.NativeSlotTable(16)
    keys = ["a", "b", "a", "a", "c", "b"]
    p = native.NativeBatchPlanner(t, keys, 100)
    rounds = []
    while True:
        r = p.next_round()
        if r is None:
            break
        lane, slots, exists = r
        rounds.append(list(lane))
        p.commit_round(np.full(len(lane), 500, np.int64), np.zeros(len(lane), np.uint8))
    # Skip-and-defer: duplicates wait for the next round, unique keys
    # keep flowing; the k-th request for a key always sees the (k-1)-th's
    # committed state, and round count = max key multiplicity.
    assert rounds == [[0, 1, 4], [2, 5], [3]]


def test_planner_exists_reflects_commits():
    t = native.NativeSlotTable(16)
    p = native.NativeBatchPlanner(t, ["x", "x"], 100)
    lane, slots, exists = p.next_round()
    assert list(exists) == [False]
    p.commit_round(np.array([500], np.int64), np.array([0], np.uint8))
    lane, slots, exists = p.next_round()
    assert list(exists) == [True]  # round 1's commit is visible
    p.commit_round(np.array([500], np.int64), np.array([0], np.uint8))


def _req(key, hits=1, limit=10, duration=9 * SECOND, algo=Algorithm.TOKEN_BUCKET, behavior=0):
    return RateLimitRequest(
        name="nat", unique_key=key, hits=hits, limit=limit,
        duration=duration, algorithm=algo, behavior=behavior,
    )


def test_shardstore_native_vs_python_sequences():
    """Same request stream through the native fast path and the Python
    fallback gives byte-identical responses."""
    now = 1_700_000_000_000
    a = ShardStore(capacity=64, use_native=True)
    b = ShardStore(capacity=64, use_native=False)
    assert a._native and not b._native
    rng = np.random.RandomState(3)
    for t in range(20):
        reqs = [
            _req(
                f"k{rng.randint(0, 12)}",
                hits=int(rng.randint(0, 4)),
                limit=5,
                algo=Algorithm(int(rng.randint(0, 2))),
            )
            for _ in range(16)
        ]
        ra = a.apply(reqs, now + t * 250)
        rb = b.apply(reqs, now + t * 250)
        assert ra == rb, t


def test_apply_columns_matches_apply():
    now = 1_700_000_000_000
    st = ShardStore(capacity=128)
    reqs = [_req(f"c{i % 7}", hits=1, limit=100) for i in range(32)]
    expect = ShardStore(capacity=128).apply(reqs, now)
    out = st.apply_columns(
        keys=[r.hash_key() for r in reqs],
        algorithm=[int(r.algorithm) for r in reqs],
        behavior=[0] * len(reqs),
        hits=[r.hits for r in reqs],
        limit=[r.limit for r in reqs],
        duration=[r.duration for r in reqs],
        now_ms=now,
    )
    for i, e in enumerate(expect):
        assert int(out["status"][i]) == e.status
        assert int(out["remaining"][i]) == e.remaining
        assert int(out["reset_time"][i]) == e.reset_time


def test_native_store_capacity_eviction_parity():
    """Under capacity pressure both paths evict LRU and keep working."""
    now = 1_700_000_000_000
    a = ShardStore(capacity=8, use_native=True)
    b = ShardStore(capacity=8, use_native=False)
    for t in range(40):
        reqs = [_req(f"e{(t + j) % 20}", limit=1000) for j in range(6)]
        assert a.apply(reqs, now + t) == b.apply(reqs, now + t)
    assert sorted(a.table.keys()) == sorted(b.table.keys())


def test_plan_single_dispatch_round_ids():
    """gt_batch_plan assigns the same rounds as the interleaved planner
    without needing per-round commits."""
    t = native.NativeSlotTable(16)
    keys = ["a", "b", "a", "a", "c", "b"]
    p = native.NativeBatchPlanner(t, keys, 100)
    round_id, slots, exists, n_rounds = p.plan()
    assert n_rounds == 3
    assert list(round_id) == [0, 0, 1, 2, 0, 1]
    # First occurrences are misses; chained occurrences trust the device.
    assert list(exists) == [False, False, True, True, False, True]
    assert slots[0] == slots[2] == slots[3]
    assert slots[1] == slots[5]
    # commit_plan folds the last write per key into the table.
    exp = np.arange(100, 106, dtype=np.int64) + 1000
    p.commit_plan(exp, np.zeros(6, np.uint8))
    assert t.lookup_or_assign("a", 1100) == (int(slots[3]), True)  # expire 1103


def test_reset_remaining_then_hit_same_batch():
    """Token RESET_REMAINING followed by hits on the same key in ONE
    batch: the reset removes the bucket, the next hit recreates it, and
    the recreated bucket must survive into the next batch (the remove-
    then-recreate commit chain)."""
    now = 1_700_000_000_000
    a = ShardStore(capacity=32, use_native=True)
    b = ShardStore(capacity=32, use_native=False)
    warm = [_req("rr", hits=4, limit=10)]
    batch = [
        _req("rr", hits=0, behavior=int(Behavior.RESET_REMAINING), limit=10),
        _req("rr", hits=3, limit=10),
    ]
    after = [_req("rr", hits=1, limit=10)]
    for st in (a, b):
        st.apply(warm, now)
        st.apply(batch, now + 1)
        (r,) = st.apply(after, now + 2)
        assert r.remaining == 6, r  # 10 - 3 - 1: recreation persisted
    assert a.table.get_slot("nat_rr") is not None


def test_plan_path_overlimit_chain():
    """Duplicate chain crossing the limit: k-th request sees (k-1)-th's
    state exactly as the mutex-serialized reference would."""
    now = 1_700_000_000_000
    a = ShardStore(capacity=32, use_native=True)
    b = ShardStore(capacity=32, use_native=False)
    # remaining=5: [hits=7 OVER no-mutate, hits=3 UNDER ->2, hits=3 OVER, hits=2 UNDER ->0]
    reqs = [_req("ol", hits=h, limit=5) for h in (7, 3, 3, 2)]
    ra, rb = a.apply(reqs, now), b.apply(reqs, now)
    assert ra == rb
    assert [r.status for r in ra] == [1, 0, 1, 0]
    assert [r.remaining for r in ra] == [5, 2, 2, 0]


def test_plan_path_random_stress_vs_python():
    """Randomized mixed workload (dups, resets, algo switches, expiry,
    capacity pressure) through the single-dispatch path vs the Python
    twin."""
    now = 1_700_000_000_000
    a = ShardStore(capacity=16, use_native=True)
    b = ShardStore(capacity=16, use_native=False)
    rng = np.random.RandomState(11)
    for t in range(30):
        reqs = []
        for _ in range(24):
            behavior = int(Behavior.RESET_REMAINING) if rng.random() < 0.1 else 0
            reqs.append(
                _req(
                    f"s{rng.randint(0, 10)}",
                    hits=int(rng.randint(0, 4)),
                    limit=6,
                    duration=int(rng.choice([200, 5000])),
                    algo=Algorithm(int(rng.randint(0, 2))),
                    behavior=behavior,
                )
            )
        step = now + t * 150
        ra, rb = a.apply(reqs, step), b.apply(reqs, step)
        assert ra == rb, t
    assert sorted(a.table.keys()) == sorted(b.table.keys())


def test_eviction_skips_pending_write_slots():
    """Under capacity pressure, LRU eviction must not steal a slot whose
    device write from an earlier un-resolved (pipelined) batch is still
    in flight — doing so silently drops that batch's device state
    (advisor finding, host_runtime.cpp lookup_or_assign)."""
    from gubernator_tpu.models.shard import _Columns

    nat = native.NativeSlotTable(4)
    now = 1000

    # Batch A plans k0,k1: their slots carry pending writes until commit.
    cols = _Columns(2)
    cols.algo[:] = 0
    cols.behavior[:] = 0
    cols.hits[:] = 1
    cols.limit[:] = 10
    cols.duration[:] = 60_000
    planner = native.NativeBatchPlanner(nat, ["k0", "k1"], now)
    _, slots_a, _, _, _, _ = planner.plan_grouped(cols, int(Behavior.RESET_REMAINING))
    pending = set(int(s) for s in slots_a)

    # Fill the rest of the capacity with committed keys.
    s2, _ = nat.lookup_or_assign("k2", now)
    s3, _ = nat.lookup_or_assign("k3", now)
    nat.set_expire(s2, now + 60_000)
    nat.set_expire(s3, now + 60_000)

    # Table full; a new key must evict — but NOT a pending slot, even
    # though k0/k1 are the LRU-coldest entries.
    s4, _ = nat.lookup_or_assign("k4", now)
    assert s4 not in pending
    assert s4 == s2  # first non-pending in LRU order
    assert nat.get_slot("k0") is not None and nat.get_slot("k1") is not None

    # After commit the claims are released: next eviction takes k0.
    planner.commit_plan(
        np.full(2, now + 60_000, dtype=np.int64), np.zeros(2, dtype=np.uint8)
    )
    s5, _ = nat.lookup_or_assign("k5", now)
    assert s5 in pending
    assert nat.get_slot("k0") is None


def test_eviction_falls_back_when_all_pending():
    """When every slot has an in-flight write, eviction degrades to the
    raw LRU head instead of failing."""
    from gubernator_tpu.models.shard import _Columns

    nat = native.NativeSlotTable(2)
    now = 1000
    cols = _Columns(2)
    cols.algo[:] = 0
    cols.behavior[:] = 0
    cols.hits[:] = 1
    cols.limit[:] = 10
    cols.duration[:] = 60_000
    planner = native.NativeBatchPlanner(nat, ["k0", "k1"], now)
    planner.plan_grouped(cols, int(Behavior.RESET_REMAINING))

    s, exists = nat.lookup_or_assign("k2", now)
    assert not exists
    assert 0 <= s < 2  # evicted the LRU head despite the pending claim


def test_passthrough_reset_survives_pipelined_eviction(monkeypatch):
    """The narrow-wire keep-sentinel reconstructs an unchanged reset_time
    from the host expiry mirror; that value must be snapshotted at
    dispatch time, because a later pipelined batch's planning can evict
    and reassign the slot (zeroing expire_ms) before the earlier batch
    resolves (advisor finding, shard.py _dispatch_columns).

    The sentinel itself only fires for far-future expiries the i32 wire
    can't carry, so instead of driving the kernel there this asserts the
    snapshot timing directly: the expiry array handed to unpack_output32
    must hold dispatch-time values even when the table mutates before
    resolve."""
    from gubernator_tpu.ops import buckets

    now = 1_700_000_000_000
    st = ShardStore(capacity=4, use_native=True)

    def cols_for(key, hits):
        return dict(
            keys=[key], algorithm=[0], behavior=[0], hits=[hits],
            limit=[10], duration=[60_000],
        )

    # Create "a": reset = now + 60s, committed.
    r0 = st.apply_columns(**cols_for("a", 1), now_ms=now)
    assert int(r0["reset_time"][0]) == now + 60_000
    slot_a = st.table.get_slot(st.table.keys()[0])

    captured = []
    real_unpack = buckets.unpack_output32

    def spy(packed, now_ms, table_expire):
        captured.append(np.array(table_expire, copy=True))
        return real_unpack(packed, now_ms, table_expire)

    monkeypatch.setattr(buckets, "unpack_output32", spy)

    # Dispatch a status query on "a", then clobber the table's expiry
    # (as a later pipelined batch's eviction would) BEFORE resolving.
    ha = st.apply_columns_async(**cols_for("a", 0), now_ms=now + 1)
    st.table.set_expire(slot_a, 0)
    ra = ha.result()

    assert len(captured) == 1
    # Snapshot taken at dispatch: pre-clobber value.
    assert int(captured[0][0]) == now + 60_000
    assert int(ra["remaining"][0]) == 9
    assert int(ra["reset_time"][0]) == now + 60_000
