"""Cost observatory (profiling.py + the metrics/gateway/service/config
wiring): the continuous host sampler (scope/tag fold semantics, the
compiled-out discipline, the named-attribution integration gate), the
per-tenant cost ledger (Zipf-oracle accuracy, exact other-rollup
conservation through promotion/eviction churn, bounded metric
cardinality under 10k distinct names, the audit-pairing rule), the
/debug/pprof & /debug/tenants surfaces, the /debug/profile host-window
pairing, config plumbing, and the bench-history trend gate."""

import importlib.util
import json
import os
import threading
import time

import numpy as np
import pytest

from gubernator_tpu import audit as audit_mod
from gubernator_tpu import profiling, saturation, tracing
from gubernator_tpu.gateway import handle_request
from gubernator_tpu.metrics import Metrics
from gubernator_tpu.service import (
    ColumnarResult,
    IngressColumns,
    ServiceConfig,
    V1Service,
)
from gubernator_tpu.types import PeerInfo, RateLimitResponse


@pytest.fixture(autouse=True)
def _clean_plane():
    prev = profiling.enabled()
    profiling.set_enabled(True)
    profiling.reset()
    saturation.reset()
    yield
    profiling.reset()
    saturation.reset()
    profiling.set_enabled(prev)


def _cols(names, hits=None, uk=None):
    n = len(names)
    return IngressColumns(
        names=list(names),
        unique_keys=list(uk) if uk is not None else [f"k{i}" for i in range(n)],
        algorithm=np.zeros(n, np.int32),
        behavior=np.zeros(n, np.int32),
        hits=(
            np.asarray(hits, np.int64) if hits is not None
            else np.ones(n, np.int64)
        ),
        limit=np.full(n, 1_000_000, np.int64),
        duration=np.full(n, 3_600_000, np.int64),
    )


def _service(**kw):
    svc = V1Service(ServiceConfig(cache_size=512, **kw))
    svc.set_peers([PeerInfo(grpc_address="127.0.0.1:1", is_owner=True)])
    return svc


def _assert_conserves(snap):
    """The rollup invariant the ledger promises: for every stat,
    top-K rows + other == totals EXACTLY (audit-style, but two-sided
    because nothing in the ledger is lag-tolerant)."""
    for stat in ("hits", "lanes", "overLimit", "shed", "ingressBytes"):
        parts = sum(r[stat] for r in snap["topk"]) + snap["other"][stat]
        assert parts == snap["totals"][stat], (stat, snap)


# ---------------------------------------------------------------------
# Sampler: scopes, tags, fold, compiled-out discipline
# ---------------------------------------------------------------------
def test_scope_nesting_restores_and_pops():
    ident = threading.get_ident()
    assert ident not in profiling._scopes
    with profiling.scope("ingress.parse"):
        assert profiling._scopes[ident] == "ingress.parse"
        with profiling.scope("response.encode"):
            assert profiling._scopes[ident] == "response.encode"
        assert profiling._scopes[ident] == "ingress.parse"
    # Outermost exit POPS (thread idents recycle; a parked None would
    # leak an entry per pool thread).
    assert ident not in profiling._scopes


def test_scope_disabled_is_shared_noop():
    profiling.set_enabled(False)
    try:
        s1 = profiling.scope("ingress.parse")
        s2 = profiling.scope("dispatch.launch")
        assert s1 is s2  # the one-branch compiled-out contract
        with s1:
            assert threading.get_ident() not in profiling._scopes
    finally:
        profiling.set_enabled(True)


def test_sampler_folds_scoped_and_tagged_threads():
    ready = threading.Event()
    release = threading.Event()

    def scoped_worker():
        with profiling.scope("dispatch.launch"):
            ready.set()
            release.wait(10)

    def tagged_worker():
        profiling.tag_thread("epoll.wait")
        profiling.set_program("mesh.solo.narrow")
        ready2.set()
        release.wait(10)

    ready2 = threading.Event()
    t1 = threading.Thread(target=scoped_worker, name="scoped")
    t2 = threading.Thread(target=tagged_worker, name="tagged")
    t1.start(), t2.start()
    assert ready.wait(10) and ready2.wait(10)
    s = profiling.Sampler()  # not started: driven manually
    try:
        for _ in range(5):
            s.sample_once()
    finally:
        release.set()
        t1.join(), t2.join()
    win = s.merged(60)
    assert win.samples > 0
    assert win.phases.get("dispatch.launch", 0) >= 5
    assert win.phases.get("epoll.wait", 0) >= 5
    # The program label rides beside the phase (the PR 9 mirror).
    assert win.programs.get("mesh.solo.narrow", 0) >= 5
    # Collapsed lines carry phase;stack count and fold the wait frames.
    stacks = {tag for (tag, _stack) in win.stacks}
    assert "dispatch.launch" in stacks and "epoll.wait" in stacks


def test_worker_suffix_strip_folds_pools():
    assert profiling._strip_worker_suffix("ThreadPoolExecutor-0_3") == (
        "ThreadPoolExecutor-0"
    )
    assert profiling._strip_worker_suffix("drainer-7") == "drainer"
    assert profiling._strip_worker_suffix("epoll") == "epoll"
    # All-digit names survive (never fold to the empty tag).
    assert profiling._strip_worker_suffix("123") == "123"


def test_profile_snapshot_and_collapsed_render():
    release = threading.Event()
    started = threading.Event()

    def worker():
        with profiling.scope("ingress.parse"):
            started.set()
            release.wait(10)

    t = threading.Thread(target=worker)
    t.start()
    assert started.wait(10)
    s = profiling._get_sampler(start=True)
    try:
        for _ in range(8):
            s.sample_once()
    finally:
        release.set()
        t.join()
    doc = profiling.profile_snapshot(seconds=60, top=5)
    assert doc["samples"] > 0
    assert doc["phases"].get("ingress.parse", 0) >= 8
    assert len(doc["topStacks"]) <= 5
    assert doc["namedFraction"] > 0
    text = profiling.collapsed(60)
    lines = [ln for ln in text.splitlines() if ln]
    assert lines, text
    for ln in lines:
        stack, _, count = ln.rpartition(" ")
        assert stack and count.isdigit(), ln


# ---------------------------------------------------------------------
# Tenant ledger: Zipf oracle, conservation, cardinality
# ---------------------------------------------------------------------
def test_tenant_zipf_oracle_within_sketch_error():
    rng = np.random.RandomState(11)
    n_names, n_lanes = 2000, 40_000
    ranks = np.minimum(
        rng.zipf(1.3, size=n_lanes) - 1, n_names - 1
    ).astype(np.int64)
    names = [f"tenant:{r}" for r in range(n_names)]
    true_counts = np.bincount(ranks, minlength=n_names)
    led = profiling.TenantLedger(topk=8, width=4096, depth=4)
    for lo in range(0, n_lanes, 1000):
        batch = ranks[lo:lo + 1000]
        led.fold_admit(_cols([names[r] for r in batch]))
    snap = led.snapshot()
    assert snap["totals"]["hits"] == n_lanes
    assert snap["totals"]["lanes"] == n_lanes
    _assert_conserves(snap)
    got = {r["tenant"]: r for r in snap["topk"]}
    true_top = np.argsort(true_counts)[::-1]
    # The heaviest tenants must be tracked, with count-min's one-sided
    # error on the ranking estimate: estimate >= truth, within a small
    # overcount of total traffic.
    for r in true_top[:3]:
        name = names[int(r)]
        assert name in got, (name, list(got)[:8])
        assert got[name]["estimate"] >= true_counts[r]
        assert got[name]["estimate"] <= true_counts[r] + n_lanes * 0.01


def test_tenant_cardinality_bounded_under_10k_names():
    led = profiling.TenantLedger(topk=8, width=4096, depth=4)
    # 10k distinct names, one lane each, folded in column batches.
    for lo in range(0, 10_000, 500):
        led.fold_admit(_cols([f"n{i}" for i in range(lo, lo + 500)]))
    snap = led.snapshot()
    assert snap["trackedTenants"] <= 8
    assert len(snap["topk"]) <= 8
    _assert_conserves(snap)

    # And the EXPORTED cardinality holds: <= K tenant label values on
    # gubernator_tenant_cost plus the single `other` rollup family.
    class _Svc:
        tenants = led

    m = Metrics()
    m.observe_cost(_Svc())
    text = m.render().decode()
    tenants = {
        line.split('tenant="', 1)[1].split('"', 1)[0]
        for line in text.splitlines()
        if line.startswith("gubernator_tenant_cost{")
    }
    assert 0 < len(tenants) <= 8, tenants
    assert "gubernator_tenant_other" in text
    assert "gubernator_tenant_total" in text


def test_tenant_conservation_through_eviction_churn():
    led = profiling.TenantLedger(topk=2, width=256, depth=2)
    rng = np.random.RandomState(3)
    # Rotating hot tenants force promote/evict churn at topk=2; the
    # rollup must conserve after EVERY batch, not just at the end.
    for round_ in range(30):
        hot = f"hot{round_ % 5}"
        names = [hot] * 40 + [f"cold{rng.randint(50)}" for _ in range(10)]
        led.fold_admit(_cols(names, hits=rng.randint(1, 4, size=50)))
        _assert_conserves(led.snapshot())


def test_tenant_outcome_and_shed_folds():
    led = profiling.TenantLedger(topk=4)
    cols = _cols(["a", "a", "b", "c"], hits=[1, 2, 3, 4])
    ctx = led.fold_admit(cols)
    assert ctx is not None
    res = ColumnarResult.empty(4)
    res.status = np.array([1, 0, 1, 0], np.int32)
    # A sparse override flips lane 3 to OVER_LIMIT; lane 0's array says
    # over but an errored override would cancel it.
    res.overrides[3] = RateLimitResponse(status=1)
    led.fold_outcome(ctx, res)
    led.fold_shed(ctx, np.array([0, 1]))  # tenant a sheds two lanes
    snap = led.snapshot()
    rows = {r["tenant"]: r for r in snap["topk"]}
    assert snap["totals"]["hits"] == 10
    assert rows["a"]["overLimit"] == 1  # lane 0 (array)
    assert rows["b"]["overLimit"] == 1  # lane 2 (array)
    assert rows["c"]["overLimit"] == 1  # lane 3 (override)
    assert rows["a"]["shed"] == 2
    assert snap["totals"]["overLimit"] == 3
    assert snap["totals"]["shed"] == 2
    _assert_conserves(snap)
    # overLimitRate derives from lanes.
    assert rows["a"]["overLimitRate"] == pytest.approx(0.5)


def test_tenant_proportional_shares():
    led = profiling.TenantLedger(topk=4)
    led.fold_admit(_cols(["a"] * 30 + ["b"] * 10))
    profiling.note_lane_time(40, 0.8)    # 20 ms/lane
    profiling.note_queue_wait(40, 0.1)   # 0.1 s x 40 lanes / 40 lanes
    snap = led.snapshot()
    rows = {r["tenant"]: r for r in snap["topk"]}
    assert rows["a"]["laneTimeS"] == pytest.approx(30 * 0.02, rel=1e-6)
    assert rows["b"]["laneTimeS"] == pytest.approx(10 * 0.02, rel=1e-6)
    assert rows["a"]["queueS"] == pytest.approx(30 * 0.1, rel=1e-6)
    assert snap["laneTimeSPerLane"] == pytest.approx(0.02, rel=1e-6)


def test_tenant_single_and_dataclass_folds():
    led = profiling.TenantLedger(topk=4)
    led.fold_one("solo", hits=7, nbytes=100)
    snap = led.snapshot()
    assert snap["totals"]["hits"] == 7
    assert snap["totals"]["ingressBytes"] == 100  # pre-computed budget
    names = led.fold_requests([])
    assert names is None
    _assert_conserves(snap)


def test_tenant_scalar_fold_matches_vector_twin():
    """fold_one is a scalar twin of fold_admit: totals and the
    count-min sketch must agree exactly with the batch fold over the
    same lanes, and conservation must hold on both.  (The row/`other`
    SPLIT may differ — promotion moves only the current fold's
    contribution, and the scalar path folds one lane at a time.)"""
    rng = np.random.RandomState(3)
    names = [f"t{rng.zipf(1.3) % 12}" for _ in range(400)]
    uks = [f"k{i}" for i in range(400)]
    hits = rng.randint(1, 5, 400)
    a = profiling.TenantLedger(topk=4)
    b = profiling.TenantLedger(topk=4)
    a.fold_admit(_cols(names, hits=hits, uk=uks))
    for n, u, h in zip(names, uks, hits):
        b.fold_one(n, int(h),
                   len(n) + len(u) + profiling.NUMERIC_LANE_BYTES)
    assert a.totals() == b.totals()
    assert np.array_equal(a._tab, b._tab)
    sa, sb = a.snapshot(), b.snapshot()
    _assert_conserves(sa)
    _assert_conserves(sb)
    # Same est ranking feeds both: the top tenant agrees.
    assert sa["topk"][0]["tenant"] == sb["topk"][0]["tenant"]


# ---------------------------------------------------------------------
# Service pairing: every audit ingress note has a tenant fold beside it
# ---------------------------------------------------------------------
def test_service_tenant_folds_reconcile_with_audit():
    svc = _service()
    try:
        base = audit_mod.ledger_snapshot()
        body = json.dumps({"requests": [
            {"name": f"ten{i % 3}", "uniqueKey": f"k{i}", "hits": "2",
             "limit": "100", "duration": "60000"} for i in range(30)
        ]}).encode()
        st, _, _ = handle_request(svc, "POST", "/v1/GetRateLimits", body)
        assert st == 200
        led = audit_mod.ledger_snapshot()
        ingress_delta = (
            led.get("ingress_hits", 0) - base.get("ingress_hits", 0)
            + led.get("peer_ingress_hits", 0)
            - base.get("peer_ingress_hits", 0)
        )
        totals = svc.tenants.totals()
        assert totals["hits"] == ingress_delta == 60
        assert totals["lanes"] == 30
        snap = svc.tenants.snapshot()
        assert {r["tenant"] for r in snap["topk"]} == {
            "ten0", "ten1", "ten2"
        }
        _assert_conserves(snap)
    finally:
        svc.close()


# ---------------------------------------------------------------------
# /debug surfaces + the >= 80% named-attribution integration gate
# ---------------------------------------------------------------------
def test_pprof_named_fraction_on_loaded_daemon():
    """The acceptance gate: on a daemon under load, >= 80% of profiler
    samples attribute to a NAMED phase/thread tag, not `unknown`."""
    svc = _service()
    stop = threading.Event()

    def worker(wid):
        i = 0
        while not stop.is_set():
            body = json.dumps({"requests": [
                {"name": f"load{wid}", "uniqueKey": f"k{i}:{j}",
                 "hits": "1", "limit": "1000000",
                 "duration": "60000"} for j in range(32)
            ]}).encode()
            handle_request(svc, "POST", "/v1/GetRateLimits", body)
            i += 1

    threads = [
        threading.Thread(target=worker, args=(w,), name=f"load-{w}")
        for w in range(4)
    ]
    s = profiling._get_sampler(start=True)
    try:
        for t in threads:
            t.start()
        deadline = time.time() + 2.0
        while time.time() < deadline:
            s.sample_once()
            time.sleep(0.005)
        st, ctype, payload = handle_request(
            svc, "GET", "/debug/pprof?format=json&seconds=60", b""
        )
        assert st == 200 and ctype == "application/json"
        doc = json.loads(payload)
        assert doc["samples"] >= 40, doc["samples"]
        assert doc["namedFraction"] >= 0.8, doc["phases"]
        # The collapsed view serves the same window as text.
        st, ctype, text = handle_request(
            svc, "GET", "/debug/pprof?seconds=60", b""
        )
        assert st == 200 and ctype.startswith("text/plain")
        assert text.decode().splitlines()
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=30)
        svc.close()


def test_debug_tenants_and_status_surfaces():
    svc = _service()
    try:
        body = json.dumps({"requests": [
            {"name": "acme", "uniqueKey": f"k{i}", "hits": "1",
             "limit": "100", "duration": "60000"} for i in range(8)
        ]}).encode()
        st, _, _ = handle_request(svc, "POST", "/v1/GetRateLimits", body)
        assert st == 200
        st, ctype, payload = handle_request(svc, "GET", "/debug/tenants", b"")
        assert st == 200 and ctype == "application/json"
        doc = json.loads(payload)
        assert doc["topk"][0]["tenant"] == "acme"
        assert doc["topkLimit"] >= 1
        _assert_conserves(doc)
        st, _, payload = handle_request(svc, "GET", "/debug/status", b"")
        status = json.loads(payload)
        assert status["tenants"]["topk"][0]["tenant"] == "acme"
        assert status["profile"]["enabled"] is True
        assert status["profile"]["hz"] == profiling.hz()
        # The scrape carries the new families.
        st, _, metrics = handle_request(svc, "GET", "/metrics", b"")
        text = metrics.decode()
        for fam in ("gubernator_tenant_cost", "gubernator_tenant_other",
                    "gubernator_tenant_total", "gubernator_profile_hz"):
            assert fam in text, fam
    finally:
        svc.close()


def test_debug_profile_pairs_host_window(tmp_path, monkeypatch):
    """POST /debug/profile answers with the host-profiler pairing: the
    live pprof URL covering the same seconds, and the collapsed host
    window written beside the device trace when the run completes."""
    from gubernator_tpu import gateway

    monkeypatch.chdir(tmp_path)
    prev = tracing.sample_rate()
    tracing.set_sample_rate(1.0)
    try:
        st, _, body = gateway.handle_request(
            None, "POST", "/debug/profile", b'{"durationMs": 50}'
        )
        assert st == 202, body
        doc = json.loads(body)
        assert doc["hostPprof"] == "/debug/pprof?seconds=1"
        assert doc["hostProfile"] == f"{doc['logDir']}/host_profile.collapsed"
        t = gateway._profile_state["thread"]
        if t is not None:
            t.join(timeout=60)
        assert os.path.exists(doc["hostProfile"])
    finally:
        tracing.set_sample_rate(prev)


# ---------------------------------------------------------------------
# Config plumbing
# ---------------------------------------------------------------------
def test_config_knobs_loud_validation():
    from gubernator_tpu.config import setup_daemon_config

    conf = setup_daemon_config(env={
        "GUBER_PROFILE": "0", "GUBER_PROFILE_HZ": "101",
        "GUBER_TENANT_TOPK": "32",
    })
    assert conf.behaviors.profile is False
    assert conf.behaviors.profile_hz == 101.0
    assert conf.behaviors.tenant_topk == 32
    # Defaults (the shipped always-on plane).
    conf = setup_daemon_config(env={})
    assert conf.behaviors.profile is True
    assert conf.behaviors.profile_hz == 67.0
    assert conf.behaviors.tenant_topk == 16
    for bad in (
        {"GUBER_PROFILE_HZ": "fast"},
        {"GUBER_PROFILE_HZ": "0"},        # 0 is GUBER_PROFILE=0's job
        {"GUBER_PROFILE_HZ": "5000"},     # loud, not clamped
        {"GUBER_TENANT_TOPK": "0"},
        {"GUBER_TENANT_TOPK": "99999"},
        {"GUBER_TENANT_TOPK": "many"},
    ):
        with pytest.raises(ValueError):
            setup_daemon_config(env=bad)


def test_service_tenant_topk_from_behaviors():
    from gubernator_tpu.cluster import fast_test_behaviors

    beh = fast_test_behaviors()
    beh.tenant_topk = 3
    svc = V1Service(ServiceConfig(cache_size=512, behaviors=beh))
    try:
        assert svc.tenants.topk == 3
    finally:
        svc.close()


# ---------------------------------------------------------------------
# Bench gate row + bench-history trend tooling
# ---------------------------------------------------------------------
def test_gate_thresholds_carry_profiling_floor():
    with open("benchmarks/gate_thresholds.json") as f:
        th = json.load(f)
    assert th["profiling_overhead_ratio"]["fail_below"] == 0.95


def _load_trend():
    spec = importlib.util.spec_from_file_location(
        "bench_trend", os.path.join("scripts", "bench_trend.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_bench_trend_helpers():
    bt = _load_trend()
    assert bt.median([3.0, 1.0, 2.0]) == 2.0
    assert bt.median([4.0, 1.0, 2.0, 3.0]) == 2.5
    assert bt.lower_is_better("service_ingress_latency_ms_p99")
    assert bt.lower_is_better("device_batch_us")
    assert not bt.lower_is_better("service_ingress_checks_per_sec")
    assert len(bt.spark([1, 2, 3])) == 3


def _write_history(tmp_path, rows):
    hist = tmp_path / "benchmarks" / "history"
    hist.mkdir(parents=True)
    for i, row in enumerate(rows):
        row.setdefault("time", float(i + 1))
        (hist / f"run{i}.json").write_text(json.dumps(row))


def test_bench_trend_regression_gate(tmp_path, monkeypatch, capsys):
    bt = _load_trend()
    monkeypatch.setattr(bt, "REPO", str(tmp_path))
    _write_history(tmp_path, [
        {"backend": "cpu", "service_ingress_checks_per_sec": 100_000.0},
        {"backend": "cpu", "service_ingress_checks_per_sec": 110_000.0},
        {"backend": "cpu", "service_ingress_checks_per_sec": 105_000.0},
        # Newest: >20% below the rolling median (105k) -> FAIL.
        {"backend": "cpu", "service_ingress_checks_per_sec": 70_000.0},
    ])
    monkeypatch.setattr("sys.argv", ["bench_trend.py"])
    assert bt.main() == 1
    out = capsys.readouterr().out
    assert "REGRESSION" in out and "service_ingress_checks_per_sec" in out
    # --no-gate always passes (the readable-history mode).
    monkeypatch.setattr("sys.argv", ["bench_trend.py", "--no-gate"])
    assert bt.main() == 0


def test_bench_trend_backend_partition_and_small_n(tmp_path, monkeypatch):
    bt = _load_trend()
    monkeypatch.setattr(bt, "REPO", str(tmp_path))
    # The fast prior runs are TPU; the slow newest is CPU — not
    # comparable, and a single same-backend prior is weather, not a
    # trend: both rules must keep the gate green.
    _write_history(tmp_path, [
        {"backend": "tpu", "service_ingress_checks_per_sec": 1_000_000.0},
        {"backend": "tpu", "service_ingress_checks_per_sec": 1_100_000.0},
        {"backend": "cpu", "service_ingress_checks_per_sec": 90_000.0},
        {"backend": "cpu", "service_ingress_checks_per_sec": 50_000.0},
    ])
    monkeypatch.setattr("sys.argv", ["bench_trend.py"])
    assert bt.main() == 0


def test_bench_trend_lower_is_better_and_noise(tmp_path, monkeypatch):
    bt = _load_trend()
    monkeypatch.setattr(bt, "REPO", str(tmp_path))
    _write_history(tmp_path, [
        {"backend": "cpu", "device_batch_us": 100.0},
        {"backend": "cpu", "device_batch_us": 110.0},
        {"backend": "cpu", "device_batch_us": 105.0},
        # 40% above the median: a latency regression...
        {"backend": "cpu", "device_batch_us": 147.0,
         # ...but the recorded noise covers the excess -> inconclusive,
         # never a FAIL (the bench-gate SKIP discipline).
         "device_batch_us_noise_us": 50.0},
    ])
    monkeypatch.setattr("sys.argv", ["bench_trend.py"])
    assert bt.main() == 0
    # Without the noise allowance the same run fails.
    hist = tmp_path / "benchmarks" / "history"
    row = json.loads((hist / "run3.json").read_text())
    del row["device_batch_us_noise_us"]
    (hist / "run3.json").write_text(json.dumps(row))
    assert bt.main() == 1


def test_bench_appends_history(tmp_path, monkeypatch):
    import bench

    monkeypatch.chdir(tmp_path)
    bench.append_history({"metric": "rate_limit_checks_per_sec",
                          "value": 123.0})
    files = list((tmp_path / "benchmarks" / "history").glob("*.json"))
    assert len(files) == 1
    row = json.loads(files[0].read_text())
    assert row["value"] == 123.0
    assert row["backend"]  # jax backend stamped
    assert "git_sha" in row and "time" in row
