"""K8sPool discovery tests against an in-process fake Kubernetes API
server speaking the list+watch protocol (reference kubernetes.go, which
is exercised against a real cluster via k8s-deployment.yaml).
"""

import json
import queue
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

import pytest

from gubernator_tpu.config import setup_daemon_config
from gubernator_tpu.k8s_pool import (
    K8sApiClient,
    K8sPool,
    watch_mechanism_from_string,
)


def wait_until(fn, timeout_s=5.0, every_s=0.02, msg="condition"):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if fn():
            return
        time.sleep(every_s)
    raise AssertionError(f"timed out waiting for {msg}")


class FakeK8sApi:
    """Serves LIST and WATCH for a namespaced resource with the real
    apiserver's conformance surfaces (round-4 verdict: 410-Gone,
    bookmarks, chunked lists were unproven): list honors limit= +
    continue= pagination; watch streams queued events as JSON lines,
    answers a resourceVersion older than `compacted_rv` with a 410 Gone
    ERROR event (the reflector relist trigger), and can interleave
    BOOKMARK events."""

    def __init__(self):
        self.items = {}  # (resource, name) -> object
        self.rv = 10
        self.compacted_rv = 0  # watch rv < this -> 410 Gone ERROR event
        self.lists_served = 0  # pagination observability for tests
        self._watchers = []  # (resource, queue)
        self._lock = threading.Lock()
        fake = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # quiet
                pass

            def do_GET(self):
                parsed = urlparse(self.path)
                params = parse_qs(parsed.query)
                resource = parsed.path.rsplit("/", 1)[-1]
                if params.get("watch", ["false"])[0] == "true":
                    self._serve_watch(resource, params)
                else:
                    self._serve_list(resource, params)

            def _serve_list(self, resource, params):
                limit = int(params.get("limit", ["0"])[0] or 0)
                cont = int(params.get("continue", ["0"])[0] or 0)
                with fake._lock:
                    fake.lists_served += 1
                    items = [
                        o for (r, _), o in sorted(fake.items.items()) if r == resource
                    ]
                    meta = {"resourceVersion": str(fake.rv)}
                    if limit and cont + limit < len(items):
                        # apiserver chunking: opaque continue token (here
                        # just the offset) + the SAME resourceVersion for
                        # every chunk of one logical list.
                        meta["continue"] = str(cont + limit)
                        items = items[cont:cont + limit]
                    elif limit:
                        items = items[cont:]
                    body = json.dumps({"items": items, "metadata": meta}).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _serve_watch(self, resource, params):
                rv = int(params.get("resourceVersion", ["0"])[0] or 0)
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()

                def send(event):
                    line = (json.dumps(event) + "\n").encode()
                    self.wfile.write(f"{len(line):x}\r\n".encode())
                    self.wfile.write(line + b"\r\n")
                    self.wfile.flush()

                with fake._lock:
                    stale = fake.compacted_rv and rv < fake.compacted_rv
                if stale:
                    # Real apiserver: watch from a compacted rv gets one
                    # ERROR event with a 410 Status, then EOF.
                    try:
                        send({
                            "type": "ERROR",
                            "object": {
                                "kind": "Status", "code": 410,
                                "reason": "Expired",
                                "message": "too old resource version",
                            },
                        })
                        self.wfile.write(b"0\r\n\r\n")
                    except OSError:
                        pass
                    self.close_connection = True
                    return
                q = queue.Queue()
                with fake._lock:
                    fake._watchers.append((resource, q))
                try:
                    while True:
                        try:
                            event = q.get(timeout=0.1)
                        except queue.Empty:
                            continue
                        if event is None:
                            # Clean server-side stream end: terminate the
                            # chunked body, else a keep-alive connection
                            # leaves the client blocked in readline.
                            self.wfile.write(b"0\r\n\r\n")
                            self.close_connection = True
                            break
                        send(event)
                except OSError:
                    pass
                finally:
                    with fake._lock:
                        fake._watchers.remove((resource, q))

        self._server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self._server.daemon_threads = True
        self.url = f"http://127.0.0.1:{self._server.server_port}"
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True, kwargs={"poll_interval": 0.05}
        )
        self._thread.start()

    def emit(self, resource, etype, obj):
        """Mutate state + push a watch event."""
        with self._lock:
            self.rv += 1
            obj.setdefault("metadata", {})["resourceVersion"] = str(self.rv)
            key = (resource, obj["metadata"].get("name", ""))
            if etype == "DELETED":
                self.items.pop(key, None)
            else:
                self.items[key] = obj
            for r, q in self._watchers:
                if r == resource:
                    q.put({"type": etype, "object": obj})

    def emit_bookmark(self, resource):
        """Push a BOOKMARK progress event (allowWatchBookmarks surface):
        carries only a resourceVersion, never membership data."""
        with self._lock:
            for r, q in self._watchers:
                if r == resource:
                    q.put({
                        "type": "BOOKMARK",
                        "object": {"metadata": {"resourceVersion": str(self.rv)}},
                    })

    def compact(self, rv=None):
        """Age out watch history: watches from below rv get 410 Gone."""
        with self._lock:
            self.compacted_rv = self.rv if rv is None else rv

    def kill_watchers(self):
        with self._lock:
            for _, q in self._watchers:
                q.put(None)

    def n_watchers(self):
        with self._lock:
            return len(self._watchers)

    def stop(self):
        with self._lock:
            for _, q in self._watchers:
                q.put(None)
        self._server.shutdown()
        self._server.server_close()


@pytest.fixture
def api():
    s = FakeK8sApi()
    yield s
    s.stop()


def endpoints_obj(name, ips):
    return {
        "metadata": {"name": name, "namespace": "default"},
        "subsets": [{"addresses": [{"ip": ip} for ip in ips]}],
    }


def pod_obj(name, ip, ready=True, running=True):
    state = {"running": {}} if running else {"waiting": {}}
    return {
        "metadata": {"name": name, "namespace": "default"},
        "status": {
            "podIP": ip,
            "containerStatuses": [{"ready": ready, "state": state}],
        },
    }


def make_pool(api, updates, **kw):
    kw.setdefault("mechanism", "endpoints")
    return K8sPool(
        on_update=updates.append,
        pod_port="81",
        api_client=K8sApiClient(api_url=api.url),
        backoff_s=0.05,
        **kw,
    )


def test_mechanism_parse():
    assert watch_mechanism_from_string("") == "endpoints"
    assert watch_mechanism_from_string("pods") == "pods"
    with pytest.raises(ValueError):
        watch_mechanism_from_string("nodes")


def test_endpoints_list_and_watch(api):
    api.emit("endpoints", "ADDED", endpoints_obj("guber", ["10.0.0.1"]))
    updates = []
    pool = make_pool(api, updates, pod_ip="10.0.0.1")
    try:
        wait_until(
            lambda: updates
            and [p.grpc_address for p in updates[-1]] == ["10.0.0.1:81"],
            msg="initial list lands",
        )
        assert updates[-1][0].is_owner
        # A scale-up arrives via the watch stream.
        api.emit("endpoints", "MODIFIED", endpoints_obj("guber", ["10.0.0.1", "10.0.0.2"]))
        wait_until(
            lambda: updates
            and [p.grpc_address for p in updates[-1]]
            == ["10.0.0.1:81", "10.0.0.2:81"],
            msg="watch event adds the new address",
        )
        api.emit("endpoints", "DELETED", endpoints_obj("guber", []))
        wait_until(
            lambda: updates and updates[-1] == [], msg="deletion empties the peer list"
        )
    finally:
        pool.close()


def test_pods_watch_skips_not_ready(api):
    api.emit("pods", "ADDED", pod_obj("a", "10.0.0.1"))
    api.emit("pods", "ADDED", pod_obj("b", "10.0.0.2", ready=False))
    api.emit("pods", "ADDED", pod_obj("c", "10.0.0.3", running=False))
    updates = []
    pool = make_pool(api, updates, mechanism="pods")
    try:
        wait_until(
            lambda: updates
            and [p.grpc_address for p in updates[-1]] == ["10.0.0.1:81"],
            msg="only the ready+running pod is a peer",
        )
        api.emit("pods", "MODIFIED", pod_obj("b", "10.0.0.2"))
        wait_until(
            lambda: updates
            and [p.grpc_address for p in updates[-1]]
            == ["10.0.0.1:81", "10.0.0.2:81"],
            msg="pod becoming ready joins",
        )
    finally:
        pool.close()


def test_watch_stream_failure_relists(api):
    api.emit("endpoints", "ADDED", endpoints_obj("guber", ["10.0.0.1"]))
    updates = []
    pool = make_pool(api, updates)
    try:
        wait_until(lambda: api.n_watchers() == 1, msg="watch established")
        # Kill the stream server-side; mutate state while no watch is
        # active; the pool must relist and converge anyway.
        api.emit("endpoints", "MODIFIED", endpoints_obj("guber", ["10.0.0.9"]))
        with api._lock:
            for _, q in api._watchers:
                q.put(None)
        wait_until(
            lambda: updates
            and [p.grpc_address for p in updates[-1]] == ["10.0.0.9:81"],
            msg="relist after stream failure",
        )
    finally:
        pool.close()


def test_k8s_env_parsing():
    conf = setup_daemon_config(
        env={
            "GUBER_PEER_DISCOVERY_TYPE": "k8s",
            "GUBER_K8S_NAMESPACE": "rate-limits",
            "GUBER_K8S_POD_IP": "10.9.9.9",
            "GUBER_K8S_POD_PORT": "1051",
            "GUBER_K8S_ENDPOINTS_SELECTOR": "app=gubernator",
            "GUBER_K8S_WATCH_MECHANISM": "pods",
        }
    )
    assert conf.k8s_namespace == "rate-limits"
    assert conf.k8s_pod_ip == "10.9.9.9"
    assert conf.k8s_pod_port == "1051"
    assert conf.k8s_selector == "app=gubernator"
    assert conf.k8s_mechanism == "pods"


def test_k8s_selector_required():
    with pytest.raises(ValueError, match="ENDPOINTS_SELECTOR"):
        setup_daemon_config(env={"GUBER_PEER_DISCOVERY_TYPE": "k8s"})


def test_kubeconfig_local_mode(tmp_path, monkeypatch):
    """Out-of-cluster client from a kubeconfig file
    (kubernetesconfig_local.go:1-38 parity): server/CA/token from the
    current-context chain; inline base64 *-data materializes to files;
    $KUBECONFIG is honored by auto() outside a cluster."""
    import base64

    from gubernator_tpu.k8s_pool import K8sApiClient
    from gubernator_tpu.tls import self_ca

    ca_crt, _ = self_ca(str(tmp_path))
    ca_pem = open(ca_crt, "rb").read()
    kc = tmp_path / "config"
    kc.write_text(
        "\n".join([
            "apiVersion: v1",
            "kind: Config",
            "current-context: dev",
            "contexts:",
            "- name: dev",
            "  context: {cluster: devc, user: devu}",
            "- name: other",
            "  context: {cluster: devc, user: devu}",
            "clusters:",
            "- name: devc",
            "  cluster:",
            "    server: https://k8s.example:6443",
            f"    certificate-authority-data: {base64.b64encode(ca_pem).decode()}",
            "users:",
            "- name: devu",
            "  user:",
            "    token: sekret",
        ])
    )
    client = K8sApiClient.from_kubeconfig(str(kc))
    assert client.api_url == "https://k8s.example:6443"
    assert client.token == "sekret"
    assert client._ssl_ctx is not None

    # auto() outside a cluster follows $KUBECONFIG
    monkeypatch.delenv("KUBERNETES_SERVICE_HOST", raising=False)
    monkeypatch.setenv("KUBECONFIG", str(kc))
    auto = K8sApiClient.auto()
    assert auto.api_url == "https://k8s.example:6443"

    # unknown context name errors clearly
    with pytest.raises(ValueError, match="contexts"):
        K8sApiClient.from_kubeconfig(str(kc), context="missing")


def test_kubeconfig_http_server_no_tls(tmp_path):
    from gubernator_tpu.k8s_pool import K8sApiClient

    kc = tmp_path / "config"
    kc.write_text(
        "\n".join([
            "current-context: dev",
            "contexts:",
            "- name: dev",
            "  context: {cluster: c, user: u}",
            "clusters:",
            "- name: c",
            "  cluster: {server: 'http://127.0.0.1:8001'}",
            "users:",
            "- name: u",
            "  user: {}",
        ])
    )
    client = K8sApiClient.from_kubeconfig(str(kc))
    assert client.api_url == "http://127.0.0.1:8001"
    assert client._ssl_ctx is None


def test_kubeconfig_client_cert_relative_paths(tmp_path):
    """Client-certificate auth with RELATIVE paths: clientcmd resolves
    them against the kubeconfig's own directory, and so do we; the ssl
    context must actually load the chain (a bad key errors here)."""
    from gubernator_tpu.k8s_pool import K8sApiClient
    from gubernator_tpu.tls import self_ca, self_cert

    ca_crt, ca_key = self_ca(str(tmp_path))
    crt, key = self_cert(str(tmp_path), ca_crt, ca_key, name="client", client=True)
    kc = tmp_path / "config"
    kc.write_text(
        "\n".join([
            "current-context: dev",
            "contexts:",
            "- name: dev",
            "  context: {cluster: c, user: u}",
            "clusters:",
            "- name: c",
            "  cluster:",
            "    server: https://k8s.example:6443",
            "    certificate-authority: ca.crt",  # relative to kubeconfig dir
            "users:",
            "- name: u",
            "  user:",
            "    client-certificate: client.crt",
            "    client-key: client.key",
        ])
    )
    client = K8sApiClient.from_kubeconfig(str(kc))
    assert client._ssl_ctx is not None  # chain loaded without error


def test_kubeconfig_exec_auth_rejected(tmp_path):
    from gubernator_tpu.k8s_pool import K8sApiClient

    kc = tmp_path / "config"
    kc.write_text(
        "\n".join([
            "current-context: dev",
            "contexts:",
            "- name: dev",
            "  context: {cluster: c, user: u}",
            "clusters:",
            "- name: c",
            "  cluster: {server: 'https://k8s.example:6443'}",
            "users:",
            "- name: u",
            "  user:",
            "    exec: {command: aws}",
        ])
    )
    with pytest.raises(ValueError, match="exec"):
        K8sApiClient.from_kubeconfig(str(kc))


def test_chunked_list_pagination(api):
    """Conformance: the reflector LIST is chunked (limit= + continue=);
    every chunk of one logical list shares a resourceVersion and the
    client must merge them (kubernetes.go:107-134's client-go does this
    inside List()).  6 pods at page size 4 -> 2 chunks."""
    for i in range(6):
        api.emit("pods", "ADDED", pod_obj(f"p{i}", f"10.0.1.{i}"))
    client = K8sApiClient(api_url=api.url)
    client.LIST_LIMIT = 4
    before = api.lists_served
    items, rv = client.list("default", "pods")
    assert len(items) == 6
    assert api.lists_served - before == 2  # two chunks actually served
    assert rv == str(api.rv)
    # And the pool end-to-end with a paginated list:
    updates = []
    pool = make_pool(api, updates, mechanism="pods", pod_ip="10.0.1.0")
    pool.client.LIST_LIMIT = 4
    try:
        wait_until(
            lambda: updates and len(updates[-1]) == 6,
            msg="all six pods via chunked list",
        )
    finally:
        pool.close()


def test_watch_410_gone_triggers_relist(api):
    """Conformance: a watch from a compacted resourceVersion is answered
    with ONE 410-Status ERROR event then EOF; the informer must relist
    and converge (kubernetes.go:174-186's reflector behavior)."""
    api.emit("endpoints", "ADDED", endpoints_obj("guber", ["10.0.0.1"]))
    updates = []
    pool = make_pool(api, updates, pod_ip="10.0.0.1")
    try:
        wait_until(lambda: bool(updates), msg="initial list")
        # Compact BEYOND the current rv and kill the live stream: every
        # re-watch now starts below the compaction point and gets the
        # 410 ERROR event, so the informer sits in its 410 -> relist
        # loop (this is the surface under test).  Then membership
        # changes advance the rv past the compaction; the next
        # relist+watch goes live and must converge.
        api.compact(api.rv + 3)
        api.kill_watchers()
        time.sleep(0.2)  # several 410->relist cycles at backoff_s=0.05
        for n, ips in enumerate((
            ["10.0.0.1", "10.0.0.2"],
            ["10.0.0.1", "10.0.0.2", "10.0.0.3"],
            ["10.0.0.1", "10.0.0.2", "10.0.0.3"],
        )):
            api.emit("endpoints", "MODIFIED", endpoints_obj("guber", ips))
        assert api.rv >= api.compacted_rv
        wait_until(
            lambda: updates
            and [p.grpc_address for p in updates[-1]]
            == ["10.0.0.1:81", "10.0.0.2:81", "10.0.0.3:81"],
            msg="membership recovered after 410 Gone",
        )
    finally:
        pool.close()


def test_bookmark_events_ignored(api):
    """Conformance: BOOKMARK progress events carry no membership and
    must not disturb the store or fire spurious updates."""
    api.emit("endpoints", "ADDED", endpoints_obj("guber", ["10.0.0.1"]))
    updates = []
    pool = make_pool(api, updates, pod_ip="10.0.0.1")
    try:
        wait_until(lambda: bool(updates), msg="initial list")
        n = len(updates)
        for _ in range(3):
            api.emit_bookmark("endpoints")
        time.sleep(0.3)
        assert len(updates) == n  # no update fired for bookmarks
        # Stream still live: a real event after bookmarks lands.
        api.emit("endpoints", "MODIFIED",
                 endpoints_obj("guber", ["10.0.0.1", "10.0.0.9"]))
        wait_until(
            lambda: updates
            and "10.0.0.9:81" in [p.grpc_address for p in updates[-1]],
            msg="post-bookmark event lands",
        )
    finally:
        pool.close()
