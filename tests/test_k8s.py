"""K8sPool discovery tests against an in-process fake Kubernetes API
server speaking the list+watch protocol (reference kubernetes.go, which
is exercised against a real cluster via k8s-deployment.yaml).
"""

import json
import queue
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

import pytest

from gubernator_tpu.config import setup_daemon_config
from gubernator_tpu.k8s_pool import (
    K8sApiClient,
    K8sPool,
    watch_mechanism_from_string,
)


def wait_until(fn, timeout_s=5.0, every_s=0.02, msg="condition"):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if fn():
            return
        time.sleep(every_s)
    raise AssertionError(f"timed out waiting for {msg}")


class FakeK8sApi:
    """Serves LIST and WATCH for a namespaced resource: list returns the
    current items; watch streams queued events as JSON lines."""

    def __init__(self):
        self.items = {}  # (resource, name) -> object
        self.rv = 10
        self._watchers = []  # (resource, queue)
        self._lock = threading.Lock()
        fake = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # quiet
                pass

            def do_GET(self):
                parsed = urlparse(self.path)
                params = parse_qs(parsed.query)
                resource = parsed.path.rsplit("/", 1)[-1]
                if params.get("watch", ["false"])[0] == "true":
                    self._serve_watch(resource)
                else:
                    self._serve_list(resource)

            def _serve_list(self, resource):
                with fake._lock:
                    items = [
                        o for (r, _), o in sorted(fake.items.items()) if r == resource
                    ]
                    body = json.dumps(
                        {
                            "items": items,
                            "metadata": {"resourceVersion": str(fake.rv)},
                        }
                    ).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _serve_watch(self, resource):
                q = queue.Queue()
                with fake._lock:
                    fake._watchers.append((resource, q))
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()
                try:
                    while True:
                        try:
                            event = q.get(timeout=0.1)
                        except queue.Empty:
                            continue
                        if event is None:
                            break
                        line = (json.dumps(event) + "\n").encode()
                        self.wfile.write(f"{len(line):x}\r\n".encode())
                        self.wfile.write(line + b"\r\n")
                        self.wfile.flush()
                except OSError:
                    pass
                finally:
                    with fake._lock:
                        fake._watchers.remove((resource, q))

        self._server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self._server.daemon_threads = True
        self.url = f"http://127.0.0.1:{self._server.server_port}"
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True, kwargs={"poll_interval": 0.05}
        )
        self._thread.start()

    def emit(self, resource, etype, obj):
        """Mutate state + push a watch event."""
        with self._lock:
            self.rv += 1
            obj.setdefault("metadata", {})["resourceVersion"] = str(self.rv)
            key = (resource, obj["metadata"].get("name", ""))
            if etype == "DELETED":
                self.items.pop(key, None)
            else:
                self.items[key] = obj
            for r, q in self._watchers:
                if r == resource:
                    q.put({"type": etype, "object": obj})

    def n_watchers(self):
        with self._lock:
            return len(self._watchers)

    def stop(self):
        with self._lock:
            for _, q in self._watchers:
                q.put(None)
        self._server.shutdown()
        self._server.server_close()


@pytest.fixture
def api():
    s = FakeK8sApi()
    yield s
    s.stop()


def endpoints_obj(name, ips):
    return {
        "metadata": {"name": name, "namespace": "default"},
        "subsets": [{"addresses": [{"ip": ip} for ip in ips]}],
    }


def pod_obj(name, ip, ready=True, running=True):
    state = {"running": {}} if running else {"waiting": {}}
    return {
        "metadata": {"name": name, "namespace": "default"},
        "status": {
            "podIP": ip,
            "containerStatuses": [{"ready": ready, "state": state}],
        },
    }


def make_pool(api, updates, **kw):
    kw.setdefault("mechanism", "endpoints")
    return K8sPool(
        on_update=updates.append,
        pod_port="81",
        api_client=K8sApiClient(api_url=api.url),
        backoff_s=0.05,
        **kw,
    )


def test_mechanism_parse():
    assert watch_mechanism_from_string("") == "endpoints"
    assert watch_mechanism_from_string("pods") == "pods"
    with pytest.raises(ValueError):
        watch_mechanism_from_string("nodes")


def test_endpoints_list_and_watch(api):
    api.emit("endpoints", "ADDED", endpoints_obj("guber", ["10.0.0.1"]))
    updates = []
    pool = make_pool(api, updates, pod_ip="10.0.0.1")
    try:
        wait_until(
            lambda: updates
            and [p.grpc_address for p in updates[-1]] == ["10.0.0.1:81"],
            msg="initial list lands",
        )
        assert updates[-1][0].is_owner
        # A scale-up arrives via the watch stream.
        api.emit("endpoints", "MODIFIED", endpoints_obj("guber", ["10.0.0.1", "10.0.0.2"]))
        wait_until(
            lambda: updates
            and [p.grpc_address for p in updates[-1]]
            == ["10.0.0.1:81", "10.0.0.2:81"],
            msg="watch event adds the new address",
        )
        api.emit("endpoints", "DELETED", endpoints_obj("guber", []))
        wait_until(
            lambda: updates and updates[-1] == [], msg="deletion empties the peer list"
        )
    finally:
        pool.close()


def test_pods_watch_skips_not_ready(api):
    api.emit("pods", "ADDED", pod_obj("a", "10.0.0.1"))
    api.emit("pods", "ADDED", pod_obj("b", "10.0.0.2", ready=False))
    api.emit("pods", "ADDED", pod_obj("c", "10.0.0.3", running=False))
    updates = []
    pool = make_pool(api, updates, mechanism="pods")
    try:
        wait_until(
            lambda: updates
            and [p.grpc_address for p in updates[-1]] == ["10.0.0.1:81"],
            msg="only the ready+running pod is a peer",
        )
        api.emit("pods", "MODIFIED", pod_obj("b", "10.0.0.2"))
        wait_until(
            lambda: updates
            and [p.grpc_address for p in updates[-1]]
            == ["10.0.0.1:81", "10.0.0.2:81"],
            msg="pod becoming ready joins",
        )
    finally:
        pool.close()


def test_watch_stream_failure_relists(api):
    api.emit("endpoints", "ADDED", endpoints_obj("guber", ["10.0.0.1"]))
    updates = []
    pool = make_pool(api, updates)
    try:
        wait_until(lambda: api.n_watchers() == 1, msg="watch established")
        # Kill the stream server-side; mutate state while no watch is
        # active; the pool must relist and converge anyway.
        api.emit("endpoints", "MODIFIED", endpoints_obj("guber", ["10.0.0.9"]))
        with api._lock:
            for _, q in api._watchers:
                q.put(None)
        wait_until(
            lambda: updates
            and [p.grpc_address for p in updates[-1]] == ["10.0.0.9:81"],
            msg="relist after stream failure",
        )
    finally:
        pool.close()


def test_k8s_env_parsing():
    conf = setup_daemon_config(
        env={
            "GUBER_PEER_DISCOVERY_TYPE": "k8s",
            "GUBER_K8S_NAMESPACE": "rate-limits",
            "GUBER_K8S_POD_IP": "10.9.9.9",
            "GUBER_K8S_POD_PORT": "1051",
            "GUBER_K8S_ENDPOINTS_SELECTOR": "app=gubernator",
            "GUBER_K8S_WATCH_MECHANISM": "pods",
        }
    )
    assert conf.k8s_namespace == "rate-limits"
    assert conf.k8s_pod_ip == "10.9.9.9"
    assert conf.k8s_pod_port == "1051"
    assert conf.k8s_selector == "app=gubernator"
    assert conf.k8s_mechanism == "pods"


def test_k8s_selector_required():
    with pytest.raises(ValueError, match="ENDPOINTS_SELECTOR"):
        setup_daemon_config(env={"GUBER_PEER_DISCOVERY_TYPE": "k8s"})


def test_kubeconfig_local_mode(tmp_path, monkeypatch):
    """Out-of-cluster client from a kubeconfig file
    (kubernetesconfig_local.go:1-38 parity): server/CA/token from the
    current-context chain; inline base64 *-data materializes to files;
    $KUBECONFIG is honored by auto() outside a cluster."""
    import base64

    from gubernator_tpu.k8s_pool import K8sApiClient
    from gubernator_tpu.tls import self_ca

    ca_crt, _ = self_ca(str(tmp_path))
    ca_pem = open(ca_crt, "rb").read()
    kc = tmp_path / "config"
    kc.write_text(
        "\n".join([
            "apiVersion: v1",
            "kind: Config",
            "current-context: dev",
            "contexts:",
            "- name: dev",
            "  context: {cluster: devc, user: devu}",
            "- name: other",
            "  context: {cluster: devc, user: devu}",
            "clusters:",
            "- name: devc",
            "  cluster:",
            "    server: https://k8s.example:6443",
            f"    certificate-authority-data: {base64.b64encode(ca_pem).decode()}",
            "users:",
            "- name: devu",
            "  user:",
            "    token: sekret",
        ])
    )
    client = K8sApiClient.from_kubeconfig(str(kc))
    assert client.api_url == "https://k8s.example:6443"
    assert client.token == "sekret"
    assert client._ssl_ctx is not None

    # auto() outside a cluster follows $KUBECONFIG
    monkeypatch.delenv("KUBERNETES_SERVICE_HOST", raising=False)
    monkeypatch.setenv("KUBECONFIG", str(kc))
    auto = K8sApiClient.auto()
    assert auto.api_url == "https://k8s.example:6443"

    # unknown context name errors clearly
    with pytest.raises(ValueError, match="contexts"):
        K8sApiClient.from_kubeconfig(str(kc), context="missing")


def test_kubeconfig_http_server_no_tls(tmp_path):
    from gubernator_tpu.k8s_pool import K8sApiClient

    kc = tmp_path / "config"
    kc.write_text(
        "\n".join([
            "current-context: dev",
            "contexts:",
            "- name: dev",
            "  context: {cluster: c, user: u}",
            "clusters:",
            "- name: c",
            "  cluster: {server: 'http://127.0.0.1:8001'}",
            "users:",
            "- name: u",
            "  user: {}",
        ])
    )
    client = K8sApiClient.from_kubeconfig(str(kc))
    assert client.api_url == "http://127.0.0.1:8001"
    assert client._ssl_ctx is None


def test_kubeconfig_client_cert_relative_paths(tmp_path):
    """Client-certificate auth with RELATIVE paths: clientcmd resolves
    them against the kubeconfig's own directory, and so do we; the ssl
    context must actually load the chain (a bad key errors here)."""
    from gubernator_tpu.k8s_pool import K8sApiClient
    from gubernator_tpu.tls import self_ca, self_cert

    ca_crt, ca_key = self_ca(str(tmp_path))
    crt, key = self_cert(str(tmp_path), ca_crt, ca_key, name="client", client=True)
    kc = tmp_path / "config"
    kc.write_text(
        "\n".join([
            "current-context: dev",
            "contexts:",
            "- name: dev",
            "  context: {cluster: c, user: u}",
            "clusters:",
            "- name: c",
            "  cluster:",
            "    server: https://k8s.example:6443",
            "    certificate-authority: ca.crt",  # relative to kubeconfig dir
            "users:",
            "- name: u",
            "  user:",
            "    client-certificate: client.crt",
            "    client-key: client.key",
        ])
    )
    client = K8sApiClient.from_kubeconfig(str(kc))
    assert client._ssl_ctx is not None  # chain loaded without error


def test_kubeconfig_exec_auth_rejected(tmp_path):
    from gubernator_tpu.k8s_pool import K8sApiClient

    kc = tmp_path / "config"
    kc.write_text(
        "\n".join([
            "current-context: dev",
            "contexts:",
            "- name: dev",
            "  context: {cluster: c, user: u}",
            "clusters:",
            "- name: c",
            "  cluster: {server: 'https://k8s.example:6443'}",
            "users:",
            "- name: u",
            "  user:",
            "    exec: {command: aws}",
        ])
    )
    with pytest.raises(ValueError, match="exec"):
        K8sApiClient.from_kubeconfig(str(kc))
