"""GLOBAL behavior: replica caches, device-side hit accumulation, and
the collective sync program, on the 8-device mesh.

Reference model under test: non-owner answers locally and forwards hits
async (gubernator.go:231-255, global.go:77-160); owner applies and
broadcasts authoritative status (global.go:163-243); peers then answer
from the broadcast cache until it expires (gubernator.go:241-249,
259-272).  Convergence observed here by stepping `sync_globals()` —
the in-process equivalent of waiting out GlobalSyncWait ticks as
TestGlobalRateLimits does by polling metrics (functional_test.go:478-546).
"""

import pytest

from gubernator_tpu.parallel.mesh import MeshBucketStore, shard_of_key
from gubernator_tpu.types import Algorithm, Behavior, RateLimitRequest, Status
from gubernator_tpu.utils.clock import Clock

T0 = 1_573_430_430_000
GLOBAL = Behavior.GLOBAL


def mk(key, hits=1, limit=10, duration=60_000, behavior=GLOBAL):
    return RateLimitRequest(
        name="glob", unique_key=key, hits=hits, limit=limit,
        duration=duration, algorithm=Algorithm.TOKEN_BUCKET, behavior=behavior,
    )


def owner_and_other(store, key):
    owner = shard_of_key(f"glob_{key}", store.n_shards)
    other = (owner + 1) % store.n_shards
    return owner, other


def test_non_owner_answers_locally_then_converges():
    store = MeshBucketStore(capacity_per_shard=64, g_capacity=32)
    owner, other = owner_and_other(store, "k1")

    # First hit lands at a non-owner: replica cache is cold, so it
    # computes as-if-owner locally (gubernator.go:250-254).
    r = store.apply([mk("k1")], T0, home_shard=other)[0]
    assert r.status == Status.UNDER_LIMIT and r.remaining == 9

    # Sync: the hit reaches the owner, owner broadcasts.
    res = store.sync_globals(T0 + 1)
    assert res.broadcast_count == 1
    assert store.gtable.rep_expire[store.gtable.get("glob_k1")] > T0

    # Now the non-owner answers from the broadcast cache: remaining is
    # the owner's authoritative value, static until the next broadcast.
    r = store.apply([mk("k1")], T0 + 2, home_shard=other)[0]
    assert r.status == Status.UNDER_LIMIT and r.remaining == 9
    r = store.apply([mk("k1")], T0 + 3, home_shard=other)[0]
    assert r.remaining == 9  # still the cached value (reference semantics)

    # Those two cached hits converge at the next sync.
    store.sync_globals(T0 + 4)
    g = store.gtable.get("glob_k1")
    assert store.gtable.rep_expire[g] > T0
    r = store.apply([mk("k1", hits=0)], T0 + 5, home_shard=other)[0]
    assert r.remaining == 7  # 10 - 1 (pre-sync) - 2 (cached hits)


def test_owner_local_hits_broadcast_without_forwarding():
    store = MeshBucketStore(capacity_per_shard=64, g_capacity=32)
    owner, other = owner_and_other(store, "k2")

    # Hits at the owner apply directly (gubernator.go:176) and mark the
    # key dirty for broadcast (QueueUpdate, gubernator.go:339-341).
    r = store.apply([mk("k2", hits=4)], T0, home_shard=owner)[0]
    assert r.remaining == 6
    store.sync_globals(T0 + 1)

    # Another shard answers from the broadcast without ever computing.
    r = store.apply([mk("k2", hits=1)], T0 + 2, home_shard=other)[0]
    assert r.remaining == 6  # owner's broadcast value


def test_hot_key_skew_converges_across_shards():
    """BASELINE config 4: GLOBAL hot key hammered from every shard."""
    store = MeshBucketStore(capacity_per_shard=64, g_capacity=32)
    owner, _ = owner_and_other(store, "hot")
    limit = 1000
    total = 0
    clock = Clock()
    clock.freeze(T0)

    # Warm the cache with one owner-side hit + sync.
    store.apply([mk("hot", hits=1, limit=limit)], clock.now_ms(), home_shard=owner)
    total += 1
    store.sync_globals(clock.now_ms())

    # 5 windows of skewed traffic from every shard.
    for window in range(5):
        clock.advance(10)
        for s in range(store.n_shards):
            if s == owner:
                continue
            hits = 7 + (s % 3)
            r = store.apply(
                [mk("hot", hits=hits, limit=limit)], clock.now_ms(), home_shard=s
            )[0]
            assert r.status == Status.UNDER_LIMIT  # cached answers
            total += hits
        clock.advance(10)
        store.sync_globals(clock.now_ms())

    # The authoritative count must equal the exact sum of all hits.
    r = store.apply([mk("hot", hits=0, limit=limit)], clock.now_ms(), home_shard=owner)[0]
    assert r.remaining == limit - total


def test_over_limit_propagates_to_replicas():
    store = MeshBucketStore(capacity_per_shard=64, g_capacity=32)
    owner, other = owner_and_other(store, "k3")

    store.apply([mk("k3", hits=10, limit=10)], T0, home_shard=owner)
    store.sync_globals(T0 + 1)

    # The broadcast carries the owner's STICKY status: draining to 0 via
    # a hits==limit create leaves Status UNDER_LIMIT (algorithms.go:
    # 147-159 never sets it), so replicas serve UNDER/0 until a hit
    # actually bounces at the owner.
    for i in range(3):
        r = store.apply([mk("k3", hits=1, limit=10)], T0 + 2 + i, home_shard=other)[0]
        assert r.status == Status.UNDER_LIMIT
        assert r.remaining == 0

    # Next sync: the 3 forwarded hits bounce (remaining==0 & hits>0 =>
    # OVER + sticky, algorithms.go:112-117) and OVER propagates.
    store.sync_globals(T0 + 9)
    r = store.apply([mk("k3", hits=0, limit=10)], T0 + 10, home_shard=owner)[0]
    assert r.status == Status.OVER_LIMIT
    assert r.remaining == 0
    r = store.apply([mk("k3", hits=1, limit=10)], T0 + 11, home_shard=other)[0]
    assert r.status == Status.OVER_LIMIT  # replica now serves OVER from cache


def test_gslot_eviction_clears_device_rows():
    """A recycled gslot must never serve the evicted key's broadcast."""
    store = MeshBucketStore(capacity_per_shard=64, g_capacity=2)

    # Warm e1: broadcast makes its replica rows live (remaining=4).
    owner1, other1 = owner_and_other(store, "e1")
    store.apply([mk("e1", hits=6, limit=10)], T0, home_shard=owner1)
    store.sync_globals(T0 + 1)
    g_e1 = store.gtable.get("glob_e1")
    assert store.gtable.rep_expire[g_e1] > T0

    # Two more keys exhaust the 2-entry table; e1 is evicted and its
    # gslot recycled for e3.
    for k in ["e2", "e3"]:
        _, oth = owner_and_other(store, k)
        store.apply([mk(k)], T0 + 2, home_shard=oth)
    assert store.gtable.get("glob_e1") is None
    g_e3 = store.gtable.get("glob_e3")
    assert g_e3 == g_e1  # recycled

    # e3's non-owner answer above must have computed locally (fresh
    # bucket: 10-1=9), not served e1's stale broadcast (remaining=4).
    _, oth3 = owner_and_other(store, "e3")
    r = store.apply([mk("e3", hits=0)], T0 + 3, home_shard=oth3)[0]
    assert r.remaining == 9


def test_measure_sync_cost_and_autotune():
    """measure_sync_cost_s returns the device cost of one collective;
    the GlobalManager sizes the sync window from its in-situ sync
    timings (<=10% overhead, clamped) once GLOBAL traffic is observed."""
    from gubernator_tpu.service import GlobalManager, ServiceConfig, V1Service
    from gubernator_tpu.types import PeerInfo

    store = MeshBucketStore(capacity_per_shard=256, g_capacity=64)
    cost = store.measure_sync_cost_s(T0, iters=2)
    assert 0 < cost < 60.0

    clock = Clock()
    clock.freeze(T0)
    svc = V1Service(ServiceConfig(store=store, clock=clock,
                                  advertise_address="127.0.0.1:9991"))
    svc.set_peers([PeerInfo(grpc_address="127.0.0.1:9991", is_owner=True)])
    try:
        mgr = svc.global_mgr
        # default config leaves the window on AUTO at the fallback value
        assert mgr._auto and mgr.sync_wait_s == GlobalManager.SYNC_WAIT_FALLBACK_S
        # drive ticks manually: the background interval must not race us
        mgr._interval.stop()
        from gubernator_tpu.types import GetRateLimitsRequest

        svc.get_rate_limits(
            GetRateLimitsRequest(requests=[mk("tune", hits=1, limit=10)])
        )
        mgr._tick()  # one real tick: does work, observes its own cost
        assert mgr.measured_sync_cost_s is not None
        expected = GlobalManager.window_for_cost(mgr.measured_sync_cost_s)
        assert mgr.sync_wait_s == pytest.approx(expected)
        assert mgr._interval.duration_s == pytest.approx(expected)
        # still AUTO: the window keeps adapting as sync cost changes
        assert mgr._auto
        # The estimator is min-of-recent (best-of-N): ONE contaminated
        # outlier must NOT move the window (round 4: a single ~300ms
        # startup sample had locked the EMA at the 1s clamp)...
        before = mgr.sync_wait_s
        mgr._observe_sync_cost(10.0)
        assert mgr.sync_wait_s == pytest.approx(before)
        # ...but a SUSTAINED cost rise lifts every sample in the deque
        # and the window follows, clamped at the max.
        for _ in range(GlobalManager.SYNC_COST_SAMPLES):
            mgr._observe_sync_cost(10.0)
        assert mgr.sync_wait_s == GlobalManager.SYNC_WAIT_MAX_S
    finally:
        svc.close()


def test_configured_sync_wait_disables_autotune():
    from gubernator_tpu.config import BehaviorConfig
    from gubernator_tpu.service import ServiceConfig, V1Service
    from gubernator_tpu.types import PeerInfo

    clock = Clock()
    clock.freeze(T0)
    svc = V1Service(ServiceConfig(
        cache_size=256,
        behaviors=BehaviorConfig(global_sync_wait_s=0.05),
        clock=clock, advertise_address="127.0.0.1:9992",
    ))
    svc.set_peers([PeerInfo(grpc_address="127.0.0.1:9992", is_owner=True)])
    try:
        assert not svc.global_mgr._auto
        assert svc.global_mgr.sync_wait_s == 0.05
    finally:
        svc.close()


def test_global_cache_auto_sizes_to_bucket_capacity():
    """Unset global_cache_size auto-sizes the replica table to the
    bucket-table capacity, clamped [4096, 65536] — the reference has no
    separate GLOBAL key cap (GLOBAL keys share its cache,
    global.go:83-91), so a working set that fits the cache must fit the
    replica table.  An explicit setting still wins."""
    from gubernator_tpu.service import ServiceConfig, V1Service

    for cache, explicit, want in (
        (256, None, 4096),        # clamp floor
        (20_000, None, 20_000),   # match capacity
        (500_000, None, 65_536),  # clamp ceiling
        (20_000, 512, 512),       # explicit wins
    ):
        svc = V1Service(ServiceConfig(
            cache_size=cache, global_cache_size=explicit,
        ))
        try:
            assert svc.store.g_capacity == want, (cache, explicit, want)
        finally:
            svc.close()


def test_sync_fast_path_survives_owner_slot_eviction():
    """The generation-gated resolution fast path (round 5): a sync pass
    skips owner-slot verification for shards with no mapping churn, but
    MUST re-resolve when the owner's slot was evicted between syncs —
    the stale slot would otherwise read another key's row."""
    store = MeshBucketStore(capacity_per_shard=4, g_capacity=32)
    owner, _ = owner_and_other(store, "gk")

    store.apply([mk("gk", hits=3, limit=10)], T0, home_shard=owner)
    store.sync_globals(T0)
    slot_before = int(store.gtable.owner_slot[store.gtable.get("glob_gk")])

    # Churn the owner shard's tiny table until gk's slot is stolen
    # (filler keys chosen to hash onto the owner shard).
    filler_keys = [
        f"fill{i}" for i in range(256)
        if shard_of_key(f"glob_fill{i}", store.n_shards) == owner
    ][:8]
    filler = [
        RateLimitRequest(name="glob", unique_key=k, hits=1,
                         limit=100, duration=60_000,
                         algorithm=Algorithm.TOKEN_BUCKET)
        for k in filler_keys
    ]
    store.apply(filler, T0 + 1, home_shard=owner)
    assert store.tables[owner].get_slot("glob_gk") is None  # evicted

    # More GLOBAL hits; the next sync must re-resolve (generation
    # bumped), reassign a slot, and still converge the counter.
    store.apply([mk("gk", hits=2, limit=10)], T0 + 2, home_shard=owner)
    res = store.sync_globals(T0 + 2)
    g = store.gtable.get("glob_gk")
    slot_after = int(store.gtable.owner_slot[g])
    assert store.tables[owner].get_slot("glob_gk") == slot_after
    bc = {b.key: b for b in res.broadcasts}
    assert "glob_gk" in bc
    # Eviction lost the first 3 hits (reference-grade loss); the
    # re-resolved slot carries the post-eviction state consistently.
    assert bc["glob_gk"].status.remaining == 8, (slot_before, slot_after, bc)


def test_sync_fast_path_steady_state_skips_verification():
    """With no mapping churn between syncs, the second pass must not
    touch the tables' lookup path at all (the O(active) -> O(changed)
    contract).  Pinned by COUNTING get_slot calls on the owner shard's
    table during the second sync — deleting the shard_clean fast path
    from _sync_globals_locked fails this test."""
    store = MeshBucketStore(capacity_per_shard=64, g_capacity=32)
    owner, _ = owner_and_other(store, "s1")
    store.apply([mk("s1", hits=1, limit=100)], T0, home_shard=owner)
    store.sync_globals(T0)
    gen_before = [t.generation for t in store.tables]

    # Hits only (no new keys): values change, mapping doesn't.
    store.apply([mk("s1", hits=1, limit=100)], T0 + 1, home_shard=owner)
    assert [t.generation for t in store.tables] == gen_before

    calls = {"n": 0}
    table = store.tables[owner]
    orig = table.get_slot

    def counting_get_slot(key):
        calls["n"] += 1
        return orig(key)

    table.get_slot = counting_get_slot
    try:
        store.sync_globals(T0 + 1)
    finally:
        del table.get_slot  # restore the bound method
    assert calls["n"] == 0, "clean shard must skip owner-slot verification"
    # And the resolved slot is still correct.
    g = store.gtable.get("glob_s1")
    assert store.tables[owner].get_slot("glob_s1") == int(
        store.gtable.owner_slot[g]
    )
