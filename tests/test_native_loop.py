"""Native service loop (host_runtime.cpp gt_ingress_* + the
multi-acceptor epoll edge): fast-lane end-to-end oracle + byte-identity
with the PR 8 Python-assembled edge, the same-host UDS lane,
adversarial byte-fuzz of the native frame parser on both transports,
REUSEPORT acceptor fairness, the adaptive idle timeout, native route
parity with hash_ring, and native-shed wording parity."""

from __future__ import annotations

import json
import os
import random
import resource
import socket
import struct
import threading
import time

import numpy as np
import pytest

from gubernator_tpu import native, wire
from gubernator_tpu.client import ColumnsV1Client, V1Client
from gubernator_tpu.cluster import fast_test_behaviors
from gubernator_tpu.config import DaemonConfig
from gubernator_tpu.daemon import Daemon
from gubernator_tpu.parallel.hash_ring import ReplicatedConsistentHash
from gubernator_tpu.service import IngressShedError
from gubernator_tpu.types import SECOND, Behavior
from gubernator_tpu.utils.clock import Clock

T0 = 1_573_430_400_000

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native runtime unavailable"
)


def _standalone(clock, *, native_ingress: bool, acceptors: int = 1,
                uds_path: str = "") -> Daemon:
    behaviors = fast_test_behaviors()
    behaviors.global_sync_wait_s = 3600.0
    behaviors.multi_region_sync_wait_s = 3600.0
    behaviors.native_ingress = native_ingress
    d = Daemon(
        DaemonConfig(
            listen_address="127.0.0.1:0",
            grpc_listen_address="127.0.0.1:0",
            cache_size=4096,
            global_cache_size=256,
            behaviors=behaviors,
            peer_discovery_type="static",
            native_http=True,
            acceptors=acceptors,
            uds_path=uds_path,
        ),
        clock=clock,
    ).start()
    d.set_peers([d.peer_info])
    return d


@pytest.fixture(scope="module")
def daemons(tmp_path_factory):
    """One native-loop daemon (2 acceptors + a UDS lane) and one
    GUBER_NATIVE_INGRESS=0 daemon — exactly the PR 8 edge — sharing a
    frozen clock, so the two must answer the same frames with the same
    bytes."""
    clock = Clock()
    clock.freeze(T0)
    sock = str(tmp_path_factory.mktemp("uds") / "gub.sock")
    fast = _standalone(clock, native_ingress=True, acceptors=2,
                       uds_path=sock)
    pr8 = _standalone(clock, native_ingress=False)
    yield fast, pr8, clock, sock
    fast.close()
    pr8.close()


def _frame(name, keys, hits=1, limit=1000, duration=3_600_000, algo=0,
           behavior=0):
    n = len(keys)
    return wire.encode_ingress_frame((
        [name] * n, list(keys),
        np.full(n, algo, np.int32), np.full(n, behavior, np.int32),
        np.full(n, hits, np.int64), np.full(n, limit, np.int64),
        np.full(n, duration, np.int64),
    ))


def _connect(target):
    if isinstance(target, str):
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.connect(target)
    else:
        s = socket.create_connection(("127.0.0.1", target))
    s.settimeout(30.0)
    return s


def _post_raw(sock, body,
              ctype=wire.COLUMNS_CONTENT_TYPE) -> "tuple[bytes, bytes]":
    """One POST /v1/GetRateLimits on an open socket; returns the raw
    (full response bytes, body bytes)."""
    head = (
        f"POST /v1/GetRateLimits HTTP/1.1\r\nHost: t\r\n"
        f"Content-Type: {ctype}\r\nContent-Length: {len(body)}\r\n\r\n"
    ).encode()
    sock.sendall(head + body)
    buf = b""
    while b"\r\n\r\n" not in buf:
        chunk = sock.recv(65536)
        if not chunk:
            raise ConnectionError("server closed mid-response")
        buf += chunk
    hdr, _, rest = buf.partition(b"\r\n\r\n")
    clen = 0
    for line in hdr.split(b"\r\n"):
        if line.lower().startswith(b"content-length"):
            clen = int(line.split(b":")[1])
    while len(rest) < clen:
        chunk = sock.recv(65536)
        if not chunk:
            raise ConnectionError("server closed mid-body")
        rest += chunk
    return hdr + b"\r\n\r\n" + rest[:clen], rest[:clen]


def _post(target, body, **kw):
    s = _connect(target)
    try:
        return _post_raw(s, body, **kw)
    finally:
        s.close()


# ---------------------------------------------------------------------
# fast lane end to end + byte identity with the PR 8 edge
# ---------------------------------------------------------------------

def test_fast_lane_serves_frames_natively(daemons):
    fast, _pr8, _clock, _sock = daemons
    before = fast.gateway.pump.stats()
    raw, body = _post(fast.gateway._edge.port,
                      _frame("nl", [f"fast{i}" for i in range(16)]))
    assert raw.startswith(b"HTTP/1.1 200 OK")
    rc = wire.decode_ingress_result_frame(body)
    assert rc.n == 16
    assert (np.asarray(rc.remaining) == 999).all()
    after = fast.gateway.pump.stats()
    assert after["frames"] == before["frames"] + 1
    assert after["lanes"] == before["lanes"] + 16


def test_fast_lane_byte_identical_to_python_edge(daemons):
    """The knob-off interop line: the native loop's kind-6 fill (and
    its HTTP envelope) must be byte-identical to the PR 8
    Python-assembled response for the same frame against the same
    frozen-clock state."""
    fast, pr8, _clock, _sock = daemons
    for frame in (
        _frame("ident", [f"b{i}" for i in range(9)]),
        _frame("ident", [f"b{i}" for i in range(9)], hits=3, limit=5),
        _frame("ident", ["dup", "dup", "dup"], limit=2),
        _frame("ident", [f"l{i}" for i in range(4)], algo=1, limit=7),
    ):
        raw_fast, _ = _post(fast.gateway._edge.port, frame)
        raw_pr8, _ = _post(pr8.gateway._edge.port, frame)
        assert raw_fast == raw_pr8
    assert fast.gateway.pump.stats()["frames"] >= 3  # dup frame may round


def test_classic_json_clients_untouched(daemons):
    """GUBER_ACCEPTORS>1 + the fast lane must leave plain JSON clients
    byte-identical to the PR 8 edge."""
    fast, pr8, _clock, _sock = daemons
    body = json.dumps({
        "requests": [
            {"name": "cj", "uniqueKey": f"k{i}", "hits": "1",
             "limit": "10", "duration": "60000"}
            for i in range(5)
        ]
    }).encode()
    raw_fast, body_fast = _post(fast.gateway._edge.port, body,
                                ctype="application/json")
    raw_pr8, body_pr8 = _post(pr8.gateway._edge.port, body,
                              ctype="application/json")
    assert raw_fast == raw_pr8
    assert json.loads(body_fast) == json.loads(body_pr8)


def test_slow_behavior_bits_fall_back_to_python(daemons):
    """GLOBAL lanes need the replica path: the native submit must
    refuse the frame (fallback counter) and the Python edge must still
    answer it correctly."""
    fast, _pr8, _clock, _sock = daemons
    before = fast.gateway.pump.stats()
    frame = _frame("gl", ["g1", "g2"], behavior=int(Behavior.GLOBAL))
    raw, body = _post(fast.gateway._edge.port, frame)
    assert raw.startswith(b"HTTP/1.1 200 OK")
    rc = wire.decode_ingress_result_frame(body)
    assert rc.n == 2
    after = fast.gateway.pump.stats()
    assert after["fallbacks"] > before["fallbacks"]
    assert after["frames"] == before["frames"]  # never entered the ring


def test_validation_error_lanes_fall_back_with_exact_wording(daemons):
    fast, pr8, _clock, _sock = daemons
    n = 3
    frame = wire.encode_ingress_frame((
        ["v", "", "v"], ["a", "b", ""],
        np.zeros(n, np.int32), np.zeros(n, np.int32),
        np.ones(n, np.int64), np.full(n, 10, np.int64),
        np.full(n, 60_000, np.int64),
    ))
    raw_fast, body = _post(fast.gateway._edge.port, frame)
    raw_pr8, _ = _post(pr8.gateway._edge.port, frame)
    assert raw_fast == raw_pr8
    rc = wire.decode_ingress_result_frame(body)
    assert rc.overrides[1].error == "field 'namespace' cannot be empty"
    assert rc.overrides[2].error == "field 'unique_key' cannot be empty"


# ---------------------------------------------------------------------
# same-host UDS lane
# ---------------------------------------------------------------------

def test_uds_end_to_end_oracle_vs_tcp(daemons):
    """The UDS lane must serve the same kind-5/6 protocol: a fresh key
    sequence over UDS behaves exactly like its twin over TCP (limit
    algebra + OVER_LIMIT), and the raw response bytes match lane for
    lane."""
    fast, _pr8, _clock, sock = daemons
    port = fast.gateway._edge.port
    for i in range(4):
        f_tcp = _frame("udso", [f"tcp{i}"], limit=2)
        f_uds = _frame("udso", [f"uds{i}"], limit=2)
        raw_t, body_t = _post(port, f_tcp)
        raw_u, body_u = _post(sock, f_uds)
        rt = wire.decode_ingress_result_frame(body_t)
        ru = wire.decode_ingress_result_frame(body_u)
        assert list(rt.remaining) == list(ru.remaining)
        assert list(rt.status) == list(ru.status)
    # Hit one UDS key to exhaustion: OVER_LIMIT must appear exactly
    # like on TCP.
    statuses = []
    for _ in range(4):
        _, body = _post(sock, _frame("udso", ["burn"], limit=2))
        rc = wire.decode_ingress_result_frame(body)
        statuses.append(int(rc.status[0]))
    assert statuses == [0, 0, 1, 1]


def test_columns_client_speaks_unix_target(daemons):
    fast, _pr8, _clock, sock = daemons
    client = ColumnsV1Client(f"unix://{sock}", timeout_s=15.0)
    try:
        resp = client.check("udsc", "k1", hits=1, limit=5,
                            duration=60_000).result(timeout=15)
        assert resp.remaining == 4
        assert client.health_check().status == "healthy"
    finally:
        client.close()
    # The classic client also speaks unix:// (health/metrics surface).
    v1 = V1Client(f"unix://{sock}", timeout_s=15.0)
    try:
        assert v1.health_check().status == "healthy"
    finally:
        v1.close()


# ---------------------------------------------------------------------
# adversarial byte-fuzz of the native frame parser (TCP and UDS edges)
# ---------------------------------------------------------------------

def _mutations(rng, frame: bytes):
    """Seeded adversarial mutations: truncations, non-monotone string
    offsets, overflow column lengths, bad UTF-8, garbage flips."""
    yield frame[:9]                      # shorter than the header
    yield frame[:rng.randrange(10, len(frame))]          # truncated body
    yield frame + b"X"                   # trailing garbage
    mut = bytearray(frame)
    mut[14:18], mut[18:22] = mut[18:22], mut[14:18]  # offsets swap
    yield bytes(mut)
    mut = bytearray(frame)
    struct.pack_into("<I", mut, 10, 0x7FFFFFFF)  # name blob len overflow
    yield bytes(mut)
    mut = bytearray(frame)
    struct.pack_into("<I", mut, 6, 2**31 - 1)    # absurd lane count
    yield bytes(mut)
    # bad UTF-8 inside the name blob (keeps lengths/offsets valid)
    mut = bytearray(frame)
    n = struct.unpack_from("<I", frame, 6)[0]
    blob_pos = 10 + 4 + 4 * (n + 1)
    mut[blob_pos] = 0xFF
    yield bytes(mut)
    for _ in range(12):
        mut = bytearray(frame)
        for _ in range(rng.randrange(1, 8)):
            mut[rng.randrange(len(mut))] = rng.randrange(256)
        yield bytes(mut)


@pytest.mark.parametrize("transport", ["tcp", "uds"])
def test_fuzzed_frames_never_crash_and_400_with_reason(daemons, transport):
    fast, _pr8, _clock, sock = daemons
    target = fast.gateway._edge.port if transport == "tcp" else sock
    rng = random.Random(0xC0FFEE if transport == "tcp" else 0xBEEF)
    base = _frame("fz", [f"k{i}" for i in range(6)], limit=50)
    for mut in _mutations(rng, base):
        raw, body = _post(target, mut)
        status = int(raw.split(b" ", 2)[1])
        # Every mutation answers: a clean 200 (the flips that happen to
        # stay valid) or a reasoned 4xx — never a hang, reset or 5xx.
        assert status in (200, 400), (status, body[:120], mut[:40].hex())
        if status == 400:
            msg = json.loads(body)
            assert msg["message"], msg
    # The daemon survived with full service: a clean frame still works.
    _, body = _post(target, _frame("fz", [f"alive-{transport}"], limit=50))
    rc = wire.decode_ingress_result_frame(body)
    assert int(rc.remaining[0]) == 49
    assert fast.service.health_check().status == "healthy"


# ---------------------------------------------------------------------
# REUSEPORT acceptor fairness + per-acceptor counters
# ---------------------------------------------------------------------

def test_acceptor_fairness_under_concurrent_clients(daemons):
    """16 concurrent pipelined clients over the 2-acceptor REUSEPORT
    group: every TCP acceptor must see connections and requests (the
    kernel shards by 4-tuple), the per-acceptor counters must be
    populated, and every response must decode clean."""
    fast, _pr8, _clock, _sock = daemons
    port = fast.gateway._edge.port
    before = {
        i: r for i, r in enumerate(fast.gateway._edge.acceptor_stats())
    }
    errors = []

    def one(t):
        try:
            s = _connect(port)
            try:
                for j in range(3):
                    _, body = _post_raw(
                        s, _frame("fair", [f"t{t}j{j}l{i}" for i in range(8)])
                    )
                    rc = wire.decode_ingress_result_frame(body)
                    assert rc.n == 8
            finally:
                s.close()
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=one, args=(t,)) for t in range(16)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    rows = fast.gateway._edge.acceptor_stats()
    tcp_rows = [r for r in rows if not r["uds"]]
    assert len(tcp_rows) == 2
    for i, row in enumerate(tcp_rows):
        assert row["accepted"] > before[i]["accepted"], rows
        assert row["requests"] > before[i]["requests"], rows
    # The fast lane consumed the frames (not the Python path): lanes
    # counters advanced across the group.
    assert sum(r["ingressLanes"] for r in tcp_rows) >= sum(
        before[i]["ingressLanes"] for i in range(2)
    ) + 16 * 3 * 8


def test_acceptor_metrics_exported(daemons):
    fast, _pr8, _clock, _sock = daemons
    v1 = V1Client(f"127.0.0.1:{fast.gateway._edge.port}", timeout_s=15.0)
    try:
        text = v1.metrics_text()
    finally:
        v1.close()
    assert 'gubernator_ingress_acceptor_requests{acceptor="0",transport="tcp"}' in text
    assert 'gubernator_ingress_acceptor_requests{acceptor="1",transport="tcp"}' in text
    assert 'transport="uds"' in text
    assert 'gubernator_native_ingress_batches_total{stat="lanes"}' in text


# ---------------------------------------------------------------------
# adaptive idle timeout (satellite: no fixed-tick burn per acceptor)
# ---------------------------------------------------------------------

def test_idle_acceptors_block_without_wakeups():
    """An idle edge must not tick: with the adaptive timeout the epoll
    loops block indefinitely (wakeup counters frozen) and the process
    burns ~no CPU while idle; a request afterwards still answers
    (the eventfd wake path)."""
    edge = native.HttpEdge("127.0.0.1:0", acceptors=3)
    try:
        time.sleep(0.2)  # accept-queue settle
        w0 = [r["wakeups"] for r in edge.acceptor_stats()]
        cpu0 = resource.getrusage(resource.RUSAGE_SELF)
        t0 = time.monotonic()
        time.sleep(0.6)
        w1 = [r["wakeups"] for r in edge.acceptor_stats()]
        cpu1 = resource.getrusage(resource.RUSAGE_SELF)
        elapsed = time.monotonic() - t0
        assert w1 == w0, f"idle acceptors woke: {w0} -> {w1}"
        burn = (cpu1.ru_utime - cpu0.ru_utime) + (
            cpu1.ru_stime - cpu0.ru_stime
        )
        # Not a tight bound (other threads of the test process run),
        # just proof there is no per-acceptor busy tick.
        assert burn < 0.5 * elapsed, f"idle CPU {burn:.3f}s over {elapsed:.3f}s"
        # Liveness after the indefinite block: accept + respond works.
        s = _connect(edge.port)
        try:
            s.sendall(b"GET /x HTTP/1.1\r\nHost: t\r\nContent-Length: 0\r\n\r\n")
            got = edge.next(timeout_ms=2000)
            assert got is not None and got[2] == "/x"
            edge.respond(got[0], 200, b"{}")
            raw, _ = _read_response(s)
            assert raw.startswith(b"HTTP/1.1 200")
        finally:
            s.close()
    finally:
        edge.shutdown()
        edge.free()


def _read_response(sock):
    buf = b""
    while b"\r\n\r\n" not in buf:
        chunk = sock.recv(65536)
        if not chunk:
            break
        buf += chunk
    hdr, _, rest = buf.partition(b"\r\n\r\n")
    clen = 0
    for line in hdr.split(b"\r\n"):
        if line.lower().startswith(b"content-length"):
            clen = int(line.split(b":")[1])
    while len(rest) < clen:
        rest += sock.recv(65536)
    return hdr + b"\r\n\r\n" + rest, rest


# ---------------------------------------------------------------------
# native route + shed parity units (bare edge + batcher, no daemon)
# ---------------------------------------------------------------------

def _edge_with_batcher(ring_peers, self_id, cap_lanes=0):
    """Bare HttpEdge + IngressBatcher with a ring snapshot computed
    EXACTLY the way NativeIngressPump.update_ring does, from a real
    ReplicatedConsistentHash."""
    edge = native.HttpEdge("127.0.0.1:0")
    b = native.IngressBatcher()
    ring = ReplicatedConsistentHash()
    for pid in ring_peers:
        ring.add(pid)
    codes = np.asarray(ring._vnode_code, dtype=np.int32)
    self_codes = [c for c, pid in enumerate(ring._code_ids)
                  if pid == self_id]
    vself = np.isin(codes, np.asarray(self_codes, np.int32)).astype(np.uint8)
    b.set_ring(
        np.asarray(ring._vnode_hashes, np.uint64), vself,
        all_self=len(ring_peers) == 1 and ring_peers[0] == self_id,
        enabled=True, cap_lanes=cap_lanes, max_frame_lanes=16384,
        behavior_mask=1 | 2 | 4 | 16,
    )
    return edge, b, ring


def test_native_route_matches_hash_ring():
    """The C++ searchsorted route must agree with
    hash_ring.get_batch_codes lane for lane: frames whose keys all map
    to self enqueue; frames with any remote-owned lane fall back."""
    edge, b, ring = _edge_with_batcher(["peerA", "peerB"], "peerA")
    try:
        # Index-FIRST keys: FNV-1 clusters suffix-varying keys onto one
        # vnode run (the documented test_hash_ring finding).
        keys = [f"{i}route" for i in range(64)]
        codes, ids = ring.get_batch_codes([f"rt_{k}" for k in keys])
        owner_is_a = np.asarray(
            [ids[c] == "peerA" for c in codes], dtype=bool
        )
        mine = [k for k, m in zip(keys, owner_is_a) if m]
        theirs = [k for k, m in zip(keys, owner_is_a) if not m]
        assert mine and theirs  # both classes present at 64 keys
        s = _connect(edge.port)
        try:
            # All-mine frame: consumed natively (worker returns FAST_LANE).
            s.sendall(_http_post(_frame("rt", mine)))
            got = edge.next(timeout_ms=2000, ingress=b)
            assert got is native.FAST_LANE
            tb = b.take(65536, timeout_ms=2000)
            assert tb is not None and tb.n == len(mine)
            # The hashes the native route computed match fnv1_batch.
            expect = native.fnv1_batch([f"rt_{k}" for k in mine])
            assert (tb.hashes == expect).all()
            b.fail(tb, 500, "Error", "application/json", b"{}")
            _read_response(s)
            # Any-remote frame: falls back to the Python path.
            s.sendall(_http_post(_frame("rt", [mine[0], theirs[0]])))
            got = edge.next(timeout_ms=2000, ingress=b)
            assert got is not native.FAST_LANE and got is not None
            assert b.stats()["fallbacks"] == 1
            edge.respond(got[0], 200, b"{}")
            _read_response(s)
        finally:
            s.close()
    finally:
        b.stop()
        edge.shutdown()
        edge.free()
        b.free()


def _http_post(body):
    return (
        f"POST /v1/GetRateLimits HTTP/1.1\r\nHost: t\r\nContent-Type: "
        f"{wire.COLUMNS_CONTENT_TYPE}\r\nContent-Length: {len(body)}\r\n\r\n"
    ).encode() + body


def test_native_shed_matches_python_wording():
    """The native 429 must be byte-identical to the Python
    IngressShedError triplet (code 2, same message, same status) so
    clients cannot tell which tier declined."""
    edge, b, _ring = _edge_with_batcher(["me"], "me", cap_lanes=100)
    try:
        s = _connect(edge.port)
        try:
            s.sendall(_http_post(_frame("shed", [f"s{i}" for i in range(200)])))
            got = edge.next(timeout_ms=2000, ingress=b)
            assert got is native.FAST_LANE  # handled: shed IS native
            raw, body = _read_response(s)
            assert raw.startswith(b"HTTP/1.1 429")
            exc = IngressShedError(0, 100)
            assert json.loads(body) == {"code": 2, "message": exc.message}
            stats = b.stats()
            assert stats["shedFrames"] == 1 and stats["shedLanes"] == 200
        finally:
            s.close()
    finally:
        b.stop()
        edge.shutdown()
        edge.free()
        b.free()


def test_reshard_window_disables_fast_lane(daemons):
    """A membership change with an open double-dispatch window must
    turn the fast lane off (moved keys owe the old owner a peek only
    the Python router performs) and re-enable after the window."""
    fast, _pr8, _clock, _sock = daemons
    pump = fast.gateway.pump
    svc = fast.service
    try:
        with svc._peer_mutex:
            svc._prev_picker = svc.local_picker
            svc._handoff_deadline = time.monotonic() + 0.4
        pump.update_ring()
        before = pump.stats()["fallbacks"]
        raw, _body = _post(fast.gateway._edge.port,
                           _frame("rw", ["w1", "w2"]))
        assert raw.startswith(b"HTTP/1.1 200 OK")
        assert pump.stats()["fallbacks"] > before  # Python path served it
        # After the deadline the pump loop re-pushes enabled.
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            frames0 = pump.stats()["frames"]
            raw, _body = _post(fast.gateway._edge.port,
                               _frame("rw", [f"w3{time.monotonic()}"]))
            if pump.stats()["frames"] > frames0:
                break
            time.sleep(0.05)
        else:
            pytest.fail("fast lane never re-enabled after the window")
    finally:
        with svc._peer_mutex:
            svc._prev_picker = None
            svc._handoff_deadline = 0.0
        pump.update_ring()
