"""Black-box functional tests over real HTTP daemons.

Mirrors functional_test.go: an in-process cluster of real daemons on
loopback (TestMain :39-59), requests via the client against a random
peer (exercising owner-forwarding), frozen-clock algorithm behavior,
validation errors, GLOBAL end-to-end convergence observed by polling
/metrics (TestGlobalRateLimits :478-546), and health checking.
"""

import time

import pytest

from gubernator_tpu.client import V1Client
from gubernator_tpu.cluster import DATA_CENTER_NONE, DATA_CENTER_ONE, Cluster
from gubernator_tpu.types import (
    Algorithm,
    Behavior,
    GetRateLimitsRequest,
    RateLimitRequest,
    Status,
    SECOND,
)
from gubernator_tpu.utils.clock import Clock

T0 = 1_573_430_430_000


@pytest.fixture(scope="module")
def clock():
    c = Clock()
    c.freeze(T0)
    return c


@pytest.fixture(scope="module")
def cluster(clock):
    cl = Cluster().start_with(
        [DATA_CENTER_NONE, DATA_CENTER_NONE, DATA_CENTER_NONE, DATA_CENTER_ONE, DATA_CENTER_ONE],
        clock=clock,
    )
    yield cl
    cl.stop()


def client_for(cluster, dc=DATA_CENTER_NONE):
    return V1Client(cluster.get_random_peer(dc).http_address)


def mk(name, key, hits=1, limit=10, duration=9 * SECOND, algo=Algorithm.TOKEN_BUCKET, behavior=0):
    return RateLimitRequest(
        name=name, unique_key=key, hits=hits, limit=limit,
        duration=duration, algorithm=algo, behavior=behavior,
    )


def until_pass(fn, timeout_s=5.0, interval_s=0.05):
    """testutil.UntilPass equivalent."""
    deadline = time.monotonic() + timeout_s
    last = None
    while time.monotonic() < deadline:
        try:
            if fn():
                return True
        except Exception as e:  # noqa: BLE001
            last = e
        time.sleep(interval_s)
    if last:
        raise last
    return False


def get_metric(text: str, name: str) -> float:
    """Prometheus text parser (functional_test.go:844-869)."""
    for line in text.splitlines():
        if line.startswith(name + " ") or line.startswith(name + "{"):
            return float(line.rsplit(" ", 1)[1])
    return 0.0


def test_over_the_limit(cluster):
    client = client_for(cluster)
    expect = [(1, Status.UNDER_LIMIT), (0, Status.UNDER_LIMIT), (0, Status.OVER_LIMIT)]
    for remaining, status in expect:
        resp = client.get_rate_limits(
            GetRateLimitsRequest(requests=[mk("test_over_limit", "account:1234", limit=2)])
        )
        rl = resp.responses[0]
        assert rl.error == ""
        assert rl.status == status
        assert rl.remaining == remaining
        assert rl.limit == 2
        assert rl.reset_time != 0


def test_token_bucket_expiry_over_http(cluster, clock):
    client = client_for(cluster)
    table = [(1, Status.UNDER_LIMIT, 0), (0, Status.UNDER_LIMIT, 100), (1, Status.UNDER_LIMIT, 0)]
    for remaining, status, sleep_ms in table:
        resp = client.get_rate_limits(
            GetRateLimitsRequest(
                requests=[mk("test_token_bucket", "account:1234", limit=2, duration=5)]
            )
        )
        rl = resp.responses[0]
        assert rl.error == ""
        assert (rl.status, rl.remaining) == (status, remaining)
        clock.advance(sleep_ms)


def test_missing_fields(cluster):
    """functional_test.go:415-476."""
    client = client_for(cluster)
    cases = [
        (mk("", "account:1234", limit=10, duration=0), "field 'namespace' cannot be empty"),
        (mk("test_missing_fields", "", limit=10, duration=0), "field 'unique_key' cannot be empty"),
    ]
    for req, want_err in cases:
        resp = client.get_rate_limits(GetRateLimitsRequest(requests=[req]))
        assert resp.responses[0].error == want_err
        assert resp.responses[0].status == Status.UNDER_LIMIT
    # Zero hits / zero limit are accepted (same table).
    resp = client.get_rate_limits(
        GetRateLimitsRequest(requests=[mk("test_missing_fields", "account:1234", hits=0, limit=10)])
    )
    assert resp.responses[0].error == ""


def test_batch_size_cap(cluster):
    client = client_for(cluster)
    reqs = [mk("cap", f"k{i}") for i in range(1001)]
    with pytest.raises(RuntimeError, match="list too large"):
        client.get_rate_limits(GetRateLimitsRequest(requests=reqs))


def test_forwarding_sets_owner_metadata(cluster):
    """A key owned by a different daemon is forwarded; the response
    carries the owner's address (gubernator.go:190,209)."""
    entry = cluster.daemons[0]
    # find a key NOT owned by daemon 0 (vary the PREFIX: FNV clusters
    # common-prefix keys onto the same owner)
    for i in range(100):
        key = f"{i}_fwd"
        peer = entry.service.get_peer(f"test_forward_{key}")
        if not peer.info.is_owner:
            break
    else:
        pytest.skip("no foreign key found")
    client = V1Client(entry.peer_info.http_address)
    resp = client.get_rate_limits(
        GetRateLimitsRequest(requests=[mk("test_forward", key, limit=5)])
    )
    rl = resp.responses[0]
    assert rl.error == ""
    assert rl.remaining == 4
    assert rl.metadata.get("owner") == peer.info.grpc_address
    # hitting it again via the owner's daemon shows shared state
    owner_daemon = cluster.daemon_for(peer.info)
    oc = V1Client(owner_daemon.peer_info.http_address)
    rl = oc.get_rate_limits(
        GetRateLimitsRequest(requests=[mk("test_forward", key, limit=5)])
    ).responses[0]
    assert rl.remaining == 3


def test_columnar_batch_mixes_local_and_forwarded(cluster):
    """A multi-item request (the columnar gateway path) whose keys are
    owned by DIFFERENT daemons: locally-owned lanes answer columnar,
    foreign lanes forward — all in one call, each lane correct."""
    entry = cluster.daemons[0]
    reqs = [mk("test_colfwd", f"{i}_cf", limit=7) for i in range(20)]
    owners = {
        r.unique_key: entry.service.get_peer(r.hash_key()).info for r in reqs
    }
    assert any(o.is_owner for o in owners.values())
    assert any(not o.is_owner for o in owners.values())
    client = V1Client(entry.peer_info.http_address)
    resp = client.get_rate_limits(GetRateLimitsRequest(requests=reqs))
    assert len(resp.responses) == 20
    for r, rl in zip(reqs, resp.responses):
        assert rl.error == ""
        assert rl.remaining == 6
        if not owners[r.unique_key].is_owner:
            assert rl.metadata.get("owner") == owners[r.unique_key].grpc_address
    # Second pass shows shared state across the same mixed routing.
    resp = client.get_rate_limits(GetRateLimitsRequest(requests=reqs))
    assert all(rl.remaining == 5 for rl in resp.responses)


def test_health_check(cluster):
    client = client_for(cluster)
    hc = client.health_check()
    assert hc.status == "healthy"
    assert hc.peer_count == 3  # peers in DataCenterNone ring


def test_global_rate_limits(cluster, clock):
    """TestGlobalRateLimits (functional_test.go:478-546): send GLOBAL
    through a NON-owner, observe async + broadcast pipelines via
    /metrics, then see the broadcast cache serve."""
    # find entry daemon that does NOT own the key
    key, name = "account:12345", "test_global"
    hash_key = f"{name}_{key}"
    entry = None
    for d in cluster.daemons[:3]:
        if not d.service.get_peer(hash_key).info.is_owner:
            entry = d
            break
    assert entry is not None
    owner_daemon = cluster.daemon_for(entry.service.get_peer(hash_key).info)
    client = V1Client(entry.peer_info.http_address)

    def send(hits=1):
        return client.get_rate_limits(
            GetRateLimitsRequest(
                requests=[mk(name, key, hits=hits, limit=5, duration=60 * SECOND,
                             behavior=Behavior.GLOBAL)]
            )
        ).responses[0]

    rl = send()
    assert rl.error == ""
    assert rl.status == Status.UNDER_LIMIT
    assert rl.remaining == 4
    assert rl.metadata.get("owner") == owner_daemon.peer_info.grpc_address

    # Async hit pipeline on the entry daemon; broadcast pipeline on the
    # owner — observed via prometheus, like the reference.
    ec = V1Client(entry.peer_info.http_address)
    oc = V1Client(owner_daemon.peer_info.http_address)
    assert until_pass(
        lambda: get_metric(ec.metrics_text(), "gubernator_async_durations_count") > 0
    )
    assert until_pass(
        lambda: get_metric(oc.metrics_text(), "gubernator_broadcast_durations_count") > 0
    )
    # After convergence the non-owner serves the owner's authoritative
    # count from the broadcast cache.
    assert until_pass(lambda: send(hits=0).remaining == 4)

    # Now land hits directly at the OWNER: the entry can only learn
    # about them through the UpdatePeerGlobals broadcast, so this pins
    # actual broadcast delivery (not just the pipeline metrics).
    rl = oc.get_rate_limits(
        GetRateLimitsRequest(
            requests=[mk(name, key, hits=2, limit=5, duration=60 * SECOND,
                         behavior=Behavior.GLOBAL)]
        )
    ).responses[0]
    assert rl.error == ""
    assert rl.remaining == 2
    assert until_pass(lambda: send(hits=0).remaining == 2)


def test_multi_region_hits_propagate(cluster, clock):
    """TestMutliRegion is a stub in the reference (functional_test.go:
    826-834 TODOs); here the send leg is implemented, so assert the
    cross-region push actually lands."""
    name, key = "test_multi", "account:6789"
    hash_key = f"{name}_{key}"
    entry = cluster.daemons[0]  # DataCenterNone
    client = V1Client(entry.peer_info.http_address)
    rl = client.get_rate_limits(
        GetRateLimitsRequest(
            requests=[mk(name, key, hits=3, limit=100, duration=60 * SECOND,
                         behavior=Behavior.MULTI_REGION)]
        )
    ).responses[0]
    assert rl.error == ""

    # The hit is queued on the owner and pushed to the owning peer of
    # the other region (datacenter-1) within multi_region_sync_wait.
    owner_info = entry.service.get_peer(hash_key).info
    owner = cluster.daemon_for(owner_info)
    region_owner = owner.service.get_region_picker().pick(DATA_CENTER_ONE, hash_key)
    assert region_owner is not None
    dc1_daemon = cluster.daemon_for(region_owner.info)

    def landed():
        # the DC1 owner's local bucket saw the pushed hits
        resp = dc1_daemon.service.get_peer_rate_limits(
            GetRateLimitsRequest(requests=[mk(name, key, hits=0, limit=100, duration=60 * SECOND)])
        )
        return resp.responses[0].remaining == 97

    assert until_pass(landed)


def test_multi_region_no_amplification(clock):
    """Regression: with two NAMED regions, a MULTI_REGION hit pushed
    cross-region must not be re-queued by the receiver, or the regions
    ping-pong the same hits forever and drain the bucket."""
    cl = Cluster().start_with(["region-us", "region-eu"], clock=clock)
    try:
        us, eu = cl.daemons
        client = V1Client(us.peer_info.http_address)
        rl = client.get_rate_limits(
            GetRateLimitsRequest(
                requests=[mk("test_amp", "account:1", hits=3, limit=100,
                             duration=60 * SECOND, behavior=Behavior.MULTI_REGION)]
            )
        ).responses[0]
        assert rl.error == ""
        assert rl.remaining == 97

        def eu_remaining():
            resp = eu.service.get_peer_rate_limits(
                GetRateLimitsRequest(
                    requests=[mk("test_amp", "account:1", hits=0, limit=100,
                                 duration=60 * SECOND)]
                )
            )
            return resp.responses[0].remaining

        assert until_pass(lambda: eu_remaining() == 97)
        # Several sync windows later the count must be stable — not
        # repeatedly re-applied by a cross-region echo.
        time.sleep(0.5)
        assert eu_remaining() == 97
        us_resp = us.service.get_peer_rate_limits(
            GetRateLimitsRequest(
                requests=[mk("test_amp", "account:1", hits=0, limit=100,
                             duration=60 * SECOND)]
            )
        )
        assert us_resp.responses[0].remaining == 97
    finally:
        cl.stop()


def test_health_check_unhealthy_on_peer_failure(cluster, clock):
    """TestHealthCheck (functional_test.go:715-782) simplified: kill a
    peer, force a forwarded request to fail, health goes unhealthy with
    a connection error; restart recovers the cluster."""
    entry = cluster.daemons[1]
    # Pick any key owned by a daemon other than the entry: that owner
    # becomes the victim (FNV-1 clusters common-prefix keys, so a fixed
    # victim index may own none of them).
    key = victim_idx = None
    addr_to_idx = {d.peer_info.grpc_address: i for i, d in enumerate(cluster.daemons)}
    for i in range(200):
        k = f"{i}_hc"
        addr = entry.service.get_peer(f"test_health_{k}").info.grpc_address
        if addr != entry.peer_info.grpc_address:
            key, victim_idx = k, addr_to_idx[addr]
            break
    assert key is not None
    cluster.daemons[victim_idx].close()

    client = V1Client(entry.peer_info.http_address)
    resp = client.get_rate_limits(
        GetRateLimitsRequest(requests=[mk("test_health", key, limit=5)])
    )
    assert resp.responses[0].error != ""

    def unhealthy():
        hc = client.health_check()
        return hc.status == "unhealthy" and "failed" in hc.message

    assert until_pass(unhealthy)

    # An unhealthy payload is still a successful RPC: the wire-outcome
    # status label stays "0" (reference tags per-RPC outcomes, not
    # payload health, grpc_stats.go:95-118).
    counts = entry.service.metrics.request_counts
    assert (
        counts.labels(
            status="0", method="/pb.gubernator.V1/HealthCheck"
        )._value.get()
        > 0
    )

    # Restart the victim (cluster.Restart, cluster/cluster.go:87-93).
    cluster.restart(victim_idx, clock=clock)
    resp = client.get_rate_limits(
        GetRateLimitsRequest(requests=[mk("test_health", key, limit=5)])
    )
    assert resp.responses[0].error == ""


def test_health_check_error_label_on_raise(cluster, monkeypatch):
    """A HealthCheck RPC that RAISES is counted with status="1" (wire
    outcome) at the transport edge, matching the reference's per-RPC
    error tagging (grpc_stats.go:95-118)."""
    daemon = cluster.daemons[0]
    svc = daemon.service
    counts = svc.metrics.request_counts
    label = counts.labels(status="1", method="/pb.gubernator.V1/HealthCheck")
    before = label._value.get()
    monkeypatch.setattr(
        svc, "_health_check", lambda: (_ for _ in ()).throw(RuntimeError("boom"))
    )
    client = V1Client(daemon.peer_info.http_address)
    with pytest.raises(Exception):
        client.health_check()  # gateway returns 500; edge counts the raise
    assert label._value.get() == before + 1


def test_change_limit_over_http(cluster):
    """Dynamic config change on a live limit (TestChangeLimit,
    functional_test.go:548-641): raising/lowering the limit adjusts
    remaining by the delta; the algorithm can be switched mid-stream
    (which resets the bucket)."""
    client = client_for(cluster)

    def hit(limit, algo=Algorithm.TOKEN_BUCKET):
        resp = client.get_rate_limits(
            GetRateLimitsRequest(
                requests=[mk("test_change_limit", "acct:9", limit=limit, algo=algo)]
            )
        )
        rl = resp.responses[0]
        assert rl.error == ""
        return rl

    r = hit(10)
    assert (r.status, r.remaining, r.limit) == (Status.UNDER_LIMIT, 9, 10)
    # Lower the limit: remaining += (5 - 10) -> 4 - 1 hit = wait,
    # delta applies pre-hit: 9 + (5-10) = 4, then this hit -> 3.
    r = hit(5)
    assert (r.status, r.remaining, r.limit) == (Status.UNDER_LIMIT, 3, 5)
    # Raise the limit: 3 + (50-5) = 48, hit -> 47.
    r = hit(50)
    assert (r.status, r.remaining, r.limit) == (Status.UNDER_LIMIT, 47, 50)
    # Switch the algorithm: bucket resets (algorithms.go:54-62).
    r = hit(3, algo=Algorithm.LEAKY_BUCKET)
    assert (r.status, r.remaining, r.limit) == (Status.UNDER_LIMIT, 2, 3)


def test_reset_remaining_over_http(cluster):
    """RESET_REMAINING refills a drained bucket (functional_test.go:643-713)."""
    client = client_for(cluster)

    def hit(hits=1, behavior=0):
        resp = client.get_rate_limits(
            GetRateLimitsRequest(
                requests=[
                    mk("test_reset_remaining", "acct:77", hits=hits, limit=3,
                       behavior=behavior)
                ]
            )
        )
        return resp.responses[0]

    assert hit(hits=3).remaining == 0
    assert hit().status == Status.OVER_LIMIT
    r = hit(hits=0, behavior=Behavior.RESET_REMAINING)
    assert r.error == ""
    r = hit()
    assert (r.status, r.remaining) == (Status.UNDER_LIMIT, 2)


def test_ingress_batching_coalesces_concurrent_requests():
    """Concurrent single-item client requests on one daemon must
    coalesce into fewer device dispatches (the ingress BatchWait
    window) while preserving sequential per-key semantics."""
    import threading

    from gubernator_tpu.config import BehaviorConfig, DaemonConfig
    from gubernator_tpu.daemon import spawn_daemon

    d = spawn_daemon(
        DaemonConfig(
            listen_address="127.0.0.1:0",
            cache_size=1024,
            # express=False: this test pins the WINDOWED coalescing
            # mechanism itself (with the express lane on, a shallow
            # herd of singles bypasses the window by design and rides
            # solo/fused dispatches instead — tests/test_express.py
            # covers that path).
            behaviors=BehaviorConfig(batch_wait_s=0.05, express=False),
        )
    )
    try:
        store = d.service.store
        calls = []
        orig_apply = store.apply
        orig_cols = store.apply_columns_async

        def counting_apply(reqs, now, **kw):
            calls.append(len(reqs))
            return orig_apply(reqs, now, **kw)

        def counting_cols(keys, *a, **kw):
            # Single-key BATCHING requests ride the columnar coalescer
            # (service._submit_single_local); count those dispatches too.
            calls.append(len(keys))
            return orig_cols(keys, *a, **kw)

        store.apply = counting_apply
        store.apply_columns_async = counting_cols
        client = V1Client(d.gateway.address)
        results = []
        lock = threading.Lock()
        # Fire all requests as simultaneously as the host allows; under
        # load, staggered arrivals can otherwise each miss the window.
        barrier = threading.Barrier(20)

        def one():
            barrier.wait(timeout=10)
            r = client.get_rate_limits(
                GetRateLimitsRequest(
                    requests=[mk("ingress_batch", "same_key", limit=100)]
                )
            ).responses[0]
            with lock:
                results.append(r)

        threads = [threading.Thread(target=one) for _ in range(20)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert len(results) == 20
        assert all(r.error == "" for r in results)
        # Sequential semantics: 20 hits on one key -> 20 distinct
        # remaining values 99..80, regardless of coalescing.
        assert sorted(r.remaining for r in results) == list(range(80, 100))
        # Coalescing happened: fewer dispatches than requests.
        batched = [c for c in calls if c > 1]
        assert batched, f"no coalesced dispatch observed: {calls}"
    finally:
        d.close()
