"""Build-chain discipline for the native runtime: the shipped
`_host_runtime_<digest>.so` (the gitignored build cache `make native`
and the on-import rebuild both populate) must match a source hash of
host_runtime.cpp — the hash-suffix rule — so a source edit can never
silently serve a stale binary and superseded binaries never linger in
the package."""

from __future__ import annotations

import glob
import os

from gubernator_tpu import native


def test_built_so_matches_source_hash():
    """The .so whose name suffix is sha256(host_runtime.cpp)[:16] must
    exist next to the source (build it with `make native`)."""
    path = native.lib_path()
    assert os.path.exists(path), (
        f"native runtime binary is stale or missing: expected {path} "
        f"(source digest {native.source_digest()}); run `make native`"
    )


def test_no_stale_binaries_shipped():
    """Exactly one hash-suffixed .so may live in the package: stale
    siblings from superseded sources must not serve (defense in depth
    over the age-based runtime prune)."""
    here = os.path.dirname(os.path.abspath(native.__file__))
    sos = sorted(glob.glob(os.path.join(here, "_host_runtime_*.so")))
    assert sos == [native.lib_path()], (
        f"unexpected native binaries checked in: {sos} "
        f"(want exactly {native.lib_path()})"
    )


def test_build_is_idempotent_and_loads():
    """`native.build()` with the binary already present is a no-op
    returning the same path, and the runtime actually loads."""
    path = native.build()
    assert path == native.lib_path()
    assert native.available(), native.build_error()
