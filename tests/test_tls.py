"""TLS integration tests (reference tls_test.go).

Covers: daemon with generated file certs served over HTTPS, AutoTLS
(self-signed CA + server cert on the fly, tls_test.go:57-76), mTLS
require-and-verify incl. the negative no-client-cert case
(tls_test.go:157-204), and a 2-node TLS cluster where a real
peer-forwarded call is verified by scraping the owner's /metrics for
the peer data-plane request count (tls_test.go:206-260).
"""

import os
import shutil
import ssl

import pytest

from gubernator_tpu import tls as tlsmod
from gubernator_tpu.client import V1Client
from gubernator_tpu.cluster import fast_test_behaviors
from gubernator_tpu.config import DaemonConfig, setup_daemon_config
from gubernator_tpu.daemon import Daemon
from gubernator_tpu.types import (
    Algorithm,
    GetRateLimitsRequest,
    RateLimitRequest,
    SECOND,
)

# Checked-in long-lived test certs (certs/, reference parity with the
# reference repo's certs/ + cli-tls.conf) so the file-cert paths run
# without the openssl binary; only AutoTLS (which self-signs at
# runtime) still needs it.
_CERT_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "certs")

needs_openssl = pytest.mark.skipif(
    shutil.which("openssl") is None, reason="openssl binary required"
)


@pytest.fixture(scope="module")
def certs():
    d = _CERT_DIR
    fixture = {
        "ca": os.path.join(d, "ca.pem"),
        "ca_key": os.path.join(d, "ca.key"),
        "crt": os.path.join(d, "gubernator.pem"),
        "key": os.path.join(d, "gubernator.key"),
        "cli_crt": os.path.join(d, "client-auth.pem"),
        "cli_key": os.path.join(d, "client-auth.key"),
    }
    missing = [p for p in fixture.values() if not os.path.exists(p)]
    assert not missing, f"committed cert fixtures missing: {missing}"
    return fixture


def spawn(tls_conf, dc=""):
    return Daemon(
        DaemonConfig(
            listen_address="127.0.0.1:0",
            behaviors=fast_test_behaviors(),
            peer_discovery_type="static",
            data_center=dc,
            tls=tls_conf,
        )
    ).start()


def mk(key, hits=1, limit=10):
    return RateLimitRequest(
        name="tls_test", unique_key=key, hits=hits, limit=limit,
        duration=9 * SECOND, algorithm=Algorithm.TOKEN_BUCKET,
    )


def one(client, key, limit=10):
    resp = client.get_rate_limits(GetRateLimitsRequest(requests=[mk(key, limit=limit)]))
    return resp.responses[0]


def test_server_tls_with_file_certs(certs):
    d = spawn(tlsmod.TLSConfig(ca_file=certs["ca"], cert_file=certs["crt"], key_file=certs["key"]))
    try:
        ctx = tlsmod.client_context(ca_file=certs["ca"])
        ctx.check_hostname = False  # cert SANs cover IPs, not required here
        client = V1Client(d.peer_info.http_address, tls_context=ctx)
        rl = one(client, "file_certs")
        assert rl.error == "" and rl.remaining == 9
        assert "gubernator_cache_size" in client.metrics_text()
    finally:
        d.close()


@needs_openssl
def test_auto_tls(certs):
    """tls_test.go:57-76: no cert files at all; AutoTLS self-signs."""
    d = spawn(tlsmod.TLSConfig(auto_tls=True))
    try:
        ctx = tlsmod.client_context(insecure_skip_verify=True)
        client = V1Client(d.peer_info.http_address, tls_context=ctx)
        assert one(client, "auto_tls").error == ""
    finally:
        d.close()


def test_mtls_require_and_verify(certs):
    conf = tlsmod.TLSConfig(
        ca_file=certs["ca"], cert_file=certs["crt"], key_file=certs["key"],
        client_auth="require-and-verify",
        client_auth_cert_file=certs["cli_crt"],
        client_auth_key_file=certs["cli_key"],
    )
    d = spawn(conf)
    try:
        ctx = tlsmod.client_context(
            ca_file=certs["ca"], cert_file=certs["cli_crt"], key_file=certs["cli_key"]
        )
        ctx.check_hostname = False
        client = V1Client(d.peer_info.http_address, tls_context=ctx)
        assert one(client, "mtls_ok").error == ""

        # Negative: no client cert -> handshake/request must fail
        # (tls_test.go:157-204).
        bare = tlsmod.client_context(ca_file=certs["ca"])
        bare.check_hostname = False
        bad = V1Client(d.peer_info.http_address, tls_context=bare, timeout_s=2.0)
        with pytest.raises((ssl.SSLError, OSError, RuntimeError)):
            one(bad, "mtls_missing_cert")
    finally:
        d.close()


def test_two_node_tls_cluster_peer_forwarding(certs):
    """tls_test.go:206-260: two TLS daemons; a key owned by the OTHER
    node forces a peer-forwarded call over mTLS, observed via the
    owner's gubernator_grpc_request_counts for GetPeerRateLimits."""
    conf = lambda: tlsmod.TLSConfig(  # noqa: E731
        ca_file=certs["ca"], cert_file=certs["crt"], key_file=certs["key"],
        client_auth="require-and-verify",
    )
    d1, d2 = spawn(conf()), spawn(conf())
    try:
        peers = [d1.peer_info, d2.peer_info]
        d1.set_peers(peers)
        d2.set_peers(peers)
        ctx = tlsmod.client_context(
            ca_file=certs["ca"], cert_file=certs["crt"], key_file=certs["key"]
        )
        ctx.check_hostname = False
        client = V1Client(d1.peer_info.http_address, tls_context=ctx)
        # find a key d1 does NOT own so the call crosses the TLS peer leg
        for i in range(100):
            key = f"{i}_fwd_tls"
            if not d1.service.get_peer(f"tls_test_{key}").info.is_owner:
                break
        else:
            pytest.skip("no foreign key found")
        rl = one(client, key)
        assert rl.error == "" and rl.remaining == 9
        oc = V1Client(d2.peer_info.http_address, tls_context=ctx)
        metrics = oc.metrics_text()
        # Either PeersV1 data-plane method proves the forward crossed
        # the TLS peer leg (columnar peers use GetPeerRateLimitsColumns,
        # classic peers GetPeerRateLimits — wire.py "columnar peer hop").
        assert (
            'method="/pb.gubernator.PeersV1/GetPeerRateLimitsColumns"' in metrics
            or 'method="/pb.gubernator.PeersV1/GetPeerRateLimits"' in metrics
        )
    finally:
        d1.close()
        d2.close()


def test_tls_env_config(certs):
    conf = setup_daemon_config(env={
        "GUBER_TLS_CA": certs["ca"],
        "GUBER_TLS_CERT": certs["crt"],
        "GUBER_TLS_KEY": certs["key"],
        "GUBER_TLS_CLIENT_AUTH": "require-and-verify",
    })
    assert conf.tls is not None
    assert conf.tls.client_auth == "require-and-verify"
    assert setup_daemon_config(env={}).tls is None


def test_cli_tls_conf_fixture_parses():
    """The checked-in cli-tls.conf (reference cli-tls.conf:1-6 twin)
    must wire the committed certs/ fixtures into a TLS DaemonConfig."""
    root = os.path.dirname(_CERT_DIR)
    conf = setup_daemon_config(config_file=os.path.join(root, "cli-tls.conf"), env={})
    assert conf.tls is not None
    assert conf.tls.ca_file.endswith("certs/ca.pem")
    assert conf.tls.cert_file.endswith("certs/gubernator.pem")
