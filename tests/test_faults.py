"""Unit tests for the peer fault-tolerance layer (gubernator_tpu.faults):
circuit breaker state machine, jittered backoff, deterministic fault
plans, the PeerClient integration (breaker gate + injected faults +
bounded error LRU), config knobs, and seedable gossip probe ordering.

Cluster-level chaos scenarios (peer kill / partition under load) live
in tests/test_chaos.py.
"""

import random

import pytest

from gubernator_tpu import faults
from gubernator_tpu.config import BehaviorConfig, setup_daemon_config
from gubernator_tpu.faults import Backoff, CircuitBreaker, FaultPlan, FaultRule
from gubernator_tpu.peer_client import (
    PeerClient,
    PeerError,
    is_circuit_open,
    is_not_ready,
)
from gubernator_tpu.types import GetRateLimitsRequest, PeerInfo, RateLimitRequest


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, s):
        self.t += s


# ----------------------------------------------------------------------
# CircuitBreaker
# ----------------------------------------------------------------------
def test_breaker_opens_after_threshold():
    clk = FakeClock()
    b = CircuitBreaker(failure_threshold=3, open_interval_s=1.0, clock=clk)
    for _ in range(2):
        assert b.allow()
        b.record_failure()
    assert b.state == faults.CLOSED
    assert b.allow()
    b.record_failure()
    assert b.state == faults.OPEN
    assert not b.allow()  # fast-fail while open


def test_breaker_success_resets_failure_count():
    clk = FakeClock()
    b = CircuitBreaker(failure_threshold=2, open_interval_s=1.0, clock=clk)
    b.record_failure()
    b.record_success()
    b.record_failure()
    assert b.state == faults.CLOSED  # never reached 2 consecutive


def test_breaker_half_open_single_probe_then_close():
    clk = FakeClock()
    b = CircuitBreaker(failure_threshold=1, open_interval_s=1.0, clock=clk)
    b.record_failure()
    assert b.state == faults.OPEN
    clk.advance(1.0)
    assert b.state == faults.HALF_OPEN  # observer view past the interval
    assert b.allow()  # this caller is the probe
    assert not b.allow()  # only one probe slot
    b.record_success()
    assert b.state == faults.CLOSED
    assert b.allow()


def test_breaker_half_open_probe_failure_reopens():
    clk = FakeClock()
    b = CircuitBreaker(failure_threshold=1, open_interval_s=1.0, clock=clk)
    b.record_failure()
    clk.advance(1.0)
    assert b.allow()
    b.record_failure()
    assert b.state == faults.OPEN
    assert not b.allow()  # a fresh open interval started
    clk.advance(1.0)
    assert b.allow()
    b.record_success()
    assert b.state == faults.CLOSED


def test_breaker_is_open_covers_half_open():
    clk = FakeClock()
    b = CircuitBreaker(failure_threshold=1, open_interval_s=1.0, clock=clk)
    assert not b.is_open
    b.record_failure()
    assert b.is_open
    clk.advance(1.0)
    assert b.is_open  # half-open peers are not yet re-trusted


def test_breaker_transition_callback():
    clk = FakeClock()
    seen = []
    b = CircuitBreaker(
        failure_threshold=1, open_interval_s=1.0, clock=clk,
        on_transition=seen.append,
    )
    b.record_failure()
    clk.advance(1.0)
    b.allow()
    b.record_success()
    assert seen == [faults.OPEN, faults.HALF_OPEN, faults.CLOSED]


# ----------------------------------------------------------------------
# Backoff
# ----------------------------------------------------------------------
def test_backoff_cap_growth_and_ceiling():
    bo = Backoff(base_s=0.1, max_s=0.5, multiplier=2.0)
    assert bo.cap(0) == pytest.approx(0.1)
    assert bo.cap(1) == pytest.approx(0.2)
    assert bo.cap(2) == pytest.approx(0.4)
    assert bo.cap(3) == pytest.approx(0.5)  # clamped
    assert bo.cap(10) == pytest.approx(0.5)


def test_backoff_full_jitter_within_envelope_and_seeded():
    a = Backoff(base_s=0.1, max_s=1.0, rng=random.Random(7))
    b = Backoff(base_s=0.1, max_s=1.0, rng=random.Random(7))
    for attempt in range(6):
        da, db = a.delay(attempt), b.delay(attempt)
        assert da == db  # same seed, same jitter sequence
        assert 0.0 <= da <= a.cap(attempt)


# ----------------------------------------------------------------------
# FaultPlan
# ----------------------------------------------------------------------
def test_plan_error_nth_window():
    p = FaultPlan(seed=1)
    p.error_nth("a:1", 2, count=2)
    assert p.intercept("a:1", "Op") is None  # call 1
    assert p.intercept("a:1", "Op").kind == faults.ERROR  # call 2
    assert p.intercept("a:1", "Op").kind == faults.ERROR  # call 3
    assert p.intercept("a:1", "Op") is None  # call 4: window over
    assert p.calls("a:1", "Op") == 4


def test_plan_drop_is_timeout_shaped():
    p = FaultPlan(seed=1)
    rule = p.drop_nth("a:1", 1)
    act = p.intercept("a:1", "Op")
    assert act.kind == faults.DROP
    assert act.not_ready is False  # may have executed server-side: no retry
    assert p.fired(rule) == 1


def test_plan_counters_are_per_peer_and_op():
    p = FaultPlan(seed=1)
    p.error_nth("a:1", 2)
    assert p.intercept("a:1", "X") is None
    assert p.intercept("a:1", "Y") is None  # different op: own counter
    assert p.intercept("b:1", "X") is None  # different peer: own counter
    assert p.intercept("a:1", "X").kind == faults.ERROR


def test_plan_rate_is_seed_deterministic():
    def decisions(seed):
        p = FaultPlan(seed=seed)
        p.add(FaultRule(peer="*", op="*", kind=faults.ERROR, rate=0.5))
        return [p.intercept("a:1", "Op") is not None for _ in range(64)]

    d1, d2 = decisions(42), decisions(42)
    assert d1 == d2  # same seed, same decision sequence
    assert any(d1) and not all(d1)  # the rate actually gates


def test_plan_heal_and_partition():
    p = FaultPlan(seed=1)
    p.partition("a:1")
    assert p.intercept("a:1", "Op").kind == faults.ERROR
    assert p.intercept("b:1", "Op") is None
    assert p.heal("a:1") == 1
    assert p.intercept("a:1", "Op") is None


def test_install_uninstall_and_context_manager():
    plan = FaultPlan(seed=1)
    assert faults.active() is None
    with faults.injected(plan) as got:
        assert got is plan
        assert faults.active() is plan
    assert faults.active() is None


# ----------------------------------------------------------------------
# PeerClient integration
# ----------------------------------------------------------------------
def _client(plan=None, threshold=3):
    behaviors = BehaviorConfig(
        circuit_threshold=threshold, circuit_open_interval_s=60.0
    )
    info = PeerInfo(grpc_address="127.0.0.1:1", http_address="127.0.0.1:1")
    return PeerClient(info, behaviors, transport="grpc", faults=plan)


def _req():
    return GetRateLimitsRequest(
        requests=[RateLimitRequest(name="n", unique_key="k", hits=1, limit=1)]
    )


def test_peer_client_injected_fault_counts_toward_breaker():
    plan = FaultPlan(seed=1)
    plan.partition("127.0.0.1:1")
    c = _client(plan, threshold=3)
    for _ in range(3):
        with pytest.raises(PeerError) as ei:
            c.get_peer_rate_limits(_req())
        assert is_not_ready(ei.value)
        assert not is_circuit_open(ei.value)
    assert c.breaker.state == faults.OPEN
    # Breaker now fast-fails BEFORE the fault plan / wire is consulted.
    before = plan.calls("127.0.0.1:1", "GetPeerRateLimits")
    with pytest.raises(PeerError) as ei:
        c.get_peer_rate_limits(_req())
    assert is_circuit_open(ei.value)
    assert is_not_ready(ei.value)
    assert plan.calls("127.0.0.1:1", "GetPeerRateLimits") == before
    # Injected transport errors land in the health error LRU.
    assert any("injected" in e for e in c.get_last_err())
    c.shutdown()


def test_wrong_count_reply_trips_breaker():
    """A peer that consistently returns the wrong number of rate limits
    (version skew) must trip its breaker like any transport failure —
    the count check runs INSIDE the guarded call, so the failure streak
    is not reset by the transport-level success."""
    behaviors = BehaviorConfig(circuit_threshold=2, circuit_open_interval_s=60.0)
    info = PeerInfo(grpc_address="127.0.0.1:1", http_address="127.0.0.1:1")
    c = PeerClient(info, behaviors, transport="http")
    c._post_inner = lambda path, payload, timeout_s: {"rateLimits": []}
    for _ in range(2):
        with pytest.raises(PeerError) as ei:
            c.get_peer_rate_limits(_req())
        assert "returned 0 rate limits for 1" in str(ei.value)
    assert c.breaker.state == faults.OPEN
    with pytest.raises(PeerError) as ei:
        c.get_peer_rate_limits(_req())
    assert is_circuit_open(ei.value)
    c.shutdown()


def test_peer_client_last_err_is_bounded():
    c = _client()
    for i in range(2 * PeerClient.LAST_ERR_MAX):
        c._set_last_err(f"error #{i}")
    errs = c.get_last_err()
    assert len(errs) == PeerClient.LAST_ERR_MAX
    # Oldest evicted, newest kept.
    assert any("error #0 " in e for e in errs) is False
    assert any(f"error #{2 * PeerClient.LAST_ERR_MAX - 1}" in e for e in errs)
    c.shutdown()


# ----------------------------------------------------------------------
# Config knobs
# ----------------------------------------------------------------------
def test_fault_tolerance_env_knobs():
    conf = setup_daemon_config(env={
        "GUBER_CIRCUIT_THRESHOLD": "9",
        "GUBER_CIRCUIT_OPEN_INTERVAL": "500",  # bare number = ms
        "GUBER_FORWARD_RETRY_LIMIT": "2",
        "GUBER_RETRY_BACKOFF_BASE": "10ms",
        "GUBER_RETRY_BACKOFF_MAX": "2s",
        "GUBER_GLOBAL_SEND_RETRIES": "3",
        "GUBER_GOSSIP_SEED": "1234",
    })
    b = conf.behaviors
    assert b.circuit_threshold == 9
    assert b.circuit_open_interval_s == pytest.approx(0.5)
    assert b.forward_retry_limit == 2
    assert b.retry_backoff_base_s == pytest.approx(0.01)
    assert b.retry_backoff_max_s == pytest.approx(2.0)
    assert b.global_send_retries == 3
    assert conf.gossip_seed == 1234


def test_circuit_threshold_must_be_positive():
    with pytest.raises(ValueError):
        setup_daemon_config(env={"GUBER_CIRCUIT_THRESHOLD": "0"})


def test_gossip_seed_defaults_to_none():
    assert setup_daemon_config(env={}).gossip_seed is None


# ----------------------------------------------------------------------
# Gossip: seedable probe ordering
# ----------------------------------------------------------------------
def test_gossip_probe_order_is_seed_deterministic():
    from gubernator_tpu.gossip import Gossip, Member

    def probe_sequence(seed, rounds=12):
        g = Gossip("127.0.0.1:0", probe_interval_s=3600, sync_interval_s=3600,
                   seed=seed)
        try:
            for i in range(6):
                name = f"peer-{i}"
                g._members[name] = Member(
                    name=name, host="127.0.0.1", port=40000 + i
                )
            return [g._next_probe_target().name for _ in range(rounds)]
        finally:
            g.close()

    s1, s2 = probe_sequence(99), probe_sequence(99)
    assert s1 == s2  # same seed -> same SWIM probe schedule
    # Every member is visited each full ring pass (shuffled round-robin).
    assert set(s1[:6]) == {f"peer-{i}" for i in range(6)}


def test_gossip_probe_delay_eats_ack_timeout():
    """An injected DELAY >= the probe timeout is a lost probe (returned
    immediately, no real sleep); a smaller delay leaves only the
    remainder for the ack wait — injected latency can drive suspicion."""
    import time as _time

    from gubernator_tpu.gossip import Gossip

    plan = FaultPlan(seed=1)
    plan.delay("127.0.0.1:9", 10.0, op=faults.OP_GOSSIP_PROBE)
    g = Gossip("127.0.0.1:0", probe_interval_s=3600, sync_interval_s=3600,
               probe_timeout_s=0.3, faults=plan)
    try:
        t0 = _time.monotonic()
        assert g._ping(("127.0.0.1", 9)) is False
        # No 10s sleep AND no 0.3s ack wait: the oversized delay is an
        # immediate loss.
        assert _time.monotonic() - t0 < 0.2
        assert plan.calls("127.0.0.1:9", faults.OP_GOSSIP_PROBE) == 1
    finally:
        g.close()


# ----------------------------------------------------------------------
# WAN rule (federation plane chaos: latency/jitter/loss)
# ----------------------------------------------------------------------
def test_wan_resolves_to_plain_delay_and_drop_actions():
    """WAN resolves at intercept time to the ordinary action kinds, so
    every interception point (PeerClient, gossip) applies it with no
    WAN-specific handling."""
    p = FaultPlan(seed=3)
    p.wan("a:1", latency_s=0.05, jitter_s=0.0, loss=0.0)
    act = p.intercept("a:1", "UpdateRegionColumns")
    assert act.kind == faults.DELAY
    assert act.delay_s == pytest.approx(0.05)

    p2 = FaultPlan(seed=3)
    p2.wan("a:1", latency_s=0.05, jitter_s=0.0, loss=1.0)
    act = p2.intercept("a:1", "UpdateRegionColumns")
    # A lost call is timeout-shaped: it may have applied remotely, so
    # callers must not blind-retry (the federation sender drops these
    # hits COUNTED rather than requeueing).
    assert act.kind == faults.DROP
    assert act.not_ready is False


def test_wan_streams_are_seed_deterministic():
    """Same seed -> the same loss pattern AND the same latency series,
    per (peer, op) stream — the replayable WAN weather the 2x2 soak
    leans on."""
    def run(seed):
        p = FaultPlan(seed=seed)
        p.wan("a:1", latency_s=0.04, jitter_s=0.02, loss=0.3)
        out = []
        for _ in range(64):
            act = p.intercept("a:1", "UpdateRegionColumns")
            out.append(
                ("drop",) if act.kind == faults.DROP
                else ("delay", act.delay_s)
            )
        return out

    a, b = run(11), run(11)
    assert a == b
    assert run(12) != a  # a different seed is different weather
    kinds = {k for k, *_ in a}
    assert kinds == {"drop", "delay"}  # loss=0.3 fires both ways
    delays = [d for k, *rest in a for d in rest]
    assert all(d >= 0.0 for d in delays)  # gauss clamped at 0
    assert len(set(delays)) > 1  # jitter actually varies the latency


def test_wan_streams_are_independent_per_peer_op():
    """Concurrent calls to OTHER peers/ops must not perturb a stream
    (the per-(peer, op) RNG rule every rate-gated kind shares)."""
    p = FaultPlan(seed=5)
    p.wan("*", latency_s=0.04, jitter_s=0.02, loss=0.3)
    solo = FaultPlan(seed=5)
    solo.wan("*", latency_s=0.04, jitter_s=0.02, loss=0.3)

    seq = []
    for i in range(32):
        if i % 2:
            p.intercept("other:1", "Noise")  # interleaved foreign draws
        act = p.intercept("a:1", "UpdateRegionColumns")
        seq.append(act.kind if act.kind == faults.DROP else act.delay_s)
    expect = []
    for _ in range(32):
        act = solo.intercept("a:1", "UpdateRegionColumns")
        expect.append(act.kind if act.kind == faults.DROP else act.delay_s)
    assert seq == expect


def test_wan_rate_gate_composes():
    """rate<1 leaves a fraction of calls untouched (no delay at all) —
    the WAN rule composes with the shared rate machinery."""
    p = FaultPlan(seed=9)
    p.wan("a:1", latency_s=0.01, jitter_s=0.0, loss=0.0, rate=0.5)
    hits = sum(
        1 for _ in range(200) if p.intercept("a:1", "Op") is not None
    )
    assert 60 < hits < 140  # ~half, seeded


def test_wan_heal_removes_the_weather():
    p = FaultPlan(seed=1)
    p.wan("a:1", latency_s=0.01)
    assert p.intercept("a:1", "Op") is not None
    assert p.heal("a:1") == 1
    assert p.intercept("a:1", "Op") is None


def test_specific_rules_beat_wildcard_wan_shape():
    """The 2x2 soak's layering: a steady peer="*" WAN shape installed
    FIRST must not shadow a later per-victim storm or partition —
    most-specific rule wins (exact peer beats "*", then exact op), and
    healing the specific rule falls back to the steady shape."""
    p = FaultPlan(seed=5)
    steady = p.wan(op="UpdateRegionColumns", latency_s=0.02,
                   jitter_s=0.0, loss=0.0)
    # Storm: per-victim total loss layered over the steady shape.
    storm = p.wan(peer="v:1", op="UpdateRegionColumns",
                  latency_s=0.0, jitter_s=0.0, loss=1.0)
    act = p.intercept("v:1", "UpdateRegionColumns")
    assert act.kind == faults.DROP  # the storm, not a 20ms delay
    # Other peers still ride the steady shape.
    act = p.intercept("v:2", "UpdateRegionColumns")
    assert act.kind == faults.DELAY
    assert act.delay_s == pytest.approx(0.02)
    # Healing ONLY the storm (exact peer) falls back to the steady
    # wildcard for the victim too.
    assert p.heal("v:1", "UpdateRegionColumns") == 1
    act = p.intercept("v:1", "UpdateRegionColumns")
    assert act.kind == faults.DELAY
    assert p.fired(steady) >= 1 and p.fired(storm) >= 1

    # partition(victim) is op="*" — less op-specific than the steady
    # rule but MORE peer-specific, and peer specificity dominates: a
    # fully partitioned daemon errors on its region wire as well.
    part = p.partition("v:3")
    act = p.intercept("v:3", "UpdateRegionColumns")
    assert act.kind == faults.ERROR
    assert p.fired(part) == 1


def test_wan_parameter_validation():
    p = FaultPlan(seed=1)
    with pytest.raises(ValueError):
        p.wan("a:1", loss=1.5)
    with pytest.raises(ValueError):
        p.wan("a:1", latency_s=-0.1)
    with pytest.raises(ValueError):
        p.wan("a:1", jitter_s=-0.1)
