"""Property tests for parallel/region.py (region_picker.go:7-95 parity).

The federation plane (federation.py) leans on three picker properties
that were previously untested:

* `get_clients(key)` returns EXACTLY one owner per non-empty region and
  never None (the pre-fix code emitted None when a ring mapped a key to
  a departed peer, and raised outright on an emptied region — either
  crashed the MULTI_REGION flush loop);
* `pick(dc, key)` agrees with that region's ring (it IS the region
  entry of the fan-out set);
* regions are independent rings: add/remove in one region never moves
  ownership in another (the per-region reshard-independence rule the
  2x2 soak's per-region churn leans on).
"""

from __future__ import annotations

import random

import pytest

from gubernator_tpu.parallel.region import RegionPicker
from gubernator_tpu.types import PeerInfo


class FakePeer:
    def __init__(self, addr: str, dc: str):
        self.info = PeerInfo(
            grpc_address=addr, http_address=f"h{addr}", data_center=dc
        )

    def __repr__(self):  # pragma: no cover — assertion messages only
        return f"FakePeer({self.info.grpc_address}@{self.info.data_center})"


def build(topology: dict) -> tuple:
    """{dc: n_peers} -> (picker, {dc: [peers]})."""
    rp = RegionPicker()
    peers = {}
    for dc, n in topology.items():
        peers[dc] = [FakePeer(f"{dc}-{i}:81", dc) for i in range(n)]
        for p in peers[dc]:
            rp.add(p)
    return rp, peers


KEYS = [f"name_k{i}" for i in range(200)]


def test_get_clients_exactly_one_owner_per_region():
    rp, _ = build({"us": 3, "eu": 2, "ap": 1})
    for key in KEYS:
        owners = rp.get_clients(key)
        assert len(owners) == 3
        assert all(o is not None for o in owners)
        # one owner PER region — no region double-represented
        dcs = [o.info.data_center for o in owners]
        assert sorted(dcs) == ["ap", "eu", "us"]


def test_pick_agrees_with_the_per_region_ring():
    rp, _ = build({"us": 3, "eu": 2})
    for key in KEYS:
        by_fanout = {
            o.info.data_center: o.info.grpc_address
            for o in rp.get_clients(key)
        }
        for dc in ("us", "eu"):
            picked = rp.pick(dc, key)
            assert picked is not None
            assert picked.info.grpc_address == by_fanout[dc]
            # and the underlying ring agrees with both
            ring = rp.regions[dc]
            assert picked.info.grpc_address == ring.get(key)


def test_pick_unknown_or_empty_region_is_none():
    rp, peers = build({"us": 1})
    assert rp.pick("nowhere", "name_k") is None
    rp.remove(peers["us"][0])
    # last peer left: the region disappears rather than lingering empty
    assert "us" not in rp.regions
    assert rp.pick("us", "name_k") is None
    assert rp.get_clients("name_k") == []
    assert rp.region_names() == []


def test_add_remove_keeps_other_regions_ownership_stable():
    rp, peers = build({"us": 4, "eu": 3, "ap": 2})
    before = {
        dc: {k: rp.pick(dc, k).info.grpc_address for k in KEYS}
        for dc in ("eu", "ap")
    }
    # Churn the US region hard: drop two members, add two new ones.
    rp.remove(peers["us"][0])
    rp.remove(peers["us"][2])
    rp.add(FakePeer("us-9:81", "us"))
    rp.add(FakePeer("us-10:81", "us"))
    for dc in ("eu", "ap"):
        after = {k: rp.pick(dc, k).info.grpc_address for k in KEYS}
        assert after == before[dc], f"{dc} ownership moved under US churn"
    # and US itself still answers exactly one live owner per key
    live = {p.info.grpc_address for p in rp.regions["us"].peers()}
    for k in KEYS:
        assert rp.pick("us", k).info.grpc_address in live


def test_remove_departed_peer_never_yields_none():
    """The satellite bug: after a member departs, every key it owned
    must re-map to a surviving peer — get_clients must keep the
    one-owner-per-region property, not emit None."""
    rng = random.Random(7)
    rp, peers = build({"us": 5, "eu": 3})
    order = peers["us"][:]
    rng.shuffle(order)
    for departing in order[:4]:  # leave one survivor
        rp.remove(departing)
        gone = departing.info.grpc_address
        for key in KEYS:
            owners = rp.get_clients(key)
            assert len(owners) == 2
            assert all(o is not None for o in owners)
            assert all(o.info.grpc_address != gone for o in owners)


def test_remove_non_member_is_a_noop():
    rp, _ = build({"us": 2})
    before = {k: rp.pick("us", k).info.grpc_address for k in KEYS}
    rp.remove(FakePeer("us-99:81", "us"))       # never added
    rp.remove(FakePeer("eu-0:81", "eu"))        # unknown region
    after = {k: rp.pick("us", k).info.grpc_address for k in KEYS}
    assert after == before


def test_region_names_tracks_membership():
    rp, peers = build({"us": 1, "eu": 1})
    assert sorted(rp.region_names()) == ["eu", "us"]
    rp.remove(peers["eu"][0])
    assert rp.region_names() == ["us"]


def test_new_inherits_template_but_not_members():
    rp, _ = build({"us": 2})
    fresh = rp.new()
    assert fresh.regions == {}
    assert fresh.get_clients("name_k") == []


@pytest.mark.parametrize("n", [1, 2, 7])
def test_pick_is_stable_and_member_valued(n):
    """Owner picks are deterministic and always live members.  (Full
    coverage of every member is NOT a property of this ring: at the
    reference's replica count an unlucky vnode layout can leave a
    member owning ~no keys — replicated_hash.go accepts that too.)"""
    rp, peers = build({"us": n})
    members = {p.info.grpc_address for p in peers["us"]}
    first = {k: rp.pick("us", k).info.grpc_address for k in KEYS}
    assert set(first.values()) <= members
    again = {k: rp.pick("us", k).info.grpc_address for k in KEYS}
    assert again == first
