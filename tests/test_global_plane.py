"""Columnar GLOBAL replication plane (architecture.md "GLOBAL plane").

Covers the acceptance legs of the encode-once / batched-commit design:

* receiver — an N-item broadcast commits in O(1) device dispatches
  (counted, not timed), and the batched commit is state-identical to
  the per-item loop it replaced (eviction pressure and duplicate keys
  included);
* sender — the broadcast fan-out is concurrent and encode-once (every
  peer receives the SAME BroadcastBatch object), and aggregated hits
  whose owner is unroutable or whose send provably never applied
  REQUEUE into the next tick instead of being dropped (the pre-columns
  sender lost them — the regression this pins);
* mixed-version interop — a columnar-plane daemon and a daemon running
  GUBER_GLOBAL_COLUMNS=0 (+ GUBER_PEER_COLUMNS=0: the full pre-columns
  wire behavior) replicate to each other in both directions, with the
  negotiation landing where it must and health staying clean;
* chaos — seeded FaultPlan drop/error/delay on the broadcast and
  hit-forward RPCs: breaker interplay, and every hit lane accounted
  (delivered exactly once, requeued, or counted dropped).
"""

import time

import numpy as np
import pytest

from gubernator_tpu import wire
from gubernator_tpu.cluster import fast_test_behaviors
from gubernator_tpu.config import BehaviorConfig, DaemonConfig
from gubernator_tpu.daemon import Daemon
from gubernator_tpu.faults import ERROR, DROP, FaultPlan, FaultRule
from gubernator_tpu.parallel.global_mgr import GlobalsColumns
from gubernator_tpu.parallel.mesh import MeshBucketStore
from gubernator_tpu.service import ServiceConfig, V1Service
from gubernator_tpu.types import (
    Behavior,
    GetRateLimitsRequest,
    PeerInfo,
    RateLimitRequest,
    RateLimitResponse,
    UpdatePeerGlobal,
)
from gubernator_tpu.utils.clock import Clock

T0 = 1_573_430_430_000


def _update(key, remaining=4, limit=5, reset=T0 + 60_000, algorithm=0):
    return UpdatePeerGlobal(
        key=key, algorithm=algorithm,
        status=RateLimitResponse(
            status=0, limit=limit, remaining=remaining, reset_time=reset
        ),
    )


def _metric_value(counter) -> float:
    return counter._value.get()  # noqa: SLF001 (test-only introspection)


# ----------------------------------------------------------------------
# Receiver: batched replica commit
# ----------------------------------------------------------------------
def test_set_replica_batch_commits_in_o1_dispatches():
    """The acceptance criterion, by dispatch COUNT: a 64-item broadcast
    commits with one scatter program (no evictions -> exactly one),
    and the committed state matches the per-item loop exactly."""
    batched = MeshBucketStore(capacity_per_shard=64, g_capacity=128)
    reference = MeshBucketStore(capacity_per_shard=64, g_capacity=128)
    updates = [
        _update(f"gp_k{i}", remaining=i, limit=100, reset=T0 + 1000 + i)
        for i in range(64)
    ]
    d0 = batched.replica_commit_dispatches
    batched.set_replica_batch(GlobalsColumns.from_updates(updates), T0)
    assert batched.replica_commit_dispatches - d0 == 1

    for u in updates:
        # Reference semantics: the per-item receive (itself a 1-lane
        # batch — d0-delta 64 here, which is exactly what the batched
        # path collapses).
        reference.set_replica(u, T0)

    for u in updates:
        gb = batched.gtable.get(u.key)
        gr = reference.gtable.get(u.key)
        assert gb is not None and gr is not None
        assert batched.gtable.rep_expire[gb] == reference.gtable.rep_expire[gr]
        assert (
            np.asarray(batched.gcols.rep_remaining)[:, gb]
            == np.asarray(reference.gcols.rep_remaining)[:, gr]
        ).all()
        assert (
            np.asarray(batched.gcols.rep_reset)[:, gb]
            == np.asarray(reference.gcols.rep_reset)[:, gr]
        ).all()


def test_set_replica_batch_eviction_and_duplicates_match_per_item():
    """Oracle under pressure: a batch larger than g_capacity (forcing
    evictions mid-batch) with duplicate keys must leave the same final
    host+device state as the per-item loop (keep-last for dupes)."""
    cap = 8
    batched = MeshBucketStore(capacity_per_shard=64, g_capacity=cap)
    reference = MeshBucketStore(capacity_per_shard=64, g_capacity=cap)
    updates = [
        _update(f"gp_e{i}", remaining=i, reset=T0 + 100 + i) for i in range(12)
    ]
    # Duplicates: same key twice with different payloads (last wins).
    updates.append(_update("gp_e11", remaining=77, reset=T0 + 777))
    batched.set_replica_batch(GlobalsColumns.from_updates(updates), T0)
    for u in updates:
        reference.set_replica(u, T0)

    b_rem = np.asarray(batched.gcols.rep_remaining)[0]
    r_rem = np.asarray(reference.gcols.rep_remaining)[0]
    for i in range(12):
        key = f"gp_e{i}"
        gb = batched.gtable.get(key)
        gr = reference.gtable.get(key)
        assert (gb is None) == (gr is None), key
        if gb is None:
            continue
        assert b_rem[gb] == r_rem[gr], key
        assert batched.gtable.rep_expire[gb] == reference.gtable.rep_expire[gr]
    assert b_rem[batched.gtable.get("gp_e11")] == 77


def _quiet_service(**kw) -> V1Service:
    behaviors = BehaviorConfig(
        global_sync_wait_s=3600.0, multi_region_sync_wait_s=3600.0, **kw
    )
    return V1Service(
        ServiceConfig(
            cache_size=1024, global_cache_size=64, behaviors=behaviors
        )
    )


def test_update_peer_globals_batches_unless_knob_off():
    """The service-level receive batches even CLASSIC-encoded
    broadcasts into one commit; GUBER_GLOBAL_COLUMNS=0 restores the
    pre-columns one-dispatch-per-item behavior exactly."""
    svc = _quiet_service()
    try:
        store = svc.store
        d0 = store.replica_commit_dispatches
        svc.update_peer_globals([_update(f"gp_b{i}") for i in range(16)])
        assert store.replica_commit_dispatches - d0 == 1

        svc.conf.behaviors.global_columns = False  # live opt-out
        d0 = store.replica_commit_dispatches
        svc.update_peer_globals([_update(f"gp_c{i}") for i in range(16)])
        assert store.replica_commit_dispatches - d0 == 16
    finally:
        svc.close()


def test_globals_columns_receive_is_lane_capped():
    """Like the forwarded-hits edge, the columnar broadcast receive
    rejects oversized batches (the sender chunks at the same cap) —
    an uncapped batch could churn the whole gslot table in one RPC."""
    from gubernator_tpu.config import PEER_COLUMNS_MAX_LANES
    from gubernator_tpu.service import ApiError

    svc = _quiet_service()
    try:
        n = PEER_COLUMNS_MAX_LANES + 1
        big = GlobalsColumns(
            keys=[f"gp_x{i}" for i in range(n)],
            algorithm=np.zeros(n, np.int32),
            status=np.zeros(n, np.int32),
            limit=np.ones(n, np.int64),
            remaining=np.ones(n, np.int64),
            reset_time=np.full(n, T0 + 60_000, np.int64),
        )
        with pytest.raises(ApiError):
            svc.update_peer_globals_columns(big)
    finally:
        svc.close()


# ----------------------------------------------------------------------
# Sender: requeue accounting + concurrent encode-once fan-out
# ----------------------------------------------------------------------
def test_unroutable_owner_requeues_hits_until_delivered():
    """REGRESSION (pre-columns run_once silently dropped aggregated
    hits when get_peer raised PeerError): with an empty pool the lanes
    carry across ticks without double-counting, and deliver intact
    once an owner is routable."""
    svc = _quiet_service()
    try:
        svc.set_peers([])  # empty pool: get_peer raises PeerError
        req = RateLimitRequest(
            name="glob", unique_key="rq", hits=3, limit=100,
            duration=60_000, behavior=Behavior.GLOBAL,
        )
        svc.store.apply([req], T0, remote_global=True)
        mgr = svc.global_mgr

        mgr.run_once()
        assert mgr._hit_carry["glob_rq"][4] == 3  # requeued, not dropped
        assert _metric_value(svc.metrics.global_requeued_hits) == 1

        mgr.run_once()  # still unroutable: carried again, hits UNCHANGED
        assert mgr._hit_carry["glob_rq"][4] == 3

        delivered = []

        class _StubPeer:
            info = PeerInfo(grpc_address="stub:1", is_owner=False)

            def send_columns_direct(self, cols, timeout_s=None, trace_ctx=None):
                delivered.append(cols)

        svc.get_peer = lambda key: _StubPeer()
        mgr.run_once()
        assert not mgr._hit_carry
        (cols,) = delivered
        assert list(cols[0]) == ["glob"] and list(cols[1]) == ["rq"]
        assert list(cols[4]) == [3]  # hits arrive exactly once
        assert int(cols[3][0]) & int(Behavior.GLOBAL)  # wire keeps GLOBAL
    finally:
        svc.close()


def test_broadcast_fanout_is_concurrent_and_encode_once():
    """Two stub peers must be inside their sends AT THE SAME TIME (the
    barrier only releases when both arrive — a serial fan-out would
    deadlock and fail the timeout), and both must receive the SAME
    BroadcastBatch object (encode-once across peers)."""
    import threading

    svc = _quiet_service()
    try:
        barrier = threading.Barrier(2, timeout=10.0)
        received = []

        class _StubPeer:
            def __init__(self, addr):
                self.info = PeerInfo(grpc_address=addr, is_owner=False)

            def update_peer_globals_batch(self, batch, timeout_s=None,
                                          trace_ctx=None):
                barrier.wait()
                received.append(batch)

        stubs = [_StubPeer("stub:1"), _StubPeer("stub:2")]
        svc.get_peer_list = lambda: stubs
        bcols = GlobalsColumns.from_updates([_update("gp_f0")])
        svc.global_mgr._broadcast(bcols, None)
        assert len(received) == 2
        assert received[0] is received[1]  # one encoded batch, all peers
    finally:
        svc.close()


# ----------------------------------------------------------------------
# Mixed-version interop: columnar plane <-> pre-columns daemon
# ----------------------------------------------------------------------
@pytest.fixture(scope="module")
def mixed_global_cluster():
    """Daemon A runs the columnar GLOBAL plane; daemon B runs
    GUBER_GLOBAL_COLUMNS=0 + GUBER_PEER_COLUMNS=0 — the full wire
    behavior of a pre-columns build (no globals gRPC method, no frame
    sniff, per-item replica commits, classic sender)."""
    clock = Clock()
    clock.freeze(T0)
    daemons = []
    for new_plane in (True, False):
        behaviors = fast_test_behaviors()
        behaviors.peer_columns = new_plane
        behaviors.global_columns = new_plane
        behaviors.global_sync_wait_s = 3600.0
        behaviors.multi_region_sync_wait_s = 3600.0
        d = Daemon(
            DaemonConfig(
                listen_address="127.0.0.1:0",
                grpc_listen_address="127.0.0.1:0",
                cache_size=4096,
                global_cache_size=256,
                behaviors=behaviors,
                peer_discovery_type="static",
            ),
            clock=clock,
        ).start()
        daemons.append(d)
    peers = [d.peer_info for d in daemons]
    for d in daemons:
        d.set_peers(peers)
    yield daemons, clock
    for d in daemons:
        d.close()


def _owned_key(owner, name, taken=()):
    """A unique_key whose hash key is owned by `owner`."""
    i = 0
    while True:
        key = f"k{i}"
        if key not in taken and owner.service.get_peer(
            f"{name}_{key}"
        ).info.is_owner:
            return key
        i += 1


def _global_req(name, key, hits=1, limit=50):
    return RateLimitRequest(
        name=name, unique_key=key, hits=hits, limit=limit,
        duration=60_000, behavior=Behavior.GLOBAL,
    )


def _peer_client_for(entry, addr):
    for p in entry.service.get_peer_list():
        if p.info.grpc_address == addr:
            return p
    raise AssertionError(f"no client for {addr}")


def test_interop_new_owner_broadcasts_to_old_peer(mixed_global_cluster):
    daemons, clock = mixed_global_cluster
    new, old = daemons
    key = _owned_key(new, "iba")
    hk = f"iba_{key}"
    new.service.get_rate_limits(
        GetRateLimitsRequest(requests=[_global_req("iba", key, hits=4)])
    )
    assert new.service.global_mgr.run_once()
    # The probe got UNIMPLEMENTED from the pre-columns daemon; the
    # classic resend landed inside the same guarded call.
    client = _peer_client_for(new, old.peer_info.grpc_address)
    assert client._globals_columnar is False
    g = old.service.store.gtable.get(hk)
    assert g is not None and old.service.store.gtable.rep_expire[g] > T0
    # Breaker/health-neutral negotiation.
    assert not client.breaker.is_open
    assert new.service.health_check().status == "healthy"


def test_interop_old_owner_broadcasts_to_new_peer(mixed_global_cluster):
    daemons, clock = mixed_global_cluster
    new, old = daemons
    key = _owned_key(old, "ibb")
    hk = f"ibb_{key}"
    old.service.get_rate_limits(
        GetRateLimitsRequest(requests=[_global_req("ibb", key, hits=2)])
    )
    d0 = new.service.store.replica_commit_dispatches
    assert old.service.global_mgr.run_once()
    # Old sender never probes (knob off at construction); the new
    # receiver still commits the classic broadcast as ONE batch.
    client = _peer_client_for(old, new.peer_info.grpc_address)
    assert client._globals_columnar is False
    g = new.service.store.gtable.get(hk)
    assert g is not None and new.service.store.gtable.rep_expire[g] > T0
    assert new.service.store.replica_commit_dispatches - d0 == 1


def test_interop_hits_converge_new_entry_old_owner(mixed_global_cluster):
    """Full loop: GLOBAL hits land at the NEW daemon for a key the OLD
    daemon owns; the forwarded hits ride the (classic-negotiated)
    GetPeerRateLimits leg, the old owner applies and broadcasts back,
    and the authoritative count is exact."""
    daemons, clock = mixed_global_cluster
    new, old = daemons
    key = _owned_key(old, "ibc")
    total = 0
    for hits in (3, 2):
        new.service.get_rate_limits(
            GetRateLimitsRequest(requests=[_global_req("ibc", key, hits=hits)])
        )
        total += hits
    assert new.service.global_mgr.run_once()  # forward aggregated hits
    old.service.global_mgr.run_once()  # owner applies + broadcasts
    r = old.service.get_rate_limits(
        GetRateLimitsRequest(requests=[_global_req("ibc", key, hits=0)])
    ).responses[0]
    assert not r.error
    assert r.remaining == 50 - total


def test_http_transport_globals_frame_and_fallback(mixed_global_cluster):
    """The HTTP leg of the broadcast wire: a frame POST to the new
    daemon's gateway commits batched; the same frame to the knob-off
    daemon answers 4xx (its JSON parse rejects the magic, exactly like
    a pre-columns build), the client downgrades inside the guarded
    call, and health stays clean."""
    daemons, clock = mixed_global_cluster
    new, old = daemons
    behaviors = fast_test_behaviors()
    bcols = GlobalsColumns.from_updates(
        [_update(f"http_h{i}", reset=T0 + 60_000) for i in range(8)]
    )
    for daemon, want_columnar, expect_batched in (
        (new, True, True), (old, False, False)
    ):
        from gubernator_tpu.peer_client import PeerClient

        client = PeerClient(
            PeerInfo(
                grpc_address=daemon.peer_info.grpc_address,
                http_address=daemon.peer_info.http_address,
            ),
            behaviors,
            transport="http",
        )
        try:
            store = daemon.service.store
            d0 = store.replica_commit_dispatches
            client.update_peer_globals_batch(
                wire.BroadcastBatch(bcols), timeout_s=5.0
            )
            assert client._globals_columnar is want_columnar
            assert client.get_last_err() == []  # probe is health-neutral
            g = store.gtable.get("http_h3")
            assert g is not None and store.gtable.rep_expire[g] > T0
            if expect_batched:
                assert store.replica_commit_dispatches - d0 == 1
            else:
                assert store.replica_commit_dispatches - d0 == len(bcols)
        finally:
            client.shutdown(timeout_s=2.0)


# ----------------------------------------------------------------------
# Chaos: the GLOBAL plane under partition (seeded FaultPlan)
# ----------------------------------------------------------------------
@pytest.mark.chaos
def test_global_plane_partition_breaker_and_no_lost_hits():
    """ERROR-shaped partition on the hit-forward leg: every tick's
    failed send requeues (never drops), the per-peer breaker opens at
    its threshold and fast-fails the next tick (still requeueing), and
    once the partition heals + the breaker's half-open probe passes,
    the owner's authoritative count equals EXACTLY the hits taken —
    nothing lost, nothing double-counted.  The broadcast leg runs
    under an injected DELAY the whole time."""
    clock = Clock()
    clock.freeze(T0)
    behaviors = fast_test_behaviors()
    behaviors.global_sync_wait_s = 3600.0
    behaviors.multi_region_sync_wait_s = 3600.0
    behaviors.circuit_open_interval_s = 0.3
    behaviors.retry_backoff_base_s = 0.001
    behaviors.retry_backoff_max_s = 0.01

    plans = [FaultPlan(seed=11), FaultPlan(seed=12)]
    daemons = []
    for plan in plans:
        d = Daemon(
            DaemonConfig(
                listen_address="127.0.0.1:0",
                grpc_listen_address="127.0.0.1:0",
                cache_size=4096,
                global_cache_size=256,
                behaviors=behaviors,
                peer_discovery_type="static",
                fault_plan=plan,
            ),
            clock=clock,
        ).start()
        daemons.append(d)
    try:
        peers = [d.peer_info for d in daemons]
        for d in daemons:
            d.set_peers(peers)
        entry, owner = daemons
        key = _owned_key(owner, "chaos")
        owner_addr = owner.peer_info.grpc_address
        # Partition the hit-forward RPC for the first 6 calls from the
        # entry daemon (connection-shaped: provably unapplied).
        plans[0].add(FaultRule(
            peer=owner_addr, op="GetPeerRateLimits", kind=ERROR, count=6,
        ))
        # The owner's broadcasts to the entry ride a 5ms injected delay
        # throughout (the delay leg of the satellite).
        plans[1].add(FaultRule(
            peer=entry.peer_info.grpc_address, op="UpdatePeerGlobals",
            kind="delay", delay_s=0.005,
        ))

        total = 5
        entry.service.get_rate_limits(GetRateLimitsRequest(
            requests=[_global_req("chaos", key, hits=total, limit=100)]
        ))
        mgr = entry.service.global_mgr
        client = _peer_client_for(entry, owner_addr)
        # Ticks 1-2 burn faulted calls 1-4 (global_send_retries=1 => 2
        # attempts per tick); tick 3 burns call 5 — the breaker's 5th
        # consecutive failure OPENS it — and its second attempt
        # fast-fails circuit-open.  Every tick requeues.
        for tick in range(3):
            mgr.run_once()
            assert mgr._hit_carry[f"chaos_{key}"][4] == total, tick
        assert client.breaker.is_open
        rq = _metric_value(entry.service.metrics.global_requeued_hits)
        assert rq >= 3  # one requeued lane per failed tick
        assert _metric_value(entry.service.metrics.global_dropped_hits) == 0

        # Breaker open: the next tick never reaches the wire (the
        # FaultPlan sees no call) and still requeues.
        fired_before = plans[0].fired(plans[0]._rules[0])  # noqa: SLF001
        mgr.run_once()
        assert plans[0].fired(plans[0]._rules[0]) == fired_before
        assert mgr._hit_carry[f"chaos_{key}"][4] == total

        # Heal: wait out the open interval; the half-open probe burns
        # faulted call 6 (re-opens), wait again, then the send lands.
        deadline = time.time() + 10.0
        while mgr._hit_carry and time.time() < deadline:
            time.sleep(behaviors.circuit_open_interval_s + 0.05)
            mgr.run_once()
        assert not mgr._hit_carry, "hits never delivered after heal"

        # Owner applies + broadcasts (through the injected delay).
        assert owner.service.global_mgr.run_once()
        r = owner.service.get_rate_limits(GetRateLimitsRequest(
            requests=[_global_req("chaos", key, hits=0, limit=100)]
        )).responses[0]
        assert not r.error
        assert r.remaining == 100 - total  # exactly once, nothing lost
        # The entry's replica saw the (delayed) broadcast.
        g = entry.service.store.gtable.get(f"chaos_{key}")
        assert g is not None
        assert entry.service.store.gtable.rep_expire[g] > T0
    finally:
        for d in daemons:
            d.close()


@pytest.mark.chaos
def test_global_plane_drop_is_accounted_not_requeued():
    """DROP-shaped (timeout) failures may have applied server-side:
    requeueing would double-count, so the lanes are DROPPED and the
    accounting shows up in gubernator_global_dropped_hits — every lane
    is delivered, requeued, or counted, never silently lost."""
    svc = _quiet_service()
    try:
        svc.set_peers([])
        plan = FaultPlan(seed=7)
        plan.add(FaultRule(peer="stub:1", op="*", kind=DROP))

        class _StubPeer:
            info = PeerInfo(grpc_address="stub:1", is_owner=False)

            def send_columns_direct(self, cols, timeout_s=None, trace_ctx=None):
                from gubernator_tpu.peer_client import PeerError

                act = plan.intercept("stub:1", "GetPeerRateLimits")
                raise PeerError("injected timeout", not_ready=act.not_ready)

        svc.get_peer = lambda key: _StubPeer()
        req = RateLimitRequest(
            name="glob", unique_key="dr", hits=2, limit=100,
            duration=60_000, behavior=Behavior.GLOBAL,
        )
        svc.store.apply([req], T0, remote_global=True)
        svc.global_mgr.run_once()
        assert not svc.global_mgr._hit_carry  # NOT requeued (double-count risk)
        assert _metric_value(svc.metrics.global_dropped_hits) == 1
    finally:
        svc.close()
