"""PeerClient concurrency/shutdown tests (reference
peer_client_test.go:15-85): many threads issue requests through one
client with each behavior while it is shut down mid-flight; every
request must either succeed or fail with the closing error — never
hang, never crash.
"""

import threading
import time

import pytest

from gubernator_tpu.config import BehaviorConfig, DaemonConfig
from gubernator_tpu.daemon import spawn_daemon
from gubernator_tpu.peer_client import ERR_CLOSING, PeerClient, PeerError
from gubernator_tpu.types import Behavior, PeerInfo, RateLimitRequest


@pytest.fixture(scope="module")
def daemon():
    d = spawn_daemon(
        DaemonConfig(
            listen_address="127.0.0.1:0",
            grpc_listen_address="127.0.0.1:0",
            cache_size=4096,
            behaviors=BehaviorConfig(batch_wait_s=0.001),
        )
    )
    yield d
    d.close()


@pytest.mark.parametrize(
    "behavior", [Behavior.BATCHING, Behavior.NO_BATCHING, Behavior.GLOBAL]
)
def test_concurrent_requests_during_shutdown(daemon, behavior):
    client = PeerClient(
        PeerInfo(grpc_address=daemon.grpc.address), BehaviorConfig(batch_wait_s=0.001)
    )
    errors = []
    ok = []
    lock = threading.Lock()

    def worker(n):
        for i in range(10):
            req = RateLimitRequest(
                name="pc_test", unique_key=f"k{n}", hits=1, limit=1_000_000,
                duration=60_000, behavior=behavior,
            )
            try:
                r = client.get_peer_rate_limit(req)
                with lock:
                    ok.append(r)
            except PeerError as e:
                with lock:
                    errors.append(str(e))
            except Exception as e:  # noqa: BLE001
                with lock:
                    errors.append(f"UNEXPECTED {type(e).__name__}: {e}")

    threads = [threading.Thread(target=worker, args=(n,)) for n in range(10)]
    for t in threads:
        t.start()
    time.sleep(0.15)  # past the lazy connect, into the request stream
    # Under full-suite load the fixed sleep can elapse before ANY
    # request completes (1-core host); the mid-flight property needs at
    # least one success to exist, so wait (bounded) for it.
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        with lock:
            if ok:
                break
        time.sleep(0.01)
    client.shutdown()  # mid-flight
    for t in threads:
        t.join(timeout=30)
        assert not t.is_alive(), "worker hung after shutdown"
    # Every outcome is either a success or the closing error; in-flight
    # batches were drained, not dropped (peer_client.go:351-385).
    assert ok, "no request completed before shutdown"
    for e in errors:
        assert ERR_CLOSING in e or "failed" in e, e


def test_shutdown_drains_queued_batch(daemon):
    """Requests already queued when shutdown starts still get answers
    (the drain leg of peer_client.go:351-385)."""
    client = PeerClient(
        PeerInfo(grpc_address=daemon.grpc.address),
        BehaviorConfig(batch_wait_s=0.05),  # wide window: requests queue up
    )
    results = []

    def one(i):
        try:
            results.append(
                client.get_peer_rate_limit(
                    RateLimitRequest(
                        name="pc_drain", unique_key=f"d{i}", hits=1,
                        limit=10, duration=60_000,
                    )
                )
            )
        except PeerError:
            results.append(None)

    threads = [threading.Thread(target=one, args=(i,)) for i in range(5)]
    for t in threads:
        t.start()
    time.sleep(0.005)  # let them enqueue inside the batch window
    client.shutdown()
    for t in threads:
        t.join(timeout=30)
        assert not t.is_alive()
    answered = [r for r in results if r is not None]
    assert answered, "queued batch was dropped instead of drained"
    for r in answered:
        assert r.remaining == 9
