"""Client-library test against a subprocess cluster (reference
python/tests/test_client.py:24-56: launch the cluster binary, wait for
"Ready" on stdout, then exercise the client helpers against it).
"""

import datetime
import os
import re
import signal
import subprocess
import sys

import pytest

from gubernator_tpu.client import (
    V1Client,
    from_timestamp,
    from_unix_milliseconds,
    sleep_until_reset,
    to_timestamp,
)
from gubernator_tpu.types import GetRateLimitsRequest, RateLimitRequest, Status


@pytest.fixture(scope="module")
def cluster_proc():
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env["JAX_PLATFORMS"] = "cpu"
    # Fresh interpreter: share the persistent compile cache or the
    # daemons' warmup pays full cold compiles.
    env["JAX_COMPILATION_CACHE_DIR"] = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))), ".jax_cache"
    )
    proc = subprocess.Popen(
        [sys.executable, "-m", "gubernator_tpu.cmd.cluster_main", "--nodes", "2"],
        stdout=subprocess.PIPE,
        text=True,
        env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    # Watchdog: a daemon that hangs before "Ready" must fail the test,
    # not block the session forever on the stdout read.
    import threading

    ready = threading.Event()
    killer = threading.Timer(240.0, lambda: None if ready.is_set() else proc.kill())
    killer.start()
    peers = []
    try:
        for line in proc.stdout:  # wait for Ready like the reference fixture
            m = re.match(r"peer: http://(\S+) grpc://(\S+)", line)
            if m:
                peers.append(m.group(1))
            if line.strip() == "Ready":
                ready.set()
                break
        if not ready.is_set():
            raise RuntimeError("cluster exited (or was killed) before Ready")
        yield peers
    finally:
        killer.cancel()
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=30)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=10)


def test_client_against_subprocess_cluster(cluster_proc):
    client = V1Client(cluster_proc[0], timeout_s=60.0)
    resp = client.get_rate_limits(
        GetRateLimitsRequest(
            requests=[
                RateLimitRequest(
                    name="subproc", unique_key="k1", hits=1, limit=2,
                    duration=2_000,
                )
            ]
        )
    )
    rl = resp.responses[0]
    assert rl.error == ""
    assert (rl.status, rl.remaining) == (Status.UNDER_LIMIT, 1)

    hc = client.health_check()
    assert hc.status == "healthy" and hc.peer_count == 2

    # Drain, then sleep_until_reset unblocks the limit (the Python
    # client's convenience helper, python/gubernator/__init__.py:12-17).
    client.get_rate_limits(
        GetRateLimitsRequest(
            requests=[RateLimitRequest(name="subproc", unique_key="k1",
                                       hits=1, limit=2, duration=2_000)]
        )
    )
    over = client.get_rate_limits(
        GetRateLimitsRequest(
            requests=[RateLimitRequest(name="subproc", unique_key="k1",
                                       hits=1, limit=2, duration=2_000)]
        )
    ).responses[0]
    assert over.status == Status.OVER_LIMIT
    sleep_until_reset(over)
    after = client.get_rate_limits(
        GetRateLimitsRequest(
            requests=[RateLimitRequest(name="subproc", unique_key="k1",
                                       hits=1, limit=2, duration=2_000)]
        )
    ).responses[0]
    assert after.status == Status.UNDER_LIMIT


def test_time_helpers():
    assert to_timestamp(datetime.timedelta(seconds=2)) == 2000
    dt = from_unix_milliseconds(1_700_000_000_000)
    assert dt.year == 2023 and dt.tzinfo is not None
    # A timestamp in the past yields a positive delta from now.
    assert from_timestamp(1_700_000_000_000) > datetime.timedelta(0)
