"""Durability plane (snapshot.py): format goldens, rejection cases,
store dump/restore twins, and the service-level boot/shutdown wiring.

The byte-layout test follows the `test_wire_golden` discipline: the
expected bytes are PINNED — any layout change must bump
SNAPSHOT_VERSION and update the literal in the same reviewed change,
because a silently-moved field turns every deployed snapshot file into
a checksum-valid garbage restore.
"""

import os
import struct
import threading

import numpy as np
import pytest

from gubernator_tpu import snapshot as snap
from gubernator_tpu.models.shard import ShardStore
from gubernator_tpu.parallel.mesh import MeshBucketStore
from gubernator_tpu.reshard import TransferColumns
from gubernator_tpu.service import ServiceConfig, V1Service
from gubernator_tpu.store import (
    CacheItem,
    LeakyBucketItem,
    MockLoader,
    TokenBucketItem,
)
from gubernator_tpu.types import (
    Algorithm,
    GetRateLimitsRequest,
    PeerInfo,
    RateLimitRequest,
)
from gubernator_tpu.utils.clock import Clock

NOW = 1_573_430_430_000


def _clock():
    c = Clock()
    c.freeze(NOW)
    return c


def _cols(keys, remaining, expire, algo=None, limit=100):
    n = len(keys)
    return TransferColumns(
        keys=list(keys),
        algorithm=np.asarray(
            algo if algo is not None else [int(Algorithm.TOKEN_BUCKET)] * n,
            np.int32,
        ),
        status=np.zeros(n, np.int32),
        limit=np.full(n, limit, np.int64),
        remaining=np.asarray(remaining, np.int64),
        duration=np.full(n, 60_000, np.int64),
        stamp=np.full(n, NOW, np.int64),
        expire_at=np.asarray(expire, np.int64),
    )


def _req(key, hits=1, limit=100, name="snap", algorithm=Algorithm.TOKEN_BUCKET):
    return RateLimitRequest(
        name=name, unique_key=key, hits=hits, limit=limit,
        duration=60_000, algorithm=algorithm,
    )


# ---------------------------------------------------------------------
# Format: golden bytes + codec roundtrip
# ---------------------------------------------------------------------
# encode_snapshot of the 2-lane batch below, saved_at_ms=
# 1_573_430_430_500, ring_hash=0xDEADBEEF12345678.  FROZEN: changing
# any byte of the layout requires a SNAPSHOT_VERSION bump.
GOLDEN_HEX = (
    "47554253010002000000240bc3576e01000078563412efbeadde030000000100"
    "000003000000616263000000000100000000000000010000000a000000000000"
    "0014000000000000000700000000000000dc0500000000000060ea0000000000"
    "00d0070000000000003009c3576e0100003109c3576e01000090f3c3576e0100"
    "000011c3576e010000e08d6f25"
)


def _golden_cols():
    return TransferColumns(
        keys=["a", "bc"],
        algorithm=np.array([0, 1], np.int32),
        status=np.array([0, 1], np.int32),
        limit=np.array([10, 20], np.int64),
        remaining=np.array([7, 1500], np.int64),
        duration=np.array([60_000, 2_000], np.int64),
        stamp=np.array([NOW, NOW + 1], np.int64),
        expire_at=np.array([NOW + 60_000, NOW + 2_000], np.int64),
    )


def test_snapshot_golden_bytes():
    raw = snap.encode_snapshot(
        _golden_cols(), saved_at_ms=1_573_430_430_500,
        ring_hash=0xDEADBEEF12345678,
    )
    assert raw == bytes.fromhex(GOLDEN_HEX)
    # Spot-pin the header fields on top of the blob compare, so a
    # failure names the moved field instead of "bytes differ".
    assert raw[:4] == b"GUBS" and raw[4] == snap.SNAPSHOT_VERSION == 1
    assert struct.unpack_from("<I", raw, 6)[0] == 2  # n
    assert struct.unpack_from("<q", raw, 10)[0] == 1_573_430_430_500
    assert struct.unpack_from("<Q", raw, 18)[0] == 0xDEADBEEF12345678


def test_codec_roundtrip_including_unicode_keys():
    cols = _cols(
        ["plain", "unié_汉", "x" * 300],
        remaining=[1, 2, 3],
        expire=[NOW + 1, NOW + 2, NOW + 3],
        algo=[0, 1, 0],
    )
    raw = snap.encode_snapshot(cols, NOW, ring_hash=42)
    got, meta = snap.decode_snapshot(raw)
    assert got.keys == cols.keys
    for f in ("algorithm", "status", "limit", "remaining", "duration",
              "stamp", "expire_at"):
        np.testing.assert_array_equal(getattr(got, f), getattr(cols, f))
    assert got.ring_hash == 42
    assert meta == {
        "version": 1, "lanes": 3, "saved_at_ms": NOW, "ring_hash": 42,
        "bytes": len(raw),
    }


def test_empty_snapshot_roundtrip():
    raw = snap.encode_snapshot(TransferColumns.empty(), NOW)
    got, meta = snap.decode_snapshot(raw)
    assert len(got) == 0 and meta["lanes"] == 0


# ---------------------------------------------------------------------
# Rejections: every defect is a SnapshotError, never a partial decode
# ---------------------------------------------------------------------
def test_rejects_truncation_at_every_class_of_cut():
    raw = snap.encode_snapshot(_golden_cols(), NOW)
    for cut in (0, 4, snap._HEADER.size - 1, snap._HEADER.size + 3,
                len(raw) // 2, len(raw) - 1):
        with pytest.raises(snap.SnapshotError, match="truncated"):
            snap.decode_snapshot(raw[:cut])
    # ...and APPENDED garbage is just as torn as missing bytes.
    with pytest.raises(snap.SnapshotError, match="truncated"):
        snap.decode_snapshot(raw + b"\x00")


def test_rejects_bit_flips_everywhere():
    raw = bytearray(snap.encode_snapshot(_golden_cols(), NOW))
    # One flip in each region: header count-independent field, key
    # blob, a column, and the CRC itself.
    for pos in (11, snap._HEADER.size + 9, len(raw) - 20, len(raw) - 1):
        flipped = bytearray(raw)
        flipped[pos] ^= 0x40
        with pytest.raises(snap.SnapshotError):
            snap.decode_snapshot(bytes(flipped))


def test_rejects_wrong_magic_and_version():
    raw = bytearray(snap.encode_snapshot(_golden_cols(), NOW))
    bad_magic = b"NOPE" + bytes(raw[4:])
    with pytest.raises(snap.SnapshotError, match="magic"):
        snap.decode_snapshot(bad_magic)
    bad_ver = bytearray(raw)
    bad_ver[4] = 99
    with pytest.raises(snap.SnapshotError, match="version"):
        snap.decode_snapshot(bytes(bad_ver))


def test_strict_ring_fencing():
    raw_fenced = snap.encode_snapshot(_golden_cols(), NOW, ring_hash=5)
    raw_unfenced = snap.encode_snapshot(_golden_cols(), NOW, ring_hash=0)
    # Matching fence passes; mismatch rejects; an UNFENCED file (ring 0)
    # is accepted under any expectation — the TransferColumns convention.
    snap.decode_snapshot(raw_fenced, expected_ring=5)
    with pytest.raises(snap.SnapshotError, match="ring fingerprint"):
        snap.decode_snapshot(raw_fenced, expected_ring=6)
    snap.decode_snapshot(raw_unfenced, expected_ring=6)


def test_rejects_invalid_utf8_keys_with_valid_crc():
    # Re-sign a corrupted key blob so ONLY the utf-8 check can catch it.
    raw = bytearray(snap.encode_snapshot(_golden_cols(), NOW))
    raw[snap._HEADER.size + 8] = 0xFF  # first key byte -> invalid utf-8
    body = bytes(raw[:-4])
    import zlib

    good = body + struct.pack("<I", zlib.crc32(body) & 0xFFFFFFFF)
    with pytest.raises(snap.SnapshotError, match="utf-8"):
        snap.decode_snapshot(good)


# ---------------------------------------------------------------------
# Crash-safe write: temp + fsync + rename
# ---------------------------------------------------------------------
def test_write_failure_leaves_previous_snapshot_intact(tmp_path, monkeypatch):
    path = str(tmp_path / "gub.snap")
    snap.write_snapshot(path, _golden_cols(), NOW)
    before = open(path, "rb").read()

    def boom(_fd):
        raise OSError("disk full")

    monkeypatch.setattr(os, "fsync", boom)
    with pytest.raises(OSError):
        snap.write_snapshot(path, _cols(["k"], [1], [NOW + 1]), NOW + 1)
    monkeypatch.undo()
    # The failed write neither tore the previous file nor leaked a temp.
    assert open(path, "rb").read() == before
    assert [f for f in os.listdir(tmp_path) if f != "gub.snap"] == []
    got, _ = snap.read_snapshot(path)
    assert got.keys == ["a", "bc"]


def test_torn_temp_file_is_not_the_snapshot(tmp_path):
    # A kill -9 between the temp write and the rename leaves a stray
    # .tmp — the snapshot PATH still reads back the previous complete
    # file (the rename is the commit point).
    path = str(tmp_path / "gub.snap")
    snap.write_snapshot(path, _golden_cols(), NOW)
    torn = snap.encode_snapshot(_cols(["z"], [9], [NOW + 9]), NOW)[:30]
    with open(str(tmp_path / ".gub.snap.tmp.9999"), "wb") as f:
        f.write(torn)
    got, _ = snap.read_snapshot(path)
    assert got.keys == ["a", "bc"]


# ---------------------------------------------------------------------
# Store twins: one gather to dump, one merge-commit to restore
# ---------------------------------------------------------------------
def test_shard_store_snapshot_roundtrip_o1_dispatches():
    src, dst = ShardStore(capacity=64), ShardStore(capacity=64)
    src.apply([_req(f"s{i}", hits=4) for i in range(6)], NOW)
    before = src.device_dispatches
    cols = src.snapshot_columns(NOW)
    assert src.device_dispatches - before == 1  # ONE gather program
    assert len(cols) == 6
    # Gather-only: unlike drain_keys the table keeps every key.
    assert len(src.resident_keys()) == 6
    before = dst.device_dispatches
    assert dst.commit_transfer(cols, NOW) == 6
    assert dst.device_dispatches - before == 2  # gather + scatter
    out = dst.apply([_req(f"s{i}", hits=0) for i in range(6)], NOW)
    assert [r.remaining for r in out] == [96] * 6


def test_mesh_store_snapshot_roundtrip_o1_dispatches():
    src = MeshBucketStore(capacity_per_shard=64, g_capacity=32)
    dst = MeshBucketStore(capacity_per_shard=64, g_capacity=32)
    src.apply([_req(f"m{i}", hits=2) for i in range(12)], NOW)
    before = src.device_dispatches
    cols = src.snapshot_columns(NOW)
    assert src.device_dispatches - before == 1  # ONE mesh-wide gather
    assert sorted(cols.keys) == sorted(
        _req(f"m{i}").hash_key() for i in range(12)
    )
    before = dst.device_dispatches
    assert dst.commit_transfer(cols, NOW) == 12
    assert dst.device_dispatches - before == 2  # O(1): gather + scatter
    out = dst.apply([_req(f"m{i}", hits=0) for i in range(12)], NOW)
    assert [r.remaining for r in out] == [98] * 12


def test_warmup_keys_stay_out_of_the_file():
    st = MeshBucketStore(capacity_per_shard=64, g_capacity=32)
    st.warmup(NOW)
    st.apply([_req("real", hits=1)], NOW)
    cols = st.snapshot_columns(NOW)
    assert cols.keys == [_req("real").hash_key()]


def test_restore_drops_expired_rows():
    dst = ShardStore(capacity=64)
    cols = _cols(["live", "dead"], remaining=[5, 5],
                 expire=[NOW + 1000, NOW - 1])
    assert dst.commit_transfer(cols, NOW) == 1
    assert dst.resident_keys() == ["live"]


# ---------------------------------------------------------------------
# Service wiring: boot restore, shutdown save, knob-off, Loader SPI
# ---------------------------------------------------------------------
def _service(path="", loader=None, interval_s=0.0, cache=2048):
    from gubernator_tpu.config import BehaviorConfig

    beh = BehaviorConfig(
        global_sync_wait_s=3600.0, multi_region_sync_wait_s=3600.0,
        snapshot_interval_s=interval_s,
    )
    svc = V1Service(ServiceConfig(
        cache_size=cache, clock=_clock(), behaviors=beh, loader=loader,
        advertise_address="127.0.0.1:9999", snapshot_path=path,
    ))
    svc.set_peers([PeerInfo(grpc_address="127.0.0.1:9999", is_owner=True)])
    return svc


def test_service_shutdown_save_then_boot_restore(tmp_path):
    path = str(tmp_path / "svc.snap")
    svc = _service(path)
    svc.get_rate_limits(GetRateLimitsRequest(
        requests=[_req(f"b{i}", hits=3, limit=10) for i in range(8)]
    ))
    svc.close()
    assert os.path.exists(path)
    assert svc.snapshots.saves_ok == 1 and svc.snapshots.saved_lanes == 8

    svc2 = _service(path)
    try:
        assert svc2.snapshots.restore_result == "ok"
        assert svc2.snapshots.restored_lanes == 8
        r = svc2.get_rate_limits(GetRateLimitsRequest(
            requests=[_req(f"b{i}", hits=0, limit=10) for i in range(8)]
        ))
        # Zero-downtime restart: the spend survives the process.
        assert [x.remaining for x in r.responses] == [7] * 8
        # Restore is O(1) device programs, pinned by the ledger the
        # acceptance criteria name (commit = gather + scatter).
        assert svc2.snapshots.last_restore_seconds > 0
    finally:
        svc2.close()


def test_snapshot_disabled_is_the_pre_durability_daemon(tmp_path):
    path = str(tmp_path / "off.snap")
    svc = _service(path)
    svc.get_rate_limits(GetRateLimitsRequest(
        requests=[_req("reset_me", hits=3, limit=10)]
    ))
    svc.close()
    # Restart WITHOUT the knob: full reset (the legacy failure class).
    svc2 = _service("")
    try:
        assert not svc2.snapshots.enabled
        assert svc2.snapshots.restore_result == "disabled"
        r = svc2.get_rate_limits(GetRateLimitsRequest(
            requests=[_req("reset_me", hits=0, limit=10)]
        ))
        assert r.responses[0].remaining == 10
    finally:
        svc2.close()


def test_corrupt_snapshot_is_a_loud_cold_start(tmp_path):
    path = str(tmp_path / "corrupt.snap")
    with open(path, "wb") as f:
        f.write(b"GUBS" + os.urandom(64))
    svc = _service(path)
    try:
        assert svc.snapshots.restore_result == "rejected"
        assert svc.snapshots.restored_lanes == 0
        got = svc.metrics.snapshot_restores.labels(
            result="rejected"
        )._value.get()  # noqa: SLF001
        assert got == 1
        # Cold start: fresh traffic serves normally.
        r = svc.get_rate_limits(GetRateLimitsRequest(
            requests=[_req("fresh", hits=1, limit=10)]
        ))
        assert r.responses[0].remaining == 9
    finally:
        svc.close()


def test_loader_spi_rides_the_columnar_path(tmp_path):
    # Loader.load feeds ONE merge-commit; Loader.save still receives
    # CacheItems (reference backends port unchanged) — and the monotone
    # merge means a snapshot can never un-spend what a loader already
    # admitted (lower remaining wins).
    path = str(tmp_path / "both.snap")
    key = _req("merge", limit=10).hash_key()
    snap.write_snapshot(path, _cols([key], remaining=[7], expire=[NOW + 60_000],
                                    limit=10), NOW)
    loader = MockLoader()
    loader.cache_items.append(CacheItem(
        algorithm=int(Algorithm.TOKEN_BUCKET), key=key,
        value=TokenBucketItem(limit=10, duration=60_000, remaining=2,
                              created_at=NOW),
        expire_at=NOW + 60_000,
    ))
    svc = _service(path, loader=loader)
    try:
        assert loader.called["Load()"] == 1
        r = svc.get_rate_limits(GetRateLimitsRequest(
            requests=[_req("merge", hits=0, limit=10)]
        ))
        assert r.responses[0].remaining == 2  # min wins: no un-spend
    finally:
        svc.close()
    assert loader.called["Save()"] == 1
    saved = {i.key: i for i in loader.cache_items[1:]}
    assert saved[key].value.remaining == 2


def test_loader_leaky_items_roundtrip_fixed_point():
    items = [CacheItem(
        algorithm=int(Algorithm.LEAKY_BUCKET), key="leaky",
        value=LeakyBucketItem(limit=10, duration=60_000, remaining=4.5,
                              updated_at=NOW),
        expire_at=NOW + 60_000,
    )]
    cols = snap.items_to_columns(items)
    back = snap.columns_to_items(cols)
    assert isinstance(back[0].value, LeakyBucketItem)
    assert back[0].value.remaining == pytest.approx(4.5)
    assert back[0].value.updated_at == NOW


def test_interval_cadence_writes_in_the_background(tmp_path):
    path = str(tmp_path / "cadence.snap")
    svc = _service(path, interval_s=0.05)
    try:
        svc.get_rate_limits(GetRateLimitsRequest(
            requests=[_req("tick", hits=1)]
        ))
        deadline = threading.Event()
        for _ in range(100):
            if svc.snapshots.saves_ok >= 2:
                break
            deadline.wait(0.05)
        assert svc.snapshots.saves_ok >= 2, "interval writer never fired"
        assert os.path.exists(path)
        got, _ = snap.read_snapshot(path)
        assert _req("tick").hash_key() in got.keys
    finally:
        svc.close()


def test_boot_sweeps_orphaned_temp_files(tmp_path):
    # A kill -9 mid-write orphans a pid-suffixed temp this process will
    # never name again; boot must sweep siblings or a crash-looping
    # daemon accretes one ~file-sized orphan per crash.
    path = str(tmp_path / "sweep.snap")
    snap.write_snapshot(path, _golden_cols(), NOW)
    for pid in (111, 222):
        with open(str(tmp_path / f".sweep.snap.tmp.{pid}"), "wb") as f:
            f.write(b"torn")
    with open(str(tmp_path / "unrelated.tmp"), "wb") as f:
        f.write(b"keep")
    svc = _service(path)
    try:
        assert svc.snapshots.restore_result == "ok"
        assert sorted(os.listdir(tmp_path)) == ["sweep.snap", "unrelated.tmp"]
    finally:
        svc.close()


def test_restore_violation_fires_audit_surface_directly(tmp_path):
    # The windowed Auditor is constructed AFTER the boot restore (its
    # arm() baselines the restore's ledger notes away), so a commit
    # that MINTS lanes must fire the violation metric + dump from the
    # restore path itself.
    path = str(tmp_path / "mint.snap")
    key = _req("mint").hash_key()
    snap.write_snapshot(path, _cols([key], [5], [NOW + 60_000]), NOW)
    svc = _service("")
    try:
        mgr = snap.SnapshotManager(svc, path=path)
        real = svc.store.commit_transfer
        svc.store.commit_transfer = lambda cols, now: real(cols, now) + 3
        mgr.restore()
        got = svc.metrics.audit_violations.labels(
            invariant="snapshot_restore"
        )._value.get()  # noqa: SLF001
        assert got == 1
    finally:
        svc.close()


def test_audit_ledger_snapshot_conservation(tmp_path):
    # The snapshot_restore invariant: committed lanes can never exceed
    # loaded lanes; a clean save/restore cycle reconciles silently.
    from gubernator_tpu import audit

    path = str(tmp_path / "audit.snap")
    base = audit.ledger_snapshot()
    svc = _service(path)
    svc.get_rate_limits(GetRateLimitsRequest(
        requests=[_req(f"a{i}", hits=1) for i in range(4)]
    ))
    svc.close()
    svc2 = _service(path)
    try:
        d = {
            k: v - base.get(k, 0)
            for k, v in audit.ledger_snapshot().items()
        }
        assert d["snapshot_saved_lanes"] >= 4
        assert d["snapshot_loaded_lanes"] >= 4
        assert d["snapshot_committed_lanes"] <= d["snapshot_loaded_lanes"]
        assert not svc2.auditor.check_now()  # silent on a clean cycle
    finally:
        svc2.close()
