"""SWIM gossip membership tests (reference memberlist backend,
memberlist.go).  All nodes run in-process on loopback with ephemeral
ports and aggressive timers, mirroring how the reference's cluster
harness shortens behavior knobs for tests (cluster/cluster.go:104-110).
"""

import time

import pytest

from gubernator_tpu.gossip import Gossip, GossipPool
from gubernator_tpu.types import PeerInfo

FAST = dict(
    probe_interval_s=0.05,
    probe_timeout_s=0.1,
    suspect_timeout_s=0.3,
    sync_interval_s=0.2,
)


def wait_until(fn, timeout_s=5.0, every_s=0.02, msg="condition"):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if fn():
            return
        time.sleep(every_s)
    raise AssertionError(f"timed out waiting for {msg}")


def make_node(name, **kw):
    opts = {**FAST, **kw}
    return Gossip("127.0.0.1:0", name=name, **opts)


class TestGossip:
    def test_three_nodes_converge(self):
        nodes = [make_node(f"n{i}") for i in range(3)]
        try:
            nodes[1].join([nodes[0].address])
            nodes[2].join([nodes[0].address])
            for n in nodes:
                wait_until(
                    lambda n=n: len(n.members()) == 3,
                    msg=f"{n.name} sees 3 members",
                )
        finally:
            for n in nodes:
                n.close()

    def test_graceful_leave_disseminates(self):
        nodes = [make_node(f"l{i}") for i in range(3)]
        try:
            nodes[1].join([nodes[0].address])
            nodes[2].join([nodes[1].address])
            for n in nodes:
                wait_until(lambda n=n: len(n.members()) == 3, msg="join")
            nodes[2].leave()
            nodes[2].close()
            for n in nodes[:2]:
                wait_until(
                    lambda n=n: {m.name for m in n.members()} == {"l0", "l1"},
                    msg=f"{n.name} drops l2",
                )
        finally:
            for n in nodes:
                n.close()

    def test_crash_detected_via_suspicion(self):
        nodes = [make_node(f"c{i}") for i in range(3)]
        try:
            nodes[1].join([nodes[0].address])
            nodes[2].join([nodes[0].address])
            for n in nodes:
                wait_until(lambda n=n: len(n.members()) == 3, msg="join")
            nodes[2].close()  # crash: no leave broadcast
            for n in nodes[:2]:
                wait_until(
                    lambda n=n: {m.name for m in n.members()} == {"c0", "c1"},
                    timeout_s=10.0,
                    msg=f"{n.name} detects c2 dead",
                )
        finally:
            for n in nodes:
                n.close()

    def test_meta_update_propagates(self):
        a = make_node("ma")
        b = make_node("mb")
        try:
            b.join([a.address])
            wait_until(lambda: len(a.members()) == 2, msg="join")
            b.set_meta({"grpcAddress": "10.0.0.9:81"})
            wait_until(
                lambda: next(
                    (m for m in a.members() if m.name == "mb"), None
                ) is not None
                and next(m for m in a.members() if m.name == "mb").meta.get("grpcAddress")
                == "10.0.0.9:81",
                msg="meta propagates",
            )
        finally:
            a.close()
            b.close()

    def test_rejoin_after_graceful_leave(self):
        """A restarted node reusing its name must out-increment its own
        stale LEFT rumor and become visible again."""
        a = make_node("r0")
        b = make_node("r1")
        try:
            b.join([a.address])
            wait_until(lambda: len(a.members()) == 2, msg="join")
            b.leave()
            b.close()
            wait_until(
                lambda: {m.name for m in a.members()} == {"r0"}, msg="left"
            )
            b2 = make_node("r1")  # fresh process, incarnation restarts at 1
            try:
                b2.join([a.address])
                wait_until(
                    lambda: {m.name for m in a.members()} == {"r0", "r1"},
                    msg="rejoin visible despite stale LEFT tombstone",
                )
            finally:
                b2.close()
        finally:
            a.close()
            b.close()

    def test_join_unreachable_seed_times_out(self):
        a = make_node("t0")
        try:
            with pytest.raises(TimeoutError):
                a.join(["127.0.0.1:1"], timeout_s=0.5)
        finally:
            a.close()


class TestGossipPool:
    def test_pool_delivers_peerinfo(self):
        updates = {0: [], 1: [], 2: []}
        pools = []
        try:
            for i in range(3):
                seeds = [pools[0].address] if pools else []
                pools.append(
                    GossipPool(
                        advertise=PeerInfo(
                            grpc_address=f"127.0.0.1:{9000 + i}",
                            http_address=f"127.0.0.1:{9100 + i}",
                            data_center="dc-1" if i == 2 else "",
                        ),
                        member_list_address="127.0.0.1:0",
                        on_update=lambda peers, i=i: updates[i].append(peers),
                        known_nodes=seeds,
                        node_name=f"p{i}",
                        **FAST,
                    )
                )
            want = {f"127.0.0.1:{9000 + i}" for i in range(3)}
            for i in range(3):
                wait_until(
                    lambda i=i: updates[i]
                    and {p.grpc_address for p in updates[i][-1]} == want,
                    msg=f"pool {i} sees all three PeerInfos",
                )
            # Metadata fields survive the gossip round trip.
            last = updates[0][-1]
            dc = next(p for p in last if p.grpc_address == "127.0.0.1:9002")
            assert dc.data_center == "dc-1"
            assert dc.http_address == "127.0.0.1:9102"
        finally:
            for p in pools:
                p.close()

    def test_pool_close_removes_peer(self):
        updates = {0: [], 1: []}
        pools = []
        try:
            for i in range(2):
                seeds = [pools[0].address] if pools else []
                pools.append(
                    GossipPool(
                        advertise=PeerInfo(grpc_address=f"127.0.0.1:{9200 + i}"),
                        member_list_address="127.0.0.1:0",
                        on_update=lambda peers, i=i: updates[i].append(peers),
                        known_nodes=seeds,
                        node_name=f"q{i}",
                        **FAST,
                    )
                )
            wait_until(
                lambda: updates[0] and len(updates[0][-1]) == 2, msg="both join"
            )
            pools[1].close()
            wait_until(
                lambda: updates[0]
                and [p.grpc_address for p in updates[0][-1]] == ["127.0.0.1:9200"],
                msg="peer removed after close",
            )
        finally:
            for p in pools:
                p.close()


def test_leave_reaches_every_member_in_large_cluster():
    """leave() must carry the LEFT update in EVERY outgoing datagram, not
    rely on the piggyback queue's RETRANSMIT credits — in clusters larger
    than the credit count the later targets would otherwise get an empty
    packet and only learn of the departure via the suspicion cycle
    (advisor finding, gossip.py leave)."""
    import json
    import socket

    from gubernator_tpu.gossip import ALIVE, LEFT, RETRANSMIT, Member

    # Probe/sync loops effectively disabled: a probe ping racing the
    # leave would otherwise occupy a listener's first datagram.
    node = make_node("leaver", probe_interval_s=3600, sync_interval_s=3600)
    # Twice as many listeners as the piggyback credit budget, each a bare
    # UDP socket standing in for a remote member.
    n_targets = RETRANSMIT * 2
    socks = []
    try:
        with node._lock:
            for i in range(n_targets):
                s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
                s.bind(("127.0.0.1", 0))
                s.settimeout(2.0)
                socks.append(s)
                port = s.getsockname()[1]
                node._members[f"m{i}"] = Member(
                    name=f"m{i}", host="127.0.0.1", port=port, state=ALIVE
                )
        node.leave()

        def got_left(s):
            # Drain until a gossip datagram carries the LEFT update
            # (robust against any stray probe traffic).
            while True:
                data, _ = s.recvfrom(65536)  # raises timeout on starvation
                msg = json.loads(data.decode())
                if any(
                    u["s"] == LEFT and u["name"] == "leaver"
                    for u in msg.get("g", [])
                ):
                    return True

        for i, s in enumerate(socks):
            assert got_left(s), f"target {i} did not receive the LEFT update"
    finally:
        node.close()
        for s in socks:
            s.close()


class TestVersionSkew:
    """Wire-tolerance contract (gossip.py WIRE_VERSION): a NEWER node
    may stamp a higher version, add fields to updates, introduce new
    message types, or gossip new member states — an older node must
    ignore what it doesn't know and keep the membership converging.
    This is the rolling-upgrade story the hashicorp wire gets from its
    protocol-version range; here it is by-construction JSON tolerance,
    and these tests pin it so a future field addition can't break it."""

    def test_future_wire_fields_and_types_ignored(self):
        import json
        import socket

        node = make_node("skew0")
        try:
            # A "v2" peer announces itself: higher version stamp, extra
            # unknown fields at every level, plus an unknown message
            # type in the same packet stream.
            s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
            addr = ("127.0.0.1", node.port)
            s.sendto(json.dumps({"t": "mesh-scan", "v": 2, "depth": 3}).encode(), addr)
            s.sendto(
                json.dumps(
                    {
                        "t": "ping",
                        "v": 2,
                        "seq": 7,
                        "hmac": "ab12",  # unknown field
                        "g": [
                            {
                                "s": "alive",
                                "name": "future-node",
                                "addr": ["127.0.0.1", port],
                                "inc": 1,
                                "meta": {"grpc_address": "127.0.0.1:9"},
                                "shard_epoch": 42,  # unknown field
                            },
                            # Unknown state: must be skipped, not crash.
                            {"s": "draining", "name": "x", "addr": ["127.0.0.1", 1], "inc": 1},
                        ],
                    }
                ).encode(),
                addr,
            )
            # The ping must still be acked (v2 stamp didn't spook v1)...
            s.settimeout(2.0)
            data, _ = s.recvfrom(65536)
            msg = json.loads(data.decode())
            assert msg["t"] == "ack" and msg["seq"] == 7
            # ...and the alive update (with its unknown extras) landed.
            wait_until(
                lambda: any(m.name == "future-node" for m in node.members()),
                msg="future-node joined membership",
            )
            assert not any(m.name == "x" for m in node.members())
        finally:
            node.close()
            s.close()

    def test_old_node_without_version_stamp_accepted(self):
        """The inverse skew: a pre-WIRE_VERSION packet (no "v" key at
        all) is still handled — receivers never require the stamp."""
        import json
        import socket

        node = make_node("skew1")
        try:
            s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            s.bind(("127.0.0.1", 0))
            s.settimeout(2.0)
            s.sendto(
                json.dumps({"t": "ping", "seq": 3}).encode(),
                ("127.0.0.1", node.port),
            )
            data, _ = s.recvfrom(65536)
            msg = json.loads(data.decode())
            assert msg["t"] == "ack" and msg["seq"] == 3
        finally:
            node.close()
            s.close()

    def test_push_pull_tolerates_future_state_entries(self):
        """Anti-entropy with a newer node: unknown states / extra keys
        inside the TCP push-pull state dump are skipped lane-wise."""
        node = make_node("skew2")
        try:
            node.merge_state(
                [
                    {"s": "alive", "name": "ok-node", "addr": ["127.0.0.1", 5],
                     "inc": 1, "meta": {}, "zone": "z1"},
                    {"s": "quarantined", "name": "weird", "addr": ["127.0.0.1", 6],
                     "inc": 1},
                    {"bogus": True},
                ]
            )
            assert any(m.name == "ok-node" for m in node.members())
            assert not any(m.name == "weird" for m in node.members())
        finally:
            node.close()
