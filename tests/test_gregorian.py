"""Gregorian interval math, ported from interval_test.go:27-116."""

import datetime as dt

import pytest

from gubernator_tpu.utils import gregorian as g

UTC = dt.timezone.utc


def ms(y, mo, d, h=0, mi=0, s=0, msec=0):
    return int(dt.datetime(y, mo, d, h, mi, s, msec * 1000, tzinfo=UTC).timestamp() * 1000)


def test_expiration_minute():
    now = dt.datetime(2019, 11, 11, 0, 0, 0, tzinfo=UTC)
    assert g.gregorian_expiration(now, g.GREGORIAN_MINUTES) == ms(2019, 11, 11, 0, 0, 59, 999)
    now = dt.datetime(2019, 11, 11, 0, 0, 30, 100, tzinfo=UTC)
    assert g.gregorian_expiration(now, g.GREGORIAN_MINUTES) == 1573430459999


def test_expiration_hour():
    now = dt.datetime(2019, 11, 11, 0, 0, 0, tzinfo=UTC)
    assert g.gregorian_expiration(now, g.GREGORIAN_HOURS) == ms(2019, 11, 11, 0, 59, 59, 999)
    now = dt.datetime(2019, 11, 11, 0, 20, 1, 2134, tzinfo=UTC)
    assert g.gregorian_expiration(now, g.GREGORIAN_HOURS) == 1573433999999


def test_expiration_day():
    now = dt.datetime(2019, 11, 11, 0, 0, 0, tzinfo=UTC)
    assert g.gregorian_expiration(now, g.GREGORIAN_DAYS) == ms(2019, 11, 11, 23, 59, 59, 999)
    now = dt.datetime(2019, 11, 11, 12, 10, 9, 2345, tzinfo=UTC)
    assert g.gregorian_expiration(now, g.GREGORIAN_DAYS) == 1573516799999


def test_expiration_month():
    now = dt.datetime(2019, 11, 1, 0, 0, 0, tzinfo=UTC)
    assert g.gregorian_expiration(now, g.GREGORIAN_MONTHS) == ms(2019, 11, 30, 23, 59, 59, 999)
    now = dt.datetime(2019, 11, 11, 22, 2, 23, tzinfo=UTC)
    assert g.gregorian_expiration(now, g.GREGORIAN_MONTHS) == 1575158399999
    # January has 31 days
    now = dt.datetime(2019, 1, 1, 0, 0, 0, tzinfo=UTC)
    eom_ns = int(dt.datetime(2019, 2, 1, tzinfo=UTC).timestamp()) * 10**9 - 1
    assert g.gregorian_expiration(now, g.GREGORIAN_MONTHS) == eom_ns // 10**6


def test_expiration_year():
    now = dt.datetime(2019, 1, 1, 0, 0, 0, tzinfo=UTC)
    assert g.gregorian_expiration(now, g.GREGORIAN_YEARS) == ms(2019, 12, 31, 23, 59, 59, 999)
    now = dt.datetime(2019, 3, 1, 20, 30, 0, tzinfo=UTC)
    assert g.gregorian_expiration(now, g.GREGORIAN_YEARS) == 1577836799999


def test_expiration_invalid():
    now = dt.datetime(2019, 1, 1, tzinfo=UTC)
    with pytest.raises(g.GregorianError, match="not a valid gregorian interval"):
        g.gregorian_expiration(now, 99)
    with pytest.raises(g.GregorianError, match="not yet supported"):
        g.gregorian_expiration(now, g.GREGORIAN_WEEKS)


def test_duration_constants():
    now = dt.datetime(2019, 1, 1, tzinfo=UTC)
    assert g.gregorian_duration(now, g.GREGORIAN_MINUTES) == 60_000
    assert g.gregorian_duration(now, g.GREGORIAN_HOURS) == 3_600_000
    assert g.gregorian_duration(now, g.GREGORIAN_DAYS) == 86_400_000
    with pytest.raises(g.GregorianError):
        g.gregorian_duration(now, g.GREGORIAN_WEEKS)
    with pytest.raises(g.GregorianError):
        g.gregorian_duration(now, 42)


def test_duration_month_bugcompat():
    """The reference computes end_ns - begin_ms for months/years
    (interval.go:97,103 operator precedence); we are bug-compatible."""
    now = dt.datetime(2019, 11, 11, tzinfo=UTC)
    begin_s = int(dt.datetime(2019, 11, 1, tzinfo=UTC).timestamp())
    end_ns = int(dt.datetime(2019, 12, 1, tzinfo=UTC).timestamp()) * 10**9 - 1
    assert g.gregorian_duration(now, g.GREGORIAN_MONTHS) == end_ns - begin_s * 1000
