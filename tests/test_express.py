"""Millisecond express lane (PR 14): shallow-queue bypass equivalence,
the host scalar slot's oracle equivalence against the device kernel,
audit-ledger balance with express and batched dispatches interleaving,
the chaos DELAY-on-batched-path isolation, the GUBER_EXPRESS knobs, and
NO_BATCHING on the native hot path."""

from __future__ import annotations

import random
import threading
import time

import numpy as np
import pytest

from gubernator_tpu import audit as audit_mod
from gubernator_tpu import faults, native, saturation
from gubernator_tpu.client import V1Client
from gubernator_tpu.cluster import Cluster, fast_test_behaviors
from gubernator_tpu.config import BehaviorConfig, setup_daemon_config
from gubernator_tpu.faults import FaultPlan
from gubernator_tpu.models.shard import ShardStore, host_readback
from gubernator_tpu.parallel.mesh import MeshBucketStore
from gubernator_tpu.service import IngressColumns, ServiceConfig, V1Service
from gubernator_tpu.types import (
    Behavior,
    GetRateLimitsRequest,
    PeerInfo,
    RateLimitRequest,
)
from gubernator_tpu.utils.batch_window import BatchWindow


# ---------------------------------------------------------------------
# Window cap: GUBER_LATENCY_TARGET_MS binds
# ---------------------------------------------------------------------

def test_window_cap_clamps_effective_wait():
    w = BatchWindow(lambda b: None, wait_s=0.5, limit=1000, lazy=True,
                    cap_s=0.005)
    assert w.effective_wait_s() == 0.005
    # Adaptive sizing also yields to the cap (occupancy -> latency).
    w2 = BatchWindow(lambda b: None, wait_s=0.5, limit=1000, lazy=True,
                     adaptive=True, cap_s=0.002)
    w2._rate = 10.0  # adaptive would pick limit/rate = 100s
    assert w2.effective_wait_s() == 0.002
    # No cap = the pre-express window, untouched.
    w3 = BatchWindow(lambda b: None, wait_s=0.5, limit=1000, lazy=True)
    assert w3.effective_wait_s() == 0.5


def test_latency_target_caps_batcher_windows():
    # A deliberately wide window (500 ms) with a 10 ms target: the cap
    # (target/2 — half the budget coalesces, half pays dispatch) must
    # bind on both batchers.
    beh = BehaviorConfig(latency_target_ms=10.0, batch_wait_s=0.5)
    svc = _service(beh)
    try:
        assert svc.columnar_batcher._window.effective_wait_s() == 0.005
        assert svc.local_batcher._window.effective_wait_s() == 0.005
    finally:
        svc.close()
    # Knob off (express=0): occupancy mode keeps the window.
    svc = _service(BehaviorConfig(
        latency_target_ms=10.0, batch_wait_s=0.5, express=False
    ))
    try:
        assert svc.columnar_batcher._window.effective_wait_s() == 0.5
    finally:
        svc.close()


# ---------------------------------------------------------------------
# Bypass-vs-windowed byte identity (2 seeds, ShardStore + mesh)
# ---------------------------------------------------------------------

class _FixedClock:
    """Deterministic clock: byte-identity across two services needs
    identical now_ms at every dispatch (reset_time derives from it)."""

    def __init__(self, t0: int = 1_700_000_000_000):
        self.t = t0

    def now_ms(self) -> int:
        return self.t


def _service(behaviors: BehaviorConfig, store=None, clock=None) -> V1Service:
    svc = V1Service(ServiceConfig(
        store=store, cache_size=2048, global_cache_size=256,
        behaviors=behaviors, advertise_address="127.0.0.1:9991",
        **({"clock": clock} if clock is not None else {}),
    ))
    svc.set_peers([PeerInfo(grpc_address="127.0.0.1:9991", is_owner=True)])
    return svc


def _drive_stream(svc: V1Service, seed: int):
    """One seeded request stream — singles and small column batches,
    token + leaky, occasional RESET_REMAINING and duplicate keys —
    returning every response triple in order."""
    rng = random.Random(seed)
    out = []
    for step in range(60):
        if rng.random() < 0.5:
            r = RateLimitRequest(
                name="xt", unique_key=f"k{rng.randrange(8)}", hits=1,
                limit=20, duration=60_000,
                algorithm=rng.choice([0, 1]),
            )
            resp = svc.get_rate_limits(
                GetRateLimitsRequest(requests=[r])
            ).responses[0]
            out.append((resp.status, resp.remaining, resp.reset_time))
        else:
            n = rng.choice([2, 3, 4, 8])
            ks = [f"k{rng.randrange(8)}" for _ in range(n)]
            cols = IngressColumns(
                names=["xt"] * n, unique_keys=ks,
                algorithm=np.array(
                    [rng.choice([0, 1]) for _ in range(n)], np.int32
                ),
                behavior=np.array(
                    [rng.choice([0, 0, 0, 8]) for _ in range(n)], np.int32
                ),
                hits=np.ones(n, np.int64),
                limit=np.full(n, 20, np.int64),
                duration=np.full(n, 60_000, np.int64),
            )
            rc = svc.get_rate_limits_columns(cols)
            for i in range(n):
                resp = rc.response_at(i)
                out.append((resp.status, resp.remaining, resp.reset_time))
    return out


@pytest.mark.parametrize("store_kind", ["shard", "mesh"])
@pytest.mark.parametrize("seed", [21, 22])
def test_bypass_vs_windowed_byte_identical(store_kind, seed):
    """The express bypass changes WHEN a dispatch launches, never what
    it computes: the same seeded request stream through an express-on
    and an express-off service answers identically."""
    def mk(express: bool):
        store = (
            ShardStore(capacity=512) if store_kind == "shard"
            else MeshBucketStore(capacity_per_shard=128)
        )
        return _service(BehaviorConfig(express=express), store=store,
                        clock=_FixedClock())

    on, off = mk(True), mk(False)
    try:
        got_on = _drive_stream(on, seed)
        got_off = _drive_stream(off, seed)
        assert got_on == got_off
        # The on-service actually exercised the lane (bypass + the
        # host scalar slot) while the off-service stayed fully classic.
        assert on.store.scalar_applies > 0
        assert off.store.scalar_applies == 0
        assert off.store.scalar_fast_path is False
    finally:
        on.close()
        off.close()


# ---------------------------------------------------------------------
# Scalar fast path vs the device kernel (the oracle pin)
# ---------------------------------------------------------------------

def _drive_store(store, seed: int, steps: int = 150):
    """Randomized small batches against the bulk columnar API: expiry
    edges (clock jumps past short durations), duplicate-heavy batches,
    token + leaky, RESET_REMAINING."""
    rng = random.Random(seed)
    out = []
    now = 1_000_000
    for step in range(steps):
        n = rng.choice([1, 1, 2, 3, 4])
        ks = [f"k{rng.randrange(6)}" for _ in range(n)]
        if rng.random() < 0.35:
            ks = [ks[0]] * n  # duplicate group
        algo = np.array([rng.choice([0, 1]) for _ in range(n)], np.int32)
        beh = np.array([rng.choice([0, 0, 0, 8]) for _ in range(n)], np.int32)
        hits = np.array([rng.choice([0, 1, 1, 2, 5, 11]) for _ in range(n)],
                        np.int64)
        limit = np.full(n, rng.choice([1, 3, 10, 30]), np.int64)
        dur = np.full(n, rng.choice([7, 50, 100, 1000]), np.int64)
        now += rng.choice([0, 0, 1, 3, 60, 120, 1500])  # expiry edges
        r = store.apply_columns(ks, algo, beh, hits, limit, dur, now)
        out.append(tuple(
            (int(r["status"][i]), int(r["remaining"][i]),
             int(r["reset_time"][i]))
            for i in range(n)
        ))
    return out


@pytest.mark.parametrize("seed", [31, 32])
def test_scalar_oracle_shard(seed):
    a = ShardStore(capacity=64)
    b = ShardStore(capacity=64)
    b.scalar_fast_path = True
    ra, rb = _drive_store(a, seed), _drive_store(b, seed)
    if not b.scalar_applies:
        pytest.skip("scalar fast path unavailable on this backend")
    assert b.device_dispatches == 0  # zero programs: the whole point
    assert ra == rb


@pytest.mark.parametrize("seed", [31, 32])
def test_scalar_oracle_mesh(seed):
    a = MeshBucketStore(capacity_per_shard=32)
    b = MeshBucketStore(capacity_per_shard=32)
    b.scalar_fast_path = True
    ra, rb = _drive_store(a, seed), _drive_store(b, seed)
    if not b.scalar_applies:
        pytest.skip("scalar fast path unavailable on this backend")
    assert b.device_dispatches == 0
    assert ra == rb


def test_scalar_oracle_eviction_pressure():
    """A tiny table forces mid-batch slot takeovers (a different key's
    create evicting into a just-written slot) — the case the
    sequential-exists rule must not confuse with a duplicate group."""
    a, b = ShardStore(capacity=4), ShardStore(capacity=4)
    b.scalar_fast_path = True
    ra, rb = _drive_store(a, 41, steps=120), _drive_store(b, 41, steps=120)
    if not b.scalar_applies:
        pytest.skip("scalar fast path unavailable on this backend")
    assert ra == rb


def test_scalar_gregorian_lane():
    """DURATION_IS_GREGORIAN lanes carry host-precomputed expiry; the
    scalar slot must select them exactly like the kernel."""
    now = 1_700_000_000_000
    ge = np.array([now + 3_600_000], np.int64)
    gd = np.array([3_600_000], np.int64)

    def drive(store):
        out = []
        for i in range(4):
            r = store.apply_columns(
                ["gk"], np.zeros(1, np.int32),
                np.full(1, int(Behavior.DURATION_IS_GREGORIAN), np.int32),
                np.ones(1, np.int64), np.full(1, 10, np.int64),
                np.full(1, 4, np.int64),  # calendar enum, not ms
                now + i, greg_expire=ge, greg_duration=gd,
            )
            out.append((int(r["status"][0]), int(r["remaining"][0]),
                        int(r["reset_time"][0])))
        return out

    a, b = ShardStore(capacity=16), ShardStore(capacity=16)
    b.scalar_fast_path = True
    ra, rb = drive(a), drive(b)
    if not b.scalar_applies:
        pytest.skip("scalar fast path unavailable on this backend")
    assert ra == rb
    assert rb[0] == (0, 9, now + 3_600_000)


# ---------------------------------------------------------------------
# Audit ledger balanced with express interleaving batched dispatches
# ---------------------------------------------------------------------

def test_audit_balanced_with_express_interleaving():
    svc = _service(BehaviorConfig())
    try:
        rng = random.Random(7)
        for step in range(40):
            n = rng.choice([1, 1, 2, 24])  # express singles + batched
            ks = [f"ak{rng.randrange(12)}" for _ in range(n)]
            cols = IngressColumns(
                names=["at"] * n, unique_keys=ks,
                algorithm=np.zeros(n, np.int32),
                behavior=np.zeros(n, np.int32),
                hits=np.ones(n, np.int64),
                limit=np.full(n, 1000, np.int64),
                duration=np.full(n, 60_000, np.int64),
            )
            svc.get_rate_limits_columns(cols)
        assert svc.store.scalar_applies > 0  # the lane really ran
        violations = svc.auditor.check_now()
        assert violations == [], violations
    finally:
        svc.close()


# ---------------------------------------------------------------------
# Chaos: DELAY on the batched (forwarded) path must not stall express
# ---------------------------------------------------------------------

@pytest.mark.chaos
def test_chaos_delay_on_batched_path_does_not_stall_express():
    """A FaultPlan DELAY on every peer forward (the batched remote leg)
    slows remote-owned keys to ~delay_s; locally-owned express singles
    riding the bypass must keep answering orders of magnitude faster —
    the lanes are independent by construction."""
    cluster = Cluster().start(2)
    try:
        d0 = cluster.daemon_at(0)
        svc = d0.service
        # One locally-owned and one remotely-owned key, seen from d0.
        # Index-FIRST keys: FNV-1 clusters suffix-varying keys into one
        # vnode gap (the documented test_hash_ring finding), which can
        # land all 64 on a single owner.
        local_key = remote_key = None
        for i in range(64):
            k = f"{i}ck"
            peer = svc.get_peer(f"ct_{k}")
            if peer.info.is_owner and local_key is None:
                local_key = k
            if not peer.info.is_owner and remote_key is None:
                remote_key = k
            if local_key and remote_key:
                break
        assert local_key and remote_key

        plan = FaultPlan(seed=3)
        plan.delay("*", 1.5, op="GetPeerRateLimits")
        with faults.injected(plan):
            def one(k):
                return svc.get_rate_limits(GetRateLimitsRequest(requests=[
                    RateLimitRequest(name="ct", unique_key=k, hits=1,
                                     limit=100, duration=60_000)
                ])).responses[0]

            t0 = time.monotonic()
            slow_done = threading.Event()
            threading.Thread(
                target=lambda: (one(remote_key), slow_done.set()),
                daemon=True,
            ).start()
            fast = [one(local_key) for _ in range(5)]
            fast_elapsed = time.monotonic() - t0
            assert all(r.error == "" for r in fast)
            # 5 express rounds complete well inside ONE delayed
            # forward (the bound is HALF the injected delay: isolation
            # is the claim, with headroom for 2-core suite weather —
            # express rounds are ~2-30 ms each).
            assert fast_elapsed < 0.75, fast_elapsed
            assert not slow_done.is_set()  # the delayed leg still parked
            assert slow_done.wait(timeout=10.0)
    finally:
        cluster.stop()


# ---------------------------------------------------------------------
# Config plumbing + the GUBER_EXPRESS=0 interop switch
# ---------------------------------------------------------------------

def test_express_knobs_env_plumbing():
    conf = setup_daemon_config(env={
        "GUBER_EXPRESS": "0",
        "GUBER_EXPRESS_QUEUE_DEPTH": "128",
        "GUBER_EXPRESS_MAX_LANES": "8",
        "GUBER_EXPRESS_SCALAR": "0",
    })
    b = conf.behaviors
    assert b.express is False
    assert b.express_queue_depth == 128
    assert b.express_max_lanes == 8
    assert b.express_scalar is False
    # Defaults: the lane ships ON.
    d = setup_daemon_config(env={})
    assert d.behaviors.express is True
    assert d.behaviors.express_queue_depth == 64
    assert d.behaviors.express_max_lanes == 4
    assert d.behaviors.express_scalar is True


@pytest.mark.parametrize("env", [
    {"GUBER_EXPRESS_QUEUE_DEPTH": "0"},
    {"GUBER_EXPRESS_QUEUE_DEPTH": "2000000"},
    {"GUBER_EXPRESS_MAX_LANES": "0"},
    {"GUBER_EXPRESS_MAX_LANES": "65"},
])
def test_express_knobs_loud_validation(env):
    with pytest.raises(ValueError):
        setup_daemon_config(env=env)


def test_express_off_is_pre_express_behavior():
    """GUBER_EXPRESS=0: no bypass, no scalar slot, windows uncapped —
    every submission waits out the coalescing window exactly as before
    the lane existed."""
    saturation.reset()
    svc = _service(BehaviorConfig(express=False, latency_target_ms=5.0))
    try:
        assert svc.store.scalar_fast_path is False
        assert svc.columnar_batcher._express.enabled is False
        assert svc.columnar_batcher._window.cap_s is None
        for i in range(4):
            svc.get_rate_limits(GetRateLimitsRequest(requests=[
                RateLimitRequest(name="off", unique_key=f"k{i}", hits=1,
                                 limit=10, duration=60_000)
            ]))
        snap = saturation.express_snapshot()
        assert snap["lanes"]["bypass"] == 0
        assert snap["lanes"]["scalar"] == 0
        assert snap["lanes"]["windowed"] > 0
        assert svc.store.scalar_applies == 0
    finally:
        svc.close()
        saturation.reset()


# ---------------------------------------------------------------------
# Native hot path: NO_BATCHING rides the express queue, not Python
# ---------------------------------------------------------------------

@pytest.mark.skipif(not native.available(),
                    reason="native runtime unavailable")
@pytest.mark.parametrize("express", [True, False])
def test_native_no_batching_express_vs_fallback(express):
    """With the lane on, a NO_BATCHING kind-5 frame is served natively
    through the express queue (expressFrames counted, zero fallbacks);
    with GUBER_EXPRESS=0 it falls back to the Python path — exactly the
    PR 13 behavior — and both answer correct bytes."""
    from tests.test_native_loop import _frame, _post, _standalone
    from gubernator_tpu import wire
    from gubernator_tpu.utils.clock import Clock

    import tests.test_native_loop as tnl

    d = tnl._standalone(Clock(), native_ingress=True)
    try:
        if not express:
            d.service.conf.behaviors.express = False
            d.gateway.pump.update_ring()  # re-push the masks
        pump = d.gateway.pump
        before = pump.stats()
        frame = _frame("nb", ["k1"], behavior=int(Behavior.NO_BATCHING))
        raw, body = _post(d.gateway._edge.port, frame)
        assert raw.startswith(b"HTTP/1.1 200 OK")
        rc = wire.decode_ingress_result_frame(body)
        assert rc.n == 1 and int(rc.status[0]) == 0
        after = pump.stats()
        if express:
            assert after["expressFrames"] == before["expressFrames"] + 1
            assert after["fallbacks"] == before["fallbacks"]
        else:
            assert after["expressFrames"] == before["expressFrames"]
            assert after["fallbacks"] > before["fallbacks"]
            assert after["frames"] == before["frames"]  # never in the ring
    finally:
        d.close()


@pytest.mark.skipif(not native.available(),
                    reason="native runtime unavailable")
def test_debug_surfaces_report_express():
    import json
    import urllib.request

    from tests.test_native_loop import _frame, _post, _standalone
    from gubernator_tpu.utils.clock import Clock

    d = _standalone(Clock(), native_ingress=True)
    try:
        frame = _frame("dbg", ["k1"], behavior=int(Behavior.NO_BATCHING))
        _post(d.gateway._edge.port, frame)
        # Give the pump's stats poll a beat to fold the express delta.
        deadline = time.time() + 5.0
        port = d.gateway._edge.port
        while time.time() < deadline:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/debug/status", timeout=5
            ) as f:
                status = json.loads(f.read())
            if status["express"]["lanes"].get("native", 0) > 0:
                break
            time.sleep(0.05)
        assert status["express"]["enabled"] is True
        assert status["express"]["lanes"]["native"] >= 1
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/debug/latency", timeout=5
        ) as f:
            lat = json.loads(f.read())
        assert "express" in lat and "hitRate" in lat["express"]
    finally:
        d.close()


@pytest.mark.skipif(not native.available(),
                    reason="native runtime unavailable")
def test_native_take_is_express_pure():
    """An express frame queued behind bulk backlog jumps the queue AND
    its take never keeps filling from the bulk queue — otherwise the
    express response would wait out a full coalesced dispatch and
    outgrow the scalar slot."""
    from tests.test_native_loop import (
        _connect, _edge_with_batcher, _frame, _http_post,
    )

    edge, b, _ring = _edge_with_batcher(["me"], "me")
    # Re-push with the express mask on (the pump's GUBER_EXPRESS shape).
    b.set_ring(
        np.zeros(0, np.uint64), np.zeros(0, np.uint8), all_self=True,
        enabled=True, cap_lanes=0, max_frame_lanes=16384,
        behavior_mask=2 | 4 | 16, express_mask=1,
    )
    socks = []
    try:
        # Two bulk frames, then one NO_BATCHING express frame — one
        # connection each (response plumbing is not what this pins).
        for i in range(2):
            s = _connect(edge.port)
            socks.append(s)
            s.sendall(_http_post(_frame("xp", [f"b{i}a", f"b{i}b"])))
            assert edge.next(timeout_ms=2000, ingress=b) is native.FAST_LANE
        s = _connect(edge.port)
        socks.append(s)
        s.sendall(_http_post(_frame(
            "xp", ["xk"], behavior=int(Behavior.NO_BATCHING)
        )))
        assert edge.next(timeout_ms=2000, ingress=b) is native.FAST_LANE
        # First take: the express frame ALONE (jumped 4 bulk lanes).
        tb = b.take(65536, timeout_ms=2000)
        assert tb is not None and tb.n == 1 and tb.n_frames == 1
        b.fail(tb, 500, "Error", "application/json", b"{}")
        # Second take: the bulk frames, coalesced.
        tb2 = b.take(65536, timeout_ms=2000)
        assert tb2 is not None and tb2.n == 4 and tb2.n_frames == 2
        b.fail(tb2, 500, "Error", "application/json", b"{}")
        assert b.stats()["expressLanes"] == 1
    finally:
        for s in socks:
            s.close()
        b.free()
        edge.shutdown()


# ---------------------------------------------------------------------
# Readback-flake quarantine (the counted single retry)
# ---------------------------------------------------------------------

def test_host_readback_retries_indexerror_once():
    from gubernator_tpu.models import shard as shard_mod

    class Flaky:
        def __init__(self, fail_times):
            self.fails = fail_times

        def __array__(self, dtype=None, copy=None):
            if self.fails:
                self.fails -= 1
                raise IndexError("list index out of range")
            return np.arange(3)

    before = shard_mod.readback_retries_total()
    out = host_readback(Flaky(1))
    assert list(out) == [0, 1, 2]
    assert shard_mod.readback_retries_total() == before + 1
    # A second consecutive failure propagates (one retry, not a loop).
    with pytest.raises(IndexError):
        host_readback(Flaky(2))
    # Non-IndexError failures propagate untouched.
    class Broken:
        def __array__(self, dtype=None, copy=None):
            raise ValueError("boom")
    with pytest.raises(ValueError):
        host_readback(Broken())
