"""Saturation & SLO observability plane (saturation.py + the metrics /
gateway / service wiring): latency attribution reservoirs, ceil-rank
percentiles, the SLO burn-rate engine, the hot-key sketch, occupancy
telemetry vs an oracle (with the ZERO-extra-device-dispatch pin), the
/debug/status|latency|hotkeys surfaces on both gateways, and the
sample-0 wire-parity contract with the plane active."""

import json

import numpy as np
import pytest

from gubernator_tpu import native, saturation, tracing, wire
from gubernator_tpu.gateway import GatewayServer, handle_request
from gubernator_tpu.metrics import Metrics
from gubernator_tpu.service import IngressColumns, ServiceConfig, V1Service
from gubernator_tpu.types import PeerInfo

T0 = 1_573_430_430_000


@pytest.fixture(autouse=True)
def _clean_plane():
    saturation.reset()
    tracing.reset()
    yield
    saturation.reset()
    tracing.reset()


def _cols(n, salt=0, name="obs"):
    return IngressColumns(
        names=[name] * n,
        unique_keys=[f"k{salt}:{i}" for i in range(n)],
        algorithm=np.zeros(n, np.int32),
        behavior=np.zeros(n, np.int32),
        hits=np.ones(n, np.int64),
        limit=np.full(n, 1_000_000, np.int64),
        duration=np.full(n, 3_600_000, np.int64),
    )


def _service(**kw):
    svc = V1Service(ServiceConfig(cache_size=512, **kw))
    svc.set_peers([PeerInfo(grpc_address="127.0.0.1:1", is_owner=True)])
    return svc


# ---------------------------------------------------------------------
# Ceil-rank percentiles (the bench.py p99 bugfix)
# ---------------------------------------------------------------------
def test_percentile_nearest_rank():
    # n=100, q=0.99: nearest rank is 99 (1-based) -> index 98.  The old
    # floor form min(n-1, int(n*q)) indexed 99 — a different sample.
    vals = list(range(100))
    assert saturation.percentile(vals, 0.99) == 98
    assert saturation.percentile_rank(100, 0.99) == 98
    # Small n: ceil rank keeps the tail honest.
    assert saturation.percentile([1, 2, 3], 0.5) == 2
    assert saturation.percentile([1, 2, 3], 0.99) == 3
    assert saturation.percentile([7], 0.99) == 7
    assert saturation.percentile_rank(10, 0.5) == 4  # rank 5 of 10
    with pytest.raises(ValueError):
        saturation.percentile([], 0.5)


def test_bench_shares_the_percentile():
    import bench

    assert bench.percentile is saturation.percentile


def test_gate_verdict_ceiling_rows():
    import bench

    spec = {"fail_above": 650.0}
    assert bench.gate_verdict(200.0, spec) == ("PASS", 650.0)
    assert bench.gate_verdict(651.0, spec) == ("FAIL", 650.0)
    # Noise straddling the ceiling is inconclusive, never a flip.
    assert bench.gate_verdict(640.0, spec, noise=50.0) == ("SKIP", 650.0)
    assert bench.gate_verdict(700.0, spec, noise=100.0) == ("SKIP", 650.0)


def test_gate_thresholds_carry_latency_ceilings():
    with open("benchmarks/gate_thresholds.json") as f:
        th = json.load(f)
    for row in ("service_ingress_latency_ms_p50",
                "service_ingress_latency_ms_p99"):
        assert "fail_above" in th[row], row
        assert th[row]["min_samples"] >= 1, row


# ---------------------------------------------------------------------
# Phase reservoirs + saturation accumulators
# ---------------------------------------------------------------------
def test_phase_snapshot_percentiles():
    for ms in range(1, 101):
        saturation.observe_phase("dispatch.launch", ms / 1000.0)
    snap = saturation.phase_snapshot()["dispatch.launch"]
    assert snap["count"] == 100
    assert snap["n_samples"] == 100
    assert snap["p50_ms"] == pytest.approx(50.0)
    assert snap["p99_ms"] == pytest.approx(99.0)
    assert snap["max_ms"] == pytest.approx(100.0)
    assert snap["sum_ms"] == pytest.approx(5050.0)


def test_lane_util_and_busy_take_semantics():
    saturation.lane_util.add(1000, 1024)
    saturation.lane_util.add(200, 256)
    assert saturation.lane_util.take() == (1200, 1280, 2)
    assert saturation.lane_util.take() == (0, 0, 0)  # drained
    saturation.dispatcher_busy.add(0.5)
    busy, elapsed = saturation.dispatcher_busy.take()
    assert busy == pytest.approx(0.5)
    assert elapsed > 0


def test_queue_depth_snapshot():
    for d in range(1, 101):
        saturation.observe_queue_depth(d)
    snap = saturation.queue_depth_snapshot()
    assert snap["n_samples"] == 100
    assert snap["p50"] == 50
    assert snap["p99"] == 99
    assert snap["max"] == 100


# ---------------------------------------------------------------------
# SLO engine: burn-rate window math + fast-burn dump
# ---------------------------------------------------------------------
def test_slo_burn_rate_window_math():
    clock = [1000.0]
    slo = saturation.SloEngine(
        target_ms=100.0, objective=0.99, time_fn=lambda: clock[0]
    )
    # 100 requests in the current bucket: 2 bad -> bad fraction 0.02,
    # budget 0.01 -> burn 2.0 on every window containing the bucket.
    for i in range(100):
        good = slo.observe(0.05 if i >= 2 else 0.5)
        assert good is (i >= 2)
    assert slo.burn_rate(300) == pytest.approx(2.0)
    assert slo.burn_rate(3600) == pytest.approx(2.0)
    # 6 minutes later the 5m window has rolled past the counts; the 1h
    # window still sees them.
    clock[0] += 360.0
    assert slo.burn_rate(300) == 0.0
    assert slo.burn_rate(3600) == pytest.approx(2.0)
    # 61 minutes later everything expired.
    clock[0] += 3660.0
    assert slo.burn_rate(3600) == 0.0
    snap = slo.snapshot()
    assert snap["enabled"] is True
    assert snap["target_ms"] == 100.0


def test_slo_bucket_ring_reuse_zeroes_stale_slots():
    clock = [0.0]
    slo = saturation.SloEngine(100.0, 0.99, time_fn=lambda: clock[0])
    slo.observe(1.0)  # bad, bucket epoch 0
    # Exactly one ring revolution later the SAME slot is reused: the
    # stale count must not leak into the new epoch.
    clock[0] += slo.BUCKET_S * slo.N_BUCKETS
    slo.observe(0.01)  # good
    good, bad = slo._window_counts(clock[0], slo.BUCKET_S)
    assert (good, bad) == (1, 0)


def test_slo_disabled_is_inert():
    slo = saturation.SloEngine(target_ms=0.0)
    assert slo.observe(99.0) is None
    assert slo.burn_rate(300) == 0.0
    assert slo.snapshot() == {
        "enabled": False, "target_ms": 0.0, "objective": 0.99,
    }


def test_slo_fast_burn_trips_flight_recorder():
    clock = [50_000.0]
    slo = saturation.SloEngine(10.0, 0.999, time_fn=lambda: clock[0])
    # Below the volume floor nothing trips, no matter how bad: a lone
    # post-restart warmup request must not read as a page (the burn
    # analogue of the bench gate's min_samples thin-tail rule).
    for _ in range(saturation.SloEngine.FAST_MIN_TOTAL - 1):
        slo.observe(5.0)
        clock[0] += 0.05
    assert not [e for e in tracing.events_snapshot()
                if e["kind"] == "slo-fast-burn"]
    # Past the floor, all-bad traffic (burn = 1/0.001 = 1000 >> 14.4)
    # trips on the next check.
    for _ in range(20):
        slo.observe(5.0)
        clock[0] += 0.1
    events = [e for e in tracing.events_snapshot()
              if e["kind"] == "slo-fast-burn"]
    assert events, "fast burn did not trip the flight-recorder event"
    assert events[0]["burn_rate"] >= saturation.SloEngine.FAST_BURN
    # Rate-limited: a second trip inside TRIP_MIN_INTERVAL_S is absorbed.
    for _ in range(20):
        slo.observe(5.0)
        clock[0] += 0.1
    events = [e for e in tracing.events_snapshot()
              if e["kind"] == "slo-fast-burn"]
    assert len(events) == 1


def test_behavior_config_env_knobs():
    from gubernator_tpu.config import setup_daemon_config

    conf = setup_daemon_config(
        env={"GUBER_LATENCY_TARGET_MS": "250", "GUBER_SLO_OBJECTIVE": "0.999"},
    )
    assert conf.behaviors.latency_target_ms == 250.0
    assert conf.behaviors.slo_objective == 0.999
    with pytest.raises(ValueError):
        setup_daemon_config(env={"GUBER_SLO_OBJECTIVE": "99"})
    with pytest.raises(ValueError):
        setup_daemon_config(env={"GUBER_LATENCY_TARGET_MS": "fast"})


# ---------------------------------------------------------------------
# Hot-key sketch
# ---------------------------------------------------------------------
def test_hotkey_sketch_zipf_accuracy():
    rng = np.random.RandomState(7)
    n_keys, n_lanes = 2000, 40_000
    # Zipf-ish: ranks 0..9 soak most of the traffic.
    ranks = np.minimum(
        rng.zipf(1.3, size=n_lanes) - 1, n_keys - 1
    ).astype(np.int64)
    keys = [f"zipf:{r}" for r in range(n_keys)]
    true_counts = np.bincount(ranks, minlength=n_keys)
    sketch = saturation.HotKeySketch(width=4096, depth=4, topk=8)
    for lo in range(0, n_lanes, 1000):
        batch = ranks[lo:lo + 1000]
        batch_keys = [keys[r] for r in batch]
        hs = native.fnv1_batch(batch_keys) if native.available() else np.array(
            [hash(k) & 0xFFFFFFFFFFFFFFFF for k in batch_keys], np.uint64
        )
        sketch.update(hs, batch_keys)
    snap = sketch.snapshot()
    assert snap["total_lanes"] == n_lanes
    got = {row["key"]: row["estimate"] for row in snap["topk"]}
    true_top = np.argsort(true_counts)[::-1]
    # The heaviest keys must be in the top-K with count-min's one-sided
    # error: estimate >= truth, and within a small overcount.
    for r in true_top[:3]:
        key = keys[int(r)]
        assert key in got, (key, list(got)[:8])
        assert got[key] >= true_counts[r]
        assert got[key] <= true_counts[r] + n_lanes * 0.01


def test_hotkey_sketch_decay_halves():
    clock = [0.0]
    sk = saturation.HotKeySketch(
        width=256, depth=2, topk=4, decay_s=10.0, time_fn=lambda: clock[0]
    )
    hs = np.full(64, 12345, np.uint64)
    sk.update(hs, ["hot"] * 64)
    assert sk.snapshot()["topk"][0]["estimate"] == 64
    clock[0] = 11.0
    sk.update(np.array([999], np.uint64), ["cold"])
    est = {r["key"]: r["estimate"] for r in sk.snapshot()["topk"]}
    assert est["hot"] == 32  # halved by the decay


def test_hash_ring_feeds_sketch():
    from gubernator_tpu.parallel.hash_ring import ReplicatedConsistentHash

    ring = ReplicatedConsistentHash()
    ring.add("peer-a")
    ring.add("peer-b")
    sk = saturation.HotKeySketch(width=512, depth=2, topk=4)
    keys = ["viral"] * 50 + [f"cold{i}" for i in range(10)]
    codes, ids = ring.get_batch_codes(keys, sketch=sk)
    assert len(codes) == len(keys) and set(ids) == {"peer-a", "peer-b"}
    snap = sk.snapshot()
    assert snap["total_lanes"] == 60
    assert snap["topk"][0]["key"] == "viral"
    assert snap["topk"][0]["estimate"] >= 50


# ---------------------------------------------------------------------
# Occupancy telemetry vs oracle + the zero-extra-dispatch pin
# ---------------------------------------------------------------------
@pytest.mark.skipif(not native.available(), reason="native runtime unavailable")
def test_occupancy_and_evictions_vs_oracle():
    from gubernator_tpu.models.shard import ShardStore

    cap = 64
    store = ShardStore(capacity=cap)
    n_batches, per_batch = 3, 64

    def batch(salt):
        keys = [f"ev{salt}:{i}" for i in range(per_batch)]
        z = np.zeros(per_batch, np.int32)
        return keys, z, z.copy()

    for b in range(n_batches):
        keys, algo, beh = batch(b)
        store.apply_columns(
            keys, algo, beh,
            np.ones(per_batch, np.int64),
            np.full(per_batch, 1_000, np.int64),
            np.full(per_batch, 3_600_000, np.int64),
            T0 + b,
        )
    # Oracle: 192 distinct keys through a 64-slot LRU = first batch
    # fills, each later distinct key evicts exactly one.
    assert store.size() == cap
    expected_evictions = n_batches * per_batch - cap
    assert store.table.evictions == expected_evictions

    # ZERO-extra-dispatch pin (the replica_commit_dispatches playbook):
    # scraping occupancy/saturation and serving /debug/status must not
    # launch device programs — counted, not timed.
    svc = _service()
    try:
        before = store.device_dispatches
        assert before >= n_batches  # the traffic itself dispatched
        m = Metrics()
        m.slo = saturation.SloEngine(100.0)

        class _Wrap:
            store = None
            conf = svc.conf
            columnar_batcher = svc.columnar_batcher
            local_batcher = svc.local_batcher
            hotkeys = svc.hotkeys

            def ingress_queued_lanes(self):
                return 0

        w = _Wrap()
        w.store = store
        for _ in range(5):
            m.observe_saturation(w)
        assert store.device_dispatches == before
        # The service's own debug surface over its mesh store: same pin.
        svc.get_rate_limits_columns(_cols(32))
        sd = svc.store.device_dispatches
        rd = getattr(svc.store, "replica_commit_dispatches", 0)
        for _ in range(5):
            svc.debug_status()
            svc.metrics.observe_saturation(svc)
        assert svc.store.device_dispatches == sd
        assert getattr(svc.store, "replica_commit_dispatches", 0) == rd
        # And the gauges reflect the oracle numbers.
        ev = m.occupancy_evictions.labels(shard="0")._value.get()  # noqa: SLF001
        assert ev == expected_evictions
    finally:
        svc.close()


# ---------------------------------------------------------------------
# /debug endpoints on both gateways
# ---------------------------------------------------------------------
def _check_debug_payloads(get):
    status = json.loads(get("/debug/status"))
    assert status["health"]["status"] == "healthy"
    assert status["version"]
    assert status["occupancy"]["capacity"] > 0
    assert status["occupancy"]["used"] >= 1
    assert "queuedLanes" in status["ingress"]
    assert "slo" in status and "hotkeys" in status
    latency = json.loads(get("/debug/latency"))
    assert "dispatch.launch" in latency["phases"]
    assert latency["phases"]["dispatch.launch"]["count"] >= 1
    assert "ingress.total" in latency["phases"]
    assert "slo" in latency
    hot = json.loads(get("/debug/hotkeys"))
    assert {"topk", "total_lanes", "width", "depth"} <= set(hot)


def test_debug_endpoints_handle_request():
    svc = _service()
    try:
        body = json.dumps({"requests": [
            {"name": "obs", "uniqueKey": f"k{i}", "hits": "1",
             "limit": "100", "duration": "60000"} for i in range(8)
        ]}).encode()
        st, _, _ = handle_request(svc, "POST", "/v1/GetRateLimits", body)
        assert st == 200

        def get(path):
            st, ctype, payload = handle_request(svc, "GET", path, b"")
            assert st == 200, (path, payload)
            assert ctype == "application/json"
            return payload

        _check_debug_payloads(get)
        # The scrape carries the new families.
        st, _, metrics = handle_request(svc, "GET", "/metrics", b"")
        text = metrics.decode()
        for fam in ("gubernator_latency_attribution_seconds",
                    "gubernator_occupancy_slots",
                    "gubernator_slo_burn_rate",
                    "gubernator_dispatcher_busy_ratio"):
            assert fam in text, fam
    finally:
        svc.close()


def test_debug_endpoints_sync_gateway():
    import urllib.request

    svc = _service()
    gw = GatewayServer(svc)
    gw.start()
    try:
        req = urllib.request.Request(
            f"http://{gw.address}/v1/GetRateLimits",
            data=json.dumps({"requests": [
                {"name": "obs", "uniqueKey": f"g{i}", "hits": "1",
                 "limit": "10", "duration": "60000"} for i in range(8)
            ]}).encode(),
        )
        with urllib.request.urlopen(req, timeout=30) as r:
            assert r.status == 200

        def get(path):
            with urllib.request.urlopen(
                f"http://{gw.address}{path}", timeout=30
            ) as r:
                assert r.status == 200
                return r.read()

        _check_debug_payloads(get)
    finally:
        gw.close()
        svc.close()


@pytest.mark.skipif(not native.available(), reason="native runtime unavailable")
def test_debug_endpoints_native_gateway():
    import urllib.request

    from gubernator_tpu.gateway import NativeGatewayServer

    svc = _service()
    gw = NativeGatewayServer(svc, "127.0.0.1:0")
    gw.start()
    try:
        req = urllib.request.Request(
            f"http://{gw.address}/v1/GetRateLimits",
            data=json.dumps({"requests": [
                {"name": "obs", "uniqueKey": f"n{i}", "hits": "1",
                 "limit": "10", "duration": "60000"} for i in range(8)
            ]}).encode(),
        )
        with urllib.request.urlopen(req, timeout=30) as r:
            assert r.status == 200

        def get(path):
            with urllib.request.urlopen(
                f"http://{gw.address}{path}", timeout=30
            ) as r:
                assert r.status == 200
                return r.read()

        _check_debug_payloads(get)
    finally:
        gw.close()
        svc.close()


# ---------------------------------------------------------------------
# SLO + attribution wired through the request path
# ---------------------------------------------------------------------
def test_observe_latency_feeds_slo_and_total_phase():
    m = Metrics()
    m.slo = saturation.SloEngine(target_ms=100.0, objective=0.9)
    m.observe_latency("/pb.gubernator.V1/GetRateLimits", 0.05)   # good
    m.observe_latency("/pb.gubernator.V1/GetRateLimits", 0.5)    # bad
    m.observe_latency("/pb.gubernator.V1/HealthCheck", 9.9)      # ignored
    snap = m.slo.snapshot()
    assert snap["good_5m"] == 1 and snap["bad_5m"] == 1
    phases = saturation.phase_snapshot()
    assert phases["ingress.total"]["count"] == 2
    good = m.slo_requests.labels(verdict="good")._value.get()  # noqa: SLF001
    bad = m.slo_requests.labels(verdict="bad")._value.get()  # noqa: SLF001
    assert (good, bad) == (1, 2 - 1)


def test_service_latency_target_from_behaviors():
    from gubernator_tpu.config import BehaviorConfig

    beh = BehaviorConfig(latency_target_ms=150.0, slo_objective=0.95)
    svc = V1Service(ServiceConfig(cache_size=256, behaviors=beh))
    try:
        assert svc.slo.enabled and svc.slo.target_ms == 150.0
        assert svc.metrics.slo is svc.slo
        assert svc.slo.objective == 0.95
    finally:
        svc.close()


# ---------------------------------------------------------------------
# Wire parity: the plane must not touch a single wire byte at sample 0
# ---------------------------------------------------------------------
def test_sample0_wire_identical_with_plane_active():
    cols = (
        ["obs"] * 4,
        [f"w{i}" for i in range(4)],
        np.zeros(4, np.int32),
        np.zeros(4, np.int32),
        np.ones(4, np.int64),
        np.full(4, 100, np.int64),
        np.full(4, 60_000, np.int64),
    )
    assert tracing.sample_rate() == 0.0
    before = wire.encode_columns_frame(cols)
    # Exercise every always-on surface: attribution, SLO (enabled and
    # burning), the sketch, queue-depth samples, and a live request.
    svc = _service()
    try:
        svc.slo.target_ms, svc.slo.enabled = 1e-9, True
        svc.get_rate_limits_columns(_cols(16))
        saturation.observe_phase("peer.rpc", 0.001)
        saturation.observe_queue_depth(5)
        svc.hotkeys.update(np.array([1, 2, 3], np.uint64), ["a", "b", "c"])
        handle_request(svc, "GET", "/metrics", b"")
        handle_request(svc, "GET", "/debug/status", b"")
    finally:
        svc.close()
    after = wire.encode_columns_frame(cols)
    assert before == after  # byte-identical: no trace/telemetry bytes
