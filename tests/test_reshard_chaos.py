"""Elastic-membership chaos: live resharding against a real in-process
cluster, under seeded FaultPlans.

The acceptance scenarios for the state-handoff plane:

  * REGRESSION — with GUBER_RESHARD on (default), a ring change no
    longer resets moved buckets: the exactly-once oracle sees the same
    per-key totals a single-owner run would.
  * The GUBER_RESHARD=0 interop mode reproduces the pre-reshard
    behavior bit-for-bit (moved buckets reset, no transfer surface,
    senders negotiate down sticky + breaker/health-neutral).
  * DELAY on transfer frames — reads during the in-flight window
    double-dispatch (new owner + zero-hit peek at the old) and never
    observe a reset bucket; the delayed transfer still commits and the
    final accounting is exact.
  * DROP on transfer frames / owner death mid-transfer, under two
    FaultPlan seeds — transfers abort (counted + flight-recorder
    event), and the oracle's bounds hold: no double-commit ever, and
    over-admission is bounded by the documented slack (the consumption
    that failed to ship).

Every scenario runs under explicit fault-plan seeds so failures replay
bit-for-bit in CI (`make chaos` runs the marker; the fast ones ride
tier-1, the multi-cluster heavy ones are `slow`).
"""

import time

import pytest

from gubernator_tpu import faults, tracing
from gubernator_tpu.cluster import Cluster, fast_test_behaviors
from gubernator_tpu.config import DaemonConfig
from gubernator_tpu.daemon import Daemon
from gubernator_tpu.faults import FaultPlan, FaultRule
from gubernator_tpu.parallel.hash_ring import ReplicatedConsistentHash
from gubernator_tpu.types import (
    Algorithm,
    GetRateLimitsRequest,
    RateLimitRequest,
    SECOND,
)
from gubernator_tpu.utils.clock import Clock

T0 = 1_573_430_430_000
LIMIT = 1000
# Enough keys that every membership delta moves SOME of them, with the
# index LEADING the key: FNV-1 folds trailing bytes in after the last
# multiply, so keys differing only in a suffix ("userN") cluster into
# one vnode gap and move all-or-nothing — index-first keys spread over
# the whole ring (~1/3 move on a 2->3 join, never zero).
KEYS = [f"{i}user" for i in range(64)]

pytestmark = pytest.mark.chaos


def _behaviors(**over):
    beh = fast_test_behaviors()
    # No GLOBAL / MULTI_REGION traffic here: park the sync ticks so the
    # shared 8-device CPU mesh only runs this module's dispatches.
    beh.global_sync_wait_s = 3600.0
    beh.multi_region_sync_wait_s = 3600.0
    beh.retry_backoff_base_s = 0.002
    beh.retry_backoff_max_s = 0.01
    for k, v in over.items():
        setattr(beh, k, v)
    return beh


def _mk(key, hits):
    return RateLimitRequest(
        name="ns", unique_key=key, hits=hits, limit=LIMIT,
        duration=3600 * SECOND, algorithm=Algorithm.TOKEN_BUCKET,
    )


def _hit_all(daemon, hits):
    resp = daemon.service.get_rate_limits(
        GetRateLimitsRequest(requests=[_mk(k, hits) for k in KEYS])
    )
    for k, r in zip(KEYS, resp.responses):
        assert not r.error, (k, r.error)
    return resp.responses


def _remaining(daemon, keys=KEYS):
    resp = daemon.service.get_rate_limits(
        GetRateLimitsRequest(requests=[_mk(k, 0) for k in keys])
    )
    for k, r in zip(keys, resp.responses):
        assert not r.error, (k, r.error)
    return {k: r.remaining for k, r in zip(keys, resp.responses)}


def _spawn_extra(cluster, behaviors):
    conf = DaemonConfig(
        listen_address="127.0.0.1:0", grpc_listen_address="127.0.0.1:0",
        cache_size=2048, global_cache_size=256, behaviors=behaviors,
        peer_discovery_type="static",
    )
    d = Daemon(conf, clock=cluster.daemons[0].clock).start()
    cluster.daemons.append(d)
    cluster.peers = [dm.peer_info for dm in cluster.daemons]
    for dm in cluster.daemons:
        dm.set_peers(cluster.peers)
    return d


def _wait_handoffs(cluster, timeout=30.0):
    for d in cluster.daemons:
        assert d.service.reshard.wait_idle(timeout)


def _moved_keys(old_addrs, new_addrs):
    """Keys whose OWNER differs between the two membership sets (the
    same vectorized diff the drain scan uses)."""
    old, new = ReplicatedConsistentHash(), ReplicatedConsistentHash()
    for a in old_addrs:
        old.add(a)
    for a in new_addrs:
        new.add(a)
    hk = lambda k: _mk(k, 0).hash_key()  # noqa: E731
    return [k for k in KEYS if old.get(hk(k)) != new.get(hk(k))]


@pytest.fixture
def clock():
    c = Clock()
    c.freeze(T0)
    return c


def _start_pair(clock, behaviors=None):
    cl = Cluster().start_with(
        ["", ""], clock=clock, behaviors=behaviors or _behaviors(),
        cache_size=2048,
    )
    # Pre-compile the shapes the scenarios hit so fault timing below
    # never races a first-call device compile.
    for d in cl.daemons:
        d.service.store.apply([_mk("warm", 0)], clock.now_ms())
    return cl


# ---------------------------------------------------------------------
# The headline regression: a ring change no longer resets moved buckets
# ---------------------------------------------------------------------
def test_join_does_not_reset_moved_buckets(clock):
    cl = _start_pair(clock)
    try:
        _hit_all(cl.daemons[0], 7)
        old_addrs = [d.service.advertise_address for d in cl.daemons]
        _spawn_extra(cl, _behaviors())
        _wait_handoffs(cl)
        new_addrs = [d.service.advertise_address for d in cl.daemons]
        moved = _moved_keys(old_addrs, new_addrs)
        assert moved, "expected some keys to move to the joiner"
        committed = sum(
            d.service.reshard.snapshot()["transfersCommitted"]
            for d in cl.daemons
        )
        assert committed >= 1
        # Phase 2 through a different daemon, then the oracle: every
        # key — moved or not — carries BOTH phases.  Pre-PR, moved keys
        # came back with remaining == LIMIT - 7 (reset).
        _hit_all(cl.daemons[1], 7)
        final = _remaining(cl.daemons[2])
        assert all(v == LIMIT - 14 for v in final.values()), {
            k: v for k, v in final.items() if v != LIMIT - 14
        }
        aborted = sum(
            d.service.reshard.snapshot()["transfersAborted"]
            for d in cl.daemons
        )
        assert aborted == 0
    finally:
        cl.stop()


def test_knob_off_reproduces_legacy_reset(clock):
    """GUBER_RESHARD=0 everywhere: the ring change is metadata-only and
    moved buckets DO reset — the documented pre-reshard semantics this
    plane exists to remove (and the contrast proving the regression
    test above tests the plane, not luck)."""
    beh = _behaviors(reshard=False)
    cl = _start_pair(clock, behaviors=beh)
    try:
        _hit_all(cl.daemons[0], 7)
        old_addrs = [d.service.advertise_address for d in cl.daemons]
        _spawn_extra(cl, beh)
        for d in cl.daemons:
            d.service.reshard.wait_idle(5)
            assert d.service.reshard.snapshot()["transfersStarted"] == 0
        new_addrs = [d.service.advertise_address for d in cl.daemons]
        moved = _moved_keys(old_addrs, new_addrs)
        assert moved
        _hit_all(cl.daemons[1], 7)
        final = _remaining(cl.daemons[0])
        for k in KEYS:
            expect = LIMIT - 7 if k in moved else LIMIT - 14
            assert final[k] == expect, (k, final[k], expect)
    finally:
        cl.stop()


# ---------------------------------------------------------------------
# DELAY on transfer frames: double-dispatch reads bridge the window
# ---------------------------------------------------------------------
def test_delayed_transfer_reads_never_see_reset(clock):
    beh = _behaviors(reshard_handoff_s=8.0)
    cl = _start_pair(clock, behaviors=beh)
    try:
        _hit_all(cl.daemons[0], 7)
        old_addrs = [d.service.advertise_address for d in cl.daemons]
        plan = FaultPlan(seed=7)
        plan.add(FaultRule(op="TransferOwnership", kind=faults.DELAY,
                           delay_s=2.5))
        with faults.injected(plan):
            d3 = _spawn_extra(cl, beh)
            new_addrs = [d.service.advertise_address for d in cl.daemons]
            moved = _moved_keys(old_addrs, new_addrs)
            assert moved
            # Reads WHILE the transfer frames are still in flight (the
            # 2.5s injected delay): the primary answer comes from the
            # new owner's fresh bucket, the zero-hit peek from the old
            # owner's still-resident copy; the monotone merge must
            # surface the pre-handoff consumption.
            during = _remaining(cl.daemons[1], moved)
            assert all(v == LIMIT - 7 for v in during.values()), during
            # Same guarantee on the COLUMNAR ingress path (the grouped
            # per-prev-owner peek, not the per-lane dataclass leg).
            import numpy as np

            from gubernator_tpu.service import IngressColumns

            m = len(moved)
            rc = cl.daemons[1].service.get_rate_limits_columns(
                IngressColumns(
                    names=["ns"] * m,
                    unique_keys=list(moved),
                    algorithm=np.zeros(m, np.int32),
                    behavior=np.zeros(m, np.int32),
                    hits=np.zeros(m, np.int64),
                    limit=np.full(m, LIMIT, np.int64),
                    duration=np.full(m, 3600 * SECOND, np.int64),
                )
            )
            cols_during = {
                k: rc.response_at(j) for j, k in enumerate(moved)
            }
            for k, r in cols_during.items():
                assert not r.error, (k, r.error)
                assert r.remaining == LIMIT - 7, (k, r.remaining)
            _wait_handoffs(cl, timeout=60.0)
        # The delayed frames still committed: accounting stays exact.
        _hit_all(cl.daemons[1], 7)
        final = _remaining(d3)
        assert all(v == LIMIT - 14 for v in final.values()), final
    finally:
        cl.stop()


# ---------------------------------------------------------------------
# DROP on transfer frames under two seeds: aborts are counted, bounds
# hold (the exactly-once oracle's slack contract)
# ---------------------------------------------------------------------
@pytest.mark.slow
@pytest.mark.parametrize("seed", [11, 23])
def test_dropped_transfers_abort_with_bounded_slack(clock, seed):
    beh = _behaviors(reshard_handoff_s=0.2)
    cl = _start_pair(clock, behaviors=beh)
    try:
        _hit_all(cl.daemons[0], 7)
        plan = FaultPlan(seed=seed)
        # Seeded partial drop: some transfer chunks vanish in flight
        # (timeout-shaped — the receiver MAY have applied them), the
        # rest land.  Both outcomes must satisfy the oracle bounds.
        plan.drop(op="TransferOwnership", rate=0.7)
        ev_before = len(
            [e for e in tracing.events_snapshot()
             if e.get("kind") == "reshard-aborted"]
        )
        with faults.injected(plan):
            _spawn_extra(cl, beh)
            _wait_handoffs(cl, timeout=60.0)
        snaps = [d.service.reshard.snapshot() for d in cl.daemons]
        started = sum(s["transfersStarted"] for s in snaps)
        aborted = sum(s["transfersAborted"] for s in snaps)
        assert started >= 1
        if aborted:
            # Counted AND flight-recorded (the PR 4 auto-dump path).
            ev_after = [
                e for e in tracing.events_snapshot()
                if e.get("kind") == "reshard-aborted"
            ]
            assert len(ev_after) > ev_before
        # Let the double-dispatch window lapse so the oracle reads the
        # settled (post-handoff) state.
        time.sleep(0.3)
        _hit_all(cl.daemons[1], 7)
        final = _remaining(cl.daemons[0])
        for k, rem in final.items():
            consumed = LIMIT - rem
            # No double-commit, ever: a key can never have consumed
            # more than the hits actually sent.
            assert consumed <= 14, (k, consumed)
            # Bounded loss: at worst the pre-handoff consumption (7)
            # failed to ship — phase 2 is always accounted.
            assert consumed >= 7, (k, consumed)
    finally:
        cl.stop()


# ---------------------------------------------------------------------
# Owner death mid-transfer under two seeds
# ---------------------------------------------------------------------
@pytest.mark.slow
@pytest.mark.parametrize("seed", [5, 17])
def test_owner_death_mid_transfer(clock, seed):
    beh = _behaviors(reshard_handoff_s=0.2)
    cl = _start_pair(clock, behaviors=beh)
    try:
        _hit_all(cl.daemons[0], 7)
        victim = cl.daemons[0]
        plan = FaultPlan(seed=seed)
        # The victim's transfers all vanish (its frames never arrive) —
        # then the process dies.
        plan.drop(op="TransferOwnership")
        with faults.injected(plan):
            _spawn_extra(cl, beh)
            # Kill the old owner while its handoff is mid-flight.
            victim.close()
            cl.daemons.remove(victim)
        plan.heal()
        # The survivors re-converge on a ring without the dead owner.
        cl.peers = [dm.peer_info for dm in cl.daemons]
        for dm in cl.daemons:
            dm.set_peers(cl.peers)
        _wait_handoffs(cl, timeout=60.0)
        time.sleep(0.3)  # let the double-dispatch window lapse
        _hit_all(cl.daemons[0], 7)
        final = _remaining(cl.daemons[1])
        for k, rem in final.items():
            consumed = LIMIT - rem
            # No double-commit: never more than the hits sent.
            assert consumed <= 14, (k, consumed)
            # Bounded loss: the dead owner's unshipped phase-1
            # consumption is the documented slack; phase 2 is always
            # accounted.
            assert consumed >= 7, (k, consumed)
        # And the cluster is healthy again.
        for dm in cl.daemons:
            hc = dm.service.health_check()
            assert hc.peer_count == len(cl.daemons)
    finally:
        cl.stop()


# ---------------------------------------------------------------------
# Mixed-version interop: a GUBER_RESHARD=0 receiver negotiates cleanly
# ---------------------------------------------------------------------
def test_knob_off_receiver_negotiates_sticky_and_neutral(clock):
    beh = _behaviors(reshard_handoff_s=0.2)
    cl = _start_pair(clock, behaviors=beh)
    try:
        _hit_all(cl.daemons[0], 7)
        old_addrs = [d.service.advertise_address for d in cl.daemons]
        # The joiner speaks NO transfer plane (GUBER_RESHARD=0): its
        # gRPC server never registers TransferOwnership, exactly like a
        # pre-reshard build.
        d3 = _spawn_extra(cl, _behaviors(reshard=False))
        _wait_handoffs(cl)
        new_addrs = [d.service.advertise_address for d in cl.daemons]
        moved = _moved_keys(old_addrs, new_addrs)
        assert moved
        aborted = sum(
            d.service.reshard.snapshot()["transfersAborted"]
            for d in cl.daemons[:2]
        )
        assert aborted >= 1  # classic fallback: counted, not silent
        for d in cl.daemons[:2]:
            for p in d.service.get_peer_list():
                if p.info.grpc_address == d3.service.advertise_address:
                    # Sticky downgrade, breaker- and health-neutral:
                    # the version probe is an answer, not a fault.
                    assert p._transfer_supported is False
                    assert p.breaker.state_code == 0  # closed
            hc = d.service.health_check()
            assert hc.status == "healthy", hc.message
        # Legacy semantics for the moved keys after the window lapses:
        # they reset on the new owner (the documented fallback).
        time.sleep(0.3)
        _hit_all(cl.daemons[1], 7)
        final = _remaining(cl.daemons[0])
        for k in KEYS:
            expect = LIMIT - 7 if k in moved else LIMIT - 14
            assert final[k] == expect, (k, final[k], expect)
    finally:
        cl.stop()
