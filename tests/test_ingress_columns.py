"""Public columnar ingress (the front door): ColumnsV1Client end to
end against live daemons, mixed-version negotiation both directions,
validation parity, tracing continuity, and the V1Client keep-alive
retry satellite.  Wire-byte goldens live in test_wire_golden.py."""

from __future__ import annotations

import json
import socket
import threading

import numpy as np
import pytest

from gubernator_tpu import tracing, wire
from gubernator_tpu.client import ColumnsV1Client, GrpcV1Client, V1Client
from gubernator_tpu.cluster import fast_test_behaviors
from gubernator_tpu.config import (
    INGRESS_COLUMNS_MAX_LANES,
    DaemonConfig,
)
from gubernator_tpu.daemon import Daemon
from gubernator_tpu.gateway import handle_request
from gubernator_tpu.service import ServiceConfig, V1Service
from gubernator_tpu.types import (
    SECOND,
    GetRateLimitsRequest,
    PeerInfo,
    RateLimitRequest,
)
from gubernator_tpu.utils.clock import Clock

from . import oracle

T0 = 1_573_430_400_000


def _standalone(clock, ingress_columns: bool) -> Daemon:
    behaviors = fast_test_behaviors()
    behaviors.ingress_columns = ingress_columns
    behaviors.global_sync_wait_s = 3600.0
    behaviors.multi_region_sync_wait_s = 3600.0
    d = Daemon(
        DaemonConfig(
            listen_address="127.0.0.1:0",
            grpc_listen_address="127.0.0.1:0",
            cache_size=4096,
            global_cache_size=256,
            behaviors=behaviors,
            peer_discovery_type="static",
        ),
        clock=clock,
    ).start()
    d.set_peers([d.peer_info])
    return d


@pytest.fixture(scope="module")
def daemons():
    """One columns-speaking daemon and one GUBER_INGRESS_COLUMNS=0
    daemon — the exact front-door wire behavior of a pre-columns
    build (no gRPC columns method, no frame sniff)."""
    clock = Clock()
    clock.freeze(T0)
    cols_d = _standalone(clock, ingress_columns=True)
    classic_d = _standalone(clock, ingress_columns=False)
    yield cols_d, classic_d, clock
    cols_d.close()
    classic_d.close()


def _check_against_oracle(client, clock, name, n_keys=6, hits_each=3,
                          limit=2):
    cache = oracle.OracleCache()
    keys = [f"k{i}" for i in range(n_keys)]
    for _ in range(hits_each):
        reqs = [
            RateLimitRequest(
                name=name, unique_key=k, hits=1, limit=limit,
                duration=9 * SECOND,
            )
            for k in keys
        ]
        got = client.get_rate_limits(
            GetRateLimitsRequest(requests=reqs)
        ).responses
        assert len(got) == len(keys)
        for k, r, req in zip(keys, got, reqs):
            assert not r.error, (k, r.error)
            expect = oracle.apply(cache, req, clock.now_ms())
            assert r.status == expect.status, (k, r, expect)
            assert r.remaining == expect.remaining, (k, r, expect)


def _batches_counter(daemon, encoding: str) -> float:
    c = daemon.service.metrics.ingress_columns_batches.labels(
        encoding=encoding
    )
    return c._value.get()


def test_columns_client_end_to_end(daemons):
    """ColumnsV1Client against a columns daemon: oracle-correct
    answers, the negotiation locks in columnar, and the daemon served
    the batches from the frame path (counted per encoding)."""
    cols_d, _classic_d, clock = daemons
    before = _batches_counter(cols_d, "frame")
    c = ColumnsV1Client(cols_d.peer_info.http_address, timeout_s=10.0)
    try:
        _check_against_oracle(c, clock, "fdoor_e2e")
        assert c._columnar is True
        assert _batches_counter(cols_d, "frame") > before
    finally:
        c.close()


def test_concurrent_checks_coalesce_into_frames(daemons):
    """Concurrent single checks ride ONE window: far fewer wire frames
    than checks (the client-side batching the front door exists for)."""
    cols_d, _classic_d, _clock = daemons
    before = _batches_counter(cols_d, "frame")
    c = ColumnsV1Client(
        cols_d.peer_info.http_address, timeout_s=10.0, batch_wait_s=0.02
    )
    try:
        c.check("fdoor_warm", "w", hits=1, limit=100,
                duration=60_000).result(timeout=10)
        futs = [
            c.check("fdoor_coal", f"k{i}", hits=1, limit=100,
                    duration=60_000)
            for i in range(64)
        ]
        for f in futs:
            assert f.result(timeout=10).remaining >= 0
        frames = _batches_counter(cols_d, "frame") - before
        assert frames < 16, frames  # 65 checks, a handful of frames
    finally:
        c.close()


def test_knob_off_downgrades_sticky_and_byte_identical(daemons):
    """Against a GUBER_INGRESS_COLUMNS=0 daemon the first frame answers
    400 (its json.loads rejects the binary body, exactly a pre-columns
    build); the client downgrades sticky inside the same flush and its
    classic bodies are BYTE-IDENTICAL to a pre-PR V1Client's."""
    _cols_d, classic_d, clock = daemons
    c = ColumnsV1Client(classic_d.peer_info.http_address, timeout_s=10.0)
    sent: list = []
    orig = c._json_client._roundtrip

    def spy(method, path, body, content_type="application/json"):
        sent.append((path, body))
        return orig(method, path, body, content_type)

    c._json_client._roundtrip = spy
    try:
        _check_against_oracle(c, clock, "fdoor_mix")
        assert c._columnar is False  # negotiated down, remembered
        assert sent, "downgrade never sent classic JSON"
        reqs = [
            RateLimitRequest(
                name="fdoor_mix", unique_key=f"k{i}", hits=1, limit=2,
                duration=9 * SECOND,
            )
            for i in range(6)
        ]
        want = json.dumps(
            GetRateLimitsRequest(requests=reqs).to_json()
        ).encode()
        assert any(body == want for _path, body in sent), (
            "no classic body matched the pre-PR client encoding"
        )
        # Sticky: later requests never probe with a frame again.
        sent.clear()
        c.get_rate_limits(GetRateLimitsRequest(requests=reqs[:2]))
        assert len(sent) == 1 and sent[0][0] == "/v1/GetRateLimits"
    finally:
        c.close()


def test_plain_json_client_untouched_by_knob(daemons):
    """A classic JSON client gets byte-identical responses from a
    columns daemon and a knob-off daemon (same frozen clock): the
    front door changes nothing for classic traffic."""
    cols_d, classic_d, _clock = daemons
    body = json.dumps({
        "requests": [{
            "name": "fdoor_plain", "uniqueKey": "pk", "hits": "1",
            "limit": "10", "duration": "60000",
            "algorithm": "TOKEN_BUCKET", "behavior": 0,
        }]
    }).encode()
    raws = []
    for d in (cols_d, classic_d):
        v = V1Client(d.peer_info.http_address, timeout_s=10.0)
        try:
            status, raw = v._roundtrip("POST", "/v1/GetRateLimits", body)
            assert status == 200
            raws.append(raw)
        finally:
            v.close()
    assert raws[0] == raws[1]


def test_grpc_columns_negotiation_both_directions(daemons):
    """gRPC front door: the columns daemon serves
    V1/GetRateLimitsColumns; the knob-off daemon answers UNIMPLEMENTED
    and the client downgrades sticky to classic GetRateLimits."""
    cols_d, classic_d, _clock = daemons
    n = 4
    cols = (
        ["fdoor_grpc"] * n, [f"g{i}" for i in range(n)],
        np.zeros(n, np.int32), np.zeros(n, np.int32),
        np.ones(n, np.int64), np.full(n, 10, np.int64),
        np.full(n, 60_000, np.int64),
    )
    before = _batches_counter(cols_d, "proto")
    gc = GrpcV1Client(cols_d.peer_info.grpc_address, timeout_s=10.0)
    try:
        rc = gc.get_rate_limits_columns(cols)
        assert gc._columnar is True
        assert list(rc.remaining) == [9] * n
        assert _batches_counter(cols_d, "proto") > before
        # Untrusted-client validation parity with the HTTP frame edge:
        # an out-of-range algorithm is rejected, never kernel-routed.
        import grpc

        bad = (cols[0], cols[1], np.full(n, 7, np.int32), *cols[3:])
        with pytest.raises(grpc.RpcError) as ei:
            gc.get_rate_limits_columns(bad)
        assert ei.value.code() == grpc.StatusCode.INVALID_ARGUMENT
        # Ragged columns (short algorithm) likewise: INVALID_ARGUMENT,
        # never a server traceback / silent truncation.
        from gubernator_tpu.proto import peers_columns_pb2 as pc_pb

        ragged = pc_pb.PeerColumnsReq(
            names=["a", "b"], unique_keys=["x", "y"], algorithm=[0],
            behavior=[0, 0], hits=[1, 1], limit=[1, 1], duration=[1, 1],
        )
        with pytest.raises(grpc.RpcError) as ei2:
            gc._get_rate_limits_columns(ragged, timeout=10.0)
        assert ei2.value.code() == grpc.StatusCode.INVALID_ARGUMENT
    finally:
        gc.close()
    gc2 = GrpcV1Client(classic_d.peer_info.grpc_address, timeout_s=10.0)
    try:
        rc2 = gc2.get_rate_limits_columns(cols)
        assert gc2._columnar is False
        assert [rc2.response_at(i).remaining for i in range(n)] == [9] * n
        rc3 = gc2.get_rate_limits_columns(cols)  # sticky, still correct
        assert [rc3.response_at(i).remaining for i in range(n)] == [8] * n
        # Downgraded OVERSIZE batch: the classic leg must re-chunk to
        # the 1000-item cap instead of sending one rejected request.
        m = 1500
        big = (
            ["fdoor_grpc_big"] * m, [f"b{i}" for i in range(m)],
            np.zeros(m, np.int32), np.zeros(m, np.int32),
            np.ones(m, np.int64), np.full(m, 10, np.int64),
            np.full(m, 60_000, np.int64),
        )
        rcb = gc2.get_rate_limits_columns(big)
        assert rcb.n == m
        assert rcb.response_at(0).remaining == 9
        assert rcb.response_at(m - 1).remaining == 9
    finally:
        gc2.close()


def test_frame_validation_parity(daemons):
    """Empty unique_key / name lanes in a frame answer per-lane errors
    with the exact JSON-path wording; good lanes in the same frame
    still serve."""
    cols_d, _classic_d, _clock = daemons
    cols = (
        ["fdoor_val", "", "fdoor_val"], ["", "u", "ok"],
        np.zeros(3, np.int32), np.zeros(3, np.int32),
        np.ones(3, np.int64), np.full(3, 10, np.int64),
        np.full(3, 60_000, np.int64),
    )
    st, ct, body = handle_request(
        cols_d.service, "POST", "/v1/GetRateLimits",
        wire.encode_ingress_frame(cols),
    )
    assert st == 200 and ct == wire.COLUMNS_CONTENT_TYPE
    rc = wire.decode_ingress_result_frame(body)
    assert rc.overrides[0].error == "field 'unique_key' cannot be empty"
    assert rc.overrides[1].error == "field 'namespace' cannot be empty"
    assert 2 not in rc.overrides and rc.remaining[2] == 9


def test_oversize_and_malformed_frames_answer_400(daemons):
    cols_d, _classic_d, _clock = daemons
    n = INGRESS_COLUMNS_MAX_LANES + 1
    cols = (
        ["t"] * n, ["k"] * n,
        np.zeros(n, np.int32), np.zeros(n, np.int32),
        np.ones(n, np.int64), np.ones(n, np.int64), np.ones(n, np.int64),
    )
    st, _ct, body = handle_request(
        cols_d.service, "POST", "/v1/GetRateLimits",
        wire.encode_ingress_frame(cols),
    )
    assert st == 400 and b"too large" in body
    # Truncated frame: 400 naming the frame, not a 500.
    frame = wire.encode_ingress_frame((
        ["a"], ["b"], np.zeros(1, np.int32), np.zeros(1, np.int32),
        np.ones(1, np.int64), np.ones(1, np.int64), np.ones(1, np.int64),
    ))
    st2, _ct2, body2 = handle_request(
        cols_d.service, "POST", "/v1/GetRateLimits", frame[:-3]
    )
    assert st2 == 400 and b"invalid columns frame" in body2
    # Out-of-range algorithm: rejected at the decode edge.
    bad = (
        ["a"], ["b"], np.array([7], np.int32), np.zeros(1, np.int32),
        np.ones(1, np.int64), np.ones(1, np.int64), np.ones(1, np.int64),
    )
    st3, _ct3, body3 = handle_request(
        cols_d.service, "POST", "/v1/GetRateLimits",
        wire.encode_ingress_frame(bad),
    )
    assert st3 == 400 and b"algorithm out of range" in body3
    # Invalid UTF-8 in a string column: 400 at the decode edge (NOT a
    # 500 from a deferred lazy decode deep in routing) — identical on
    # the native and numpy decode paths.
    ok = wire.encode_ingress_frame((
        ["ab"], ["u"], np.zeros(1, np.int32), np.zeros(1, np.int32),
        np.ones(1, np.int64), np.ones(1, np.int64), np.ones(1, np.int64),
    ))
    corrupt = bytearray(ok)
    name_pos = corrupt.index(b"ab")
    corrupt[name_pos:name_pos + 2] = b"\xff\xfe"
    st4, _ct4, body4 = handle_request(
        cols_d.service, "POST", "/v1/GetRateLimits", bytes(corrupt)
    )
    assert st4 == 400 and b"not valid utf-8" in body4


def test_trace_continuity_client_to_dispatch(daemons):
    """A sampled client request yields ONE trace id from the client
    through the daemon's dispatch: the frame's GTRC trailer feeds
    request_links, so the batch spans link the client's context (the
    PR 4 span-link rule, now crossing the PUBLIC hop)."""
    cols_d, _classic_d, _clock = daemons
    prev = tracing.sample_rate()
    tracing.set_sample_rate(1.0)
    try:
        tid, sid = 0x1234567890ABCDEF1234567890ABCDEF, 0x1122334455667788
        cols = (
            ["fdoor_trace"], ["tk"],
            np.zeros(1, np.int32), np.zeros(1, np.int32),
            np.ones(1, np.int64), np.full(1, 10, np.int64),
            np.full(1, 60_000, np.int64),
        )
        # 1-lane requests ride the dataclass router; use a 2-lane batch
        # so the columnar dispatch (where links attach) serves it.
        cols = tuple(
            c * 2 if isinstance(c, list) else np.concatenate([c, c])
            for c in cols
        )
        frame = wire.encode_ingress_frame(cols, trace=[(0, 2, tid, sid)])
        st, _ct, _body = handle_request(
            cols_d.service, "POST", "/v1/GetRateLimits", frame
        )
        assert st == 200
        spans = tracing.spans_snapshot(f"{tid:032x}")
        assert any(s["name"].startswith("dispatch.") or
                   s["name"] == "batch.window" for s in spans), spans
    finally:
        tracing.set_sample_rate(prev)


def test_client_rejects_bad_algorithm_per_caller(daemons):
    """submit_columns validates algorithm BEFORE coalescing: one bad
    caller must not 400 a shared frame and fail innocent riders — and
    a columns-aware daemon's frame 400 must never read as a version
    answer (no silent permanent downgrade)."""
    cols_d, _classic_d, _clock = daemons
    c = ColumnsV1Client(cols_d.peer_info.http_address, timeout_s=10.0)
    try:
        with pytest.raises(ValueError):
            c.check("fdoor_bad", "k", algorithm=7)
        assert c._columnar is None  # nothing was sent, nothing negotiated
        # A 400 naming the columns frame (columns-aware daemon, client
        # bug) fails the chunk but does NOT downgrade the client.
        from concurrent.futures import Future

        fut: Future = Future()
        cols = (
            ["a"], ["b"], np.zeros(1, np.int32), np.zeros(1, np.int32),
            np.ones(1, np.int64), np.ones(1, np.int64),
            np.ones(1, np.int64),
        )
        reply: Future = Future()
        reply.set_result(
            (400, b'{"code": 3, "message": "invalid columns frame: x"}')
        )
        c._on_frame_reply([(cols, fut)], cols, reply)
        assert c._columnar is None
        with pytest.raises(RuntimeError):
            fut.result(timeout=1)
    finally:
        c.close()


def test_sample_zero_wire_identity():
    """GUBER_TRACE_SAMPLE=0 keeps the client's frames byte-identical to
    the traceless layout (the PR 4 parity contract on the public hop):
    nothing in the client attaches a trailer when tracing is off."""
    assert tracing.sample_rate() == 0.0
    cols = (
        ["a"], ["b"], np.zeros(1, np.int32), np.zeros(1, np.int32),
        np.ones(1, np.int64), np.ones(1, np.int64), np.ones(1, np.int64),
    )
    c = ColumnsV1Client("127.0.0.1:1", timeout_s=0.1)
    try:
        chunk = [(cols, type("F", (), {"done": lambda self: True})())]
        assert c._trace_entries(chunk) is None
    finally:
        c._closed = True  # nothing was ever sent; skip the flush
        c._window.stop(timeout_s=0.1)


# ---------------------------------------------------------------------
# Satellite: V1Client transparent retry on stale keep-alive sockets
# ---------------------------------------------------------------------

class _OneShotKeepAliveServer(threading.Thread):
    """Accepts connections, serves exactly ONE response per connection
    (advertising keep-alive), then closes the socket — the idle-expiry
    behavior that makes a reused client connection go stale."""

    def __init__(self, close_immediately: bool = False):
        super().__init__(daemon=True)
        self.sock = socket.socket()
        self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.sock.bind(("127.0.0.1", 0))
        self.sock.listen(8)
        self.address = "127.0.0.1:%d" % self.sock.getsockname()[1]
        self.connections = 0
        self.requests = 0
        self.close_immediately = close_immediately
        self._stop = threading.Event()

    def run(self):
        while not self._stop.is_set():
            try:
                conn, _ = self.sock.accept()
            except OSError:
                return
            self.connections += 1
            if self.close_immediately:
                conn.close()
                continue
            try:
                conn.settimeout(5.0)
                buf = b""
                while b"\r\n\r\n" not in buf:
                    chunk = conn.recv(65536)
                    if not chunk:
                        raise OSError("client closed")
                    buf += chunk
                head, _, rest = buf.partition(b"\r\n\r\n")
                clen = 0
                for line in head.split(b"\r\n"):
                    if line.lower().startswith(b"content-length:"):
                        clen = int(line.split(b":")[1])
                while len(rest) < clen:
                    rest += conn.recv(65536)
                self.requests += 1
                body = b'{"status": "healthy", "peerCount": 1}'
                conn.sendall(
                    b"HTTP/1.1 200 OK\r\nContent-Type: application/json\r\n"
                    b"Content-Length: %d\r\n\r\n%s" % (len(body), body)
                )
            except OSError:
                pass
            finally:
                conn.close()  # keep-alive advertised, socket closed anyway

    def stop(self):
        self._stop.set()
        try:
            self.sock.close()
        except OSError:
            pass


def test_v1client_retries_stale_keepalive_once():
    """A server that closes idle kept-alive sockets: the second request
    hits the dead socket and is retried ONCE on a fresh connection
    transparently — the caller never sees the expiry race."""
    srv = _OneShotKeepAliveServer()
    srv.start()
    try:
        c = V1Client(srv.address, timeout_s=5.0)
        assert c.health_check().status == "healthy"  # conn 1
        # The server closed the socket after responding; this request
        # writes into the dead conn, gets the disconnect, and must
        # retry on a fresh connection without surfacing the error.
        assert c.health_check().status == "healthy"  # conn 2 (retried)
        assert c.health_check().status == "healthy"  # conn 3 (retried)
        assert srv.requests == 3
        assert srv.connections == 3
        c.close()
    finally:
        srv.stop()


def test_v1client_fresh_connection_failure_surfaces():
    """The retry covers ONLY the stale-reuse race: a server that kills
    fresh connections is a real failure and must raise."""
    srv = _OneShotKeepAliveServer(close_immediately=True)
    srv.start()
    try:
        c = V1Client(srv.address, timeout_s=5.0)
        with pytest.raises(Exception):
            c.health_check()
        c.close()
    finally:
        srv.stop()


def test_service_rejects_oversize_without_columns_flag():
    """The classic MAX_BATCH_SIZE cap still guards the dataclass/JSON
    surface: only the columnar edges opt into the larger lane cap."""
    svc = V1Service(ServiceConfig(cache_size=1024))
    try:
        svc.set_peers([PeerInfo(grpc_address="127.0.0.1:1", is_owner=True)])
        from gubernator_tpu.service import ApiError, IngressColumns

        n = 1001
        cols = IngressColumns(
            names=["t"] * n, unique_keys=[f"k{i}" for i in range(n)],
            algorithm=np.zeros(n, np.int32), behavior=np.zeros(n, np.int32),
            hits=np.ones(n, np.int64), limit=np.ones(n, np.int64),
            duration=np.ones(n, np.int64),
        )
        with pytest.raises(ApiError):
            svc.get_rate_limits_columns(cols)
        # The columnar edge's cap admits the same batch.
        rc = svc.get_rate_limits_columns(
            cols, max_lanes=INGRESS_COLUMNS_MAX_LANES
        )
        assert rc.n == n
    finally:
        svc.close()
