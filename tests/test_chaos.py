"""Chaos tests: seeded fault plans against a real in-process cluster.

The acceptance scenario for the fault-tolerance layer: partition a
key's owner under sustained load and watch the full breaker cycle —
consecutive failures open the circuit, traffic degrades to local
evaluation without blocking the batch window, and the breaker re-closes
once the peer returns (half-open probe succeeds).  Every test runs
under explicit fault-plan seeds so failures replay bit-for-bit in CI
(`make chaos` runs the marker; the fast ones also ride tier-1).
"""

import http.client
import json
import threading
import time

import pytest

from gubernator_tpu import faults
from gubernator_tpu.cluster import Cluster, fast_test_behaviors
from gubernator_tpu.faults import FaultPlan, FaultRule
from gubernator_tpu.types import (
    Algorithm,
    GetRateLimitsRequest,
    RateLimitRequest,
    SECOND,
)
from gubernator_tpu.utils.clock import Clock

T0 = 1_573_430_430_000

pytestmark = pytest.mark.chaos


@pytest.fixture(scope="module")
def clock():
    c = Clock()
    c.freeze(T0)
    return c


@pytest.fixture(scope="module")
def cluster(clock):
    behaviors = fast_test_behaviors()
    behaviors.circuit_threshold = 3
    behaviors.circuit_open_interval_s = 1.0
    behaviors.forward_retry_limit = 4
    behaviors.retry_backoff_base_s = 0.002
    behaviors.retry_backoff_max_s = 0.01
    # No GLOBAL / MULTI_REGION traffic in these tests: park the sync
    # intervals so the per-daemon sync ticks don't add device load (and
    # sync-collective serialization waits, mesh._SYNC_COLLECTIVE_LOCK)
    # under the already-heavy traffic the degraded-local-eval path
    # generates on the shared 8-device CPU mesh.
    behaviors.global_sync_wait_s = 3600.0
    behaviors.multi_region_sync_wait_s = 3600.0
    cl = Cluster().start_with(["", "", ""], clock=clock, behaviors=behaviors)
    # Pre-compile the single-item store.apply shape the degraded path
    # uses, so breaker-interval timing below never races a first-call
    # device compile.
    for d in cl.daemons:
        d.service.store.apply([_mk("warmup", "w", hits=0)], clock.now_ms())
    yield cl
    cl.stop()


def _mk(name, key, hits=1, limit=10):
    return RateLimitRequest(
        name=name, unique_key=key, hits=hits, limit=limit,
        duration=9 * SECOND, algorithm=Algorithm.TOKEN_BUCKET,
    )


def _entry_and_owner(cluster, hash_key):
    """A daemon that does NOT own `hash_key`, plus its PeerClient for
    the daemon that does."""
    for d in cluster.daemons:
        peer = d.service.get_peer(hash_key)
        if not peer.info.is_owner:
            return d, peer
    raise RuntimeError("no non-owner daemon found")


def _one(daemon, req):
    return daemon.service.get_rate_limits(
        GetRateLimitsRequest(requests=[req])
    ).responses[0]


def _shape(resp):
    if resp.error:
        return "error"
    if (resp.metadata or {}).get("degraded") == "true":
        return "degraded"
    return "ok"


def _get_json(http_address, path):
    host, _, port = http_address.partition(":")
    conn = http.client.HTTPConnection(host, int(port), timeout=5.0)
    try:
        conn.request("GET", path)
        r = conn.getresponse()
        return r.status, json.loads(r.read())
    finally:
        conn.close()


def until_pass(fn, timeout_s=5.0, interval_s=0.05):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if fn():
            return True
        time.sleep(interval_s)
    return False


# ----------------------------------------------------------------------
# The acceptance scenario, under two different fault-plan seeds
# ----------------------------------------------------------------------
@pytest.mark.parametrize("seed", [11, 23])
def test_breaker_cycle_under_partition(cluster, seed):
    req = _mk(f"chaos_breaker_{seed}", "k", limit=1000)
    hk = req.hash_key()
    entry, owner_peer = _entry_and_owner(cluster, hk)
    owner_addr = owner_peer.info.grpc_address

    plan = FaultPlan(seed=seed)
    plan.partition(owner_addr)
    with faults.injected(plan):
        # Sustained load against the partitioned owner.  Request 1 burns
        # the re-pick budget observing real failures (threshold=3 opens
        # the breaker mid-retry) and errors; every request after that
        # fast-fails at the breaker and degrades to local evaluation.
        start = time.monotonic()
        trace = [_shape(_one(entry, req)) for _ in range(8)]
        elapsed = time.monotonic() - start
        assert trace[0] == "error"
        assert trace[1:] == ["degraded"] * 7, trace
        assert owner_peer.breaker.is_open
        # Degraded traffic never waits on the dead peer: 7 local evals
        # plus one budgeted retry loop complete far inside the 5 s batch
        # window the old code would have burned PER send.
        assert elapsed < 4.0
        # Degraded responses still enforce the limit from the local
        # shard and name the unreachable owner.
        resp = _one(entry, req)
        assert resp.metadata["owner"] == owner_addr
        assert int(resp.remaining) < 1000

        # Health surfaces the open breaker, on the wire via /healthz.
        assert entry.service.health_check().breaker_open_count >= 1
        status, payload = _get_json(entry.peer_info.http_address, "/healthz")
        assert status == 200
        assert payload["breakerOpenCount"] >= 1

        # The peer returns: heal the partition, let the open interval
        # lapse — the half-open probe succeeds and re-closes the breaker.
        plan.heal(owner_addr)
        time.sleep(behavior_open_interval(cluster) + 0.05)

        def recovered():
            r = _one(entry, req)
            return _shape(r) == "ok" and r.metadata.get("owner") == owner_addr

        assert until_pass(recovered, timeout_s=5.0)
        assert owner_peer.breaker.state == faults.CLOSED


def behavior_open_interval(cluster):
    return cluster.daemons[0].conf.behaviors.circuit_open_interval_s


def test_metrics_export_breaker_and_degraded_counters(cluster):
    """After a breaker cycle the scrape surface carries the new series."""
    status, _ = _get_json(cluster.daemons[0].peer_info.http_address, "/healthz")
    assert status == 200
    host, _, port = cluster.daemons[0].peer_info.http_address.partition(":")
    conn = http.client.HTTPConnection(host, int(port), timeout=5.0)
    try:
        conn.request("GET", "/metrics")
        text = conn.getresponse().read().decode()
    finally:
        conn.close()
    assert "gubernator_circuit_breaker_state" in text
    assert "gubernator_degraded_local_evals" in text


# ----------------------------------------------------------------------
# Forward re-pick under peer death: exactly-once
# ----------------------------------------------------------------------
def test_forward_repick_lands_exactly_once(cluster):
    """Kill a key's owner mid-request: the re-pick loop must land the
    hit on the re-picked peer exactly once — no double count on either
    the dead owner or the survivor."""
    req = _mk("chaos_repick", "k")
    hk = req.hash_key()
    entry, owner_peer = _entry_and_owner(cluster, hk)
    owner_addr = owner_peer.info.grpc_address
    behaviors = entry.service.conf.behaviors
    old_budget = behaviors.forward_retry_limit
    old_threshold = owner_peer.breaker.failure_threshold
    # Keep the retry loop alive (no breaker trip, big budget) long
    # enough for "discovery" to remove the dead node deterministically.
    behaviors.forward_retry_limit = 200
    owner_peer.breaker.failure_threshold = 10_000

    plan = FaultPlan(seed=5)
    plan.partition(owner_addr)
    survivors = [p for p in cluster.peers if p.grpc_address != owner_addr]
    resp_box = {}
    try:
        with faults.injected(plan):
            t = threading.Thread(
                target=lambda: resp_box.update(resp=_one(entry, req))
            )
            t.start()
            # The owner is dead: wait until the loop has observed at
            # least two connection-shaped failures mid-retry...
            assert until_pass(
                lambda: plan.calls(owner_addr, "GetPeerRateLimits") >= 2
            )
            # ...then membership drops the dead node and the re-pick
            # resolves to a surviving owner.
            entry.set_peers(survivors)
            t.join(timeout=10.0)
            assert not t.is_alive()
    finally:
        behaviors.forward_retry_limit = old_budget
        owner_peer.breaker.failure_threshold = old_threshold
        owner_peer.breaker.record_success()
        entry.set_peers(cluster.peers)

    resp = resp_box["resp"]
    assert not resp.error
    new_owner = resp.metadata["owner"]
    assert new_owner != owner_addr
    assert new_owner in {p.grpc_address for p in survivors}
    # Applied exactly once on the re-picked peer...
    assert int(resp.remaining) == req.limit - req.hits
    # ...and never on the dead owner (the injected partition is
    # connection-shaped, so the RPC never reached it).
    probe = _mk("chaos_repick", "k", hits=0)
    dead = cluster.daemon_for(owner_peer.info)
    assert int(_one(dead, probe).remaining) == req.limit


def test_timeout_shaped_fault_is_not_retried(cluster):
    """The DEADLINE_EXCEEDED caveat (peer_client.py:44-49): a DROP
    fault presents as a timeout, which may have executed server-side —
    the forward loop must surface the error, not retry into a
    double-count."""
    req = _mk("chaos_drop", "k")
    hk = req.hash_key()
    entry, owner_peer = _entry_and_owner(cluster, hk)
    owner_addr = owner_peer.info.grpc_address

    plan = FaultPlan(seed=7)
    plan.drop_nth(owner_addr, 1)
    with faults.injected(plan):
        resp = _one(entry, req)
        assert resp.error and "injected drop" in resp.error
        assert plan.calls(owner_addr, "GetPeerRateLimits") == 1  # no retry
        # The next request (fault window over) succeeds and shows the
        # dropped hit was never double-applied anywhere.
        ok = _one(entry, req)
        assert not ok.error
        assert int(ok.remaining) == req.limit - req.hits


# ----------------------------------------------------------------------
# Gossip: seeded suspect -> confirm under a probe partition
# ----------------------------------------------------------------------
@pytest.mark.parametrize("seed", [3, 17])
def test_gossip_suspect_confirm_deterministic(seed):
    """Drop every SWIM probe between two nodes (both directions, so no
    refutation path exists) and assert each confirms the other dead —
    reproducibly under the plan seed, with the probe schedule pinned by
    the gossip seed."""
    from gubernator_tpu.gossip import Gossip

    plan = FaultPlan(seed=seed)
    plan.add(FaultRule(peer="*", op=faults.OP_GOSSIP_PROBE, kind=faults.ERROR))
    a = Gossip("127.0.0.1:0", name="a", probe_interval_s=0.05,
               probe_timeout_s=0.05, suspect_timeout_s=0.25,
               sync_interval_s=3600, seed=seed, faults=plan)
    b = Gossip("127.0.0.1:0", name="b", probe_interval_s=0.05,
               probe_timeout_s=0.05, suspect_timeout_s=0.25,
               sync_interval_s=3600, seed=seed, faults=plan)
    try:
        # Join over TCP push-pull (not a probe: unaffected by the plan).
        b.join([a.address], timeout_s=5.0)
        assert until_pass(lambda: len(a.members()) == 2, timeout_s=5.0)
        # Probes all drop: suspicion, then confirmation, on both sides.
        assert until_pass(
            lambda: len(a.members()) == 1 and len(b.members()) == 1,
            timeout_s=10.0,
        )
        assert [m.name for m in a.members()] == ["a"]
        assert [m.name for m in b.members()] == ["b"]
        assert plan.calls(b.address, faults.OP_GOSSIP_PROBE) >= 1
    finally:
        a.close()
        b.close()
