"""Two-tier (front/back) bucket table tests.

The front table absorbs every kernel scatter; LRU evictions demote live
rows to the device-resident back tier instead of dropping them, and
later lookups promote them back (native Table two-tier mode +
ops/buckets.apply_moves).  The semantic contract: a store with front F
and back B behaves EXACTLY like a plain store big enough to never evict
— state survives any number of demote/promote round trips — until the
back tier itself wraps (FIFO), which is the only true loss.
"""

import random

import numpy as np
import pytest

from gubernator_tpu import native
from gubernator_tpu.models.shard import ShardStore
from gubernator_tpu.parallel.mesh import MeshBucketStore
from gubernator_tpu.types import Algorithm, Behavior, RateLimitRequest, Status

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native runtime required"
)

T0 = 1_573_430_430_000


def mk(key, hits=1, limit=10, duration=60_000, algo=Algorithm.TOKEN_BUCKET):
    return RateLimitRequest(
        name="tt", unique_key=key, hits=hits, limit=limit, duration=duration,
        algorithm=algo,
    )


def test_native_table_demote_promote_records():
    t = native.NativeSlotTable(2)
    t.enable_back(8)
    s1, e1 = t.lookup_or_assign("a", T0)
    t.set_expire(s1, T0 + 60_000)  # materialize: only live rows demote
    s2, _ = t.lookup_or_assign("b", T0)
    t.set_expire(s2, T0 + 60_000)
    # capacity 2 full; "c" evicts LRU ("a"), demoting it
    s3, e3 = t.lookup_or_assign("c", T0)
    assert s3 == s1 and e3 is False
    np_, nd = t.move_counts()
    assert (np_, nd) == (0, 1)
    # "a" promotes back (evicting "b" -> demote)
    s4, e4 = t.lookup_or_assign("a", T0)
    assert e4 is True  # state survived: logical hit
    np_, nd = t.move_counts()
    assert (np_, nd) == (1, 2)
    pk, ps, pdst, ds, dd = t.take_moves()
    # the promo source is front slot s1's parked copy or a back slot;
    # the same-window re-promotion must be front-sourced (kind 1)
    assert pk[0] == 1 and pdst[0] == s4
    assert t.move_counts() == (0, 0)
    total, back_keys, demotions, promotions, back_ev = t.tier_stats
    assert demotions == 2 and promotions == 1 and back_ev == 0
    assert total == 3  # a, c in front; b in back


def test_native_table_expired_rows_drop_not_demote():
    t = native.NativeSlotTable(1)
    t.enable_back(4)
    s, _ = t.lookup_or_assign("x", T0)
    t.set_expire(s, T0 + 10)
    t.lookup_or_assign("y", T0 + 1000)  # x expired: plain drop
    assert t.move_counts() == (0, 0)
    assert t.tier_stats[1] == 0  # nothing in back


def test_native_table_back_fifo_eviction():
    t = native.NativeSlotTable(1)
    t.enable_back(2)
    for i, k in enumerate(["a", "b", "c", "d"]):
        s, _ = t.lookup_or_assign(k, T0)
        t.set_expire(s, T0 + 60_000)
    # a, b, c were demoted into a 2-slot FIFO back: a fell off
    total, back_keys, demotions, promotions, back_ev = t.tier_stats
    assert back_keys == 2 and back_ev == 1
    _, e = t.lookup_or_assign("a", T0)
    assert e is False  # truly lost


def churn_workload(rng, n_keys, steps):
    reqs = []
    for step in range(steps):
        k = rng.randrange(n_keys)
        reqs.append((f"k{k}", rng.choice([1, 1, 1, 2])))
    return reqs


@pytest.mark.parametrize("algo", [Algorithm.TOKEN_BUCKET, Algorithm.LEAKY_BUCKET])
def test_two_tier_matches_unevicted_reference(algo):
    """front=8 forces constant demote/promote churn; responses must be
    byte-identical to a store that never evicts."""
    rng = random.Random(11)
    two = MeshBucketStore(capacity_per_shard=2, back_capacity_per_shard=512)
    ref = ShardStore(capacity=4096)
    now = T0
    for step in range(300):
        key = f"k{rng.randrange(40)}"
        r = mk(key, hits=rng.choice([0, 1, 1, 2]), algo=algo)
        now += rng.randrange(0, 500)
        got = two.apply([r], now)[0]
        want = ref.apply([r], now)[0]
        assert (got.status, got.remaining, got.reset_time) == (
            want.status, want.remaining, want.reset_time,
        ), (step, key, got, want)
    # churn actually happened
    stats = [t.tier_stats for t in two.tables]
    assert sum(s[2] for s in stats) > 50, stats  # demotions
    assert sum(s[3] for s in stats) > 50, stats  # promotions
    two.check_consistency()


def test_two_tier_columnar_matches_unevicted_reference():
    """Churn ACROSS batches (shifting key windows): every batch's
    per-shard working set fits the front (the two-tier contract — a
    single batch whose unique keys exceed the front degrades to the
    planner's documented all-pending-slots fallback, reference-grade
    loss), but consecutive windows force constant demote/promote."""
    rng = np.random.RandomState(5)
    two = MeshBucketStore(capacity_per_shard=16, back_capacity_per_shard=2048)
    ref = ShardStore(capacity=8192)
    now = T0
    for step in range(12):
        n = 200
        ids = (step * 40) + rng.randint(0, 80, size=n)
        keys = [f"c{k}" for k in ids]
        algo = (ids % 2).astype(np.int32)
        behavior = np.zeros(n, np.int32)
        hits = np.ones(n, np.int64)
        limit = np.full(n, 50, np.int64)
        duration = np.full(n, 60_000, np.int64)
        now += 700
        got = two.apply_columns(keys, algo, behavior, hits, limit, duration, now)
        want = ref.apply_columns(keys, algo, behavior, hits, limit, duration, now)
        for f in ("status", "remaining", "reset_time"):
            assert np.array_equal(got[f], want[f]), (step, f)
    assert sum(t.tier_stats[2] for t in two.tables) > 100
    two.check_consistency()


def test_two_tier_snapshot_includes_back_rows():
    two = MeshBucketStore(capacity_per_shard=8, back_capacity_per_shard=256)
    now = T0
    for i in range(64):
        two.apply([mk(f"s{i}")], now)
    items = {it.key for it in two.snapshot_items()}
    # every live key must appear regardless of tier
    assert items == {f"tt_s{i}" for i in range(64)}


def test_two_tier_global_sync_promotes_owner_keys():
    """A GLOBAL key demoted by plain-traffic churn must still sync:
    sync_globals re-promotes owner keys before the collective."""
    two = MeshBucketStore(
        capacity_per_shard=4, g_capacity=16, back_capacity_per_shard=256
    )
    now = T0
    g = mk("gk")
    g = RateLimitRequest(
        name="tt", unique_key="gk", hits=1, limit=10, duration=60_000,
        behavior=Behavior.GLOBAL,
    )
    two.apply([g], now)
    # churn every shard's front table so gk demotes
    for i in range(64):
        two.apply([mk(f"churn{i}")], now + 1)
    res = two.sync_globals(now + 2)
    assert res.broadcast_count == 1
    st = res.broadcasts[0].status
    assert st.remaining == 9, st


def test_two_tier_rejects_store_spi():
    class DummyStore:
        def get(self, *a):
            return None

        def on_change(self, *a):
            pass

        def remove(self, *a):
            pass

    with pytest.raises(ValueError, match="Store SPI"):
        MeshBucketStore(
            capacity_per_shard=8, back_capacity_per_shard=64, store=DummyStore()
        )


def test_daemon_passes_back_cache_size_through():
    """GUBER_BACK_CACHE_SIZE must reach the store (round-4 drive found
    the daemon dropping it on the DaemonConfig -> ServiceConfig
    translation: the two-tier flag silently no-opped end-to-end)."""
    from gubernator_tpu.cluster import fast_test_behaviors
    from gubernator_tpu.config import setup_daemon_config
    from gubernator_tpu.daemon import Daemon

    conf = setup_daemon_config(env={
        "GUBER_CACHE_SIZE": "64", "GUBER_BACK_CACHE_SIZE": "4096",
    })
    conf.listen_address = "127.0.0.1:0"
    conf.behaviors = fast_test_behaviors()
    conf.peer_discovery_type = "static"
    d = Daemon(conf).start()
    try:
        assert d.service.store.back is not None
        assert d.service.store.back_capacity_per_shard == 4096 // 8
    finally:
        d.close()


def test_fifo_wrap_during_promotion_preserves_both_keys():
    """Round-4 review repro: promoting 'a' evicts 'b', whose demotion
    must NOT wrap the FIFO cursor onto a's in-flight back slot — that
    handed a the victim's expiry/row and destroyed b outright."""
    t = native.NativeSlotTable(1)
    t.enable_back(2)
    sa, _ = t.lookup_or_assign("a", T0)
    t.set_expire(sa, T0 + 60_000)
    sb, _ = t.lookup_or_assign("b", T0)  # evicts+demotes a
    t.set_expire(sb, T0 + 50_000)
    t.take_moves()
    sa2, ea = t.lookup_or_assign("a", T0)  # promote a; evict+demote b
    assert ea is True
    assert t.get_expire_bulk([sa2])[0] == T0 + 60_000  # a's OWN expiry
    # b survived into the back tier
    bkeys, _, bexp = t.back_entries()
    assert bkeys == ["b"] and bexp[0] == T0 + 50_000
    sb2, eb = t.lookup_or_assign("b", T0)
    assert eb is True


def test_back_capacity_one_degenerates_to_loss_not_corruption():
    t = native.NativeSlotTable(1)
    t.enable_back(1)
    sa, _ = t.lookup_or_assign("a", T0)
    t.set_expire(sa, T0 + 60_000)
    sb, _ = t.lookup_or_assign("b", T0)
    t.set_expire(sb, T0 + 50_000)
    t.take_moves()
    sa2, ea = t.lookup_or_assign("a", T0)  # promote a; b has nowhere to go
    assert ea is True
    assert t.get_expire_bulk([sa2])[0] == T0 + 60_000
    _, eb = t.lookup_or_assign("b", T0)
    assert eb is False  # b dropped (documented degenerate), not corrupted


def test_starved_fallback_never_serves_another_keys_row():
    """Round-4 review repro: with every front slot holding a pending
    promotion, the all-pending eviction fallback must CANCEL the
    promo (state loss) — demoting it would park the previous
    occupant's device row under the promoted key's name and later
    lookups would serve another key's counters."""
    t = native.NativeSlotTable(2)
    t.enable_back(8)
    for k in ("ka", "kb"):
        s, _ = t.lookup_or_assign(k, T0)
        t.set_expire(s, T0 + 60_000)
    for k in ("kc", "kd"):  # demote ka, kb
        s, _ = t.lookup_or_assign(k, T0)
        t.set_expire(s, T0 + 60_000)
    t.take_moves()
    # One window: promote ka and kb (both slots pending-promo), then a
    # miss forces the starved fallback.
    sa, ea = t.lookup_or_assign("ka", T0)
    sb, eb = t.lookup_or_assign("kb", T0)
    assert ea and eb
    se, ee = t.lookup_or_assign("ke", T0)
    assert ee is False
    pk, ps, pdst, ds, dd = t.take_moves()
    # the evicted promo was cancelled (src -1), and no demo record may
    # target a slot whose row never arrived
    live_promos = [(int(k), int(s), int(d))
                   for k, s, d in zip(pk, ps, pdst) if s >= 0]
    assert len(live_promos) == 1, (pk, ps, pdst)
    assert all(int(s) < 0 or int(dsl) != se for s, dsl in zip(ds, dd))
    # the evicted promoted key lost its state (loss, not corruption)
    _, e_again = t.lookup_or_assign(
        "ka" if se == sa else "kb", T0
    )
    assert e_again is False
