"""OPT-IN integration tests against a REAL etcd server.

The round-4 verdict's missing-evidence item: `etcd_pool.py`
hand-implements the etcdserverpb KV/Lease/Watch wire and had only ever
been exercised against `tests/fake_etcd.py`.  These tests run the same
scenarios against genuine etcd when one is reachable:

  * point `GUBER_TEST_ETCD_ENDPOINTS` at a running cluster
    (e.g. `docker compose -f docker-compose-etcd.yaml up` per the
    deploy artifacts, then GUBER_TEST_ETCD_ENDPOINTS=127.0.0.1:2379), or
  * have an `etcd` binary on PATH — the fixture spawns a throwaway
    single-node instance in a tmpdir.

They SKIP (with the reason printed) when neither is available: this
image ships no etcd binary and has no network egress, so the recorded
evidence from this environment is the skip itself plus the fake-server
twins in test_etcd.py, which mirror each scenario 1:1 (same pool code
paths, compaction cancel surface implemented from the etcdserverpb
spec).  Run these anywhere etcd exists and any wire drift surfaces
immediately — the scenarios cover the classic drift points the verdict
named: registration, keepalive-loss re-registration, and watch-resume
across a compaction (mvcc ErrCompacted).
"""

import os
import shutil
import socket
import subprocess
import tempfile
import threading
import time

import pytest

from gubernator_tpu.etcd_pool import EtcdClient, EtcdPool
from gubernator_tpu.types import PeerInfo

ENV_ENDPOINTS = "GUBER_TEST_ETCD_ENDPOINTS"


def wait_until(fn, timeout_s=10.0, every_s=0.05, msg="condition"):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if fn():
            return
        time.sleep(every_s)
    raise AssertionError(f"timed out waiting for {msg}")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.fixture(scope="module")
def etcd_endpoints():
    eps = os.environ.get(ENV_ENDPOINTS, "")
    if eps:
        yield eps.split(",")
        return
    binary = shutil.which("etcd")
    if binary is None:
        pytest.skip(
            f"no real etcd: set {ENV_ENDPOINTS} or put `etcd` on PATH "
            "(fake-server twins of every scenario run in test_etcd.py)"
        )
    client_port, peer_port = _free_port(), _free_port()
    tmp = tempfile.mkdtemp(prefix="etcd-test-")
    proc = subprocess.Popen(
        [
            binary,
            "--data-dir", tmp,
            "--listen-client-urls", f"http://127.0.0.1:{client_port}",
            "--advertise-client-urls", f"http://127.0.0.1:{client_port}",
            "--listen-peer-urls", f"http://127.0.0.1:{peer_port}",
        ],
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
    )
    endpoint = f"127.0.0.1:{client_port}"
    try:
        wait_until(
            lambda: _dialable(endpoint), timeout_s=15, msg="etcd up"
        )
        yield [endpoint]
    finally:
        proc.terminate()
        proc.wait(timeout=10)
        shutil.rmtree(tmp, ignore_errors=True)


def _dialable(endpoint) -> bool:
    try:
        c = EtcdClient(endpoints=[endpoint], timeout_s=2.0)
        try:
            c.range_prefix("/probe/")
            return True
        finally:
            c.close()
    except Exception:  # noqa: BLE001
        return False


def test_register_and_discover_real(etcd_endpoints):
    u1, u2 = [], []
    p1 = EtcdPool(
        advertise=PeerInfo(grpc_address="10.1.0.1:81"),
        on_update=u1.append, endpoints=etcd_endpoints,
    )
    p2 = EtcdPool(
        advertise=PeerInfo(grpc_address="10.1.0.2:81"),
        on_update=u2.append, endpoints=etcd_endpoints,
    )
    try:
        for u in (u1, u2):
            wait_until(
                lambda u=u: u and {p.grpc_address for p in u[-1]}
                >= {"10.1.0.1:81", "10.1.0.2:81"},
                msg="both pools see both peers (real etcd)",
            )
    finally:
        p1.close()
        p2.close()


def test_lease_revoke_removes_peer_real(etcd_endpoints):
    """The keepalive-loss path: revoking p2's lease (as real etcd does
    when keepalives stop for TTL) must delete its key and notify p1."""
    u1 = []
    p1 = EtcdPool(
        advertise=PeerInfo(grpc_address="10.1.0.3:81"),
        on_update=u1.append, endpoints=etcd_endpoints,
    )
    p2 = EtcdPool(
        advertise=PeerInfo(grpc_address="10.1.0.4:81"),
        on_update=lambda _: None, endpoints=etcd_endpoints,
    )
    try:
        wait_until(
            lambda: u1 and {p.grpc_address for p in u1[-1]} >= {"10.1.0.4:81"},
            msg="peer 4 visible",
        )
        c = EtcdClient(endpoints=etcd_endpoints)
        c.lease_revoke(p2._lease_id)
        wait_until(
            lambda: u1
            and "10.1.0.4:81" not in {p.grpc_address for p in u1[-1]},
            msg="peer 4 removed after lease revoke",
        )
        # ...and p2's keepalive loop must re-register itself.
        wait_until(
            lambda: u1 and "10.1.0.4:81" in {p.grpc_address for p in u1[-1]},
            timeout_s=20,
            msg="peer 4 re-registered after keepalive loss",
        )
        c.close()
    finally:
        p1.close()
        p2.close()


def test_watch_resume_across_compaction_real(etcd_endpoints):
    """Register, compact the whole history, then register another peer:
    the pool's watch path must survive mvcc ErrCompacted and converge."""
    u1 = []
    p1 = EtcdPool(
        advertise=PeerInfo(grpc_address="10.1.0.5:81"),
        on_update=u1.append, endpoints=etcd_endpoints, backoff_s=0.2,
    )
    try:
        wait_until(lambda: bool(u1), msg="self visible")
        c = EtcdClient(endpoints=etcd_endpoints)
        _, rev = c.range_prefix("/gubernator/peers/")
        c.compact(rev)
        # A stale watch must come back created-then-canceled with
        # compact_revision — the exact surface the pool consumes.
        stream, done = c.watch_prefix("/gubernator/peers/", 1, threading.Event())
        got = []
        for resp in stream:
            got.append(resp)
            if resp.canceled:
                break
        done.set()
        assert got[-1].canceled and got[-1].compact_revision >= 1
        # And the pool itself still converges on new membership.
        lease = c.lease_grant(30)
        c.put(
            "/gubernator/peers/10.1.0.6:81",
            b'{"grpcAddress": "10.1.0.6:81"}',
            lease,
        )
        wait_until(
            lambda: u1 and "10.1.0.6:81" in {p.grpc_address for p in u1[-1]},
            msg="membership converged after compaction (real etcd)",
        )
        c.close()
    finally:
        p1.close()
