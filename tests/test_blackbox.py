"""Incident black box (gubernator_tpu/blackbox.py) + replay.

Units for the ring byte budget, the tap classifier, trigger
coalescing / rate limiting / manual bypass, and bounded retention;
loader fuzz (truncation, bit flips, wrong versions, manifest damage
— every defect must reject the WHOLE bundle, never half-replay) with
scripts/blackbox_fsck.py exit codes; the GUBER_BLACKBOX=0 wire-byte
identity golden; and the acceptance oracle: a seeded FaultPlan
DUPLICATE on a live 2-daemon cluster trips forward_conservation,
auto-writes a bundle, and scripts/replay.py reproduces the same
violation from the bundle — deterministically, twice.
"""

from __future__ import annotations

import importlib.util
import json
import os
import time

import pytest

from gubernator_tpu import audit, blackbox, faults, tracing, wire
from gubernator_tpu.cluster import Cluster
from gubernator_tpu.config import BehaviorConfig
from gubernator_tpu.types import GetRateLimitsRequest, RateLimitRequest
from gubernator_tpu.utils.clock import Clock

SCRIPTS = os.path.join(os.path.dirname(__file__), "..", "scripts")


def _script(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(SCRIPTS, f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(autouse=True)
def _clean():
    tracing.reset()
    blackbox.force_disable(False)
    blackbox.set_enabled(True)
    yield
    tracing.reset()
    faults.uninstall()
    blackbox.force_disable(False)


def _cols(key: str = "k", hits: int = 3):
    return (["bb"], [key], [1], [0], [hits], [1000], [60_000])


def _peer_frame(key: str = "k", hits: int = 3) -> bytes:
    return wire.encode_columns_frame(_cols(key, hits))


# ---------------------------------------------------------------------
# Rings + taps
# ---------------------------------------------------------------------
def test_ring_byte_budget_evicts_oldest():
    ring = blackbox._WireRing(budget=4096)
    frames = [_peer_frame(f"key-{i:04d}") for i in range(200)]
    for i, f in enumerate(frames):
        ring.record((i, i, "in", "", 1, f))
    n, nbytes, total = ring.stats()
    assert total == 200          # lifetime count survives eviction
    assert n < 200               # budget forced evictions
    assert nbytes <= 4096
    kept = ring.freeze()
    # Evict-oldest: what remains is exactly the newest suffix, in order.
    assert [r[5] for r in kept] == frames[200 - n:]


def test_tap_classifies_by_kind_and_sniffs_magic(tmp_path):
    bb = blackbox.BlackBox(None, path=str(tmp_path), budget_mb=1)
    bb.tap("in", "", b'{"requests": []}')       # JSON body: ignored
    bb.tap("in", "", b"GU")                     # short junk: ignored
    bb.tap("in", "", wire.encode_ingress_frame(_cols()))
    bb.tap("out", "10.0.0.2:81", _peer_frame())
    bb.tap("out", "10.0.0.2:81",
           wire.encode_columns_frame(_cols(), kind=3))
    expect = {"public": 1, "peer": 1, "global": 1,
              "transfer": 0, "region": 0}
    got = {w: bb.rings[w].stats()[0] for w in blackbox.WIRES}
    assert got == expect
    rec = bb.rings["peer"].freeze()[0]
    assert (rec[2], rec[3], rec[4]) == ("out", "10.0.0.2:81", 1)


def test_force_disable_is_dark(tmp_path):
    bb = blackbox.BlackBox(None, path=str(tmp_path), budget_mb=1)
    blackbox.force_disable(True)
    assert not bb.live()
    bb.tap("in", "", wire.encode_ingress_frame(_cols()))
    bb.on_trigger("audit-violation", {})
    blackbox.force_disable(False)
    assert all(bb.rings[w].stats() == (0, 0, 0) for w in blackbox.WIRES)
    assert bb._pending == []


# ---------------------------------------------------------------------
# Triggers: coalescing, rate limit, manual bypass, retention
# ---------------------------------------------------------------------
def _wait_bundles(path: str, n: int = 1, timeout_s: float = 10.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        found = [os.path.join(path, e) for e in blackbox.list_bundles(path)]
        if len(found) >= n:
            return found
        time.sleep(0.02)
    raise AssertionError(
        f"no {n} bundles under {path} within {timeout_s}s: "
        f"{blackbox.list_bundles(path)}"
    )


def test_trigger_storm_coalesces_into_one_bundle(tmp_path):
    bb = blackbox.BlackBox(None, path=str(tmp_path), budget_mb=1)
    bb.coalesce_s = 0.05
    try:
        for i in range(5):
            bb.on_trigger("breaker-open", {"peer": f"p{i}"})
        bundles = _wait_bundles(str(tmp_path), 1)
        assert len(bundles) == 1
        manifest = json.loads(
            (tmp_path / os.path.basename(bundles[0]) / "manifest.json")
            .read_bytes()
        )
        assert len(manifest["triggers"]) == 5
        assert {t["kind"] for t in manifest["triggers"]} == {"breaker-open"}
    finally:
        bb.close()


def test_rate_limit_suppresses_and_manual_bypasses(tmp_path):
    bb = blackbox.BlackBox(None, path=str(tmp_path), budget_mb=1)
    bb.coalesce_s = 0.02
    bb.min_interval_s = 3600.0
    try:
        bb.on_trigger("audit-violation", {"invariant": "x"})
        _wait_bundles(str(tmp_path), 1)
        # Inside the rate-limit window: triggers are counted, not
        # written.
        bb.on_trigger("audit-violation", {"invariant": "x"})
        deadline = time.monotonic() + 5.0
        while (bb.snapshot()["suppressedTriggers"] == 0
               and time.monotonic() < deadline):
            time.sleep(0.02)
        assert len(blackbox.list_bundles(str(tmp_path))) == 1
        assert bb.snapshot()["suppressedTriggers"] == 1
        # The operator bypass: a manual trigger writes despite the
        # window and carries the suppressed count into the manifest.
        bb.trigger_manual("on purpose")
        bundles = _wait_bundles(str(tmp_path), 2)
        manifest = json.loads(
            (tmp_path / os.path.basename(bundles[-1]) / "manifest.json")
            .read_bytes()
        )
        assert manifest["suppressedTriggers"] >= 1
        assert manifest["triggers"][-1]["kind"] == "manual"
    finally:
        bb.close()


def test_retention_prunes_oldest(tmp_path):
    bb = blackbox.BlackBox(None, path=str(tmp_path), budget_mb=1, retain=2)
    try:
        names = [
            os.path.basename(bb.write_bundle([{"kind": "manual"}]))
            for _ in range(4)
        ]
        kept = [os.path.basename(p)
                for p in blackbox.list_bundles(str(tmp_path))]
        assert kept == names[-2:]
    finally:
        bb.close()


# ---------------------------------------------------------------------
# Loader fuzz: any defect rejects the whole bundle (and fsck agrees)
# ---------------------------------------------------------------------
def _good_bundle(tmp_path) -> str:
    bb = blackbox.BlackBox(None, path=str(tmp_path), budget_mb=1)
    bb.tap("in", "", wire.encode_ingress_frame(_cols("a")))
    bb.tap("out", "p:1", _peer_frame("b"))
    bb.tap("out", "p:1", _peer_frame("c", hits=5))
    path = bb.write_bundle([{"kind": "manual", "wallNs": 1, "monoNs": 1,
                             "fields": {}}])
    bb.close()
    return path


def _flip_byte(path: str, offset: int) -> None:
    with open(path, "r+b") as f:
        f.seek(offset)
        b = f.read(1)
        f.seek(offset)
        f.write(bytes([b[0] ^ 0xFF]))


CORRUPTIONS = [
    ("gfl-truncated", lambda d: open(
        os.path.join(d, "wire-peer.gfl"), "r+b").truncate(
        os.path.getsize(os.path.join(d, "wire-peer.gfl")) - 3)),
    ("gfl-bit-flip", lambda d: _flip_byte(
        os.path.join(d, "wire-peer.gfl"),
        os.path.getsize(os.path.join(d, "wire-peer.gfl")) - 5)),
    ("gfl-bad-magic", lambda d: _flip_byte(
        os.path.join(d, "wire-public.gfl"), 0)),
    ("file-missing", lambda d: os.unlink(
        os.path.join(d, "wire-global.gfl"))),
    ("manifest-garbage", lambda d: open(
        os.path.join(d, "manifest.json"), "wb").write(b"not json")),
    ("manifest-wrong-version", lambda d: _rewrite_manifest(
        d, lambda m: m.__setitem__("version", 999))),
    ("manifest-wrong-format", lambda d: _rewrite_manifest(
        d, lambda m: m.__setitem__("format", "something-else"))),
    ("manifest-bad-crc", lambda d: _rewrite_manifest(
        d, lambda m: m["files"]["wire-peer.gfl"].__setitem__("crc32", 1))),
]


def _rewrite_manifest(bundle_dir: str, mutate) -> None:
    p = os.path.join(bundle_dir, "manifest.json")
    with open(p) as f:
        m = json.load(f)
    mutate(m)
    with open(p, "w") as f:
        json.dump(m, f)


@pytest.mark.parametrize("name,corrupt", CORRUPTIONS,
                         ids=[c[0] for c in CORRUPTIONS])
def test_corrupt_bundle_never_half_loads(tmp_path, name, corrupt):
    bundle = _good_bundle(tmp_path)
    assert blackbox.load_bundle(bundle).merged_records()
    corrupt(bundle)
    with pytest.raises(blackbox.BundleError):
        blackbox.load_bundle(bundle)
    # replay refuses before driving a single frame...
    replay = _script("replay")
    with pytest.raises(blackbox.BundleError):
        replay.replay_bundle(bundle)
    # ...and the offline verifier exits 1 on exactly the same defect.
    assert _script("blackbox_fsck").main([bundle]) == 1


def test_fsck_ok_and_usage_exits(tmp_path, capsys):
    bundle = _good_bundle(tmp_path)
    fsck = _script("blackbox_fsck")
    assert fsck.main([bundle, "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["ok"] is True
    assert doc["frames"]["peer"] == 2 and doc["frames"]["public"] == 1
    assert fsck.main([str(tmp_path / "nope")]) == 2


def test_incident_collect_stitches_and_rejects(tmp_path, capsys):
    a = _good_bundle(tmp_path / "a")
    b = _good_bundle(tmp_path / "b")
    ic = _script("incident_collect")
    assert ic.main(["--scan", str(tmp_path), "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert len(doc["bundles"]) == 2 and not doc["rejected"]
    assert len(doc["frames"]) == 6  # 3 per bundle, one merged timeline
    assert [t["kind"] for t in doc["triggers"]] == ["manual", "manual"]
    _flip_byte(os.path.join(b, "wire-peer.gfl"), 20)
    assert ic.main([a, b]) == 1
    capsys.readouterr()


def test_cluster_status_blackbox_column():
    cs = _script("cluster_status")
    assert cs.COLUMNS[-1] == "blackbox"
    row = cs.summarize("a:1", {"blackbox": {
        "enabled": True, "bundles": 2, "bundlesOnDisk": 3,
        "lastTriggerAgeS": 31.4,
    }})
    assert row["blackbox"] == "2/3 31s ago"
    assert cs.summarize("a:1", {})["blackbox"] == "-"


# ---------------------------------------------------------------------
# GUBER_BLACKBOX=0 golden: the wire is byte-identical either way
# ---------------------------------------------------------------------
def _mini_service(blackbox_dir: str = ""):
    from gubernator_tpu.service import ServiceConfig, V1Service

    clock = Clock()
    clock.freeze(1_573_430_400_000)
    behaviors = BehaviorConfig(audit=False, snapshot_interval_s=0.0)
    svc = V1Service(ServiceConfig(
        cache_size=1024,
        behaviors=behaviors,
        advertise_address="bbtest:0",
        clock=clock,
        blackbox_dir=blackbox_dir,
    ))
    svc.set_peers([])
    return svc


def test_disabled_wire_bytes_identical_and_rings_dark():
    from gubernator_tpu import gateway

    frames = [wire.encode_ingress_frame(_cols(f"gk{i}", hits=2))
              for i in range(4)]

    def drive(svc):
        out = []
        for f in frames:
            status, _ct, body = gateway.handle_request(
                svc, "POST", "/v1/GetRateLimits", f
            )
            assert status == 200
            out.append(bytes(body))
        return out

    svc_on = _mini_service()
    try:
        on_bodies = drive(svc_on)
        assert svc_on.blackbox.rings["public"].stats()[0] == 8  # req+resp
    finally:
        svc_on.close()
    blackbox.force_disable(True)
    svc_off = _mini_service()
    try:
        off_bodies = drive(svc_off)
        assert all(
            svc_off.blackbox.rings[w].stats() == (0, 0, 0)
            for w in blackbox.WIRES
        )
    finally:
        svc_off.close()
        blackbox.force_disable(False)
    assert on_bodies == off_bodies


# ---------------------------------------------------------------------
# /debug/incident + debug surfaces
# ---------------------------------------------------------------------
def test_debug_incident_endpoint_and_surfaces(tmp_path):
    from gubernator_tpu import gateway

    svc = _mini_service(blackbox_dir=str(tmp_path))
    try:
        svc.blackbox.coalesce_s = 0.02
        status, _ct, body = gateway.handle_request(
            svc, "POST", "/debug/incident", b'{"reason": "drill"}'
        )
        assert status == 202, body
        bundles = _wait_bundles(str(tmp_path), 1)
        manifest = json.loads(
            open(os.path.join(bundles[0], "manifest.json"), "rb").read()
        )
        assert manifest["triggers"][0]["kind"] == "manual"
        assert manifest["service"]["advertiseAddress"] == "bbtest:0"
        # debug_status carries the blackbox section cluster_status reads.
        snap = svc.debug_status()["blackbox"]
        assert snap["enabled"] and snap["bundles"] >= 1
        assert snap["ringBudgetBytes"] > 0
        # /metrics: the gubernator_blackbox_* families render.
        status, _ct, metrics_body = gateway.handle_request(
            svc, "GET", "/metrics", b""
        )
        text = metrics_body.decode()
        for family in (
            "gubernator_blackbox_frames_total",
            "gubernator_blackbox_ring_bytes",
            "gubernator_blackbox_bundles_total",
            "gubernator_blackbox_last_trigger_age_seconds",
        ):
            assert family in text, family
        # Disabled process-wide: the endpoint refuses (403).
        blackbox.force_disable(True)
        status, _ct, body = gateway.handle_request(
            svc, "POST", "/debug/incident", b""
        )
        assert status == 403
        blackbox.force_disable(False)
    finally:
        svc.close()
    # No bundle dir configured: 409, bundles cannot be written.
    svc2 = _mini_service()
    try:
        status, _ct, body = gateway.handle_request(
            svc2, "POST", "/debug/incident", b""
        )
        assert status == 409
    finally:
        svc2.close()


# ---------------------------------------------------------------------
# The acceptance oracle: capture -> bundle -> deterministic replay
# ---------------------------------------------------------------------
@pytest.mark.chaos
@pytest.mark.slow  # live 2-daemon cluster + two full replays: `make chaos` runs it
def test_seeded_incident_bundle_replays_deterministically(tmp_path):
    """FaultPlan DUPLICATE double-delivers the forward wire on a live
    2-daemon cluster; the audit trips forward_conservation, whose
    auto-dump freezes the rings into a bundle.  scripts/replay.py then
    re-drives the captured frames against a fresh daemon and must
    reproduce the SAME violation — twice, with byte-identical
    reports."""
    cl = Cluster().start(2)
    plan = faults.FaultPlan(seed=11)
    plan.duplicate(op="GetPeerRateLimits")
    try:
        for i, d in enumerate(cl.daemons):
            d.service.blackbox.path = str(tmp_path / f"d{i}")
            d.service.blackbox.coalesce_s = 0.05
        svc0 = cl.daemons[0].service
        auditor = svc0.auditor
        auditor.arm()
        auditor.check_now()  # seed pass (see Auditor.arm)
        faults.install(plan)
        me = svc0.advertise_address
        import hashlib

        cand = [hashlib.md5(str(i).encode()).hexdigest() for i in range(64)]
        reqs = [
            RateLimitRequest(
                name="bb", unique_key=uk, hits=3, limit=1000,
                duration=60_000,
            )
            for uk in cand
            if svc0.get_peer(
                RateLimitRequest(name="bb", unique_key=uk).hash_key()
            ).info.grpc_address != me
        ]
        assert reqs, "no remotely-owned keys in the probe range"
        svc0.get_rate_limits(GetRateLimitsRequest(requests=reqs))
        found = auditor.check_now()
        assert "forward_conservation" in [v["invariant"] for v in found]
        faults.uninstall()
        # The violation's auto-dump must have frozen a bundle.
        bundles = _wait_bundles(str(tmp_path / "d0"), 1)
        bundle = bundles[-1]
        assert _script("blackbox_fsck").main([bundle]) == 0
        manifest = json.loads(
            open(os.path.join(bundle, "manifest.json"), "rb").read()
        )
        assert "audit-violation" in [
            t["kind"] for t in manifest["triggers"]
        ]
        # The duplicated delivery is IN the capture: at least one
        # byte-identical consecutive outbound pair on the peer wire.
        peer_out = [
            r[5] for r in blackbox.load_bundle(bundle).frames["peer"]
            if r[2] == "out"
        ]
        assert any(
            a == b for a, b in zip(peer_out, peer_out[1:])
        ), "no duplicated forward frame captured"
    finally:
        faults.uninstall()
        cl.stop()

    replay = _script("replay")
    first = replay.replay_bundle(bundle)
    second = replay.replay_bundle(bundle)
    assert json.dumps(first, sort_keys=True) == json.dumps(
        second, sort_keys=True
    )
    assert first["violations"].get("forward_conservation", 0) >= 1
    assert first["bundleViolations"].get("forward_conservation", 0) >= 1
    assert first["reproducesBundleViolations"] is True
    # --to-test: the emitted regression file is a valid pytest module
    # pinned to this bundle.
    out = tmp_path / "test_incident_regression.py"
    replay.emit_test(bundle, str(out))
    src = out.read_text()
    compile(src, str(out), "exec")
    assert "def test_" in src and os.path.basename(bundle) in src
