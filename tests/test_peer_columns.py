"""Columnar peer-hop tests (wire.py "columnar peer hop").

Covers the four acceptance legs of the zero-dataclass forwarded path:

* wire goldens — the binary frame's byte layout is pinned (a silent
  layout change would break rolling upgrades mid-flight);
* mixed-version interop — a columnar-speaking daemon and a daemon
  running with GUBER_PEER_COLUMNS=0 (the pre-columns wire behavior)
  forward to each other and every response matches the reference
  oracle;
* fault semantics — the PR-1 breaker/FaultPlan contract holds
  unchanged on the columnar send path (same op name, same
  degraded-local-eval fallback);
* the adaptive window and demand-sized drainer that pace the hop.
"""

import threading
import time
from concurrent.futures import Future

import numpy as np
import pytest

from gubernator_tpu import wire
from gubernator_tpu.cluster import fast_test_behaviors
from gubernator_tpu.config import BehaviorConfig, DaemonConfig
from gubernator_tpu.daemon import Daemon
from gubernator_tpu.faults import FaultPlan
from gubernator_tpu.peer_client import PeerClient, PeerError, is_circuit_open
from gubernator_tpu.service import ColumnarResult
from gubernator_tpu.types import (
    Behavior,
    GetRateLimitsRequest,
    PeerInfo,
    RateLimitRequest,
    RateLimitResponse,
    SECOND,
)
from gubernator_tpu.utils.batch_window import BatchWindow
from gubernator_tpu.utils.clock import Clock

from . import oracle

T0 = 1_573_430_430_000


def _cols(names, uks, algo=None, beh=None, hits=None, limit=None, dur=None):
    n = len(names)
    return (
        names,
        uks,
        np.asarray(algo if algo is not None else [0] * n, np.int32),
        np.asarray(beh if beh is not None else [0] * n, np.int32),
        np.asarray(hits if hits is not None else [1] * n, np.int64),
        np.asarray(limit if limit is not None else [10] * n, np.int64),
        np.asarray(dur if dur is not None else [9 * SECOND] * n, np.int64),
    )


# ----------------------------------------------------------------------
# Wire goldens: the binary frame layout is a wire contract
# ----------------------------------------------------------------------
def test_request_frame_golden():
    frame = wire.encode_columns_frame(
        _cols(["a"], ["b"], algo=[1], beh=[0], hits=[1], limit=[2], dur=[3])
    )
    expected = (
        b"GUBC"                      # magic
        + bytes([1, 1])              # version 1, kind 1 (request)
        + (1).to_bytes(4, "little")  # n = 1
        # names column: blob_len, offsets[2], blob
        + (1).to_bytes(4, "little")
        + (0).to_bytes(4, "little") + (1).to_bytes(4, "little")
        + b"a"
        # unique_keys column
        + (1).to_bytes(4, "little")
        + (0).to_bytes(4, "little") + (1).to_bytes(4, "little")
        + b"b"
        + (1).to_bytes(4, "little", signed=True)   # algorithm i32
        + (0).to_bytes(4, "little", signed=True)   # behavior i32
        + (1).to_bytes(8, "little", signed=True)   # hits i64
        + (2).to_bytes(8, "little", signed=True)   # limit i64
        + (3).to_bytes(8, "little", signed=True)   # duration i64
    )
    assert frame == expected
    cols = wire.decode_columns_frame(frame)
    assert cols.names == ["a"] and cols.unique_keys == ["b"]
    assert int(cols.algorithm[0]) == 1 and int(cols.duration[0]) == 3


def test_response_frame_golden():
    r = ColumnarResult.empty(1)
    r.status[0], r.limit[0], r.remaining[0], r.reset_time[0] = 1, 10, 9, 1000
    frame = wire.encode_result_frame(r)
    expected = (
        b"GUBC"
        + bytes([1, 2])                # version 1, kind 2 (response)
        + (1).to_bytes(4, "little")    # n = 1
        + (1).to_bytes(4, "little", signed=True)      # status i32
        + (10).to_bytes(8, "little", signed=True)     # limit i64
        + (9).to_bytes(8, "little", signed=True)      # remaining i64
        + (1000).to_bytes(8, "little", signed=True)   # reset_time i64
        + (0).to_bytes(4, "little")    # n_overrides = 0
    )
    assert frame == expected
    rc = wire.decode_result_frame(frame)
    assert (int(rc.status[0]), int(rc.remaining[0])) == (1, 9)


def test_frame_roundtrip_unicode_and_overrides():
    cols = _cols(["náme", ""], ["k€y", "k2"], beh=[0, int(Behavior.GLOBAL)])
    got = wire.decode_columns_frame(wire.encode_columns_frame(cols))
    assert got.names == ["náme", ""]
    assert got.unique_keys == ["k€y", "k2"]
    r = ColumnarResult.empty(2)
    r.overrides[1] = RateLimitResponse(
        error="boom", metadata={"owner": "1.2.3.4:81"}
    )
    rc = wire.decode_result_frame(wire.encode_result_frame(r))
    assert rc.overrides[1].error == "boom"
    assert rc.overrides[1].metadata == {"owner": "1.2.3.4:81"}
    assert 0 not in rc.overrides


def test_frame_rejects_foreign_bytes():
    with pytest.raises(ValueError):
        wire.decode_columns_frame(b'{"requests": []}')
    assert not wire.is_columns_frame(b'{"requests": []}')
    # A response frame is not a request frame.
    r = ColumnarResult.empty(0)
    with pytest.raises(ValueError):
        wire.decode_columns_frame(wire.encode_result_frame(r))


# ----------------------------------------------------------------------
# Mixed-version interop: columnar daemon <-> pre-columns daemon
# ----------------------------------------------------------------------
@pytest.fixture(scope="module")
def mixed_cluster():
    """Daemon A speaks columns; daemon B runs GUBER_PEER_COLUMNS=0 —
    the exact wire behavior of a pre-columns build (no gRPC columns
    method, no frame sniff, classic sender)."""
    clock = Clock()
    clock.freeze(T0)
    daemons = []
    for peer_columns in (True, False):
        behaviors = fast_test_behaviors()
        behaviors.peer_columns = peer_columns
        behaviors.global_sync_wait_s = 3600.0
        behaviors.multi_region_sync_wait_s = 3600.0
        d = Daemon(
            DaemonConfig(
                listen_address="127.0.0.1:0",
                grpc_listen_address="127.0.0.1:0",
                cache_size=4096,
                global_cache_size=256,
                behaviors=behaviors,
                peer_discovery_type="static",
            ),
            clock=clock,
        ).start()
        daemons.append(d)
    peers = [d.peer_info for d in daemons]
    for d in daemons:
        d.set_peers(peers)
    yield daemons, clock
    for d in daemons:
        d.close()


def _forwarded_keys(entry, name, want=6):
    """Keys whose owner is NOT `entry` (so entry must forward)."""
    out = []
    i = 0
    while len(out) < want:
        key = f"k{i}"
        if not entry.service.get_peer(f"{name}_{key}").info.is_owner:
            out.append(key)
        i += 1
    return out


def _check_against_oracle(entry, name, keys, clock, hits_each=3, limit=2):
    """Drive `hits_each` single-hit rounds through `entry` for every
    key and compare each response to the reference oracle (remaining
    AND the UNDER->OVER_LIMIT transition at this small limit)."""
    cache = oracle.OracleCache()
    for _ in range(hits_each):
        reqs = [
            RateLimitRequest(
                name=name, unique_key=k, hits=1, limit=limit,
                duration=9 * SECOND,
            )
            for k in keys
        ]
        got = entry.service.get_rate_limits(
            GetRateLimitsRequest(requests=reqs)
        ).responses
        for k, r, req in zip(keys, got, reqs):
            assert not r.error, (k, r.error)
            expect = oracle.apply(cache, req, clock.now_ms())
            assert r.status == expect.status, (k, r, expect)
            assert r.remaining == expect.remaining, (k, r, expect)
            assert r.metadata.get("owner"), (k, r.metadata)


def test_mixed_version_interop(mixed_cluster):
    daemons, clock = mixed_cluster
    columnar, classic = daemons

    # columnar -> classic peer: the probe gets UNIMPLEMENTED, the
    # client falls back to the per-request encoding and every response
    # is still oracle-correct.
    keys = _forwarded_keys(columnar, "mixa")
    _check_against_oracle(columnar, "mixa", keys, clock)
    for p in columnar.service.get_peer_list():
        if not p.info.is_owner:
            assert p._columnar is False  # negotiated down, remembered

    # classic -> columnar peer: an old sender never probes; the new
    # daemon serves the classic encoding unchanged.
    keys = _forwarded_keys(classic, "mixb")
    _check_against_oracle(classic, "mixb", keys, clock)
    for p in classic.service.get_peer_list():
        if not p.info.is_owner:
            assert p._columnar is False  # config opt-out: never probed

    # The benign negotiation probe must not have poisoned health.
    hc = columnar.service.health_check()
    assert hc.status == "healthy", hc.message


def test_columnar_pair_negotiates_columns(mixed_cluster):
    """Self-check for the fixture above: against a columns-speaking
    peer the probe LOCKS IN columnar (otherwise the interop test would
    silently test classic<->classic)."""
    daemons, clock = mixed_cluster
    columnar, classic = daemons
    # classic's gateway serves frames? No — but columnar's does; use a
    # fresh HTTP-transport client against the COLUMNAR daemon.
    client = PeerClient(
        PeerInfo(
            grpc_address=columnar.peer_info.grpc_address,
            http_address=columnar.peer_info.http_address,
        ),
        fast_test_behaviors(),
        transport="http",
    )
    try:
        fut = client.forward_columns(_cols(["negot"], ["h1"]))
        rc, lo, hi = fut.result(timeout=10)
        assert (lo, hi) == (0, 1)
        assert int(rc.remaining[lo]) == 9
        assert client._columnar is True
        assert client.get_last_err() == []
    finally:
        client.shutdown()
    # And over gRPC (the default transport).
    client = PeerClient(
        PeerInfo(grpc_address=columnar.peer_info.grpc_address),
        fast_test_behaviors(),
    )
    try:
        rc = client.send_columns_direct(_cols(["negot"], ["g1"]))
        assert rc.n == 1 and int(rc.remaining[0]) == 9
        assert client._columnar is True
    finally:
        client.shutdown()


def test_http_fallback_to_json_peer(mixed_cluster):
    """HTTP transport against the pre-columns daemon: the frame probe
    gets a 400, the client falls back to JSON inside the same guarded
    call, the answer is correct, and neither health nor the breaker
    saw a failure."""
    daemons, _clock = mixed_cluster
    _columnar, classic = daemons
    client = PeerClient(
        PeerInfo(
            grpc_address=classic.peer_info.grpc_address,
            http_address=classic.peer_info.http_address,
        ),
        fast_test_behaviors(),
        transport="http",
    )
    try:
        fut = client.forward_columns(_cols(["httpfall"], ["k1"]))
        rc, lo, _hi = fut.result(timeout=10)
        assert int(rc.remaining[lo]) == 9
        assert client._columnar is False
        assert client.get_last_err() == []  # benign probe, not an error
        assert client.breaker.state == "closed"
        # Second call goes straight to JSON (no re-probe).
        rc2, lo2, _ = client.forward_columns(
            _cols(["httpfall"], ["k1"])
        ).result(timeout=10)
        assert int(rc2.remaining[lo2]) == 8
    finally:
        client.shutdown()


def test_downgrade_after_confirmed_columnar(mixed_cluster):
    """A peer that STOPS speaking columns (in-place downgrade after the
    client already confirmed columnar) answers 4xx to the frame; the
    client must downgrade and resend classic — re-chunked to the
    classic MAX_BATCH_SIZE cap, since the failed chunk was sized for a
    columns speaker — instead of erroring a healthy peer's batches."""
    daemons, _clock = mixed_cluster
    columnar, _classic = daemons
    client = PeerClient(
        PeerInfo(
            grpc_address=columnar.peer_info.grpc_address,
            http_address=columnar.peer_info.http_address,
        ),
        BehaviorConfig(batch_wait_s=0.05, batch_timeout_s=15.0),
        transport="http",
    )
    try:
        rc, lo, _hi = client.forward_columns(
            _cols(["downg"], ["k0"], limit=[1_000_000])
        ).result(timeout=10)
        assert client._columnar is True
        columnar.service.conf.behaviors.peer_columns = False  # live downgrade
        try:
            futs = [
                client.forward_columns(
                    _cols(
                        ["downg"] * 600,
                        [f"{part}:{i}" for i in range(600)],
                        limit=[1_000_000] * 600,
                    )
                )
                for part in ("d1", "d2")  # coalesce to 1200 > classic cap
            ]
            for fut in futs:
                rc, lo, hi = fut.result(timeout=20)
                assert hi - lo == 600
                assert (rc.remaining[lo:hi] == 999_999).all()
            assert client._columnar is False
            assert client.breaker.state == "closed"
        finally:
            columnar.service.conf.behaviors.peer_columns = True
    finally:
        client.shutdown()


def test_malformed_frame_answers_400(mixed_cluster):
    """A truncated columns frame is the sender's fault: the receiver
    answers 400 (so the HTTP negotiation can tell 'old peer' / 'bad
    payload' apart from a server fault), never a 500."""
    import http.client

    daemons, _clock = mixed_cluster
    columnar, _classic = daemons
    frame = wire.encode_columns_frame(_cols(["a", "b"], ["x", "y"]))
    host, _, port = columnar.gateway.address.partition(":")
    for body in (frame[:-7], frame[:12]):
        conn = http.client.HTTPConnection(host, int(port), timeout=10)
        try:
            conn.request(
                "POST", "/v1/peer.GetPeerRateLimits", body=body,
                headers={"Content-Type": wire.COLUMNS_CONTENT_TYPE},
            )
            r = conn.getresponse()
            payload = r.read()
            assert r.status == 400, (r.status, payload)
        finally:
            conn.close()


def test_oversize_coalesce_chunks_at_cap(mixed_cluster):
    """Two sub-batches that together exceed MAX_BATCH_SIZE coalesce in
    the window but are chunked into <=1000-lane RPCs (the receiver
    enforces the cap hard)."""
    daemons, _clock = mixed_cluster
    columnar, _classic = daemons
    client = PeerClient(
        PeerInfo(grpc_address=columnar.peer_info.grpc_address),
        BehaviorConfig(batch_wait_s=0.05, batch_timeout_s=10.0),
    )
    try:
        subs = []
        for part in ("p1", "p2"):
            n = 600
            subs.append(
                client.forward_columns(
                    _cols(
                        ["chunk"] * n,
                        [f"{part}:{i}" for i in range(n)],
                        limit=[1_000_000] * n,
                    )
                )
            )
        for fut in subs:
            rc, lo, hi = fut.result(timeout=15)
            assert hi - lo == 600
            assert rc.n <= 1000  # each RPC respected the cap
            seg = rc.remaining[lo:hi]
            assert (seg == 999_999).all()
    finally:
        client.shutdown()


# ----------------------------------------------------------------------
# Chaos: breaker + FaultPlan semantics on the columnar send path
# ----------------------------------------------------------------------
@pytest.mark.chaos
def test_faultplan_breaker_on_columnar_send():
    """The PR-1 contract, unchanged on the columnar path: rules match
    the SAME op name (GetPeerRateLimits), consecutive injected failures
    open the breaker, and an open breaker fast-fails without touching
    the wire (call counter frozen)."""
    plan = FaultPlan(seed=7)
    addr = "127.0.0.1:9"  # never dialed: every send dies in the plan
    plan.partition(addr, op="GetPeerRateLimits")
    behaviors = BehaviorConfig(
        batch_wait_s=0.001, batch_timeout_s=2.0,
        circuit_threshold=3, circuit_open_interval_s=60.0,
    )
    client = PeerClient(PeerInfo(grpc_address=addr), behaviors, faults=plan)
    try:
        for i in range(3):
            fut = client.forward_columns(_cols([f"n{i}"], ["k"]))
            with pytest.raises(PeerError) as ei:
                fut.result(timeout=5)
            assert ei.value.not_ready, "injected ERROR must look connection-shaped"
        assert client.breaker.state == "open"
        assert plan.calls(addr, "GetPeerRateLimits") == 3
        # Open circuit: fail fast, wire untouched.
        fut = client.forward_columns(_cols(["n3"], ["k"]))
        with pytest.raises(PeerError) as ei:
            fut.result(timeout=5)
        assert is_circuit_open(ei.value)
        assert plan.calls(addr, "GetPeerRateLimits") == 3
    finally:
        client.shutdown(timeout_s=1.0)


@pytest.mark.chaos
def test_faultplan_drop_is_not_retryable_on_columnar_send():
    """DROP (timeout-shaped) faults keep not_ready=False through the
    columnar path — the caller must never treat them as safely
    retryable (the DEADLINE_EXCEEDED caveat)."""
    plan = FaultPlan(seed=11)
    addr = "127.0.0.1:9"
    plan.drop_nth(addr, 1, op="GetPeerRateLimits")
    client = PeerClient(
        PeerInfo(grpc_address=addr),
        BehaviorConfig(batch_wait_s=0.001, batch_timeout_s=2.0),
        faults=plan,
    )
    try:
        fut = client.forward_columns(_cols(["d"], ["k"]))
        with pytest.raises(PeerError) as ei:
            fut.result(timeout=5)
        assert not ei.value.not_ready
        assert not is_circuit_open(ei.value)
    finally:
        client.shutdown(timeout_s=1.0)


def test_degraded_local_eval_on_columnar_group(mixed_cluster):
    """An owner whose breaker is OPEN degrades the whole forwarded
    columnar group to local evaluation (metadata degraded=true), same
    as the PR-1 dataclass path."""
    daemons, _clock = mixed_cluster
    entry, _ = daemons
    keys = _forwarded_keys(entry, "degr", want=3)
    peer = entry.service.get_peer(f"degr_{keys[0]}")
    # Force the breaker open without network churn.
    for _ in range(peer.behaviors.circuit_threshold):
        peer.breaker.record_failure()
    assert peer.breaker.state == "open"
    try:
        reqs = [
            RateLimitRequest(
                name="degr", unique_key=k, hits=1, limit=10,
                duration=9 * SECOND,
            )
            for k in keys
        ]
        got = entry.service.get_rate_limits(
            GetRateLimitsRequest(requests=reqs)
        ).responses
        for k, r in zip(keys, got):
            assert not r.error, (k, r.error)
            assert r.metadata.get("degraded") == "true", (k, r.metadata)
    finally:
        peer.breaker.record_success()  # close it for later tests


# ----------------------------------------------------------------------
# Adaptive window + demand-sized drainer
# ----------------------------------------------------------------------
def test_adaptive_window_shrinks_under_load():
    flushed = []
    w = BatchWindow(
        flushed.append, wait_s=0.05, limit=100, adaptive=True,
        weigh=lambda item: item,
    )
    try:
        assert w.effective_wait_s() == 0.05  # no rate estimate yet
        # A fast burst: 100-lane submissions fill the limit instantly,
        # so the measured arrival rate is far above limit/wait_s and
        # the next window must shrink below the configured wait.
        for _ in range(6):
            w.submit(100)
        deadline = time.monotonic() + 5
        while len(flushed) < 6 and time.monotonic() < deadline:
            time.sleep(0.001)
        assert sum(len(b) for b in flushed) >= 6
        assert w.effective_wait_s() < 0.05
        assert w.effective_wait_s() >= 0.0
    finally:
        w.stop(timeout_s=2.0)


def test_adaptive_window_keeps_full_wait_for_trickle():
    flushed = []
    w = BatchWindow(
        flushed.append, wait_s=0.01, limit=1000, adaptive=True,
        weigh=lambda item: item,
    )
    try:
        # One tiny item per window: measured rate ~ 1/wait << limit/wait,
        # so the effective wait stays pinned at the configured maximum.
        for _ in range(3):
            w.submit(1)
            time.sleep(0.03)
        assert w.effective_wait_s() == 0.01
    finally:
        w.stop(timeout_s=2.0)


def test_drainer_scales_with_dispatch_depth():
    from gubernator_tpu.service import _HandleDrainer

    class _Handle:
        def __init__(self):
            self.ev = threading.Event()
            self.started = threading.Event()

        def result(self):
            self.started.set()
            self.ev.wait(timeout=10)
            return "done"

    d = _HandleDrainer()
    d.start()
    assert len(d._threads) == d.MIN_THREADS
    handles = [_Handle() for _ in range(8)]
    done: list = []
    try:
        for h in handles:
            d.register(h, lambda v, e: done.append((v, e)))
        # All 8 readbacks must end up in-flight CONCURRENTLY (none
        # resolves until ev fires): the pool grew past MIN_THREADS to
        # match the dispatch depth instead of queueing behind a fixed
        # width.
        for h in handles:
            assert h.started.wait(timeout=5), "readback queued behind pool"
        assert len(d._threads) >= 8
        assert len(d._threads) <= d.MAX_THREADS
        for h in handles:
            h.ev.set()
        deadline = time.monotonic() + 5
        while len(done) < 8 and time.monotonic() < deadline:
            time.sleep(0.001)
        assert len(done) == 8
        assert all(v == "done" and e is None for v, e in done)
    finally:
        d.stop(timeout_s=2.0)
