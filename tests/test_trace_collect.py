"""scripts/trace_collect.py — cross-daemon trace stitching.

The acceptance case: ONE trace id, reassembled into a single tree
spanning two LIVE daemons (ingress daemon + owner daemon), with the
peer hop visible — the ingress daemon's `peer.rpc` client span and the
owner daemon's batch/dispatch spans all stitched under the ingress
root via parent/link edges.  Plus unit tests of the stitcher's edge
rules and the incremental `since` cursor.
"""

from __future__ import annotations

import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "scripts"))

import trace_collect  # noqa: E402

from gubernator_tpu import tracing  # noqa: E402
from gubernator_tpu.client import V1Client  # noqa: E402
from gubernator_tpu.cluster import Cluster, fast_test_behaviors  # noqa: E402
from gubernator_tpu.types import (  # noqa: E402
    GetRateLimitsRequest,
    RateLimitRequest,
)


@pytest.fixture(autouse=True)
def _clean_rings():
    tracing.reset()
    prev = tracing.sample_rate()
    yield
    tracing.set_sample_rate(prev)
    tracing.reset()


# ---------------------------------------------------------------------
# Stitcher unit rules
# ---------------------------------------------------------------------
def _span(name, trace, span, daemon, parent="", links=(), wall=0, dur=0):
    return {
        "name": name, "trace_id": trace, "span_id": span,
        "parent_id": parent, "daemon": daemon, "wall_ns": wall,
        "dur_ns": dur, "start_ns": 0, "thread": "t", "attrs": {},
        "links": [
            {"trace_id": t, "span_id": s} for t, s in links
        ],
    }


def test_stitch_parent_and_link_edges():
    t = "a" * 32
    spans = [
        _span("ingress.http", t, "r" * 16, "d0", wall=100, dur=90),
        _span("peer.rpc", t, "p" * 16, "d0", parent="r" * 16,
              wall=90, dur=40),
        # Owner daemon's window span carries its OWN trace but LINKS the
        # ingress span — the cross-daemon edge.
        _span("batch.window", "b" * 32, "w" * 16, "d1",
              links=[(t, "r" * 16)], wall=95, dur=20),
        _span("dispatch.launch", "b" * 32, "l" * 16, "d1",
              parent="w" * 16, links=[(t, "r" * 16)], wall=94, dur=5),
    ]
    trees = trace_collect.stitch(spans)
    tree = trees[t]
    assert tree["daemons"] == ["d0", "d1"]
    assert tree["spanCount"] == 4
    assert len(tree["roots"]) == 1
    root = tree["roots"][0]
    assert root["span"]["name"] == "ingress.http"
    kids = {c["span"]["name"]: c for c in root["children"]}
    assert kids["peer.rpc"]["via"] == "parent"
    assert kids["batch.window"]["via"] == "link"
    # The owner-side dispatch span nests under its own-daemon parent.
    sub = {c["span"]["name"] for c in kids["batch.window"]["children"]}
    assert "dispatch.launch" in sub


def test_stitch_reports_cross_daemon_hop():
    t = "c" * 32
    spans = [
        # rpc wall window: start 500_000 .. end 2_000_000
        _span("peer.rpc", t, "1" * 16, "d0", wall=2_000_000, dur=1_500_000,
              links=[]),
        # remote span starts INSIDE the rpc window (start 1_600_000)
        _span("batch.window", t, "2" * 16, "d1", wall=2_000_000,
              dur=400_000),
        # a remote span OUTSIDE the window must not become a hop
        _span("batch.window", t, "3" * 16, "d2", wall=9_000_000,
              dur=100_000),
    ]
    spans[0]["attrs"] = {"peer": "d1:81"}
    trees = trace_collect.stitch(spans)
    hops = trees[t]["hops"]
    assert len(hops) == 1, hops
    assert hops[0]["from"] == "d0" and hops[0]["to"] == "d1"
    assert hops[0]["peer"] == "d1:81"
    assert hops[0]["latency_ms"] >= 0


def test_limit_page_never_ends_mid_tie():
    """Concurrent record_span calls can stamp identical wall_ns; a page
    must extend through the boundary tie, or a poller's strict
    `since >` cursor would skip the tied remainder forever."""
    tracing.reset()
    for i, w in enumerate([1, 2, 2, 2, 3]):
        tracing._spans.record({
            "name": f"s{i}", "trace_id": "t" * 32, "span_id": str(i),
            "parent_id": "", "start_ns": 0, "dur_ns": 0, "wall_ns": w,
            "links": [], "attrs": {}, "thread": "t",
        })
    page = tracing.spans_snapshot(limit=2)
    assert [s["wall_ns"] for s in page] == [1, 2, 2, 2]  # soft cap: tie kept
    nxt = tracing.spans_snapshot(
        since_ns=max(s["wall_ns"] for s in page), limit=2
    )
    assert [s["wall_ns"] for s in nxt] == [3]  # nothing lost between pages


# ---------------------------------------------------------------------
# The live 2-daemon acceptance case
# ---------------------------------------------------------------------
import contextlib  # noqa: E402
import signal  # noqa: E402
import socket  # noqa: E402
import subprocess  # noqa: E402


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@contextlib.contextmanager
def _daemon_pair():
    """TWO daemon SUBPROCESSES peered to each other, trace sample 1.0.
    Separate processes are the point: each daemon has its OWN flight
    recorder, so a stitched tree spanning both addresses proves the
    trace genuinely crossed the wire (in-process cluster daemons share
    one module-global ring, which would vacuously 'span' daemons)."""
    import shutil
    import tempfile

    ports = [(_free_port(), _free_port()) for _ in range(2)]
    static = ",".join(
        f"127.0.0.1:{g}|127.0.0.1:{h}" for h, g in ports
    )
    procs = []
    # FRESH compile-cache dir per DAEMON: the shared .jax_cache gets
    # corrupted by concurrent writers (bench daemons, other suites,
    # each other) and a corrupt cache aborts daemon warmup with no
    # Python traceback.
    cache_root = tempfile.mkdtemp(prefix="trace-collect-jax-cache-")
    try:
        for http_port, grpc_port in ports:
            env = dict(os.environ)
            env.update(
                XLA_FLAGS="--xla_force_host_platform_device_count=2",
                JAX_PLATFORMS="cpu",
                JAX_COMPILATION_CACHE_DIR=os.path.join(
                    cache_root, str(http_port)
                ),
                GUBER_HTTP_ADDRESS=f"127.0.0.1:{http_port}",
                GUBER_GRPC_ADDRESS=f"127.0.0.1:{grpc_port}",
                GUBER_STATIC_PEERS=static,
                GUBER_TRACE_SAMPLE="1.0",
                GUBER_GLOBAL_SYNC_WAIT="3600s",
                GUBER_MULTI_REGION_SYNC_WAIT="3600s",
                GUBER_BATCH_TIMEOUT="30s",
                GUBER_CACHE_SIZE="4096",
            )
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "gubernator_tpu.cmd.server"],
                stdout=subprocess.PIPE, text=True, env=env,
                cwd=os.path.join(os.path.dirname(__file__), ".."),
            ))
        for p in procs:
            line = p.stdout.readline()
            assert "listening" in line, f"daemon failed to start: {line!r}"
        yield [f"127.0.0.1:{h}" for h, _ in ports]
    finally:
        for p in procs:
            p.send_signal(signal.SIGTERM)
        for p in procs:
            try:
                p.wait(timeout=15)
            except subprocess.TimeoutExpired:
                p.kill()
        shutil.rmtree(cache_root, ignore_errors=True)


@pytest.mark.slow
def test_one_trace_spans_two_live_daemons():
    with _daemon_pair() as addrs:
        # Hash-derived keys: FNV-1 clusters structured key families
        # onto one owner (the documented hash_ring property); md5-hex
        # keys disperse, so among a handful at least one lane crosses
        # the forward hop whatever the port draw.
        import hashlib

        client = V1Client(addrs[0], timeout_s=60.0)
        resp = client.get_rate_limits(GetRateLimitsRequest(requests=[
            RateLimitRequest(
                name="trace",
                unique_key=hashlib.md5(str(i).encode()).hexdigest(),
                hits=1, limit=100, duration=60_000,
            )
            for i in range(16)
        ]))
        assert not any(r.error for r in resp.responses)
        coll = trace_collect.Collector(addrs)
        assert coll.poll() > 0
        trees = trace_collect.stitch(coll.spans)
        # The acceptance case: ONE trace id whose stitched tree spans
        # BOTH live daemons, rooted at the entry daemon's ingress span.
        multi = {
            tid: t for tid, t in trees.items()
            if sorted(t["daemons"]) == sorted(addrs) and any(
                r["span"]["name"] == "ingress.http"
                and r["span"]["daemon"] == addrs[0]
                for r in t["roots"]
            )
        }
        assert multi, (
            f"no trace spans both daemons: "
            f"{[(tid, t['daemons']) for tid, t in trees.items()]}"
        )
        tid, tree = next(iter(multi.items()))

        def flatten(node, acc):
            acc.append(node)
            for c in node["children"]:
                flatten(c, acc)
            return acc

        root = next(
            r for r in tree["roots"]
            if r["span"]["name"] == "ingress.http"
            and r["span"]["daemon"] == addrs[0]
        )
        nodes = flatten(root, [])
        names_by_daemon = {}
        for n in nodes:
            names_by_daemon.setdefault(
                n["span"]["daemon"], set()
            ).add(n["span"]["name"])
        # The peer hop is visible: the entry daemon's client-side
        # peer.rpc span AND the owner daemon's spans, all stitched
        # under the ONE ingress root via parent/link edges.
        assert "peer.rpc" in names_by_daemon[addrs[0]], names_by_daemon
        assert addrs[1] in names_by_daemon, (
            f"owner daemon's spans not stitched under the ingress root: "
            f"{names_by_daemon}"
        )
        assert names_by_daemon[addrs[1]] & {
            "batch.window", "dispatch.launch", "dispatch.commit",
            "ingress.http", "ingress.grpc",
        }, names_by_daemon
        # The hop report names the two daemons with a plausible delta.
        assert any(
            h["from"] == addrs[0] and h["to"] == addrs[1]
            for h in tree["hops"]
        ), tree["hops"]


@pytest.mark.slow
def test_since_cursor_filters_old_spans():
    beh = fast_test_behaviors()
    beh.trace_sample = 1.0
    cl = Cluster().start_with([""], behaviors=beh)
    try:
        addr = cl.daemons[0].gateway.address
        client = V1Client(addr, timeout_s=30.0)
        client.get_rate_limits(GetRateLimitsRequest(requests=[
            RateLimitRequest(name="sc", unique_key="k", hits=1,
                             limit=10, duration=60_000),
        ]))
        first = trace_collect.fetch_spans(addr)
        assert first
        newest = max(s["wall_ns"] for s in first)
        # since=newest: everything recorded so far is filtered out.
        assert trace_collect.fetch_spans(addr, since_ns=newest) == []
        # limit: newest-N slice.
        assert len(trace_collect.fetch_spans(addr, limit=2)) <= 2
    finally:
        cl.stop()
