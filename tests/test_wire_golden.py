"""Golden wire-bytes tests: the generated protobuf stubs must produce
the exact bytes the reference's schema defines (proto/gubernator.proto,
proto/peers.proto field numbers), or cross-implementation gRPC
compatibility silently breaks.  Expected bytes are hand-derived from
the proto3 wire format: tag = (field_number << 3) | wire_type,
varints little-endian base-128.
"""

from gubernator_tpu.proto import etcd_kv_pb2 as kvpb
from gubernator_tpu.proto import etcd_rpc_pb2 as etcd_rpc
from gubernator_tpu.proto import gubernator_pb2 as pb
from gubernator_tpu.proto import peers_columns_pb2 as pc_pb
from gubernator_tpu.proto import peers_pb2 as peers_pb


def test_rate_limit_req_golden():
    m = pb.RateLimitReq(
        name="a", unique_key="b", hits=1, limit=2, duration=3,
        algorithm=1, behavior=2,
    )
    assert m.SerializeToString() == bytes(
        [
            0x0A, 0x01, ord("a"),  # 1: name
            0x12, 0x01, ord("b"),  # 2: unique_key
            0x18, 0x01,            # 3: hits varint
            0x20, 0x02,            # 4: limit
            0x28, 0x03,            # 5: duration
            0x30, 0x01,            # 6: algorithm enum LEAKY_BUCKET
            0x38, 0x02,            # 7: behavior enum GLOBAL
        ]
    )


def test_rate_limit_resp_golden():
    m = pb.RateLimitResp(status=1, limit=5, remaining=4, reset_time=1000)
    m.metadata["owner"] = "x"
    assert m.SerializeToString() == bytes(
        [
            0x08, 0x01,              # 1: status OVER_LIMIT
            0x10, 0x05,              # 2: limit
            0x18, 0x04,              # 3: remaining
            0x20, 0xE8, 0x07,        # 4: reset_time = 1000
            # 6: metadata map entry {key: "owner", value: "x"}
            0x32, 0x0A,
            0x0A, 0x05, *b"owner",
            0x12, 0x01, ord("x"),
        ]
    )


def test_batch_envelopes_golden():
    req = pb.GetRateLimitsReq(requests=[pb.RateLimitReq(name="n", hits=1)])
    assert req.SerializeToString() == bytes(
        [0x0A, 0x05, 0x0A, 0x01, ord("n"), 0x18, 0x01]
    )
    presp = peers_pb.GetPeerRateLimitsResp(
        rate_limits=[pb.RateLimitResp(remaining=7)]
    )
    # peers.proto: rate_limits is field 1
    assert presp.SerializeToString() == bytes([0x0A, 0x02, 0x18, 0x07])


def test_update_peer_globals_golden():
    m = peers_pb.UpdatePeerGlobalsReq(
        globals=[
            peers_pb.UpdatePeerGlobal(
                key="k", status=pb.RateLimitResp(remaining=3), algorithm=1
            )
        ]
    )
    assert m.SerializeToString() == bytes(
        [
            0x0A, 0x09,              # 1: globals (len 9)
            0x0A, 0x01, ord("k"),    # 1: key
            0x12, 0x02, 0x18, 0x03,  # 2: status {remaining: 3}
            0x18, 0x01,              # 3: algorithm
        ]
    )


def test_peer_columns_req_golden():
    """peers_columns.proto: column arrays, proto3-packed numerics.
    The descriptor is built without protoc (scripts/gen_columns_proto),
    so these bytes pin that the hand-built schema encodes exactly what
    protoc would."""
    m = pc_pb.PeerColumnsReq(
        names=["a"], unique_keys=["b"], algorithm=[1], behavior=[2],
        hits=[3], limit=[4], duration=[5],
    )
    assert m.SerializeToString() == bytes(
        [
            0x0A, 0x01, ord("a"),  # 1: names[0]
            0x12, 0x01, ord("b"),  # 2: unique_keys[0]
            0x1A, 0x01, 0x01,      # 3: algorithm, packed
            0x22, 0x01, 0x02,      # 4: behavior, packed
            0x2A, 0x01, 0x03,      # 5: hits, packed
            0x32, 0x01, 0x04,      # 6: limit, packed
            0x3A, 0x01, 0x05,      # 7: duration, packed
        ]
    )


def test_globals_columns_req_golden():
    """peers_columns.proto GlobalsColumnsReq (the columnar GLOBAL
    broadcast): packed numerics, field numbers pinned so the
    protoc-less descriptor stays wire-identical to the schema."""
    m = pc_pb.GlobalsColumnsReq(
        keys=["k"], algorithm=[1], status=[1], limit=[2], remaining=[3],
        reset_time=[1000],
    )
    assert m.SerializeToString() == bytes(
        [
            0x0A, 0x01, ord("k"),    # 1: keys[0]
            0x12, 0x01, 0x01,        # 2: algorithm, packed
            0x1A, 0x01, 0x01,        # 3: status, packed
            0x22, 0x01, 0x02,        # 4: limit, packed
            0x2A, 0x01, 0x03,        # 5: remaining, packed
            0x32, 0x02, 0xE8, 0x07,  # 6: reset_time = 1000, packed
        ]
    )


def test_globals_frame_golden():
    """The GUBC globals frame (kind 3) byte layout is a wire contract:
    header | key string column | algo i32 | status i32 | limit i64 |
    remaining i64 | reset i64, all little-endian."""
    import numpy as np

    from gubernator_tpu import wire
    from gubernator_tpu.parallel.global_mgr import GlobalsColumns

    cols = GlobalsColumns(
        keys=["a", "bc"],
        algorithm=np.array([1, 0], np.int32),
        status=np.array([0, 1], np.int32),
        limit=np.array([5, 6], np.int64),
        remaining=np.array([4, 5], np.int64),
        reset_time=np.array([1000, 2000], np.int64),
    )
    raw = wire.encode_globals_frame(cols)
    i32 = lambda v: int(v).to_bytes(4, "little")  # noqa: E731
    i64 = lambda v: int(v).to_bytes(8, "little")  # noqa: E731
    expected = (
        b"GUBC" + bytes([1, 3]) + i32(2)          # magic, ver, kind, n
        + i32(3) + i32(0) + i32(1) + i32(3) + b"abc"  # key column
        + i32(1) + i32(0)                         # algorithm
        + i32(0) + i32(1)                         # status
        + i64(5) + i64(6)                         # limit
        + i64(4) + i64(5)                         # remaining
        + i64(1000) + i64(2000)                   # reset_time
    )
    assert raw == expected
    assert wire.is_globals_frame(raw)
    back = wire.decode_globals_frame(raw)
    assert back.keys == ["a", "bc"]
    assert list(back.reset_time) == [1000, 2000]


def test_transfer_columns_req_golden():
    """peers_columns.proto TransferColumnsReq (the ownership-transfer
    RPC): field numbers pinned so the protoc-less descriptor stays
    wire-identical to the schema."""
    m = pc_pb.TransferColumnsReq(
        ring_hash=5, keys=["k"], algorithm=[1], status=[1], limit=[2],
        remaining=[3], duration=[4], stamp=[6], expire_at=[7],
    )
    assert m.SerializeToString() == bytes(
        [
            0x08, 0x05,              # 1: ring_hash = 5 (varint)
            0x12, 0x01, ord("k"),    # 2: keys[0]
            0x1A, 0x01, 0x01,        # 3: algorithm, packed
            0x22, 0x01, 0x01,        # 4: status, packed
            0x2A, 0x01, 0x02,        # 5: limit, packed
            0x32, 0x01, 0x03,        # 6: remaining, packed
            0x3A, 0x01, 0x04,        # 7: duration, packed
            0x42, 0x01, 0x06,        # 8: stamp, packed
            0x4A, 0x01, 0x07,        # 9: expire_at, packed
        ]
    )
    resp = pc_pb.TransferResp(committed=2, rejected=1)
    assert resp.SerializeToString() == bytes(
        [0x08, 0x02, 0x10, 0x01]     # 1: committed, 2: rejected
    )


def test_transfer_frame_golden():
    """The GUBC transfer frame (kind 4) byte layout is a wire contract:
    header | ring_hash u64 | key string column | algo i32 | status i32
    | limit i64 | remaining i64 | duration i64 | stamp i64 | expire_at
    i64, all little-endian."""
    import numpy as np

    from gubernator_tpu import wire
    from gubernator_tpu.reshard import TransferColumns

    cols = TransferColumns(
        keys=["a", "bc"],
        algorithm=np.array([1, 0], np.int32),
        status=np.array([0, 1], np.int32),
        limit=np.array([5, 6], np.int64),
        remaining=np.array([4, 5], np.int64),
        duration=np.array([60, 70], np.int64),
        stamp=np.array([1000, 2000], np.int64),
        expire_at=np.array([3000, 4000], np.int64),
        ring_hash=0x0102030405060708,
    )
    raw = wire.encode_transfer_frame(cols)
    i32 = lambda v: int(v).to_bytes(4, "little")  # noqa: E731
    i64 = lambda v: int(v).to_bytes(8, "little")  # noqa: E731
    expected = (
        b"GUBC" + bytes([1, 4]) + i32(2)          # magic, ver, kind, n
        + i64(0x0102030405060708)                 # ring_hash (epoch fence)
        + i32(3) + i32(0) + i32(1) + i32(3) + b"abc"  # key column
        + i32(1) + i32(0)                         # algorithm
        + i32(0) + i32(1)                         # status
        + i64(5) + i64(6)                         # limit
        + i64(4) + i64(5)                         # remaining
        + i64(60) + i64(70)                       # duration
        + i64(1000) + i64(2000)                   # stamp
        + i64(3000) + i64(4000)                   # expire_at
    )
    assert raw == expected
    assert wire.is_transfer_frame(raw)
    assert not wire.is_globals_frame(raw)
    back = wire.decode_transfer_frame(raw)
    assert back.keys == ["a", "bc"]
    assert back.ring_hash == 0x0102030405060708
    assert list(back.expire_at) == [3000, 4000]


def test_classic_broadcast_bytes_unchanged():
    """GUBER_GLOBAL_COLUMNS=0 / classic-negotiated peers must see
    byte-identical wire to the pre-columns sender in BOTH encodings:
    the BroadcastBatch classic legs reproduce the legacy per-item
    pb/JSON encoders exactly."""
    import json

    from gubernator_tpu import wire
    from gubernator_tpu.parallel.global_mgr import GlobalsColumns
    from gubernator_tpu.types import RateLimitResponse, UpdatePeerGlobal

    updates = [
        UpdatePeerGlobal(
            key="gp_k", algorithm=1,
            status=RateLimitResponse(
                status=1, limit=5, remaining=0, reset_time=1_573_430_430_000
            ),
        ),
        UpdatePeerGlobal(
            key="gp_j",
            status=RateLimitResponse(limit=9, remaining=9, reset_time=7),
        ),
    ]
    bb = wire.BroadcastBatch(GlobalsColumns.from_updates(updates))
    assert (
        bb.classic_pb().SerializeToString()
        == wire.update_globals_req_to_pb(updates).SerializeToString()
    )
    assert bb.classic_json_bytes() == json.dumps(
        {"globals": [u.to_json() for u in updates]}
    ).encode("utf-8")


def test_peer_columns_resp_golden():
    m = pc_pb.PeerColumnsResp(
        status=[1], limit=[10], remaining=[9], reset_time=[1000],
    )
    ov = m.overrides.add()
    ov.lane = 0  # proto3 default: omitted on the wire
    ov.resp.CopyFrom(pb.RateLimitResp(error="x"))
    assert m.SerializeToString() == bytes(
        [
            0x0A, 0x01, 0x01,        # 1: status, packed
            0x12, 0x01, 0x0A,        # 2: limit, packed
            0x1A, 0x01, 0x09,        # 3: remaining, packed
            0x22, 0x02, 0xE8, 0x07,  # 4: reset_time = 1000, packed
            # 5: overrides[0] {resp: {error: "x"}}
            0x2A, 0x05,
            0x12, 0x03, 0x2A, 0x01, ord("x"),
        ]
    )


def test_ingress_frame_golden():
    """The GUBC public ingress frame (kind 5) byte layout is a wire
    contract: identical to the kind-1 peer frame except the kind byte —
    header | name column | unique_key column | algo i32 | behavior i32
    | hits i64 | limit i64 | duration i64, all little-endian.  With
    GUBER_TRACE_SAMPLE=0 (no trailer) the bytes must stay exactly
    this."""
    import numpy as np

    from gubernator_tpu import wire

    cols = (
        ["a", "bc"], ["x", "yz"],
        np.array([1, 0], np.int32), np.array([0, 2], np.int32),
        np.array([1, 2], np.int64), np.array([5, 6], np.int64),
        np.array([1000, 2000], np.int64),
    )
    raw = wire.encode_ingress_frame(cols)
    i32 = lambda v: int(v).to_bytes(4, "little")  # noqa: E731
    i64 = lambda v: int(v).to_bytes(8, "little")  # noqa: E731
    expected = (
        b"GUBC" + bytes([1, 5]) + i32(2)          # magic, ver, kind, n
        + i32(3) + i32(0) + i32(1) + i32(3) + b"abc"  # name column
        + i32(3) + i32(0) + i32(1) + i32(3) + b"xyz"  # unique_key column
        + i32(1) + i32(0)                         # algorithm
        + i32(0) + i32(2)                         # behavior
        + i64(1) + i64(2)                         # hits
        + i64(5) + i64(6)                         # limit
        + i64(1000) + i64(2000)                   # duration
    )
    assert raw == expected
    assert wire.is_ingress_frame(raw)
    assert wire.is_columns_frame(raw)  # still GUBC magic
    assert not wire.is_transfer_frame(raw)
    # Same columns on the peer hop differ ONLY in the kind byte.
    peer = wire.encode_columns_frame(cols)
    assert peer[:5] == raw[:5] and peer[6:] == raw[6:]
    assert peer[5] == 1 and raw[5] == 5
    back = wire.decode_ingress_frame(raw)
    assert list(back.names) == ["a", "bc"]
    assert list(back.unique_keys) == ["x", "yz"]
    assert list(back.duration) == [1000, 2000]
    # Trace trailer: appended GTRC block, byte-exact; absent = the
    # sample-0 identity above (the PR 4 wire-parity contract).
    entry = (0, 2, 0x0102030405060708090A0B0C0D0E0F10, 0x1112131415161718)
    traced = wire.encode_ingress_frame(cols, trace=[entry])
    assert traced == raw + (
        b"GTRC" + i32(1) + i32(0) + i32(2)
        + bytes(range(1, 17)) + bytes(range(0x11, 0x19))
    )
    assert wire.decode_ingress_frame(traced).trace_ctx == [entry]


def test_ingress_result_frame_golden():
    """The GUBC public ingress response frame (kind 6): the kind-2
    arrays + `u32 n_owner_addrs [owner column | owner_of i32[n]]` +
    sparse override pairs."""
    import numpy as np

    from gubernator_tpu import wire
    from gubernator_tpu.service import ColumnarResult

    r = ColumnarResult.empty(2)
    r.status[:] = [0, 1]
    r.limit[:] = [10, 20]
    r.remaining[:] = [9, 0]
    r.reset_time[:] = [1000, 2000]
    r.set_owner(np.array([1]), "h:1")
    raw = wire.encode_ingress_result_frame(r)
    i32 = lambda v: int(v).to_bytes(4, "little", signed=True)  # noqa: E731
    i64 = lambda v: int(v).to_bytes(8, "little")  # noqa: E731
    expected = (
        b"GUBC" + bytes([1, 6]) + i32(2)          # magic, ver, kind, n
        + i32(0) + i32(1)                         # status
        + i64(10) + i64(20)                       # limit
        + i64(9) + i64(0)                         # remaining
        + i64(1000) + i64(2000)                   # reset_time
        + i32(1)                                  # n_owner_addrs
        + i32(3) + i32(0) + i32(3) + b"h:1"       # owner addr column
        + i32(-1) + i32(0)                        # owner_of
        + i32(0)                                  # n_overrides
    )
    assert raw == expected
    assert wire.is_ingress_result_frame(raw)
    back = wire.decode_ingress_result_frame(raw)
    assert back.owner_addrs == ["h:1"]
    assert back.response_at(1).metadata == {"owner": "h:1"}
    assert back.response_at(0).metadata == {}
    # No forwarded lanes: the owner section is a single zero count.
    r2 = ColumnarResult.empty(1)
    raw2 = wire.encode_ingress_result_frame(r2)
    assert raw2.endswith(i32(0) + i32(0))  # n_owner_addrs=0, n_overrides=0
    assert wire.decode_ingress_result_frame(raw2).owner_of is None


def test_ingress_columns_resp_pb_golden():
    """peers_columns.proto IngressColumnsResp (the gRPC front door):
    field numbers pinned so the protoc-less descriptor stays
    wire-identical to the schema.  The request message is
    PeerColumnsReq verbatim (pinned by test_peer_columns_req_golden)."""
    m = pc_pb.IngressColumnsResp(
        status=[1], limit=[10], remaining=[9], reset_time=[1000],
        owner_of=[-1], owner_addrs=["h"],
    )
    ov = m.overrides.add()
    ov.lane = 0
    ov.resp.CopyFrom(pb.RateLimitResp(error="x"))
    assert m.SerializeToString() == bytes(
        [
            0x0A, 0x01, 0x01,        # 1: status, packed
            0x12, 0x01, 0x0A,        # 2: limit, packed
            0x1A, 0x01, 0x09,        # 3: remaining, packed
            0x22, 0x02, 0xE8, 0x07,  # 4: reset_time = 1000, packed
            # 5: overrides[0] {resp: {error: "x"}}
            0x2A, 0x05,
            0x12, 0x03, 0x2A, 0x01, ord("x"),
            # 6: owner_of = [-1], packed (10-byte varint)
            0x32, 0x0A,
            0xFF, 0xFF, 0xFF, 0xFF, 0xFF, 0xFF, 0xFF, 0xFF, 0xFF, 0x01,
            0x3A, 0x01, ord("h"),    # 7: owner_addrs[0]
        ]
    )


def test_health_check_resp_golden():
    m = pb.HealthCheckResp(status="healthy", peer_count=3)
    assert m.SerializeToString() == bytes(
        [0x0A, 0x07, *b"healthy", 0x18, 0x03]
    )


def test_etcd_subset_golden():
    """etcdserverpb wire subset: field numbers must match the real etcd
    schema or a production cluster misreads every request."""
    r = etcd_rpc.RangeRequest(key=b"/a", range_end=b"/b", limit=5)
    assert r.SerializeToString() == bytes(
        [0x0A, 0x02, *b"/a", 0x12, 0x02, *b"/b", 0x18, 0x05]
    )
    p = etcd_rpc.PutRequest(key=b"k", value=b"v", lease=7)
    assert p.SerializeToString() == bytes(
        [0x0A, 0x01, ord("k"), 0x12, 0x01, ord("v"), 0x18, 0x07]
    )
    g = etcd_rpc.LeaseGrantRequest(TTL=30)
    assert g.SerializeToString() == bytes([0x08, 0x1E])
    w = etcd_rpc.WatchRequest(
        create_request=etcd_rpc.WatchCreateRequest(key=b"p", start_revision=9)
    )
    assert w.SerializeToString() == bytes(
        [0x0A, 0x05, 0x0A, 0x01, ord("p"), 0x18, 0x09]
    )
    kv = kvpb.KeyValue(key=b"x", mod_revision=2, value=b"y", lease=4)
    assert kv.SerializeToString() == bytes(
        [0x0A, 0x01, ord("x"), 0x18, 0x02, 0x2A, 0x01, ord("y"), 0x30, 0x04]
    )
    ev = kvpb.Event(type=kvpb.Event.DELETE, kv=kvpb.KeyValue(key=b"x"))
    assert ev.SerializeToString() == bytes(
        [0x08, 0x01, 0x12, 0x03, 0x0A, 0x01, ord("x")]
    )


def test_region_columns_req_pb_golden():
    """RegionColumnsReq (peers_columns.proto, the federation plane's
    proto twin of the GUBC region frame): field numbers are a wire
    contract with every deployed region."""
    m = pc_pb.RegionColumnsReq(
        origin="dc", names=["a"], unique_keys=["b"],
        algorithm=[1], behavior=[2], hits=[3], limit=[4], duration=[5],
    )
    assert m.SerializeToString() == bytes(
        [
            0x0A, 0x02, *b"dc",    # 1: origin
            0x12, 0x01, ord("a"),  # 2: names
            0x1A, 0x01, ord("b"),  # 3: unique_keys
            0x22, 0x01, 0x01,      # 4: algorithm (packed)
            0x2A, 0x01, 0x02,      # 5: behavior (packed)
            0x32, 0x01, 0x03,      # 6: hits (packed)
            0x3A, 0x01, 0x04,      # 7: limit (packed)
            0x42, 0x01, 0x05,      # 8: duration (packed)
        ]
    )
    resp = pc_pb.RegionColumnsResp(applied=7)
    assert resp.SerializeToString() == bytes([0x08, 0x07])


def test_region_frame_golden():
    """The GUBC region frame (kind 7) byte layout is a wire contract:
    header | u32 origin_len | origin utf-8 | names column | unique_keys
    column | algo i32 | behavior i32 | hits i64 | limit i64 | duration
    i64, all little-endian (string columns in the shared
    blob_len/offsets/blob form)."""
    import numpy as np

    from gubernator_tpu import wire
    from gubernator_tpu.federation import RegionColumns

    cols = RegionColumns(
        origin="dc-a",
        names=["a", "bc"],
        unique_keys=["x", "yz"],
        algorithm=np.array([1, 0], np.int32),
        behavior=np.array([0, 4], np.int32),
        hits=np.array([2, 3], np.int64),
        limit=np.array([10, 20], np.int64),
        duration=np.array([60, 70], np.int64),
    )
    raw = wire.encode_region_frame(cols)
    i32 = lambda v: int(v).to_bytes(4, "little")  # noqa: E731
    i64 = lambda v: int(v).to_bytes(8, "little")  # noqa: E731
    expected = (
        b"GUBC" + bytes([1, 7]) + i32(2)               # magic, ver, kind, n
        + i32(4) + b"dc-a"                             # origin
        + i32(3) + i32(0) + i32(1) + i32(3) + b"abc"   # names column
        + i32(3) + i32(0) + i32(1) + i32(3) + b"xyz"   # unique_keys column
        + i32(1) + i32(0)                              # algorithm
        + i32(0) + i32(4)                              # behavior
        + i64(2) + i64(3)                              # hits
        + i64(10) + i64(20)                            # limit
        + i64(60) + i64(70)                            # duration
    )
    assert raw == expected
    assert wire.is_region_frame(raw)
    assert not wire.is_transfer_frame(raw)
    back = wire.decode_region_frame(raw)
    assert back.origin == "dc-a"
    assert back.names == ["a", "bc"]
    assert back.unique_keys == ["x", "yz"]
    assert list(back.hits) == [2, 3]
    assert list(back.duration) == [60, 70]
    # Truncation / foreign frames answer ValueError (the gateway's 400)
    import pytest

    with pytest.raises(ValueError):
        wire.decode_region_frame(raw[:-1])
    with pytest.raises(ValueError):
        wire.decode_region_frame(raw + b"\x00")


def test_classic_region_bytes_unchanged():
    """GUBER_REGION_COLUMNS=0 / classic-negotiated peers must see
    byte-identical wire to the PRE-FEDERATION sender in both
    encodings: the RegionBatch classic chunk legs reproduce the legacy
    per-item GetPeerRateLimits encoders exactly (MULTI_REGION already
    stripped, as the old MultiRegionManager stripped it on the wire)."""
    import dataclasses
    import json

    from gubernator_tpu import wire
    from gubernator_tpu.federation import RegionBatch, RegionColumns
    from gubernator_tpu.types import (
        Behavior,
        GetRateLimitsRequest,
        RateLimitRequest,
        set_behavior,
    )

    reqs = [
        RateLimitRequest(
            name="mr", unique_key=f"k{i}", hits=2, limit=10, duration=1000,
            behavior=int(Behavior.MULTI_REGION), algorithm=i % 2,
        )
        for i in range(3)
    ]
    batch = RegionBatch(RegionColumns.from_requests("dc-a", reqs))
    stripped = [
        dataclasses.replace(
            r, behavior=set_behavior(r.behavior, Behavior.MULTI_REGION, False)
        )
        for r in reqs
    ]
    legacy = GetRateLimitsRequest(requests=stripped)
    # gRPC: the exact GetPeerRateLimitsReq the pre-PR sender serialized
    (chunk,) = batch.classic_pb_chunks(1000)
    assert (
        chunk.SerializeToString()
        == wire.peer_rate_limits_req_to_pb(legacy).SerializeToString()
    )
    # HTTP: the exact JSON body (peer_client._post_inner's json.dumps)
    (body,) = batch.classic_json_chunks(1000)
    assert body == json.dumps(legacy.to_json()).encode("utf-8")
    # and chunking splits at the classic per-RPC cap, preserving order
    chunks = batch.classic_pb_chunks(2)
    assert [len(c.requests) for c in chunks] == [2, 1]
    assert chunks[1].requests[0].unique_key == "k2"
