"""Concurrency hardening: traffic storms racing peer churn, batcher
flushes, GLOBAL syncs, and shutdown.

The reference runs its whole suite under Go's race detector
(`Makefile:8-9`); Python has no `-race`, so these tests hammer the
lock-heavy host tier from many threads with faulthandler armed and
verify (a) nothing deadlocks or raises out of the service surface,
(b) every response is well-formed, and (c) the slot tables stay
internally consistent (MeshBucketStore.check_consistency).
"""

import faulthandler
import threading
import time

import numpy as np
import pytest

from gubernator_tpu.service import IngressColumns, ServiceConfig, V1Service
from gubernator_tpu.types import (
    Behavior,
    GetRateLimitsRequest,
    PeerInfo,
    RateLimitRequest,
)
from gubernator_tpu.utils.clock import Clock

T0 = 1_573_430_430_000

faulthandler.enable()


def make_service(addr="127.0.0.1:9901"):
    clock = Clock()
    clock.freeze(T0)
    svc = V1Service(ServiceConfig(cache_size=8192, clock=clock,
                                  advertise_address=addr))
    svc.set_peers([PeerInfo(grpc_address=addr, is_owner=True)])
    return svc


def cols_for(tid, i, n=50, behavior=0):
    ids = (np.arange(n) * 131 + i * 7 + tid) % 500
    return IngressColumns(
        names=["race"] * n,
        unique_keys=[f"k{k}" for k in ids],
        algorithm=(ids % 2).astype(np.int32),
        behavior=np.full(n, behavior, np.int32),
        hits=np.ones(n, np.int64),
        limit=np.full(n, 1_000_000, np.int64),
        duration=np.full(n, 60_000, np.int64),
    )


def run_storm(svc, n_workers, iters, churn_fn=None, behaviors=(0,)):
    """Drive traffic from n_workers threads while churn_fn runs in a
    loop; returns (errors, malformed) collected across workers."""
    errors, malformed = [], []
    stop = threading.Event()
    lock = threading.Lock()

    def worker(tid):
        try:
            for i in range(iters):
                beh = behaviors[i % len(behaviors)]
                if i % 3 == 0:
                    # dataclass path incl. the LocalBatcher leg
                    resp = svc.get_rate_limits(GetRateLimitsRequest(requests=[
                        RateLimitRequest(name="race", unique_key=f"k{(i * 13 + tid) % 500}",
                                         hits=1, limit=1_000_000, duration=60_000,
                                         behavior=beh)
                    ]))
                    rls = resp.responses
                else:
                    result = svc.get_rate_limits_columns(cols_for(tid, i, behavior=beh))
                    rls = [result.response_at(j) for j in range(result.n)]
                for r in rls:
                    ok_value = r.error or (r.reset_time > 0 and r.limit > 0)
                    if not ok_value:
                        with lock:
                            malformed.append(r)
        except Exception as e:  # noqa: BLE001
            with lock:
                errors.append(e)

    def churner():
        while not stop.is_set():
            try:
                churn_fn()
            except Exception as e:  # noqa: BLE001
                with lock:
                    errors.append(e)
            time.sleep(0.002)

    # daemon=True + stop in finally: a DETECTED deadlock must fail the
    # test, not hang pytest at interpreter exit with the diagnosis lost.
    threads = [
        threading.Thread(target=worker, args=(t,), daemon=True)
        for t in range(n_workers)
    ]
    churn_thread = (
        threading.Thread(target=churner, daemon=True) if churn_fn else None
    )
    try:
        for t in threads:
            t.start()
        if churn_thread:
            churn_thread.start()
        for t in threads:
            t.join(timeout=120)
            assert not t.is_alive(), "worker deadlocked"
    finally:
        stop.set()
    if churn_thread:
        churn_thread.join(timeout=10)
        assert not churn_thread.is_alive(), "churner deadlocked"
    return errors, malformed


def test_set_peers_storm_during_traffic():
    """Traffic from 8 threads while the peer list churns between
    self-only and self+unreachable-fakes: requests whose keys re-hash
    to fake owners error per-lane, everything else answers, nothing
    deadlocks, and the slot tables stay consistent."""
    svc = make_service()
    me = PeerInfo(grpc_address="127.0.0.1:9901", is_owner=True)
    fakes = [PeerInfo(grpc_address=f"127.0.0.1:1{n}") for n in range(3)]
    state = {"flip": False}

    def churn():
        state["flip"] = not state["flip"]
        svc.set_peers([me] + (fakes if state["flip"] else []))

    try:
        errors, malformed = run_storm(svc, n_workers=8, iters=30, churn_fn=churn)
        assert errors == []
        assert malformed == []
        svc.store.check_consistency()
        # service still fully functional with the stable peer list
        svc.set_peers([me])
        r = svc.get_rate_limits(GetRateLimitsRequest(requests=[
            RateLimitRequest(name="after", unique_key="storm", hits=1,
                             limit=10, duration=60_000)
        ]))
        assert r.responses[0].error == "" and r.responses[0].remaining == 9
    finally:
        svc.close()


def test_global_sync_races_columnar_traffic():
    """GLOBAL syncs (device collective + donated-buffer swaps) racing
    columnar dispatches from many threads must serialize correctly."""
    svc = make_service("127.0.0.1:9902")

    def churn():
        svc.global_mgr.run_once()

    try:
        errors, malformed = run_storm(
            svc, n_workers=6, iters=20, churn_fn=churn,
            behaviors=(0, int(Behavior.GLOBAL)),
        )
        assert errors == []
        assert malformed == []
        svc.store.check_consistency()
    finally:
        svc.close()


def test_shutdown_races_traffic():
    """close() during a storm: every in-flight request completes with a
    result or a well-formed per-lane error — never a hang or an
    unhandled exception from the service surface."""
    svc = make_service("127.0.0.1:9903")
    started = threading.Event()
    outcome = {"errors": [], "done": 0}
    lock = threading.Lock()

    def worker(tid):
        started.set()
        for i in range(40):
            try:
                result = svc.get_rate_limits_columns(cols_for(tid, i, n=20))
                for j in range(result.n):
                    result.response_at(j)
            except Exception as e:  # noqa: BLE001
                with lock:
                    outcome["errors"].append(e)
            with lock:
                outcome["done"] += 1

    threads = [
        threading.Thread(target=worker, args=(t,), daemon=True) for t in range(4)
    ]
    for t in threads:
        t.start()
    started.wait(timeout=10)
    time.sleep(0.05)
    svc.close()
    for t in threads:
        t.join(timeout=60)
        assert not t.is_alive(), "worker hung across close()"
    # post-close requests must degrade to per-lane errors, not raise
    assert outcome["errors"] == []
    assert outcome["done"] == 4 * 40


def test_concurrent_single_key_exactness():
    """The canonical race check: many threads draining ONE key must
    admit exactly `limit` hits across every ingress path."""
    svc = make_service("127.0.0.1:9904")
    limit = 60
    admitted = []
    lock = threading.Lock()

    def worker(tid):
        got = 0
        for i in range(10):
            n = 4
            cols = IngressColumns(
                names=["exact"] * n,
                unique_keys=["one"] * n,
                algorithm=np.zeros(n, np.int32),
                behavior=np.zeros(n, np.int32),
                hits=np.ones(n, np.int64),
                limit=np.full(n, limit, np.int64),
                duration=np.full(n, 3_600_000, np.int64),
            )
            r = svc.get_rate_limits_columns(cols)
            got += sum(1 for j in range(n) if r.response_at(j).status == 0)
        with lock:
            admitted.append(got)

    try:
        threads = [threading.Thread(target=worker, args=(t,)) for t in range(5)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
            assert not t.is_alive()
        assert sum(admitted) == limit  # 5*10*4=200 attempts, exactly 60 pass
        svc.store.check_consistency()
    finally:
        svc.close()
