"""gRPC data plane tests: the V1/PeersV1 wire surface.

The reference's clients speak gRPC (client.go:41-57; functional_test.go
dials with DialV1Server) — these tests exercise the same path end to
end: client RPCs, peer forwarding over gRPC channels, error status
codes, raw-protobuf wire-format parity, and TLS/mTLS on the gRPC port.
"""

import grpc
import pytest

from gubernator_tpu.client import GrpcV1Client, dial_v1_server
from gubernator_tpu.cluster import Cluster
from gubernator_tpu.config import DaemonConfig
from gubernator_tpu.daemon import Daemon
from gubernator_tpu.grpc_server import channel_credentials
from gubernator_tpu.proto import V1_SERVICE, gubernator_pb2 as pb
from gubernator_tpu.tls import TLSConfig
from gubernator_tpu.types import (
    Algorithm,
    GetRateLimitsRequest,
    RateLimitRequest,
    Status,
    SECOND,
)
from gubernator_tpu.utils.clock import Clock

T0 = 1_573_430_430_000


@pytest.fixture(scope="module")
def clock():
    c = Clock()
    c.freeze(T0)
    return c


@pytest.fixture(scope="module")
def cluster(clock):
    cl = Cluster().start(3, clock=clock)
    yield cl
    cl.stop()


def mk(name, key, hits=1, limit=10, duration=9 * SECOND):
    return RateLimitRequest(
        name=name, unique_key=key, hits=hits, limit=limit,
        duration=duration, algorithm=Algorithm.TOKEN_BUCKET,
    )


def test_token_bucket_over_grpc(cluster, clock):
    client = dial_v1_server(cluster.peers[0].grpc_address)
    try:
        for want_remaining, want_status in [(9, 0), (8, 0), (7, 0)]:
            resp = client.get_rate_limits(
                GetRateLimitsRequest(requests=[mk("grpc_tb", "account:9")])
            )
            rl = resp.responses[0]
            assert rl.error == ""
            assert rl.remaining == want_remaining
            assert rl.status == want_status
    finally:
        client.close()


def test_grpc_forwarding_owner_metadata(cluster, clock):
    """A request entering at a non-owner peer is forwarded over the gRPC
    peer channel; the response metadata names the owner
    (gubernator.go:190,209)."""
    owner_addr = cluster.daemons[0].service.get_peer(
        "grpc_fw_account:1"
    ).info.grpc_address
    entry = next(
        d for d in cluster.daemons if d.peer_info.grpc_address != owner_addr
    )
    client = dial_v1_server(entry.peer_info.grpc_address)
    try:
        resp = client.get_rate_limits(
            GetRateLimitsRequest(requests=[mk("grpc_fw", "account:1")])
        )
        rl = resp.responses[0]
        assert rl.error == ""
        assert rl.metadata.get("owner") == owner_addr
    finally:
        client.close()


def test_grpc_batch_too_large(cluster):
    client = dial_v1_server(cluster.peers[0].grpc_address)
    reqs = [mk("grpc_big", f"k{i}") for i in range(1001)]
    try:
        with pytest.raises(grpc.RpcError) as err:
            client.get_rate_limits(GetRateLimitsRequest(requests=reqs))
        assert err.value.code() == grpc.StatusCode.OUT_OF_RANGE
    finally:
        client.close()


def test_health_check_over_grpc(cluster):
    client = dial_v1_server(cluster.peers[0].grpc_address)
    try:
        hc = client.health_check()
        assert hc.status == "healthy"
        assert hc.peer_count == 3
    finally:
        client.close()


def test_grpc_transport_metrics_interceptor(cluster):
    """Every gRPC RPC is tagged at the TRANSPORT layer (the reference's
    stats handler tags all methods, grpc_stats.go:95-118): HealthCheck
    and GetRateLimits counts and durations appear after one call each,
    and an OutOfRange abort is counted as an error."""
    d = cluster.daemons[0]

    def count(method, status):
        c = d.service.metrics.request_counts
        return c.labels(status=status, method=method)._value.get()

    hc_before = count("/pb.gubernator.V1/HealthCheck", "0")
    rl_before = count("/pb.gubernator.V1/GetRateLimits", "0")
    err_before = count("/pb.gubernator.V1/GetRateLimits", "1")
    client = dial_v1_server(cluster.peers[0].grpc_address)
    try:
        client.health_check()
        client.get_rate_limits(
            GetRateLimitsRequest(requests=[mk("grpc_metrics", "m1")])
        )
        with pytest.raises(grpc.RpcError):
            client.get_rate_limits(
                GetRateLimitsRequest(
                    requests=[mk("grpc_metrics", f"m{i}") for i in range(1001)]
                )
            )
    finally:
        client.close()
    assert count("/pb.gubernator.V1/HealthCheck", "0") == hc_before + 1
    assert count("/pb.gubernator.V1/GetRateLimits", "0") == rl_before + 1
    assert count("/pb.gubernator.V1/GetRateLimits", "1") == err_before + 1
    # Durations ride the same tagging (summary count tracks the counter).
    dur = d.service.metrics.request_duration.labels(
        method="/pb.gubernator.V1/HealthCheck"
    )
    assert dur._count.get() >= hc_before + 1


def test_raw_protobuf_wire_parity(cluster):
    """Dial with a bare channel + hand-built protobuf bytes: proves the
    fully-qualified method names and field numbers match the published
    schema (a stock Gubernator client's wire format)."""
    channel = grpc.insecure_channel(cluster.peers[0].grpc_address)
    try:
        rpc = channel.unary_unary(
            f"/{V1_SERVICE}/GetRateLimits",
            request_serializer=lambda b: b,  # pre-serialized bytes
            response_deserializer=pb.GetRateLimitsResp.FromString,
        )
        raw = pb.GetRateLimitsReq(
            requests=[
                pb.RateLimitReq(
                    name="wire", unique_key="k", hits=1, limit=5,
                    duration=60_000, algorithm=pb.LEAKY_BUCKET,
                )
            ]
        ).SerializeToString()
        resp = rpc(raw, timeout=5.0)
        assert resp.responses[0].status == pb.UNDER_LIMIT
        assert resp.responses[0].limit == 5
    finally:
        channel.close()


def test_grpc_tls_mtls_roundtrip(clock, tmp_path):
    """AutoTLS daemon: the gRPC port serves TLS; a client presenting the
    CA (and cert, under require-and-verify) connects, one without valid
    credentials is rejected (tls_test.go:157-260 equivalent on gRPC)."""
    conf = DaemonConfig(
        listen_address="127.0.0.1:0",
        grpc_listen_address="127.0.0.1:0",
        cache_size=512,
        tls=TLSConfig(auto_tls=True, client_auth="require-and-verify"),
    )
    d = Daemon(conf, clock=clock).start()
    try:
        creds = channel_credentials(d.conf.tls)
        client = GrpcV1Client(d.peer_info.grpc_address, credentials=creds)
        resp = client.get_rate_limits(
            GetRateLimitsRequest(requests=[mk("grpc_tls", "k")])
        )
        assert resp.responses[0].error == ""
        assert resp.responses[0].remaining == 9
        client.close()

        # No client cert => handshake rejected under require-and-verify.
        with open(d.conf.tls.ca_file, "rb") as f:
            ca_only = grpc.ssl_channel_credentials(root_certificates=f.read())
        bad = GrpcV1Client(d.peer_info.grpc_address, credentials=ca_only, timeout_s=2.0)
        with pytest.raises(grpc.RpcError):
            bad.get_rate_limits(GetRateLimitsRequest(requests=[mk("grpc_tls", "k2")]))
        bad.close()
    finally:
        d.close()


def test_grpc_peer_transport_used(cluster):
    """Peer forwarding must ride the gRPC channel (not the HTTP
    fallback): after a forwarded call, the owner's PeersV1 gRPC method
    counter moves."""
    owner_addr = cluster.daemons[0].service.get_peer(
        "grpc_count_account:2"
    ).info.grpc_address
    entry = next(
        d for d in cluster.daemons if d.peer_info.grpc_address != owner_addr
    )
    owner = next(
        d for d in cluster.daemons if d.peer_info.grpc_address == owner_addr
    )
    before = _peer_rpc_count(owner)
    client = dial_v1_server(entry.peer_info.grpc_address)
    try:
        client.get_rate_limits(
            GetRateLimitsRequest(requests=[mk("grpc_count", "account:2")])
        )
    finally:
        client.close()
    assert _peer_rpc_count(owner) == before + 1
    # Pin the transport itself, not just the service-layer counter
    # (which the HTTP gateway peer route also increments): the entry
    # daemon's client for the owner must have exercised the gRPC
    # channel (lazily built on first gRPC use) and never opened the
    # HTTP fallback connection.
    peer = entry.service.get_peer("grpc_count_account:2")
    assert peer.transport == "grpc"
    assert peer._channel is not None
    assert peer._conn is None


def _peer_rpc_count(daemon) -> float:
    # Either PeersV1 data-plane method counts: columnar-speaking peers
    # forward via GetPeerRateLimitsColumns, classic peers via
    # GetPeerRateLimits (wire.py "columnar peer hop").
    total = 0.0
    for metric in daemon.service.metrics.registry.collect():
        if metric.name == "gubernator_grpc_request_counts":
            for s in metric.samples:
                # _total only: the family also emits a _created sample
                # (a unix timestamp) per labelset, which must not be
                # summed as if it were a request count — it made this
                # helper order-dependent (correct only when an earlier
                # test had already created the owner's labelset).
                if s.name.endswith("_total") and s.labels.get("method") in (
                    "/pb.gubernator.PeersV1/GetPeerRateLimits",
                    "/pb.gubernator.PeersV1/GetPeerRateLimitsColumns",
                ):
                    total += s.value
    return total


def test_max_conn_age_option(monkeypatch):
    """GUBER_GRPC_MAX_CONN_AGE_SEC -> grpc.max_connection_age_ms server
    option (daemon.go:91-96)."""
    from gubernator_tpu.config import setup_daemon_config

    monkeypatch.setenv("GUBER_GRPC_MAX_CONN_AGE_SEC", "7")
    conf = setup_daemon_config()
    assert conf.grpc_max_conn_age_s == 7

    captured = {}
    import grpc as _grpc

    real_server = _grpc.server

    def spy(executor, options=None, **kw):
        captured["options"] = dict(options or [])
        return real_server(executor, options=options, **kw)

    monkeypatch.setattr(_grpc, "server", spy)
    from gubernator_tpu.grpc_server import GrpcServer
    from gubernator_tpu.service import ServiceConfig, V1Service

    svc = V1Service(ServiceConfig(cache_size=64))
    try:
        srv = GrpcServer(svc, "127.0.0.1:0", max_conn_age_s=7)
        srv.start().close()
        assert captured["options"]["grpc.max_connection_age_ms"] == 7000
        assert captured["options"]["grpc.max_connection_age_grace_ms"] == 30000
    finally:
        svc.close()
