"""Native JSON edge tests: the C++ parser/renderer (host_runtime.cpp
gt_json_parse / gt_json_render) against the Python path's behavior.

The parser must either produce EXACTLY what parse_columns would, or
return None so the gateway falls back — these tests pin both sides of
that contract, including the fallback triggers found in review
(duplicate "requests" keys, trailing garbage, escapes, floats).
"""

import json

import numpy as np
import pytest

from gubernator_tpu import native
from gubernator_tpu.native import PackedKeys

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native runtime unavailable"
)


def parse(obj_or_bytes):
    raw = (
        obj_or_bytes
        if isinstance(obj_or_bytes, bytes)
        else json.dumps(obj_or_bytes).encode()
    )
    return native.parse_json_batch(raw)


def test_basic_batch():
    pj = parse(
        {
            "requests": [
                {"name": "a", "uniqueKey": "k1", "hits": 2, "limit": 10,
                 "duration": 60000},
                {"name": "b", "unique_key": "k2", "hits": "3", "limit": "20",
                 "duration": "1000", "algorithm": "LEAKY_BUCKET",
                 "behavior": "NO_BATCHING"},
            ]
        }
    )
    assert pj is not None and pj.n == 2
    assert pj.algo.tolist() == [0, 1]
    assert pj.behavior.tolist() == [0, 1]
    assert pj.hits.tolist() == [2, 3]
    assert pj.limit.tolist() == [10, 20]
    assert pj.duration.tolist() == [60000, 1000]
    assert pj.err.tolist() == [0, 0]
    assert list(pj.hash_keys) == ["a_k1", "b_k2"]
    assert pj.name_at(1) == "b" and pj.unique_key_at(0) == "k1"


def test_validation_codes_match_reference_order():
    pj = parse(
        {
            "requests": [
                {"name": "a", "uniqueKey": ""},  # empty unique_key first
                {"name": "", "uniqueKey": ""},   # both empty: unique_key wins
                {"name": "", "uniqueKey": "k"},
                {"name": "a", "uniqueKey": "k"},
            ]
        }
    )
    assert pj.err.tolist() == [1, 1, 2, 0]


def test_behavior_numeric_and_enum_int():
    pj = parse({"requests": [{"name": "a", "uniqueKey": "k", "behavior": 18,
                              "algorithm": 1}]})
    assert pj.behavior.tolist() == [18]
    assert pj.algo.tolist() == [1]


def test_unknown_fields_skipped():
    pj = parse(
        {
            "requests": [
                {"name": "a", "uniqueKey": "k", "metadata": {"x": [1, {"y": 2}]},
                 "weird": None, "flag": True, "hits": 1}
            ]
        }
    )
    assert pj is not None and pj.n == 1 and pj.hits.tolist() == [1]


@pytest.mark.parametrize(
    "raw",
    [
        b'{"requests": [{"name": "a\\n", "uniqueKey": "k"}]}',  # escape in name
        b'{"requests": [{"name": "a", "uniqueKey": "k", "hits": 1.5}]}',  # float
        b'{"requests": [{"name": "a", "uniqueKey": "k", "behavior": ["GLOBAL"]}]}',  # list
        b'{"requests": []} junk',  # trailing garbage
        b'{} xx',  # trailing garbage on empty object
        b'{"requests": [{"name": "a", "uniqueKey": "x"}], "requests": [{"name": "b", "uniqueKey": "y"}]}',  # dup key
        b'{"requests": [{"name": "a" "uniqueKey": "k"}]}',  # malformed
        b'{"requests": [{"name": "a", "uniqueKey": "k", "hits": 99999999999999999999}]}',  # >18 digits
    ],
)
def test_fallback_triggers(raw):
    assert native.parse_json_batch(raw) is None


def test_bad_enum_token_reports_err_code():
    pj = parse({"requests": [{"name": "a", "uniqueKey": "k",
                              "algorithm": "NOT_A_BUCKET"}]})
    assert pj is not None and pj.err.tolist() == [3]
    pj = parse({"requests": [{"name": "a", "uniqueKey": "k",
                              "behavior": "NOT_A_FLAG"}]})
    assert pj is not None and pj.err.tolist() == [4]


def test_empty_shapes():
    assert parse({"requests": []}).n == 0
    assert parse({}).n == 0
    pj = parse({"other": 1})
    assert pj is not None and pj.n == 0


def test_render_matches_python_renderer():
    """Differential: the native render must serialize exactly what the
    Python renderer (gateway.render_columns) would."""
    from gubernator_tpu.gateway import render_columns
    from gubernator_tpu.service import ColumnarResult

    status = np.array([0, 1, 0], np.int32)
    limit = np.array([10, 20, 30], np.int64)
    remaining = np.array([9, 0, 3], np.int64)
    reset = np.array([111, 222, 1 << 40], np.int64)
    out = native.render_json(status, limit, remaining, reset, {})
    expected = render_columns(
        ColumnarResult(n=3, status=status, limit=limit,
                       remaining=remaining, reset_time=reset)
    )
    assert json.loads(out) == expected


def test_render_with_overrides():
    status = np.zeros(3, np.int32)
    z = np.zeros(3, np.int64)
    ov = {1: json.dumps({"error": "boom"}, separators=(",", ":")).encode()}
    out = native.render_json(status, z, z, z, ov)
    decoded = json.loads(out)
    assert decoded["responses"][1] == {"error": "boom"}
    assert decoded["responses"][0]["status"] == "UNDER_LIMIT"


def test_packed_keys_subset_concat():
    pk = PackedKeys(*native.pack_keys(["alpha", "b", "", "gamma"]))
    assert len(pk) == 4 and pk[2] == "" and pk[3] == "gamma"
    sub = pk.subset(np.array([3, 0]))
    assert list(sub) == ["gamma", "alpha"]
    cat = PackedKeys.concat([pk, sub])
    assert list(cat) == ["alpha", "b", "", "gamma", "gamma", "alpha"]


def test_parser_roundtrip_against_python_parse():
    """Differential: for a supported body the native columns must equal
    parse_columns' output exactly."""
    from gubernator_tpu.gateway import parse_columns

    items = [
        {"name": f"n{i}", "uniqueKey": f"k{i}", "hits": i, "limit": 100 + i,
         "duration": 1000 * i, "algorithm": "TOKEN_BUCKET" if i % 2 else 1,
         "behavior": i % 32}
        for i in range(1, 50)
    ]
    raw = json.dumps({"requests": items}).encode()
    pj = native.parse_json_batch(raw)
    cols = parse_columns(items)
    assert pj.n == len(cols)
    np.testing.assert_array_equal(pj.algo, cols.algorithm)
    np.testing.assert_array_equal(pj.behavior, cols.behavior)
    np.testing.assert_array_equal(pj.hits, cols.hits)
    np.testing.assert_array_equal(pj.limit, cols.limit)
    np.testing.assert_array_equal(pj.duration, cols.duration)
    assert list(pj.hash_keys) == [
        f"{n}_{u}" for n, u in zip(cols.names, cols.unique_keys)
    ]
