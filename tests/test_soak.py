"""Concurrency soak: many client threads drive a live cluster through
every behavior (plain, NO_BATCHING, GLOBAL, RESET_REMAINING, Gregorian,
MULTI_REGION) while peers churn, asserting nothing deadlocks, no request
errors, and per-key accounting stays sane.

The reference runs its whole suite under Go's race detector with real
concurrent daemons (Makefile:8-9, peer_client_test.go); Python has no
race detector, so this test leans on the same structure — real daemons,
real concurrency, shutdown mid-traffic — to surface deadlocks and
torn state as failures or hangs.
"""

import threading
import time

import pytest

from gubernator_tpu.client import V1Client
from gubernator_tpu.cluster import Cluster
from gubernator_tpu.types import (
    Algorithm,
    Behavior,
    GetRateLimitsRequest,
    RateLimitRequest,
)


@pytest.mark.slow
def test_cluster_soak_under_mixed_traffic():
    cl = Cluster().start_with(["", "", "", "dc-b"])
    stop = threading.Event()
    failures = []
    totals = {"requests": 0}
    lock = threading.Lock()

    behaviors = [
        0,
        Behavior.NO_BATCHING,
        Behavior.GLOBAL,
        Behavior.DURATION_IS_GREGORIAN,
        Behavior.MULTI_REGION,
    ]

    def worker(wid):
        client = V1Client(cl.daemons[wid % len(cl.daemons)].gateway.address,
                         timeout_s=30.0)
        i = 0
        while not stop.is_set():
            b = behaviors[i % len(behaviors)]
            duration = 2 if b == Behavior.DURATION_IS_GREGORIAN else 60_000
            reqs = [
                RateLimitRequest(
                    name="soak",
                    unique_key=f"k{(i + j) % 7}",
                    hits=1,
                    limit=1_000_000,
                    duration=duration,
                    algorithm=Algorithm.TOKEN_BUCKET if j % 2 else Algorithm.LEAKY_BUCKET,
                    behavior=b,
                )
                for j in range(4)
            ]
            try:
                resp = client.get_rate_limits(GetRateLimitsRequest(requests=reqs))
                for r in resp.responses:
                    if r.error:
                        with lock:
                            failures.append(r.error)
            except Exception as e:  # noqa: BLE001
                with lock:
                    failures.append(f"{type(e).__name__}: {e}")
            with lock:
                totals["requests"] += len(reqs)
            i += 1

    threads = [threading.Thread(target=worker, args=(w,)) for w in range(8)]
    try:
        for t in threads:
            t.start()
        deadline = time.monotonic() + 4.0
        churned = False
        while time.monotonic() < deadline:
            time.sleep(0.5)
            if not churned:
                # Membership churn mid-traffic: drop one daemon from
                # every peer list, then restore (SetPeers path).
                full = [d.peer_info for d in cl.daemons]
                for d in cl.daemons:
                    d.set_peers(full[:-1])
                time.sleep(0.3)
                for d in cl.daemons:
                    d.set_peers(full)
                churned = True
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=60)
            assert not t.is_alive(), "worker deadlocked"
        cl.stop()

    # Peer churn may transiently fail forwards to the dropped daemon;
    # anything systemic (every request failing, deadlock-adjacent
    # timeouts) must show as a high failure rate.
    with lock:
        assert totals["requests"] > 100, "soak made no progress"
        rate = len(failures) / max(totals["requests"], 1)
        assert rate < 0.05, f"{len(failures)}/{totals['requests']} failed; first: {failures[:3]}"
