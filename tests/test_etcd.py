"""EtcdPool discovery tests against the in-process fake etcd server
(reference etcd.go, which is exercised via docker-compose-etcd.yaml —
here the etcd cluster runs inside the test process).
"""

import time

import pytest

from gubernator_tpu.config import setup_daemon_config
from gubernator_tpu.etcd_pool import EtcdClient, EtcdPool, prefix_range_end
from gubernator_tpu.types import PeerInfo

from .fake_etcd import FakeEtcd


def wait_until(fn, timeout_s=5.0, every_s=0.02, msg="condition"):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if fn():
            return
        time.sleep(every_s)
    raise AssertionError(f"timed out waiting for {msg}")


@pytest.fixture
def server():
    s = FakeEtcd()
    yield s
    s.stop()


def make_pool(server, addr, updates, **kw):
    return EtcdPool(
        advertise=PeerInfo(grpc_address=addr),
        on_update=updates.append,
        endpoints=[server.address],
        **kw,
    )


def test_prefix_range_end():
    assert prefix_range_end(b"/gubernator/peers/") == b"/gubernator/peers0"
    assert prefix_range_end(b"a\xff") == b"b"
    assert prefix_range_end(b"\xff\xff") == b"\0"


def test_register_and_discover(server):
    u1, u2 = [], []
    p1 = make_pool(server, "10.0.0.1:81", u1)
    p2 = make_pool(server, "10.0.0.2:81", u2)
    try:
        assert server.keys() == [
            "/gubernator/peers/10.0.0.1:81",
            "/gubernator/peers/10.0.0.2:81",
        ]
        for u in (u1, u2):
            wait_until(
                lambda u=u: u and {p.grpc_address for p in u[-1]}
                == {"10.0.0.1:81", "10.0.0.2:81"},
                msg="both pools see both peers",
            )
    finally:
        p1.close()
        p2.close()


def test_close_deregisters(server):
    u1, u2 = [], []
    p1 = make_pool(server, "10.0.0.1:81", u1)
    p2 = make_pool(server, "10.0.0.2:81", u2)
    try:
        wait_until(lambda: u1 and len(u1[-1]) == 2, msg="join")
        p2.close()
        wait_until(
            lambda: u1 and [p.grpc_address for p in u1[-1]] == ["10.0.0.1:81"],
            msg="p2 deregistered on close",
        )
        assert server.keys() == ["/gubernator/peers/10.0.0.1:81"]
    finally:
        p1.close()
        p2.close()


def test_lease_expiry_removes_crashed_peer(server):
    """A peer that stops keepaliving (crash) must disappear when its
    lease TTL lapses (etcd.go:34 leaseTTL=30s; 1s here so the test
    observes expiry)."""
    u1, u2 = [], []
    p1 = make_pool(server, "10.0.0.1:81", u1, lease_ttl_s=1)
    p2 = make_pool(server, "10.0.0.2:81", u2, lease_ttl_s=1)
    try:
        wait_until(lambda: u1 and len(u1[-1]) == 2, msg="join")
        # Crash p2: kill its threads without deregistering.
        p2._stop.set()
        wait_until(
            lambda: u1 and [p.grpc_address for p in u1[-1]] == ["10.0.0.1:81"],
            msg="lease expiry removes crashed peer",
        )
    finally:
        p1.close()
        p2.close()


def test_keepalive_loss_triggers_reregistration(server):
    """Server-side lease revocation ends the keepalive stream; the pool
    must re-register (etcd.go:266-295)."""
    u1, u2 = [], []
    p1 = make_pool(server, "10.0.0.1:81", u1, backoff_s=0.05, lease_ttl_s=1)
    p2 = make_pool(server, "10.0.0.2:81", u2)
    try:
        wait_until(lambda: u2 and len(u2[-1]) == 2, msg="join")
        server.revoke_lease(p1._lease_id)
        wait_until(
            lambda: u2 and [p.grpc_address for p in u2[-1]] == ["10.0.0.2:81"],
            msg="revocation removes p1",
        )
        wait_until(
            lambda: u2 and len(u2[-1]) == 2,
            msg="p1 re-registers after keepalive loss",
        )
    finally:
        p1.close()
        p2.close()


def test_watch_survives_malformed_peer_value(server):
    u1 = []
    p1 = make_pool(server, "10.0.0.1:81", u1)
    try:
        client = EtcdClient([server.address])
        client.put("/gubernator/peers/bogus", b"not json{{")
        client.put(
            "/gubernator/peers/10.0.0.3:81",
            b'{"grpcAddress": "10.0.0.3:81"}',
        )
        wait_until(
            lambda: u1
            and {p.grpc_address for p in u1[-1]} == {"10.0.0.1:81", "10.0.0.3:81"},
            msg="valid peer lands despite malformed sibling",
        )
        client.close()
    finally:
        p1.close()


def test_custom_key_prefix(server):
    u1 = []
    p1 = make_pool(server, "10.0.0.1:81", u1, key_prefix="/custom-peers/")
    try:
        assert server.keys() == ["/custom-peers/10.0.0.1:81"]
    finally:
        p1.close()


def test_endpoint_failover(server):
    """A dead first endpoint must not prevent registration when a later
    endpoint is healthy (the Go client balances across endpoints;
    rotate() is the explicit equivalent)."""
    u1 = []
    p1 = EtcdPool(
        advertise=PeerInfo(grpc_address="10.0.0.1:81"),
        on_update=u1.append,
        endpoints=["127.0.0.1:1", server.address],  # first is dead
    )
    try:
        assert server.keys() == ["/gubernator/peers/10.0.0.1:81"]
        wait_until(lambda: u1 and len(u1[-1]) == 1, msg="registered via failover")
    finally:
        p1.close()


def test_keepalive_ttl_zero_triggers_reregistration(server):
    """Real etcd answers an expired lease with TTL=0 on an open stream;
    the pool must treat that as keepalive loss and re-register."""
    u2 = []
    p1 = make_pool(server, "10.0.0.1:81", [], backoff_s=0.05, lease_ttl_s=1)
    p2 = make_pool(server, "10.0.0.2:81", u2)
    try:
        wait_until(lambda: u2 and len(u2[-1]) == 2, msg="join")
        # Expire p1's lease server-side WITHOUT deleting via revoke_lease
        # bookkeeping: drop the lease record only, so keepalives get
        # TTL=0 while the key initially remains.
        with server._lock:
            server._leases.pop(p1._lease_id, None)
        wait_until(
            lambda: len(server.keys()) == 2 and p1._lease_id in server._leases,
            timeout_s=5.0,
            msg="p1 re-registered with a fresh lease",
        )
    finally:
        p1.close()
        p2.close()


def test_etcd_env_parsing():
    conf = setup_daemon_config(
        env={
            "GUBER_PEER_DISCOVERY_TYPE": "etcd",
            "GUBER_ETCD_ENDPOINTS": "e1:2379, e2:2379",
            "GUBER_ETCD_KEY_PREFIX": "/my-peers",
            "GUBER_ETCD_ADVERTISE_ADDRESS": "10.1.1.1:81",
        }
    )
    assert conf.etcd_endpoints == ["e1:2379", "e2:2379"]
    assert conf.etcd_key_prefix == "/my-peers"
    assert conf.etcd_advertise_address == "10.1.1.1:81"


# ---------------------------------------------------------------------
# TLS + username/password auth (config.go:309-310, setupEtcdTLS
# config.go:390-433): a secured etcd cluster must be usable for
# discovery.
# ---------------------------------------------------------------------


@pytest.fixture
def tls_server(tmp_path):
    import grpc

    from gubernator_tpu import tls as gtls

    ca_crt, ca_key = gtls.self_ca(str(tmp_path))
    crt, key = gtls.self_cert(str(tmp_path), ca_crt, ca_key, name="etcd")
    with open(key, "rb") as f:
        key_pem = f.read()
    with open(crt, "rb") as f:
        crt_pem = f.read()
    creds = grpc.ssl_server_credentials([(key_pem, crt_pem)])
    s = FakeEtcd(tls_creds=creds, auth_users={"guber": "s3cret"})
    s.ca_file = ca_crt
    yield s
    s.stop()


class _EtcdConf:
    """The GUBER_ETCD_* surface as credentials_from_config consumes it."""

    def __init__(self, server, **kw):
        self.etcd_endpoints = [f"localhost:{server.port}"]
        self.etcd_tls_ca = kw.get("ca", "")
        self.etcd_tls_cert = kw.get("cert", "")
        self.etcd_tls_key = kw.get("key", "")
        self.etcd_tls_enable = kw.get("enable", False)
        self.etcd_tls_skip_verify = kw.get("skip", False)


def test_tls_auth_register_and_discover(tls_server):
    from gubernator_tpu.etcd_pool import credentials_from_config

    creds = credentials_from_config(_EtcdConf(tls_server, ca=tls_server.ca_file))
    assert creds is not None
    updates = []
    pool = EtcdPool(
        advertise=PeerInfo(grpc_address="10.1.0.1:81"),
        on_update=updates.append,
        endpoints=[f"localhost:{tls_server.port}"],
        credentials=creds,
        username="guber",
        password="s3cret",
    )
    try:
        wait_until(lambda: updates and len(updates[-1]) == 1, msg="peer update")
        assert updates[-1][0].grpc_address == "10.1.0.1:81"
    finally:
        pool.close()


def test_auth_rejects_bad_password(tls_server):
    from gubernator_tpu.etcd_pool import credentials_from_config

    creds = credentials_from_config(_EtcdConf(tls_server, ca=tls_server.ca_file))
    with pytest.raises(Exception):
        EtcdPool(
            advertise=PeerInfo(grpc_address="10.1.0.2:81"),
            on_update=lambda *_: None,
            endpoints=[f"localhost:{tls_server.port}"],
            credentials=creds,
            username="guber",
            password="wrong",
        )


def test_auth_required_without_token(tls_server):
    from gubernator_tpu.etcd_pool import credentials_from_config

    creds = credentials_from_config(_EtcdConf(tls_server, ca=tls_server.ca_file))
    with pytest.raises(Exception):
        EtcdPool(
            advertise=PeerInfo(grpc_address="10.1.0.3:81"),
            on_update=lambda *_: None,
            endpoints=[f"localhost:{tls_server.port}"],
            credentials=creds,
        )


def test_etcd_env_surface(monkeypatch, tmp_path):
    """GUBER_ETCD_USER/PASSWORD/TLS_* parse into DaemonConfig."""
    monkeypatch.setenv("GUBER_ETCD_USER", "u1")
    monkeypatch.setenv("GUBER_ETCD_PASSWORD", "p1")
    monkeypatch.setenv("GUBER_ETCD_TLS_ENABLE", "true")
    ca = tmp_path / "ca.pem"
    ca.write_text("x")
    monkeypatch.setenv("GUBER_ETCD_TLS_CA", str(ca))
    conf = setup_daemon_config()
    assert conf.etcd_user == "u1"
    assert conf.etcd_password == "p1"
    assert conf.etcd_tls_enable is True
    assert conf.etcd_tls_ca == str(ca)


def test_watch_resume_across_compaction(server):
    """Real-etcd drift point (etcd.go:174-220 vs mvcc compaction): a
    watch whose resume revision has been compacted is answered
    created-then-CANCELED with compact_revision set; the pool must
    fall back to a fresh list+watch and converge on membership changes
    that happened behind the compaction.  The fake implements the
    etcdserverpb Compact RPC + cancel surface; test_etcd_real.py runs
    the same scenario against a real etcd when one is available."""
    u1 = []
    p1 = make_pool(server, "10.0.0.1:81", u1, backoff_s=0.05)
    try:
        wait_until(lambda: u1 and len(u1[-1]) == 1, msg="self visible")

        # Second peer registers directly (no pool): its PUT advances the
        # revision past p1's watch position after we compact.
        c = EtcdClient(endpoints=[server.address])
        lease = c.lease_grant(30)
        c.put("/gubernator/peers/10.0.0.9:81",
              b'{"grpcAddress": "10.0.0.9:81"}', lease)
        wait_until(lambda: u1 and len(u1[-1]) == 2, msg="peer 2 via watch")

        # Compact everything, then kill p1's live stream so it must
        # re-create a watch.  If the pool tried to resume from its old
        # revision it would get canceled+compact_revision — either way
        # it must recover membership.
        c.compact(server._revision)
        server.cancel_watchers()
        c.put("/gubernator/peers/10.0.0.10:81",
              b'{"grpcAddress": "10.0.0.10:81"}', lease)
        wait_until(
            lambda: u1 and {p.grpc_address for p in u1[-1]}
            == {"10.0.0.1:81", "10.0.0.9:81", "10.0.0.10:81"},
            msg="membership recovered after compaction",
        )
        c.close()
    finally:
        p1.close()


def test_stale_watch_canceled_with_compact_revision(server):
    """The wire surface itself: a Watch created below the compact
    revision gets canceled=True + compact_revision (the exact etcd v3
    behavior the pool's canceled branch consumes)."""
    import threading

    c = EtcdClient(endpoints=[server.address])
    lease = c.lease_grant(30)
    for i in range(4):
        c.put(f"/gubernator/peers/10.0.0.{i}:81", b"{}", lease)
    c.compact(server._revision)

    stream, done = c.watch_prefix("/gubernator/peers/", 1, threading.Event())
    resps = []
    for resp in stream:
        resps.append(resp)
        if resp.canceled:
            break
    done.set()
    assert resps[0].created
    assert resps[-1].canceled
    assert resps[-1].compact_revision == server._revision
    c.close()
