"""XLA/device telemetry plane (gubernator_tpu/telemetry.py).

Covers: backend-compile counting via the jax.monitoring listener with
program-label attribution, the warmup fence (compiles before
mark_steady are warmup; after it they are steady-state recompiles),
recompile-storm detection firing the flight-recorder event, per-program
execution timings drained per scrape, device snapshots, the
GUBER_XLA_TELEMETRY=0 no-op contract, the metrics observer, and the
GET /debug/device + /debug/status surfaces on a live daemon.
"""

from __future__ import annotations

import json
import urllib.request

import jax
import numpy as np
import pytest

from gubernator_tpu import telemetry, tracing


@pytest.fixture(autouse=True)
def _clean_plane():
    telemetry.set_enabled(True)
    telemetry.reset()
    tracing.reset()
    yield
    telemetry.reset()
    tracing.reset()


def _fresh_jit():
    """A jit whose every distinct input shape forces one backend
    compile (closure identity makes the cache per-call-site)."""
    salt = np.random.randn()
    return jax.jit(lambda x: x * 2.0 + salt)


def test_compiles_attributed_to_program_label():
    fn = _fresh_jit()
    before = telemetry.compile_count()
    with telemetry.program("test:labelled"):
        fn(np.arange(3, dtype=np.float32))
    snap = telemetry.compile_snapshot()
    assert telemetry.compile_count() == before + 1
    assert snap["test:labelled"]["count"] == 1
    assert snap["test:labelled"]["total_s"] > 0


def test_unlabelled_compiles_bucketed():
    fn = _fresh_jit()
    fn(np.arange(4, dtype=np.float32))
    snap = telemetry.compile_snapshot()
    assert snap["unlabeled"]["count"] >= 1


def test_warmup_fence_and_steady_recompiles():
    fn = _fresh_jit()
    telemetry.begin_warmup()
    with telemetry.program("test:warm"):
        fn(np.arange(2, dtype=np.float32))  # warmup compile
    assert telemetry.steady_recompile_count() == 0
    telemetry.mark_steady()
    with telemetry.program("test:churn"):
        fn(np.arange(5, dtype=np.float32))  # shape churn after warmup
    assert telemetry.steady_recompile_count() == 1
    assert telemetry.compile_snapshot()["test:churn"]["steady_recompiles"] == 1
    # Re-running the SAME shape hits the jit cache: no new compile.
    with telemetry.program("test:churn"):
        fn(np.arange(5, dtype=np.float32))
    assert telemetry.steady_recompile_count() == 1


def test_lazy_labels_exempt_from_steady_and_storm():
    """Programs warmup DELIBERATELY defers (wide wires, the reshard
    drain/commit pair) are declared lazy at their call sites: their
    post-steady compiles count per label but never feed the
    steady-recompile counter or the storm trip."""
    fn = _fresh_jit()
    telemetry.mark_steady()
    for n in range(3, 3 + telemetry.STORM_THRESHOLD + 1):
        with telemetry.program("test:lazy", lazy=True):
            fn(np.arange(n, dtype=np.float32))
    assert telemetry.steady_recompile_count() == 0
    snap = telemetry.compile_snapshot()
    assert snap["test:lazy"]["count"] >= telemetry.STORM_THRESHOLD
    assert snap["test:lazy"]["steady_recompiles"] == 0
    kinds = [e["kind"] for e in tracing.events_snapshot()]
    assert "recompile-storm" not in kinds


def test_recompile_storm_fires_flight_recorder_event():
    fn = _fresh_jit()
    telemetry.mark_steady()
    for n in range(2, 2 + telemetry.STORM_THRESHOLD + 1):
        with telemetry.program("test:storm"):
            fn(np.arange(n, dtype=np.float32))
    kinds = [e["kind"] for e in tracing.events_snapshot()]
    assert "recompile-storm" in kinds
    assert telemetry.snapshot()["recompileStorms"] >= 1


def test_disabled_is_noop():
    telemetry.set_enabled(False)
    fn = _fresh_jit()
    ctx = telemetry.program("test:off")
    assert ctx is telemetry._NOOP  # the shared no-op, no allocation
    with ctx:
        fn(np.arange(7, dtype=np.float32))
    assert telemetry.compile_count() == 0
    assert telemetry.device_snapshot() == []
    telemetry.note_program_created("test:off")
    assert telemetry.snapshot()["programsCreated"] == {}


def test_exec_stats_drained_per_scrape():
    with telemetry.program("test:exec"):
        pass
    with telemetry.program("test:exec"):
        pass
    stats = telemetry.take_exec_stats()
    assert stats["test:exec"][0] == 2
    assert telemetry.take_exec_stats() == {}  # drained


def test_device_snapshot_reports_live_buffers():
    arr = jax.device_put(np.arange(1024, dtype=np.float32))
    rows = telemetry.device_snapshot()
    assert rows, "expected at least one device row"
    dev = str(next(iter(arr.devices())))
    row = next(r for r in rows if r["device"] == dev)
    assert row["live_buffers"] >= 1
    assert row["live_bytes"] >= arr.nbytes
    del arr


def test_metrics_observer_exports_families():
    from gubernator_tpu.metrics import Metrics

    fn = _fresh_jit()
    with telemetry.program("test:metrics"):
        fn(np.arange(11, dtype=np.float32))
    m = Metrics()
    m.observe_telemetry()
    rendered = m.render().decode()
    assert 'gubernator_xla_compiles_total{program="test:metrics"}' in rendered
    assert "gubernator_xla_program_runs" in rendered
    assert "gubernator_device_live_buffers" in rendered


def test_program_label_nesting_inner_wins():
    fn = _fresh_jit()
    with telemetry.program("outer"):
        with telemetry.program("inner"):
            fn(np.arange(13, dtype=np.float32))
    snap = telemetry.compile_snapshot()
    assert "inner" in snap and "outer" not in snap


@pytest.mark.slow
def test_debug_device_endpoint_live_daemon():
    from gubernator_tpu.cluster import Cluster, fast_test_behaviors

    cl = Cluster().start_with([""], behaviors=fast_test_behaviors())
    try:
        addr = cl.daemons[0].gateway.address
        with urllib.request.urlopen(
            f"http://{addr}/debug/device", timeout=10
        ) as r:
            doc = json.loads(r.read())
        assert doc["enabled"] is True
        assert doc["steady"] is True  # daemon warmup marked steady
        # compileTotal can legitimately be 0 here: in a shared test
        # process the jit caches are already warm, so daemon startup
        # may compile nothing — assert the surface, not cold-start luck.
        assert doc["compileTotal"] >= 0
        assert isinstance(doc["devices"], list)
        with urllib.request.urlopen(
            f"http://{addr}/debug/status", timeout=10
        ) as r:
            status = json.loads(r.read())
        assert "xla" in status and status["xla"]["enabled"] is True
    finally:
        cl.stop()


def test_dispatch_launch_labels_programs():
    """The pipeline's launch site declares mesh/shard program identity
    (models/shard.py _program_label) — drive one columnar batch and
    expect a labelled execution row."""
    from gubernator_tpu.parallel.mesh import MeshBucketStore

    store = MeshBucketStore(capacity_per_shard=64, g_capacity=64)
    try:
        telemetry.take_exec_stats()  # clear
        keys = [f"tk{i}" for i in range(8)]
        n = len(keys)
        store.apply_columns(
            keys,
            np.zeros(n, np.int32), np.zeros(n, np.int32),
            np.ones(n, np.int64), np.full(n, 100, np.int64),
            np.full(n, 60_000, np.int64), 1_700_000_000_000,
        )
        stats = telemetry.take_exec_stats()
        assert any(k.startswith("mesh:dispatch:") for k in stats), stats
    finally:
        store.close() if hasattr(store, "close") else None
