"""Freezable millisecond clock.

The reference freezes time in tests via holster `clock.Freeze`/`Advance`
(functional_test.go:108-167 et al.).  Because our kernels take `now_ms`
as an explicit argument, freezing is just swapping the source the service
layer reads from.
"""

from __future__ import annotations

import datetime as _dt
import threading
import time


class Clock:
    """Wall clock by default; freeze()/advance() for deterministic tests."""

    def __init__(self):
        self._lock = threading.Lock()
        self._frozen_ms: "int | None" = None

    def now_ms(self) -> int:
        """Milliseconds since epoch (reference `MillisecondNow`, cache.go:133-135)."""
        with self._lock:
            if self._frozen_ms is not None:
                return self._frozen_ms
        return time.time_ns() // 1_000_000

    def now_dt(self) -> _dt.datetime:
        """Timezone-aware datetime view of now (for Gregorian math)."""
        return _dt.datetime.fromtimestamp(self.now_ms() / 1000.0, tz=_dt.timezone.utc)

    def freeze(self, at_ms: "int | None" = None) -> None:
        with self._lock:
            self._frozen_ms = at_ms if at_ms is not None else time.time_ns() // 1_000_000

    def advance(self, delta_ms: int) -> None:
        with self._lock:
            if self._frozen_ms is None:
                raise RuntimeError("advance() requires a frozen clock")
            self._frozen_ms += delta_ms

    def unfreeze(self) -> None:
        with self._lock:
            self._frozen_ms = None

    @property
    def frozen(self) -> bool:
        with self._lock:
            return self._frozen_ms is not None


# Process-default clock, shared by daemon components unless overridden.
DEFAULT_CLOCK = Clock()
