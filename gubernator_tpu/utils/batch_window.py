"""Shared batching-window worker (the reference's Interval-drained
queue shape, peer_client.go:272-312): the first enqueued item opens a
`wait_s` window; the batch flushes when `limit` items collect or the
window closes.  Used by the peer-forward client (PeerClient) and the
ingress-local coalescer (service.LocalBatcher) so the drain semantics
live in exactly one place.

Two extensions over the reference shape:

* `weigh` — items can count for more than one unit against `limit`
  (the columnar peer coalescer submits whole multi-lane sub-batches;
  the limit bounds LANES per flush, not submissions).

* `adaptive` — the window sizes itself to the measured arrival rate:
  effective wait = min(wait_s, limit / rate), where rate is an EMA of
  lanes/second measured across flush cycles (idle gaps included, so a
  traffic lull decays the estimate).  At high arrival rates the batch
  fills long before wait_s anyway, so shrinking the wait cuts the
  latency of the LAST window of a burst — the one that would otherwise
  sit out the full wait with a partial batch — while a trickle still
  gets the full wait_s of coalescing.  `wait_s` is the upper bound
  always.

* `cap_s` — a latency-SLO HARD CEILING on the effective wait
  (GUBER_LATENCY_TARGET_MS binding, architecture.md "Express lane"):
  when set, occupancy mode yields to latency mode — whatever wait the
  static/adaptive sizing picked is clamped to `cap_s`, so no
  submission can spend more than the configured slice of its latency
  budget coalescing.  None (the default) keeps the occupancy-driven
  window untouched.

`stop()` joins the worker FIRST and then drains + flushes anything
still queued — including items that raced past a closing check into
the queue — so no submitted item is ever silently dropped.
"""

from __future__ import annotations

import threading
import time
from queue import Empty, Queue
from typing import Callable, List, Optional


class BatchWindow:
    # EMA smoothing for the adaptive arrival-rate estimate: 0.5 tracks
    # a rate step within ~2 flush cycles without pinning to one
    # outlier window.
    RATE_EMA = 0.5

    def __init__(
        self,
        flush: Callable[[List], None],
        wait_s: float,
        limit: int,
        lazy: bool = False,
        adaptive: bool = False,
        weigh: Optional[Callable[[object], int]] = None,
        cap_s: Optional[float] = None,
    ):
        self._flush = flush
        self.wait_s = wait_s
        self.limit = limit
        self.adaptive = adaptive
        self.cap_s = cap_s
        self._weigh = weigh
        self._rate: float = 0.0  # EMA weighted-items/s (adaptive only)
        self._last_flush_t: Optional[float] = None
        self._queue: "Queue" = Queue()
        self._stopped = threading.Event()
        self._worker: "threading.Thread | None" = None
        self._worker_lock = threading.Lock()
        if not lazy:
            self._ensure_worker()

    @property
    def stopped(self) -> bool:
        return self._stopped.is_set()

    def submit(self, item) -> None:
        """Enqueue one item.  Items enqueued before (or racing with, or
        even after) stop() are still flushed: a post-stop submit drains
        the queue itself, since no worker remains to do it."""
        self._ensure_worker()
        self._queue.put(item)
        if self._stopped.is_set():
            self._drain_flush()

    def _ensure_worker(self) -> None:
        if self._stopped.is_set():
            return
        with self._worker_lock:
            if self._worker is None or not self._worker.is_alive():
                self._worker = threading.Thread(target=self._run, daemon=True)
                self._worker.start()

    def _weight(self, item) -> int:
        return 1 if self._weigh is None else self._weigh(item)

    def effective_wait_s(self) -> float:
        """The wait the NEXT window will use (exposed for tests/metrics)."""
        if not self.adaptive or self._rate <= 0:
            wait = self.wait_s
        else:
            wait = min(self.wait_s, self.limit / self._rate)
        if self.cap_s is not None:
            wait = min(wait, self.cap_s)
        return wait

    def _run(self) -> None:
        while not self._stopped.is_set():
            try:
                first = self._queue.get(timeout=0.05)
            except Empty:
                continue
            t_first = time.monotonic()
            batch = [first]
            count = self._weight(first)
            deadline = t_first + self.effective_wait_s()
            while count < self.limit:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                try:
                    item = self._queue.get(timeout=remaining)
                except Empty:
                    break
                batch.append(item)
                count += self._weight(item)
            if self.adaptive:
                now = time.monotonic()
                # Rate over the whole inter-flush period (idle time
                # between windows included), so the estimate decays
                # when traffic pauses instead of freezing at burst
                # level.
                span = now - (self._last_flush_t
                              if self._last_flush_t is not None else t_first)
                self._last_flush_t = now
                inst = count / max(span, 1e-6)
                self._rate = (
                    inst if self._rate == 0.0
                    else (1 - self.RATE_EMA) * self._rate + self.RATE_EMA * inst
                )
            self._flush(batch)

    def stop(self, timeout_s: float = 5.0) -> None:
        """Stop the worker, then drain-and-flush every leftover item."""
        self._stopped.set()
        worker = self._worker
        if worker is not None and worker.is_alive():
            worker.join(timeout=timeout_s)
        self._drain_flush()

    def _drain_flush(self) -> None:
        leftovers = []
        while True:
            try:
                leftovers.append(self._queue.get_nowait())
            except Empty:
                break
        if leftovers:
            self._flush(leftovers)
