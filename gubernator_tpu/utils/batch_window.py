"""Shared batching-window worker (the reference's Interval-drained
queue shape, peer_client.go:272-312): the first enqueued item opens a
`wait_s` window; the batch flushes when `limit` items collect or the
window closes.  Used by the peer-forward client (PeerClient) and the
ingress-local coalescer (service.LocalBatcher) so the drain semantics
live in exactly one place.

`stop()` joins the worker FIRST and then drains + flushes anything
still queued — including items that raced past a closing check into
the queue — so no submitted item is ever silently dropped.
"""

from __future__ import annotations

import threading
import time
from queue import Empty, Queue
from typing import Callable, List


class BatchWindow:
    def __init__(
        self,
        flush: Callable[[List], None],
        wait_s: float,
        limit: int,
        lazy: bool = False,
    ):
        self._flush = flush
        self.wait_s = wait_s
        self.limit = limit
        self._queue: "Queue" = Queue()
        self._stopped = threading.Event()
        self._worker: "threading.Thread | None" = None
        self._worker_lock = threading.Lock()
        if not lazy:
            self._ensure_worker()

    @property
    def stopped(self) -> bool:
        return self._stopped.is_set()

    def submit(self, item) -> None:
        """Enqueue one item.  Items enqueued before (or racing with, or
        even after) stop() are still flushed: a post-stop submit drains
        the queue itself, since no worker remains to do it."""
        self._ensure_worker()
        self._queue.put(item)
        if self._stopped.is_set():
            self._drain_flush()

    def _ensure_worker(self) -> None:
        if self._stopped.is_set():
            return
        with self._worker_lock:
            if self._worker is None or not self._worker.is_alive():
                self._worker = threading.Thread(target=self._run, daemon=True)
                self._worker.start()

    def _run(self) -> None:
        while not self._stopped.is_set():
            try:
                first = self._queue.get(timeout=0.05)
            except Empty:
                continue
            batch = [first]
            deadline = time.monotonic() + self.wait_s
            while len(batch) < self.limit:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                try:
                    batch.append(self._queue.get(timeout=remaining))
                except Empty:
                    break
            self._flush(batch)

    def stop(self, timeout_s: float = 5.0) -> None:
        """Stop the worker, then drain-and-flush every leftover item."""
        self._stopped.set()
        worker = self._worker
        if worker is not None and worker.is_alive():
            worker.join(timeout=timeout_s)
        self._drain_flush()

    def _drain_flush(self) -> None:
        leftovers = []
        while True:
            try:
                leftovers.append(self._queue.get_nowait())
            except Empty:
                break
        if leftovers:
            self._flush(leftovers)
