"""Gregorian calendar interval math (reference: interval.go:72-146).

All functions take a timezone-aware (or naive = local) datetime `now` and
return milliseconds.  Computed host-side, before kernel entry: the kernels
only see a precomputed `greg_expire` / `greg_duration` per request
(reference computes these inline at algorithms.go:90-95,140-145,216-232).

Bug-compat note: the reference's `GregorianDuration` for months/years
computes `end.UnixNano() - begin.UnixNano()/1000000` — nanoseconds minus
milliseconds due to operator precedence (interval.go:97,103).  Since that
value feeds the observable leaky-bucket leak rate under
DURATION_IS_GREGORIAN, we reproduce it exactly rather than "fixing" it.
"""

from __future__ import annotations

import datetime as _dt

# Duration enum values (interval.go:72-79).
GREGORIAN_MINUTES = 0
GREGORIAN_HOURS = 1
GREGORIAN_DAYS = 2
GREGORIAN_WEEKS = 3
GREGORIAN_MONTHS = 4
GREGORIAN_YEARS = 5

ERR_WEEKS = "`Duration = GregorianWeeks` not yet supported; consider making a PR!`"
ERR_INVALID = (
    "behavior DURATION_IS_GREGORIAN is set; but `Duration` is not a valid gregorian interval"
)


class GregorianError(ValueError):
    pass


def _epoch_seconds(dt: _dt.datetime) -> int:
    # All boundaries used here are whole seconds; float timestamp() is exact
    # for integer epoch-second values in this range.
    return int(dt.timestamp())


def _next_month(dt: _dt.datetime) -> _dt.datetime:
    y, m = dt.year, dt.month
    if m == 12:
        y, m = y + 1, 1
    else:
        m += 1
    return dt.replace(year=y, month=m)


def _boundary_seconds(now: _dt.datetime, d: int) -> int:
    """Epoch seconds of the *next* interval boundary (start of next interval)."""
    if d == GREGORIAN_MINUTES:
        trunc = now.replace(second=0, microsecond=0)
        return _epoch_seconds(trunc) + 60
    if d == GREGORIAN_HOURS:
        trunc = now.replace(minute=0, second=0, microsecond=0)
        return _epoch_seconds(trunc) + 3600
    if d == GREGORIAN_DAYS:
        trunc = now.replace(hour=0, minute=0, second=0, microsecond=0)
        return _epoch_seconds(trunc + _dt.timedelta(days=1))
    if d == GREGORIAN_MONTHS:
        begin = now.replace(day=1, hour=0, minute=0, second=0, microsecond=0)
        return _epoch_seconds(_next_month(begin))
    if d == GREGORIAN_YEARS:
        begin = now.replace(month=1, day=1, hour=0, minute=0, second=0, microsecond=0)
        return _epoch_seconds(begin.replace(year=begin.year + 1))
    if d == GREGORIAN_WEEKS:
        raise GregorianError(ERR_WEEKS)
    raise GregorianError(ERR_INVALID)


def gregorian_expiration(now: _dt.datetime, d: int) -> int:
    """End of the current Gregorian interval, in ms since epoch.

    Matches reference `GregorianExpiration` (interval.go:115-146): the
    boundary minus one nanosecond, floored to milliseconds — i.e.
    `boundary_seconds * 1000 - 1`.
    """
    return _boundary_seconds(now, d) * 1000 - 1


def gregorian_duration(now: _dt.datetime, d: int) -> int:
    """Entire duration of the Gregorian interval (interval.go:82-107).

    Minutes/hours/days are constants in ms.  Months/years reproduce the
    reference's `end_ns - begin_ms` formula (see module docstring).
    """
    if d == GREGORIAN_MINUTES:
        return 60_000
    if d == GREGORIAN_HOURS:
        return 3_600_000
    if d == GREGORIAN_DAYS:
        return 86_400_000
    if d == GREGORIAN_WEEKS:
        raise GregorianError(ERR_WEEKS)
    if d == GREGORIAN_MONTHS:
        begin = now.replace(day=1, hour=0, minute=0, second=0, microsecond=0)
        begin_s = _epoch_seconds(begin)
        end_ns = _epoch_seconds(_next_month(begin)) * 1_000_000_000 - 1
        return end_ns - begin_s * 1000
    if d == GREGORIAN_YEARS:
        begin = now.replace(month=1, day=1, hour=0, minute=0, second=0, microsecond=0)
        begin_s = _epoch_seconds(begin)
        end_ns = _epoch_seconds(begin.replace(year=begin.year + 1)) * 1_000_000_000 - 1
        return end_ns - begin_s * 1000
    raise GregorianError(ERR_INVALID)
