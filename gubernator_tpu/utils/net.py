"""Network discovery helpers (reference net.go).

`resolve_host_ip` mirrors ResolveHostIP (net.go:12-33): when a daemon
binds a wildcard address (0.0.0.0 / ::), the advertised peer address
must be a routable interface IP, or every peer would "forward" to its
own loopback and the ring would never agree on owners.
"""

from __future__ import annotations

import socket


def discover_ip() -> str:
    """Best non-loopback IPv4 of this host (net.go:58-67).

    The UDP connect never sends a packet; it only asks the kernel which
    source interface routes toward a public address.
    """
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.connect(("8.8.8.8", 80))
            ip = s.getsockname()[0]
            if not ip.startswith("127."):
                return ip
    except OSError:
        pass
    try:
        for info in socket.getaddrinfo(socket.gethostname(), None, socket.AF_INET):
            ip = info[4][0]
            if not ip.startswith("127."):
                return ip
    except OSError:
        pass
    return "127.0.0.1"


def resolve_host_ip(addr: str) -> str:
    """Replace a wildcard host in 'host:port' with a routable IP
    (net.go:12-33)."""
    host, sep, port = addr.rpartition(":")
    if not sep:
        return addr
    if host in ("", "0.0.0.0", "::", "[::]"):
        return f"{discover_ip()}:{port}"
    return addr
