"""Network discovery helpers (reference net.go).

`resolve_host_ip` mirrors ResolveHostIP (net.go:12-33): when a daemon
binds a wildcard address (0.0.0.0 / ::), the advertised peer address
must be a routable interface IP, or every peer would "forward" to its
own loopback and the ring would never agree on owners.
"""

from __future__ import annotations

import socket


def discover_ip() -> str:
    """Best non-loopback IPv4 of this host (net.go:58-67).

    The UDP connect never sends a packet; it only asks the kernel which
    source interface routes toward a public address.
    """
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.connect(("8.8.8.8", 80))
            ip = s.getsockname()[0]
            if not ip.startswith("127."):
                return ip
    except OSError:
        pass
    try:
        for info in socket.getaddrinfo(socket.gethostname(), None, socket.AF_INET):
            ip = info[4][0]
            if not ip.startswith("127."):
                return ip
    except OSError:
        pass
    return "127.0.0.1"


def discover_network_addresses() -> "tuple[list[str], list[str]]":
    """Every non-loopback IPv4 interface address on this host plus the
    DNS names they reverse-resolve to (net.go:70-106) — the SAN set for
    AutoTLS self-signed certificates.  Interface enumeration uses the
    Linux SIOCGIFADDR ioctl; other platforms degrade to the
    route-probed address from discover_ip()."""
    ips = set()
    try:
        import fcntl
        import struct

        SIOCGIFADDR = 0x8915
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            for _, ifname in socket.if_nameindex():
                try:
                    packed = fcntl.ioctl(
                        s.fileno(), SIOCGIFADDR,
                        struct.pack("256s", ifname[:15].encode()),
                    )
                except OSError:
                    continue  # interface without an IPv4 address
                ip = socket.inet_ntoa(packed[20:24])
                if not ip.startswith("127."):
                    ips.add(ip)
    except (ImportError, OSError):
        pass
    fallback = discover_ip()
    if fallback != "127.0.0.1":
        ips.add(fallback)
    # Reverse-DNS with a hard deadline: a broken resolver must not add
    # its full timeout+retry cycle per IP to daemon startup (this runs
    # inside AutoTLS cert generation).  Plain DAEMON threads, not a
    # ThreadPoolExecutor: concurrent.futures' atexit hook joins its
    # non-daemon workers, so one stuck gethostbyaddr would hang process
    # shutdown; daemon threads genuinely die with the process.
    names: set = set()
    if ips:
        import threading

        lock = threading.Lock()

        def rdns(ip):
            try:
                name = socket.gethostbyaddr(ip)[0]
            except OSError:
                return
            with lock:
                names.add(name)

        threads = [
            threading.Thread(target=rdns, args=(ip,), daemon=True) for ip in ips
        ]
        for t in threads:
            t.start()
        deadline = 1.5
        import time

        end = time.monotonic() + deadline
        for t in threads:
            t.join(timeout=max(end - time.monotonic(), 0))
        with lock:
            snapshot = set(names)
        return sorted(ips), sorted(snapshot)
    return sorted(ips), sorted(names)


def resolve_host_ip(addr: str) -> str:
    """Replace a wildcard host in 'host:port' with a routable IP
    (net.go:12-33)."""
    host, sep, port = addr.rpartition(":")
    if not sep:
        return addr
    if host in ("", "0.0.0.0", "::", "[::]"):
        return f"{discover_ip()}:{port}"
    return addr
