"""Re-armable interval timer (reference `Interval`, interval.go:27-70).

`next()` arms the timer; `on_tick` fires once ~duration later.  Calls to
`next()` while armed coalesce (the reference's 1-buffered channel with
non-blocking send).  Paces the host-tier GLOBAL and multi-region
pipelines; the peer-client batch window is inlined in its queue loop.
"""

from __future__ import annotations

import threading
from typing import Callable


class Interval:
    def __init__(self, duration_s: float, on_tick: Callable[[], None]):
        self.duration_s = duration_s
        self._on_tick = on_tick
        self._armed = threading.Event()
        self._stopped = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._stopped.is_set():
            if not self._armed.wait(timeout=0.05):
                continue
            self._armed.clear()
            if self._stopped.wait(timeout=self.duration_s):
                return
            try:
                self._on_tick()
            except Exception:  # noqa: BLE001 — timer thread must survive
                pass

    def next(self) -> None:
        """Arm the next tick; ignored if one is already pending
        (interval.go:63-70)."""
        self._armed.set()

    def stop(self) -> None:
        self._stopped.set()
        self._thread.join(timeout=1.0)
