"""64-bit FNV-1 / FNV-1a hashing with an optional C fast path.

The reference uses github.com/segmentio/fasthash fnv1/fnv1a for its
consistent-hash ring (`replicated_hash.go:31,59-64`).  These are the
standard FNV-64 parameter sets, reimplemented here from the published
algorithm.  A batched C implementation (native/hashing.c, loaded via
ctypes) accelerates the hot host-side path of hashing many keys per
request batch; the pure-Python path is the fallback and the semantics
oracle.
"""

from __future__ import annotations

import ctypes
import os
from typing import Iterable, List

_FNV_OFFSET64 = 0xCBF29CE484222325
_FNV_PRIME64 = 0x100000001B3
_MASK64 = 0xFFFFFFFFFFFFFFFF


def fnv1a_64(data: bytes) -> int:
    """FNV-1a 64-bit hash (xor, then multiply)."""
    h = _FNV_OFFSET64
    for b in data:
        h ^= b
        h = (h * _FNV_PRIME64) & _MASK64
    return h


def fnv1_64(data: bytes) -> int:
    """FNV-1 64-bit hash (multiply, then xor)."""
    h = _FNV_OFFSET64
    for b in data:
        h = (h * _FNV_PRIME64) & _MASK64
        h ^= b
    return h


def hash_string_64(s: str) -> int:
    """Default key hash: FNV-1a over UTF-8 bytes (replicated_hash.go:31)."""
    return fnv1a_64(s.encode("utf-8"))


class _NativeHasher:
    """ctypes binding to the batched C hasher (native/libguberhash.so)."""

    def __init__(self, path: str):
        lib = ctypes.CDLL(path)
        lib.fnv1a64_batch.argtypes = [
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_uint32),
            ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.fnv1a64_batch.restype = None
        self._lib = lib

    def hash_batch(self, keys: List[bytes]) -> List[int]:
        n = len(keys)
        if n == 0:
            return []
        blob = b"".join(keys)
        lens = (ctypes.c_uint32 * n)(*[len(k) for k in keys])
        out = (ctypes.c_uint64 * n)()
        self._lib.fnv1a64_batch(blob, lens, n, out)
        return list(out)


_native: "_NativeHasher | None" = None


def _load_native() -> "_NativeHasher | None":
    global _native
    if _native is not None:
        return _native
    so = os.path.join(os.path.dirname(__file__), "..", "..", "native", "libguberhash.so")
    so = os.path.abspath(so)
    if os.path.exists(so):
        try:
            _native = _NativeHasher(so)
        except OSError:
            _native = None
    return _native


def hash_batch_64(keys: Iterable[str]) -> List[int]:
    """FNV-1a-64 over a batch of string keys; uses the C fast path if built."""
    encoded = [k.encode("utf-8") for k in keys]
    native = _load_native()
    if native is not None:
        return native.hash_batch(encoded)
    return [fnv1a_64(k) for k in encoded]
