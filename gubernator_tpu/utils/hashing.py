"""64-bit FNV-1 / FNV-1a hashing with an optional C fast path.

The reference uses github.com/segmentio/fasthash fnv1/fnv1a for its
consistent-hash ring (`replicated_hash.go:31,59-64`).  These are the
standard FNV-64 parameter sets, reimplemented here from the published
algorithm.  The batched C++ implementation in the host runtime
(native/host_runtime.cpp) accelerates the hot host-side path of hashing
many keys per request batch; the pure-Python path is the fallback and
the semantics oracle.
"""

from __future__ import annotations

from typing import Iterable, List

_FNV_OFFSET64 = 0xCBF29CE484222325
_FNV_PRIME64 = 0x100000001B3
_MASK64 = 0xFFFFFFFFFFFFFFFF


def fnv1a_64(data: bytes) -> int:
    """FNV-1a 64-bit hash (xor, then multiply)."""
    h = _FNV_OFFSET64
    for b in data:
        h ^= b
        h = (h * _FNV_PRIME64) & _MASK64
    return h


def fnv1_64(data: bytes) -> int:
    """FNV-1 64-bit hash (multiply, then xor)."""
    h = _FNV_OFFSET64
    for b in data:
        h = (h * _FNV_PRIME64) & _MASK64
        h ^= b
    return h


def hash_string_64(s: str) -> int:
    """Default key hash: FNV-1a over UTF-8 bytes (replicated_hash.go:31)."""
    return fnv1a_64(s.encode("utf-8"))


def hash_batch_64(keys: Iterable[str]) -> List[int]:
    """FNV-1a-64 over a batch of string keys; delegates to the C++ host
    runtime (native/host_runtime.cpp::gt_fnv1_batch) when built."""
    keys = list(keys)
    from .. import native

    if native.available():
        return [int(h) for h in native.fnv1_batch(keys, variant_1a=True)]
    return [fnv1a_64(k.encode("utf-8")) for k in keys]
