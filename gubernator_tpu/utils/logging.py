"""Logging helpers (reference logging/logging.go + memberlist.go:268-286).

The reference ships two small utilities around logrus: a log level that
(un)marshals to JSON so daemon config files can carry it
(logging/logging.go:26-54), and a pipe-writer adapter that feeds a
third-party library's raw log output into the structured logger
(newLogWriter, memberlist.go:268-286).  Python equivalents over the
stdlib logging module, plus the `category=gubernator` logger setup the
daemon and CLIs share (gubernator.go:67, config.go:231-235).
"""

from __future__ import annotations

import io
import json
import logging
import sys
from typing import Optional

CATEGORY = "gubernator"


class LogLevelJSON:
    """JSON-(un)marshalable wrapper around a logging level
    (logging/logging.go:26-54): serializes as the lowercase level name,
    parses either a name or a numeric level."""

    def __init__(self, level: int = logging.INFO):
        self.level = level

    def to_json(self) -> str:
        return json.dumps(logging.getLevelName(self.level).lower())

    @classmethod
    def from_json(cls, data: str) -> "LogLevelJSON":
        v = json.loads(data)
        if isinstance(v, int):
            return cls(v)
        name = str(v).upper()
        level = logging.getLevelName(name)
        if not isinstance(level, int):
            raise ValueError(f"unknown log level '{v}'")
        return cls(level)

    def __eq__(self, other) -> bool:
        return isinstance(other, LogLevelJSON) and other.level == self.level

    def __repr__(self) -> str:
        return f"LogLevelJSON({logging.getLevelName(self.level)})"


class LogWriter(io.TextIOBase):
    """File-like adapter that forwards complete lines into a logger at
    DEBUG — the newLogWriter pattern (memberlist.go:268-286) for
    capturing third-party components' raw output (e.g. an embedded
    server's access log) into the structured log."""

    def __init__(self, logger: logging.Logger, level: int = logging.DEBUG):
        self.logger = logger
        self.level = level
        self._buf = ""

    def write(self, s: str) -> int:
        self._buf += s
        while "\n" in self._buf:
            line, _, self._buf = self._buf.partition("\n")
            if line.strip():
                self.logger.log(self.level, line.rstrip())
        return len(s)

    def flush(self) -> None:
        if self._buf.strip():
            self.logger.log(self.level, self._buf.rstrip())
        self._buf = ""


def category_logger(name: str = "") -> logging.Logger:
    """The shared `category=gubernator` logger tree (gubernator.go:67)."""
    return logging.getLogger(f"{CATEGORY}.{name}" if name else CATEGORY)


class TraceContextFilter(logging.Filter):
    """Stamps trace_id/span_id onto every record from the calling
    thread's active trace context (tracing.py), so logs and traces join
    on one id; "-" when no sampled trace is active.  A Filter, not a
    LogRecordFactory: the stamp must apply only to the gubernator tree,
    not hijack the process-global record factory."""

    def filter(self, record: logging.LogRecord) -> bool:
        # Local import: tracing imports category_logger from here.
        from .. import tracing

        ctx = tracing.current() if tracing.enabled() else None
        record.trace_id = ctx.trace_hex if ctx is not None else "-"
        record.span_id = ctx.span_hex if ctx is not None else "-"
        return True


def setup_logging(debug: bool = False, stream=None) -> logging.Logger:
    """Configure the gubernator logger tree: level from the debug flag
    (GUBER_DEBUG / -debug, config.go:231-235), one structured line per
    record, trace/span ids stamped when a trace context is active."""
    logger = logging.getLogger(CATEGORY)
    logger.setLevel(logging.DEBUG if debug else logging.INFO)
    if not logger.handlers:
        handler = logging.StreamHandler(stream or sys.stderr)
        handler.addFilter(TraceContextFilter())
        handler.setFormatter(
            logging.Formatter(
                fmt=(
                    "time=%(asctime)s level=%(levelname)s category=" + CATEGORY +
                    " logger=%(name)s trace_id=%(trace_id)s"
                    " span_id=%(span_id)s msg=%(message)s"
                ),
                datefmt="%Y-%m-%dT%H:%M:%S%z",
            )
        )
        logger.addHandler(handler)
        logger.propagate = False
    return logger
