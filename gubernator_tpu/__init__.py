"""gubernator_tpu — a TPU-native distributed rate-limiting framework.

Capabilities match the reference Gubernator service (see SURVEY.md):
token-bucket / leaky-bucket algorithms, key-ownership sharding, request
batching, GLOBAL eventually-consistent limits, Gregorian resets,
pluggable persistence, HTTP/gRPC ingress — redesigned for TPU: bucket
state as sharded integer columns on a device mesh, whole batches
evaluated per jitted kernel call, peer traffic as ICI collectives.
"""

import jax as _jax

# Rate-limit arithmetic is int64 end-to-end (epoch-ms timestamps, 64-bit
# limits per the proto), so x64 must be on before any array is created.
_jax.config.update("jax_enable_x64", True)

from .types import (  # noqa: E402
    Algorithm,
    Behavior,
    GetRateLimitsRequest,
    GetRateLimitsResponse,
    HealthCheckResponse,
    PeerInfo,
    RateLimitRequest,
    RateLimitResponse,
    Status,
    has_behavior,
    set_behavior,
    MILLISECOND,
    SECOND,
    MINUTE,
    HOUR,
)

__version__ = "0.1.0"

__all__ = [
    "Algorithm",
    "Behavior",
    "Status",
    "RateLimitRequest",
    "RateLimitResponse",
    "GetRateLimitsRequest",
    "GetRateLimitsResponse",
    "HealthCheckResponse",
    "PeerInfo",
    "has_behavior",
    "set_behavior",
    "MILLISECOND",
    "SECOND",
    "MINUTE",
    "HOUR",
]
