"""Multi-region federation plane: columnar cross-region hit replication.

The reference replicates MULTI_REGION hits asynchronously between
clusters (multiregion.go:8-83 — its send leg is a TODO stub;
region_picker.go:7-95 picks the owner peer per region).  The pre-PR
build honored those TODOs with a per-item loop: every flush built one
classic GetPeerRateLimits RPC per remote owner from per-key dataclasses
— the exact shape the PR 2/5/7 columnar playbook replaced at the peer,
GLOBAL, and reshard tiers.  This module applies that playbook at the
final tier:

* **Per-region accumulator** — MULTI_REGION lanes aggregate per key
  (hits summed, multiregion.go:37-47) into one host-side map, flushed
  every `multi_region_sync_wait_s` or IMMEDIATELY when the map reaches
  `multi_region_batch_limit` distinct keys (the reference's queue-full
  flush, multiregion.go:49-62 — the knob was parsed but unenforced
  before this plane).

* **Encode-once columnar batch** — a flush builds ONE RegionColumns
  batch (per-key summed hits + this daemon's GUBER_DATA_CENTER as the
  origin-region id, MULTI_REGION stripped so the receiver cannot echo)
  and fans it to each remote region's owner peers CONCURRENTLY through
  a bounded pool (the PR 5 fan-out model).  When every region's ring
  maps the whole flush to one owner — the common topology — all
  regions share the SAME RegionBatch object, so the frame/proto bytes
  are encoded once per flush, not once per region.

* **Partition semantics** — a send that provably never applied
  (breaker fast-fail, connection-level not-ready) requeues into that
  REGION's carry (hits summed per key, capped at REGION_CARRY_MAX,
  overflow drops COUNTED); a timeout-shaped failure may have applied
  remotely, so it drops counted instead of double-sending — the PR 5
  hit-carry discipline, per destination region.  Breaker/backoff per
  remote peer ride unchanged inside service._peer_send_ex.

* **Audit contract** (audit.py `region_*`): origin-admitted >=
  wire-reached >= remote-applied, each pair side-local and
  lag-tolerant.  A FaultPlan DUPLICATE on the region wire doubles
  `region_wire_hits` against a single `region_admitted_hits` note and
  trips `region_conservation` — the seeded byzantine re-delivery the
  soak's 2x2 topology proves caught.

Eventual-consistency slack (documented in architecture.md
"Multi-region federation"): a remote region's view lags by up to one
flush window plus carry residence; under prolonged partition at most
REGION_CARRY_MAX distinct keys per region are retained and overflow
hits drop counted (`gubernator_region_dropped_hits`) — bounded loss,
never double-apply.
"""

from __future__ import annotations

import json
import threading
import time
from dataclasses import dataclass, replace
from typing import Dict, List, Optional, Tuple

import numpy as np

from . import audit
from . import tracing
from . import wire
from .config import PEER_COLUMNS_MAX_LANES
from .peer_client import is_circuit_open, is_not_ready
from .types import Behavior, RateLimitRequest, set_behavior
from .utils.interval import Interval

# Requeue-carry bound per destination region (distinct keys): hits for
# a region that stays partitioned accumulate between flushes; past the
# cap new keys drop (counted in gubernator_region_dropped_hits) — the
# GLOBAL plane's bounded-loss posture (service.GlobalManager
# .HIT_CARRY_MAX), applied per region.  The audit's region_slack
# invariant checks the live carry against this.
REGION_CARRY_MAX = 16_384


@dataclass
class RegionColumns:
    """One cross-region hit batch in column form — the wire currency of
    the federation plane (GUBC frame kind 7 / RegionColumnsReq).
    `origin` is the sending daemon's GUBER_DATA_CENTER; the behavior
    column has MULTI_REGION already stripped (the receiver applies, it
    must not re-queue — the no-amplification rule)."""

    origin: str
    names: List[str]
    unique_keys: List[str]
    algorithm: np.ndarray  # i32[n]
    behavior: np.ndarray  # i32[n], MULTI_REGION stripped
    hits: np.ndarray  # i64[n]
    limit: np.ndarray  # i64[n]
    duration: np.ndarray  # i64[n]

    def __len__(self) -> int:
        return len(self.names)

    def hash_key_at(self, i: int) -> str:
        return f"{self.names[i]}_{self.unique_keys[i]}"

    def peer_columns(self):
        """This batch as a wire.PeerColumns tuple (the classic-fallback
        encoders consume it)."""
        return (
            self.names, self.unique_keys, self.algorithm, self.behavior,
            self.hits, self.limit, self.duration,
        )

    def slice(self, lo: int, hi: int) -> "RegionColumns":
        return RegionColumns(
            origin=self.origin,
            names=self.names[lo:hi],
            unique_keys=self.unique_keys[lo:hi],
            algorithm=self.algorithm[lo:hi],
            behavior=self.behavior[lo:hi],
            hits=self.hits[lo:hi],
            limit=self.limit[lo:hi],
            duration=self.duration[lo:hi],
        )

    @classmethod
    def from_requests(
        cls, origin: str, reqs: List[RateLimitRequest]
    ) -> "RegionColumns":
        n = len(reqs)
        return cls(
            origin=origin,
            names=[r.name for r in reqs],
            unique_keys=[r.unique_key for r in reqs],
            algorithm=np.fromiter(
                (int(r.algorithm) for r in reqs), np.int32, count=n
            ),
            behavior=np.fromiter(
                (set_behavior(r.behavior, Behavior.MULTI_REGION, False)
                 for r in reqs),
                np.int32, count=n,
            ),
            hits=np.fromiter((int(r.hits) for r in reqs), np.int64, count=n),
            limit=np.fromiter((int(r.limit) for r in reqs), np.int64, count=n),
            duration=np.fromiter(
                (int(r.duration) for r in reqs), np.int64, count=n
            ),
        )


class RegionBatch:
    """One flush's columns with every wire encoding cached, so an
    N-region fan-out encodes each form at most once (wire.BroadcastBatch
    for the region tier).  The classic encodings are built through the
    exact per-item codecs the pre-PR sender used
    (wire.peer_columns_to_classic_pb/_json), so a GUBER_REGION_COLUMNS=0
    daemon — or a classic-negotiated peer — sees byte-identical wire.

    Lazy init is LOCKED: the fan-out pool hands one batch to many
    concurrent sends."""

    __slots__ = ("cols", "_lock", "_frame", "_pb", "_classic_pb",
                 "_classic_json", "_total_hits")

    def __init__(self, cols: RegionColumns):
        self.cols = cols
        self._lock = threading.Lock()
        self._frame: Optional[bytes] = None
        self._pb = None
        # Classic fallbacks chunk at the receiver's classic per-RPC cap,
        # which can differ per client config: cache per cap.
        self._classic_pb: Dict[int, list] = {}
        self._classic_json: Dict[int, list] = {}
        self._total_hits = int(np.asarray(cols.hits).sum())

    def __len__(self) -> int:
        return len(self.cols)

    def total_hits(self) -> int:
        return self._total_hits

    def frame(self) -> bytes:
        with self._lock:
            if self._frame is None:
                self._frame = wire.encode_region_frame(self.cols)
            return self._frame

    def columns_pb(self):
        with self._lock:
            if self._pb is None:
                self._pb = wire.region_cols_to_pb(self.cols)
            return self._pb

    def classic_pb_chunks(self, cap: int) -> list:
        """The pre-PR wire: per-item GetPeerRateLimitsReq messages,
        chunked at the classic per-RPC cap."""
        with self._lock:
            chunks = self._classic_pb.get(cap)
            if chunks is None:
                pc = self.cols.peer_columns()
                n = len(self.cols)
                chunks = [
                    wire.peer_columns_to_classic_pb(
                        wire.peer_columns_slice(pc, lo, min(lo + cap, n))
                    )
                    for lo in range(0, n, cap)
                ]
                self._classic_pb[cap] = chunks
            return chunks

    def classic_json_chunks(self, cap: int) -> list:
        """The pre-PR HTTP wire: per-item {"requests": [...]} bodies."""
        with self._lock:
            chunks = self._classic_json.get(cap)
            if chunks is None:
                pc = self.cols.peer_columns()
                n = len(self.cols)
                chunks = [
                    json.dumps(
                        wire.peer_columns_to_classic_json(
                            wire.peer_columns_slice(pc, lo, min(lo + cap, n))
                        )
                    ).encode("utf-8")
                    for lo in range(0, n, cap)
                ]
                self._classic_json[cap] = chunks
            return chunks


class FederationManager:
    """MULTI_REGION hit pipeline (multiregion.go:8-83, the send-leg
    TODOs honored columnar).  Aggregates hits per key, flushes them as
    encode-once RegionColumns batches to each remote region's owner
    peers concurrently, and carries provably-unapplied sends into the
    next flush per region.  Module docstring has the full contract."""

    def __init__(self, service):
        self.service = service
        self._lock = threading.Lock()
        # Per-key aggregation (hits summed; stored copies so callers'
        # requests are never mutated) — the multiregion.go:37-47 map.
        self._hits: Dict[str, RateLimitRequest] = {}
        self._stopped = False
        # Serializes flushes: the interval tick, the batch-limit early
        # kick, and direct test callers must not interleave the
        # take-accumulator / merge-carry / requeue sequence.
        self._flush_lock = threading.Lock()
        self._kick_pending = False
        # Per-REGION requeue carry: region -> hash_key -> private
        # RateLimitRequest copy (hits summed).  Flush-thread-only
        # mutation (under _flush_lock); snapshots read sizes only.
        self._carry: Dict[str, Dict[str, RateLimitRequest]] = {}
        self._fanout_pool = None
        # Status counters (hit totals, for GET /debug/status).
        self.sent_hits = 0
        self.requeued_hits = 0
        self.dropped_hits = 0
        self.flushes = 0
        self._last_flush_monotonic: Optional[float] = None
        self._interval = Interval(
            service.conf.behaviors.multi_region_sync_wait_s, self._tick
        )
        self._interval.next()

    # -- queueing ------------------------------------------------------
    def _tick(self) -> None:
        try:
            self.run_once()
        finally:
            if not self._stopped:
                self._interval.next()

    def queue_hits(self, r: RateLimitRequest) -> None:
        """Aggregate by hash key, summing hits (multiregion.go:37-47).
        Reaching multi_region_batch_limit distinct keys flushes
        immediately instead of waiting out the window — the reference's
        queue-full flush, previously unenforced."""
        limit = self.service.conf.behaviors.multi_region_batch_limit
        with self._lock:
            key = r.hash_key()
            cur = self._hits.get(key)
            if cur is None:
                self._hits[key] = replace(r)
            else:
                cur.hits += r.hits
            kick = (
                limit > 0
                and len(self._hits) >= limit
                and not self._kick_pending
                and not self._stopped
            )
            if kick:
                self._kick_pending = True
        if kick:
            threading.Thread(
                target=self.run_once, daemon=True, name="region-flush"
            ).start()

    # -- the flush -----------------------------------------------------
    def run_once(self) -> bool:
        """One flush pass; returns whether any region send happened."""
        with self._flush_lock:
            return self._run_locked()

    def _run_locked(self) -> bool:
        svc = self.service
        my_dc = svc.conf.data_center
        with self._lock:
            self._kick_pending = False
            new, self._hits = self._hits, {}
        rp = svc.get_region_picker()
        regions = [dc for dc in rp.region_names() if dc != my_dc]
        # Carry owed to regions that left the membership: bounded loss,
        # counted — there is no longer anywhere to deliver it.  (Inner
        # carry dicts are flush-thread-only; TOP-LEVEL _carry mutations
        # take _lock so snapshot() can iterate concurrently.)
        for dc in list(self._carry):
            if dc not in regions:
                with self._lock:
                    gone = self._carry.pop(dc)
                if gone:
                    self._drop(sum(int(r.hits) for r in gone.values()),
                               len(gone))
        if not regions:
            # No remote regions (GUBER_DATA_CENTER unset, or a
            # single-region cluster): drain and discard, exactly the
            # pre-PR no-op shape.  Hits were never admitted toward any
            # region, so no ledger notes.
            return False
        if not new and not self._carry:
            return False
        self.flushes += 1
        self._last_flush_monotonic = time.monotonic()
        tick = tracing.BatchTrace(()) if tracing.sampled() else None
        t0_ns = time.monotonic_ns()
        new_hits_total = sum(int(r.hits) for r in new.values())

        # Plan every (region, owner) send.  The shared no-carry path
        # reuses ONE RegionBatch (and therefore one encode) across all
        # regions whose ring maps the whole flush to a single owner.
        shared: Optional[List[RegionBatch]] = None
        sends: List[tuple] = []  # (dc, addr, client, batches, entries)
        for dc in regions:
            with self._lock:
                carry = self._carry.pop(dc, None)
            if carry:
                merged = carry  # private copies: safe to sum into
                for k, r in new.items():
                    cur = merged.get(k)
                    if cur is None:
                        merged[k] = r
                    else:
                        cur.hits += int(r.hits)
            else:
                merged = new  # shared, read-only from here on
            if not merged:
                continue
            if new:
                # Origin-admitted ledger (audit.py): NEW hits only, per
                # destination region — carried lanes were counted the
                # flush they first aggregated toward this region.
                audit.note("region_agg_hits", new_hits_total)
            groups: Dict[str, List[str]] = {}
            clients: Dict[str, object] = {}
            unroutable: List[str] = []
            for k in merged:
                peer = rp.pick(dc, k)
                if peer is None:
                    unroutable.append(k)
                    continue
                addr = peer.info.grpc_address
                groups.setdefault(addr, []).append(k)
                clients[addr] = peer
            if unroutable:
                # Region ring churned mid-flush: provably unapplied.
                self._requeue(dc, [(k, merged[k]) for k in unroutable])
            for addr, keys in groups.items():
                entries = [(k, merged[k]) for k in keys]
                if merged is new and len(keys) == len(merged):
                    if shared is None:
                        shared = self._make_batches(my_dc, entries)
                    batches = shared
                else:
                    batches = self._make_batches(my_dc, entries)
                sends.append((dc, addr, clients[addr], batches, entries))

        if sends:
            pool = self._get_pool()
            ctx = tick.ctx if tick is not None else None
            futs = [
                (dc, addr, batches, entries,
                 pool.submit(self._send_region, client, batches, ctx))
                for dc, addr, client, batches, entries in sends
            ]
            for dc, addr, batches, entries, fut in futs:
                statuses = fut.result()
                pos = 0
                for batch, status in zip(batches, statuses):
                    chunk = entries[pos:pos + len(batch)]
                    pos += len(batch)
                    chunk_hits = batch.total_hits()
                    if status == "sent":
                        audit.note("region_sent_hits", chunk_hits)
                        self.sent_hits += chunk_hits
                    elif status == "requeue":
                        self._requeue(dc, chunk)
                    else:  # "drop": timeout-shaped, may have applied
                        self._drop(chunk_hits, len(chunk))
                    if status != "sent":
                        tracing.record_event(
                            "region-send-failed", region=dc, peer=addr,
                            lanes=len(chunk), outcome=status,
                        )
        carry_keys = sum(len(c) for c in self._carry.values())
        audit.set_gauge(audit.REGION_CARRY_GAUGE, carry_keys)
        svc.metrics.region_carry_keys.set(carry_keys)
        if tick is not None:
            tracing.record_span(
                "region.flush", tick.ctx,
                start_ns=t0_ns, end_ns=time.monotonic_ns(),
                regions=len(regions), sends=len(sends),
                keys=len(new),
            )
        return bool(sends)

    def _make_batches(self, origin: str, entries) -> List[RegionBatch]:
        """Entries -> RegionBatch list, chunked at the columnar receive
        cap (classic-negotiated clients re-chunk further themselves)."""
        cols = RegionColumns.from_requests(origin, [r for _, r in entries])
        n = len(cols)
        if n <= PEER_COLUMNS_MAX_LANES:
            return [RegionBatch(cols)]
        return [
            RegionBatch(cols.slice(lo, min(lo + PEER_COLUMNS_MAX_LANES, n)))
            for lo in range(0, n, PEER_COLUMNS_MAX_LANES)
        ]

    def _send_region(self, client, batches: List[RegionBatch],
                     ctx) -> List[str]:
        """Send one owner's batches; per-batch outcome: "sent",
        "requeue" (provably unapplied — breaker fast-fail or
        connection-level not-ready), or "drop" (timeout-shaped: the
        batch may have applied remotely, so re-sending would
        double-count)."""
        svc = self.service
        timeout = svc.conf.behaviors.multi_region_timeout_s
        out: List[str] = []
        for batch in batches:
            ok, err = svc._peer_send_ex(
                "multi_region",
                lambda b=batch: client.update_region_columns(
                    b, timeout_s=timeout, trace_ctx=ctx
                ),
            )
            if ok:
                out.append("sent")
            elif is_circuit_open(err) or is_not_ready(err):
                out.append("requeue")
            else:
                out.append("drop")
        return out

    def _requeue(self, dc: str, entries) -> None:
        """Fold failed lanes into the region's carry (hits summed per
        key), bounded at REGION_CARRY_MAX distinct keys."""
        with self._lock:
            carry = self._carry.setdefault(dc, {})
        requeued = dropped_keys = dropped_hits = 0
        for k, r in entries:
            cur = carry.get(k)
            if cur is not None:
                cur.hits += int(r.hits)
                requeued += 1
                continue
            if len(carry) >= REGION_CARRY_MAX:
                dropped_keys += 1
                dropped_hits += int(r.hits)
                continue
            carry[k] = replace(r)
            requeued += 1
        if requeued:
            self.requeued_hits += sum(
                int(r.hits) for k, r in entries if k in carry
            )
            self.service.metrics.region_requeued_hits.inc(requeued)
        if dropped_hits or dropped_keys:
            self._drop(dropped_hits, dropped_keys)

    def _drop(self, hits: int, keys: int) -> None:
        if hits:
            audit.note("region_dropped_hits", hits)
            self.dropped_hits += hits
        if keys:
            self.service.metrics.region_dropped_hits.inc(keys)

    def _get_pool(self):
        # Flush-thread-only under _flush_lock: no extra lock needed.
        if self._fanout_pool is None:
            from concurrent.futures import ThreadPoolExecutor

            self._fanout_pool = ThreadPoolExecutor(
                max_workers=max(
                    1,
                    getattr(self.service.conf.behaviors, "global_fanout", 8),
                ),
                thread_name_prefix="region-fanout",
            )
        return self._fanout_pool

    # -- observers -----------------------------------------------------
    def snapshot(self) -> dict:
        """The `region` section of GET /debug/status (federation half;
        the service adds per-region peer/breaker counts under its peer
        mutex)."""
        with self._lock:
            pending = len(self._hits)
            # Top-level _carry mutations also hold _lock (the flush
            # thread's pops and _requeue's setdefault); len() of the
            # inner flush-thread-owned dicts is atomic.
            carry = {dc: len(c) for dc, c in self._carry.items()}
        age = (
            round(time.monotonic() - self._last_flush_monotonic, 3)
            if self._last_flush_monotonic is not None
            else None
        )
        return {
            "dataCenter": self.service.conf.data_center,
            "columnsEnabled": getattr(
                self.service.conf.behaviors, "region_columns", True
            ),
            "pendingKeys": pending,
            "carryKeys": carry,
            "carryKeyTotal": sum(carry.values()),
            "flushes": self.flushes,
            "lastFlushAgeS": age,
            "sentHits": self.sent_hits,
            "requeuedHits": self.requeued_hits,
            "droppedHits": self.dropped_hits,
        }

    def stop(self) -> None:
        self._stopped = True
        self._interval.stop()
        if self._fanout_pool is not None:
            self._fanout_pool.shutdown(wait=False)
