"""Always-on conservation audit: the chaos-suite oracles, productionized.

The exactly-once invariants this system promises — no double-committed
hits on the peer wire, GLOBAL hit lanes delivered exactly once or
counted, reshard transfer lanes conserved, device grants bounded by
what was dispatched — are pinned today by offline chaos tests (PRs 5
and 7).  This module keeps a windowed LEDGER of the same quantities on
the live path and reconciles them every `GUBER_AUDIT_INTERVAL`
seconds, so an accounting bug (or a byzantine network duplicating
deliveries) surfaces as `gubernator_audit_violations_total{invariant}`
plus a flight-recorder auto-dump — not as a customer noticing their
rate limit ran double.

**Ledger.**  Cumulative counters recorded at DISTINCT layers of the
stack (each invariant compares two different layers' views of the same
hits, which is what makes the reconciliation meaningful):

  ingress_hits            hits entering the public front door
  peer_ingress_hits       hits entering via GetPeerRateLimits
  dispatched_hits         hits entering the columnar dispatch pipeline
  applied_hits            hits GRANTED by the device (UNDER_LIMIT
                          lanes at commit decode)
  forward_admitted_hits   hits handed to the peer-forward wire
  forward_wire_hits       hits that REACHED a peer, per transport
                          attempt (success or timeout-ambiguous;
                          provably-unapplied failures do not count)
  global_agg_hits         GLOBAL hits aggregated by the sync collective
  global_sent_hits        GLOBAL hits delivered owner-ward
  global_dropped_hits     GLOBAL hits dropped counted (timeout-shaped
                          / carry overflow)
  reshard_drained_lanes   lanes gathered off this owner for transfer
  reshard_acked_lanes     lanes a new owner ACKed (forgotten locally)
  reshard_received_lanes  transfer lanes received from old owners
  reshard_committed_lanes merge-committed here
  reshard_rejected_lanes  received but not owned under the current ring
  snapshot_saved_lanes    lanes gathered into a completed snapshot dump
  snapshot_loaded_lanes   lanes decoded from a snapshot file at boot
  snapshot_committed_lanes lanes merge-committed by the boot restore
  region_agg_hits         MULTI_REGION hits admitted toward a remote
                          region at flush (new lanes only, counted per
                          destination region; carried lanes were
                          counted the flush they first aggregated)
  region_sent_hits        region hits delivered to a remote owner (ok)
  region_dropped_hits     region hits dropped counted (timeout-shaped
                          sends that may have applied remotely, carry
                          overflow, departed regions)
  region_admitted_hits    region hits handed to the wire per logical
                          send (federation.RegionBatch)
  region_wire_hits        region hits that REACHED a peer, per
                          transport delivery (success or
                          timeout-ambiguous; provably-unapplied
                          failures do not count)
  region_recv_hits        hits decoded from a received
                          UpdateRegionColumns batch
  region_applied_hits     region hits the receiver applied locally
  negative_remaining      decoded lanes with remaining < 0 (device
                          arithmetic corruption; must stay 0)

**Invariants.**  Each is a one-sided inequality that tolerates
in-flight lag (the later layer's counter lags the earlier one's), so
interval windowing can never false-positive — only EXCESS on the later
side (hits materializing from nowhere = a double-commit / conservation
break) trips it:

  device_conservation    applied_hits            <= dispatched_hits
  forward_conservation   forward_wire_hits       <= forward_admitted_hits
  global_conservation    global_sent + dropped   <= global_agg_hits
  global_slack           requeue carry keys      <= HIT_CARRY_MAX
                         (the documented bounded-loss slack, PR 5)
  reshard_out            reshard_acked_lanes     <= reshard_drained_lanes
  reshard_in             committed + rejected    <= reshard_received_lanes
  snapshot_restore       snapshot_committed      <= snapshot_loaded
                         (a restore can only drop lanes — expired in
                         transit, duplicate keys — never mint them)
  region_conservation    region_wire_hits        <= region_admitted_hits
                         (the federation plane's exactly-once chain,
                         sender side: a DUPLICATE re-delivery on the
                         region wire doubles the wire side and fires)
  region_delivery        region_sent + dropped   <= region_agg_hits
  region_apply           region_applied_hits     <= region_recv_hits
  region_slack           region carry keys       <= REGION_CARRY_MAX
                         (federation.py's documented bounded-loss
                         slack per destination region, summed)
  negative_remaining     negative_remaining      == 0

The federation chain "origin-admitted >= wire-reached >= remote-applied"
is audited as SIDE-LOCAL pairs: admitted/wire on the sender,
recv/applied on the receiver.  In an in-process multi-daemon soak the
shared ledger additionally keeps the cross-daemon inequality
(wire >= recv) true by construction; across real processes each daemon
reconciles only its own pairs, so a receiver is never falsely blamed
for hits whose admit note lives in another process.

A FaultPlan DUPLICATE rule (faults.py) — the injectable model of a
network/proxy re-delivering an applied RPC — makes the sender count
`forward_wire_hits` twice for hits admitted once: the seeded
double-commit the chaos suite uses to prove the audit fires.  A clean
run keeps every inequality slack and the audit silent.

The ledger is MODULE-GLOBAL (the saturation/tracing convention: one
daemon per process in production; in-process test clusters share one
plane and the inequalities still hold summed across daemons because
both sides of each are summed).  Each `Auditor` captures a BASELINE
snapshot when armed, so ledger traffic from earlier same-process tests
or startup warmup cannot leak into its verdicts.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from . import tracing
from .utils.logging import category_logger

logger = category_logger("audit")

# Ledger counter names, in report order.
COUNTERS = (
    "ingress_hits",
    "peer_ingress_hits",
    "dispatched_hits",
    "applied_hits",
    "forward_admitted_hits",
    "forward_wire_hits",
    "global_agg_hits",
    "global_sent_hits",
    "global_dropped_hits",
    "reshard_drained_lanes",
    "reshard_acked_lanes",
    "reshard_received_lanes",
    "reshard_committed_lanes",
    "reshard_rejected_lanes",
    "snapshot_saved_lanes",
    "snapshot_loaded_lanes",
    "snapshot_committed_lanes",
    "region_agg_hits",
    "region_sent_hits",
    "region_dropped_hits",
    "region_admitted_hits",
    "region_wire_hits",
    "region_recv_hits",
    "region_applied_hits",
    "negative_remaining",
)

_lock = threading.Lock()
_ledger: Dict[str, int] = {k: 0 for k in COUNTERS}
# Gauges: absolute values set by their owner (not cumulative).
_gauges: Dict[str, float] = {}


def note(counter: str, n: int) -> None:
    """Record `n` units into a cumulative ledger counter.  Called per
    BATCH / per RPC, never per lane — one lock, one int add."""
    if n <= 0:
        return
    with _lock:
        _ledger[counter] = _ledger.get(counter, 0) + int(n)


def set_gauge(name: str, value: float) -> None:
    with _lock:
        _gauges[name] = value


def ledger_snapshot() -> Dict[str, int]:
    with _lock:
        return dict(_ledger)


def gauges_snapshot() -> Dict[str, float]:
    with _lock:
        return dict(_gauges)


def reset() -> None:
    """Test hook: zero the ledger and gauges."""
    with _lock:
        for k in list(_ledger):
            _ledger[k] = 0
        _gauges.clear()


# ---------------------------------------------------------------------
# Invariant table: name -> (lhs counters, rhs counters, slack).
# Violation when sum(lhs) > sum(rhs) + slack, evaluated on
# baseline-relative deltas.
# ---------------------------------------------------------------------
INVARIANTS = {
    "device_conservation": (("applied_hits",), ("dispatched_hits",), 0),
    "forward_conservation": (
        ("forward_wire_hits",), ("forward_admitted_hits",), 0,
    ),
    "global_conservation": (
        ("global_sent_hits", "global_dropped_hits"), ("global_agg_hits",), 0,
    ),
    "reshard_out": (("reshard_acked_lanes",), ("reshard_drained_lanes",), 0),
    "reshard_in": (
        ("reshard_committed_lanes", "reshard_rejected_lanes"),
        ("reshard_received_lanes",), 0,
    ),
    "snapshot_restore": (
        ("snapshot_committed_lanes",), ("snapshot_loaded_lanes",), 0,
    ),
    "region_conservation": (
        ("region_wire_hits",), ("region_admitted_hits",), 0,
    ),
    "region_delivery": (
        ("region_sent_hits", "region_dropped_hits"), ("region_agg_hits",), 0,
    ),
    "region_apply": (("region_applied_hits",), ("region_recv_hits",), 0),
    "negative_remaining": (("negative_remaining",), (), 0),
}

# The documented GLOBAL requeue-carry bound (service.GlobalManager
# .HIT_CARRY_MAX; imported lazily to avoid a cycle) — checked as a
# gauge invariant: carry beyond the cap means the bounded-loss contract
# the architecture documents no longer holds.
GLOBAL_CARRY_GAUGE = "global_carry_keys"

# The federation requeue-carry bound (federation.REGION_CARRY_MAX),
# checked the same way: carry beyond the cap means the documented
# bounded-loss contract of the region plane no longer holds.
REGION_CARRY_GAUGE = "region_carry_keys"


def _carry_cap() -> int:
    from .service import GlobalManager

    return GlobalManager.HIT_CARRY_MAX


def _region_carry_cap() -> int:
    from .federation import REGION_CARRY_MAX

    return REGION_CARRY_MAX


class Auditor:
    """Periodic reconciliation of the ledger against the invariant
    table.  `metrics` (a metrics.Metrics) receives live violation /
    check counters; detected violations also record an
    `audit-violation` flight-recorder event (auto-dump, rate-limited by
    tracing's dump throttle).  One auditor per V1Service; `arm()`
    captures the baseline so pre-existing same-process ledger traffic
    is excluded from its verdicts."""

    def __init__(self, metrics=None, interval_s: float = 5.0,
                 enabled: bool = True, time_fn=time.monotonic,
                 recorder=None):
        self.metrics = metrics
        self.interval_s = max(float(interval_s), 0.05)
        self.enabled = bool(enabled)
        self._time = time_fn
        # The owning service's flight recorder: bound in the audit
        # thread so violation events (and the incident bundles they
        # trigger) attribute to THIS daemon, not the process default —
        # co-resident soak daemons each get their own black box.
        self.recorder = recorder
        self._baseline: Dict[str, int] = {}
        self._violation_extents: Dict[str, int] = {}
        self.violations: Dict[str, int] = {}
        self.checks = 0
        self.last_check_monotonic = 0.0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # Serializes check_now: the interval thread and direct callers
        # (soak final pass, tests, a future scrape hook) must not race
        # the extent-compare-then-count sequence — one real violation
        # must increment the counter exactly once.
        self._check_lock = threading.Lock()
        self.arm()

    def arm(self) -> None:
        """(Re)capture the ledger baseline: deltas reported by check()
        are relative to this point.  The FIRST reconciliation after
        arming SEEDS the extent table without counting: arming is not
        atomic with the paired notes (an RPC whose admitted side landed
        before the baseline delivers its wire side after it), so the
        in-flight halves of operations straddling the arm read as
        excess exactly once — attributing that to the arm instead of
        firing keeps a daemon constructed under live same-process
        traffic from dumping a false violation.  Real conservation
        breaks keep producing excess and fire on GROWTH at the next
        interval."""
        self._baseline = ledger_snapshot()
        self._violation_extents = {}
        self._seeded = False

    def start(self) -> None:
        if not self.enabled or self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="conservation-audit"
        )
        self._thread.start()

    def stop(self, timeout_s: float = 5.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=timeout_s)
            self._thread = None

    def _run(self) -> None:
        if self.recorder is not None:
            tracing.bind_recorder(self.recorder)
        while not self._stop.wait(self.interval_s):
            try:
                self.check_now()
            except Exception:  # noqa: BLE001 — the audit must never die
                logger.exception("conservation audit check failed")

    # ------------------------------------------------------------------
    def deltas(self) -> Dict[str, int]:
        cur = ledger_snapshot()
        return {
            k: cur.get(k, 0) - self._baseline.get(k, 0) for k in cur
        }

    def check_now(self) -> List[dict]:
        """One reconciliation pass.  Returns the list of violations
        FOUND this pass (new or grown); persisting-unchanged violations
        are reported in snapshot() but not re-counted, so a single
        double-commit increments the counter once, not once per
        interval forever.  The first pass after arm() seeds extents
        silently (see arm): it counts as a check but can never fire."""
        with self._check_lock:
            return self._check_locked()

    def _check_locked(self) -> List[dict]:
        seeding = not self._seeded
        self._seeded = True
        d = self.deltas()
        found: List[dict] = []
        for name, (lhs, rhs, slack) in INVARIANTS.items():
            excess = sum(d.get(k, 0) for k in lhs) - (
                sum(d.get(k, 0) for k in rhs) + slack
            )
            if excess > 0:
                prev = self._violation_extents.get(name, 0)
                if excess > prev:
                    self._violation_extents[name] = excess
                    found.append({
                        "invariant": name,
                        "excess": excess,
                        "lhs": {k: d.get(k, 0) for k in lhs},
                        "rhs": {k: d.get(k, 0) for k in rhs},
                    })
        carry = gauges_snapshot().get(GLOBAL_CARRY_GAUGE)
        if carry is not None and carry > _carry_cap():
            excess = int(carry) - _carry_cap()
            if excess > self._violation_extents.get("global_slack", 0):
                self._violation_extents["global_slack"] = excess
                found.append({
                    "invariant": "global_slack",
                    "excess": excess,
                    "lhs": {GLOBAL_CARRY_GAUGE: int(carry)},
                    "rhs": {"HIT_CARRY_MAX": _carry_cap()},
                })
        rcarry = gauges_snapshot().get(REGION_CARRY_GAUGE)
        if rcarry is not None and rcarry > _region_carry_cap():
            excess = int(rcarry) - _region_carry_cap()
            if excess > self._violation_extents.get("region_slack", 0):
                self._violation_extents["region_slack"] = excess
                found.append({
                    "invariant": "region_slack",
                    "excess": excess,
                    "lhs": {REGION_CARRY_GAUGE: int(rcarry)},
                    "rhs": {"REGION_CARRY_MAX": _region_carry_cap()},
                })
        self.checks += 1
        self.last_check_monotonic = self._time()
        if self.metrics is not None:
            self.metrics.audit_checks.inc()
        if seeding:
            return []
        for v in found:
            name = v["invariant"]
            self.violations[name] = self.violations.get(name, 0) + 1
            if self.metrics is not None:
                self.metrics.audit_violations.labels(invariant=name).inc()
            logger.warning(
                "conservation audit VIOLATION %s: excess=%d lhs=%s rhs=%s",
                name, v["excess"], v["lhs"], v["rhs"],
            )
            # The PR 4 auto-dump path: a conservation break is exactly
            # the moment the flight recorder's last-N spans matter.
            tracing.record_event(
                "audit-violation", invariant=name, excess=v["excess"],
            )
        return found

    def snapshot(self) -> dict:
        """The GET /debug/audit document."""
        return {
            "enabled": self.enabled,
            "intervalS": self.interval_s,
            "checks": self.checks,
            "violations": dict(self.violations),
            "violationTotal": sum(self.violations.values()),
            "ledger": self.deltas(),
            "gauges": gauges_snapshot(),
            "invariants": {
                name: {"lhs": list(lhs), "rhs": list(rhs), "slack": slack}
                for name, (lhs, rhs, slack) in INVARIANTS.items()
            },
        }
