"""gRPC data plane — serves `pb.gubernator.V1` and `pb.gubernator.PeersV1`.

Parity with the reference's gRPC server registration
(gubernator.go:72-76, daemon.go:86-136): both services share one
grpc.Server, receive size is capped at 1 MiB (daemon.go:88), and TLS /
mTLS credentials wrap the port (daemon.go:102-106).  Service stubs are
wired with `grpc.method_handlers_generic_handler` over the protoc
message classes (no grpc_python_plugin in this image), so the wire
format and fully-qualified method names match the reference exactly —
a stock Gubernator client can dial this server.
"""

from __future__ import annotations

import logging
from concurrent.futures import ThreadPoolExecutor
from typing import Optional

import grpc

from . import profiling
from . import tracing
from . import wire
from .config import INGRESS_COLUMNS_MAX_LANES, PEER_COLUMNS_MAX_LANES
from .proto import PEERS_V1_SERVICE, V1_SERVICE
from .proto import gubernator_pb2 as pb
from .proto import peers_columns_pb2 as pc_pb
from .proto import peers_pb2 as peers_pb
from .service import ApiError, V1Service

log = logging.getLogger("gubernator.grpc")

MAX_RECV_BYTES = 1024 * 1024  # daemon.go:88


class MetricsInterceptor(grpc.ServerInterceptor):
    """Per-RPC stats at the TRANSPORT layer (reference GRPCStatsHandler,
    grpc_stats.go:95-118): every method served by this grpc.Server —
    including ones added later — is counted and timed under
    gubernator_grpc_request_counts / gubernator_grpc_request_duration,
    with no per-handler hand-instrumentation.  An abort() or raise
    counts as status="1"."""

    def __init__(self, metrics):
        self.metrics = metrics

    def intercept_service(self, continuation, handler_call_details):
        handler = continuation(handler_call_details)
        if handler is None or self.metrics is None or handler.unary_unary is None:
            return handler  # only unary-unary methods exist here
        inner = handler.unary_unary
        method = handler_call_details.method
        # W3C trace-context ingress (tracing.py): extract `traceparent`
        # from the invocation metadata, run the handler under the span,
        # and emit the context back as trailing metadata so callers can
        # join logs/traces on one id.  Zero-cost when tracing is off —
        # ingress_span returns the shared no-op.
        traceparent = None
        for k, v in handler_call_details.invocation_metadata or ():
            if k == "traceparent":
                traceparent = v
                break

        def wrapped(request, context):
            # Span OUTSIDE the metrics timer: observe_rpc's exit hook
            # attaches a trace exemplar from the still-active context.
            with tracing.ingress_span("grpc", method, traceparent) as sp:
                with self.metrics.observe_rpc(method):
                    resp = inner(request, context)
                    tp = sp.traceparent()
                    if tp is not None:
                        context.set_trailing_metadata((("traceparent", tp),))
                    return resp

        return grpc.unary_unary_rpc_method_handler(
            wrapped,
            request_deserializer=handler.request_deserializer,
            response_serializer=handler.response_serializer,
        )

_STATUS_CODES = {
    "InvalidArgument": grpc.StatusCode.INVALID_ARGUMENT,
    "OutOfRange": grpc.StatusCode.OUT_OF_RANGE,
    "Internal": grpc.StatusCode.INTERNAL,
    # The reshard epoch fence (service.transfer_ownership): a transfer
    # stamped with a dead ring's fingerprint must not commit, and the
    # sender must see a distinct, non-retryable answer.
    "FailedPrecondition": grpc.StatusCode.FAILED_PRECONDITION,
}


class GrpcServer:
    """One gRPC listener serving both services."""

    def __init__(
        self,
        service: V1Service,
        listen_address: str = "127.0.0.1:0",
        tls_conf=None,  # Optional[tls.TLSConfig] (file paths already resolved)
        # Handlers BLOCK on device rounds, so this pool caps in-flight
        # RPCs — and therefore how many concurrent callers one
        # coalescing window can merge (the convoy measured on the HTTP
        # edge, RESULTS.md round-5 A/B).  128 covers the reference's
        # 100-way benchmark fan-in; idle-blocked threads are cheap.
        max_workers: int = 128,
        max_conn_age_s: int = 0,
    ):
        self.service = service
        options = [
            ("grpc.max_receive_message_length", MAX_RECV_BYTES),
            ("grpc.so_reuseport", 0),
        ]
        if max_conn_age_s > 0:
            # GUBER_GRPC_MAX_CONN_AGE_SEC (daemon.go:91-96): rotate
            # long-lived client connections so load rebalances across a
            # changing cluster; same 30s grace the reference sets.
            options.append(("grpc.max_connection_age_ms", max_conn_age_s * 1000))
            options.append(("grpc.max_connection_age_grace_ms", 30 * 1000))
        self._server = grpc.server(
            ThreadPoolExecutor(max_workers=max_workers, thread_name_prefix="grpc"),
            options=options,
            interceptors=(MetricsInterceptor(service.metrics),),
        )
        self._server.add_generic_rpc_handlers(
            (_v1_handler(service), _peers_v1_handler(service))
        )
        host, _, port = listen_address.partition(":")
        target = f"{host or '127.0.0.1'}:{port or 0}"
        if tls_conf is not None and tls_conf.enabled:
            creds = server_credentials(tls_conf)
            bound = self._server.add_secure_port(target, creds)
        else:
            bound = self._server.add_insecure_port(target)
        if bound == 0:
            raise OSError(f"gRPC server failed to bind {target}")
        self.address = f"{host or '127.0.0.1'}:{bound}"

    def start(self) -> "GrpcServer":
        self._server.start()
        return self

    def close(self, grace_s: float = 0.5) -> None:
        self._server.stop(grace=grace_s).wait(timeout=grace_s + 1.0)


def server_credentials(tls_conf) -> grpc.ServerCredentials:
    """Build grpc server creds from a resolved TLSConfig (tls.go:118-263:
    cert chain + optional client-auth CA; require-and-verify maps to
    require_client_auth)."""
    with open(tls_conf.cert_file, "rb") as f:
        cert = f.read()
    with open(tls_conf.key_file, "rb") as f:
        key = f.read()
    root = None
    require = False
    if tls_conf.client_auth:
        ca_file = tls_conf.client_auth_ca_file or tls_conf.ca_file
        with open(ca_file, "rb") as f:
            root = f.read()
        require = tls_conf.client_auth == "require-and-verify"
    return grpc.ssl_server_credentials(
        [(key, cert)], root_certificates=root, require_client_auth=require
    )


def channel_credentials(tls_conf) -> grpc.ChannelCredentials:
    """Client-side creds: trust the configured CA, present this node's
    client cert under mTLS (tls.go:188-207 equivalent)."""
    root = None
    if tls_conf.ca_file:
        with open(tls_conf.ca_file, "rb") as f:
            root = f.read()
    key = cert = None
    cert_file = tls_conf.client_auth_cert_file or (
        tls_conf.cert_file if tls_conf.client_auth else ""
    )
    key_file = tls_conf.client_auth_key_file or (
        tls_conf.key_file if tls_conf.client_auth else ""
    )
    if cert_file:
        with open(cert_file, "rb") as f:
            cert = f.read()
        with open(key_file, "rb") as f:
            key = f.read()
    return grpc.ssl_channel_credentials(
        root_certificates=root, private_key=key, certificate_chain=cert
    )


def _abort_api_error(context: grpc.ServicerContext, e: ApiError):
    context.abort(_STATUS_CODES.get(e.code, grpc.StatusCode.UNKNOWN), e.message)


def _v1_handler(service: V1Service) -> grpc.GenericRpcHandler:
    def get_rate_limits(request: pb.GetRateLimitsReq, context) -> pb.GetRateLimitsResp:
        try:
            result = service.get_rate_limits_columns(wire.columns_from_pb(request))
            return wire.columns_to_pb(result)
        except ApiError as e:
            _abort_api_error(context, e)

    def get_rate_limits_columns(
        request: pc_pb.PeerColumnsReq, context
    ) -> pc_pb.IngressColumnsResp:
        """The public columnar ingress (the front door, wire.py "public
        columnar ingress"): proto columns decode straight into
        IngressColumns and the result arrays — owner annotation
        included — serialize straight back, no per-lane dataclasses
        either way."""
        try:
            # Untrusted-client validation, the HTTP frame edge's twin
            # (wire._decode_req_frame validate=True) — the two
            # transports must not diverge.  Ragged columns would crash
            # the decode (or silently truncate); an out-of-range
            # algorithm must not reach the kernel as a garbage branch
            # selector.
            n = len(request.names)
            if any(
                len(col) != n
                for col in (
                    request.unique_keys, request.algorithm,
                    request.behavior, request.hits, request.limit,
                    request.duration,
                )
            ):
                raise ApiError(
                    "InvalidArgument", "column length mismatch"
                )
            with profiling.scope("ingress.parse"):
                cols = wire.ingress_from_peer_columns_pb(request)
            if len(cols) and bool(
                ((cols.algorithm < 0) | (cols.algorithm > 1)).any()
            ):
                raise ApiError(
                    "InvalidArgument", "algorithm out of range"
                )
            result = service.get_rate_limits_columns(
                cols, max_lanes=INGRESS_COLUMNS_MAX_LANES,
            )
            with profiling.scope("response.encode"):
                resp = wire.result_to_ingress_columns_pb(result)
            service.metrics.ingress_columns_batches.labels(
                encoding="proto"
            ).inc()
            return resp
        except ApiError as e:
            _abort_api_error(context, e)

    def health_check(request: pb.HealthCheckReq, context) -> pb.HealthCheckResp:
        return wire.health_to_pb(service.health_check())

    methods = {
        "GetRateLimits": grpc.unary_unary_rpc_method_handler(
            get_rate_limits,
            request_deserializer=pb.GetRateLimitsReq.FromString,
            response_serializer=pb.GetRateLimitsResp.SerializeToString,
        ),
        "HealthCheck": grpc.unary_unary_rpc_method_handler(
            health_check,
            request_deserializer=pb.HealthCheckReq.FromString,
            response_serializer=pb.HealthCheckResp.SerializeToString,
        ),
    }
    if service.serves_ingress_columns:
        # The shared advertisement rule (V1Service.serves_ingress_
        # columns): GUBER_INGRESS_COLUMNS=0 — or a store without
        # columnar support — withholds the method entirely, so clients
        # see UNIMPLEMENTED, exactly what a pre-columns daemon answers
        # (the mixed-version interop mode).
        methods["GetRateLimitsColumns"] = grpc.unary_unary_rpc_method_handler(
            get_rate_limits_columns,
            request_deserializer=pc_pb.PeerColumnsReq.FromString,
            response_serializer=pc_pb.IngressColumnsResp.SerializeToString,
        )
    return grpc.method_handlers_generic_handler(V1_SERVICE, methods)


def _peers_v1_handler(service: V1Service) -> grpc.GenericRpcHandler:
    def get_peer_rate_limits(
        request: peers_pb.GetPeerRateLimitsReq, context
    ) -> peers_pb.GetPeerRateLimitsResp:
        try:
            result = service.get_peer_rate_limits_columns(
                wire.columns_from_pb(request)
            )
            return wire.columns_to_peer_pb(result)
        except ApiError as e:
            _abort_api_error(context, e)

    def get_peer_rate_limits_columns(
        request: pc_pb.PeerColumnsReq, context
    ) -> pc_pb.PeerColumnsResp:
        """The columnar peer hop (peers_columns.proto): proto columns
        decode straight into IngressColumns and the result arrays
        serialize straight back — no per-lane dataclasses either way."""
        try:
            with profiling.scope("ingress.parse"):
                cols = wire.ingress_from_peer_columns_pb(request)
            result = service.get_peer_rate_limits_columns(
                cols, max_lanes=PEER_COLUMNS_MAX_LANES,
            )
            with profiling.scope("response.encode"):
                return wire.result_to_peer_columns_pb(result)
        except ApiError as e:
            _abort_api_error(context, e)

    def update_peer_globals(
        request: peers_pb.UpdatePeerGlobalsReq, context
    ) -> peers_pb.UpdatePeerGlobalsResp:
        service.update_peer_globals(wire.update_globals_req_from_pb(request))
        return peers_pb.UpdatePeerGlobalsResp()

    def update_peer_globals_columns(
        request: pc_pb.GlobalsColumnsReq, context
    ) -> peers_pb.UpdatePeerGlobalsResp:
        """Columnar GLOBAL broadcast receive (peers_columns.proto
        GlobalsColumnsReq): the whole batch decodes into arrays and
        commits as ONE replica scatter (store.set_replica_batch)."""
        try:
            service.update_peer_globals_columns(
                wire.globals_cols_from_pb(request)
            )
            return peers_pb.UpdatePeerGlobalsResp()
        except ApiError as e:
            _abort_api_error(context, e)

    def update_region_columns(
        request: pc_pb.RegionColumnsReq, context
    ) -> pc_pb.RegionColumnsResp:
        """Cross-region federation receive (federation.py): one
        columnar hit batch from a remote region's flush, applied
        through the same columnar path a classic per-item send lands
        in (service.update_region_columns)."""
        try:
            applied = service.update_region_columns(
                wire.region_cols_from_pb(request)
            )
            return pc_pb.RegionColumnsResp(applied=applied)
        except ApiError as e:
            _abort_api_error(context, e)

    def transfer_ownership(
        request: pc_pb.TransferColumnsReq, context
    ) -> pc_pb.TransferResp:
        """Ownership-transfer receive (elastic membership, reshard.py):
        the whole batch merge-commits through ONE batched device
        gather+scatter (store.commit_transfer); a dead-epoch batch is
        fenced with FAILED_PRECONDITION."""
        try:
            committed, rejected = service.transfer_ownership(
                wire.transfer_cols_from_pb(request)
            )
            return pc_pb.TransferResp(committed=committed, rejected=rejected)
        except ApiError as e:
            _abort_api_error(context, e)

    methods = {
        "GetPeerRateLimits": grpc.unary_unary_rpc_method_handler(
            get_peer_rate_limits,
            request_deserializer=peers_pb.GetPeerRateLimitsReq.FromString,
            response_serializer=peers_pb.GetPeerRateLimitsResp.SerializeToString,
        ),
        "UpdatePeerGlobals": grpc.unary_unary_rpc_method_handler(
            update_peer_globals,
            request_deserializer=peers_pb.UpdatePeerGlobalsReq.FromString,
            response_serializer=peers_pb.UpdatePeerGlobalsResp.SerializeToString,
        ),
    }
    if service.serves_peer_columns:
        # The shared advertisement rule (V1Service.serves_peer_columns):
        # GUBER_PEER_COLUMNS=0 — or a store without columnar support —
        # withholds the method entirely, so callers see UNIMPLEMENTED,
        # exactly what a pre-columns daemon answers (the mixed-version
        # interop mode).
        methods["GetPeerRateLimitsColumns"] = grpc.unary_unary_rpc_method_handler(
            get_peer_rate_limits_columns,
            request_deserializer=pc_pb.PeerColumnsReq.FromString,
            response_serializer=pc_pb.PeerColumnsResp.SerializeToString,
        )
    if service.serves_global_columns:
        # Same advertisement rule as the forward hop, on its own knob
        # (V1Service.serves_global_columns): GUBER_GLOBAL_COLUMNS=0
        # withholds the method so senders see UNIMPLEMENTED — exactly
        # what a pre-columns daemon answers — and fall back to the
        # classic per-item UpdatePeerGlobals.
        methods["UpdatePeerGlobalsColumns"] = grpc.unary_unary_rpc_method_handler(
            update_peer_globals_columns,
            request_deserializer=pc_pb.GlobalsColumnsReq.FromString,
            response_serializer=peers_pb.UpdatePeerGlobalsResp.SerializeToString,
        )
    if service.serves_region_columns:
        # Same advertisement rule on the federation knob
        # (V1Service.serves_region_columns): GUBER_REGION_COLUMNS=0
        # withholds the method so senders see UNIMPLEMENTED — exactly
        # what a pre-federation daemon answers — and fall back sticky
        # to the classic per-item GetPeerRateLimits encoding.
        methods["UpdateRegionColumns"] = grpc.unary_unary_rpc_method_handler(
            update_region_columns,
            request_deserializer=pc_pb.RegionColumnsReq.FromString,
            response_serializer=pc_pb.RegionColumnsResp.SerializeToString,
        )
    if service.serves_reshard:
        # Same advertisement rule on the reshard knob
        # (V1Service.serves_reshard): GUBER_RESHARD=0 withholds the
        # method so senders see UNIMPLEMENTED — exactly what a
        # pre-reshard daemon answers — and degrade sticky to the
        # classic (reset-on-move) behavior for this peer.
        methods["TransferOwnership"] = grpc.unary_unary_rpc_method_handler(
            transfer_ownership,
            request_deserializer=pc_pb.TransferColumnsReq.FromString,
            response_serializer=pc_pb.TransferResp.SerializeToString,
        )
    return grpc.method_handlers_generic_handler(PEERS_V1_SERVICE, methods)
