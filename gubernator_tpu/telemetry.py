"""XLA / device telemetry: compile tracking, recompile-storm detection,
per-program execution timings, device memory sampling.

PRs 4 and 6 made the HOST side of the daemon observable; this module
watches the layer below it — the XLA programs the dispatch pipeline
launches.  A shape-churn recompile storm (a batch size wobbling across
pad buckets after warmup, a config change invalidating a donated
layout) otherwise reads only as mysterious latency: each backend
compile steals tens of ms (CPU) to tens of seconds (remote tunnel)
from whatever request triggered it.

Three signals, all host-side (the occupancy-from-readback rule: the
plane adds ZERO device programs):

* **Compile tracking** — a `jax.monitoring` duration listener counts
  and times every backend compile, attributed to the PROGRAM LABEL the
  launching thread declared via `program(label)` (labels name program
  identity: solo vs fused-K dispatches, wide/narrow wires, mesh twins,
  the GLOBAL sync collective, reshard gather/commit).  Compilation
  runs synchronously on the calling thread, so thread-local
  attribution is exact.

* **Steady-state recompiles** — after `mark_steady()` (the daemon
  calls it once startup warmup finishes; bench legs call it between
  warm and measured epochs) any further backend compile is SHAPE CHURN
  by definition and is counted per label.  A burst of them
  (`GUBER_XLA_STORM` compiles inside `GUBER_XLA_STORM_WINDOW` seconds)
  fires the PR 4 flight-recorder auto-dump (`recompile-storm` event)
  while the evidence of WHICH programs churned is still in the rings.

* **Execution timings** — `program(label)` also times the launch call
  itself (enqueue wall time, not device completion — the async
  dispatch returns at enqueue), aggregated per label and drained per
  metrics scrape like the dispatch-stage gauges.

`device_snapshot()` samples per-device memory (`memory_stats()` where
the backend reports it — TPU/GPU) and live-buffer counts/bytes
(`jax.live_arrays()`, the CPU fallback) — served by `GET /debug/device`
and the `gubernator_device_*` gauges.  Sampling happens per scrape /
debug request only, never on the hot path.

State is MODULE-GLOBAL like the tracing flight recorder and the
saturation plane: one daemon per process in production; in-process
multi-daemon tests share one plane.  `GUBER_XLA_TELEMETRY=0` disables
everything: `program()` returns a shared no-op context (one branch on
the hot path) and the listener body returns immediately.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from . import profiling, tracing
from .utils.logging import category_logger

logger = category_logger("telemetry")

# The jax.monitoring duration event one XLA backend compile emits
# (jax 0.4.x: _src/interpreters/pxla.py).  Trace/lowering events are
# deliberately NOT counted — one logical compile emits several of
# them, and the backend compile is the one that costs real time.
_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

_UNLABELED = "unlabeled"


def _env_flag(name: str, default: bool) -> bool:
    v = os.environ.get(name, "")
    if not v:
        return default
    return v.strip().lower() in ("1", "true", "yes", "on")


def _env_int(name: str, default: int) -> int:
    v = os.environ.get(name, "")
    try:
        return int(v) if v else default
    except ValueError:
        return default


def _env_duration(name: str, default_s: float) -> float:
    """Go-duration env knob (the GUBER_* convention: '60s', '2m'; a
    bare number means ms), warn-free fallback on garbage — module
    import must never raise."""
    v = os.environ.get(name, "")
    if not v:
        return default_s
    try:
        from .config import parse_duration

        return parse_duration(v)
    except Exception:  # noqa: BLE001 — import-time safety
        return default_s


_ENABLED: bool = _env_flag("GUBER_XLA_TELEMETRY", True)
# Recompile-storm trip: >= STORM_THRESHOLD steady-state compiles within
# STORM_WINDOW_S seconds fires the flight-recorder dump.  Module-level
# env reads cover library embeddings; daemons re-apply their parsed
# config via set_storm (config-file -> env -> default precedence).
STORM_THRESHOLD = max(_env_int("GUBER_XLA_STORM", 3), 1)
STORM_WINDOW_S = max(_env_duration("GUBER_XLA_STORM_WINDOW", 60.0), 0.001)
_STORM_MIN_INTERVAL_S = 30.0  # between storm events (dump rate limit)

_lock = threading.Lock()
_tls = threading.local()

# label -> [count, total_s, max_s] (cumulative, process lifetime)
_compiles: Dict[str, list] = {}
# label -> count of compiles AFTER mark_steady() (shape churn)
_steady_recompiles: Dict[str, int] = {}
# label -> [count, total_s, max_s] execution (enqueue) wall; drained
# per metrics scrape (the dispatch-stage gauge convention)
_exec_stats: Dict[str, list] = {}
# distinct jitted callables created by the program caches
# (buckets.fused_packed_jit and the mesh twin note creations here)
_programs_created: Dict[str, int] = {}
_steady = False
_recent_steady_compiles: "deque[float]" = deque()
_storms = 0
_last_storm = [-float("inf")]
_listener_attempted = [False]
_listener_registered = [False]


def set_enabled(flag: bool) -> None:
    """Process-wide switch (the daemon applies its parsed
    GUBER_XLA_TELEMETRY at startup, like tracing.set_sample_rate)."""
    global _ENABLED
    _ENABLED = bool(flag)
    if _ENABLED:
        _ensure_listener()


def set_storm(threshold: int, window_s: float) -> None:
    """Process-wide storm-trip parameters (the daemon applies its
    parsed GUBER_XLA_STORM / GUBER_XLA_STORM_WINDOW at startup — the
    config-file -> env -> default precedence every other knob honors;
    the module-level env read only covers library embeddings)."""
    global STORM_THRESHOLD, STORM_WINDOW_S
    STORM_THRESHOLD = max(int(threshold), 1)
    STORM_WINDOW_S = max(float(window_s), 0.001)


def enabled() -> bool:
    return _ENABLED


def _ensure_listener() -> None:
    """Register the jax.monitoring compile listener exactly once.
    Listeners cannot be individually unregistered, so the body gates on
    _ENABLED instead — compile events are rare, the check is free."""
    with _lock:
        if _listener_attempted[0]:
            return
        _listener_attempted[0] = True
    try:
        import jax.monitoring as _mon

        _mon.register_event_duration_secs_listener(_on_duration_event)
        _listener_registered[0] = True
    except Exception as e:  # noqa: BLE001 — telemetry must never fail imports
        logger.warning("xla telemetry listener unavailable: %s", e)


def listener_active() -> bool:
    """Whether compile counting can actually observe compiles: the
    plane is on AND the jax.monitoring listener registered.  Consumers
    that would read an always-0 count as a verdict (the bench
    steady_state_recompiles gate) must SKIP instead when this is
    False."""
    return _ENABLED and _listener_registered[0]


def _on_duration_event(name: str, dur_s: float, **_kw) -> None:
    if not _ENABLED or name != _COMPILE_EVENT:
        return
    label = getattr(_tls, "program", None) or _UNLABELED
    lazy = bool(getattr(_tls, "program_lazy", False))
    now = time.monotonic()
    storm = None
    with _lock:
        st = _compiles.setdefault(label, [0, 0.0, 0.0])
        st[0] += 1
        st[1] += dur_s
        st[2] = max(st[2], dur_s)
        if _steady and not lazy:
            _steady_recompiles[label] = _steady_recompiles.get(label, 0) + 1
            _recent_steady_compiles.append(now)
            while (_recent_steady_compiles
                   and now - _recent_steady_compiles[0] > STORM_WINDOW_S):
                _recent_steady_compiles.popleft()
            if (len(_recent_steady_compiles) >= STORM_THRESHOLD
                    and now - _last_storm[0] >= _STORM_MIN_INTERVAL_S):
                _last_storm[0] = now
                globals()["_storms"] = _storms + 1
                storm = len(_recent_steady_compiles)
    if storm is not None:
        # The PR 4 auto-dump path — OUTSIDE the telemetry lock (the
        # dump serializes and logs; a slow handler must not stall
        # whichever dispatcher is unlucky enough to be compiling).
        tracing.record_event(
            "recompile-storm", compiles=storm, window_s=STORM_WINDOW_S,
            label=label,
        )
        logger.warning(
            "XLA recompile storm: %d steady-state compiles in %.0fs "
            "(last label %s) — shape churn after warmup",
            storm, STORM_WINDOW_S, label,
        )


# ---------------------------------------------------------------------
# Program label scopes (the launch-site hook)
# ---------------------------------------------------------------------
class _NoopProgram:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP = _NoopProgram()


class _Program:
    __slots__ = ("label", "lazy", "_prev", "_prev_lazy", "_t0")

    def __init__(self, label: str, lazy: bool):
        self.label = label
        self.lazy = lazy

    def __enter__(self):
        self._prev = getattr(_tls, "program", None)
        self._prev_lazy = getattr(_tls, "program_lazy", False)
        _tls.program = self.label
        _tls.program_lazy = self.lazy
        if profiling.enabled():
            # Mirror the label into the cost-profiler's cross-thread
            # registry (thread-locals are invisible to the sampler):
            # samples taken during this launch carry program identity.
            profiling.set_program(self.label)
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dt = time.perf_counter() - self._t0
        _tls.program = self._prev
        _tls.program_lazy = self._prev_lazy
        # Unconditional (unlike the enter-side mirror): if the profiler
        # was toggled off mid-launch, a conditional restore would park
        # this label in the cross-thread registry forever and every
        # later sample of this thread would carry it.
        profiling.set_program(self._prev)
        with _lock:
            st = _exec_stats.setdefault(self.label, [0, 0.0, 0.0])
            st[0] += 1
            st[1] += dt
            st[2] = max(st[2], dt)
        return False


def program(label: str, lazy: bool = False):
    """Label scope for one program launch: attributes any compile the
    call triggers to `label` and aggregates the call's wall time.  The
    disabled path is one branch returning a shared no-op.

    `lazy=True` declares the program DELIBERATELY warmup-deferred
    (mesh warmup's own carve-outs: wide int64 wires, the reshard
    drain/commit pair — programs that structurally can only compile
    after mark_steady, e.g. the first membership change): their
    compiles are counted and timed per label but do NOT feed the
    steady-state recompile counter or the storm trip, so a healthy
    reshard event or a rare wide batch can never fire a false
    recompile-storm dump."""
    if not _ENABLED:
        return _NOOP
    return _Program(label, lazy)


def note_program_created(label: str) -> None:
    """One distinct jitted callable materialized by a program cache
    (buckets.fused_packed_jit / the mesh twin): counted so the
    program-population growth is visible even before first dispatch."""
    if not _ENABLED:
        return
    with _lock:
        _programs_created[label] = _programs_created.get(label, 0) + 1


# ---------------------------------------------------------------------
# Warmup fencing
# ---------------------------------------------------------------------
def begin_warmup() -> None:
    """Re-open the warmup window (daemon startup warmup; each daemon
    start in an in-process test cluster re-opens it)."""
    global _steady
    with _lock:
        _steady = False


def mark_steady() -> None:
    """Warmup complete: from here on every backend compile counts as a
    steady-state recompile (shape churn)."""
    global _steady
    with _lock:
        _steady = True
        _recent_steady_compiles.clear()


def is_steady() -> bool:
    return _steady


# ---------------------------------------------------------------------
# Read side
# ---------------------------------------------------------------------
def compile_count() -> int:
    with _lock:
        return sum(st[0] for st in _compiles.values())


def steady_recompile_count() -> int:
    with _lock:
        return sum(_steady_recompiles.values())


def compile_snapshot() -> Dict[str, dict]:
    with _lock:
        return {
            label: {
                "count": st[0],
                "total_s": round(st[1], 6),
                "max_s": round(st[2], 6),
                "steady_recompiles": _steady_recompiles.get(label, 0),
            }
            for label, st in sorted(_compiles.items())
        }


def take_exec_stats() -> Dict[str, tuple]:
    """Drain per-program execution aggregates accumulated since the
    last call: {label: (count, total_s, max_s)}."""
    with _lock:
        out = {k: tuple(v) for k, v in _exec_stats.items()}
        _exec_stats.clear()
    return out


def snapshot() -> dict:
    """The GET /debug/device document (minus live device stats, which
    device_snapshot() adds — they cost a live-buffer walk)."""
    with _lock:
        exec_view = {
            label: {
                "count": st[0],
                "total_s": round(st[1], 6),
                "max_s": round(st[2], 6),
            }
            for label, st in sorted(_exec_stats.items())
        }
        created = dict(sorted(_programs_created.items()))
        storms = _storms
    return {
        "enabled": _ENABLED,
        "steady": _steady,
        "compiles": compile_snapshot(),
        "compileTotal": compile_count(),
        "steadyRecompiles": steady_recompile_count(),
        "recompileStorms": storms,
        "stormThreshold": STORM_THRESHOLD,
        "stormWindowS": STORM_WINDOW_S,
        "programRuns": exec_view,
        "programsCreated": created,
    }


def device_snapshot() -> List[dict]:
    """Per-device memory / live-buffer stats.  `memory_stats()` is
    backend-reported (TPU/GPU; None on CPU); the live-array walk is the
    universal fallback — both are read on scrape / debug request only."""
    if not _ENABLED:
        return []
    try:
        import jax
    except Exception:  # noqa: BLE001 — no jax, no devices
        return []
    per_dev: Dict[str, dict] = {}
    try:
        for d in jax.local_devices():
            row = {"device": str(d), "platform": d.platform}
            try:
                stats = d.memory_stats()
            except Exception:  # noqa: BLE001 — backend without stats
                stats = None
            if stats:
                for k in ("bytes_in_use", "peak_bytes_in_use",
                          "bytes_limit", "num_allocs"):
                    if k in stats:
                        row[k] = int(stats[k])
            row["live_buffers"] = 0
            row["live_bytes"] = 0
            per_dev[str(d)] = row
        for arr in jax.live_arrays():
            try:
                devs = arr.devices()
                nbytes = int(arr.nbytes) // max(len(devs), 1)
                for d in devs:
                    row = per_dev.get(str(d))
                    if row is not None:
                        row["live_buffers"] += 1
                        row["live_bytes"] += nbytes
            except Exception:  # noqa: BLE001 — deleted/donated mid-walk
                continue
    except Exception as e:  # noqa: BLE001 — diagnostics must never raise
        logger.warning("device snapshot failed: %s", e)
    return list(per_dev.values())


def reset(steady: bool = False) -> None:
    """Test hook: clear every aggregate (mirrors tracing.reset)."""
    global _steady, _storms
    with _lock:
        _compiles.clear()
        _steady_recompiles.clear()
        _exec_stats.clear()
        _programs_created.clear()
        _recent_steady_compiles.clear()
        _steady = steady
        _storms = 0
        _last_storm[0] = -float("inf")
    _tls.program = None
    _tls.program_lazy = False


# Module init: honor the environment; the listener registers lazily on
# first enable so disabled library embeddings never touch jax.
if _ENABLED:
    _ensure_listener()
