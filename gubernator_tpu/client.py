"""Client helpers (reference client.go + python/gubernator/__init__.py).

`GrpcV1Client` (via `dial_v1_server`) speaks the gRPC V1 service — the
reference's DialV1Server path (client.go:41-57).  `V1Client` speaks the
HTTP/JSON gateway.  Both expose the same get_rate_limits / health_check
surface; `sleep_until_reset` is the Python client's convenience
(python/gubernator/__init__.py:12-17).
"""

from __future__ import annotations

import datetime
import http.client
import json
import random
import ssl
import string
import time
from typing import List, Optional

from .types import (
    MILLISECOND,  # noqa: F401 — duration consts re-exported (client.go:30-34)
    MINUTE,  # noqa: F401
    SECOND,  # noqa: F401
    GetRateLimitsRequest,
    GetRateLimitsResponse,
    HealthCheckResponse,
    PeerInfo,
    RateLimitResponse,
)


class V1Client:
    def __init__(
        self,
        endpoint: str = "127.0.0.1:1050",
        timeout_s: float = 5.0,
        tls_context: Optional[ssl.SSLContext] = None,
    ):
        self.endpoint = endpoint
        self.timeout_s = timeout_s
        self.tls_context = tls_context

    def _connect(self):
        host, _, port = self.endpoint.partition(":")
        if self.tls_context is not None:
            return http.client.HTTPSConnection(
                host, int(port or 443), timeout=self.timeout_s, context=self.tls_context
            )
        return http.client.HTTPConnection(host, int(port or 80), timeout=self.timeout_s)

    def _request(self, method: str, path: str, payload: Optional[dict] = None) -> dict:
        conn = self._connect()
        try:
            body = json.dumps(payload).encode() if payload is not None else None
            conn.request(
                method, path, body=body, headers={"Content-Type": "application/json"}
            )
            r = conn.getresponse()
            raw = r.read()
            data = json.loads(raw) if raw else {}
            if r.status != 200:
                raise RuntimeError(
                    f"{path} returned HTTP {r.status}: {data.get('message', raw[:200])}"
                )
            return data
        finally:
            conn.close()

    def get_rate_limits(self, req: GetRateLimitsRequest) -> GetRateLimitsResponse:
        return GetRateLimitsResponse.from_json(
            self._request("POST", "/v1/GetRateLimits", req.to_json())
        )

    def health_check(self) -> HealthCheckResponse:
        return HealthCheckResponse.from_json(self._request("GET", "/v1/HealthCheck"))

    def metrics_text(self) -> str:
        conn = self._connect()
        try:
            conn.request("GET", "/metrics")
            return conn.getresponse().read().decode()
        finally:
            conn.close()


class GrpcV1Client:
    """gRPC client for the V1 service (client.go:41-57 DialV1Server)."""

    def __init__(self, endpoint: str, timeout_s: float = 5.0, credentials=None):
        import grpc

        from .proto import V1_SERVICE
        from .proto import gubernator_pb2 as pb

        self.endpoint = endpoint
        self.timeout_s = timeout_s
        if credentials is not None:
            self._channel = grpc.secure_channel(endpoint, credentials)
        else:
            self._channel = grpc.insecure_channel(endpoint)
        self._get_rate_limits = self._channel.unary_unary(
            f"/{V1_SERVICE}/GetRateLimits",
            request_serializer=pb.GetRateLimitsReq.SerializeToString,
            response_deserializer=pb.GetRateLimitsResp.FromString,
        )
        self._health_check = self._channel.unary_unary(
            f"/{V1_SERVICE}/HealthCheck",
            request_serializer=pb.HealthCheckReq.SerializeToString,
            response_deserializer=pb.HealthCheckResp.FromString,
        )

    def get_rate_limits(self, req: GetRateLimitsRequest) -> GetRateLimitsResponse:
        from . import wire

        m = self._get_rate_limits(
            wire.get_rate_limits_req_to_pb(req), timeout=self.timeout_s
        )
        return wire.get_rate_limits_resp_from_pb(m)

    def health_check(self) -> HealthCheckResponse:
        from . import wire
        from .proto import gubernator_pb2 as pb

        return wire.health_from_pb(self._health_check(pb.HealthCheckReq(), timeout=self.timeout_s))

    def close(self) -> None:
        self._channel.close()


def dial_v1_server(address: str, credentials=None, timeout_s: float = 5.0) -> GrpcV1Client:
    """client.go:41-57."""
    return GrpcV1Client(address, timeout_s=timeout_s, credentials=credentials)


def sleep_until_reset(rate_limit: RateLimitResponse) -> None:
    """python/gubernator/__init__.py:12-17."""
    now = time.time()
    delta = rate_limit.reset_time / 1000.0 - now
    if delta > 0:
        time.sleep(delta)


def to_timestamp(duration: datetime.timedelta) -> int:
    """Duration -> unix-millisecond count for request duration fields
    (client.go:62-64)."""
    return int(duration.total_seconds() * 1000)


def from_unix_milliseconds(ts: int) -> datetime.datetime:
    """Unix-ms timestamp -> aware datetime (client.go:76-78)."""
    return datetime.datetime.fromtimestamp(ts / 1000.0, tz=datetime.timezone.utc)


def from_timestamp(ts: int) -> datetime.timedelta:
    """Unix-ms timestamp -> elapsed time since it (now - ts, matching
    client.go:69-72): positive for past timestamps, NEGATIVE for future
    ones.  To wait out a reset_time, use sleep_until_reset, not this."""
    return datetime.datetime.now(tz=datetime.timezone.utc) - from_unix_milliseconds(ts)


def random_peer(peers: List[PeerInfo]) -> PeerInfo:
    """client.go:81-86."""
    return random.choice(peers)


def random_string(prefix: str = "", n: int = 10) -> str:
    """client.go:89-97."""
    return prefix + "".join(random.choices(string.ascii_lowercase + string.digits, k=n))