"""Client helpers (reference client.go + python/gubernator/__init__.py).

`V1Client` speaks the HTTP/JSON gateway (the reference's
DialV1Server gRPC channel maps to the same surface).  Includes the
Python client's `sleep_until_reset` convenience.
"""

from __future__ import annotations

import http.client
import json
import random
import ssl
import string
import time
from typing import List, Optional

from .types import (
    MILLISECOND,  # noqa: F401 — duration consts re-exported (client.go:30-34)
    MINUTE,  # noqa: F401
    SECOND,  # noqa: F401
    GetRateLimitsRequest,
    GetRateLimitsResponse,
    HealthCheckResponse,
    PeerInfo,
    RateLimitResponse,
)


class V1Client:
    def __init__(
        self,
        endpoint: str = "127.0.0.1:1050",
        timeout_s: float = 5.0,
        tls_context: Optional[ssl.SSLContext] = None,
    ):
        self.endpoint = endpoint
        self.timeout_s = timeout_s
        self.tls_context = tls_context

    def _connect(self):
        host, _, port = self.endpoint.partition(":")
        if self.tls_context is not None:
            return http.client.HTTPSConnection(
                host, int(port or 443), timeout=self.timeout_s, context=self.tls_context
            )
        return http.client.HTTPConnection(host, int(port or 80), timeout=self.timeout_s)

    def _request(self, method: str, path: str, payload: Optional[dict] = None) -> dict:
        conn = self._connect()
        try:
            body = json.dumps(payload).encode() if payload is not None else None
            conn.request(
                method, path, body=body, headers={"Content-Type": "application/json"}
            )
            r = conn.getresponse()
            raw = r.read()
            data = json.loads(raw) if raw else {}
            if r.status != 200:
                raise RuntimeError(
                    f"{path} returned HTTP {r.status}: {data.get('message', raw[:200])}"
                )
            return data
        finally:
            conn.close()

    def get_rate_limits(self, req: GetRateLimitsRequest) -> GetRateLimitsResponse:
        return GetRateLimitsResponse.from_json(
            self._request("POST", "/v1/GetRateLimits", req.to_json())
        )

    def health_check(self) -> HealthCheckResponse:
        return HealthCheckResponse.from_json(self._request("GET", "/v1/HealthCheck"))

    def metrics_text(self) -> str:
        conn = self._connect()
        try:
            conn.request("GET", "/metrics")
            return conn.getresponse().read().decode()
        finally:
            conn.close()


def sleep_until_reset(rate_limit: RateLimitResponse) -> None:
    """python/gubernator/__init__.py:12-17."""
    now = time.time()
    delta = rate_limit.reset_time / 1000.0 - now
    if delta > 0:
        time.sleep(delta)


def random_peer(peers: List[PeerInfo]) -> PeerInfo:
    """client.go:81-86."""
    return random.choice(peers)


def random_string(prefix: str = "", n: int = 10) -> str:
    """client.go:89-97."""
    return prefix + "".join(random.choices(string.ascii_lowercase + string.digits, k=n))