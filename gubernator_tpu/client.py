"""Client helpers (reference client.go + python/gubernator/__init__.py).

`GrpcV1Client` (via `dial_v1_server`) speaks the gRPC V1 service — the
reference's DialV1Server path (client.go:41-57).  `V1Client` speaks the
HTTP/JSON gateway.  Both expose the same get_rate_limits / health_check
surface; `sleep_until_reset` is the Python client's convenience
(python/gubernator/__init__.py:12-17).

`ColumnsV1Client` is the columnar front-door client (architecture.md
"Columnar pipeline: the front door"): checks accumulate client-side
into numpy-backed column sub-batches behind an adaptive BatchWindow,
flush as ONE GUBC ingress frame each, and pipeline multiple in-flight
frames per connection; a daemon without the columnar surface
(pre-columns build or GUBER_INGRESS_COLUMNS=0) answers the first frame
with 400/404 and the client falls back sticky to the classic JSON
encoding — wire-identical to a plain V1Client from then on.
"""

from __future__ import annotations

import datetime
import http.client
import json
import random
import socket
import ssl
import string
import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import List, Optional

import numpy as np

from .types import (
    MILLISECOND,  # noqa: F401 — duration consts re-exported (client.go:30-34)
    MINUTE,  # noqa: F401
    SECOND,  # noqa: F401
    GetRateLimitsRequest,
    GetRateLimitsResponse,
    HealthCheckResponse,
    PeerInfo,
    RateLimitRequest,
    RateLimitResponse,
)


def _uds_endpoint(endpoint: str) -> Optional[str]:
    """Socket path of a `unix:///path` endpoint, else None.  The UDS
    lane (GUBER_UDS_PATH on the native edge) speaks the identical
    HTTP/1.1 + GUBC protocol over an AF_UNIX stream — same clients,
    same bytes, no TCP stack."""
    if endpoint.startswith("unix://"):
        return endpoint[len("unix://"):]
    return None


class _UnixHTTPConnection(http.client.HTTPConnection):
    """http.client over an AF_UNIX stream (the classic-JSON leg of a
    unix:// target; the frame leg rides _PipelinedConn)."""

    def __init__(self, path: str, timeout_s: float):
        super().__init__("localhost", timeout=timeout_s)
        self._uds_path = path

    def connect(self):  # noqa: D102 — stdlib override
        self.sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self.sock.settimeout(self.timeout)
        self.sock.connect(self._uds_path)


class V1Client:
    """HTTP/JSON gateway client.

    Connections are persistent (HTTP/1.1 keep-alive, one per calling
    thread) — the pre-PR client paid a TCP handshake per request.  A
    server may close an idle kept-alive socket at any time; the expiry
    race (RemoteDisconnected / reset on a PREVIOUSLY-USED connection)
    is retried once on a fresh connection transparently, the urllib3
    retry rule — the request provably never reached a handler, so the
    retry cannot double-count.  Failures on a fresh connection surface
    to the caller unchanged.

    `endpoint` may be host:port or `unix:///path` (the native edge's
    same-host UDS lane, GUBER_UDS_PATH); TLS does not apply to UDS
    targets."""

    def __init__(
        self,
        endpoint: str = "127.0.0.1:1050",
        timeout_s: float = 5.0,
        tls_context: Optional[ssl.SSLContext] = None,
    ):
        self.endpoint = endpoint
        self.timeout_s = timeout_s
        self.tls_context = tls_context
        self._local = threading.local()  # per-thread persistent conn

    def _connect(self):
        uds = _uds_endpoint(self.endpoint)
        if uds is not None:
            if self.tls_context is not None:
                raise ValueError("TLS is not supported over unix:// targets")
            return _UnixHTTPConnection(uds, self.timeout_s)
        host, _, port = self.endpoint.partition(":")
        if self.tls_context is not None:
            return http.client.HTTPSConnection(
                host, int(port or 443), timeout=self.timeout_s, context=self.tls_context
            )
        return http.client.HTTPConnection(host, int(port or 80), timeout=self.timeout_s)

    def _drop_conn(self) -> None:
        conn = getattr(self._local, "conn", None)
        if conn is not None:
            try:
                conn.close()
            except OSError:
                pass
            self._local.conn = None

    def _roundtrip(self, method: str, path: str, body: Optional[bytes],
                   content_type: str = "application/json"):
        """One request over this thread's persistent connection;
        returns (status, raw body).  Stale keep-alive sockets retry
        once (see class docstring).  The retry covers ONLY the phases
        where the request provably never executed — the send, and a
        RemoteDisconnected BEFORE any status line (the server closed
        the idle socket without answering).  Once a status line has
        arrived the handler ran, so a failure while reading the body
        must surface: resending a POST there would double-count."""
        for _ in range(2):
            fresh = getattr(self._local, "conn", None) is None
            try:
                if fresh:
                    self._local.conn = self._connect()
                conn = self._local.conn
                conn.request(
                    method, path, body=body,
                    headers={"Content-Type": content_type},
                )
                r = conn.getresponse()
            except (
                http.client.RemoteDisconnected,
                BrokenPipeError,
                ConnectionResetError,
            ):
                self._drop_conn()
                if fresh:
                    # A NEW connection failing is a real server problem,
                    # not the keep-alive expiry race — surface it.
                    raise
                # Reused socket the server closed while idle: no status
                # line was ever received, so the request was not
                # answered and the close predates (or raced) our bytes
                # — one transparent retry is safe.
                continue
            except (OSError, http.client.HTTPException):
                self._drop_conn()
                raise
            try:
                raw = r.read()
            except (OSError, http.client.HTTPException):
                # Status received = the handler executed; a body-read
                # failure is NOT retry-safe (the urllib3 rule's limit).
                self._drop_conn()
                raise
            if r.will_close:
                self._drop_conn()
            return r.status, raw
        raise RuntimeError("unreachable")  # pragma: no cover

    def _request(self, method: str, path: str, payload: Optional[dict] = None) -> dict:
        body = json.dumps(payload).encode() if payload is not None else None
        status, raw = self._roundtrip(method, path, body)
        data = json.loads(raw) if raw else {}
        if status != 200:
            raise RuntimeError(
                f"{path} returned HTTP {status}: {data.get('message', raw[:200])}"
            )
        return data

    def get_rate_limits(self, req: GetRateLimitsRequest) -> GetRateLimitsResponse:
        return GetRateLimitsResponse.from_json(
            self._request("POST", "/v1/GetRateLimits", req.to_json())
        )

    def health_check(self) -> HealthCheckResponse:
        return HealthCheckResponse.from_json(self._request("GET", "/v1/HealthCheck"))

    def metrics_text(self) -> str:
        _status, raw = self._roundtrip("GET", "/metrics", None)
        return raw.decode()

    def close(self) -> None:
        """Close THIS thread's persistent connection (other threads'
        sockets close when their threads exit / on GC)."""
        self._drop_conn()


class _PipelinedConn:
    """One persistent HTTP/1.1 connection with request PIPELINING: the
    sender writes each request as soon as it is encoded (under a write
    lock) and a reader thread resolves responses in FIFO order — so
    several in-flight frames share one socket and the client never
    waits a round trip between window flushes.  Both gateway edges
    serve pipelined requests in arrival order (the stdlib handler
    serially; the native epoll edge via its token-ordered response
    queue), which is what makes FIFO matching correct.

    Responses resolve as (status, raw_body) on the posted Future; a
    connection-level failure fails every in-flight future and marks the
    conn dead (the owner builds a fresh one)."""

    MAX_INFLIGHT = 32  # bound pipelined requests per socket

    def __init__(self, endpoint: str, timeout_s: float,
                 tls_context: Optional[ssl.SSLContext] = None):
        uds = _uds_endpoint(endpoint)
        if uds is not None:
            # Same-host UDS lane: identical protocol, no TCP stack.
            if tls_context is not None:
                raise ValueError("TLS is not supported over unix:// targets")
            self._host = "localhost"
            self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            self._sock.settimeout(timeout_s)
            self._sock.connect(uds)
        else:
            host, _, port = endpoint.partition(":")
            self._host = host
            self._sock = socket.create_connection(
                (host, int(port or 80)), timeout=timeout_s
            )
            self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        if tls_context is not None:
            # Handshake still under timeout_s: a server that accepts
            # TCP but never completes TLS must not park the window's
            # only flusher thread forever.
            self._sock = tls_context.wrap_socket(self._sock, server_hostname=host)
        # AFTER connect+handshake, reads must BLOCK: the reader thread
        # sits in readline between responses (idle keep-alive
        # included), so a socket-level read timeout would tear the conn
        # down whenever the pipeline runs dry.  Response deadlines
        # belong to the waiters' fut.result timeouts; _fail unblocks
        # the reader by shutting the socket down.
        self._sock.settimeout(None)
        self._rfile = self._sock.makefile("rb")
        # _wlock serializes WRITERS only.  Liveness state (dead flag +
        # pending queue) lives under its own lock so _fail()/close()
        # can tear the conn down while a writer is parked in sendall on
        # a full send buffer — teardown shutdown()s the socket, which
        # unblocks that sendall with an error.  Taking _wlock for
        # teardown would deadlock behind exactly the stuck writer it
        # needs to rescue.
        self._wlock = threading.Lock()
        self._state_lock = threading.Lock()
        self._pending: "deque[Future]" = deque()
        self._slots = threading.BoundedSemaphore(self.MAX_INFLIGHT)
        self.dead = False
        self._reader = threading.Thread(
            target=self._read_loop, daemon=True, name="columns-client-reader"
        )
        self._reader.start()

    def post(self, path: str, body: bytes, content_type: str) -> Future:
        """Write one POST; returns a Future of (status, raw_body).
        Raises ConnectionError when the conn is dead."""
        self._slots.acquire()
        fut: Future = Future()
        head = (
            f"POST {path} HTTP/1.1\r\nHost: {self._host}\r\n"
            f"Content-Type: {content_type}\r\n"
            f"Content-Length: {len(body)}\r\n\r\n"
        ).encode()
        queued = False
        try:
            with self._wlock:
                with self._state_lock:
                    if self.dead:
                        raise ConnectionError("connection is closed")
                    # Queue BEFORE the write: a response cannot arrive
                    # for a request whose bytes have not gone out yet,
                    # so the reader can never pop an unqueued future.
                    self._pending.append(fut)
                    queued = True
                self._sock.sendall(head + body)
        except BaseException:
            # _fail releases one slot per QUEUED future (ours included
            # once queued); releasing here too would double-release the
            # bounded semaphore.
            if not queued:
                self._slots.release()
            self._fail(ConnectionError("send failed"))
            raise
        return fut

    def _read_loop(self) -> None:
        try:
            while True:
                line = self._rfile.readline()
                if not line:
                    raise ConnectionError("server closed the connection")
                parts = line.split(None, 2)
                if len(parts) < 2 or not parts[0].startswith(b"HTTP/"):
                    raise ConnectionError(f"malformed status line {line[:80]!r}")
                status = int(parts[1])
                clen = 0
                will_close = False
                while True:
                    h = self._rfile.readline()
                    if h in (b"\r\n", b"\n", b""):
                        break
                    name, _, val = h.partition(b":")
                    lname = name.strip().lower()
                    if lname == b"content-length":
                        clen = int(val.strip())
                    elif lname == b"connection" and b"close" in val.lower():
                        will_close = True
                body = self._rfile.read(clen) if clen else b""
                if clen and len(body) != clen:
                    raise ConnectionError("truncated response body")
                fut = self._pending.popleft()
                self._slots.release()
                fut.set_result((status, body))
                if will_close:
                    raise ConnectionError("server is closing the connection")
        except Exception as e:  # noqa: BLE001 — fail-all teardown
            self._fail(e)

    def _fail(self, exc: BaseException) -> None:
        with self._state_lock:
            if self.dead:
                pending: List[Future] = []
            else:
                self.dead = True
                pending = list(self._pending)
                self._pending.clear()
        # shutdown BEFORE close: it reliably unblocks a writer parked
        # in sendall (and the reader in readline); the close only
        # releases the fd.  Both are no-op-swallowed on repeat calls.
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        for fut in pending:
            self._slots.release()
            if not fut.done():
                fut.set_exception(
                    ConnectionError(f"pipelined connection failed: {exc}")
                )

    def close(self) -> None:
        self._fail(ConnectionError("client closed"))


class ColumnsV1Client:
    """Columnar front-door client (the reference `python/gubernator/`
    twin rebuilt on the GUBC wire): see the module docstring for the
    batching/pipelining/negotiation model.

    * `check(...)` / `submit_columns(...)` enqueue into the adaptive
      window and return a Future — concurrent callers coalesce into one
      frame of up to `max_lanes` lanes.
    * `get_rate_limits(req)` is the blocking drop-in for V1Client.
    * Negotiation is sticky per client: the first flush probes with a
      frame; 400/404/415 (or the pre-columns gateway's codec 500) means
      "old daemon, speak JSON" — the probe batch is resent classic
      inside the same flush (the 4xx proves it was never applied) and
      every later flush goes straight to JSON, byte-identical to a
      plain V1Client.
    """

    def __init__(
        self,
        endpoint: str = "127.0.0.1:1050",
        timeout_s: float = 5.0,
        batch_wait_s: float = 0.0005,
        max_lanes: Optional[int] = None,
        connections: int = 2,
        tls_context: Optional[ssl.SSLContext] = None,
    ):
        from .config import INGRESS_COLUMNS_MAX_LANES, MAX_BATCH_SIZE
        from .utils.batch_window import BatchWindow

        self.endpoint = endpoint
        self.timeout_s = timeout_s
        self.tls_context = tls_context
        self._columns_cap = (
            INGRESS_COLUMNS_MAX_LANES if max_lanes is None else max_lanes
        )
        self._classic_cap = MAX_BATCH_SIZE
        # None = untried (probe with a frame), True = daemon speaks
        # columns, False = classic JSON only.  Sticky for the client's
        # lifetime, like PeerClient._columnar.
        self._columnar: Optional[bool] = None
        self._closed = False
        # The classic fallback leg rides a V1Client (keep-alive +
        # stale-retry): its POST body is json.dumps of the exact
        # to_json() shape, so a downgraded client is wire-identical to
        # a pre-columns one.
        self._json_client = V1Client(endpoint, timeout_s, tls_context)
        self._conns: List[Optional[_PipelinedConn]] = [None] * max(connections, 1)
        self._conn_locks = [threading.Lock() for _ in self._conns]
        self._rr = 0
        self._window = BatchWindow(
            self._send_batch,
            batch_wait_s,
            self._columns_cap,
            lazy=True,
            adaptive=True,
            weigh=lambda item: len(item[0][0]),
        )

    # -- public surface ------------------------------------------------
    def check(self, name: str, unique_key: str, hits: int = 1,
              limit: int = 0, duration: int = 0, algorithm: int = 0,
              behavior: int = 0) -> "Future":
        """One rate-limit check; resolves to a RateLimitResponse.
        Concurrent checks coalesce into one wire frame."""
        fut = self.submit_columns((
            [name], [unique_key],
            np.array([algorithm], np.int32), np.array([behavior], np.int32),
            np.array([hits], np.int64), np.array([limit], np.int64),
            np.array([duration], np.int64),
        ))
        out: Future = Future()

        def done(f):
            try:
                rc, lo, _hi = f.result()
                out.set_result(rc.response_at(lo))
            except Exception as e:  # noqa: BLE001
                out.set_exception(e)

        fut.add_done_callback(done)
        return out

    def submit_columns(self, cols) -> "Future":
        """Submit a column sub-batch (wire.PeerColumns shape) to the
        coalescing window; resolves to (ColumnarResult, lo, hi) — this
        sub-batch's slice of the flushed frame's shared result."""
        from . import tracing

        if self._closed:
            raise ConnectionError("client is closed")
        n = len(cols[0])
        if n > self._columns_cap:
            raise ValueError(
                f"batch of {n} lanes exceeds max_lanes {self._columns_cap}"
            )
        # Reject malformed sub-batches HERE, per caller: garbage inside
        # a coalesced frame (ragged columns, out-of-range algorithm)
        # would 400 — or worse, misalign — the whole flush and take
        # every innocent rider of the window down with it.
        if any(len(c) != n for c in cols[1:]):
            raise ValueError("column length mismatch")
        algo = np.asarray(cols[2])
        if n and bool(((algo < 0) | (algo > 1)).any()):
            raise ValueError("algorithm out of range")
        fut: Future = Future()
        if tracing.enabled():
            ctx = tracing.current()
            if ctx is not None:
                fut._trace_ctx = ctx
        self._window.submit((cols, fut))
        return fut

    def get_rate_limits(self, req: GetRateLimitsRequest) -> GetRateLimitsResponse:
        """Blocking drop-in for V1Client.get_rate_limits, riding the
        columnar window."""
        rs = req.requests
        fut = self.submit_columns((
            [r.name for r in rs],
            [r.unique_key for r in rs],
            np.fromiter((int(r.algorithm) for r in rs), np.int32, count=len(rs)),
            np.fromiter((int(r.behavior) for r in rs), np.int32, count=len(rs)),
            np.fromiter((int(r.hits) for r in rs), np.int64, count=len(rs)),
            np.fromiter((int(r.limit) for r in rs), np.int64, count=len(rs)),
            np.fromiter((int(r.duration) for r in rs), np.int64, count=len(rs)),
        ))
        rc, lo, hi = fut.result(timeout=self.timeout_s + 1.0)
        return GetRateLimitsResponse(
            responses=[rc.response_at(i) for i in range(lo, hi)]
        )

    def health_check(self) -> HealthCheckResponse:
        return self._json_client.health_check()

    def close(self) -> None:
        self._closed = True
        self._window.stop(timeout_s=self.timeout_s)
        # The stop() drain may have just written final frames; give
        # their in-flight responses a bounded window to land before the
        # sockets close (late waiters would otherwise see spurious
        # ConnectionErrors for answered requests).
        deadline = time.monotonic() + self.timeout_s
        for conn in self._conns:
            while (
                conn is not None and not conn.dead and conn._pending
                and time.monotonic() < deadline
            ):
                time.sleep(0.005)
        for i, conn in enumerate(self._conns):
            if conn is not None:
                conn.close()
                self._conns[i] = None
        self._json_client.close()

    # -- flush path ----------------------------------------------------
    def _get_conn(self, k: int) -> _PipelinedConn:
        with self._conn_locks[k]:
            conn = self._conns[k]
            if conn is None or conn.dead:
                conn = _PipelinedConn(
                    self.endpoint, self.timeout_s, self.tls_context
                )
                self._conns[k] = conn
            return conn

    def _send_batch(self, batch: List[tuple]) -> None:
        """Window flush: chunk the queued sub-batches to the negotiated
        cap and send each chunk as ONE pipelined POST (frame or JSON).
        Runs on the window's flusher thread; nothing here waits on a
        response — completion handlers scatter results from the reader
        thread, which is what lets consecutive flushes pipeline."""
        cap = (
            self._columns_cap if self._columnar is not False
            else self._classic_cap
        )
        chunk: List[tuple] = []
        lanes = 0
        for item in batch:
            n = len(item[0][0])
            if chunk and lanes + n > cap:
                self._send_chunk(chunk)
                chunk, lanes = [], 0
                cap = (
                    self._columns_cap if self._columnar is not False
                    else self._classic_cap
                )
            chunk.append(item)
            lanes += n
        if chunk:
            self._send_chunk(chunk)

    @staticmethod
    def _concat(chunk: List[tuple]):
        if len(chunk) == 1:
            return chunk[0][0]
        return (
            [s for c, _ in chunk for s in c[0]],
            [s for c, _ in chunk for s in c[1]],
            *(
                np.concatenate([c[i] for c, _ in chunk])
                for i in range(2, 7)
            ),
        )

    def _trace_entries(self, chunk: List[tuple]):
        from . import tracing

        if not tracing.enabled():
            return None
        entries, lo = [], 0
        for c, fut in chunk:
            hi = lo + len(c[0])
            ctx = getattr(fut, "_trace_ctx", None)
            if ctx is not None:
                entries.append((lo, hi, ctx.trace_id, ctx.span_id))
            lo = hi
        return entries or None

    def _send_chunk(self, chunk: List[tuple]) -> None:
        from . import wire

        cols = self._concat(chunk)
        try:
            if self._columnar is False:
                self._send_chunk_classic(chunk, cols)
                return
            frame = wire.encode_ingress_frame(
                cols, trace=self._trace_entries(chunk)
            )
            k = self._rr = (self._rr + 1) % len(self._conns)
            try:
                rfut = self._get_conn(k).post(
                    "/v1/GetRateLimits", frame, wire.COLUMNS_CONTENT_TYPE
                )
            except Exception:  # noqa: BLE001
                # A failed post() is provably unanswered (at worst a
                # PARTIAL request reached a closing socket — the server
                # discards incomplete bodies), which is the keep-alive
                # expiry race on this leg: the idle conn died between
                # flushes.  One resend on a fresh connection; a second
                # failure surfaces.
                rfut = self._get_conn(k).post(
                    "/v1/GetRateLimits", frame, wire.COLUMNS_CONTENT_TYPE
                )
        except Exception as e:  # noqa: BLE001
            self._fail_chunk(chunk, e)
            return
        rfut.add_done_callback(lambda f: self._on_frame_reply(chunk, cols, f))

    def _on_frame_reply(self, chunk: List[tuple], cols, rfut) -> None:
        """Reader-thread completion for a frame POST: decode + scatter,
        or negotiate down sticky and resend classic inside this same
        flush (the rejection proves the frame was never applied)."""
        from . import wire

        try:
            status, body = rfut.result()
        except Exception as e:  # noqa: BLE001
            self._fail_chunk(chunk, e)
            return
        try:
            if status == 200 and wire.is_ingress_result_frame(body):
                self._columnar = True
                self._scatter(chunk, wire.decode_ingress_result_frame(body))
                return
            # A 400 from a COLUMNS-AWARE daemon rejecting THIS frame
            # ("invalid columns frame ..." — malformed, bad algorithm —
            # or "... too large" — a max_lanes override above the
            # server's cap) is a client bug: fail the chunk, do NOT
            # downgrade — the classic resend would halve every future
            # request's throughput for nothing.  Version answers are
            # the pre-columns shapes: the 400 json.loads gives a binary
            # body, a 404/415, or the old gateway's codec 500.
            rejected = (
                status in (404, 415)
                or (
                    status == 400
                    and b"invalid columns frame" not in body
                    and b"too large" not in body
                )
                or (status == 500 and b"codec can't decode" in body)
            )
            if rejected:
                # Old daemon (or GUBER_INGRESS_COLUMNS=0): remember,
                # shrink the window to the classic per-POST cap, resend
                # THIS chunk as classic JSON — on its OWN thread, not
                # this reader thread: during the probe several frame
                # chunks may be pipelined on this socket, and a serial
                # blocking resend here would stall FIFO delivery of
                # their replies past the waiters' timeouts.  Rare by
                # construction (once per downgraded client).
                self._columnar = False
                self._window.limit = self._classic_cap
                threading.Thread(
                    target=self._send_chunk_classic, args=(chunk, cols),
                    daemon=True, name="columns-client-downgrade",
                ).start()
                return
            if status == 200:
                # A 200 with a non-frame body: the daemon ANSWERED (it
                # may have applied the hits), so a resend would
                # double-count — fail the batch, speak classic onward.
                self._columnar = False
                self._window.limit = self._classic_cap
                raise RuntimeError(
                    "daemon answered a columns frame with a non-frame 200 body"
                )
            raise RuntimeError(
                f"/v1/GetRateLimits returned HTTP {status}: {body[:200]!r}"
            )
        except Exception as e:  # noqa: BLE001
            self._fail_chunk(chunk, e)

    def _send_chunk_classic(self, chunk: List[tuple], cols) -> None:
        """Classic JSON leg: re-chunk to the reference's 1000-item cap
        and POST each piece through the keep-alive V1Client — the exact
        pre-columns wire bytes (interop-golden-tested)."""
        from . import wire

        try:
            n_total = len(cols[0])
            parts = []
            for lo in range(0, n_total, self._classic_cap):
                sub = wire.peer_columns_slice(
                    cols, lo, min(lo + self._classic_cap, n_total)
                )
                body = self._json_client._request(
                    "POST", "/v1/GetRateLimits",
                    wire.peer_columns_to_classic_json(sub),
                )
                parts.append(wire.result_from_classic_ingress_json(body))
            self._scatter(chunk, wire.concat_results(parts))
        except Exception as e:  # noqa: BLE001
            self._fail_chunk(chunk, e)

    @staticmethod
    def _scatter(chunk: List[tuple], rc) -> None:
        n = sum(len(c[0]) for c, _ in chunk)
        if rc.n != n:
            ColumnsV1Client._fail_chunk(chunk, RuntimeError(
                f"daemon returned {rc.n} rate limits for {n} requests"
            ))
            return
        lo = 0
        for c, fut in chunk:
            hi = lo + len(c[0])
            if not fut.done():
                fut.set_result((rc, lo, hi))
            lo = hi

    @staticmethod
    def _fail_chunk(chunk: List[tuple], exc: BaseException) -> None:
        for _, fut in chunk:
            if not fut.done():
                fut.set_exception(exc)


class GrpcV1Client:
    """gRPC client for the V1 service (client.go:41-57 DialV1Server)."""

    def __init__(self, endpoint: str, timeout_s: float = 5.0, credentials=None):
        import grpc

        from .proto import V1_SERVICE
        from .proto import gubernator_pb2 as pb
        from .proto import peers_columns_pb2 as pc_pb

        self.endpoint = endpoint
        self.timeout_s = timeout_s
        if credentials is not None:
            self._channel = grpc.secure_channel(endpoint, credentials)
        else:
            self._channel = grpc.insecure_channel(endpoint)
        self._get_rate_limits = self._channel.unary_unary(
            f"/{V1_SERVICE}/GetRateLimits",
            request_serializer=pb.GetRateLimitsReq.SerializeToString,
            response_deserializer=pb.GetRateLimitsResp.FromString,
        )
        self._get_rate_limits_columns = self._channel.unary_unary(
            f"/{V1_SERVICE}/GetRateLimitsColumns",
            request_serializer=pc_pb.PeerColumnsReq.SerializeToString,
            response_deserializer=pc_pb.IngressColumnsResp.FromString,
        )
        self._health_check = self._channel.unary_unary(
            f"/{V1_SERVICE}/HealthCheck",
            request_serializer=pb.HealthCheckReq.SerializeToString,
            response_deserializer=pb.HealthCheckResp.FromString,
        )
        # Columns negotiation, sticky like the HTTP client's: None =
        # probe first, False = daemon answered UNIMPLEMENTED (pre-
        # columns build / GUBER_INGRESS_COLUMNS=0), speak classic.
        self._columnar: Optional[bool] = None

    def get_rate_limits(self, req: GetRateLimitsRequest) -> GetRateLimitsResponse:
        from . import wire

        m = self._get_rate_limits(
            wire.get_rate_limits_req_to_pb(req), timeout=self.timeout_s
        )
        return wire.get_rate_limits_resp_from_pb(m)

    def get_rate_limits_columns(self, cols) -> "object":
        """Columnar GetRateLimits (wire.PeerColumns in, ColumnarResult
        out) against V1/GetRateLimitsColumns; UNIMPLEMENTED downgrades
        sticky to the classic per-request encoding — the method never
        executed, so the resend cannot double-count."""
        import grpc

        from . import wire

        if self._columnar is not False:
            try:
                m = self._get_rate_limits_columns(
                    wire.peer_columns_req_to_pb(cols), timeout=self.timeout_s
                )
                self._columnar = True
                return wire.result_from_ingress_columns_pb(m)
            except grpc.RpcError as e:
                code = e.code() if hasattr(e, "code") else None
                if code != grpc.StatusCode.UNIMPLEMENTED:
                    raise
                self._columnar = False
        from .config import MAX_BATCH_SIZE
        from .service import ColumnarResult

        # Classic downgrade: re-chunk to the reference's 1000-item cap
        # (a columnar batch may carry up to INGRESS_COLUMNS_MAX_LANES —
        # one oversize GetRateLimits would be rejected OutOfRange).
        n_total = len(cols[0])
        parts = []
        for lo in range(0, n_total, MAX_BATCH_SIZE):
            names, uks, algo, beh, hits, limit, duration = (
                wire.peer_columns_slice(
                    cols, lo, min(lo + MAX_BATCH_SIZE, n_total)
                )
            )
            resp = self.get_rate_limits(GetRateLimitsRequest(requests=[
                RateLimitRequest(
                    name=names[i], unique_key=uks[i], hits=int(hits[i]),
                    limit=int(limit[i]), duration=int(duration[i]),
                    algorithm=int(algo[i]), behavior=int(beh[i]),
                )
                for i in range(len(names))
            ]))
            part = ColumnarResult.empty(len(resp.responses))
            part.overrides = dict(enumerate(resp.responses))
            parts.append(part)
        if not parts:
            return ColumnarResult.empty(0)
        return wire.concat_results(parts)

    def health_check(self) -> HealthCheckResponse:
        from . import wire
        from .proto import gubernator_pb2 as pb

        return wire.health_from_pb(self._health_check(pb.HealthCheckReq(), timeout=self.timeout_s))

    def close(self) -> None:
        self._channel.close()


def dial_v1_server(address: str, credentials=None, timeout_s: float = 5.0) -> GrpcV1Client:
    """client.go:41-57."""
    return GrpcV1Client(address, timeout_s=timeout_s, credentials=credentials)


def sleep_until_reset(rate_limit: RateLimitResponse) -> None:
    """python/gubernator/__init__.py:12-17."""
    now = time.time()
    delta = rate_limit.reset_time / 1000.0 - now
    if delta > 0:
        time.sleep(delta)


def to_timestamp(duration: datetime.timedelta) -> int:
    """Duration -> unix-millisecond count for request duration fields
    (client.go:62-64)."""
    return int(duration.total_seconds() * 1000)


def from_unix_milliseconds(ts: int) -> datetime.datetime:
    """Unix-ms timestamp -> aware datetime (client.go:76-78)."""
    return datetime.datetime.fromtimestamp(ts / 1000.0, tz=datetime.timezone.utc)


def from_timestamp(ts: int) -> datetime.timedelta:
    """Unix-ms timestamp -> elapsed time since it (now - ts, matching
    client.go:69-72): positive for past timestamps, NEGATIVE for future
    ones.  To wait out a reset_time, use sleep_until_reset, not this."""
    return datetime.datetime.now(tz=datetime.timezone.utc) - from_unix_milliseconds(ts)


def random_peer(peers: List[PeerInfo]) -> PeerInfo:
    """client.go:81-86."""
    return random.choice(peers)


def random_string(prefix: str = "", n: int = 10) -> str:
    """client.go:89-97."""
    return prefix + "".join(random.choices(string.ascii_lowercase + string.digits, k=n))