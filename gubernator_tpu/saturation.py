"""Saturation & SLO observability plane (USE-method instrumentation).

PR 4's spans can say where ONE sampled request lost its time; this
module aggregates the same signals ALWAYS-ON, so the operator questions
("where do the p99 milliseconds go", "how full is the bucket table",
"are we burning the error budget") have live answers without sampling:

* **Latency attribution** — per-phase duration reservoirs covering the
  whole request waterfall (`PHASES`): ingress parse -> batch-window
  wait -> queue wait -> the five dispatch pipeline stages -> peer-wire
  RTT -> response encode.  Each observation also feeds the
  `gubernator_latency_attribution_seconds{phase}` histogram of the
  registered metrics sink; `GET /debug/latency` serves ceil-rank
  percentile snapshots straight from the reservoirs.

* **SLO engine** — `SloEngine` turns per-request ingress latency into
  multi-window (5m / 1h) error-budget burn rates against
  `GUBER_LATENCY_TARGET_MS`; a fast burn (Google SRE's 14.4x on the
  short window) trips the PR 4 flight-recorder auto-dump path
  (`tracing.record_event("slo-fast-burn")`).

* **Hot-key sketch** — `HotKeySketch`, a count-min sketch + top-K
  tracker fed from the owner-code hashes `hash_ring.get_batch_codes`
  ALREADY computes (zero extra hashing on the hot path), served at
  `GET /debug/hotkeys` — the detection half of the ROADMAP item-5
  hot-key defense.

* **Saturation accumulators** — per-launch lane utilization (fill vs
  pow2 pad), dispatcher busy fraction, and ingress-queue depth
  samples, drained per metrics scrape like the dispatch-stage gauges.

Reservoirs/accumulators are MODULE-GLOBAL, like the tracing flight
recorder: one daemon per process in production, and in-process
multi-daemon tests share one plane exactly as they share one span ring.
Everything here is host-side arithmetic on data the hot path already
produced — the plane adds ZERO device programs (pinned by counting
dispatches, tests/test_observability.py).
"""

from __future__ import annotations

import itertools
import math
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from . import tracing

# ---------------------------------------------------------------------
# Shared ceil-rank percentiles (the bench.py p99 bugfix lives here so
# every percentile site — bench rows, /debug/latency, queue-depth
# snapshots — indexes the same way).
# ---------------------------------------------------------------------


def percentile_rank(n: int, q: float) -> int:
    """0-based index of the q-quantile in a sorted n-sample list, by
    the NEAREST-RANK definition: 1-based rank ceil(q*n).  The previous
    bench.py form `min(n-1, int(n*q))` floor-indexed — at small n it
    lands a rank off the nearest-rank tail value, so gate verdicts on
    thin tails were judged against the wrong sample."""
    if n <= 0:
        raise ValueError("percentile of an empty sample")
    return min(n - 1, max(0, math.ceil(q * n) - 1))


def percentile(sorted_vals: Sequence[float], q: float) -> float:
    return sorted_vals[percentile_rank(len(sorted_vals), q)]


# ---------------------------------------------------------------------
# Latency attribution: per-phase reservoirs
# ---------------------------------------------------------------------

# The request waterfall, in flight order.  Snapshots list phases in
# this order so a /debug/latency reader sees the pipeline shape.
PHASES = (
    "ingress.parse",     # wire bytes -> IngressColumns (gateway)
    "batch.window",      # submit -> coalescing-window flush (batchers)
    "express.submit",    # express bypass: submit -> dispatch staged
                         # (replaces batch.window + queue.wait for
                         # express lanes — the express-vs-batched split)
    "queue.wait",        # flush -> dispatch submit (backstop + concat)
    "dispatch.prepare",  # slot-table planning (pipeline stage 1)
    "dispatch.stage",    # wire pack + H2D upload start (stage 2)
    "dispatch.launch",   # ticket-ordered jit call (stage 3)
    "dispatch.fetch",    # device->host readback
    "dispatch.commit",   # decode + table commit
    "peer.rpc",          # forwarded-hop round trip (peer_client)
    "response.encode",   # ColumnarResult -> wire bytes (gateway)
    "ingress.total",     # whole-request wall time (GetRateLimits)
)

PHASE_RING = 2048  # recent samples kept per phase


class _PhaseStats:
    """One phase's reservoir: a ring of recent durations plus lifetime
    count/sum.  A small lock per observation — observations happen per
    BATCH or per REQUEST, not per lane, so contention is negligible."""

    __slots__ = ("_buf", "_lock", "count", "sum_s", "max_s")

    def __init__(self):
        self._buf: List[float] = []
        self._lock = threading.Lock()
        self.count = 0
        self.sum_s = 0.0
        self.max_s = 0.0

    def observe(self, dt_s: float) -> None:
        with self._lock:
            self.count += 1
            self.sum_s += dt_s
            if dt_s > self.max_s:
                self.max_s = dt_s
            if len(self._buf) >= PHASE_RING:
                self._buf[self.count % PHASE_RING] = dt_s
            else:
                self._buf.append(dt_s)

    def snapshot(self) -> Optional[dict]:
        with self._lock:
            if not self.count:
                return None
            vals = sorted(self._buf)
            return {
                "count": self.count,
                "sum_ms": round(self.sum_s * 1000.0, 3),
                "max_ms": round(self.max_s * 1000.0, 3),
                "p50_ms": round(percentile(vals, 0.50) * 1000.0, 3),
                "p90_ms": round(percentile(vals, 0.90) * 1000.0, 3),
                "p99_ms": round(percentile(vals, 0.99) * 1000.0, 3),
                "n_samples": len(vals),
            }


_phases: Dict[str, _PhaseStats] = {p: _PhaseStats() for p in PHASES}
# Prometheus sink: (histogram, {phase: child}) of the most recently
# constructed Metrics instance.  Last-wins, like the tracing rings —
# production runs one daemon per process; in-process test clusters
# share the plane.
_sink: Optional[list] = None
_sink_lock = threading.Lock()


def register_sink(histogram) -> None:
    """Attach a prometheus Histogram (labeled by `phase`) that every
    observe_phase ALSO feeds — metrics.py calls this at Metrics init."""
    global _sink
    with _sink_lock:
        _sink = [histogram, {}]


def observe_phase(phase: str, dt_s: float) -> None:
    """Record one completed phase interval.  Called from the hot path
    (per batch / per request): one lock, one ring write, one histogram
    observe."""
    st = _phases.get(phase)
    if st is None:  # unknown phase: record rather than drop
        st = _phases.setdefault(phase, _PhaseStats())
    st.observe(dt_s)
    sink = _sink
    if sink is not None:
        child = sink[1].get(phase)
        if child is None:
            try:
                child = sink[1][phase] = sink[0].labels(phase=phase)
            except Exception:  # noqa: BLE001 — a dead registry must not fail requests
                return
        child.observe(dt_s)


def phase_snapshot() -> Dict[str, dict]:
    """{phase: {count, sum_ms, max_ms, p50/p90/p99_ms, n_samples}} for
    every phase that has observations, in waterfall order."""
    out: Dict[str, dict] = {}
    for p in list(_phases):
        snap = _phases[p].snapshot()
        if snap is not None:
            out[p] = snap
    return out


# ---------------------------------------------------------------------
# Saturation accumulators (drained per metrics scrape)
# ---------------------------------------------------------------------
class LaneUtil:
    """Per-launch lane utilization: real lanes vs the pow2-padded shape
    the program actually scattered.  take() drains the deltas since the
    last scrape (the dispatch-stage gauge convention)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._lanes = 0
        self._padded = 0
        self._launches = 0

    def add(self, lanes: int, padded: int) -> None:
        with self._lock:
            self._lanes += int(lanes)
            self._padded += int(padded)
            self._launches += 1

    def take(self) -> Tuple[int, int, int]:
        with self._lock:
            out = (self._lanes, self._padded, self._launches)
            self._lanes = self._padded = self._launches = 0
        return out


class BusyFraction:
    """Busy-seconds accumulator for the dispatcher (batch-window flush
    worker): take() returns (busy_s, elapsed_s) since the last take, so
    the scrape renders a utilization fraction."""

    def __init__(self, time_fn=time.monotonic):
        self._lock = threading.Lock()
        self._time = time_fn
        self._busy = 0.0
        self._last_take = time_fn()

    def add(self, dt_s: float) -> None:
        with self._lock:
            self._busy += dt_s

    def take(self) -> Tuple[float, float]:
        with self._lock:
            now = self._time()
            out = (self._busy, max(now - self._last_take, 1e-9))
            self._busy = 0.0
            self._last_take = now
        return out


class _DepthRing:
    """Lock-free ring of ingress-queue depth samples (one per admit),
    the tracing._Ring trick: itertools.count + slot store are atomic
    under the GIL."""

    CAP = 4096

    def __init__(self):
        self._buf: List[Optional[int]] = [None] * self.CAP
        self._seq = itertools.count()

    def record(self, depth: int) -> None:
        self._buf[next(self._seq) % self.CAP] = depth

    def snapshot(self) -> dict:
        vals = sorted(v for v in list(self._buf) if v is not None)
        if not vals:
            return {"n_samples": 0}
        return {
            "n_samples": len(vals),
            "p50": percentile(vals, 0.50),
            "p99": percentile(vals, 0.99),
            "max": vals[-1],
        }


class ExpressStats:
    """Express-vs-batched lane accounting (the PR 14 millisecond
    express lane).  Each dispatch notes which path its lanes took:

      * ``bypass``   — batcher shallow-queue bypass (direct dispatch,
                       no coalescing window)
      * ``scalar``   — the host-side singleton slot (ops/scalar.py;
                       also counted as whichever submit path fed it)
      * ``native``   — NO_BATCHING frames served by the native ingress
                       express queue (gt_ingress_*)
      * ``windowed`` — lanes that rode a coalesced batch: a Python
                       window flush OR the native ring's bulk path
                       (the pump feeds both into this denominator)

    `take()` drains per-scrape deltas for the gubernator_express_*
    counters; `snapshot()` serves cumulative counts + the hit rate at
    /debug/latency and /debug/status."""

    PATHS = ("bypass", "scalar", "native", "windowed")

    def __init__(self):
        self._lock = threading.Lock()
        self._lanes = {p: 0 for p in self.PATHS}
        self._dispatches = {p: 0 for p in self.PATHS}
        self._delta_lanes = {p: 0 for p in self.PATHS}

    def note(self, path: str, lanes: int) -> None:
        with self._lock:
            self._lanes[path] = self._lanes.get(path, 0) + int(lanes)
            self._dispatches[path] = self._dispatches.get(path, 0) + 1
            self._delta_lanes[path] = (
                self._delta_lanes.get(path, 0) + int(lanes)
            )

    def take(self) -> Dict[str, int]:
        with self._lock:
            out = dict(self._delta_lanes)
            self._delta_lanes = {p: 0 for p in self.PATHS}
        return out

    def snapshot(self) -> dict:
        with self._lock:
            express = (
                self._lanes.get("bypass", 0) + self._lanes.get("native", 0)
            )
            windowed = self._lanes.get("windowed", 0)
            total = express + windowed
            return {
                "lanes": dict(self._lanes),
                "dispatches": dict(self._dispatches),
                "hitRate": round(express / total, 4) if total else 0.0,
            }


lane_util = LaneUtil()
dispatcher_busy = BusyFraction()
_queue_depths = _DepthRing()
express = ExpressStats()


def note_express(path: str, lanes: int) -> None:
    """Record one express/batched dispatch (see ExpressStats)."""
    express.note(path, lanes)


def express_snapshot() -> dict:
    return express.snapshot()


def observe_queue_depth(depth: int) -> None:
    _queue_depths.record(depth)


def queue_depth_snapshot() -> dict:
    return _queue_depths.snapshot()


# ---------------------------------------------------------------------
# SLO engine: multi-window error-budget burn rates
# ---------------------------------------------------------------------
class SloEngine:
    """Latency-SLO accounting: each ingress request is GOOD (answered
    under `target_ms`) or BAD; the error budget is `1 - objective` of
    requests, and the burn rate over a window is

        burn = (bad / total in window) / (1 - objective)

    (1.0 = burning the budget exactly as fast as it accrues; the SRE
    fast-burn page threshold is 14.4x over 5 minutes).  Counts live in
    10-second buckets covering one hour, so the 5m and 1h windows read
    from the same ring.  `target_ms <= 0` disables the engine: observe
    degrades to one comparison, every gauge reads 0."""

    BUCKET_S = 10
    N_BUCKETS = 360  # 1 hour
    WINDOWS = {"5m": 300, "1h": 3600}
    FAST_BURN = 14.4          # page-level burn on the short window
    FAST_WINDOW_S = 300
    # Volume floor for the fast-burn trip: a page-level verdict from a
    # handful of requests is noise shaped like an incident (one bad
    # warmup request after a restart would read burn=100) — the same
    # thin-tail rule the bench gate's min_samples enforces.
    FAST_MIN_TOTAL = 100
    CHECK_INTERVAL_S = 1.0    # fast-burn evaluation cadence
    TRIP_MIN_INTERVAL_S = 30.0

    def __init__(self, target_ms: float, objective: float = 0.99,
                 time_fn=time.monotonic):
        self.target_ms = float(target_ms)
        self.objective = min(max(float(objective), 0.0), 0.9999)
        self.enabled = self.target_ms > 0
        self._time = time_fn
        self._lock = threading.Lock()
        self._good = np.zeros(self.N_BUCKETS, dtype=np.int64)
        self._bad = np.zeros(self.N_BUCKETS, dtype=np.int64)
        self._epoch = np.full(self.N_BUCKETS, -1, dtype=np.int64)
        self._next_check = 0.0
        self._last_trip = -float("inf")

    def observe(self, dt_s: float) -> Optional[bool]:
        """Record one request; returns True (good) / False (bad), or
        None when the engine is disabled."""
        if not self.enabled:
            return None
        good = dt_s * 1000.0 <= self.target_ms
        now = self._time()
        trip_burn = None
        with self._lock:
            i = self._slot(now)
            (self._good if good else self._bad)[i] += 1
            if now >= self._next_check:
                self._next_check = now + self.CHECK_INTERVAL_S
                w_good, w_bad = self._window_counts(now, self.FAST_WINDOW_S)
                total = w_good + w_bad
                burn = (
                    (w_bad / total) / max(1.0 - self.objective, 1e-9)
                    if total >= self.FAST_MIN_TOTAL else 0.0
                )
                if (burn >= self.FAST_BURN
                        and now - self._last_trip >= self.TRIP_MIN_INTERVAL_S):
                    self._last_trip = now
                    trip_burn = burn
        if trip_burn is not None:
            # The PR 4 auto-dump path: a fast burn is the same "the
            # service is losing its SLO" signal a breaker trip is —
            # dump the flight recorder.  OUTSIDE the engine lock: the
            # dump JSON-serializes and logs, and every ingress request
            # takes this lock — a slow log handler must not convoy the
            # whole service at the very moment it is burning.
            tracing.record_event(
                "slo-fast-burn", burn_rate=round(trip_burn, 2),
                window_s=self.FAST_WINDOW_S,
                target_ms=self.target_ms,
                objective=self.objective,
            )
        return good

    def _slot(self, now: float) -> int:
        """Bucket index for `now`, zeroing the slot if its epoch is
        stale (the ring wrapped past it).  Lock held."""
        epoch = int(now // self.BUCKET_S)
        i = epoch % self.N_BUCKETS
        if self._epoch[i] != epoch:
            self._epoch[i] = epoch
            self._good[i] = 0
            self._bad[i] = 0
        return i

    def _window_counts(self, now: float, window_s: int) -> Tuple[int, int]:
        epoch = int(now // self.BUCKET_S)
        lo = epoch - (window_s // self.BUCKET_S) + 1
        live = (self._epoch >= lo) & (self._epoch <= epoch)
        return int(self._good[live].sum()), int(self._bad[live].sum())

    def _burn_locked(self, now: float, window_s: int) -> float:
        good, bad = self._window_counts(now, window_s)
        total = good + bad
        if total == 0:
            return 0.0
        return (bad / total) / max(1.0 - self.objective, 1e-9)

    def burn_rate(self, window_s: int) -> float:
        if not self.enabled:
            return 0.0
        with self._lock:
            return self._burn_locked(self._time(), window_s)

    def snapshot(self) -> dict:
        out = {
            "enabled": self.enabled,
            "target_ms": self.target_ms,
            "objective": self.objective,
        }
        if not self.enabled:
            return out
        with self._lock:
            now = self._time()
            for name, w in self.WINDOWS.items():
                good, bad = self._window_counts(now, w)
                out[f"burn_rate_{name}"] = round(
                    self._burn_locked(now, w), 4
                )
                out[f"good_{name}"] = good
                out[f"bad_{name}"] = bad
        return out


# ---------------------------------------------------------------------
# Hot-key detection: count-min sketch + top-K
# ---------------------------------------------------------------------

# Odd 64-bit multipliers deriving d independent row indices from the
# ONE fnv1 hash the ring already computed (Dietzfelbinger-style
# multiply-shift; u64 wraparound is the intended arithmetic).
_CMS_SALTS = np.array(
    [0x9E3779B97F4A7C15, 0xC2B2AE3D27D4EB4F, 0x165667B19E3779F9,
     0x27D4EB2F165667C5],
    dtype=np.uint64,
)


class HotKeySketch:
    """Count-min sketch over per-lane key hashes plus an exact top-K
    candidate list.  update() is fully vectorized over a batch; key
    STRINGS are materialized only for the handful of lanes whose
    estimate crosses the current top-K floor, so the hot path never
    builds per-lane Python objects.  Counts decay by halving every
    `decay_s` seconds — the sketch answers "hot NOW", not "hot ever"."""

    def __init__(self, width: int = 8192, depth: int = 4, topk: int = 16,
                 decay_s: float = 30.0, time_fn=time.monotonic):
        self.width = int(width)
        self.depth = min(int(depth), len(_CMS_SALTS))
        self.topk = int(topk)
        self.decay_s = float(decay_s)
        self._time = time_fn
        self._lock = threading.Lock()
        self._tab = np.zeros((self.depth, self.width), dtype=np.int64)
        self._salts = _CMS_SALTS[: self.depth]
        self._top: Dict[int, list] = {}  # hash -> [est, key_str]
        self._last_decay = time_fn()
        self.total_lanes = 0
        self.batches = 0

    def update(self, hashes: np.ndarray, keys) -> None:
        """Fold one batch: `hashes` u64[n] (the ring lookup's fnv1
        values), `keys` indexable by lane (list or PackedKeys)."""
        n = len(hashes)
        if n == 0:
            return
        hs = np.ascontiguousarray(hashes, dtype=np.uint64)
        with self._lock:
            now = self._time()
            if now - self._last_decay >= self.decay_s:
                self._last_decay = now
                self._tab >>= 1
                for rec in self._top.values():
                    rec[0] >>= 1
            uh, first, counts = np.unique(
                hs, return_index=True, return_counts=True
            )
            idx = ((uh[None, :] * self._salts[:, None])
                   >> np.uint64(17)) % np.uint64(self.width)
            for r in range(self.depth):
                np.add.at(self._tab[r], idx[r].astype(np.intp), counts)
            est = self._tab[
                np.arange(self.depth)[:, None], idx.astype(np.intp)
            ].min(axis=0)
            self.total_lanes += n
            self.batches += 1
            # Top-K maintenance: only candidates at/above the current
            # floor materialize a key string.  While the list is still
            # filling the floor is 0, so bound the candidate scan to
            # the K largest estimates — a 1000-unique batch must not
            # loop 1000 lanes in Python.
            if len(self._top) >= self.topk:
                floor = min(rec[0] for rec in self._top.values())
                cand = np.nonzero(est >= floor)[0]
                if cand.size > self.topk:
                    # Uniform traffic concentrates estimates near the
                    # floor: without this cap, ~every unique hash would
                    # qualify and loop in Python per batch.
                    cand = cand[np.argsort(est[cand])[-self.topk:]]
            else:
                cand = np.argsort(est)[max(0, est.size - self.topk):]
            for j in cand:
                h = int(uh[j])
                rec = self._top.get(h)
                if rec is not None:
                    rec[0] = int(est[j])
                else:
                    self._top[h] = [int(est[j]), str(keys[int(first[j])])]
            if len(self._top) > self.topk:
                keep = sorted(
                    self._top.items(), key=lambda kv: kv[1][0], reverse=True
                )[: self.topk]
                self._top = dict(keep)

    def snapshot(self) -> dict:
        with self._lock:
            top = sorted(
                ({"key": rec[1], "estimate": int(rec[0])}
                 for rec in self._top.values()),
                key=lambda d: d["estimate"], reverse=True,
            )
            return {
                "topk": top,
                "total_lanes": self.total_lanes,
                "batches": self.batches,
                "width": self.width,
                "depth": self.depth,
                "decay_s": self.decay_s,
            }


# ---------------------------------------------------------------------
def reset() -> None:
    """Test hook: clear every module-global reservoir/accumulator."""
    global _phases, lane_util, dispatcher_busy, _queue_depths, express
    _phases = {p: _PhaseStats() for p in PHASES}
    lane_util = LaneUtil()
    dispatcher_busy = BusyFraction()
    _queue_depths = _DepthRing()
    express = ExpressStats()
