"""Converters between the internal dataclasses and the protobuf wire
messages (gubernator_pb2 / peers_pb2).

The dataclasses in `types.py` stay the in-process currency (the JSON
gateway and the stores use them directly); protobuf enters only at the
gRPC edge, mirroring how the reference's generated pb types live at its
gRPC boundary (gubernator.pb.go / peers.pb.go).
"""

from __future__ import annotations

from typing import Iterable, List

from .proto import gubernator_pb2 as pb
from .proto import peers_pb2 as peers_pb
from .types import (
    GetRateLimitsRequest,
    GetRateLimitsResponse,
    HealthCheckResponse,
    RateLimitRequest,
    RateLimitResponse,
    UpdatePeerGlobal,
)


# ---- RateLimitReq ----------------------------------------------------
def req_to_pb(r: RateLimitRequest) -> pb.RateLimitReq:
    return pb.RateLimitReq(
        name=r.name,
        unique_key=r.unique_key,
        hits=int(r.hits),
        limit=int(r.limit),
        duration=int(r.duration),
        algorithm=int(r.algorithm),
        behavior=int(r.behavior),
    )


def req_from_pb(m: pb.RateLimitReq) -> RateLimitRequest:
    return RateLimitRequest(
        name=m.name,
        unique_key=m.unique_key,
        hits=m.hits,
        limit=m.limit,
        duration=m.duration,
        algorithm=int(m.algorithm),
        behavior=int(m.behavior),
    )


# ---- RateLimitResp ---------------------------------------------------
def resp_to_pb(r: RateLimitResponse) -> pb.RateLimitResp:
    m = pb.RateLimitResp(
        status=int(r.status),
        limit=int(r.limit),
        remaining=int(r.remaining),
        reset_time=int(r.reset_time),
        error=r.error,
    )
    for k, v in (r.metadata or {}).items():
        m.metadata[k] = v
    return m


def resp_from_pb(m: pb.RateLimitResp) -> RateLimitResponse:
    return RateLimitResponse(
        status=int(m.status),
        limit=m.limit,
        remaining=m.remaining,
        reset_time=m.reset_time,
        error=m.error,
        metadata=dict(m.metadata),
    )


# ---- batch envelopes -------------------------------------------------
def get_rate_limits_req_to_pb(req: GetRateLimitsRequest) -> pb.GetRateLimitsReq:
    return pb.GetRateLimitsReq(requests=[req_to_pb(r) for r in req.requests])


def get_rate_limits_req_from_pb(m: pb.GetRateLimitsReq) -> GetRateLimitsRequest:
    return GetRateLimitsRequest(requests=[req_from_pb(r) for r in m.requests])


def get_rate_limits_resp_to_pb(resp: GetRateLimitsResponse) -> pb.GetRateLimitsResp:
    return pb.GetRateLimitsResp(responses=[resp_to_pb(r) for r in resp.responses])


def get_rate_limits_resp_from_pb(m: pb.GetRateLimitsResp) -> GetRateLimitsResponse:
    return GetRateLimitsResponse(responses=[resp_from_pb(r) for r in m.responses])


def peer_rate_limits_req_to_pb(req: GetRateLimitsRequest) -> peers_pb.GetPeerRateLimitsReq:
    return peers_pb.GetPeerRateLimitsReq(requests=[req_to_pb(r) for r in req.requests])


def peer_rate_limits_req_from_pb(m: peers_pb.GetPeerRateLimitsReq) -> GetRateLimitsRequest:
    return GetRateLimitsRequest(requests=[req_from_pb(r) for r in m.requests])


def peer_rate_limits_resp_to_pb(resp: GetRateLimitsResponse) -> peers_pb.GetPeerRateLimitsResp:
    return peers_pb.GetPeerRateLimitsResp(rate_limits=[resp_to_pb(r) for r in resp.responses])


def peer_rate_limits_resp_from_pb(m: peers_pb.GetPeerRateLimitsResp) -> GetRateLimitsResponse:
    return GetRateLimitsResponse(responses=[resp_from_pb(r) for r in m.rate_limits])


# ---- columnar fast path ---------------------------------------------
def columns_from_pb(m: pb.GetRateLimitsReq):
    """Parse the pb batch straight into ingress columns (the gRPC half
    of the zero-dataclass hot path)."""
    import numpy as np

    from .service import IngressColumns

    items = m.requests
    n = len(items)
    return IngressColumns(
        names=[r.name for r in items],
        unique_keys=[r.unique_key for r in items],
        algorithm=np.fromiter((r.algorithm for r in items), np.int32, count=n),
        behavior=np.fromiter((r.behavior for r in items), np.int32, count=n),
        hits=np.fromiter((r.hits for r in items), np.int64, count=n),
        limit=np.fromiter((r.limit for r in items), np.int64, count=n),
        duration=np.fromiter((r.duration for r in items), np.int64, count=n),
    )


def _columns_to_resp_list(result):
    ov = result.overrides
    status = result.status
    limit = result.limit
    remaining = result.remaining
    reset = result.reset_time
    out = []
    for i in range(result.n):
        r = ov.get(i)
        if r is not None:
            out.append(resp_to_pb(r))
        else:
            out.append(
                pb.RateLimitResp(
                    status=int(status[i]),
                    limit=int(limit[i]),
                    remaining=int(remaining[i]),
                    reset_time=int(reset[i]),
                )
            )
    return out


def columns_to_pb(result) -> pb.GetRateLimitsResp:
    """Serialize a service.ColumnarResult directly from its arrays."""
    return pb.GetRateLimitsResp(responses=_columns_to_resp_list(result))


def columns_to_peer_pb(result) -> peers_pb.GetPeerRateLimitsResp:
    """PeersV1 twin of columns_to_pb (field name rate_limits,
    peers.proto:42-45)."""
    return peers_pb.GetPeerRateLimitsResp(rate_limits=_columns_to_resp_list(result))


# ---- GLOBAL broadcast ------------------------------------------------
def update_global_to_pb(u: UpdatePeerGlobal) -> peers_pb.UpdatePeerGlobal:
    return peers_pb.UpdatePeerGlobal(
        key=u.key, status=resp_to_pb(u.status), algorithm=int(u.algorithm)
    )


def update_global_from_pb(m: peers_pb.UpdatePeerGlobal) -> UpdatePeerGlobal:
    return UpdatePeerGlobal(
        key=m.key, status=resp_from_pb(m.status), algorithm=int(m.algorithm)
    )


def update_globals_req_to_pb(updates: Iterable[UpdatePeerGlobal]) -> peers_pb.UpdatePeerGlobalsReq:
    return peers_pb.UpdatePeerGlobalsReq(globals=[update_global_to_pb(u) for u in updates])


def update_globals_req_from_pb(m: peers_pb.UpdatePeerGlobalsReq) -> List[UpdatePeerGlobal]:
    return [update_global_from_pb(u) for u in m.globals]


# ---- HealthCheck -----------------------------------------------------
def health_to_pb(h: HealthCheckResponse) -> pb.HealthCheckResp:
    return pb.HealthCheckResp(
        status=h.status, message=h.message, peer_count=int(h.peer_count)
    )


def health_from_pb(m: pb.HealthCheckResp) -> HealthCheckResponse:
    return HealthCheckResponse(
        status=m.status, message=m.message, peer_count=m.peer_count
    )
